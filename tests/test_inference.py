"""AnalysisPredictor tests: load, ir-optimize, serve.

Reference methodology: inference api tests load a saved model and
compare predictor output against the executor's
(inference/tests/api/analyzer_*_tester.cc pattern)."""

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import layers
from paddle_tpu.inference import (AnalysisConfig, AnalysisPredictor,
                                  PaddleTensor,
                                  create_paddle_predictor)


def _save_conv_model(tmp_path, rng):
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = 11
    with fluid.program_guard(main, startup):
        img = layers.data(name="img", shape=[3, 8, 8], dtype="float32")
        c = layers.conv2d(img, num_filters=8, filter_size=3, padding=1,
                          bias_attr=False)
        bn = layers.batch_norm(c)
        flat = layers.reshape(bn, shape=[-1, 8 * 8 * 8])
        pred = layers.fc(flat, size=4, act="softmax")
        loss = layers.mean(
            layers.cross_entropy(
                pred, layers.data(name="y", shape=[1], dtype="int64")))
        fluid.optimizer.SGDOptimizer(0.1).minimize(loss)
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe = fluid.Executor()
        exe.run(startup)
        for _ in range(3):  # train a little so BN stats are non-trivial
            exe.run(main, feed={
                "img": rng.rand(8, 3, 8, 8).astype(np.float32),
                "y": rng.randint(0, 4, (8, 1)).astype(np.int64)},
                fetch_list=[loss])
        feed = {"img": rng.rand(4, 3, 8, 8).astype(np.float32)}
        d = str(tmp_path / "model")
        fluid.io.save_inference_model(d, ["img"], [pred], exe,
                                      main_program=main, scope=scope)
        (expect,) = exe.run(main.clone(for_test=True), feed={
            "img": feed["img"],
            "y": np.zeros((4, 1), np.int64)}, fetch_list=[pred],
            scope=scope)
    return d, feed, np.asarray(expect)


class TestAnalysisPredictor:
    def test_optimized_predict_matches_executor(self, tmp_path, rng):
        d, feed, expect = _save_conv_model(tmp_path, rng)
        pred = create_paddle_predictor(AnalysisConfig(d))
        # conv_bn got folded, fc got fused
        types = [op.type for op in
                 pred.program.global_block().ops]
        assert "batch_norm" not in types
        assert "fc" in types
        (out,) = pred.run([PaddleTensor(feed["img"])])
        np.testing.assert_allclose(out.data, expect, atol=1e-4)

    def test_ir_optim_off(self, tmp_path, rng):
        d, feed, expect = _save_conv_model(tmp_path, rng)
        cfg = AnalysisConfig(d).switch_ir_optim(False)
        pred = AnalysisPredictor(cfg)
        types = [op.type for op in
                 pred.program.global_block().ops]
        assert "batch_norm" in types
        (out,) = pred.run([feed["img"]])
        np.testing.assert_allclose(out.data, expect, atol=1e-5)

    def test_pass_builder_delete(self, tmp_path, rng):
        d, feed, expect = _save_conv_model(tmp_path, rng)
        cfg = AnalysisConfig(d).delete_pass("conv_bn_fuse_pass")
        pred = AnalysisPredictor(cfg)
        types = [op.type for op in
                 pred.program.global_block().ops]
        assert "batch_norm" in types      # kept
        assert "fc" in types              # fc fuse still ran

    def test_input_validation(self, tmp_path, rng):
        d, feed, _ = _save_conv_model(tmp_path, rng)
        pred = AnalysisPredictor(AnalysisConfig(d))
        assert pred.get_input_names() == ["img"]
        assert len(pred.get_output_names()) == 1
        with pytest.raises(Exception, match="expects 1 input"):
            pred.run([feed["img"], feed["img"]])

    def test_predict_dict_and_clone(self, tmp_path, rng):
        d, feed, expect = _save_conv_model(tmp_path, rng)
        pred = AnalysisPredictor(AnalysisConfig(d))
        (a,) = pred.predict(feed)
        (b,) = pred.clone().predict(feed)
        np.testing.assert_allclose(a, b, atol=1e-6)
