"""Pipeline parallelism (GPipe over the ``pp`` mesh axis): the
pipelined forward AND backward must equal the sequential stage
composition exactly — the schedule is pure dataflow, so this is an
equality test, not a convergence test."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from paddle_tpu.parallel import mesh as mesh_lib
from paddle_tpu.parallel.pipeline import (gpipe_apply,
                                          stack_stage_params)

P = 4
D = 16


def _stage_fn(params, x):
    return jnp.tanh(x @ params["w"] + params["b"])


@pytest.fixture
def stages(rng):
    per_stage = [{"w": jnp.asarray(
        rng.randn(D, D).astype(np.float32) * 0.4),
        "b": jnp.asarray(rng.randn(D).astype(np.float32) * 0.1)}
        for _ in range(P)]
    return stack_stage_params(per_stage)


def _sequential(stacked, x):
    y = x
    for s in range(P):
        y = _stage_fn(jax.tree_util.tree_map(lambda a: a[s], stacked),
                      y)
    return y


def _pp_mesh():
    return mesh_lib.make_mesh({"pp": P}, jax.devices()[:P])


@pytest.mark.parametrize("n_micro", [4, 8, 1])
def test_gpipe_matches_sequential(rng, stages, n_micro):
    x = jnp.asarray(rng.randn(8, D).astype(np.float32))
    want = _sequential(stages, x)
    got = gpipe_apply(_stage_fn, stages, x, mesh=_pp_mesh(),
                      n_micro=n_micro)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               atol=1e-6, rtol=1e-6)


def test_gpipe_gradients_match(rng, stages):
    x = jnp.asarray(rng.randn(8, D).astype(np.float32))
    mesh = _pp_mesh()

    def loss_seq(params, x_):
        return jnp.sum(_sequential(params, x_) ** 2)

    def loss_pp(params, x_):
        return jnp.sum(gpipe_apply(_stage_fn, params, x_, mesh=mesh,
                                   n_micro=4) ** 2)

    gw_p, gw_x = jax.grad(loss_seq, argnums=(0, 1))(stages, x)
    gg_p, gg_x = jax.grad(loss_pp, argnums=(0, 1))(stages, x)
    np.testing.assert_allclose(np.asarray(gg_x), np.asarray(gw_x),
                               atol=1e-5, rtol=1e-5)
    for k in ("w", "b"):
        np.testing.assert_allclose(np.asarray(gg_p[k]),
                                   np.asarray(gw_p[k]),
                                   atol=1e-5, rtol=1e-5, err_msg=k)


def test_gpipe_fallback_without_mesh(rng, stages):
    x = jnp.asarray(rng.randn(4, D).astype(np.float32))
    want = _sequential(stages, x)
    got = gpipe_apply(_stage_fn, stages, x, mesh=None)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               atol=1e-6, rtol=1e-6)


def test_gpipe_rejects_indivisible_batch(rng, stages):
    x = jnp.asarray(rng.randn(6, D).astype(np.float32))
    with pytest.raises(ValueError, match="divisible"):
        gpipe_apply(_stage_fn, stages, x, mesh=_pp_mesh(), n_micro=4)
    # same validation WITHOUT a pp mesh: single-device development
    # must fail exactly like the pod (review r5)
    with pytest.raises(ValueError, match="divisible"):
        gpipe_apply(_stage_fn, stages, x, mesh=None, n_micro=4)
    with pytest.raises(ValueError, match=">= 1"):
        gpipe_apply(_stage_fn, stages, x, mesh=None, n_micro=0)


def test_gpipe_rejects_stage_count_mismatch(rng, stages):
    """A [2P]-stage stack on a P-device pp axis must fail loudly —
    the shard body would otherwise silently run every other stage."""
    import jax as _jax
    double = _jax.tree_util.tree_map(
        lambda a: jnp.concatenate([a, a]), stages)
    x = jnp.asarray(rng.randn(8, D).astype(np.float32))
    with pytest.raises(ValueError, match="one stage per device"):
        gpipe_apply(_stage_fn, double, x, mesh=_pp_mesh(), n_micro=4)
    # the no-mesh fallback legitimately runs all 8 stages
    got = gpipe_apply(_stage_fn, double, x, mesh=None, n_micro=4)
    assert got.shape == x.shape
