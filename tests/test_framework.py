"""Program/Block/Variable construction tests (reference analog:
python/paddle/fluid/tests/unittests/test_program.py, test_variable.py,
test_operator_desc.py)."""

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import layers


def test_program_build():
    main = fluid.Program()
    startup = fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data("x", shape=[4], dtype="float32")
        y = layers.fc(x, size=3)
    assert x.shape == (-1, 4)
    assert len(main.global_block().ops) >= 1
    params = main.all_parameters()
    assert len(params) == 2  # weight + bias
    # startup program holds init ops for both params
    assert len(startup.global_block().ops) == 2


def test_variable_operators_append_ops():
    main = fluid.Program()
    with fluid.program_guard(main):
        x = layers.data("x", shape=[4])
        y = layers.data("y", shape=[4])
        z = x + y
        w = z * 2.0
    types = [op.type for op in main.global_block().ops]
    assert "elementwise_add" in types
    assert "elementwise_mul" in types


def test_program_clone_for_test_flips_is_test():
    main = fluid.Program()
    with fluid.program_guard(main):
        x = layers.data("x", shape=[4])
        d = layers.dropout(x, dropout_prob=0.5)
    test_prog = main.clone(for_test=True)
    drop_ops = [op for op in test_prog.global_block().ops
                if op.type == "dropout"]
    assert drop_ops[0].attrs["is_test"] is True
    # original untouched
    orig = [op for op in main.global_block().ops if op.type == "dropout"]
    assert orig[0].attrs["is_test"] is False


def test_unique_names():
    main = fluid.Program()
    with fluid.program_guard(main):
        x = layers.data("x", shape=[4])
        a = layers.fc(x, size=3)
        b = layers.fc(x, size=3)
    names = [p.name for p in main.all_parameters()]
    assert len(names) == len(set(names)) == 4


def test_executor_runs_simple_program():
    main = fluid.Program()
    with fluid.program_guard(main):
        x = layers.data("x", shape=[4])
        y = layers.scale(x, scale=3.0, bias=1.0)
    exe = fluid.Executor()
    xv = np.ones((2, 4), dtype=np.float32)
    (out,) = exe.run(main, feed={"x": xv}, fetch_list=[y])
    np.testing.assert_allclose(out, xv * 3.0 + 1.0)


def test_startup_then_forward():
    main = fluid.Program()
    startup = fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data("x", shape=[4])
        out = layers.fc(x, size=3, act="relu")
    exe = fluid.Executor()
    exe.run(startup)
    scope = fluid.global_scope()
    for p in main.all_parameters():
        assert scope.find_var(p.name) is not None
    res, = exe.run(main, feed={"x": np.ones((5, 4), np.float32)},
                   fetch_list=[out])
    assert res.shape == (5, 3)
    assert np.all(res >= 0)


def test_shape_inference_real_dim_equal_to_sentinel():
    """A concrete dimension equal to a dynamic-dim sentinel (e.g. a
    vocab padded to the prime 8191) must not be mis-inferred as -1:
    the sentinel is chosen per op to avoid every concrete dim."""
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data("x", shape=[8191], dtype="float32")
        h = layers.fc(x, size=8191)
        assert h.shape == (-1, 8191)
        w = layers.fc(h, size=16)
        assert w.shape == (-1, 16)
        # reshape whose target mentions 8191 as a literal attr
        r = layers.reshape(w, (-1, 8, 2))
        assert r.shape == (-1, 8, 2)


class TestPruneSubBlocks:
    def test_prune_keeps_reachable_drops_dead_sub_blocks(self):
        """_prune must keep sub-blocks of KEPT ops whole (reference
        prune.cc) and empty unreachable bodies — round 4 fixed both
        directions: sub-blocks used to be sliced against root targets
        (emptying live RNN bodies in saved inference models)."""
        import paddle_tpu as fluid
        from paddle_tpu import layers

        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            x = layers.data("x", shape=[4, 3])
            enc = layers.fc(x, 6, num_flatten_dims=2,
                            bias_attr=False)
            context = layers.reduce_sum(enc, dim=1)
            drnn = layers.DynamicRNN()
            with drnn.block():
                x_t = drnn.step_input(x)
                h_prev = drnn.memory(shape=[5], value=0.0)
                h = layers.fc(x_t, size=5, act="tanh",
                              param_attr=fluid.ParamAttr(
                                  name="dec_w"))
                h = layers.elementwise_add(
                    h, layers.fc(h_prev, size=5, bias_attr=False))
                drnn.update_memory(h_prev, h)
                drnn.output(h)
            dec = drnn()

        enc_only = main._prune([context])
        assert "dec_w" not in enc_only.global_block().vars
        assert all(not b.ops for b in enc_only.blocks[1:])

        full = main._prune([dec])
        assert full.blocks[1].ops, "reachable sub-block emptied"
        assert "dec_w" in full.global_block().vars


class TestExecutorErrorUX:
    """The verify-skill probes as regression tests: every user mistake
    gets a clear, var-named error (reference: executor.py
    check_feed_shape_type + the enforce idiom)."""

    def _net(self):
        import paddle_tpu as fluid
        from paddle_tpu import layers

        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            x = layers.data("x", shape=[4])
            loss = layers.mean(layers.fc(x, 8))
        return main, startup, loss

    def test_run_before_startup(self):
        import numpy as np
        import paddle_tpu as fluid

        main, _startup, loss = self._net()
        scope = fluid.Scope()
        with fluid.scope_guard(scope):
            exe = fluid.Executor()
            with pytest.raises(Exception,
                               match="persistable var is not i"):
                exe.run(main, feed={"x": np.ones((2, 4), np.float32)},
                        fetch_list=[loss])

    def test_missing_feed_and_unknown_fetch(self):
        import numpy as np
        import paddle_tpu as fluid

        main, startup, loss = self._net()
        scope = fluid.Scope()
        with fluid.scope_guard(scope):
            exe = fluid.Executor()
            exe.run(startup)
            with pytest.raises(Exception,
                               match="missing from feed"):
                exe.run(main, feed={}, fetch_list=[loss])
            with pytest.raises(Exception, match="not produced"):
                exe.run(main,
                        feed={"x": np.ones((2, 4), np.float32)},
                        fetch_list=["nope"])

    def test_wrong_feed_shape_names_the_var(self):
        import numpy as np
        import paddle_tpu as fluid

        main, startup, loss = self._net()
        scope = fluid.Scope()
        with fluid.scope_guard(scope):
            exe = fluid.Executor()
            exe.run(startup)
            with pytest.raises(Exception,
                               match=r"feed 'x' has shape \(2, 5\)"):
                exe.run(main, feed={"x": np.ones((2, 5), np.float32)},
                        fetch_list=[loss])
            # -1 dims stay free: any batch size passes
            exe.run(main, feed={"x": np.ones((7, 4), np.float32)},
                    fetch_list=[loss])

    def test_incompatible_feed_dtype(self):
        import numpy as np
        import paddle_tpu as fluid
        from paddle_tpu import layers

        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            ids = layers.data("ids", shape=[3], dtype="int64")
            emb = layers.embedding(ids, size=(10, 4))
            loss = layers.mean(emb)
        scope = fluid.Scope()
        with fluid.scope_guard(scope):
            exe = fluid.Executor()
            exe.run(startup)
            # float feed into an int64 ids var is NOT same-kind
            with pytest.raises(Exception, match="dtype"):
                exe.run(main,
                        feed={"ids": np.ones((2, 3), np.float32)},
                        fetch_list=[loss])
