"""Beam search tests (reference: test_beam_search_op.py,
test_beam_search_decode_op.py — dense fixed-width redesign)."""

import numpy as np

import paddle_tpu as fluid
from paddle_tpu import layers
from paddle_tpu.ops.beam_search_ops import (beam_search_backtrack,
                                            beam_search_step)

import jax.numpy as jnp


def test_beam_search_step_selects_topk():
    # B=1, K=2, V=4; pre scores [0, -1]
    pre_ids = jnp.array([[3, 2]])
    pre_scores = jnp.array([[0.0, -1.0]])
    scores = jnp.log(jnp.array([[[0.1, 0.6, 0.2, 0.1],
                                 [0.7, 0.1, 0.1, 0.1]]]))
    ids, sc, parent = beam_search_step(pre_ids, pre_scores, scores,
                                       beam_size=2, end_id=0)
    # candidates: beam0: log0.6=-0.51(id1), log0.2=-1.6(id2);
    # beam1: -1+log0.7=-1.36 (id0)
    np.testing.assert_array_equal(np.asarray(ids), [[1, 0]])
    np.testing.assert_array_equal(np.asarray(parent), [[0, 1]])
    np.testing.assert_allclose(np.asarray(sc),
                               [[np.log(0.6), -1 + np.log(0.7)]],
                               rtol=1e-5)


def test_finished_beam_keeps_score_and_emits_end():
    end = 0
    pre_ids = jnp.array([[end, 2]])          # beam 0 already finished
    pre_scores = jnp.array([[-0.1, -0.2]])
    scores = jnp.log(jnp.full((1, 2, 4), 0.25))
    ids, sc, parent = beam_search_step(pre_ids, pre_scores, scores,
                                       beam_size=2, end_id=end)
    # finished beam continues with end_id at unchanged score -0.1 (best)
    assert int(ids[0, 0]) == end
    np.testing.assert_allclose(float(sc[0, 0]), -0.1, rtol=1e-6)
    assert int(parent[0, 0]) == 0


def test_backtrack_reconstructs_path():
    # T=3, B=1, K=2
    ids = [jnp.array([[5, 6]]), jnp.array([[7, 8]]),
           jnp.array([[9, 10]])]
    parents = [jnp.array([[0, 1]]), jnp.array([[1, 0]]),
               jnp.array([[0, 1]])]
    scores = jnp.array([[-1.0, -0.5]])  # beam 1 is better
    seqs, sc = beam_search_backtrack(ids, parents, scores, end_id=0)
    # best (beam1, score -0.5): t2 id=10 parent=1 -> t1 id=8 parent=0
    # -> t0 id=5
    np.testing.assert_array_equal(np.asarray(seqs[0, 0]), [5, 8, 10])
    # runner-up (beam0): t2 id=9 parent=0 -> t1 id=7 parent=1 -> t0 id=6
    np.testing.assert_array_equal(np.asarray(seqs[0, 1]), [6, 7, 9])
    np.testing.assert_allclose(np.asarray(sc), [[-0.5, -1.0]])


def test_while_loop_beam_decode_markov():
    """Full fluid-style decode: While loop + beam_search op + tensor
    arrays + beam_search_decode, on a deterministic Markov chain where
    the best path is analytically known."""
    V, K, B, T = 4, 2, 1, 3
    end_id = 0
    # transition log-probs: from any state, P(next=state+1)=0.9 wraps
    trans = np.full((V, V), 0.05, np.float32)
    for s in range(V):
        trans[s, (s + 1) % V] = 0.9
    trans = np.log(trans / trans.sum(1, keepdims=True))

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        tr = layers.data("trans", shape=[V, V], append_batch_size=False)
        pre_ids = layers.fill_constant([B, K], "int64", 1)  # start at 1
        pre_scores = layers.fill_constant([B, K], "float32", 0.0)
        # kill duplicate start beams so beam 1 explores alternatives
        mask0 = layers.assign(np.array([[0.0, -1e9]], np.float32))
        pre_scores = pre_scores + mask0
        ids_arr = layers.create_array("int64")
        par_arr = layers.create_array("int32")
        t = layers.fill_constant([1], "int32", 0)
        tmax = layers.fill_constant([1], "int32", T)
        cond = layers.less_than(t, tmax)
        w = layers.While(cond=cond)
        with w.block():
            # scores[b,k,:] = trans[pre_ids[b,k]]
            step_scores = layers.gather(tr, layers.reshape(
                pre_ids, shape=[B * K]))
            step_scores = layers.reshape(step_scores, shape=[B, K, V])
            sel_ids, sel_scores, parent = layers.beam_search(
                pre_ids, pre_scores, None, step_scores,
                beam_size=K, end_id=end_id)
            layers.array_write(sel_ids, t, array=ids_arr)
            layers.array_write(parent, t, array=par_arr)
            layers.assign(sel_ids, pre_ids)
            layers.assign(sel_scores, pre_scores)
            layers.increment(t, value=1, in_place=True)
            layers.less_than(t, tmax, cond=cond)
        seqs, sc = layers.beam_search_decode(ids_arr, par_arr,
                                             pre_scores, beam_size=K,
                                             end_id=end_id)
    exe = fluid.Executor()
    exe.run(startup)
    seqs_v, sc_v = exe.run(main, feed={"trans": trans},
                           fetch_list=[seqs, sc])
    # best path from 1: 2 -> 3 -> 0
    np.testing.assert_array_equal(seqs_v[0, 0], [2, 3, 0])
    np.testing.assert_allclose(sc_v[0, 0], 3 * trans[1, 2], rtol=1e-5)


class TestContribDecoder:
    """StateCell / TrainingDecoder / BeamSearchDecoder UX (reference:
    contrib/decoder/beam_search_decoder.py) — one cell definition
    drives teacher-forced training AND beam decoding."""

    def _cell(self, hid, ctx):
        from paddle_tpu.contrib.decoder import InitState, StateCell
        init = InitState(init=ctx)
        cell = StateCell(inputs={"x": None},
                         states={"h": init}, out_state="h")

        @cell.state_updater
        def update(c):
            x = c.get_input("x")
            h = c.get_state("h")
            c.set_state("h", layers.fc([x, h], size=hid, act="tanh",
                                       name="cell_fc"))

        return cell

    def test_training_decoder_trains(self):
        hid, vocab, s = 16, 12, 6
        main, startup = fluid.Program(), fluid.Program()
        main.random_seed = startup.random_seed = 9
        with fluid.program_guard(main, startup):
            src = layers.data("src", shape=[4])
            trg = layers.data("trg", shape=[s], dtype="int64")
            lbl = layers.data("lbl", shape=[s], dtype="int64")
            ctx = layers.fc(src, hid, act="tanh", name="enc")
            cell = self._cell(hid, ctx)
            from paddle_tpu.contrib.decoder import TrainingDecoder
            dec = TrainingDecoder(cell)
            emb_all = layers.embedding(trg, (vocab, 8),
                                       param_attr=fluid.ParamAttr(
                                           name="dec_emb"))
            with dec.block():
                x = dec.step_input(emb_all)
                cell.compute_state(inputs={"x": x})
                out = layers.fc(cell.out_state(), vocab,
                                act="softmax", name="dec_out")
                dec.output(out)
            probs = dec()                       # [b, s, vocab]
            cost = layers.cross_entropy(
                layers.reshape(probs, shape=[-1, vocab]),
                layers.reshape(lbl, shape=[-1, 1]))
            loss = layers.mean(cost)
            fluid.optimizer.AdamOptimizer(5e-3).minimize(loss)
        exe = fluid.Executor()
        exe.run(startup)
        rs = np.random.RandomState(0)
        feed = {"src": rs.randn(8, 4).astype(np.float32),
                "trg": rs.randint(0, vocab, (8, s)).astype(np.int64)}
        feed["lbl"] = np.roll(feed["trg"], -1, axis=1)
        losses = [float(exe.run(main, feed=feed,
                                fetch_list=[loss])[0])
                  for _ in range(15)]
        assert np.isfinite(losses).all()
        assert losses[-1] < losses[0]

    def test_beam_search_decoder_decodes(self):
        hid, vocab, K, T = 16, 12, 3, 5
        main, startup = fluid.Program(), fluid.Program()
        main.random_seed = startup.random_seed = 10
        with fluid.program_guard(main, startup):
            # decode programs are built shape-static (XLA inference)
            src = layers.data("src", shape=[2, 4],
                              append_batch_size=False)
            ctx = layers.fc(src, hid, act="tanh", name="enc")
            # beam-expanded context [b, K, hid] (flattened internally)
            ctx_k = layers.expand(layers.unsqueeze(ctx, [1]),
                                  expand_times=[1, K, 1])
            cell = self._cell(hid, ctx_k)
            from paddle_tpu.contrib.decoder import BeamSearchDecoder
            b = 2
            init_ids = layers.fill_constant([b, K], "int64", 1)
            init_scores = layers.assign(
                np.tile(np.array([[0.0] + [-1e9] * (K - 1)],
                                 np.float32), (b, 1)))
            dec = BeamSearchDecoder(cell, init_ids, init_scores,
                                    beam_size=K, end_id=0,
                                    max_len=T)
            with dec.block():
                prev = dec.read_input()         # [b*K] int64
                emb = layers.embedding(prev, (vocab, 8),
                                       param_attr=fluid.ParamAttr(
                                           name="dec_emb"))
                cell.compute_state(inputs={"x": emb})
                logit = layers.fc(cell.out_state(), vocab,
                                  name="dec_out")
                logp = layers.log(layers.softmax(logit) + 1e-9)
                dec.apply(logp)
            seqs, scores = dec()
        exe = fluid.Executor()
        exe.run(startup)
        feed = {"src": np.random.RandomState(1)
                .randn(2, 4).astype(np.float32)}
        sv, scv = exe.run(main, feed=feed, fetch_list=[seqs, scores])
        assert sv.shape == (2, K, T)
        assert scv.shape[:2] == (2, K)
        # best-first ordering
        assert (np.diff(scv, axis=1) <= 1e-6).all()
        assert ((sv >= 0) & (sv < vocab)).all()
