"""Expert-parallel MoE (Switch top-1 over the ep mesh axis).

Equality basis: a kept token's output is prob * FFN_expert(x) no
matter which capacity slot it lands in, so with no capacity drops the
sharded path, the single-device reference, and a per-token oracle all
agree exactly. Capacity dropping is asserted separately (per-expert
bucket occupancy)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from paddle_tpu.parallel import mesh as mesh_lib
from paddle_tpu.parallel.moe import moe_ffn, moe_ffn_reference

E, D, F = 8, 16, 32
N = 64


@pytest.fixture
def weights(rng):
    return dict(
        gate_w=jnp.asarray(rng.randn(D, E).astype(np.float32)),
        w1=jnp.asarray(rng.randn(E, D, F).astype(np.float32) * 0.2),
        b1=jnp.asarray(rng.randn(E, F).astype(np.float32) * 0.1),
        w2=jnp.asarray(rng.randn(E, F, D).astype(np.float32) * 0.2),
        b2=jnp.asarray(rng.randn(E, D).astype(np.float32) * 0.1))


def _oracle(x, wt):
    """Per-token dense computation of the same routing decision."""
    probs = jax.nn.softmax((x @ wt["gate_w"]).astype(jnp.float32), -1)
    idx = jnp.argmax(probs, -1)
    out = []
    for i in range(x.shape[0]):
        e = int(idx[i])
        h = jax.nn.relu(x[i] @ wt["w1"][e] + wt["b1"][e])
        y = h @ wt["w2"][e] + wt["b2"][e]
        out.append(y * probs[i, e])
    return jnp.stack(out)


def _ep_mesh(n=4):
    return mesh_lib.make_mesh({"ep": n}, jax.devices()[:n])


def test_reference_matches_oracle(rng, weights):
    x = jnp.asarray(rng.randn(N, D).astype(np.float32))
    want = _oracle(x, weights)
    got, _aux = moe_ffn_reference(x, capacity_factor=float(E),
                                  **weights)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               atol=1e-5, rtol=1e-5)


def test_sharded_matches_reference_no_drop(rng, weights):
    x = jnp.asarray(rng.randn(N, D).astype(np.float32))
    mesh = _ep_mesh()
    want, aux_ref = moe_ffn_reference(x, capacity_factor=float(E),
                                      **weights)
    got, aux = moe_ffn(x, mesh=mesh, capacity_factor=float(E),
                       **weights)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               atol=1e-5, rtol=1e-5)
    np.testing.assert_allclose(float(aux), float(aux_ref), rtol=1e-5)


# tier-1 headroom (PR 18): top-1 sharded gradients (~10 s) -> slow;
# forward parity stays via test_sharded_matches_reference_no_drop;
# top-2 gradients are already slow
@pytest.mark.slow
def test_sharded_gradients_match(rng, weights):
    x = jnp.asarray(rng.randn(N, D).astype(np.float32))
    mesh = _ep_mesh()

    def loss_ref(wt):
        y, aux = moe_ffn_reference(x, capacity_factor=float(E), **wt)
        return jnp.sum(y ** 2) + 0.01 * aux

    def loss_ep(wt):
        y, aux = moe_ffn(x, mesh=mesh, capacity_factor=float(E), **wt)
        return jnp.sum(y ** 2) + 0.01 * aux

    gw = jax.grad(loss_ref)(weights)
    gg = jax.grad(loss_ep)(weights)
    for k in weights:
        np.testing.assert_allclose(np.asarray(gg[k]),
                                   np.asarray(gw[k]), atol=1e-4,
                                   rtol=1e-4, err_msg=k)


def test_capacity_dropping(rng, weights):
    """Tight capacity drops tokens (zero rows) instead of crashing or
    mis-routing — the static-shape trade documented in the module."""
    x = jnp.asarray(rng.randn(N, D).astype(np.float32))
    got, _ = moe_ffn_reference(x, capacity_factor=0.25, **weights)
    oracle = _oracle(x, weights)
    zero_rows = np.where(
        np.all(np.asarray(got) == 0.0, axis=-1))[0]
    assert len(zero_rows) > 0  # something was dropped at cf=0.25
    kept = [i for i in range(N) if i not in set(zero_rows)]
    np.testing.assert_allclose(np.asarray(got)[kept],
                               np.asarray(oracle)[kept], atol=1e-5,
                               rtol=1e-5)


def test_rejects_indivisible(rng, weights):
    mesh = _ep_mesh(4)
    x = jnp.asarray(rng.randn(10, D).astype(np.float32))
    with pytest.raises(ValueError, match="divisible"):
        moe_ffn(x, mesh=mesh, **weights)


# --- top-2 (GShard) routing ------------------------------------------------

def _oracle_top2(x, wt):
    probs = jax.nn.softmax((x @ wt["gate_w"]).astype(jnp.float32), -1)
    i1 = jnp.argmax(probs, -1)
    masked = probs - jax.nn.one_hot(i1, E) * probs
    i2 = jnp.argmax(masked, -1)
    out = []
    for i in range(x.shape[0]):
        e1, e2 = int(i1[i]), int(i2[i])
        p1, p2 = float(probs[i, e1]), float(masked[i, e2])
        g1, g2 = p1 / (p1 + p2), p2 / (p1 + p2)
        y = 0.0
        for e, g in ((e1, g1), (e2, g2)):
            h = jax.nn.relu(x[i] @ wt["w1"][e] + wt["b1"][e])
            y = y + (h @ wt["w2"][e] + wt["b2"][e]) * g
        out.append(y)
    return jnp.stack(out)


def test_top2_reference_matches_oracle(rng, weights):
    x = jnp.asarray(rng.randn(N, D).astype(np.float32))
    want = _oracle_top2(x, weights)
    got, _ = moe_ffn_reference(x, capacity_factor=float(E), top_k=2,
                               **weights)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               atol=1e-5, rtol=1e-5)


def test_top2_sharded_matches_reference(rng, weights):
    x = jnp.asarray(rng.randn(N, D).astype(np.float32))
    mesh = _ep_mesh()
    want, aux_ref = moe_ffn_reference(x, capacity_factor=float(E),
                                      top_k=2, **weights)
    got, aux = moe_ffn(x, mesh=mesh, capacity_factor=float(E),
                       top_k=2, **weights)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               atol=1e-5, rtol=1e-5)
    np.testing.assert_allclose(float(aux), float(aux_ref), rtol=1e-5)


# tier-1 wall-time headroom (ISSUE 15): ~10 s; top-1 sharded grads +
# the top-2 sharded forward reference keep both classes in tier-1
@pytest.mark.slow
def test_top2_sharded_gradients_match(rng, weights):
    x = jnp.asarray(rng.randn(N, D).astype(np.float32))
    mesh = _ep_mesh()

    def loss(wt, fn, kw):
        y, aux = fn(x, capacity_factor=float(E), top_k=2, **kw, **wt)
        return jnp.sum(y ** 2) + 0.01 * aux

    gw = jax.grad(lambda w: loss(w, moe_ffn_reference, {}))(weights)
    gg = jax.grad(lambda w: loss(w, moe_ffn, {"mesh": mesh}))(weights)
    for k in weights:
        np.testing.assert_allclose(np.asarray(gg[k]),
                                   np.asarray(gw[k]), atol=1e-4,
                                   rtol=1e-4, err_msg=k)


def test_top_k_validated(rng, weights):
    x = jnp.asarray(rng.randn(N, D).astype(np.float32))
    with pytest.raises(ValueError, match="top_k"):
        moe_ffn(x, mesh=_ep_mesh(), top_k=3, **weights)


def test_top2_capacity_pressure(rng, weights):
    """cf small enough to drop: secondaries queue BEHIND primaries
    (GShard ordering), kept tokens still match the oracle's per-token
    value, and no slot collision corrupts outputs."""
    x = jnp.asarray(rng.randn(N, D).astype(np.float32))
    got, _ = moe_ffn_reference(x, capacity_factor=0.4, top_k=2,
                               **weights)
    got = np.asarray(got)
    # reconstruct which (token, choice) pairs the routing kept
    probs = jax.nn.softmax((x @ weights["gate_w"]).astype(jnp.float32),
                           -1)
    i1 = np.asarray(jnp.argmax(probs, -1))
    masked = probs - jax.nn.one_hot(i1, E) * probs
    i2 = np.asarray(jnp.argmax(masked, -1))
    C = int(-(-N * 2 * 0.4 // E))
    counts1 = {e: 0 for e in range(E)}
    kept1 = []
    for t in range(N):
        kept1.append(counts1[i1[t]] < C)
        counts1[i1[t]] += 1
    tot1 = {e: int((i1 == e).sum()) for e in range(E)}
    counts2 = {e: 0 for e in range(E)}
    kept2 = []
    for t in range(N):
        kept2.append(tot1[i2[t]] + counts2[i2[t]] < C)
        counts2[i2[t]] += 1
    assert not all(kept1) or not all(kept2)  # pressure is real
    # expected per-token value from the kept choices only
    for t in range(N):
        y = np.zeros(D, np.float32)
        p1 = float(probs[t, i1[t]]); p2 = float(masked[t, i2[t]])
        g1, g2 = p1 / (p1 + p2), p2 / (p1 + p2)
        for e, g, kept in ((i1[t], g1, kept1[t]), (i2[t], g2, kept2[t])):
            if kept:
                h = np.maximum(
                    np.asarray(x[t]) @ np.asarray(weights["w1"][e])
                    + np.asarray(weights["b1"][e]), 0.0)
                y += (h @ np.asarray(weights["w2"][e])
                      + np.asarray(weights["b2"][e])) * g
        np.testing.assert_allclose(got[t], y, atol=1e-4, rtol=1e-4,
                                   err_msg="token %d" % t)
