"""DeepFM CTR tests (BASELINE config 5): model learns synthetic CTR
signal, AUC accumulates, sharded-table mesh run matches replicated."""

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import layers, optimizer
from paddle_tpu.models import deepfm


def _tiny_cfg():
    return deepfm.DeepFMConfig(sparse_feature_dim=200,
                               embedding_size=8, layer_sizes=(32, 32))


def _build(cfg, seed=1):
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = seed
    startup.random_seed = seed
    with fluid.program_guard(main, startup):
        avg_loss, auc_var, predict = deepfm.deepfm(cfg)
        optimizer.Adam(5e-3).minimize(avg_loss)
    return main, startup, avg_loss, auc_var


def test_deepfm_trains_and_auc_improves():
    cfg = _tiny_cfg()
    main, startup, avg_loss, auc_var = _build(cfg)
    exe = fluid.Executor()
    exe.run(startup)
    losses, aucs = [], []
    for step in range(120):
        feed = deepfm.make_fake_batch(cfg, batch=256, seed=step)
        lv, av = exe.run(main, feed=feed,
                         fetch_list=[avg_loss, auc_var])
        losses.append(float(lv))
        aucs.append(float(av))
    assert losses[-1] < losses[0], (losses[0], losses[-1])
    assert aucs[-1] > 0.68, aucs[-1]  # clearly better than chance


# tier-1 headroom (PR 18): sharded-vs-replicated deepfm (~6 s) -> slow;
# deepfm training stays via test_deepfm_trains_and_auc_improves
@pytest.mark.slow
def test_deepfm_sharded_tables_match_replicated():
    """Row-sharded embedding tables over an mp axis produce the same
    loss trace as the replicated run — the TPU equivalent of the
    reference's PS-sharded-table correctness."""

    def run(shard):
        cfg = _tiny_cfg()
        main, startup, avg_loss, auc_var = _build(cfg, seed=3)
        if shard:
            deepfm.shard_tables(main)
            prog = fluid.CompiledProgram(main).with_data_parallel(
                axes={"dp": 2, "tp": 4})
        else:
            prog = main
        exe = fluid.Executor()
        scope = fluid.Scope()
        losses = []
        with fluid.scope_guard(scope):
            exe.run(startup)
            for step in range(5):
                feed = deepfm.make_fake_batch(cfg, batch=64, seed=step)
                (lv,) = exe.run(prog, feed=feed,
                                fetch_list=[avg_loss])
                losses.append(float(lv))
        return losses

    plain = run(False)
    sharded = run(True)
    np.testing.assert_allclose(sharded, plain, rtol=3e-4, atol=1e-5)


def test_criteo_dataset_pipeline():
    from paddle_tpu import dataset, reader as rd
    cfg = deepfm.DeepFMConfig(sparse_feature_dim=100000,
                              embedding_size=4, layer_sizes=(8,))
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        avg_loss, auc_var, predict = deepfm.deepfm(cfg)
    feeder = fluid.DataFeeder(
        feed_list=["dense_input", "sparse_input", "label"],
        program=main)
    batch = next(rd.batch(dataset.criteo.train(), 32)())
    feed = feeder.feed(batch)
    assert feed["dense_input"].shape == (32, 13)
    assert feed["sparse_input"].shape == (32, 26)
    exe = fluid.Executor()
    exe.run(startup)
    lv, = exe.run(main, feed=feed, fetch_list=[avg_loss])
    assert np.isfinite(lv)
