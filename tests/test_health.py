"""Fleet health plane tests: beacon/watchdog stall detection (no
false positives on a healthy run), declarative HealthRules over
registry deltas, flight-recorder blackbox dumps (incl. on SIGTERM),
the machine-readable /healthz verdict, the wedge acceptance scenarios
(stalled serving batcher, parked PS barrier), journal rotation,
tools/doctor.py auto-diagnosis, and tools/bench_diff.py."""

import json
import os
import signal
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import layers
from paddle_tpu import observability as obs
from paddle_tpu.observability import health
from paddle_tpu.observability.registry import MetricsRegistry

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
TOOLS = os.path.join(ROOT, "tools")
sys.path.insert(0, TOOLS)

pytestmark = pytest.mark.health


def _wait_for(fn, timeout=8.0, interval=0.05):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        v = fn()
        if v:
            return v
        time.sleep(interval)
    return None


@pytest.fixture
def clean_role():
    """Tests that stamp a role / blackbox dir must not leak them into
    the rest of the suite."""
    yield
    obs.set_role(None)
    health.set_blackbox_dir(None)


@pytest.fixture(autouse=True)
def _isolate_journal_ring():
    """The in-memory journal ring is process-wide, and the chaos
    scenarios this module runs emit kinds (replica_evicted, health,
    rpc_reconnect, ...) that LATER test modules wait on — e.g.
    test_serving_fleet's kill test polls journal_events(
    kind="replica_evicted") and must not break early on this
    module's stale events. Drop the ring after every test (seq
    counters are never rewound, so watermark-based consumers are
    unaffected)."""
    yield
    obs.clear_journal()


# ---------------------------------------------------------------------------
# beacon + watchdog core
# ---------------------------------------------------------------------------

class TestWatchdog:
    def test_stall_fires_within_deadline_and_clears(self):
        wd = health.Watchdog(role="t", interval_s=0.05)
        b = health.Beacon("probe")
        wd.watch("probe", beacon=b, deadline_s=0.2)
        try:
            v = _wait_for(lambda: (lambda x: x if x["state"] ==
                                   "unhealthy" else None)(
                                       wd.check_now()), timeout=3.0)
            assert v, "stall never fired"
            (p,) = v["problems"]
            assert p["reason"] == "stall:probe"
            assert p["kind"] == "stall"
            assert p["severity"] == "unhealthy"
            # verdict surfaced as a journal `health` event...
            evs = [e for e in obs.journal_events(kind="health")
                   if e.get("reason") == "stall:probe"
                   and e.get("action") == "raise"]
            assert evs and evs[-1]["severity"] == "unhealthy"
            # ...and as the health_state{role,reason} gauge
            reg = obs.registry()
            assert reg.gauge("health_state", role="t",
                             reason="stall:probe").value == 2.0
            assert reg.gauge("health_state", role="t",
                             reason="overall").value == 2.0
            # progress clears it (journal clear event + gauge reset)
            b.bump()
            v = wd.check_now()
            assert v["state"] == "healthy" and not v["problems"]
            assert any(e.get("action") == "clear" for e in
                       obs.journal_events(kind="health")
                       if e.get("reason") == "stall:probe")
            assert reg.gauge("health_state", role="t",
                             reason="stall:probe").value == 0.0
        finally:
            wd.stop()

    def test_no_false_positive_while_progressing(self):
        """A healthy loop that keeps bumping inside the deadline must
        never trip the watchdog, however long it runs."""
        wd = health.Watchdog(role="t", interval_s=0.03)
        b = health.Beacon("busy")
        wd.watch("busy", beacon=b, deadline_s=0.3)
        try:
            t_end = time.monotonic() + 1.0
            while time.monotonic() < t_end:
                b.bump()
                time.sleep(0.02)
                assert wd.check_now()["state"] == "healthy"
        finally:
            wd.stop()

    def test_pending_gate(self):
        """No work pending -> an idle beacon is healthy; pending work
        starts the stall clock."""
        wd = health.Watchdog(role="t", interval_s=0.05)
        b = health.Beacon("gated")
        pending = [False]
        wd.watch("gated", beacon=b, deadline_s=0.15,
                 pending_fn=lambda: pending[0])
        try:
            time.sleep(0.4)
            assert wd.check_now()["state"] == "healthy"
            pending[0] = True
            v = _wait_for(lambda: (lambda x: x if x["problems"]
                                   else None)(wd.check_now()),
                          timeout=3.0)
            assert v and v["problems"][0]["reason"] == "stall:gated"
            # the stall clock started when pending went TRUE, not at
            # the (much older) last bump
            assert v["problems"][0]["stalled_s"] < 2.0
        finally:
            wd.stop()

    def test_unwatch_removes(self):
        wd = health.Watchdog(role="t", interval_s=0.05)
        h = wd.watch("gone", beacon=health.Beacon("gone"),
                     deadline_s=0.05)
        time.sleep(0.15)
        assert wd.check_now()["problems"]
        wd.unwatch(h)
        assert not wd.check_now()["problems"]
        wd.stop()


class TestHealthRules:
    def test_recompile_storm_rate_above(self):
        reg = MetricsRegistry()
        wd = health.Watchdog(role="t", interval_s=999, registry_=reg)
        wd.add_rule(health.HealthRule.rate_above(
            "recompile_storm", "executor_compiles_total", per_s=2.0,
            window_s=5.0))
        c = reg.counter("executor_compiles_total")
        wd.check_now()
        assert wd.check_now()["state"] == "healthy"
        for _ in range(4):
            c.inc(5)
            time.sleep(0.05)
            v = wd.check_now()
        assert v["problems"] and \
            v["problems"][0]["reason"] == "recompile_storm"
        assert v["problems"][0]["severity"] == "degraded"
        wd.stop()

    def test_queue_saturation_gauge(self):
        reg = MetricsRegistry()
        wd = health.Watchdog(role="t", interval_s=999, registry_=reg)
        wd.add_rule(health.HealthRule.gauge_above(
            "queue_saturation", "serving_queue_depth", threshold=10))
        g = reg.gauge("serving_queue_depth", model="m")
        g.set(3)
        assert wd.check_now()["state"] == "healthy"
        g.set(12)
        v = wd.check_now()
        assert v["problems"][0]["reason"] == "queue_saturation"
        g.set(0)
        assert wd.check_now()["state"] == "healthy"
        wd.stop()

    def test_throughput_collapse_vs_rolling_baseline(self):
        reg = MetricsRegistry()
        wd = health.Watchdog(role="t", interval_s=999, registry_=reg)
        wd.add_rule(health.HealthRule.rate_collapse(
            "throughput_collapse", "executor_steps_total",
            frac=0.25, window_s=0.4, min_rate=10.0))
        c = reg.counter("executor_steps_total")
        # establish the baseline: steady fast progress
        for _ in range(10):
            c.inc(20)
            time.sleep(0.05)
            wd.check_now()
        assert wd.check_now()["state"] == "healthy"
        # collapse: counter freezes; windowed rate decays to ~0 while
        # the EWMA baseline remembers the established pace
        v = _wait_for(lambda: (lambda x: x if x["problems"]
                               else None)(wd.check_now()),
                      timeout=5.0, interval=0.1)
        assert v, "collapse never detected"
        assert v["problems"][0]["reason"] == "throughput_collapse"
        assert v["problems"][0]["baseline"] > 0
        wd.stop()


# ---------------------------------------------------------------------------
# flight recorder
# ---------------------------------------------------------------------------

class TestFlightRecorder:
    def test_dump_contents(self, tmp_path):
        rec = health.FlightRecorder(role="boxtest",
                                    dir=str(tmp_path))
        obs.registry().counter("box_probe_total").inc(3)
        rec.sample()
        obs.emit("box_probe_event", x=1)
        parked = threading.Event()
        release = threading.Event()

        def park():
            parked.set()
            release.wait(10)

        t = threading.Thread(target=park, name="park-me",
                             daemon=True)
        t.start()
        parked.wait(5)
        try:
            path = rec.dump("unit-test", extra={"k": "v"})
            assert os.path.basename(path) == "blackbox.boxtest.json"
            box = json.load(open(path))
            assert box["reason"] == "unit-test"
            assert box["extra"] == {"k": "v"}
            # all-thread stacks include the parked thread at its park
            names = {s["name"]: "".join(s["frames"])
                     for s in box["stacks"]}
            assert "park-me" in names
            assert "release.wait" in names["park-me"]
            # journal tail + metric samples + beacon ages ride along
            assert any(e["kind"] == "box_probe_event"
                       for e in box["journal_tail"])
            assert len(box["metric_samples"]) == 1
            assert "box_probe_total" in box["metrics"]["counters"]
            assert isinstance(box["beacons"], dict)
        finally:
            release.set()

    def test_dump_without_dir_is_noop(self):
        rec = health.FlightRecorder(role="nodir", dir=None)
        assert rec.dump_path() is None
        assert rec.dump("whatever") is None

    def test_blackbox_dump_on_sigterm(self, tmp_path):
        """A SIGTERMed process leaves blackbox.<role>.json with its
        thread stacks and journal tail — the black-box contract for a
        killed replica/worker."""
        code = (
            "import sys, time, threading\n"
            "sys.path.insert(0, %r)\n"
            "from paddle_tpu.observability import health, journal\n"
            "journal.set_role('victim')\n"
            "rec = health.get_recorder()\n"
            "rec.set_dir(%r)\n"
            "assert rec.install_signal_handlers()\n"
            "journal.emit('victim_alive', pid=1)\n"
            "ev = threading.Event()\n"
            "threading.Thread(target=ev.wait, args=(60,),\n"
            "                 name='parked-worker',\n"
            "                 daemon=True).start()\n"
            "print('READY', flush=True)\n"
            "time.sleep(60)\n" % (ROOT, str(tmp_path)))
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        proc = subprocess.Popen([sys.executable, "-c", code],
                                stdout=subprocess.PIPE, env=env,
                                text=True)
        try:
            line = proc.stdout.readline()
            assert "READY" in line, line
            proc.send_signal(signal.SIGTERM)
            proc.wait(timeout=30)
        finally:
            if proc.poll() is None:
                proc.kill()
        box_path = tmp_path / "blackbox.victim.json"
        assert box_path.exists(), list(tmp_path.iterdir())
        box = json.load(open(str(box_path)))
        assert box["reason"] == "SIGTERM"
        assert box["role"] == "victim"
        # all-thread capture: the main thread is there (its top
        # frames are the signal handler that took the dump — the
        # park site sits underneath), and the parked worker thread's
        # stack shows exactly where it waited
        stacks = {s["name"]: "".join(s["frames"])
                  for s in box["stacks"]}
        assert "MainThread" in stacks
        assert "parked-worker" in stacks
        assert "wait" in stacks["parked-worker"]
        assert any(e["kind"] == "victim_alive"
                   for e in box["journal_tail"])
        # the faulthandler C-level twin exists too (fires even when
        # no Python handler can run)
        assert (tmp_path / "blackbox.victim.stacks.txt").exists()


# ---------------------------------------------------------------------------
# /healthz verdict
# ---------------------------------------------------------------------------

class TestHealthz:
    def test_unknown_without_watchdog(self, monkeypatch):
        monkeypatch.setattr(health, "_WATCHDOG", None)
        code, v = health.healthz()
        assert code == 200 and v["state"] == "unknown"

    def test_healthz_scrape_healthy_and_503_on_stall(self,
                                                     monkeypatch):
        wd = health.Watchdog(role="hz", interval_s=999)
        monkeypatch.setattr(health, "_WATCHDOG", wd)
        b = health.Beacon("hz_probe")
        wd.watch("hz_probe", beacon=b, deadline_s=0.1)
        with obs.start_metrics_server() as srv:
            b.bump()
            r = urllib.request.urlopen(srv.url + "/healthz")
            assert r.status == 200
            v = json.loads(r.read().decode())
            assert v["state"] == "healthy"
            assert "hz_probe" in v["watches"]
            time.sleep(0.3)  # now stalled past the deadline
            with pytest.raises(urllib.error.HTTPError) as ei:
                urllib.request.urlopen(srv.url + "/healthz")
            assert ei.value.code == 503
            v = json.loads(ei.value.read().decode())
            assert v["state"] == "unhealthy"
            assert v["problems"][0]["reason"] == "stall:hz_probe"
        wd.stop()


# ---------------------------------------------------------------------------
# wedge acceptance: stalled serving batcher + parked PS barrier
# ---------------------------------------------------------------------------

def _save_mlp_model(tmp_path, in_dim=16, out_dim=4):
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = 7
    with fluid.program_guard(main, startup):
        x = layers.data(name="x", shape=[in_dim], dtype="float32")
        h = layers.fc(x, size=8, act="relu")
        pred = layers.fc(h, size=out_dim, act="softmax")
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe = fluid.Executor()
        exe.run(startup)
        d = str(tmp_path / "model")
        fluid.io.save_inference_model(d, ["x"], [pred], exe,
                                      main_program=main, scope=scope)
    return d


@pytest.mark.chaos
class TestWedgeDetection:
    def test_stalled_batcher_verdict_and_blackbox(self, tmp_path,
                                                  clean_role):
        """The acceptance wedge: a batcher thread that neither dies
        nor dispatches while a request is queued must produce an
        unhealthy stall verdict within its deadline AND a
        blackbox.<role>.json holding all-thread stacks + journal
        tail."""
        from paddle_tpu.serving import ServingConfig, ServingEngine
        obs.set_role("serving-wedge")
        health.set_blackbox_dir(str(tmp_path))
        model_dir = _save_mlp_model(tmp_path)
        engine = ServingEngine(model_dir, ServingConfig(
            max_batch_size=8, max_queue_wait_us=500,
            hang_deadline_s=0.4))
        worker = engine._workers["default"]
        hold = threading.Event()

        def wedge(w, batch):
            hold.wait(20)

        worker._dispatch_hook = wedge
        t0 = time.monotonic()
        fut = engine.infer({"x": np.zeros((1, 16), np.float32)})
        reason = "stall:serving_batcher/default"
        wd = health.get_watchdog()
        v = _wait_for(lambda: (lambda x: x if any(
            p["reason"] == reason for p in x["problems"]) else None)(
                wd.check_now()), timeout=10.0)
        detected_after = time.monotonic() - t0
        try:
            assert v, "stalled batcher never detected"
            # detected within deadline + a couple of watchdog ticks
            assert detected_after < 5.0
            box_path = tmp_path / "blackbox.serving-wedge.json"
            assert box_path.exists(), \
                "stall verdict did not dump the black box"
            box = json.load(open(str(box_path)))
            assert box["reason"] == "watchdog:%s" % reason
            joined = "".join("".join(s["frames"])
                             for s in box["stacks"])
            assert "hold.wait" in joined  # the wedged frame is cited
            assert box["journal_tail"], "journal tail missing"
        finally:
            hold.set()
        fut.result(timeout=20)
        # progress clears the verdict
        assert _wait_for(lambda: not any(
            p["reason"] == reason
            for p in wd.check_now()["problems"])), \
            "verdict did not clear after the batcher resumed"
        engine.shutdown(drain=True, timeout=10)

    def test_parked_ps_barrier_verdict(self, clean_role):
        """A barrier parked past its stall deadline (quorum can never
        form: 1 of 2 trainers arrived, no leases armed) must raise an
        unhealthy verdict, and the shutdown release must clear the
        beacon's pending state."""
        from paddle_tpu.distributed.ps import ListenAndServ
        from paddle_tpu.distributed.rpc import RPCClient
        s = ListenAndServ(
            "127.0.0.1:0", {"w": np.zeros(2, np.float32)},
            lambda name, grad: None, n_trainers=2, sync_mode=True,
            barrier_stall_s=0.4)
        s.start()
        client = RPCClient(s.endpoint, deadline_s=15.0, trainer_id=0)
        errors = []

        def barrier_call():
            try:
                client.barrier("send")
            except Exception as e:
                errors.append(e)

        th = threading.Thread(target=barrier_call, daemon=True)
        th.start()
        reason = "stall:ps_barrier@%s" % s.endpoint
        wd = health.get_watchdog()
        v = _wait_for(lambda: (lambda x: x if any(
            p["reason"] == reason for p in x["problems"]) else None)(
                wd.check_now()), timeout=10.0)
        assert v, "parked barrier never detected"
        p = next(p for p in v["problems"] if p["reason"] == reason)
        assert p["severity"] == "unhealthy"
        s.shutdown()  # answers the waiter with BarrierAborted
        th.join(timeout=10)
        assert errors, "parked waiter was not released"
        client.close()
        # watch unregistered at shutdown: the verdict no longer
        # carries the barrier problem
        assert _wait_for(lambda: not any(
            p["reason"] == reason
            for p in wd.check_now()["problems"]))


# ---------------------------------------------------------------------------
# journal rotation (satellite)
# ---------------------------------------------------------------------------

class TestJournalRotation:
    def test_rotation_keeps_one_and_read_stitches(self, tmp_path):
        path = str(tmp_path / "events.jsonl")
        obs.configure_journal(path, max_bytes=4096)
        try:
            # emit until exactly one rotation fires, then a few more
            # into the fresh live file — with a single rotation the
            # stitched read must be lossless
            n = 0
            while not os.path.exists(path + ".1") and n < 80:
                obs.emit("rotation_probe", i=n, pad="x" * 80)
                n += 1
            assert os.path.exists(path + ".1"), \
                "rotation never fired"
            for _ in range(5):
                obs.emit("rotation_probe", i=n, pad="x" * 80)
                n += 1
        finally:
            obs.configure_journal(None)
        # keep-one: neither file grows much past the bound
        assert os.path.getsize(path) <= 4096 + 512
        assert os.path.getsize(path + ".1") <= 4096 + 512
        # read_journal stitches rotated + live into one contiguous,
        # seq-ordered stream covering every event emitted
        evs = [e for e in obs.read_journal(path)
               if e["kind"] == "rotation_probe"]
        assert len(evs) == n  # one rotation: nothing lost
        assert [e["i"] for e in evs] == list(range(n))
        seqs = [e["seq"] for e in evs]
        assert seqs == sorted(seqs)
        # include_rotated=False sees only the live tail
        live = [e for e in obs.read_journal(path,
                                            include_rotated=False)
                if e["kind"] == "rotation_probe"]
        assert 0 < len(live) < n


# ---------------------------------------------------------------------------
# doctor (offline auto-diagnosis)
# ---------------------------------------------------------------------------

class TestDoctor:
    def _ev(self, kind, seq, **kw):
        kw.setdefault("role", "tester")
        kw.setdefault("t_wall", float(seq))
        return dict(kind=kind, seq=seq, **kw)

    def test_trainer_eviction_named_with_seq_evidence(self):
        import doctor
        rep = doctor.diagnose([
            self._ev("trainer_evicted", 412, tid=1,
                     endpoint="h:7000", lease_timeout_s=0.6,
                     role="pserver-1"),
            self._ev("barrier_aborted", 413, tids=[1],
                     role="pserver-1"),
        ])
        assert rep["top"] == "trainer_eviction"
        d = rep["diagnoses"][0]
        assert "lease expired" in d["summary"]
        assert "BarrierAborted" in d["summary"]
        cited = {c["seq"] for c in d["evidence"]}
        assert 412 in cited and 413 in cited

    def test_pserver_restart_beats_network_flaky(self):
        import doctor
        evs = [self._ev("snapshot", 10, boundary=3,
                        endpoint="h:1", role="pserver-0")]
        evs += [self._ev("rpc_reconnect", 20 + i, endpoint="h:1",
                         reconnects=i + 1, role="trainer-0")
                for i in range(4)]
        evs.append(self._ev("phase_replay", 30, what="step",
                            role="trainer-0"))
        rep = doctor.diagnose(evs)
        assert rep["top"] == "pserver_restart"
        names = [d["name"] for d in rep["diagnoses"]]
        assert "network_flaky" in names  # present, ranked below
        assert "snapshot at seq 10" in rep["diagnoses"][0]["summary"]

    def test_reconnects_without_snapshot_is_network_flaky(self):
        import doctor
        evs = [self._ev("rpc_reconnect", i + 1, endpoint="h:%d" % i,
                        role="trainer-0") for i in range(5)]
        rep = doctor.diagnose(evs)
        assert rep["top"] == "network_flaky"

    def test_recompile_storm_rate(self):
        import doctor
        evs = [self._ev("executor_compile", i + 1, entry="run",
                        nth=i, t_wall=100.0 + i * 1.5)
               for i in range(12)]
        rep = doctor.diagnose(evs)
        assert rep["top"] == "recompile_storm"
        assert "compiles/min" in rep["diagnoses"][0]["summary"]

    def test_input_bound_from_metrics_snapshot(self):
        import doctor
        rep = doctor.diagnose(
            [], metrics=[{"gauges": {"input_stall_fraction": 0.41}}])
        assert rep["top"] == "input_bound"
        assert "0.41" in rep["diagnoses"][0]["summary"]

    def test_hang_from_health_event_and_blackbox(self):
        import doctor
        rep = doctor.diagnose(
            [self._ev("health", 9, action="raise",
                      severity="unhealthy",
                      reason="stall:serving_batcher/default",
                      detail="no progress for 1.2s",
                      role="serving-0")],
            blackboxes=[{"reason":
                         "watchdog:stall:serving_batcher/default",
                         "role": "serving-0", "_path": "bb.json",
                         "stacks": [{"name": "serving-batcher-default",
                                     "frames": ["  ...",
                                                "    hold.wait(20)"]}]
                         }])
        assert rep["top"] == "hang"
        assert rep["diagnoses"][0]["detail"]  # cites the parked frame
        assert "hold.wait" in rep["diagnoses"][0]["detail"]

    def test_cli_expect_gate(self, tmp_path):
        import doctor
        p = str(tmp_path / "j.jsonl")
        with open(p, "w") as f:
            f.write(json.dumps(self._ev(
                "trainer_evicted", 5, tid=0, endpoint="e",
                role="pserver-0")) + "\n")
        assert doctor.main(["--journal", p, "--json",
                            "--expect", "trainer_eviction"]) == 0
        assert doctor.main(["--journal", p,
                            "--expect", "pserver_restart"]) == 1


# ---------------------------------------------------------------------------
# chaos: doctor must name the injected fault for real scenarios
# ---------------------------------------------------------------------------

@pytest.mark.chaos
class TestChaosDoctor:
    def _args(self, steps, **kw):
        import argparse
        return argparse.Namespace(seed=0, steps=steps, **kw)

    @pytest.mark.slow
    def test_serving_kill_diagnosed(self):
        """Run the real serving_kill chaos scenario (3 replicas, 5%
        drop, replica 0 SIGKILLed mid-flight) and assert doctor names
        replica_failure from the journal alone, citing seq
        evidence.

        ``slow`` since PR 15 (tier-1 headroom trim, the PR 14
        discipline): the replica-SIGKILL fault class stays covered in
        tier-1 twice over — test_serving_fleet's ``-m chaos`` kill
        test (zero lost futures, eviction causality) and
        test_control's ``control_loop`` scenario, whose doctor gate is
        STRICTER than this one (replica-kill diagnosis AND the full
        remediation audit). The CLI chaos suite still runs this
        scenario with ``--verdict doctor``."""
        import chaos_run
        res = chaos_run._scenario_serving_kill(self._args(4))
        assert res["ok"], res
        doc = res["doctor"]
        assert doc["top"] == "replica_failure", doc
        assert doc["match"], doc
        assert any(c.get("seq") is not None
                   for c in doc["evidence"]), doc

    # tier-1 headroom (PR 18): full 2x2 restart chaos scenario (~53 s) -> slow;
    # doctor restart diagnosis stays via TestDoctor::
    # test_pserver_restart_beats_network_flaky and exact restart
    # trajectories via test_distributed_chaos.py::TestPServerKillRestart
    @pytest.mark.slow
    def test_restart_2x2_obs_diagnosed(self):
        """The 2x2 pserver kill+restart scenario must be diagnosed as
        pserver_restart (snapshot -> reconnect/replay evidence) —
        UNDER the 5% wire drop. This test used to run at drop_rate=0.0
        because an unlucky drop pattern could phase-lock the two
        trainers' barrier replays into a 360 s retry storm; the
        barrier replay-epoch fence (a replayed already-released
        barrier is re-acked, never re-parked into the next step's
        quorum — ``dup_barrier_ack``) plus jittered replay backoff
        eliminated that class, so the lossy-wire variant is back in
        tier-1. The scenario's own ok-verdict bounds the wall time
        (steps=3 keeps the tier-1 cost down; the CLI chaos suite runs
        the longer default)."""
        import chaos_run
        res = chaos_run._scenario_restart_2x2_obs(
            self._args(3, drop_rate=0.05))
        assert res["ok"], res
        doc = res["doctor"]
        assert doc["top"] == "pserver_restart", doc
        assert doc["match"], doc
        assert any(c.get("seq") is not None
                   for c in doc["evidence"]), doc


# ---------------------------------------------------------------------------
# bench_diff (satellite)
# ---------------------------------------------------------------------------

class TestBenchDiff:
    def test_hang_flagged_on_repo_history(self):
        """The repo's own BENCH_r01..r05 artifacts: the transformer
        headline measured 65.8k in r1 and degraded to claim-timeout
        nulls — bench_diff must flag the value->null transition as
        HANG, loudly."""
        import bench_diff
        files = [os.path.join(ROOT, "BENCH_r%02d.json" % n)
                 for n in range(1, 6)]
        report = bench_diff.diff(bench_diff.load_rounds(files))
        hangs = [f for f in report["hangs"]
                 if f["metric"] == "transformer_base_train_throughput"]
        assert hangs, report["flags"]
        text = bench_diff.format_report(report)
        assert "HANG" in text
        # strict mode exits nonzero on the hang
        assert bench_diff.main(files + ["--strict", "--json"]) == 1

    def test_regression_and_recovery_flags(self, tmp_path):
        import bench_diff
        r1 = tmp_path / "BENCH_r01.json"
        r2 = tmp_path / "BENCH_r02.json"
        rows1 = [{"metric": "m_throughput", "value": 100.0,
                  "unit": "examples/sec"},
                 {"metric": "p99_latency", "value": 10.0,
                  "unit": "ms"},
                 {"metric": "dead_row", "value": None,
                  "error": "boom"}]
        rows2 = [{"metric": "m_throughput", "value": 50.0,
                  "unit": "examples/sec"},
                 {"metric": "p99_latency", "value": 30.0,
                  "unit": "ms"},
                 {"metric": "dead_row", "value": 5.0}]
        r1.write_text(json.dumps(
            {"n": 1, "tail": "\n".join(json.dumps(r)
                                       for r in rows1)}))
        r2.write_text(json.dumps(
            {"n": 2, "tail": "\n".join(json.dumps(r)
                                       for r in rows2)}))
        report = bench_diff.diff(
            bench_diff.load_rounds([str(r1), str(r2)]))
        flags = {(f["metric"], f["flag"]) for f in report["flags"]}
        assert ("m_throughput", "REGRESSION") in flags
        # lower-is-better heuristic: a latency RISE is the regression
        assert ("p99_latency", "REGRESSION") in flags
        assert ("dead_row", "RECOVERED") in flags

    def test_sparse_throughput_metrics_direction(self, tmp_path):
        """ISSUE 14 satellite: the sparse rows (rows/s throughput and
        cache hit rate) are registered HIGHER-is-better, both
        directions — a drop flags REGRESSION, a rise does not (the
        raw unit strings would otherwise trip the lower-is-better
        'rate/fraction' heuristics)."""
        import bench_diff

        def write(path, n, rps, hit):
            rows = [{"metric": "sparse_embedding_throughput",
                     "value": rps,
                     "unit": "rows/s (zipf0.9, cache+q8)"},
                    {"metric": "sparse_embedding_throughput_mix",
                     "library": "zipf0.9/cache/q8", "value": hit,
                     "unit": "cache hit rate fraction"}]
            path.write_text(json.dumps(
                {"n": n, "tail": "\n".join(json.dumps(r)
                                           for r in rows)}))

        r1 = tmp_path / "BENCH_r01.json"
        r2 = tmp_path / "BENCH_r02.json"
        # direction 1: a DROP in rows/s and hit rate is a regression
        write(r1, 1, 50000.0, 0.85)
        write(r2, 2, 20000.0, 0.40)
        report = bench_diff.diff(
            bench_diff.load_rounds([str(r1), str(r2)]))
        flags = {(f["metric"], f["flag"]) for f in report["flags"]}
        assert ("sparse_embedding_throughput", "REGRESSION") in flags
        assert ("sparse_embedding_throughput_mix[zipf0.9/cache/q8]",
                "REGRESSION") in flags
        # direction 2: a RISE reads as an improvement, no flag
        write(r1, 1, 20000.0, 0.40)
        write(r2, 2, 50000.0, 0.85)
        report = bench_diff.diff(
            bench_diff.load_rounds([str(r1), str(r2)]))
        assert not report["regressions"], report["flags"]


# ---------------------------------------------------------------------------
# singleton-lock reentrancy (PR 11 hardening)
# ---------------------------------------------------------------------------

class TestSingletonReentrancy:
    def test_accessors_safe_under_singleton_lock(self):
        """Regression for the known `_SINGLETON_MU` pitfall: the
        singleton accessors must be callable while the lock is already
        held by the same thread (a future watchdog/recorder callback
        reaching back into the accessors is exactly this shape). With
        the old non-reentrant Lock this thread parks forever — the
        deadlock that only ever surfaced in the CLI path, because
        pytest happened to create the recorder first."""
        done = []

        def inner():
            with health._SINGLETON_MU:
                health.get_recorder()
                health.get_watchdog()
            done.append(True)

        t = threading.Thread(target=inner, daemon=True)
        t.start()
        t.join(timeout=10)
        assert done, ("health singleton accessors deadlocked while "
                      "_SINGLETON_MU was held by the calling thread")

    def test_get_watchdog_still_singleton(self):
        wd1 = health.get_watchdog()
        wd2 = health.get_watchdog()
        assert wd1 is wd2
        assert health.get_recorder() in wd1._recorders
