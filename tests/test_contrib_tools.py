"""Contrib facades and tools (reference:
python/paddle/fluid/contrib/{model_stat,op_frequence,
memory_usage_calc,trainer,inferencer}.py + contrib/utils/ + the NAS
search space)."""

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import layers


def _small_cnn_program():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        img = layers.data("img", shape=[3, 8, 8])
        label = layers.data("label", shape=[1], dtype="int64")
        x = layers.conv2d(img, num_filters=4, filter_size=3,
                          padding=1)
        x = layers.batch_norm(x, act="relu")
        x = layers.pool2d(x, pool_size=2, pool_stride=2)
        pred = layers.fc(x, size=10, act="softmax")
        loss = layers.mean(layers.cross_entropy(pred, label))
    return main, startup, loss


class TestModelStat:
    def test_summary_counts(self, capsys):
        from paddle_tpu.contrib.model_stat import summary

        main, _s, _l = _small_cnn_program()
        rows, params, flops = summary(main)
        out = capsys.readouterr().out
        assert "Total PARAMs" in out and "conv2d" in out
        conv = [r for r in rows if r["type"] == "conv2d"][0]
        # 4 filters x (3*3*3 kernel) [no bias input slot on the op]
        assert conv["PARAMs"] in (108, 112)
        assert conv["FLOPs"] == 2 * 8 * 8 * 4 * 27
        mul = [r for r in rows if r["type"] == "mul"][0]
        assert mul["PARAMs"] == 4 * 4 * 4 * 10
        assert params == sum(r["PARAMs"] for r in rows)
        assert flops > 0


class TestOpFrequence:
    def test_frequency_and_pairs(self):
        from paddle_tpu.contrib import op_freq_statistic

        main, _s, _l = _small_cnn_program()
        uni, adj = op_freq_statistic(main)
        uni_d = dict(uni)
        assert uni_d["conv2d"] == 1
        assert uni_d["mul"] >= 1
        assert uni[0][1] >= uni[-1][1]  # sorted descending
        assert any("->" in k for k, _v in adj)

    def test_type_error(self):
        from paddle_tpu.contrib import op_freq_statistic
        with pytest.raises(TypeError):
            op_freq_statistic("not a program")


class TestMemoryUsage:
    def test_estimate(self):
        from paddle_tpu.contrib import memory_usage

        main, _s, _l = _small_cnn_program()
        lo, hi, unit = memory_usage(main, batch_size=32)
        assert 0 < lo < hi
        assert unit in ("B", "KB", "MB")
        lo2, hi2, unit2 = memory_usage(main, batch_size=64)
        # bigger batch, not smaller estimate (unit may coarsen)
        assert (unit2 != unit) or lo2 > lo

    def test_errors(self):
        from paddle_tpu.contrib import memory_usage
        with pytest.raises(TypeError):
            memory_usage("x", 4)
        main, _s, _l = _small_cnn_program()
        with pytest.raises(ValueError):
            memory_usage(main, 0)


class TestTrainerInferencer:
    def test_train_save_infer_roundtrip(self, tmp_path):
        from paddle_tpu.contrib import Inferencer, Trainer

        w_true = np.linspace(-0.5, 0.5, 6).astype(np.float32)

        def train_func():
            x = layers.data("x", shape=[6])
            y = layers.data("y", shape=[1])
            pred = layers.fc(x, size=1,
                             param_attr=fluid.ParamAttr(name="w"))
            return layers.reduce_mean(
                layers.square_error_cost(input=pred, label=y))

        def optimizer_func():
            return fluid.optimizer.SGD(0.2)

        def reader():
            rs = np.random.RandomState(0)
            for _ in range(40):
                x = rs.rand(16, 6).astype(np.float32)
                y = x @ w_true[:, None]
                yield list(zip(x, y))

        seen = {"steps": 0, "epochs": 0, "losses": []}

        def handler(event):
            from paddle_tpu.contrib import (BeginEpochEvent,
                                            EndStepEvent)
            if isinstance(event, EndStepEvent):
                seen["steps"] += 1
                seen["losses"].append(
                    float(np.asarray(event.metrics[0]).reshape(-1)[0]))
            elif isinstance(event, BeginEpochEvent):
                seen["epochs"] += 1

        scope = fluid.Scope()
        with fluid.scope_guard(scope):
            tr = Trainer(train_func=train_func,
                         optimizer_func=optimizer_func)
            tr.train(num_epochs=2, event_handler=handler,
                     reader=reader, feed_order=["x", "y"])
            assert seen["epochs"] == 2 and seen["steps"] == 80
            assert seen["losses"][-1] < seen["losses"][0] * 0.2
            test_metrics = tr.test(reader=reader,
                                   feed_order=["x", "y"])
            assert test_metrics[0] < seen["losses"][0]
            tr.save_params(str(tmp_path / "model"))

        def infer_func():
            x = layers.data("x", shape=[6])
            return layers.fc(x, size=1,
                             param_attr=fluid.ParamAttr(name="w"))

        inf = Inferencer(infer_func=infer_func,
                         param_path=str(tmp_path / "model"))
        xs = np.eye(6, dtype=np.float32)
        (got,) = inf.infer({"x": xs})
        # trained weights approximate w_true on the identity probe
        assert np.abs(np.asarray(got).reshape(-1)
                      - w_true).max() < 0.2

        with pytest.raises(ValueError):
            inf.infer([1, 2, 3])

    def test_stop(self):
        from paddle_tpu.contrib import EndStepEvent, Trainer

        def train_func():
            x = layers.data("x", shape=[2])
            y = layers.data("y", shape=[1])
            pred = layers.fc(x, size=1)
            return layers.reduce_mean(
                layers.square_error_cost(input=pred, label=y))

        scope = fluid.Scope()
        with fluid.scope_guard(scope):
            tr = Trainer(train_func=train_func,
                         optimizer_func=lambda: fluid.optimizer.SGD(
                             0.1))
            count = {"n": 0}

            def handler(event):
                if isinstance(event, EndStepEvent):
                    count["n"] += 1
                    tr.stop()

            def reader():
                for _ in range(100):
                    yield [(np.zeros(2, np.float32),
                            np.zeros(1, np.float32))] * 4

            tr.train(2, handler, reader=reader, feed_order=["x", "y"])
            assert count["n"] == 1


class TestHDFSUtils:
    def _client(self, fs):
        """HDFSClient against an in-memory fake 'hadoop fs'."""
        from paddle_tpu.contrib.utils import HDFSClient

        def runner(cmd):
            i = cmd.index("fs") + 1
            args = [a for a in cmd[i:] if not a.startswith("-D")]
            op = args[0]
            if op == "-test":
                flag, path = args[1], args[2]
                if flag == "-e":
                    return (0 if path in fs or any(
                        k.startswith(path + "/") for k in fs) else 1,
                        [])
                return (0 if any(k.startswith(path + "/")
                                 for k in fs) else 1, [])
            if op == "-mkdir":
                return 0, []
            if op == "-rm":
                for k in [k for k in fs if k == args[-1]
                          or k.startswith(args[-1] + "/")]:
                    del fs[k]
                return 0, []
            if op == "-mv":
                fs[args[2]] = fs.pop(args[1])
                return 0, []
            if op == "-put":
                with open(args[1]) as f:
                    fs[args[2]] = f.read()
                return 0, []
            if op == "-get":
                if args[1] not in fs:
                    return 1, ["get: no such file"]
                with open(args[2], "w") as f:
                    f.write(fs[args[1]])
                return 0, []
            if op == "-ls":
                rec = args[1] == "-R"
                path = args[-1]
                rows = ["-rw-r--r-- 1 u g 1 2026-01-01 00:00 %s" % k
                        for k in sorted(fs)
                        if k.startswith(path + "/") or k == path]
                del rec
                return 0, rows
            return 1, ["unknown op %s" % op]

        return HDFSClient("/opt/hadoop", {"fs.default.name": "x",
                                          "hadoop.job.ugi": "u,p"},
                          runner=runner)

    def test_roundtrip(self, tmp_path):
        fs = {}
        client = self._client(fs)
        local = tmp_path / "a.txt"
        local.write_text("hello")
        assert client.upload("/data/a.txt", str(local))
        assert client.is_exist("/data/a.txt")
        assert client.is_dir("/data")
        assert client.is_file("/data/a.txt")
        assert client.ls("/data") == ["/data/a.txt"]
        dst = tmp_path / "b.txt"
        assert client.download("/data/a.txt", str(dst))
        assert dst.read_text() == "hello"
        assert client.rename("/data/a.txt", "/data/c.txt")
        assert not client.is_exist("/data/a.txt")
        assert client.delete("/data/c.txt")
        assert not client.is_exist("/data/c.txt")

    def test_multi_transfer(self, tmp_path):
        from paddle_tpu.contrib.utils import (multi_download,
                                              multi_upload)
        fs = {}
        client = self._client(fs)
        src = tmp_path / "src"
        src.mkdir()
        for i in range(5):
            (src / ("f%d.txt" % i)).write_text("c%d" % i)
        assert multi_upload(client, "/up", str(src),
                            multi_processes=2) == 5
        assert len(fs) == 5
        out = tmp_path / "out"
        files = multi_download(client, "/up", str(out), trainer_id=0,
                               trainers=1, multi_processes=2)
        assert len(files) == 5
        # sharded download: two trainers split the files
        files0 = multi_download(client, "/up",
                                str(tmp_path / "o0"), 0, 2, 1)
        files1 = multi_download(client, "/up",
                                str(tmp_path / "o1"), 1, 2, 1)
        assert len(files0) + len(files1) == 5


class TestLookupTableUtils:
    def test_save_load_increment_and_inference(self, tmp_path):
        from paddle_tpu.contrib.utils import (
            convert_dist_to_sparse_program,
            load_persistables_for_increment,
            load_persistables_for_inference, save_lookup_table)
        from paddle_tpu.distributed.lookup_service import LargeScaleKV

        table = LargeScaleKV(dim=4, seed=3, optimizer="adagrad",
                             lr=0.05, init_std=0.2)
        rows = table.pull([2, 7, 11])
        table.push([2], np.ones((1, 4), np.float32))  # adagrad state
        rows = table.pull([2, 7, 11])
        save_lookup_table(table, str(tmp_path))

        # a program with a distributed lookup
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            ids = layers.data("ids", shape=[3], dtype="int64")
            emb = layers.embedding(ids, size=(16, 4),
                                   is_distributed=True,
                                   name="big_table")
            out = layers.reduce_sum(emb)
        scope = fluid.Scope()
        with fluid.scope_guard(scope):
            exe = fluid.Executor()
            exe.run(startup)
            t2 = load_persistables_for_increment(str(tmp_path), exe,
                                                 main)
            np.testing.assert_allclose(t2.pull([2, 7, 11]), rows,
                                       rtol=1e-6)
            # resume fidelity: hyperparams, lazy-init seed, and the
            # adagrad accumulator survive the checkpoint
            assert (t2.optimizer, t2.seed, t2.lr, t2.init_std) == \
                ("adagrad", 3, 0.05, 0.2)
            np.testing.assert_allclose(t2._accum[2],
                                       table._accum[2], rtol=1e-6)
            # untouched ids lazily init identically after resume
            np.testing.assert_allclose(t2.pull([99]), table.pull([99]),
                                       rtol=1e-6)

            # inference: rewrite to an in-graph lookup + materialize
            infer = convert_dist_to_sparse_program(main)
            exe.run(fluid.Program())  # no-op warm
            # create + init the dense table param in the scope
            blk = infer.global_block()
            assert blk.has_var("big_table")
            scope.set_var("big_table",
                          np.zeros((16, 4), np.float32))
            load_persistables_for_inference(str(tmp_path), exe, infer,
                                            "big_table")
            dense = np.asarray(scope.find_var("big_table"))
            np.testing.assert_allclose(dense[[2, 7, 11]], rows,
                                       rtol=1e-6)
            feed = {"ids": np.array([[2, 7, 11]], np.int64)}
            (val,) = exe.run(infer, feed=feed, fetch_list=[out])
            np.testing.assert_allclose(float(np.asarray(val)),
                                       rows.sum(), rtol=1e-5)


class TestSimpleConvSpace:
    def test_space_contract_and_net(self):
        from paddle_tpu.contrib.slim.nas import SimpleConvSpace

        sp = SimpleConvSpace(num_classes=4, image_shape=(3, 16, 16))
        toks = sp.init_tokens()
        rng = sp.range_table()
        assert len(toks) == len(rng) == 10
        assert all(0 <= t < r for t, r in zip(toks, rng))
        scope = fluid.Scope()
        with fluid.scope_guard(scope):
            main, startup, loss, acc, feeds = sp.create_net(toks)
            exe = fluid.Executor()
            exe.run(startup)
            rs = np.random.RandomState(0)
            feed = {"img": rs.rand(4, 3, 16, 16).astype(np.float32),
                    "label": rs.randint(0, 4, (4, 1)).astype(np.int64)}
            lv, av = exe.run(main, feed=feed, fetch_list=[loss, acc])
            assert np.isfinite(float(np.asarray(lv)))
            assert 0.0 <= float(np.asarray(av)) <= 1.0
        # a different architecture builds too
        scope2 = fluid.Scope()
        with fluid.scope_guard(scope2):
            alt = [t for t in toks]
            alt[0] = (alt[0] + 1) % rng[0]
            main2 = sp.create_net(alt)[0]
            assert main2.global_block().ops


class TestContribExtras:
    def test_extend_with_decoupled_weight_decay(self):
        """AdamW-style decoupling generated for ANY optimizer
        (reference extend_optimizer_with_weight_decay.py): the decay
        uses PRE-update params; coeff=0 is the base optimizer."""
        from paddle_tpu.contrib import (
            extend_with_decoupled_weight_decay)

        SGDW = extend_with_decoupled_weight_decay(
            fluid.optimizer.SGD)
        assert "WithDecoupledWeightDecay" in SGDW.__name__
        with pytest.raises(TypeError):
            extend_with_decoupled_weight_decay("not a class")

        w0 = np.full((4, 1), 2.0, np.float32)

        def run(coeff):
            scope = fluid.Scope()
            with fluid.scope_guard(scope):
                main, startup = fluid.Program(), fluid.Program()
                with fluid.program_guard(main, startup):
                    x = layers.data("x", shape=[4, 4],
                                    append_batch_size=False)
                    init = fluid.initializer.NumpyArrayInitializer
                    pred = layers.fc(
                        x, 1, bias_attr=False,
                        param_attr=fluid.ParamAttr(
                            name="w", initializer=init(w0)))
                    loss = layers.reduce_mean(pred)
                    SGDW(learning_rate=0.1, coeff=coeff).minimize(
                        loss)
                exe = fluid.Executor()
                exe.run(startup)
                exe.run(main,
                        feed={"x": np.ones((4, 4), np.float32)},
                        fetch_list=[loss])
                return np.asarray(scope.find_var("w")).copy()

        base = run(0.0)
        decayed = run(0.1)
        # decoupled: w_decayed = w_base - coeff * w_pre_update
        np.testing.assert_allclose(decayed, base - 0.1 * w0,
                                   rtol=1e-5, atol=1e-6)

    def test_fused_elemwise_activation_layer(self):
        from paddle_tpu.contrib import layers as clayers

        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            a = layers.data("a", shape=[3])
            b = layers.data("b", shape=[3])
            out = clayers.fused_elemwise_activation(
                a, b, ["elementwise_add", "relu"])
            scaled = clayers.fused_elemwise_activation(
                a, b, ["elementwise_add", "scale"], scale=0.5)
            with pytest.raises(ValueError):
                clayers.fused_elemwise_activation(a, b, ["relu"])
            # scale only parameterizes the 'scale' functor
            with pytest.raises(ValueError, match="scale"):
                clayers.fused_elemwise_activation(
                    a, b, ["elementwise_add", "relu"], scale=0.5)
        exe = fluid.Executor()
        exe.run(startup)
        av = np.array([[1.0, -5.0, 2.0]], np.float32)
        bv = np.array([[1.0, 2.0, -4.0]], np.float32)
        got, got_scaled = exe.run(main, feed={"a": av, "b": bv},
                                  fetch_list=[out, scaled])
        np.testing.assert_allclose(np.asarray(got),
                                   np.maximum(av + bv, 0.0))
        np.testing.assert_allclose(np.asarray(got_scaled),
                                   (av + bv) * 0.5)

    def test_decoupled_decay_dygraph_and_clip(self):
        """The factory composes with dygraph mode and grad_clip (the
        base-optimizer surface it must not narrow)."""
        from paddle_tpu import dygraph
        from paddle_tpu.contrib import (
            extend_with_decoupled_weight_decay)

        SGDW = extend_with_decoupled_weight_decay(
            fluid.optimizer.SGD)
        # dygraph: decay applies on pre-update values eagerly
        with dygraph.guard():
            import jax.numpy as jnp
            lin = dygraph.Linear(3, 1)
            lin.weight.value = jnp.ones((3, 1), jnp.float32)
            lin.bias.value = jnp.zeros((1,), jnp.float32)
            opt = SGDW(learning_rate=0.0, coeff=0.1)
            x = dygraph.to_variable(np.ones((2, 3), np.float32))
            d = lin(x)
            loss = dygraph.run_dygraph_op(
                "reduce_mean", {"X": [d * d]},
                {"dim": None, "keep_dim": False, "reduce_all": True})
            opt.minimize(loss, parameter_list=lin.parameters())
            # lr=0 -> pure decay: w <- w - 0.1 * w_pre
            np.testing.assert_allclose(np.asarray(lin.weight.value),
                                       np.full((3, 1), 0.9),
                                       rtol=1e-6)
        # static: grad_clip passes through
        scope = fluid.Scope()
        with fluid.scope_guard(scope):
            main, startup = fluid.Program(), fluid.Program()
            with fluid.program_guard(main, startup):
                xv = layers.data("x", shape=[3])
                loss = layers.reduce_mean(layers.fc(xv, 1))
                SGDW(learning_rate=0.1, coeff=1e-3).minimize(
                    loss,
                    grad_clip=fluid.clip.GradientClipByGlobalNorm(
                        1.0))
            exe = fluid.Executor()
            exe.run(startup)
            exe.run(main, feed={"x": np.ones((2, 3), np.float32)},
                    fetch_list=[loss])

    def test_distributed_batch_reader(self, monkeypatch):
        from paddle_tpu.contrib.reader import (
            distributed_batch_reader)

        src = lambda: iter(range(10))
        monkeypatch.setenv("PADDLE_TRAINERS_NUM", "3")
        monkeypatch.setenv("PADDLE_TRAINER_ID", "1")
        assert list(distributed_batch_reader(src)()) == [1, 4, 7]
        monkeypatch.setenv("PADDLE_TRAINER_ID", "5")
        with pytest.raises(ValueError):
            distributed_batch_reader(src)
