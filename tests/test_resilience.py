"""Guarded training (paddle_tpu/resilience/): in-graph anomaly
detection, auto-rollback, retry/backoff, checkpoint durability, and the
deterministic fault-injection (chaos) suite — ISSUE 2 acceptance.

Reference analog: the Fluid runtime's checkpoint_notify machinery and
PS RPC retry loops (the runtime, not the model script, owns failure
handling)."""

import os
import signal

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import layers
from paddle_tpu.resilience import (FaultInjector, GuardedTrainer,
                                   InjectedDispatchError, RetryPolicy,
                                   RetryBudgetExhausted, SimulatedCrash,
                                   TrainingAborted, guard,
                                   install_anomaly_guard, is_transient,
                                   make_torn_checkpoint, retry_call)


def _build(seed=7, lr=0.1):
    main, start = fluid.Program(), fluid.Program()
    main.random_seed = start.random_seed = seed
    with fluid.unique_name.guard():
        with fluid.program_guard(main, start):
            x = layers.data("x", [16], dtype="float32")
            y = layers.data("label", [1], dtype="int64")
            h = layers.fc(x, size=32, act="relu")
            pred = layers.fc(h, size=4, act="softmax")
            loss = layers.mean(layers.cross_entropy(pred, y))
            fluid.optimizer.SGD(lr).minimize(loss)
    return main, start, loss


def _batches(n, batch=16, seed=0, as_feed=True):
    rng = np.random.RandomState(seed)
    out = []
    for _ in range(n):
        x = rng.rand(batch, 16).astype(np.float32)
        y = np.argmax(x[:, :4], 1).reshape(batch, 1).astype(np.int64)
        out.append({"x": x, "label": y} if as_feed else (x, y))
    return out


# ---------------------------------------------------------------------------
# in-graph anomaly guard
# ---------------------------------------------------------------------------

class TestAnomalyGuard:
    def test_bad_step_is_select_noop(self):
        """A NaN feed must leave every parameter and optimizer slot
        bit-identical while the skip counter advances; the next good
        step trains normally and resets the consecutive counter."""
        main, start, loss = _build()
        scope = fluid.Scope()
        exe = fluid.Executor()
        with fluid.scope_guard(scope):
            exe.run(start)
            install_anomaly_guard(main, loss=loss, scope=scope)
            good = _batches(1)[0]
            bad = dict(good)
            bx = good["x"].copy()
            bx[0, 0] = np.nan
            bad["x"] = bx
            exe.run(main, feed=good, fetch_list=[loss])
            w0 = np.asarray(scope.find_var("fc_0.w_0")).copy()
            (lv,) = exe.run(main, feed=bad, fetch_list=[loss])
            assert not np.isfinite(lv)
            np.testing.assert_array_equal(
                np.asarray(scope.find_var("fc_0.w_0")), w0)
            assert guard.read_counters(scope) == (1.0, 1.0)
            exe.run(main, feed=good, fetch_list=[loss])
            assert guard.read_counters(scope) == (1.0, 0.0)
            assert not np.array_equal(
                np.asarray(scope.find_var("fc_0.w_0")), w0)

    def test_inf_loss_also_skips(self):
        """The flag folds the LOSS in, not just grads — an inf anywhere
        in the checked set gates the update."""
        main, start, loss = _build()
        scope = fluid.Scope()
        exe = fluid.Executor()
        with fluid.scope_guard(scope):
            exe.run(start)
            install_anomaly_guard(main, loss=loss, scope=scope)
            bad = _batches(1)[0]
            bx = bad["x"].copy()
            bx[:] = np.inf
            bad["x"] = bx
            exe.run(main, feed=bad, fetch_list=[loss])
            skipped, consec = guard.read_counters(scope)
            assert (skipped, consec) == (1.0, 1.0)

    def test_counters_carry_through_run_repeated_scan(self):
        """The guard compiles INTO the scan: K poisoned steps inside
        one dispatch self-skip on device and the counters come back in
        the persistable carry (no host round-trips)."""
        main, start, loss = _build()
        scope = fluid.Scope()
        exe = fluid.Executor()
        with fluid.scope_guard(scope):
            exe.run(start)
            install_anomaly_guard(main, loss=loss, scope=scope)
            feed = _batches(1)[0]
            exe.run(main, feed=feed, fetch_list=[loss])  # warm state
            w = np.asarray(scope.find_var("fc_0.w_0")).copy()
            bad = dict(feed)
            bx = feed["x"].copy()
            bx[0, 0] = np.nan
            bad["x"] = bx
            exe.run_repeated(main, feed=bad, fetch_list=[loss],
                             iters=3)
            assert guard.read_counters(scope) == (3.0, 3.0)
            np.testing.assert_array_equal(
                np.asarray(scope.find_var("fc_0.w_0")), w)

    def test_install_is_idempotent_and_needs_optimizer(self):
        main, start, loss = _build()
        scope = fluid.Scope()
        v1 = main._version
        install_anomaly_guard(main, loss=loss, scope=scope)
        v2 = main._version
        install_anomaly_guard(main, loss=loss, scope=scope)
        assert main._version == v2 > v1  # second install is a no-op

        fwd = fluid.Program()
        with fluid.program_guard(fwd):
            x = layers.data("x", [4])
            layers.fc(x, size=2)
        with pytest.raises(Exception, match="optimize"):
            install_anomaly_guard(fwd, scope=scope)

    def test_adam_states_gated_too(self):
        """Adam moments and beta-pow schedules freeze on a skipped step
        (through the batched multi-tensor path, which must apply the
        same select as the per-op gate)."""
        main, start = fluid.Program(), fluid.Program()
        main.random_seed = start.random_seed = 3
        with fluid.unique_name.guard():
            with fluid.program_guard(main, start):
                x = layers.data("x", [8], dtype="float32")
                y = layers.data("y", [1], dtype="float32")
                h = layers.fc(x, size=8, act="tanh")
                p = layers.fc(h, size=1)
                loss = layers.mean(layers.square_error_cost(p, y))
                fluid.optimizer.Adam(1e-2).minimize(loss)
        scope = fluid.Scope()
        exe = fluid.Executor()
        with fluid.scope_guard(scope):
            exe.run(start)
            install_anomaly_guard(main, loss=loss, scope=scope)
            rs = np.random.RandomState(0)
            feed = {"x": rs.rand(4, 8).astype(np.float32),
                    "y": rs.rand(4, 1).astype(np.float32)}
            exe.run(main, feed=feed, fetch_list=[loss])
            state = {n: np.asarray(scope.find_var(n)).copy()
                     for n in scope.local_var_names()
                     if "moment" in n or "beta" in n.lower()}
            assert state, "expected adam accumulators in scope"
            bad = dict(feed)
            bx = feed["x"].copy()
            bx[0, 0] = np.nan
            bad["x"] = bx
            exe.run(main, feed=bad, fetch_list=[loss])
            for n, want in state.items():
                np.testing.assert_array_equal(
                    np.asarray(scope.find_var(n)), want, err_msg=n)


# ---------------------------------------------------------------------------
# retry/backoff
# ---------------------------------------------------------------------------

class TestRetry:
    def test_classification(self):
        assert is_transient(InjectedDispatchError("UNAVAILABLE: x"))
        assert is_transient(ConnectionResetError("peer reset"))
        assert is_transient(TimeoutError("deadline"))

        class XlaRuntimeError(RuntimeError):
            pass

        assert is_transient(
            XlaRuntimeError("UNAVAILABLE: failed to connect"))
        assert not is_transient(
            XlaRuntimeError("INVALID_ARGUMENT: shape mismatch"))
        assert not is_transient(ValueError("bad value"))
        # framework-detected misuse is never transient
        from paddle_tpu.core.enforce import InvalidArgumentError
        assert not is_transient(InvalidArgumentError("UNAVAILABLE"))

    def test_schedule_deterministic_and_capped(self):
        p1 = RetryPolicy(max_retries=4, base_delay=1.0, max_delay=3.0,
                         jitter=0.5, seed=42)
        p2 = RetryPolicy(max_retries=4, base_delay=1.0, max_delay=3.0,
                         jitter=0.5, seed=42)
        assert p1.delays() == p2.delays()  # seed-driven, reproducible
        base = [min(3.0, 1.0 * 2 ** k) for k in range(4)]
        for d, b in zip(p1.delays(), base):
            assert b <= d <= b * 1.5  # jitter in [0, 50%]

    def test_budget_and_propagation(self):
        calls = []

        def flaky():
            calls.append(1)
            raise InjectedDispatchError("UNAVAILABLE: nope")

        policy = RetryPolicy(max_retries=2, base_delay=0.0)
        with pytest.raises(RetryBudgetExhausted) as ei:
            retry_call(flaky, policy)
        assert len(calls) == 3  # initial + 2 retries
        assert len(ei.value.attempts) == 3

        def broken():
            raise ValueError("permanent")

        with pytest.raises(ValueError):  # non-transient: no retry
            retry_call(broken, policy)

        n = {"left": 2}

        def heals():
            if n["left"]:
                n["left"] -= 1
                raise InjectedDispatchError("UNAVAILABLE")
            return "ok"

        out, used = retry_call(heals, policy)
        assert (out, used) == ("ok", 2)


# ---------------------------------------------------------------------------
# checkpoint durability (satellite: io.CheckpointSaver._write ordering)
# ---------------------------------------------------------------------------

def _tiny_state(tmp_path, seed=9):
    main, start = fluid.Program(), fluid.Program()
    main.random_seed = start.random_seed = seed
    with fluid.unique_name.guard():
        with fluid.program_guard(main, start):
            x = layers.data("x", shape=[4], append_batch_size=False)
            w = layers.create_parameter(shape=(4,), dtype="float32",
                                        name="w")
            loss = layers.reduce_sum(layers.square(x - w))
            fluid.optimizer.SGD(0.1).minimize(loss)
    return main, start, loss


class TestCheckpointDurability:
    @pytest.mark.chaos
    def test_marker_inside_tmp_before_rename(self, tmp_path,
                                             monkeypatch):
        """The durability contract itself: at rename time the source
        tmp dir already holds the fsynced _COMPLETE marker, so the ONE
        atomic rename publishes a checkpoint that is complete by
        construction."""
        scope = fluid.Scope()
        with fluid.scope_guard(scope):
            main, start, loss = _tiny_state(tmp_path)
            exe = fluid.Executor()
            exe.run(start)
            saver = fluid.io.CheckpointSaver(str(tmp_path), main,
                                             scope=scope)
            seen = []
            real_rename = os.rename

            def spy(src, dst):
                if os.path.basename(src).startswith(".tmp-ckpt-"):
                    seen.append(sorted(os.listdir(src)))
                return real_rename(src, dst)

            monkeypatch.setattr(os, "rename", spy)
            saver.save(1, sync=True)
            assert len(seen) == 1
            assert fluid.io.CheckpointSaver.MARKER in seen[0]
            assert saver.list_checkpoints() == [1]

    @pytest.mark.chaos
    def test_writer_killed_mid_write_stays_invisible(self, tmp_path):
        """A writer killed after N data files (preemption model) must
        strand only a tmp dir: no visible checkpoint, restore_latest
        serves the previous complete step, and a restarted saver
        sweeps the wreckage."""
        scope = fluid.Scope()
        with fluid.scope_guard(scope):
            main, start, loss = _tiny_state(tmp_path)
            exe = fluid.Executor()
            exe.run(start)
            saver = fluid.io.CheckpointSaver(str(tmp_path), main,
                                             scope=scope)
            saver.save(1, sync=True)
            w1 = np.asarray(scope.find_var("w")).copy()
            exe.run(main, feed={"x": np.ones(4, np.float32)},
                    fetch_list=[loss])
            inj = FaultInjector(seed=0).crash_save_at(2, after_files=1)
            inj.attach_saver(saver)
            with pytest.raises(SimulatedCrash):
                saver.save(2, sync=True)
            assert saver.list_checkpoints() == [1]
            stranded = [n for n in os.listdir(str(tmp_path))
                        if n.startswith(".tmp-ckpt-")]
            assert stranded  # wreckage exists but is invisible
            assert inj.events[0][0] == "crash_save"
            # restore resumes from the previous complete step
            assert saver.restore_latest(exe) == 1
            np.testing.assert_array_equal(
                np.asarray(scope.find_var("w")), w1)
            # a restarted process sweeps the tmp wreckage
            saver2 = fluid.io.CheckpointSaver(str(tmp_path), main,
                                              scope=scope)
            assert not [n for n in os.listdir(str(tmp_path))
                        if n.startswith(".tmp-ckpt-")]
            assert saver2.list_checkpoints() == [1]

    @pytest.mark.chaos
    def test_prune_killed_after_unmark_stays_invisible(self, tmp_path):
        """_prune's commit point is marker removal: a prune killed
        between unmark and rmtree leaves an unmarked dir that
        restore_latest skips and a restarted saver finishes
        deleting."""
        scope = fluid.Scope()
        with fluid.scope_guard(scope):
            main, start, loss = _tiny_state(tmp_path)
            exe = fluid.Executor()
            exe.run(start)
            saver = fluid.io.CheckpointSaver(str(tmp_path), main,
                                             max_to_keep=2,
                                             scope=scope)
            for s in (1, 2):
                saver.save(s, sync=True)
            # simulate: prune of ckpt-1 unmarked it, then died before
            # rmtree (exactly what the marker-first ordering produces)
            os.remove(str(tmp_path / "ckpt-1" /
                          fluid.io.CheckpointSaver.MARKER))
            assert saver.list_checkpoints() == [2]
            assert saver.restore_latest(exe) == 2
            fluid.io.CheckpointSaver(str(tmp_path), main, scope=scope)
            assert not (tmp_path / "ckpt-1").exists()  # swept
            assert (tmp_path / "ckpt-2").exists()

    @pytest.mark.chaos
    def test_torn_marked_checkpoint_falls_back(self, tmp_path):
        """A marked-but-torn checkpoint (pre-fix power loss shape) must
        not stop a rollback: restore_latest warns and serves the next
        older complete one."""
        scope = fluid.Scope()
        with fluid.scope_guard(scope):
            main, start, loss = _tiny_state(tmp_path)
            exe = fluid.Executor()
            exe.run(start)
            saver = fluid.io.CheckpointSaver(str(tmp_path), main,
                                             scope=scope)
            saver.save(3, sync=True)
            w3 = np.asarray(scope.find_var("w")).copy()
            make_torn_checkpoint(str(tmp_path), 9,
                                 fluid.io.CheckpointSaver.MARKER)
            assert saver.list_checkpoints() == [3, 9]
            with pytest.warns(UserWarning, match="ckpt-9"):
                assert saver.restore_latest(exe) == 3
            np.testing.assert_array_equal(
                np.asarray(scope.find_var("w")), w3)

    @pytest.mark.chaos
    def test_sigterm_mid_save_flushes_complete_checkpoint(
            self, tmp_path, monkeypatch):
        """The preemption notice arriving while a background write lies
        dead mid-tmp-dir: the handler drains, rewrites the retained
        snapshot synchronously, takes a fresh final save, and re-raises
        the default action (observed via the patched os.kill)."""
        scope = fluid.Scope()
        with fluid.scope_guard(scope):
            main, start, loss = _tiny_state(tmp_path)
            exe = fluid.Executor()
            exe.run(start)
            saver = fluid.io.CheckpointSaver(str(tmp_path), main,
                                             scope=scope)
            inj = FaultInjector(seed=0).crash_save_at(1, after_files=1)
            inj.attach_saver(saver)
            h = saver.save(1)  # background write dies mid-save
            h._thread.join()
            assert saver.list_checkpoints() == []
            w_at_save = np.asarray(scope.find_var("w")).copy()
            # weights move on after the save — the flushed ckpt-1 must
            # hold the RETAINED snapshot, not these
            exe.run(main, feed={"x": np.ones(4, np.float32)},
                    fetch_list=[loss])

            kills = []
            monkeypatch.setattr(os, "kill",
                                lambda pid, sig: kills.append(sig))
            saver.install_signal_handler(signals=(signal.SIGTERM,),
                                         get_step=lambda: 2)
            try:
                signal.raise_signal(signal.SIGTERM)
            finally:
                signal.signal(signal.SIGTERM, signal.SIG_DFL)
            assert kills == [signal.SIGTERM]
            assert saver.list_checkpoints() == [1, 2]
            import paddle_tpu.io as io_mod
            with open(str(tmp_path / "ckpt-1" / "w"), "rb") as f:
                got, _ = io_mod.deserialize_tensor(f.read())
            np.testing.assert_array_equal(got, w_at_save)


# ---------------------------------------------------------------------------
# GuardedTrainer: the chaos acceptance suite
# ---------------------------------------------------------------------------

def _trainer(tmp_path, faults=None, seed=7, **kw):
    main, start, loss = _build(seed=seed)
    scope = fluid.Scope()
    exe = fluid.Executor()
    kw.setdefault("checkpoint_every", 2)
    kw.setdefault("rollback_after", 3)
    kw.setdefault("retry", RetryPolicy(max_retries=3, base_delay=0.0))
    return GuardedTrainer(exe, main, loss, startup_program=start,
                          scope=scope, checkpoint_dir=str(tmp_path),
                          faults=faults, sync_saves=True, **kw)


class TestGuardedTrainer:
    @pytest.mark.chaos
    def test_chaos_acceptance(self, tmp_path):
        """ISSUE 2 acceptance: with NaN grads at steps 5-7, a writer
        kill mid-save at step 8, and one transient dispatch failure at
        step 11, the guarded run completes; its final loss is within
        rtol 1e-2 of the fault-free twin; and the structured summary
        reports the skipped/rolled-back/retried counts."""
        feeds = _batches(30)
        base = _trainer(tmp_path / "clean").train(feeds)
        assert base["skipped_steps"] == 0
        assert base["aborted"] is None

        inj = (FaultInjector(seed=1)
               .nan_grad_at(5, 6, 7)
               .crash_save_at(8, after_files=1)
               .transient_dispatch_at(11, times=1))
        s = _trainer(tmp_path / "chaos", faults=inj).train(feeds)
        assert s["aborted"] is None
        assert s["steps_run"] == 30
        assert s["skipped_steps"] == 3
        assert s["rollbacks"] == 1
        assert s["retries"] == 1
        assert s["save_failures"] == 1
        fired = [e[0] for e in inj.events]
        assert fired.count("nan_grad") == 3
        assert "crash_save" in fired and "transient_dispatch" in fired
        np.testing.assert_allclose(s["final_loss"],
                                   base["final_loss"], rtol=1e-2)

    @pytest.mark.chaos
    def test_rollback_replays_poisoned_window_exactly(self, tmp_path):
        """One-shot NaN faults + pre-window restore + replay: the
        post-recovery trajectory is BIT-EXACT against fault-free (the
        model has no RNG ops, so the monotonic PRNG re-fold changes
        nothing and the replayed updates land identically)."""
        feeds = _batches(14)
        base = _trainer(tmp_path / "clean").train(feeds)
        inj = FaultInjector(seed=0).nan_grad_at(4, 5, 6)
        s = _trainer(tmp_path / "chaos", faults=inj).train(feeds)
        assert s["rollbacks"] == 1
        clean = [v for v in s["losses"] if np.isfinite(v)]
        assert clean == base["losses"]  # bit-exact, including replay

    @pytest.mark.chaos
    def test_retry_budget_exhaustion_degrades_gracefully(self,
                                                         tmp_path):
        """A persistent dispatch failure aborts with a structured
        report AND a final synchronous checkpoint."""
        inj = FaultInjector(seed=0).transient_dispatch_at(3, times=99)
        t = _trainer(tmp_path, faults=inj,
                     retry=RetryPolicy(max_retries=2, base_delay=0.0))
        with pytest.raises(TrainingAborted) as ei:
            t.train(_batches(10))
        rep = ei.value.report
        assert "retry budget exhausted" in ei.value.reason
        assert rep["retries"] == 0  # budget burned, none succeeded
        assert rep["steps_run"] == 3
        assert rep["checkpoints"], "final checkpoint must be flushed"
        assert isinstance(ei.value.__cause__, RetryBudgetExhausted)

    @pytest.mark.chaos
    def test_persistent_anomaly_spends_rollback_budget(self, tmp_path):
        """NaN on EVERY step re-poisons each replay; after
        max_rollbacks the trainer aborts instead of looping forever."""
        inj = FaultInjector(seed=0).nan_grad_at(*range(40))
        t = _trainer(tmp_path, faults=inj, max_rollbacks=2)
        with pytest.raises(TrainingAborted) as ei:
            t.train(_batches(40))
        assert "anomaly persists" in ei.value.reason
        assert ei.value.report["rollbacks"] == 2

    @pytest.mark.chaos
    def test_stream_input_rollback_continues_forward(self, tmp_path):
        """train_from_dataset posture: a stream cannot be replayed, so
        rollback restores state (weights rewind) and continues with the
        NEXT batches — the run still completes finite."""
        inj = FaultInjector(seed=0).nan_grad_at(3, 4, 5)
        t = _trainer(tmp_path, faults=inj)
        s = t.train(iter(_batches(12)))
        assert s["rollbacks"] == 1
        assert s["aborted"] is None
        # 12 batches consumed, but the restore rewound steps_run to
        # the pre-window checkpoint (step 2): 2 + the 6 post-window
        # batches = 8
        assert s["steps_run"] == 8
        assert s["skipped_steps"] == 3
        assert np.isfinite(s["final_loss"])

    @pytest.mark.chaos
    def test_train_repeated_guarded_chunks(self, tmp_path):
        """The scan-chunked driver: a transient failure before a chunk
        retries; counters ride the scan carry; totals add up."""
        inj = FaultInjector(seed=0).transient_dispatch_at(4, times=1)
        t = _trainer(tmp_path, faults=inj, checkpoint_every=0)
        feed = _batches(1)[0]
        s = t.train_repeated(feed, iters=10, chunk=4)
        assert s["steps_run"] == 10
        assert s["retries"] == 1
        assert s["aborted"] is None
        assert np.isfinite(s["final_loss"])


# ---------------------------------------------------------------------------
# q8 error-feedback residuals across checkpoint/restore (satellite)
# ---------------------------------------------------------------------------

def _q8_setup(seed=11):
    import jax
    from paddle_tpu.parallel import make_mesh
    main, start, loss = _build(seed=seed)
    bs = fluid.BuildStrategy()
    bs.gradient_sync = "q8"
    prog = fluid.CompiledProgram(main).with_data_parallel(
        build_strategy=bs,
        mesh=make_mesh({"dp": 4}, jax.devices()[:4]))
    return main, start, loss, prog


class TestQ8ResidualCheckpointing:
    @pytest.mark.chaos
    def test_save_restore_continue_is_bitexact(self, tmp_path):
        """save -> restore -> continue must match an uninterrupted q8
        run's loss trajectory BIT-exactly: the error-feedback residuals
        are persistables, so they checkpoint and restore with the
        weights; losing them would silently degrade quantized
        training."""
        from paddle_tpu.parallel import collectives as C
        feeds = _batches(6)

        # uninterrupted twin
        main, start, loss, prog = _q8_setup()
        scope = fluid.Scope()
        exe = fluid.Executor()
        full = []
        with fluid.scope_guard(scope):
            exe.run(start)
            for f in feeds:
                (lv,) = exe.run(prog, feed=f, fetch_list=[loss])
                full.append(float(lv))

        # interrupted: 3 steps, checkpoint, fresh process restores
        main2, start2, loss2, prog2 = _q8_setup()
        scope2 = fluid.Scope()
        exe2 = fluid.Executor()
        with fluid.scope_guard(scope2):
            exe2.run(start2)
            first = []
            for f in feeds[:3]:
                (lv,) = exe2.run(prog2, feed=f, fetch_list=[loss2])
                first.append(float(lv))
            saver = fluid.io.CheckpointSaver(str(tmp_path), main2,
                                             scope=scope2)
            saver.save(3, sync=True)
        assert first == full[:3]
        # residual slots are IN the checkpoint, nonzero
        res_files = [n for n in os.listdir(str(tmp_path / "ckpt-3"))
                     if n.endswith(C.RESIDUAL_SUFFIX)]
        assert len(res_files) == 4, res_files

        main3, start3, loss3, prog3 = _q8_setup()
        scope3 = fluid.Scope()
        exe3 = fluid.Executor()
        with fluid.scope_guard(scope3):
            exe3.run(start3)
            # a restarted process must materialize the residual slots
            # before restoring into them
            C.ensure_residual_vars(main3, scope3)
            saver3 = fluid.io.CheckpointSaver(str(tmp_path), main3,
                                              scope=scope3)
            assert saver3.restore_latest(exe3) == 3
            cont = []
            for f in feeds[3:]:
                (lv,) = exe3.run(prog3, feed=f, fetch_list=[loss3])
                cont.append(float(lv))
        assert cont == full[3:]  # bit-exact continuation

    @pytest.mark.chaos
    def test_residuals_shielded_when_sparse_param_sorts_first(self):
        """The guard's boundary (which includes sparse-grad params)
        can sit EARLIER than the q8 collective's (which excludes
        them) — the optimizer sorts params by name, so an embedding
        named 'aaa_*' puts its optimize op first. post_sync must still
        run AFTER the collective, or a NaN step writes NaN residuals
        while reporting the step as handled."""
        import jax
        from paddle_tpu.parallel import collectives as C
        from paddle_tpu.parallel import make_mesh
        main, start = fluid.Program(), fluid.Program()
        main.random_seed = start.random_seed = 5
        with fluid.unique_name.guard():
            with fluid.program_guard(main, start):
                ids = layers.data("ids", shape=[1], dtype="int64")
                label = layers.data("label", shape=[1], dtype="int64")
                emb = layers.embedding(
                    ids, size=(40, 8), is_sparse=True,
                    param_attr=fluid.ParamAttr(name="aaa_table"))
                emb = layers.reshape(emb, (-1, 8))
                pred = layers.fc(emb, size=4, act="softmax")
                loss = layers.mean(layers.cross_entropy(pred, label))
                fluid.optimizer.SGD(0.1).minimize(loss)
        bs = fluid.BuildStrategy()
        bs.gradient_sync = "q8"
        prog = fluid.CompiledProgram(main).with_data_parallel(
            build_strategy=bs,
            mesh=make_mesh({"dp": 4}, jax.devices()[:4]))
        scope = fluid.Scope()
        exe = fluid.Executor()
        with fluid.scope_guard(scope):
            exe.run(start)
            install_anomaly_guard(main, loss=loss, scope=scope)
            # divergence precondition: guard boundary < sync boundary
            gb, _gk, _rk = guard._guard_entries(main.global_block())
            sp = C.make_plan(main.global_block(), "q8",
                             make_mesh({"dp": 4}, jax.devices()[:4]))
            assert gb < sp.boundary
            rs = np.random.RandomState(0)
            iv = rs.randint(0, 40, size=(16, 1)).astype(np.int64)
            yv = (iv % 4).astype(np.int64)
            exe.run(prog, feed={"ids": iv, "label": yv},
                    fetch_list=[loss])
            res = {n: np.asarray(scope.find_var(n)).copy()
                   for n in scope.local_var_names()
                   if n.endswith(C.RESIDUAL_SUFFIX)}
            assert res
            # both feeds are int, so poison the only float state the
            # forward reads: the embedding table — every grad NaNs
            w = np.asarray(scope.find_var("aaa_table")).copy()
            w_bad = w.copy()
            w_bad[0, 0] = np.nan
            scope.set_var("aaa_table", w_bad)
            (lv,) = exe.run(prog, feed={"ids": iv, "label": yv},
                            fetch_list=[loss])
            assert not np.isfinite(lv)
            assert guard.read_counters(scope)[1] >= 1.0
            for n, want in res.items():
                got = np.asarray(scope.find_var(n))
                assert np.isfinite(got).all(), n
                np.testing.assert_array_equal(got, want, err_msg=n)

    @pytest.mark.chaos
    def test_guard_shields_residuals_on_bad_step(self, tmp_path):
        """A NaN step through the q8 collective must leave the
        error-feedback residuals bit-identical (an unguarded NaN there
        would poison every later step through the feedback loop) while
        the guard skips the update."""
        from paddle_tpu.parallel import collectives as C
        main, start, loss, prog = _q8_setup()
        scope = fluid.Scope()
        exe = fluid.Executor()
        feeds = _batches(3)
        with fluid.scope_guard(scope):
            exe.run(start)
            install_anomaly_guard(main, loss=loss, scope=scope)
            exe.run(prog, feed=feeds[0], fetch_list=[loss])
            res = {n: np.asarray(scope.find_var(n)).copy()
                   for n in scope.local_var_names()
                   if n.endswith(C.RESIDUAL_SUFFIX)}
            assert res and any(np.abs(r).max() > 0
                               for r in res.values())
            bad = dict(feeds[1])
            bx = bad["x"].copy()
            bx[0, 0] = np.nan
            bad["x"] = bx
            (lv,) = exe.run(prog, feed=bad, fetch_list=[loss])
            assert not np.isfinite(lv)
            assert guard.read_counters(scope)[1] == 1.0
            for n, want in res.items():
                got = np.asarray(scope.find_var(n))
                assert np.isfinite(got).all(), n
                np.testing.assert_array_equal(got, want, err_msg=n)


class TestGuardLifecycle:
    def test_pre_guard_checkpoint_still_restores(self, tmp_path):
        """Checkpoints written BEFORE the guard existed lack the
        counter vars; restore must default-fill them instead of
        failing (and the trainer's resume path must work)."""
        main, start, loss = _build()
        scope = fluid.Scope()
        exe = fluid.Executor()
        with fluid.scope_guard(scope):
            exe.run(start)
            exe.run(main, feed=_batches(1)[0], fetch_list=[loss])
            fluid.io.CheckpointSaver(str(tmp_path), main,
                                     scope=scope).save(5, sync=True)
        # fresh process installs the guard, then restores the old ckpt
        main2, start2, loss2 = _build()
        scope2 = fluid.Scope()
        exe2 = fluid.Executor()
        with fluid.scope_guard(scope2):
            exe2.run(start2)
            install_anomaly_guard(main2, loss=loss2, scope=scope2)
            saver = fluid.io.CheckpointSaver(str(tmp_path), main2,
                                             scope=scope2)
            assert saver.restore_latest(exe2) == 5
            assert guard.read_counters(scope2) == (0.0, 0.0)
            exe2.run(main2, feed=_batches(1)[0], fetch_list=[loss2])

    def test_accumulation_window_stays_in_lockstep(self):
        """NaN on the window-closing micro-step (accumulate_steps=2):
        the guard zeroes the poisoned grad instead of freezing the
        window, so the accumulator resets with the counter and the
        next window cannot apply a double-sized update."""
        main, start = fluid.Program(), fluid.Program()
        main.random_seed = start.random_seed = 4
        with fluid.unique_name.guard():
            with fluid.program_guard(main, start):
                x = layers.data("x", [8], dtype="float32")
                y = layers.data("y", [1], dtype="float32")
                pred = layers.fc(x, size=1)
                loss = layers.mean(layers.square_error_cost(pred, y))
                fluid.optimizer.SGD(0.1).minimize(
                    loss, accumulate_steps=2)
        scope = fluid.Scope()
        exe = fluid.Executor()
        with fluid.scope_guard(scope):
            exe.run(start)
            install_anomaly_guard(main, loss=loss, scope=scope)
            rs = np.random.RandomState(0)
            feed = {"x": rs.rand(4, 8).astype(np.float32),
                    "y": rs.rand(4, 1).astype(np.float32)}
            bad = dict(feed)
            bx = feed["x"].copy()
            bx[0, 0] = np.nan
            bad["x"] = bx
            exe.run(main, feed=feed, fetch_list=[loss])   # micro 1
            w_mid = np.asarray(scope.find_var("fc_0.w_0")).copy()
            exe.run(main, feed=bad, fetch_list=[loss])    # closing+NaN
            acc_names = [n for n in scope.local_var_names()
                         if "_grad_acc" in n and "counter" not in n]
            assert acc_names
            w_after = np.asarray(scope.find_var("fc_0.w_0"))
            # the window CLOSED with the poisoned contribution zeroed:
            # update applied (params moved, finite), accumulator reset
            assert np.isfinite(w_after).all()
            assert not np.array_equal(w_after, w_mid)
            for n in acc_names:
                np.testing.assert_array_equal(
                    np.asarray(scope.find_var(n)),
                    np.zeros_like(np.asarray(scope.find_var(n))),
                    err_msg=n)
            assert guard.read_counters(scope) == (1.0, 1.0)
            # next full window trains normally and stays finite
            exe.run(main, feed=feed, fetch_list=[loss])
            (lv,) = exe.run(main, feed=feed, fetch_list=[loss])
            assert np.isfinite(lv)
            assert np.isfinite(
                np.asarray(scope.find_var("fc_0.w_0"))).all()

    def test_deleted_buffer_error_heals_via_retry(self):
        """A dispatch that dies after donation leaves deleted arrays;
        the NEXT attempt's 'has been deleted' error must classify
        transient so _on_retry's checkpoint heal can fire."""
        assert is_transient(
            RuntimeError("Array has been deleted with shape=f32[4]"))
        seq = [InjectedDispatchError("UNAVAILABLE: reset"),
               RuntimeError("Array has been deleted"), "ok"]
        healed = []

        def fn():
            step = seq.pop(0)
            if isinstance(step, Exception):
                raise step
            return step

        out, used = retry_call(
            fn, RetryPolicy(max_retries=2, base_delay=0.0),
            on_retry=lambda a, e, d: healed.append(str(e)))
        assert (out, used) == ("ok", 2)
        assert any("deleted" in m for m in healed)


    def test_reinstall_into_fresh_scope_keeps_counting(self):
        """A second install of an already-guarded program into a FRESH
        scope must still materialize the counters there — otherwise
        skip accounting and rollback are silently disabled for the
        second run."""
        main, start, loss = _build()
        s1, s2 = fluid.Scope(), fluid.Scope()
        exe = fluid.Executor()
        install_anomaly_guard(main, loss=loss, scope=s1)
        install_anomaly_guard(main, loss=loss, scope=s2)  # re-install
        assert s2.has_var(guard.SKIPPED_VAR)
        bad = _batches(1)[0]
        bx = bad["x"].copy()
        bx[0, 0] = np.nan
        bad["x"] = bx
        with fluid.scope_guard(s2):
            exe.run(start)
            guard.ensure_guard_state(s2)
            exe.run(main, feed=bad, fetch_list=[loss])
        assert guard.read_counters(s2) == (1.0, 1.0)
        # the in-use scope's counters must NOT be reset by re-install
        s2.set_var(guard.SKIPPED_VAR,
                   np.ones((1,), np.float32))
        install_anomaly_guard(main, loss=loss, scope=s2)
        assert guard.read_counters(s2)[0] == 1.0

    def test_to_dict_roundtrip_keeps_loss_check(self):
        """Serialization must carry the guard config — the loss name
        in particular — not just the gate attrs."""
        main, start, loss = _build()
        install_anomaly_guard(main, loss=loss, scope=fluid.Scope())
        p2 = fluid.Program.from_dict(main.to_dict())
        assert p2._anomaly_guard == {"loss": loss.name}
        # legacy desc (no anomaly_guard key): the sniff path pins
        # loss=None, and a later install with a loss upgrades it
        legacy = main.to_dict()
        legacy.pop("anomaly_guard")
        p3 = fluid.Program.from_dict(legacy)
        assert p3._anomaly_guard == {"loss": None}
        v = p3._version
        install_anomaly_guard(p3, loss=loss.name, scope=fluid.Scope())
        assert p3._anomaly_guard == {"loss": loss.name}
        assert p3._version > v  # cached steps must recompile

    def test_trainer_resumes_prior_checkpoints(self, tmp_path):
        """Pointing a trainer at a dir with prior-run checkpoints must
        RESUME (restore + adopt the step number), keeping the rollback
        invariant 'a checkpoint <= steps_run exists' intact."""
        feeds = _batches(6)
        t1 = _trainer(tmp_path, checkpoint_every=2)
        s1 = t1.train(feeds)
        assert s1["checkpoints"][-1] == 6
        w_end = np.asarray(t1._scope.find_var("fc_0.w_0")).copy()

        t2 = _trainer(tmp_path, checkpoint_every=2)
        s2 = t2.train(feeds)  # fresh trainer, same dir: resumes at 6
        assert s2["steps_run"] == 12
        assert s2["checkpoints"][-1] == 12
        # it started from the restored weights, not from init
        np.testing.assert_array_equal(
            np.asarray(t2._scope.find_var("fc_0.w_0")).shape,
            w_end.shape)
        assert s2["losses"][0] < s1["losses"][0]  # warm start


# ---------------------------------------------------------------------------
# program uid (satellite: executor cache key)
# ---------------------------------------------------------------------------

def test_program_uid_not_id_in_executor_cache():
    """Two same-shaped programs (identical version/feed/fetch
    signatures) must occupy DISTINCT run_repeated cache slots keyed by
    their monotonic uid — id() reuse after GC could alias them."""
    def build(c):
        main = fluid.Program()
        with fluid.program_guard(main):
            x = layers.data("x", [2])
            y = layers.scale(x, scale=float(c))
        return main, y

    exe = fluid.Executor()
    feed = {"x": np.ones((1, 2), np.float32)}
    m1, y1 = build(2.0)
    m2, y2 = build(3.0)
    assert m1._uid != m2._uid
    assert m1.clone()._uid not in (m1._uid, m2._uid)
    r1 = exe.run_repeated(m1, feed=feed, fetch_list=[y1.name], iters=2)
    r2 = exe.run_repeated(m2, feed=feed, fetch_list=[y2.name], iters=2)
    assert float(np.ravel(r1[0])[0]) == 2.0
    assert float(np.ravel(r2[0])[0]) == 3.0
    repeat_keys = [k for k in exe._cache if k[0] == "repeat"]
    assert sorted(k[2] for k in repeat_keys) == sorted(
        [m1._uid, m2._uid])
