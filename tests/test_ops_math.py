"""Per-op tests: math/elementwise/reduce (reference analog:
test_elementwise_add_op.py, test_mul_op.py, test_matmul_op.py,
test_reduce_op.py, test_activation_op.py ... 249 test_*op*.py files)."""

import numpy as np
import pytest

from op_test import check_grad, check_output


class TestElementwise:
    def test_add(self, rng):
        x = rng.rand(3, 4).astype(np.float32)
        y = rng.rand(3, 4).astype(np.float32)
        check_output("elementwise_add", {"X": x, "Y": y}, {}, [x + y])

    def test_add_broadcast_axis(self, rng):
        x = rng.rand(2, 3, 4).astype(np.float32)
        y = rng.rand(3,).astype(np.float32)
        check_output("elementwise_add", {"X": x, "Y": y}, {"axis": 1},
                     [x + y[None, :, None]])

    def test_sub_grad(self, rng):
        x = rng.rand(3, 4).astype(np.float32)
        y = rng.rand(3, 4).astype(np.float32)
        check_grad("elementwise_sub", {"X": x, "Y": y}, {}, ["X", "Y"])

    def test_mul_div(self, rng):
        x = rng.rand(3, 4).astype(np.float32) + 0.5
        y = rng.rand(3, 4).astype(np.float32) + 0.5
        check_output("elementwise_mul", {"X": x, "Y": y}, {}, [x * y])
        check_output("elementwise_div", {"X": x, "Y": y}, {}, [x / y])
        check_grad("elementwise_div", {"X": x, "Y": y}, {}, ["X", "Y"],
                   max_relative_error=0.02)

    def test_min_max(self, rng):
        x = rng.rand(5).astype(np.float32)
        y = rng.rand(5).astype(np.float32)
        check_output("elementwise_min", {"X": x, "Y": y}, {},
                     [np.minimum(x, y)])
        check_output("elementwise_max", {"X": x, "Y": y}, {},
                     [np.maximum(x, y)])


class TestMatmul:
    def test_matmul(self, rng):
        x = rng.rand(3, 4).astype(np.float32)
        y = rng.rand(4, 5).astype(np.float32)
        check_output("matmul", {"X": x, "Y": y}, {}, [x @ y])

    def test_matmul_transpose(self, rng):
        x = rng.rand(4, 3).astype(np.float32)
        y = rng.rand(5, 4).astype(np.float32)
        check_output("matmul", {"X": x, "Y": y},
                     {"transpose_x": True, "transpose_y": True},
                     [x.T @ y.T])

    def test_matmul_batched(self, rng):
        x = rng.rand(2, 3, 4).astype(np.float32)
        y = rng.rand(2, 4, 5).astype(np.float32)
        check_output("matmul", {"X": x, "Y": y}, {}, [x @ y])

    def test_matmul_grad(self, rng):
        x = rng.rand(3, 4).astype(np.float32)
        y = rng.rand(4, 2).astype(np.float32)
        check_grad("matmul", {"X": x, "Y": y}, {}, ["X", "Y"],
                   max_relative_error=0.01)

    def test_mul_flatten(self, rng):
        x = rng.rand(2, 3, 4).astype(np.float32)
        y = rng.rand(12, 5).astype(np.float32)
        check_output("mul", {"X": x, "Y": y}, {"x_num_col_dims": 1},
                     [x.reshape(2, 12) @ y])


class TestActivations:
    def test_relu(self, rng):
        x = (rng.rand(4, 5).astype(np.float32) - 0.5)
        check_output("relu", {"X": x}, {}, [np.maximum(x, 0)])

    def test_sigmoid_grad(self, rng):
        x = rng.rand(3, 4).astype(np.float32)
        check_output("sigmoid", {"X": x}, {}, [1 / (1 + np.exp(-x))])
        check_grad("sigmoid", {"X": x}, {}, ["X"],
                   max_relative_error=0.01)

    def test_tanh_exp_log(self, rng):
        x = rng.rand(3, 4).astype(np.float32) + 0.1
        check_output("tanh", {"X": x}, {}, [np.tanh(x)])
        check_output("exp", {"X": x}, {}, [np.exp(x)])
        check_output("log", {"X": x}, {}, [np.log(x)])

    def test_softmax(self, rng):
        x = rng.rand(3, 7).astype(np.float32)
        e = np.exp(x - x.max(-1, keepdims=True))
        check_output("softmax", {"X": x}, {}, [e / e.sum(-1,
                                                         keepdims=True)])

    def test_softmax_grad(self, rng):
        x = rng.rand(2, 5).astype(np.float32)
        check_grad("softmax", {"X": x}, {}, ["X"],
                   max_relative_error=0.02)

    def test_gelu_leaky(self, rng):
        x = (rng.rand(3, 4).astype(np.float32) - 0.5) * 2
        check_output("leaky_relu", {"X": x}, {"alpha": 0.1},
                     [np.where(x >= 0, x, 0.1 * x)])


class TestReduce:
    def test_reduce_sum(self, rng):
        x = rng.rand(3, 4, 5).astype(np.float32)
        check_output("reduce_sum", {"X": x}, {"dim": [1]},
                     [x.sum(axis=1)])
        check_output("reduce_sum", {"X": x},
                     {"dim": None, "reduce_all": True}, [x.sum()])

    def test_reduce_mean_grad(self, rng):
        x = rng.rand(3, 4).astype(np.float32)
        check_output("reduce_mean", {"X": x}, {"dim": [0]},
                     [x.mean(axis=0)])
        check_grad("reduce_mean", {"X": x}, {"dim": [0]}, ["X"])

    def test_reduce_max_keepdim(self, rng):
        x = rng.rand(3, 4).astype(np.float32)
        check_output("reduce_max", {"X": x},
                     {"dim": [1], "keep_dim": True},
                     [x.max(axis=1, keepdims=True)])

    def test_mean(self, rng):
        x = rng.rand(6, 2).astype(np.float32)
        check_output("mean", {"X": x}, {}, [np.array(x.mean())])


class TestVariadic:
    def test_sum_op(self, rng):
        xs = [rng.rand(3, 4).astype(np.float32) for _ in range(3)]
        check_output("sum", {"X": xs}, {}, [xs[0] + xs[1] + xs[2]])

    def test_concat(self, rng):
        xs = [rng.rand(2, 3).astype(np.float32) for _ in range(2)]
        check_output("concat", {"X": xs}, {"axis": 1},
                     [np.concatenate(xs, axis=1)])

    def test_concat_grad(self, rng):
        xs = [rng.rand(2, 2).astype(np.float32) for _ in range(2)]
        check_grad("concat", {"X": xs}, {"axis": 0}, ["x_0", "x_1"])

    def test_stack_split(self, rng):
        xs = [rng.rand(3,).astype(np.float32) for _ in range(2)]
        check_output("stack", {"X": xs}, {"axis": 0}, [np.stack(xs)])


class TestShapeOps:
    def test_reshape_transpose(self, rng):
        x = rng.rand(2, 6).astype(np.float32)
        check_output("reshape2", {"X": x}, {"shape": (3, 4)},
                     [x.reshape(3, 4)])
        check_output("transpose2", {"X": x}, {"axis": (1, 0)}, [x.T])

    def test_slice(self, rng):
        x = rng.rand(4, 5).astype(np.float32)
        check_output("slice", {"X": x},
                     {"axes": (0, 1), "starts": (1, 0), "ends": (3, 2)},
                     [x[1:3, 0:2]])

    def test_gather(self, rng):
        x = rng.rand(5, 3).astype(np.float32)
        idx = np.array([0, 2, 4], dtype=np.int64)
        check_output("gather", {"X": x, "Index": idx}, {"axis": 0},
                     [x[idx]])

    def test_one_hot(self):
        x = np.array([1, 0, 3], dtype=np.int64)
        expect = np.eye(4, dtype=np.float32)[x]
        check_output("one_hot", {"X": x}, {"depth": 4}, [expect])

    def test_topk(self, rng):
        x = rng.rand(3, 6).astype(np.float32)
        idx = np.argsort(-x, axis=1)[:, :2]
        vals = np.take_along_axis(x, idx, axis=1)
        check_output("top_k", {"X": x}, {"k": 2}, [vals, None])


class TestReviewRegressions:
    """Regressions from code-review findings."""

    def test_conv2d_transpose_shape_and_values(self, rng):
        # fluid contract: out = (H-1)*s - 2p + d*(k-1) + 1
        x = rng.rand(1, 2, 8, 8).astype(np.float32)
        w = rng.rand(2, 3, 5, 5).astype(np.float32)  # (in, out, kh, kw)
        from paddle_tpu import ops as R
        out = np.asarray(R.get("conv2d_transpose").fn(x, w))
        assert out.shape == (1, 3, 12, 12), out.shape
        # value check vs naive scatter-accumulate deconv
        ref = np.zeros((1, 3, 12, 12), np.float32)
        for ic in range(2):
            for oc in range(3):
                for i in range(8):
                    for j in range(8):
                        ref[0, oc, i:i + 5, j:j + 5] += \
                            x[0, ic, i, j] * w[ic, oc]
        np.testing.assert_allclose(out, ref, rtol=1e-4, atol=1e-4)

    def test_conv2d_transpose_stride_pad(self, rng):
        x = rng.rand(1, 1, 4, 4).astype(np.float32)
        w = rng.rand(1, 1, 3, 3).astype(np.float32)
        from paddle_tpu import ops as R
        out = np.asarray(R.get("conv2d_transpose").fn(
            x, w, strides=(2, 2), paddings=(1, 1)))
        # (4-1)*2 - 2*1 + 3 = 7
        assert out.shape == (1, 1, 7, 7), out.shape

    def test_getitem_negative_and_step(self, rng):
        import paddle_tpu as fluid
        from paddle_tpu import layers
        main = fluid.Program()
        with fluid.program_guard(main):
            x = layers.data("x", shape=[5, 6], append_batch_size=False)
            last = x[-1]
            strided = x[::2]
            rev = x[:, ::-1]
        exe = fluid.Executor()
        xv = np.arange(30, dtype=np.float32).reshape(5, 6)
        a, b, c = exe.run(main, feed={"x": xv},
                          fetch_list=[last, strided, rev])
        np.testing.assert_allclose(a, xv[-1])
        np.testing.assert_allclose(b, xv[::2])
        np.testing.assert_allclose(c, xv[:, ::-1])

    def test_ones_like_out_param(self):
        import paddle_tpu as fluid
        from paddle_tpu import layers
        main = fluid.Program()
        with fluid.program_guard(main):
            x = layers.data("x", shape=[3], append_batch_size=False)
            o = layers.ones_like(x)
        exe = fluid.Executor()
        (ov,) = exe.run(main, feed={"x": np.zeros(3, np.float32)},
                        fetch_list=[o])
        np.testing.assert_allclose(ov, np.ones(3))

    def test_unregistered_op_clear_error(self):
        import paddle_tpu as fluid
        from paddle_tpu.core.enforce import UnimplementedError
        main = fluid.Program()
        with fluid.program_guard(main):
            blk = main.global_block()
            v = blk.create_var(name="v", shape=(2,), dtype="float32")
            blk.append_op(type="no_such_op", inputs={},
                          outputs={"Out": [v]})
        exe = fluid.Executor()
        with pytest.raises(UnimplementedError, match="no_such_op"):
            exe.run(main, feed={}, fetch_list=["v"])

    def test_msra_fan_in(self):
        from paddle_tpu.initializer import _fan_in_out

        class V:
            shape = (512, 3, 3, 3)
        fi, fo = _fan_in_out(V)
        assert fi == 3 * 9 and fo == 512 * 9

        class V2:
            shape = (100, 50)
        fi, fo = _fan_in_out(V2)
        assert fi == 100 and fo == 50


def test_mxu_ln_grad_matches_autodiff():
    """FLAGS.mxu_ln_grad routes layer_norm's dScale/dBias through
    ones@M MXU dots (ops/nn_ops._ln_affine); values and ALL grads
    must match the plain autodiff lowering."""
    import jax
    import jax.numpy as jnp
    from paddle_tpu import ops
    from paddle_tpu.core.flags import FLAGS

    ln = ops.get("layer_norm").fn
    rs = np.random.RandomState(5)
    x = jnp.asarray(rs.randn(48, 64).astype(np.float32))
    sc = jnp.asarray(rs.rand(64).astype(np.float32) + 0.5)
    b = jnp.asarray(rs.randn(64).astype(np.float32))

    def loss(x_, s_, b_):
        y, _, _ = ln(x_, s_, b_, begin_norm_axis=1)
        return jnp.sum(y * jnp.cos(y))

    prev = FLAGS.mxu_ln_grad
    try:
        FLAGS.mxu_ln_grad = False
        want_y = ln(x, sc, b, begin_norm_axis=1)[0]
        gw = jax.grad(loss, argnums=(0, 1, 2))(x, sc, b)
        FLAGS.mxu_ln_grad = True
        got_y = ln(x, sc, b, begin_norm_axis=1)[0]
        gg = jax.grad(loss, argnums=(0, 1, 2))(x, sc, b)
    finally:
        FLAGS.mxu_ln_grad = prev
    np.testing.assert_allclose(np.asarray(got_y), np.asarray(want_y),
                               rtol=1e-6, atol=1e-6)
    for name, a, b_ in zip(["dx", "dscale", "dbias"], gg, gw):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                   rtol=1e-5, atol=1e-5, err_msg=name)
