"""Sparse gradients (SparseRows, the SelectedRows analog).

Reference test pattern: unittests/test_lookup_table_op.py (sparse grad
path), test_adam_op.py sparse adam, and the loss-equality discipline of
test_dist_base.py:316 — the sparse path must produce EXACTLY the same
training trajectory as the dense path (merge-add + lazy updates are
mathematically identical to dense updates for rows with grads; rows
without grads receive no update, which for SGD/momentum with zero grad
is also identical... adam/adagrad lazy mode differs on untouched rows
by design, so equality models touch every parameter row or compare
only touched rows)."""

import numpy as np
import pytest

import jax.numpy as jnp

import paddle_tpu as fluid
from paddle_tpu import layers
from paddle_tpu.core.selected_rows import SparseRows
from paddle_tpu.models import deepfm


def test_sparse_rows_merge_and_dense():
    rows = jnp.asarray([3, 1, 3, 7, 1], jnp.int32)
    vals = jnp.asarray(np.arange(10, dtype=np.float32).reshape(5, 2))
    s = SparseRows(rows, vals, height=8)
    d = np.asarray(s.to_dense())
    expect = np.zeros((8, 2), np.float32)
    for r, v in zip(np.asarray(rows), np.asarray(vals)):
        expect[r] += v
    np.testing.assert_allclose(d, expect)

    m = s.merged()
    np.testing.assert_allclose(np.asarray(m.to_dense()), expect)
    # merged rows are unique (sentinel = height for unused slots)
    mr = np.asarray(m.rows)
    live = mr[mr < 8]
    assert len(live) == len(set(live.tolist())) == 3

    # sparse + sparse concatenates; sparse + dense densifies
    s2 = s + SparseRows(jnp.asarray([0], jnp.int32),
                        jnp.ones((1, 2), jnp.float32), 8)
    assert isinstance(s2, SparseRows)
    dd = np.asarray(s + jnp.ones((8, 2), jnp.float32))
    np.testing.assert_allclose(dd, expect + 1.0)


def _build_emb_model(is_sparse, optimizer, vocab=50, dim=8, seed=5):
    fluid.framework._reset_default_programs()
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = seed
    with fluid.program_guard(main, startup):
        ids = layers.data("ids", shape=[6], dtype="int64")
        label = layers.data("label", shape=[1], dtype="float32")
        emb = layers.embedding(ids, size=(vocab, dim),
                               is_sparse=is_sparse)
        h = layers.reduce_sum(emb, dim=1)
        pred = layers.fc(h, 1)
        loss = layers.mean(layers.square(pred - label))
        optimizer().minimize(loss)
    return main, startup, loss


@pytest.mark.parametrize("opt_name,make_opt", [
    ("sgd", lambda: fluid.optimizer.SGD(0.1)),
    ("adam", lambda: fluid.optimizer.AdamOptimizer(1e-2)),
    ("adagrad", lambda: fluid.optimizer.AdagradOptimizer(0.1)),
])
def test_sparse_matches_dense_training(opt_name, make_opt, rng):
    """Loss-trace equality sparse vs dense embedding grads. Every batch
    touches a random subset of rows; repeated ids in a batch exercise
    duplicate-row merging. (Momentum is excluded: the reference's
    sparse momentum kernel is rows-only — lazy — so dense equality is
    not its contract; see test_sparse_momentum_full_coverage.)"""

    def run(is_sparse):
        main, startup, loss = _build_emb_model(is_sparse, make_opt)
        exe = fluid.Executor()
        scope = fluid.Scope()
        losses = []
        with fluid.scope_guard(scope):
            exe.run(startup)
            r = np.random.RandomState(0)
            for _ in range(8):
                feed = {
                    "ids": r.randint(0, 50, size=(16, 6))
                    .astype(np.int64),
                    "label": r.rand(16, 1).astype(np.float32),
                }
                (lv,) = exe.run(main, feed=feed, fetch_list=[loss])
                losses.append(float(lv))
        return losses

    dense = run(False)
    sparse = run(True)
    np.testing.assert_allclose(sparse, dense, rtol=1e-5, atol=1e-7)


def test_sparse_momentum_full_coverage(rng):
    """Sparse momentum is rows-only (reference momentum SelectedRows
    kernel): when every batch touches EVERY row, it must equal the
    dense run exactly."""
    vocab = 12

    def run(is_sparse):
        main, startup, loss = _build_emb_model(
            is_sparse, lambda: fluid.optimizer.MomentumOptimizer(
                0.1, 0.9), vocab=vocab)
        exe = fluid.Executor()
        scope = fluid.Scope()
        losses = []
        with fluid.scope_guard(scope):
            exe.run(startup)
            r = np.random.RandomState(0)
            for _ in range(6):
                base = np.tile(np.arange(vocab), 2)[None, :]
                ids = np.repeat(base, 4, axis=0)[:, :6 * 4]
                ids = np.concatenate(
                    [np.arange(vocab).reshape(2, 6),
                     r.randint(0, vocab, (14, 6))], axis=0)
                feed = {"ids": ids.astype(np.int64),
                        "label": r.rand(16, 1).astype(np.float32)}
                (lv,) = exe.run(main, feed=feed, fetch_list=[loss])
                losses.append(float(lv))
        return losses

    np.testing.assert_allclose(run(True), run(False), rtol=1e-5,
                               atol=1e-7)


def test_sparse_grad_accumulates_across_lookups(rng):
    """A table used by TWO lookups gets both contributions (the
    reference's grad-sum for repeated vars, backward.py
    _addup_repetitive_outputs_)."""

    def run(is_sparse):
        fluid.framework._reset_default_programs()
        main, startup = fluid.Program(), fluid.Program()
        main.random_seed = startup.random_seed = 9
        from paddle_tpu.param_attr import ParamAttr
        with fluid.program_guard(main, startup):
            a = layers.data("a", shape=[4], dtype="int64")
            b = layers.data("b", shape=[4], dtype="int64")
            ea = layers.embedding(a, size=(30, 6), is_sparse=is_sparse,
                                  param_attr=ParamAttr(name="shared_w"))
            eb = layers.embedding(b, size=(30, 6), is_sparse=is_sparse,
                                  param_attr=ParamAttr(name="shared_w"))
            h = layers.reduce_sum(ea + eb, dim=1)
            loss = layers.mean(layers.square(layers.fc(h, 1)))
            fluid.optimizer.SGD(0.05).minimize(loss)
        exe = fluid.Executor()
        scope = fluid.Scope()
        losses = []
        with fluid.scope_guard(scope):
            exe.run(startup)
            r = np.random.RandomState(1)
            for _ in range(6):
                feed = {"a": r.randint(0, 30, (8, 4)).astype(np.int64),
                        "b": r.randint(0, 30, (8, 4)).astype(np.int64)}
                (lv,) = exe.run(main, feed=feed, fetch_list=[loss])
                losses.append(float(lv))
        return losses

    np.testing.assert_allclose(run(True), run(False), rtol=1e-5,
                               atol=1e-7)


def test_padding_idx_rows_get_no_sparse_grad():
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = 4
    with fluid.program_guard(main, startup):
        ids = layers.data("ids", shape=[4], dtype="int64")
        emb = layers.embedding(ids, size=(10, 3), is_sparse=True,
                               padding_idx=0)
        loss = layers.mean(layers.reduce_sum(emb, dim=[1, 2]))
        fluid.optimizer.SGD(1.0).minimize(loss)
    exe = fluid.Executor()
    exe.run(startup)
    w0 = np.asarray(fluid.global_scope().find_var(
        emb.block.program.global_block().all_parameters()[0].name))
    feed = {"ids": np.array([[0, 0, 1, 2]], dtype=np.int64)}
    exe.run(main, feed=feed, fetch_list=[loss])
    w1 = np.asarray(fluid.global_scope().find_var(
        emb.block.program.global_block().all_parameters()[0].name))
    np.testing.assert_allclose(w1[0], w0[0])   # padding row untouched
    assert not np.allclose(w1[1], w0[1])       # looked-up row moved


def _criteo_model(vocab, dim):
    from paddle_tpu.initializer import ConstantInitializer
    from paddle_tpu.param_attr import ParamAttr
    fluid.framework._reset_default_programs()
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = 7
    with fluid.program_guard(main, startup):
        ids = layers.data("ids", shape=[8], dtype="int64")
        label = layers.data("label", shape=[1], dtype="float32")
        emb = layers.embedding(
            ids, size=(vocab, dim), is_sparse=True,
            # constant init: the table fill must not dominate the test
            param_attr=ParamAttr(
                name="criteo_w",
                initializer=ConstantInitializer(0.01)))
        h = layers.reduce_sum(emb, dim=1)
        pred = layers.fc(h, 1)
        loss = layers.mean(layers.square(pred - label))
        # lazy_mode: rows-only moment updates — the industrial-scale
        # configuration (anything else is O(table) per step)
        fluid.optimizer.AdamOptimizer(1e-2,
                                      lazy_mode=True).minimize(loss)
    return main, startup, loss


@pytest.mark.slow
def test_criteo_scale_sparse_table():
    """VERDICT round-1 gap #1 'done' criterion: a Criteo-scale
    (1e7 x 64) embedding table trains with sparse updates. The
    dense-grad path at this size would allocate a second 2.5 GB table
    every step (and a 1e8-row production table would not fit at all);
    the SparseRows grad and the lazy-adam update are O(batch)."""
    vocab, dim = int(1e7), 64
    main, startup, loss = _criteo_model(vocab, dim)
    exe = fluid.Executor()
    exe.run(startup)
    r = np.random.RandomState(0)
    losses = []
    for _ in range(3):
        feed = {"ids": r.randint(0, vocab, (32, 8)).astype(np.int64),
                "label": r.rand(32, 1).astype(np.float32)}
        (lv,) = exe.run(main, feed=feed, fetch_list=[loss])
        losses.append(float(lv))
    assert np.all(np.isfinite(losses))
    assert losses[-1] < losses[0]


def test_sparse_table_row_sharded_on_mesh():
    """Row-sharded table over the tp axis + dp-sharded batch: the
    sparse lookup/update path works under GSPMD with XLA-inserted
    collectives (the pserver-sharded-table analog,
    distribute_transpiler.py:1527)."""
    vocab, dim = 100000, 16
    main, startup, loss = _criteo_model(vocab, dim)
    from paddle_tpu.parallel import shard
    for p in main.all_parameters():
        if tuple(p.shape) == (vocab, dim):
            shard(p, "tp", None)
    prog = fluid.CompiledProgram(main).with_data_parallel(
        axes={"dp": 2, "tp": 4})
    exe = fluid.Executor()
    exe.run(startup)
    r = np.random.RandomState(0)
    losses = []
    for _ in range(3):
        feed = {"ids": r.randint(0, vocab, (32, 8)).astype(np.int64),
                "label": r.rand(32, 1).astype(np.float32)}
        (lv,) = exe.run(prog, feed=feed, fetch_list=[loss])
        losses.append(float(lv))
    assert np.all(np.isfinite(losses))
    assert losses[-1] < losses[0]
