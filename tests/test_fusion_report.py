"""Fusion-boundary audit tests (PR 11): HLO parsing, boundary
neighborhoods, the CLI JSON smoke, and the acceptance regression —
the executor's rewrite boundaries (gradient-sync collective, guard
gate) must not LOWER the transformer program's fused-kernel count.
"""

import json
import os
import sys

import pytest

import paddle_tpu as fluid
from paddle_tpu import observability as obs

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(ROOT, "tools"))

import fusion_report  # noqa: E402

pytestmark = pytest.mark.compile

_HLO = """\
HloModule jit_step, entry_computation_layout={()->f32[8,8]{1,0}}

%fused_computation (param_0.1: f32[8,8]) -> f32[8,8] {
  %param_0.1 = f32[8,8]{1,0} parameter(0)
  %c = f32[] constant(2)
  %b = f32[8,8]{1,0} broadcast(f32[] %c), dimensions={}
  ROOT %m = f32[8,8]{1,0} multiply(%param_0.1, %b)
}

ENTRY %main.9 (Arg_0.1: f32[8,8], Arg_1.2: f32[8,8]) -> f32[8,8] {
  %Arg_0.1 = f32[8,8]{1,0} parameter(0)
  %Arg_1.2 = f32[8,8]{1,0} parameter(1)
  %dot.3 = f32[8,8]{1,0} dot(%Arg_0.1, %Arg_1.2)
  %fus.4 = f32[8,8]{1,0} fusion(f32[8,8]{1,0} %dot.3), kind=kLoop, calls=%fused_computation
  %ar.5 = f32[8,8]{1,0} all-reduce(f32[8,8]{1,0} %fus.4), replica_groups={}
  %sel.6 = f32[8,8]{1,0} select(pred[8,8]{1,0} %Arg_0.1, %ar.5, %Arg_1.2)
  ROOT %add.7 = f32[8,8]{1,0} add(%sel.6, %Arg_1.2)
}
"""


class TestAnalyzeHlo:
    def test_counts_and_boundaries(self):
        a = fusion_report.analyze_hlo(_HLO)
        assert a["fused_kernels"] == 1
        assert a["fusion_kinds"] == {"kLoop": 1}
        assert a["instructions"] == 7
        assert a["computations"] == 2
        bounds = a["boundaries"]["collectives"]
        assert len(bounds) == 1
        ar = bounds[0]
        assert ar["op"] == "all-reduce"
        assert ar["fed_by_fusion"] is True   # fusion feeds it
        assert ar["feeds_fusion"] is False   # bare select consumes it
        assert "select" in ar["consumer_ops"]
        # the top-level select + add are unfused elementwise residue
        assert a["boundaries"]["gate_selects_top_level"] == 1
        assert a["top_level_elementwise"]["add"] == 1

    def test_tuple_typed_instructions_parse(self):
        """Multi-output fusions, combined all-reduces, and ROOT tuples
        carry a parenthesized tuple type between '=' and the opcode —
        they must not drop out of the counts the audit gates on."""
        hlo = (
            "ENTRY %main (p0: f32[8]) -> (f32[8], f32[8]) {\n"
            "  %p0 = f32[8]{0} parameter(0)\n"
            "  %ar = (f32[8]{0}, f32[8]{0}) all-reduce(%p0, %p0), "
            "replica_groups={}\n"
            "  %gte = f32[8]{0} get-tuple-element(%ar), index=0\n"
            "  %fus = (f32[8]{0}, f32[8]{0}) fusion(%gte), kind=kLoop, "
            "calls=%fc\n"
            "  ROOT %t = (f32[8]{0}, f32[8]{0}) tuple(%gte, %p0)\n"
            "}\n")
        a = fusion_report.analyze_hlo(hlo)
        assert a["instructions"] == 5
        assert a["fused_kernels"] == 1
        assert [b["op"] for b in a["boundaries"]["collectives"]] == \
            ["all-reduce"]

    def test_calls_attr_not_counted_as_operand(self):
        a = fusion_report.analyze_hlo(_HLO)
        # the fusion's operand list is %dot.3 only — calls=%fused_...
        # must not leak into the operand scan
        comps = fusion_report._parse_computations(_HLO)
        fus = next(i for i in comps["ENTRY"] if i["op"] == "fusion")
        assert fus["operands"] == ["dot.3"]
        assert a is not None


class TestFusionReportLive:
    def test_mlp_boundary_audit_q8_guard(self):
        """q8 gradient-sync on a 2-way dp mesh + anomaly guard: the
        report sees the training program, its collective boundary
        instructions, and their fusion neighborhoods."""
        rep = fusion_report.run_and_report(
            "mlp", gradient_sync="q8", guard=True, devices=2)
        train = [r for r in rep["programs"]
                 if r["analysis"] and "x=" in r["shape_key"]]
        assert train, rep["programs"]
        a = train[0]["analysis"]
        assert a["fused_kernels"] > 0
        collectives = a["boundaries"]["collectives"]
        assert collectives, "q8 rewrite produced no collective " \
            "boundary instructions"
        ops = {b["op"] for b in collectives}
        assert "all-reduce" in ops or "all-gather" in ops
        # the audit's point: every boundary should touch fusion on at
        # least one side (a boundary with bare elementwise on BOTH
        # sides means the rewrite split the fusion region)
        touching = [b for b in collectives
                    if b["fed_by_fusion"] or b["feeds_fusion"]]
        assert touching, collectives

    # tier-1 headroom (PR 17): ~36 s; the fusion-split gate class
    # stays via test_sp_axis_boundaries_do_not_split_fusion below
    @pytest.mark.slow
    def test_transformer_rewrites_do_not_split_fusion(self):
        """ACCEPTANCE: the transformer program with q8 gradient-sync +
        anomaly guard keeps a fused-kernel count not lower than the
        plain program — the executor's rewrite boundaries add their
        own fused work but do not break the existing fusion regions.
        LIKE-FOR-LIKE: the plain baseline runs on the SAME 2-device dp
        mesh (implicit GSPMD sync, no explicit rewrites), so SPMD
        partitioning cannot inflate the augmented count and mask a
        real fusion split."""
        plain = fusion_report.run_and_report("transformer", devices=2)
        aug = fusion_report.run_and_report(
            "transformer", gradient_sync="q8", guard=True, devices=2)
        assert aug["fused_kernels_total"] >= \
            plain["fused_kernels_total"], (
                "rewrites LOWERED the fused-kernel count: %d -> %d"
                % (plain["fused_kernels_total"],
                   aug["fused_kernels_total"]))
        assert aug["collective_boundaries_total"] > 0

    # tier-1 headroom (PR 18): sp-mesh fusion audit (~15 s) -> slow;
    # boundary auditing stays via test_mlp_boundary_audit_q8_guard
    @pytest.mark.slow
    @pytest.mark.mp
    def test_sp_axis_boundaries_do_not_split_fusion(self):
        """ISSUE 13 satellite: enabling sp (attention through the
        Ulysses/zigzag schedule, sequence-sharded activations) must
        not LOWER the transformer's fused-kernel count vs the same
        4-device budget spent as pure dp — and the sp-axis collective
        boundaries (the schedules' all_to_all / permute plus GSPMD's
        reshard gathers) must be visible to the audit with fused
        kernels on at least one side."""
        base = fusion_report.run_and_report("transformer",
                                            axes={"dp": 4})
        sp = fusion_report.run_and_report(
            "transformer", axes={"dp": 2, "sp": 2})
        assert sp["fused_kernels_total"] >= \
            base["fused_kernels_total"], (
                "sp LOWERED the fused-kernel count: %d -> %d"
                % (base["fused_kernels_total"],
                   sp["fused_kernels_total"]))
        assert sp["collective_boundaries_total"] > \
            base["collective_boundaries_total"]
        colls = [b for r in sp["programs"] if r.get("analysis")
                 for b in r["analysis"]["boundaries"]["collectives"]]
        assert any(b["op"] == "all-to-all" for b in colls), \
            "the Ulysses all_to_all boundary is missing — sp did " \
            "not engage"
        assert any(b["fed_by_fusion"] or b["feeds_fusion"]
                   for b in colls)


class TestCliSmoke:
    def test_json_smoke(self, capsys):
        rc = fusion_report.main(["--model", "mlp", "--json"])
        assert rc == 0
        rep = json.loads(capsys.readouterr().out)
        assert rep["model"] == "mlp"
        assert rep["fused_kernels_total"] > 0
        assert any(r["analysis"] for r in rep["programs"])
        for r in rep["programs"]:
            assert "entry" in r and "shape_key" in r

    def test_text_summary(self, capsys):
        rc = fusion_report.main(["--model", "mlp"])
        assert rc == 0
        out = capsys.readouterr().out
        assert "fused kernels" in out
