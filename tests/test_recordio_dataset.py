"""RecordIO + industrial Dataset tests.

Reference analogs: recordio tests (recordio/chunk.h round-trip,
README fault-tolerant reading), test_dataset.py (InMemoryDataset /
QueueDataset load + shuffle), and the Executor::RunFromDataset path
(executor.cc:120).
"""

import os
import struct

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import layers, recordio


class TestRecordIO:
    def test_roundtrip(self, tmp_path):
        p = str(tmp_path / "a.rio")
        recs = [b"hello", b"w" * 300, b"", b"x" * 5000]
        recordio.write_records(p, recs * 100, max_chunk_bytes=4096)
        assert recordio.read_records(p) == recs * 100

    def test_native_library_builds(self):
        """The C++ scanner must actually be in play (g++ is in the
        image); the pure-python path is only a fallback."""
        assert recordio._native() is not None

    def test_corrupt_chunk_skipped(self, tmp_path):
        p = str(tmp_path / "b.rio")
        recs = [b"r%d" % i for i in range(1000)]
        recordio.write_records(p, recs, max_chunk_bytes=1024)
        data = bytearray(open(p, "rb").read())
        data[len(data) // 2] ^= 0xFF  # flip one payload byte
        open(p, "wb").write(bytes(data))
        s = recordio.Scanner(p)
        out = list(s)
        assert s.skipped_chunks >= 1
        # lost at most a couple of chunks, kept the rest, order intact
        assert len(out) > 800
        assert out == [r for r in recs if r in set(out)]

    def test_truncated_tail_recovered(self, tmp_path):
        """A crashed writer's half-written last chunk must not poison
        the file (recordio/README.md fault-tolerant writing)."""
        p = str(tmp_path / "c.rio")
        recs = [b"%d" % i for i in range(500)]
        recordio.write_records(p, recs, max_chunk_bytes=512)
        data = open(p, "rb").read()
        open(p, "wb").write(data[:len(data) - 37])
        out = recordio.read_records(p)
        assert 0 < len(out) < 500
        assert out == recs[:len(out)]

    def test_corrupt_size_field_resyncs(self, tmp_path):
        """A flipped byte in a chunk header's size field must not eat
        the rest of the file — the reader resyncs on the next magic."""
        p = str(tmp_path / "d.rio")
        recs = [b"rec%04d" % i for i in range(400)]
        recordio.write_records(p, recs, max_chunk_bytes=256)
        data = bytearray(open(p, "rb").read())
        # find the second chunk header and blow up its size field
        second = data.find(struct.pack("<I", recordio.MAGIC), 4)
        assert second > 0
        data[second + 8] = 0xFF
        data[second + 9] = 0xFF
        open(p, "wb").write(bytes(data))
        s = recordio.Scanner(p)
        out = list(s)
        assert s.skipped_chunks >= 1
        assert len(out) > 300  # later chunks recovered

    def test_scanner_reiterable(self, tmp_path):
        p = str(tmp_path / "e.rio")
        recs = [b"a", b"bb", b"ccc"]
        recordio.write_records(p, recs)
        s = recordio.Scanner(p)
        assert list(s) == recs
        assert list(s) == recs  # a second pass rescans the file

    def test_python_native_interop(self, tmp_path):
        """The pure-python fallback writes/reads the same format."""
        import paddle_tpu.recordio as R
        p1 = str(tmp_path / "n.rio")
        p2 = str(tmp_path / "p.rio")
        recs = [b"alpha", b"beta" * 50, b""]
        R.write_records(p1, recs)  # native write
        lib = R._lib
        try:
            R._lib = None  # force python path
            assert list(R.Scanner(p1)) == recs
            R.write_records(p2, recs)
        finally:
            R._lib = lib
        assert R.read_records(p2) == recs  # native read


def _write_multislot(path, rows):
    """rows: list of (ids[4], label) — MultiSlot text format."""
    with open(path, "w") as f:
        for ids, label in rows:
            f.write("%d %s 1 %.1f\n"
                    % (len(ids), " ".join(map(str, ids)), label))


class _Var:
    def __init__(self, name, dtype):
        self.name = name
        self.dtype = dtype


class TestDataset:
    def _files(self, tmp_path, n_files=3, rows_per=20):
        rs = np.random.RandomState(5)
        paths, all_rows = [], []
        for i in range(n_files):
            p = str(tmp_path / ("part-%d.txt" % i))
            rows = [(list(rs.randint(0, 50, 4)), float(rs.rand()))
                    for _ in range(rows_per)]
            _write_multislot(p, rows)
            paths.append(p)
            all_rows.extend(rows)
        return paths, all_rows

    def _dataset(self, paths, kind="InMemoryDataset", bs=8):
        ds = fluid.DatasetFactory().create_dataset(kind)
        ds.set_filelist(paths)
        ds.set_batch_size(bs)
        ds.set_thread(3)
        ds.set_use_var([_Var("ids", "int64"), _Var("label", "float32")])
        return ds

    def test_load_and_batch(self, tmp_path):
        paths, rows = self._files(tmp_path)
        ds = self._dataset(paths)
        ds.load_into_memory()
        assert ds.get_memory_data_size() == len(rows)
        batches = list(ds.batch_iterator())
        assert len(batches) == len(rows) // 8
        b = batches[0]
        assert b["ids"].shape == (8, 4) and b["ids"].dtype == np.int64
        assert b["label"].shape == (8, 1)

    def test_local_shuffle_deterministic(self, tmp_path):
        paths, _ = self._files(tmp_path)
        orders = []
        for _ in range(2):
            ds = self._dataset(paths)
            ds.set_seed(13)
            ds.load_into_memory()
            ds.local_shuffle()
            orders.append([b["ids"].tobytes()
                           for b in ds.batch_iterator()])
        assert orders[0] == orders[1]  # same seed, same thread-count
        ds = self._dataset(paths)
        ds.set_seed(99)
        ds.load_into_memory()
        ds.local_shuffle()
        other = [b["ids"].tobytes() for b in ds.batch_iterator()]
        assert other != orders[0]

    def test_load_order_independent_of_threads(self, tmp_path):
        """Thread completion order must not leak into the data order
        (canonical sort before seeded shuffle)."""
        paths, _ = self._files(tmp_path, n_files=6)
        snaps = []
        for threads in (1, 4):
            ds = self._dataset(paths)
            ds.set_thread(threads)
            ds.set_seed(3)
            ds.load_into_memory()
            ds.local_shuffle()
            snaps.append([b["ids"].tobytes()
                          for b in ds.batch_iterator()])
        assert snaps[0] == snaps[1]

    def test_global_shuffle_partitions(self, tmp_path):
        """Worker partitions are disjoint and cover everything — the
        contract of the reference's cross-node GlobalShuffle."""
        paths, rows = self._files(tmp_path)

        class FakeFleet:
            def __init__(self, r, n):
                self._r, self._n = r, n

            def worker_index(self):
                return self._r

            def worker_num(self):
                return self._n

        sizes, seen = [], []
        for r in range(2):
            ds = self._dataset(paths)
            ds.set_seed(7)
            ds.load_into_memory()
            ds.global_shuffle(FakeFleet(r, 2))
            part = [tuple(ins[0].tolist()) + (float(ins[1][0]),)
                    for ins in ds._instances]
            sizes.append(len(part))
            seen.append(set(part))
        # rows are random → effectively unique; partitions disjoint
        assert sizes[0] + sizes[1] == len(rows)
        assert not (seen[0] & seen[1])

    def test_queue_dataset_streams(self, tmp_path):
        paths, rows = self._files(tmp_path)
        ds = self._dataset(paths, "QueueDataset", bs=10)
        batches = list(ds.batch_iterator())
        assert len(batches) == len(rows) // 10
        assert batches[0]["ids"].shape == (10, 4)

    def test_queue_dataset_early_break_no_hang(self, tmp_path):
        """Abandoning the streaming iterator must stop the reader
        threads (regression: producers used to block forever on the
        bounded queue)."""
        import threading as _t
        paths, _ = self._files(tmp_path, n_files=2, rows_per=200)
        ds = self._dataset(paths, "QueueDataset", bs=4)
        before = _t.active_count()
        it = ds.batch_iterator()
        next(it)
        it.close()  # triggers GeneratorExit → stop + join
        assert _t.active_count() <= before + 1

    def test_pipe_command_rejected(self):
        ds = fluid.DatasetFactory().create_dataset("QueueDataset")
        ds.set_pipe_command("cat")  # identity ok
        with pytest.raises(NotImplementedError):
            ds.set_pipe_command("zcat")

    def test_recordio_files_through_dataset(self, tmp_path):
        p = str(tmp_path / "data.rio")
        rows = [("3 1 2 3 1 0.5"), ("3 4 5 6 1 1.5")]
        recordio.write_records(p, [r.encode() for r in rows])
        ds = fluid.DatasetFactory().create_dataset("InMemoryDataset")
        ds.set_filelist([p])
        ds.set_batch_size(2)
        ds.set_use_var([_Var("ids", "int64"), _Var("label", "float32")])
        ds.load_into_memory()
        (batch,) = list(ds.batch_iterator())
        assert batch["ids"].shape == (2, 3)

    def test_train_from_dataset(self, tmp_path):
        """DeepFM-style CTR flow: train a model straight from files
        (the Executor::RunFromDataset analog)."""
        rs = np.random.RandomState(0)
        w_true = rs.rand(50).astype(np.float32)
        paths = []
        for i in range(2):
            p = str(tmp_path / ("train-%d.rio" % i))
            recs = []
            for _ in range(160):
                ids = rs.randint(0, 50, 4)
                label = w_true[ids].sum()
                recs.append(("4 %s 1 %.6f" % (
                    " ".join(map(str, ids)), label)).encode())
            recordio.write_records(p, recs)
            paths.append(p)

        scope = fluid.Scope()
        with fluid.scope_guard(scope):
            main, startup = fluid.Program(), fluid.Program()
            main.random_seed = startup.random_seed = 2
            with fluid.program_guard(main, startup):
                ids = layers.data("ids", shape=[8, 4], dtype="int64",
                                  append_batch_size=False)
                label = layers.data("label", shape=[8, 1],
                                    append_batch_size=False)
                emb = layers.embedding(ids, size=(50, 1),
                                       param_attr=fluid.ParamAttr(
                                           name="table"))
                pred = layers.reduce_sum(
                    layers.reshape(emb, (8, 4)), dim=1, keep_dim=True)
                loss = layers.reduce_mean(
                    layers.square_error_cost(input=pred, label=label))
                fluid.optimizer.Adam(0.1).minimize(loss)

            ds = fluid.DatasetFactory().create_dataset(
                "InMemoryDataset")
            ds.set_filelist(paths)
            ds.set_batch_size(8)
            ds.set_thread(2)
            ds.set_seed(1)
            ds.set_use_var([ids, label])
            ds.load_into_memory()
            ds.local_shuffle()

            exe = fluid.Executor()
            exe.run(startup)
            first = None
            for epoch in range(4):
                for feed in ds.batch_iterator():
                    (lv,) = exe.run(main, feed=feed,
                                    fetch_list=[loss])
                    if first is None:
                        first = float(lv)
            last = float(lv)
            assert last < first * 0.2, (first, last)
            # the Executor entry point drives the same loop
            n = exe.train_from_dataset(main, ds, fetch_list=[loss])
            assert n == ds.get_memory_data_size() // 8


class TestNativeMultiSlotParser:
    def test_native_matches_python(self, tmp_path, rng):
        """The C++ tokenizer must produce byte-identical instances to
        the Python reference parser."""
        from paddle_tpu.dataset_factory import (DatasetFactory,
                                                _multislot_lib)
        assert _multislot_lib() is not None, "native parser not built"
        rows = ["0 2 1.5 2.5", "0 1 3.5"]  # empty int slot (sparse)
        for _ in range(48):
            n1 = rng.randint(1, 5)
            n2 = rng.randint(1, 4)
            rows.append("%d %s %d %s" % (
                n1, " ".join(str(rng.randint(0, 99)) for _ in range(n1)),
                n2, " ".join("%.4f" % v for v in rng.rand(n2))))
        path = tmp_path / "part.txt"
        path.write_text("\n".join(rows) + "\n\n")  # trailing blank line

        class _V:
            def __init__(self, name, dtype, shape):
                self.name, self.dtype, self.shape = name, dtype, shape

        def load(native):
            ds = DatasetFactory().create_dataset("InMemoryDataset")
            ds.set_batch_size(10)
            ds.set_use_var([_V("ids", "int64", (-1, 8)),
                            _V("vals", "float32", (-1, 8))])
            if not native:
                # force the python tokenizer path
                ds._parse_file_native = lambda p: None
            ds.set_filelist([str(path)])
            ds.load_into_memory()
            return ds._instances

        a = load(native=True)
        b = load(native=False)
        assert len(a) == len(b) == 50
        for ia, ib in zip(a, b):
            for sa, sb in zip(ia, ib):
                assert sa.dtype == sb.dtype
                np.testing.assert_array_equal(sa, sb)

    def test_native_rejects_malformed(self, tmp_path):
        from paddle_tpu.dataset_factory import _multislot_lib
        import ctypes
        lib = _multislot_lib()
        p = tmp_path / "bad.txt"
        p.write_text("2 1.0\n")  # declares 2 values, has 1
        is_int = (ctypes.c_uint8 * 1)(0)
        h = lib.ms_parse_file(str(p).encode(), is_int, 1)
        try:
            assert lib.ms_error(h) is not None
        finally:
            lib.ms_free(h)
