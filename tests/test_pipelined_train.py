"""Pipelined data-fed training: K stacked batches ride one lax.scan
dispatch (Executor.run_pipelined) while DevicePrefetcher stages the
next chunk host-side — per-step parity is BIT-exact (same PRNG keys as
sequential run() calls) and the dispatch count collapses to
ceil(steps/K) + O(1)."""

import time

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import layers

pytestmark = pytest.mark.pipeline


def _net(seed=7, lr=1e-2):
    main, start = fluid.Program(), fluid.Program()
    main.random_seed = start.random_seed = seed
    with fluid.program_guard(main, start):
        x = layers.data("x", [32], dtype="float32")
        y = layers.data("y", [1], dtype="int64")
        h = layers.fc(x, size=64, act="relu")
        logits = layers.fc(h, size=10)
        loss = layers.reduce_mean(
            layers.softmax_with_cross_entropy(logits, y))
        fluid.optimizer.Adam(learning_rate=lr).minimize(loss)
    return main, start, loss


def _feeds(n, batch=8):
    rs = np.random.RandomState(0)
    return [{"x": rs.rand(batch, 32).astype("float32"),
             "y": rs.randint(0, 10, (batch, 1)).astype("int64")}
            for _ in range(n)]


def _stack(feeds):
    return {k: np.stack([f[k] for f in feeds]) for k in feeds[0]}


def test_matches_per_step_run_bit_for_bit():
    """Chunked scan losses equal sequential run() losses EXACTLY —
    same per-step PRNG keys (fold_in(program_key, global_step)), same
    op math, so the only difference is where the loop lives."""
    feeds = _feeds(6)
    main, start, loss = _net()
    s1 = fluid.core.Scope()
    exe = fluid.Executor()
    with fluid.scope_guard(s1):
        exe.run(start)
        seq = [float(np.ravel(exe.run(main, feed=f,
                                      fetch_list=[loss])[0])[0])
               for f in feeds]

    main2, start2, loss2 = _net()
    s2 = fluid.core.Scope()
    exe2 = fluid.Executor()
    with fluid.scope_guard(s2):
        exe2.run(start2)
        d0 = exe2.dispatch_count
        r1 = exe2.run_pipelined(main2, feed_chunk=_stack(feeds[:3]),
                                fetch_list=[loss2])
        r2 = exe2.run_pipelined(main2, feed_chunk=_stack(feeds[3:]),
                                fetch_list=[loss2])
        # 6 steps, K=3 -> exactly 2 device dispatches, 1 chunk compile
        assert exe2.dispatch_count - d0 == 2
    assert float(np.ravel(r1)[0]) == seq[2]
    assert float(np.ravel(r2)[0]) == seq[5]


def test_rng_ops_fold_the_sequential_keys():
    """Dropout inside the chunk must draw the EXACT mask the same
    global step would draw from a sequential run() call — the
    accumulated mask sums match bitwise."""
    def build():
        main, start = fluid.Program(), fluid.Program()
        main.random_seed = start.random_seed = 3
        with fluid.program_guard(main, start):
            x = layers.data("x", [64], dtype="float32")
            d = layers.dropout(x, dropout_prob=0.5)
            step_sum = layers.reduce_sum(d)
            acc = layers.create_global_var(
                shape=[1], value=0.0, dtype="float32",
                persistable=True, name="acc")
            layers.assign(layers.elementwise_add(
                acc, layers.reshape(step_sum, [1])), acc)
        return main, start

    feeds = [{"x": np.full((4, 64), 1.0 + i, np.float32)}
             for i in range(3)]

    main, start = build()
    s1 = fluid.core.Scope()
    exe = fluid.Executor()
    with fluid.scope_guard(s1):
        exe.run(start)
        for f in feeds:
            out = exe.run(main, feed=f, fetch_list=["acc"])
    want = float(np.ravel(out[0])[0])

    main2, start2 = build()
    s2 = fluid.core.Scope()
    exe2 = fluid.Executor()
    with fluid.scope_guard(s2):
        exe2.run(start2)
        out2 = exe2.run_pipelined(main2, feed_chunk=_stack(feeds),
                                  fetch_list=["acc"])
    assert float(np.ravel(out2[0])[0]) == want


def test_ragged_tail_chunk_and_compile_accounting():
    """A shorter tail chunk runs correctly and costs exactly one
    extra compile (its K is part of the shape signature)."""
    feeds = _feeds(5)
    main, start, loss = _net()
    sc = fluid.core.Scope()
    exe = fluid.Executor()
    with fluid.scope_guard(sc):
        exe.run(start)
        c0 = exe.compile_count
        exe.run_pipelined(main, feed_chunk=_stack(feeds[:4]),
                          fetch_list=[loss])
        assert exe.compile_count - c0 == 1
        exe.run_pipelined(main, feed_chunk=_stack(feeds[:4]),
                          fetch_list=[loss])
        assert exe.compile_count - c0 == 1  # same shape: cached
        out = exe.run_pipelined(main, feed_chunk=_stack(feeds[4:]),
                                fetch_list=[loss])
        assert exe.compile_count - c0 == 2  # tail K=1
    assert np.isfinite(np.ravel(out[0])[0])


def test_feed_chunk_validation():
    main, start, loss = _net()
    sc = fluid.core.Scope()
    exe = fluid.Executor()
    with fluid.scope_guard(sc):
        exe.run(start)
        with pytest.raises(fluid.core.InvalidArgumentError,
                           match="non-empty"):
            exe.run_pipelined(main, feed_chunk={},
                              fetch_list=[loss])
        bad = _stack(_feeds(3))
        bad["y"] = bad["y"][:2]
        with pytest.raises(fluid.core.InvalidArgumentError,
                           match="leading dims disagree"):
            exe.run_pipelined(main, feed_chunk=bad,
                              fetch_list=[loss])
        # per-step slice shape is validated against the declaration
        bad2 = _stack(_feeds(3))
        bad2["x"] = bad2["x"][:, :, :16]
        with pytest.raises(fluid.core.InvalidArgumentError,
                           match="shape"):
            exe.run_pipelined(main, feed_chunk=bad2,
                              fetch_list=[loss])


# ---------------------------------------------------------------------
# DevicePrefetcher
# ---------------------------------------------------------------------

def test_prefetcher_stacks_chunks_and_reports_stats():
    feeds = _feeds(7)
    with fluid.DevicePrefetcher(iter(feeds), chunk_size=3,
                                depth=2) as pf:
        got = list(pf)
    assert [k for _, k in got] == [3, 3, 1]
    chunk0 = got[0][0]
    assert chunk0["x"].shape == (3, 8, 32)
    np.testing.assert_array_equal(np.asarray(chunk0["x"]),
                                  np.stack([f["x"] for f in
                                            feeds[:3]]))
    stats = pf.stats()
    assert stats["chunks"] == 3 and stats["steps"] == 7
    assert stats["stall_s"] >= 0.0
    assert stats["stall_fraction"] is None or \
        0.0 <= stats["stall_fraction"] <= 1.0


def test_prefetcher_propagates_generator_exception():
    def gen():
        yield _feeds(1)[0]
        raise RuntimeError("reader blew up")

    pf = fluid.DevicePrefetcher(gen(), chunk_size=1)
    next(pf)
    with pytest.raises(RuntimeError, match="reader blew up"):
        next(pf)
    pf.close()


def test_prefetcher_close_releases_producer():
    """Abandoning iteration mid-stream must not leave the producer
    blocked on the bounded queue forever."""
    produced = []

    def gen():
        for f in _feeds(100):
            produced.append(1)
            yield f

    pf = fluid.DevicePrefetcher(gen(), chunk_size=2, depth=1)
    next(pf)
    pf.close()
    pf._thread.join(timeout=5)
    assert not pf._thread.is_alive()
    n = len(produced)
    time.sleep(0.2)
    assert len(produced) == n  # really stopped
    with pytest.raises(StopIteration):
        next(pf)


def test_prefetcher_rejects_heterogeneous_keys():
    batches = [{"x": np.ones((2, 4), np.float32)},
               {"y": np.ones((2, 4), np.float32)}]
    pf = fluid.DevicePrefetcher(iter(batches), chunk_size=2)
    with pytest.raises(fluid.core.InvalidArgumentError,
                       match="homogeneous"):
        next(pf)
    pf.close()


# ---------------------------------------------------------------------
# train_from_dataset / infer_from_dataset routing
# ---------------------------------------------------------------------

def _write_multislot(tmp_path, n_lines, seed=0):
    rs = np.random.RandomState(seed)
    w = rs.rand(30).astype(np.float32)
    p = tmp_path / "train.txt"
    with open(p, "w") as f:
        for _ in range(n_lines):
            ids = rs.randint(0, 30, 4)
            f.write("4 %s 1 %.6f\n"
                    % (" ".join(map(str, ids)), w[ids].sum()))
    return str(p)


def _dataset_net(lr=0.1):
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = 2
    with fluid.program_guard(main, startup):
        ids = layers.data("ids", shape=[8, 4], dtype="int64",
                          append_batch_size=False)
        label = layers.data("label", shape=[8, 1],
                            append_batch_size=False)
        emb = layers.embedding(ids, size=(30, 1),
                               param_attr=fluid.ParamAttr(
                                   name="table"))
        pred = layers.reduce_sum(
            layers.reshape(emb, (8, 4)), dim=1, keep_dim=True)
        loss = layers.reduce_mean(
            layers.square_error_cost(input=pred, label=label))
        if lr:
            fluid.optimizer.Adam(lr).minimize(loss)
    return main, startup, ids, label, loss


def _make_dataset(path, ids, label, batch=8):
    ds = fluid.DatasetFactory().create_dataset("InMemoryDataset")
    ds.set_filelist([path])
    ds.set_batch_size(batch)
    ds.set_use_var([ids, label])
    ds.load_into_memory()
    return ds


def test_train_from_dataset_dispatch_bound_and_parity(tmp_path):
    """40 data-fed steps with chunk_size=4 issue exactly ceil(40/4)
    dispatches, produce the same final weights as the per-step loop,
    and record prefetch stats."""
    path = _write_multislot(tmp_path, 320)

    def run(chunk_size):
        scope = fluid.core.Scope()
        with fluid.scope_guard(scope):
            main, startup, ids, label, loss = _dataset_net()
            ds = _make_dataset(path, ids, label)
            exe = fluid.Executor()
            exe.run(startup)
            d0 = exe.dispatch_count
            n = exe.train_from_dataset(main, ds,
                                       chunk_size=chunk_size)
            table = np.asarray(scope.find_var("table"))
            return n, exe.dispatch_count - d0, table, exe

    n_pipe, d_pipe, w_pipe, exe = run(chunk_size=4)
    assert n_pipe == 40
    assert d_pipe == 10  # ceil(40/4), zero per-step dispatches
    stats = exe.last_pipeline_stats
    assert stats is not None and stats["steps"] == 40 \
        and stats["chunks"] == 10

    n_step, d_step, w_step, _ = run(chunk_size=1)
    assert n_step == 40 and d_step == 40
    np.testing.assert_array_equal(w_pipe, w_step)


def test_entry_point_labels(tmp_path, capsys):
    """Progress lines carry the ACTUAL entry point's name — inference
    through infer_from_dataset must not print [train_from_dataset]."""
    path = _write_multislot(tmp_path, 64)
    scope = fluid.core.Scope()
    with fluid.scope_guard(scope):
        main, startup, ids, label, loss = _dataset_net()
        ds = _make_dataset(path, ids, label)
        exe = fluid.Executor()
        exe.run(startup)
        exe.train_from_dataset(main, ds, fetch_list=[loss],
                               print_period=4, chunk_size=4)
        train_out = capsys.readouterr().out
        infer_prog = main.clone(for_test=True)
        exe.infer_from_dataset(infer_prog, ds, fetch_list=[loss],
                               print_period=4, chunk_size=4)
        infer_out = capsys.readouterr().out
    assert "[train_from_dataset] step" in train_out
    assert "[infer_from_dataset] step" in infer_out
    assert "[train_from_dataset]" not in infer_out


def test_infer_from_dataset_per_step_label(tmp_path, capsys):
    """The per-step (chunk_size=1) loop is labelled too."""
    path = _write_multislot(tmp_path, 32)
    scope = fluid.core.Scope()
    with fluid.scope_guard(scope):
        main, startup, ids, label, loss = _dataset_net(lr=0)
        ds = _make_dataset(path, ids, label)
        exe = fluid.Executor()
        exe.run(startup)
        n = exe.infer_from_dataset(main, ds, fetch_list=[loss],
                                   print_period=2, chunk_size=1)
    assert n == 4
    out = capsys.readouterr().out
    assert "[infer_from_dataset] step 2" in out
    assert "[train_from_dataset]" not in out


def test_chunk_iterator_matches_prefetcher_stacking(tmp_path):
    path = _write_multislot(tmp_path, 80)
    main, startup, ids, label, loss = _dataset_net(lr=0)
    ds = _make_dataset(path, ids, label)
    chunks = list(ds.chunk_iterator(4))
    assert [k for _, k in chunks] == [4, 4, 2]
    assert chunks[0][0]["ids"].shape == (4, 8, 4)
    full = list(ds.chunk_iterator(4, drop_last_chunk=True))
    assert [k for _, k in full] == [4, 4]
