"""Tiered sparse embedding plane (ISSUE 14, docs/sparse.md): hot-tier
row cache, durable spill tier, q8 sparse wire with error feedback, and
the exactly-once restart semantics across the three tiers.

Reference discipline: the loss-equality posture of test_dist_base.py
— every approximation (q8 wire, cache mirror) is held against its
exact twin, bit-equal where the design claims bit-equal (spill
round-trip, mirror_sgd write-through, snapshot restore) and
rtol-bounded where it claims bounded (EF telescope, pull
quantization)."""

import os
import tempfile

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import layers
from paddle_tpu.distributed import (EmbeddingRowCache, LargeScaleKV,
                                    LookupServiceClient, RowSpillStore,
                                    SparseEmbeddingRuntime,
                                    SparsePServer, SparseTierConfig)
from paddle_tpu.parallel.collectives import (SPARSE_Q8_MIN_DIM,
                                             dequantize_rows_q8,
                                             quantize_rows_q8,
                                             sparse_wire_bytes)

pytestmark = pytest.mark.sparse


# ---------------------------------------------------------------------------
# q8 row codec (the shared wire format)
# ---------------------------------------------------------------------------

class TestRowCodec:
    def test_roundtrip_error_bound(self, rng):
        rows = (rng.randn(64, 32) * rng.lognormal(size=(64, 1))) \
            .astype(np.float32)
        q, scale = quantize_rows_q8(rows)
        assert q.dtype == np.int8 and scale.shape == (64,)
        err = np.abs(dequantize_rows_q8(q, scale) - rows)
        # per-element bound: half a quantization step of the row scale
        assert (err <= scale[:, None] / 2 + 1e-7).all()

    def test_all_zero_rows_dequantize_to_zero(self):
        q, scale = quantize_rows_q8(np.zeros((3, 16), np.float32))
        assert (scale == 1.0).all()
        assert (dequantize_rows_q8(q, scale) == 0.0).all()

    def test_matches_device_codec_geometry(self, rng):
        """Host rows and the device block codec agree when the block
        IS the row (block_size=dim) — one error model for wire and
        collective quantization."""
        import jax.numpy as jnp

        from paddle_tpu.parallel.collectives import (dequantize_q8,
                                                     quantize_q8)
        rows = rng.randn(8, 32).astype(np.float32)
        qh, sh = quantize_rows_q8(rows)
        qd, sd = quantize_q8(jnp.asarray(rows))
        np.testing.assert_array_equal(qh, np.asarray(qd))
        np.testing.assert_allclose(sh, np.asarray(sd), rtol=1e-6)
        np.testing.assert_allclose(
            dequantize_rows_q8(qh, sh),
            np.asarray(dequantize_q8(qd, sd)), rtol=1e-6)

    def test_wire_bytes_pricing(self):
        # dim 32: q8 moves 8+36=44 per row vs 8+128=136 fp32 -> 0.32x
        assert sparse_wire_bytes(10, 32, q8=True) == 10 * (8 + 36)
        assert sparse_wire_bytes(10, 32, q8=False) == 10 * (8 + 128)
        ratio = sparse_wire_bytes(1000, 32, True) \
            / sparse_wire_bytes(1000, 32, False)
        assert ratio <= 0.35


# ---------------------------------------------------------------------------
# Tier 0: hot row cache
# ---------------------------------------------------------------------------

class TestEmbeddingRowCache:
    def test_admission_by_touch_frequency(self):
        c = EmbeddingRowCache(dim=4, capacity_bytes=16 * 100,
                              admit_after=2)
        rows = np.ones((2, 4), np.float32)
        ids = np.array([1, 2])
        c.get_many(ids)              # 1st miss
        c.put_many(ids, rows)        # not admissible yet
        assert len(c) == 0
        c.get_many(ids)              # 2nd miss -> admissible
        c.put_many(ids, rows)
        assert len(c) == 2
        _, hit = c.get_many(ids)
        assert hit.all()

    def test_clock_eviction_respects_budget_and_second_chance(self):
        c = EmbeddingRowCache(dim=4, capacity_bytes=16 * 4)  # 4 rows
        ids = np.arange(4)
        c.get_many(ids)
        c.put_many(ids, np.ones((4, 4), np.float32))
        assert len(c) == 4
        # touch rows 0 and 1 (ref bits set), then insert two more:
        # the UNtouched 2,3 must be the victims
        c.get_many(np.array([0, 1]))
        newer = np.arange(4, 6)
        c.get_many(newer)
        c.put_many(newer, np.full((2, 4), 2.0, np.float32))
        assert len(c) == 4
        _, hit = c.get_many(np.arange(6))
        assert list(hit) == [True, True, False, False, True, True]
        assert c.stats()["evictions"] == 2
        assert c.resident_bytes() == 4 * 16

    def test_write_through_and_invalidation(self):
        c = EmbeddingRowCache(dim=2, capacity_bytes=8 * 10)
        ids = np.array([7, 9])
        c.get_many(ids)
        c.put_many(ids, np.zeros((2, 2), np.float32))
        c.apply_delta(np.array([7, 9, 11]),   # 11 absent: ignored
                      np.full((3, 2), 0.5, np.float32))
        out, hit = c.get_many(ids)
        assert hit.all()
        np.testing.assert_array_equal(out, np.full((2, 2), 0.5))
        assert c.invalidate_ids([7]) == 1
        _, hit = c.get_many(ids)
        assert list(hit) == [False, True]
        assert c.invalidate_all() == 1
        assert len(c) == 0

    def test_admission_protects_hot_set_from_one_touch_flood(self, rng):
        """The TinyLFU argument, under a long stream: a hot working
        set + a one-touch cold flood. With admit_after=2 the flood
        never displaces hot rows; with admit_after=1 it churns
        them."""

        def run(admit_after):
            c = EmbeddingRowCache(dim=4, capacity_bytes=16 * 64,
                                  admit_after=admit_after)
            hot = np.arange(50)
            hot_rows = np.ones((50, 4), np.float32)
            for step in range(60):
                _, h = c.get_many(hot)
                c.put_many(hot, hot_rows)
                flood = 10_000 + np.arange(step * 64, step * 64 + 64)
                c.get_many(flood)
                c.put_many(flood, np.zeros((64, 4), np.float32))
            _, hit = c.get_many(hot)
            return hit.mean()

        assert run(2) == 1.0          # hot set fully resident
        assert run(2) > run(1)        # and strictly better than no
        #                               admission under the same flood


# ---------------------------------------------------------------------------
# Tier 2: durable spill
# ---------------------------------------------------------------------------

class TestSpillTier:
    def test_budget_bounds_resident_and_rows_bit_equal(self, rng):
        """The acceptance shape: a logical table larger than the
        resident budget trains on, resident rows stay bounded, and
        every row (spilled or not) reads back BIT-equal to an
        unbounded twin fed the identical stream."""
        tmp = tempfile.mkdtemp()
        budget_rows = 32
        kv = LargeScaleKV(dim=8, optimizer="sgd", lr=0.1, seed=3,
                          resident_bytes=budget_rows * 32,
                          spill_dir=tmp)
        twin = LargeScaleKV(dim=8, optimizer="sgd", lr=0.1, seed=3)
        for _ in range(30):
            ids = rng.randint(0, 2000, 64)
            g = rng.randn(64, 8).astype(np.float32)
            kv.push(ids, g)
            twin.push(ids, g)
            assert kv.resident_size() <= kv.resident_rows
        assert kv.stats()["spilled_rows"] > 0
        probe = rng.randint(0, 2000, 300)
        np.testing.assert_array_equal(kv.pull(probe), twin.pull(probe))

    def test_adagrad_state_spills_with_the_row(self, rng):
        tmp = tempfile.mkdtemp()
        kv = LargeScaleKV(dim=4, optimizer="adagrad", lr=0.5, seed=1,
                          resident_bytes=8 * 8 * 2, spill_dir=tmp)
        twin = LargeScaleKV(dim=4, optimizer="adagrad", lr=0.5,
                            seed=1)
        for _ in range(20):
            ids = rng.randint(0, 200, 16)
            g = rng.randn(16, 4).astype(np.float32)
            kv.push(ids, g)
            twin.push(ids, g)
        probe = np.arange(200)
        np.testing.assert_array_equal(kv.pull(probe), twin.pull(probe))

    def test_batched_eviction_one_segment_per_op(self, rng):
        """A cold batch at budget spills via ONE reserve segment (+
        at most one trim segment), not one fsynced file per evicted
        row."""
        tmp = tempfile.mkdtemp()
        kv = LargeScaleKV(dim=8, optimizer="sgd", lr=0.1, seed=3,
                          resident_bytes=32 * 32, spill_dir=tmp)
        kv.push(np.arange(32), rng.randn(32, 8).astype(np.float32))
        segs_before = len(os.listdir(tmp))
        # 32 brand-new ids displace the 32 resident ones
        kv.push(np.arange(100, 132),
                rng.randn(32, 8).astype(np.float32))
        assert len(os.listdir(tmp)) - segs_before <= 2
        assert kv.resident_size() <= kv.resident_rows

    def test_save_lookup_table_includes_spilled_rows(self, rng):
        """contrib checkpoint x Tier 2: a budgeted table's checkpoint
        must carry the SPILLED trained rows too, and restore
        bit-equal into an unbudgeted table."""
        from paddle_tpu.contrib.utils.lookup_table_utils import (
            _load_table_file, save_lookup_table)
        tmp, ckpt = tempfile.mkdtemp(), tempfile.mkdtemp()
        kv = LargeScaleKV(dim=4, optimizer="adagrad", lr=0.3, seed=7,
                          resident_bytes=8 * 16, spill_dir=tmp)
        for _ in range(10):
            kv.push(rng.randint(0, 300, 32),
                    rng.randn(32, 4).astype(np.float32))
        assert kv.stats()["spilled_rows"] > 0
        save_lookup_table(kv, ckpt)
        blob = _load_table_file(ckpt)
        assert len(blob["ids"]) == kv.size()   # resident + spilled
        by_id = {int(i): blob["rows"][j]
                 for j, i in enumerate(blob["ids"])}
        probe = np.asarray(sorted(by_id), np.int64)
        np.testing.assert_array_equal(
            np.stack([by_id[int(i)] for i in probe]),
            kv.pull(probe))

    def test_convert_dist_program_carries_padding_idx(self):
        from paddle_tpu.contrib.utils.lookup_table_utils import (
            convert_dist_to_sparse_program)
        main, _startup, _loss = _ctr_model(50, 16, padding_idx=0)
        out = convert_dist_to_sparse_program(main)
        op = out.global_block().ops[0]
        assert op.type == "lookup_table"
        assert op.attr("padding_idx") == 0

    def test_residual_cap_bounds_map_and_keeps_hot(self, rng):
        servers, _ = _sparse_server()
        try:
            cl = LookupServiceClient("emb", [servers[0].endpoint],
                                     dim=32, trainer_id=0,
                                     push_q8=True,
                                     max_residual_rows=64)
            for step in range(8):
                ids = np.arange(step * 40, step * 40 + 40)
                cl.push(ids, rng.randn(40, 32).astype(np.float32))
            assert len(cl.residuals) <= 64
            assert cl.stats()["residuals_dropped"] > 0
            cl.close()
        finally:
            for s_ in servers:
                s_.shutdown()

    def test_duplicated_pull_ids_reserve_one_slot(self, rng):
        """pull() accepts duplicated ids; the budget reservation must
        count UNIQUE new ids, not copies — over-counting evicted warm
        rows into needless fsynced segments."""
        tmp = tempfile.mkdtemp()
        kv = LargeScaleKV(dim=8, seed=1, resident_bytes=32 * 100,
                          spill_dir=tmp)
        kv.pull(np.arange(50))
        kv.pull(np.full(90, 1000, np.int64))   # ONE new id, 90 copies
        st = kv.stats()
        assert st["spill_writes"] == 0, st
        assert st["resident_rows"] == 51

    def test_scan_skips_foreign_seg_files(self):
        tmp = tempfile.mkdtemp()
        st = RowSpillStore(tmp)
        st.spill({1: np.ones(4, np.float32)})
        open(os.path.join(tmp, "seg-copy.bak"), "w").close()
        st2 = RowSpillStore(tmp)   # must not crash on the stray file
        assert 1 in st2

    def test_gc_epoch_advances_only_on_successful_save(self, rng):
        """A failed snapshot save (disk full) must NOT advance the
        spill GC epoch — otherwise deferred-dead segments the last
        GOOD snapshot still needs get unlinked under it."""
        tmp = tempfile.mkdtemp()
        kv = LargeScaleKV(dim=4, seed=1, resident_bytes=8 * 8,
                          spill_dir=tmp)
        kv.push(np.arange(32), rng.randn(32, 4).astype(np.float32))
        state = kv.export_state()  # snapshot ATTEMPT: no epoch tick
        kv.export_state()
        assert kv._spill._epoch == 0
        kv.gc_boundary()           # save succeeded: epoch advances
        assert kv._spill._epoch == 1
        # restart: the epoch is process-local — restoring FROM a
        # snapshot must re-arm deferral immediately, or a load in
        # the restart window would eagerly unlink a <=horizon
        # segment the retained snapshot still needs (double-crash
        # data loss)
        kv2 = LargeScaleKV(dim=4, seed=1, resident_bytes=8 * 8,
                           spill_dir=tmp)
        assert kv2._spill._epoch == 0
        kv2.import_state(state)
        assert kv2._spill._epoch >= 1

    def test_spill_store_restart_rescan(self, rng):
        """A fresh store over the same dir rebuilds the index
        (newest segment wins) and rows reload bit-equal."""
        tmp = tempfile.mkdtemp()
        st = RowSpillStore(tmp)
        r1 = {1: rng.randn(4).astype(np.float32),
              2: rng.randn(4).astype(np.float32)}
        st.spill(dict(r1))
        newer = {2: rng.randn(4).astype(np.float32)}
        st.spill(dict(newer))
        st2 = RowSpillStore(tmp)
        assert 1 in st2 and 2 in st2
        np.testing.assert_array_equal(st2.load(1)[0], r1[1])
        np.testing.assert_array_equal(st2.load(2)[0], newer[2])

    def test_prune_after_rolls_back_to_horizon(self, rng):
        """Roll back to a snapshot boundary: segments written AFTER
        the horizon are dropped and a row whose newest copy was
        post-boundary falls back to its pre-boundary segment — kept
        on disk by the deferred GC that boundary mode switches on."""
        tmp = tempfile.mkdtemp()
        st = RowSpillStore(tmp)
        st.spill({1: np.ones(4, np.float32)})
        h = st.horizon()
        st.on_boundary()   # the snapshot at ``h`` commits
        st.spill({1: np.full(4, 2.0, np.float32),
                  3: np.zeros(4, np.float32)})
        st.prune_after(h)
        assert 3 not in st
        np.testing.assert_array_equal(st.load(1)[0],
                                      np.ones(4, np.float32))

    def test_gc_unlinks_two_boundaries_after_death(self):
        tmp = tempfile.mkdtemp()
        st = RowSpillStore(tmp)
        st.on_boundary()
        seg1 = st.spill({1: np.ones(4, np.float32)})
        st.spill({1: np.zeros(4, np.float32)})   # supersedes seg1
        assert os.path.exists(st._path(seg1))    # deferred, on disk
        st.on_boundary()
        assert os.path.exists(st._path(seg1))    # 1 boundary: kept
        st.on_boundary()
        st.on_boundary()
        assert not os.path.exists(st._path(seg1))  # >=2: collected

    def test_export_import_state_round_trip(self, rng):
        tmp = tempfile.mkdtemp()
        kv = LargeScaleKV(dim=4, optimizer="adagrad", lr=0.3, seed=7,
                          resident_bytes=8 * 16, spill_dir=tmp)
        for _ in range(10):
            kv.push(rng.randint(0, 300, 32),
                    rng.randn(32, 4).astype(np.float32))
        probe = np.arange(300)
        expect = kv.pull(probe)   # before handing the dir to kv2
        state = kv.export_state()
        kv2 = LargeScaleKV(dim=4, optimizer="adagrad", lr=0.3, seed=7,
                           resident_bytes=8 * 16, spill_dir=tmp)
        kv2.import_state(state)
        np.testing.assert_array_equal(kv2.pull(probe), expect)


# ---------------------------------------------------------------------------
# q8 wire verbs + seq dedup
# ---------------------------------------------------------------------------

def _sparse_server(dim=32, lr=0.25, seed=11, n=1, **kv_kw):
    tables = [{"emb": LargeScaleKV(dim=dim, optimizer="sgd", lr=lr,
                                   seed=seed + i, **kv_kw)}
              for i in range(n)]
    servers = [SparsePServer("127.0.0.1:0", tb).start()
               for tb in tables]
    return servers, tables


class TestQ8Wire:
    def test_push_q8_applies_dequantized_rows(self, rng):
        servers, tables = _sparse_server()
        try:
            cl = LookupServiceClient("emb", [servers[0].endpoint],
                                     dim=32, trainer_id=0,
                                     push_q8=True)
            ids = np.arange(6)
            before = tables[0]["emb"].pull(ids)
            g = rng.randn(6, 32).astype(np.float32)
            cl.push(ids, g)
            after = tables[0]["emb"].pull(ids)
            q, s = quantize_rows_q8(g)   # residuals start at zero
            expect = before - 0.25 * dequantize_rows_q8(q, s)
            np.testing.assert_array_equal(after, expect)
            cl.close()
        finally:
            for s_ in servers:
                s_.shutdown()

    def test_pull_q8_bounded_error(self, rng):
        servers, tables = _sparse_server()
        try:
            cl = LookupServiceClient("emb", [servers[0].endpoint],
                                     dim=32, pull_q8=True)
            ids = np.arange(20)
            exact = tables[0]["emb"].pull(ids)
            got = cl.pull(ids)
            scale = np.max(np.abs(exact), axis=1) / 127.0
            assert (np.abs(got - exact)
                    <= scale[:, None] / 2 + 1e-7).all()
            cl.close()
        finally:
            for s_ in servers:
                s_.shutdown()

    def test_q8_replay_acks_without_reapply(self, rng):
        """Duplicate quantized PUSH_SPARSE under the PR 5 seq
        tracker: second copy acked, table untouched, dup event."""
        servers, tables = _sparse_server()
        try:
            cl = LookupServiceClient("emb", [servers[0].endpoint],
                                     dim=32, trainer_id=3,
                                     push_q8=True)
            ids = np.arange(5)
            cl.push(ids, rng.randn(5, 32).astype(np.float32))
            seq_used = cl._seqs[cl.clients[0].endpoint]
            q, s = quantize_rows_q8(np.ones((5, 32), np.float32))
            state = tables[0]["emb"].pull(ids)
            cl.clients[0].push_sparse_q8("emb", ids, q, s,
                                         seq=seq_used)  # replay
            np.testing.assert_array_equal(
                tables[0]["emb"].pull(ids), state)
            dups = [e for e in servers[0].serv.events
                    if e["kind"] == "dup_push_ignored"]
            assert len(dups) == 1 and dups[0]["tid"] == 3
            cl.close()
        finally:
            for s_ in servers:
                s_.shutdown()

    def test_error_feedback_telescopes(self, rng):
        """EF convergence (the collectives residual contract, on the
        wire): pushing the SAME grad K times applies a cumulative
        update within one quantization step of K*g per row — the
        compression error is carried, not accumulated."""
        servers, tables = _sparse_server(lr=1.0)
        try:
            cl = LookupServiceClient("emb", [servers[0].endpoint],
                                     dim=32, trainer_id=0,
                                     push_q8=True)
            ids = np.arange(4)
            g = (rng.randn(4, 32) * rng.lognormal(size=(4, 1))) \
                .astype(np.float32)
            start = tables[0]["emb"].pull(ids)
            K = 16
            for _ in range(K):
                cl.push(ids, g)
            applied = start - tables[0]["emb"].pull(ids)  # lr=1.0
            err = np.abs(applied - K * g)
            # telescope: total error == the LAST residual, bounded by
            # one step's quantization error, NOT K of them
            step_bound = np.max(np.abs(g), axis=1) / 127.0 * 1.5 \
                + 1e-6
            assert (err <= step_bound[:, None]).all()
            assert len(cl.residuals) == 4
            cl.close()
        finally:
            for s_ in servers:
                s_.shutdown()

    def test_small_dim_falls_back_exact(self):
        """Below SPARSE_Q8_MIN_DIM the q8 flags are inert: the scale
        overhead erodes the win and tiny rows are latency-bound."""
        assert SPARSE_Q8_MIN_DIM == 16
        servers, tables = _sparse_server(dim=8)
        try:
            cl = LookupServiceClient("emb", [servers[0].endpoint],
                                     dim=8, trainer_id=0,
                                     push_q8=True, pull_q8=True)
            assert not cl.push_q8 and not cl.pull_q8
            ids = np.arange(3)
            before = tables[0]["emb"].pull(ids)
            g = np.full((3, 8), 0.125, np.float32)
            cl.push(ids, g)   # exact fp32: bit-exact sgd, no residual
            np.testing.assert_array_equal(
                tables[0]["emb"].pull(ids), before - 0.25 * g)
            assert not cl.residuals
            cl.close()
        finally:
            for s_ in servers:
                s_.shutdown()


# ---------------------------------------------------------------------------
# cache x wire integration: mirror write-through, incarnation fence
# ---------------------------------------------------------------------------

class TestCacheIntegration:
    def test_mirror_sgd_keeps_cache_bit_equal_to_authority(self, rng):
        servers, tables = _sparse_server(n=2, lr=0.05)
        try:
            cl = LookupServiceClient(
                "emb", [s.endpoint for s in servers], dim=32,
                trainer_id=0, cache_bytes=1 << 20, push_q8=True,
                write_policy="mirror_sgd", mirror_lr=0.05)
            ids = rng.randint(0, 100, 200)
            cl.pull(ids)
            for _ in range(5):
                cl.push(ids, rng.randn(200, 32).astype(np.float32))
            uniq = np.unique(ids)
            shard = uniq % 2
            authority = np.zeros((len(uniq), 32), np.float32)
            for s_i in range(2):
                m = shard == s_i
                authority[m] = tables[s_i]["emb"].pull(uniq[m])
            hits_before = cl.cache.hits
            cached = cl.pull(uniq)
            assert cl.cache.hits - hits_before == len(uniq)
            np.testing.assert_array_equal(cached, authority)
            cl.close()
        finally:
            for s_ in servers:
                s_.shutdown()

    def test_partial_push_failure_invalidates_touched_rows(self, rng):
        """A push that fails on shard 1 after shard 0 applied must
        drop the touched rows from the hot tier — the write-policy
        block never ran, so a surviving mirror image would serve the
        pre-push value as a hit forever."""
        from paddle_tpu.distributed.rpc import RpcError
        servers, tables = _sparse_server(n=2, lr=0.5)
        try:
            cl = LookupServiceClient(
                "emb", [s.endpoint for s in servers], dim=32,
                trainer_id=0, cache_bytes=1 << 20, deadline_s=1.0,
                write_policy="mirror_sgd", mirror_lr=0.5)
            ids = np.arange(8)          # both shards touched
            cl.pull(ids)
            servers[1].shutdown()       # shard 1 down, hard
            with pytest.raises(Exception):
                cl.push(ids, np.ones((8, 32), np.float32))
            # shard-0 rows applied server-side; the cache must NOT
            # serve any touched row as a (stale) hit now
            _, hit = cl.cache.get_many(ids)
            assert not hit.any()
            even = ids[ids % 2 == 0]    # shard-0 rows
            np.testing.assert_array_equal(cl.pull(even),
                                          tables[0]["emb"].pull(even))
            cl.close()
        finally:
            for s_ in servers:
                try:
                    s_.shutdown()
                except Exception:
                    pass

    def test_mirror_sgd_with_cache_requires_mirror_lr(self):
        """A cache armed with the default mirror_sgd policy but no
        mirror_lr would silently never write through NOR invalidate —
        stale rows with no error. The constructor refuses it."""
        from paddle_tpu.core.enforce import EnforceNotMet
        servers, _ = _sparse_server()
        try:
            with pytest.raises(EnforceNotMet, match="mirror_lr"):
                LookupServiceClient("emb", [servers[0].endpoint],
                                    dim=32, cache_bytes=1 << 20)
        finally:
            for s_ in servers:
                s_.shutdown()

    def test_invalidate_policy_drops_pushed_rows(self, rng):
        servers, _tables = _sparse_server()
        try:
            cl = LookupServiceClient("emb", [servers[0].endpoint],
                                     dim=32, trainer_id=0,
                                     cache_bytes=1 << 20,
                                     write_policy="invalidate")
            ids = np.arange(10)
            cl.pull(ids)
            cl.push(ids[:4], np.ones((4, 32), np.float32))
            _, hit = cl.cache.get_many(ids)
            assert list(hit) == [False] * 4 + [True] * 6
            cl.close()
        finally:
            for s_ in servers:
                s_.shutdown()

    def test_restart_invalidates_hot_tier_exactly_once(self, rng):
        """PR 5 __incarnation__ as the hot-tier invalidation signal:
        kill + restart the pserver (same port, durable snapshot) ->
        the NEXT wire round reconnects, re-reads the nonce, drops the
        cache EXACTLY once, and no stale row is served."""
        from paddle_tpu import observability as obs
        from paddle_tpu.resilience.retry import RetryPolicy
        snap = tempfile.mkdtemp()
        table = {"emb": LargeScaleKV(dim=32, optimizer="sgd", lr=0.5,
                                     seed=2)}
        srv = SparsePServer("127.0.0.1:0", table,
                            snapshot_dir=snap).start()
        port = srv.serv.server.port
        cl = LookupServiceClient("emb", [srv.endpoint], dim=32,
                                 trainer_id=0, cache_bytes=1 << 20,
                                 push_q8=True,
                                 write_policy="mirror_sgd",
                                 mirror_lr=0.5,
                                 retry=RetryPolicy(max_retries=6,
                                                   base_delay=0.05,
                                                   max_delay=0.4,
                                                   seed=1))
        try:
            ids = np.arange(50)
            cl.pull(ids)
            cl.push(ids, rng.randn(50, 32).astype(np.float32))
            srv.shutdown()
            table2 = {"emb": LargeScaleKV(dim=32, optimizer="sgd",
                                          lr=0.5, seed=2)}
            srv = SparsePServer("127.0.0.1:%d" % port, table2,
                                snapshot_dir=snap).start()
            mark = (obs.journal_events()[-1]["seq"]
                    if obs.journal_events() else 0)
            cl.push(ids, rng.randn(50, 32).astype(np.float32))
            assert cl.invalidation_count == 1
            # post-restart pull re-reads THROUGH the restored server
            np.testing.assert_array_equal(cl.pull(ids),
                                          table2["emb"].pull(ids))
            # steady state: further rounds do NOT re-invalidate
            cl.push(ids, rng.randn(50, 32).astype(np.float32))
            cl.pull(ids)
            assert cl.invalidation_count == 1
            evs = [e for e in obs.journal_events(since_seq=mark)
                   if e["kind"] == "sparse_cache_invalidated"]
            assert len(evs) == 1 and evs[0]["table"] == "emb"
        finally:
            srv.shutdown()
            cl.close()

    def test_residuals_survive_restart(self, rng):
        """'Loses no trainer-side residuals': the EF residual map is
        trainer state; a pserver restart must leave it untouched."""
        from paddle_tpu.resilience.retry import RetryPolicy
        snap = tempfile.mkdtemp()
        table = {"emb": LargeScaleKV(dim=32, lr=0.5, seed=2)}
        srv = SparsePServer("127.0.0.1:0", table,
                            snapshot_dir=snap).start()
        port = srv.serv.server.port
        cl = LookupServiceClient("emb", [srv.endpoint], dim=32,
                                 trainer_id=0, cache_bytes=1 << 20,
                                 push_q8=True,
                                 write_policy="invalidate",
                                 retry=RetryPolicy(max_retries=6,
                                                   base_delay=0.05,
                                                   max_delay=0.4,
                                                   seed=1))
        try:
            ids = np.arange(8)
            cl.push(ids, rng.randn(8, 32).astype(np.float32))
            saved = {k: v.copy() for k, v in cl.residuals.items()}
            assert saved
            srv.shutdown()
            srv = SparsePServer(
                "127.0.0.1:%d" % port,
                {"emb": LargeScaleKV(dim=32, lr=0.5, seed=2)},
                snapshot_dir=snap).start()
            cl.pull(ids)   # reconnect + fence
            assert cl.invalidation_count == 1
            assert set(cl.residuals) == set(saved)
            for k in saved:
                np.testing.assert_array_equal(cl.residuals[k],
                                              saved[k])
        finally:
            srv.shutdown()
            cl.close()


# ---------------------------------------------------------------------------
# SparsePServer snapshot/restore (push seqs + table state)
# ---------------------------------------------------------------------------

class TestSparseSnapshot:
    def test_restore_is_bit_exact_and_tracker_restored(self, rng):
        snap = tempfile.mkdtemp()
        kv = LargeScaleKV(dim=16, optimizer="adagrad", lr=0.2, seed=4)
        srv = SparsePServer("127.0.0.1:0", {"emb": kv},
                            snapshot_dir=snap, snapshot_every=1)
        srv.start()
        cl = LookupServiceClient("emb", [srv.endpoint], dim=16,
                                 trainer_id=1)
        ids = np.arange(30)
        for _ in range(3):
            cl.push(ids, rng.randn(30, 16).astype(np.float32))
        state = kv.pull(ids)
        used_seq = cl._seqs[cl.clients[0].endpoint]
        srv.shutdown()

        kv2 = LargeScaleKV(dim=16, optimizer="adagrad", lr=0.2,
                           seed=4)
        srv2 = SparsePServer("127.0.0.1:0", {"emb": kv2},
                             snapshot_dir=snap, snapshot_every=1)
        srv2.start()
        np.testing.assert_array_equal(kv2.pull(ids), state)
        # restored push-seq tracker: a replay of the last applied
        # push must ack-without-reapply on the NEW incarnation
        cl2 = LookupServiceClient("emb", [srv2.endpoint], dim=16,
                                  trainer_id=1)
        cl2.clients[0].push_sparse("emb", ids,
                                   np.ones((30, 16), np.float32),
                                   seq=used_seq)
        np.testing.assert_array_equal(kv2.pull(ids), state)
        dups = [e for e in srv2.serv.events
                if e["kind"] == "dup_push_ignored"]
        assert len(dups) == 1
        cl.close()
        cl2.close()
        srv2.shutdown()

    def test_spill_dir_survives_restart_with_snapshot(self, rng):
        """Tier 2 x restart: rows beyond the resident budget live in
        spill segments; a restart restores resident rows from the
        snapshot and re-scans (<= horizon) segments — every row
        bit-equal to the pre-kill table."""
        snap = tempfile.mkdtemp()
        spill = tempfile.mkdtemp()

        def make_kv(spill_dir):
            return LargeScaleKV(dim=8, optimizer="sgd", lr=0.1,
                                seed=6, resident_bytes=32 * 24,
                                spill_dir=spill_dir)

        kv = make_kv(spill)
        srv = SparsePServer("127.0.0.1:0", {"emb": kv},
                            snapshot_dir=snap, snapshot_every=1)
        srv.start()
        cl = LookupServiceClient("emb", [srv.endpoint], dim=8,
                                 trainer_id=0)
        for _ in range(6):
            cl.push(rng.randint(0, 500, 64),
                    rng.randn(64, 8).astype(np.float32))
        probe = np.arange(500)
        state = kv.pull(probe)
        assert kv.stats()["spilled_rows"] > 0
        srv.shutdown()

        kv2 = make_kv(spill)
        srv2 = SparsePServer("127.0.0.1:0", {"emb": kv2},
                             snapshot_dir=snap, snapshot_every=1)
        srv2.start()
        np.testing.assert_array_equal(kv2.pull(probe), state)
        cl.close()
        srv2.shutdown()


# ---------------------------------------------------------------------------
# end-to-end: the training loop through the tiers
# ---------------------------------------------------------------------------

def _ctr_model(vocab, dim, padding_idx=None):
    from paddle_tpu.param_attr import ParamAttr
    fluid.framework._reset_default_programs()
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = 9
    with fluid.program_guard(main, startup):
        ids = layers.data(name="ids", shape=[6], dtype="int64")
        label = layers.data(name="label", shape=[1], dtype="float32")
        emb = layers.embedding(ids, size=[vocab, dim],
                               is_distributed=True,
                               padding_idx=padding_idx,
                               param_attr=ParamAttr(name="ctr_w"))
        flat = layers.reshape(emb, shape=[-1, 6 * dim])
        h = layers.fc(flat, size=16, act="relu")
        logit = layers.fc(h, size=1)
        loss = layers.mean(
            layers.sigmoid_cross_entropy_with_logits(logit, label))
        fluid.optimizer.SGDOptimizer(0.1).minimize(loss)
    return main, startup, loss


class TestRuntimeEndToEnd:
    def _train(self, tier, steps=8, vocab=5000, dim=32,
               padding_idx=None, seed=0):
        with fluid.unique_name.guard():
            main, startup, loss = _ctr_model(vocab, dim, padding_idx)
            servers, tables = [], []
            for i in range(2):
                kv = LargeScaleKV(dim=dim, optimizer="sgd", lr=0.1,
                                  seed=2 + i)
                tables.append(kv)
                servers.append(SparsePServer(
                    "127.0.0.1:0", {"ctr_w": kv}).start())
            try:
                srt = SparseEmbeddingRuntime(
                    main, [s.endpoint for s in servers], tier=tier)
                scope = fluid.Scope()
                losses = []
                with fluid.scope_guard(scope):
                    exe = fluid.Executor()
                    exe.run(startup)
                    r = np.random.RandomState(seed)
                    ids = r.randint(0, vocab, (32, 6))
                    lbl = (ids.sum(1) % 2).reshape(-1, 1) \
                        .astype(np.float32)
                    feed0 = {"ids": ids.astype(np.int64),
                             "label": lbl}
                    for _ in range(steps):
                        feed = srt.wrap_feed(feed0)
                        out = exe.run(main, feed=feed,
                                      fetch_list=[loss]
                                      + srt.grad_fetch_names())
                        losses.append(float(
                            np.asarray(out[0]).reshape(-1)[0]))
                        srt.push_grads(feed, out[1:])
                stats = srt.stats()
                srt.close()
                return losses, stats, tables
            finally:
                for s in servers:
                    s.shutdown()

    def test_q8_cache_trajectory_within_rtol_of_exact(self):
        """The DeepFM-style acceptance: q8 push + hot cache (mirror
        write-through) must track the exact/uncached twin's loss
        trajectory within rtol — the EF telescope and the bit-equal
        mirror keep the approximation bounded."""
        exact, _, _ = self._train(SparseTierConfig())
        q8c, stats, _ = self._train(SparseTierConfig(
            cache_bytes=1 << 22, push_q8=True,
            write_policy="mirror_sgd", mirror_lr=0.1, trainer_id=0))
        np.testing.assert_allclose(q8c, exact, rtol=2e-3)
        st = stats["ctr_w"]
        assert st["push_q8"] and st["cache"]["hits"] > 0
        assert st["wire_bytes"]["total"] > 0

    def test_param_attr_str_pins_table_name(self):
        fluid.framework._reset_default_programs()
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            ids = layers.data(name="ids", shape=[4], dtype="int64")
            layers.embedding(ids, size=[100, 16],
                             is_distributed=True,
                             param_attr="pinned_tbl")
        assert main._distributed_lookups[0]["table"] == "pinned_tbl"

    def test_padding_idx_rows_zero_and_unpushed(self):
        """Distributed twin of the lookup_table padding contract:
        padding rows read as zeros and receive no sparse grad."""
        tier = SparseTierConfig(trainer_id=0)
        with fluid.unique_name.guard():
            main, startup, loss = _ctr_model(50, 16, padding_idx=0)
            kv = LargeScaleKV(dim=16, optimizer="sgd", lr=0.1, seed=1)
            srv = SparsePServer("127.0.0.1:0", {"ctr_w": kv}).start()
            try:
                srt = SparseEmbeddingRuntime(main, [srv.endpoint],
                                             tier=tier)
                row0 = kv.pull([0])[0].copy()
                scope = fluid.Scope()
                with fluid.scope_guard(scope):
                    exe = fluid.Executor()
                    exe.run(startup)
                    ids = np.array([[0, 0, 1, 2, 3, 4]] * 4,
                                   np.int64)
                    feed0 = {"ids": ids,
                             "label": np.ones((4, 1), np.float32)}
                    feed = srt.wrap_feed(feed0)
                    pad_vecs = feed[srt.lookups[0]["out"]][ids == 0]
                    assert (pad_vecs == 0.0).all()
                    out = exe.run(main, feed=feed,
                                  fetch_list=[loss]
                                  + srt.grad_fetch_names())
                    srt.push_grads(feed, out[1:])
                # padding row untouched on the server, others moved
                np.testing.assert_array_equal(kv.pull([0])[0], row0)
                assert not np.array_equal(kv.pull([1])[0],
                                          LargeScaleKV(
                                              dim=16, seed=1)
                                          .pull([1])[0])
                srt.close()
            finally:
                srv.shutdown()


# ---------------------------------------------------------------------------
# chaos: the sparse_restart scenario inside tier-1
# ---------------------------------------------------------------------------

@pytest.mark.chaos
def test_sparse_restart_scenario_green_and_diagnosed():
    """Run the real chaos scenario (kill mid-PUSH_SPARSE_Q8, restart
    from the durable snapshot on the same port): rows bit-equal to
    the fault-free twin, pulls stale-free, residuals preserved,
    exactly one hot-tier invalidation, dup replay ack-without-reapply
    — and doctor NAMES pserver_restart from the journal alone."""
    import argparse
    import sys as _sys
    TOOLS = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "tools")
    if TOOLS not in _sys.path:
        _sys.path.insert(0, TOOLS)
    import chaos_run
    res = chaos_run._scenario_sparse_restart(
        argparse.Namespace(seed=0, steps=6))
    assert res["ok"], res
    assert res["rows_bit_equal"] and res["pulls_stale_free"], res
    assert res["residuals_preserved"], res
    assert res["hot_tier_invalidations"] == 1, res
    assert res["dup_push_ack_without_reapply"], res
    doc = res["doctor"]
    assert doc["top"] == "pserver_restart" and doc["match"], doc
