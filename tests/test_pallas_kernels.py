"""Pallas kernel variants vs reference lowerings (the operators/jit
test pattern, jit/test.cc: every hand-written kernel must match its
refer impl; run in interpret mode on CPU, compiled on TPU)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from paddle_tpu import ops
from paddle_tpu.core.flags import FLAGS

import paddle_tpu as fluid
from paddle_tpu import layers


def _cmp(op_type, args, kwargs, rtol=2e-5, atol=2e-6):
    opdef = ops.get(op_type)
    ref = opdef.fn(*args, **kwargs)
    pal = opdef.variants["pallas"](*args, **kwargs)
    ref_flat = jax.tree_util.tree_leaves(ref)
    pal_flat = jax.tree_util.tree_leaves(pal)
    assert len(ref_flat) == len(pal_flat)
    for r, p in zip(ref_flat, pal_flat):
        np.testing.assert_allclose(np.asarray(p), np.asarray(r),
                                   rtol=rtol, atol=atol)


def test_sdpa_matches_reference():
    r = np.random.RandomState(0)
    B, H, Sq, Sk, Dh = 2, 4, 16, 24, 8
    q = jnp.asarray(r.randn(B, H, Sq, Dh).astype(np.float32))
    k = jnp.asarray(r.randn(B, H, Sk, Dh).astype(np.float32))
    v = jnp.asarray(r.randn(B, H, Sk, Dh).astype(np.float32))
    bias = jnp.asarray(
        np.where(r.rand(B, 1, Sq, Sk) > 0.2, 0.0, -1e9)
        .astype(np.float32))
    _cmp("scaled_dot_product_attention", (q, k, v, bias),
         {"scale": Dh ** -0.5})
    _cmp("scaled_dot_product_attention", (q, k, v, None),
         {"scale": Dh ** -0.5})


def test_sdpa_gradients_match():
    r = np.random.RandomState(1)
    B, H, S, Dh = 1, 2, 8, 4
    q = jnp.asarray(r.randn(B, H, S, Dh).astype(np.float32))
    k = jnp.asarray(r.randn(B, H, S, Dh).astype(np.float32))
    v = jnp.asarray(r.randn(B, H, S, Dh).astype(np.float32))
    opdef = ops.get("scaled_dot_product_attention")

    def loss_ref(q_, k_, v_):
        return jnp.sum(jnp.square(opdef.fn(q_, k_, v_, None,
                                           scale=0.5)))

    def loss_pal(q_, k_, v_):
        return jnp.sum(jnp.square(
            opdef.variants["pallas"](q_, k_, v_, None, scale=0.5)))

    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    gp = jax.grad(loss_pal, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gr, gp):
        np.testing.assert_allclose(np.asarray(b), np.asarray(a),
                                   rtol=2e-5, atol=2e-6)


def test_sdpa_causal_matches_reference():
    r = np.random.RandomState(7)
    B, H, S, Dh = 1, 2, 64, 16
    q = jnp.asarray(r.randn(B, H, S, Dh).astype(np.float32))
    k = jnp.asarray(r.randn(B, H, S, Dh).astype(np.float32))
    v = jnp.asarray(r.randn(B, H, S, Dh).astype(np.float32))
    bias = jnp.asarray(
        np.where(r.rand(B, 1, 1, S) > 0.15, 0.0, -1e9)
        .astype(np.float32))
    bias = jnp.broadcast_to(bias, (B, 1, S, S))
    _cmp("scaled_dot_product_attention", (q, k, v, bias),
         {"scale": Dh ** -0.5, "causal": True})
    _cmp("scaled_dot_product_attention", (q, k, v, None),
         {"scale": Dh ** -0.5, "causal": True})


def test_sdpa_flash_blocked_multi_q_causal():
    """Multiple q-blocks AND k-blocks with causal masking — the
    longseq bench geometry (S=1024): exercises the dk/dv kernel's
    q-block accumulation and the causal block-skip logic, fwd +
    grads."""
    r = np.random.RandomState(9)
    B, H, S, Dh = 1, 2, 1024, 32
    q = jnp.asarray(r.randn(B, H, S, Dh).astype(np.float32))
    k = jnp.asarray(r.randn(B, H, S, Dh).astype(np.float32))
    v = jnp.asarray(r.randn(B, H, S, Dh).astype(np.float32))
    opdef = ops.get("scaled_dot_product_attention")
    _cmp("scaled_dot_product_attention", (q, k, v, None),
         {"scale": Dh ** -0.5, "causal": True}, rtol=5e-5, atol=1e-5)

    def loss(fn):
        return lambda q_, k_, v_: jnp.sum(jnp.square(
            fn(q_, k_, v_, None, scale=Dh ** -0.5, causal=True)))

    gr = jax.grad(loss(opdef.fn), (0, 1, 2))(q, k, v)
    gp = jax.grad(loss(opdef.variants["pallas"]), (0, 1, 2))(q, k, v)
    for a, b in zip(gr, gp):
        np.testing.assert_allclose(np.asarray(b), np.asarray(a),
                                   rtol=5e-4, atol=5e-5)


def test_sdpa_flash_blocked_shapes():
    """Shapes that force multiple k-blocks through the online-softmax
    path (Sk > blk_k), fwd + grads — the flash recurrence itself."""
    r = np.random.RandomState(8)
    B, H, Sq, Sk, Dh = 1, 1, 256, 1024, 32
    q = jnp.asarray(r.randn(B, H, Sq, Dh).astype(np.float32))
    k = jnp.asarray(r.randn(B, H, Sk, Dh).astype(np.float32))
    v = jnp.asarray(r.randn(B, H, Sk, Dh).astype(np.float32))
    bias = jnp.asarray(
        np.where(r.rand(B, 1, 1, Sk) > 0.1, 0.0, -1e9)
        .astype(np.float32))
    bias = jnp.broadcast_to(bias, (B, 1, Sq, Sk))
    opdef = ops.get("scaled_dot_product_attention")
    _cmp("scaled_dot_product_attention", (q, k, v, bias),
         {"scale": Dh ** -0.5}, rtol=5e-5, atol=1e-5)

    def loss(fn):
        return lambda q_, k_, v_: jnp.sum(jnp.square(
            fn(q_, k_, v_, bias, scale=Dh ** -0.5)))

    gr = jax.grad(loss(opdef.fn), (0, 1, 2))(q, k, v)
    gp = jax.grad(loss(opdef.variants["pallas"]), (0, 1, 2))(q, k, v)
    for a, b in zip(gr, gp):
        np.testing.assert_allclose(np.asarray(b), np.asarray(a),
                                   rtol=5e-4, atol=5e-5)


def test_layer_norm_matches_reference():
    r = np.random.RandomState(2)
    x = jnp.asarray(r.randn(6, 4, 32).astype(np.float32))
    scale = jnp.asarray(r.rand(4 * 32).astype(np.float32) + 0.5)
    bias = jnp.asarray(r.randn(4 * 32).astype(np.float32))
    _cmp("layer_norm", (x, scale, bias),
         {"epsilon": 1e-5, "begin_norm_axis": 1}, rtol=1e-4)
    x2 = jnp.asarray(r.randn(3, 8, 64).astype(np.float32))
    s2 = jnp.asarray(r.rand(64).astype(np.float32) + 0.5)
    _cmp("layer_norm", (x2, s2, None),
         {"epsilon": 1e-5, "begin_norm_axis": 2}, rtol=1e-4)


def test_softmax_xent_matches_reference():
    r = np.random.RandomState(3)
    logits = jnp.asarray(r.randn(32, 10).astype(np.float32))
    label = jnp.asarray(r.randint(0, 10, (32, 1)).astype(np.int64))
    _cmp("softmax_with_cross_entropy", (logits, label), {}, rtol=1e-5)
    # gradient parity
    opdef = ops.get("softmax_with_cross_entropy")
    gr = jax.grad(lambda lg: jnp.sum(opdef.fn(lg, label)[1]))(logits)
    gp = jax.grad(lambda lg: jnp.sum(
        opdef.variants["pallas"](lg, label)[1]))(logits)
    np.testing.assert_allclose(np.asarray(gp), np.asarray(gr),
                               rtol=2e-5, atol=1e-6)


def test_fused_adam_matches_reference():
    r = np.random.RandomState(4)
    shape = (37, 13)  # deliberately lane-unaligned
    p = jnp.asarray(r.randn(*shape).astype(np.float32))
    g = jnp.asarray(r.randn(*shape).astype(np.float32))
    m1 = jnp.asarray(r.randn(*shape).astype(np.float32) * 0.1)
    m2 = jnp.asarray(np.abs(r.randn(*shape)).astype(np.float32) * 0.1)
    args = (p, g, m1, m2, jnp.float32(0.9), jnp.float32(0.999),
            jnp.float32(1e-3))
    _cmp("adam", args, {"beta1": 0.9, "beta2": 0.999, "epsilon": 1e-8},
         rtol=1e-6)


# tier-1 headroom (PR 17): ~26 s train-through-library twin -> slow;
# the pallas kernel surface stays via the sdpa flash/blocked tests
# and the smaller train smokes in this file
@pytest.mark.slow
def test_transformer_trains_with_pallas_library():
    """End-to-end: transformer eval/train step under
    FLAGS_op_library=pallas matches the default path."""
    from paddle_tpu.models import transformer as T

    def run(lib):
        fluid.framework._reset_default_programs()
        cfg = T.TransformerConfig(src_vocab=50, tgt_vocab=50,
                                  max_len=16, d_model=32, d_ffn=64,
                                  n_head=4, n_layer=1, dropout=0.0)
        main, startup = fluid.Program(), fluid.Program()
        main.random_seed = startup.random_seed = 11
        with fluid.program_guard(main, startup):
            avg_cost, token_num, logits = T.transformer(cfg,
                                                        is_test=False)
            fluid.optimizer.SGD(0.1).minimize(avg_cost)
        exe = fluid.Executor()
        scope = fluid.Scope()
        feed = T.make_fake_batch(cfg, 4)
        with fluid.scope_guard(scope):
            exe.run(startup)
            old = FLAGS.op_library
            FLAGS.op_library = lib
            try:
                losses = []
                for _ in range(3):
                    (lv,) = exe.run(main, feed=feed,
                                    fetch_list=[avg_cost])
                    losses.append(float(lv))
            finally:
                FLAGS.op_library = old
        return losses

    base = run("")
    pal = run("pallas")
    np.testing.assert_allclose(pal, base, rtol=5e-4, atol=1e-5)


def test_sdpa_per_head_bias_matches_reference(rng):
    """A per-HEAD bias [B, H, Sq, Sk] must work on the pallas path and
    match the base lowering (the two library paths used to diverge:
    pallas only accepted [B, 1, Sq, Sk])."""
    import jax
    import jax.numpy as jnp
    from paddle_tpu.ops.pallas import attention as A

    B, H, Sq, Sk, Dh = 1, 2, 128, 128, 64
    q = jnp.asarray(rng.randn(B, H, Sq, Dh).astype(np.float32)) * 0.3
    k = jnp.asarray(rng.randn(B, H, Sk, Dh).astype(np.float32)) * 0.3
    v = jnp.asarray(rng.randn(B, H, Sk, Dh).astype(np.float32)) * 0.3
    # distinct mask per head
    bias = np.zeros((B, H, Sq, Sk), np.float32)
    bias[:, 0, :, Sk // 2:] = -1e9
    bias[:, 1, :, :Sk // 4] = -1e9
    bias = jnp.asarray(bias)

    want = A._sdpa_reference(q, k, v, bias, scale=0.5)
    got = A.sdpa_pallas(q, k, v, bias, scale=0.5, is_test=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               atol=2e-3, rtol=2e-3)

    # gradients agree too (dq/dk/dv recompute path reads the bias)
    def ref_loss(a, b, c):
        return jnp.sum(A._sdpa_reference(a, b, c, bias,
                                         scale=0.5) ** 2)

    def pl_loss(a, b, c):
        return jnp.sum(A.sdpa_pallas(a, b, c, bias, scale=0.5,
                                     is_test=True) ** 2)

    gw = jax.grad(ref_loss, argnums=(0, 1, 2))(q, k, v)
    gg = jax.grad(pl_loss, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gg, gw):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=5e-3, rtol=5e-3)


def test_fused_linear_xent_matches_reference():
    """Streaming fused projection+xent kernel vs the composite lowering
    — forward and both gradients, hard labels and label smoothing,
    including a non-128-multiple vocab (masked padded tail)."""
    r = np.random.RandomState(5)
    N, D, V = 48, 16, 300
    x = jnp.asarray(r.randn(N, D).astype(np.float32)) * 0.5
    w = jnp.asarray(r.randn(D, V).astype(np.float32)) * 0.2
    lab = jnp.asarray(r.randint(0, V, size=(N, 1)).astype(np.int64))
    g = jnp.asarray(r.rand(N, 1).astype(np.float32))
    opdef = ops.get("fused_linear_xent")
    for eps in (0.0, 0.1):
        _cmp("fused_linear_xent", (x, w, lab), {"epsilon": eps},
             rtol=2e-5, atol=2e-5)
        dref = jax.grad(lambda a, b: jnp.sum(
            opdef.fn(a, b, lab, epsilon=eps) * g), argnums=(0, 1))(x, w)
        dpal = jax.grad(lambda a, b: jnp.sum(
            opdef.variants["pallas"](a, b, lab, epsilon=eps) * g),
            argnums=(0, 1))(x, w)
        for dr, dp in zip(dref, dpal):
            np.testing.assert_allclose(np.asarray(dp), np.asarray(dr),
                                       rtol=2e-4, atol=2e-5)


def test_fused_linear_xent_3d_and_bf16():
    """Leading dims flatten correctly; bf16 inputs keep f32 statistics
    (the AMP path: white-listed op, loss must stay finite/accurate)."""
    r = np.random.RandomState(6)
    B, S, D, V = 3, 8, 16, 130
    x = jnp.asarray(r.randn(B, S, D).astype(np.float32))
    w = jnp.asarray(r.randn(D, V).astype(np.float32)) * 0.3
    lab = jnp.asarray(r.randint(0, V, size=(B, S, 1)).astype(np.int64))
    opdef = ops.get("fused_linear_xent")
    ref = opdef.fn(x, w, lab, epsilon=0.1)
    pal = opdef.variants["pallas"](x, w, lab, epsilon=0.1)
    assert pal.shape == (B, S, 1) and pal.dtype == jnp.float32
    np.testing.assert_allclose(np.asarray(pal), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)
    xb, wb = x.astype(jnp.bfloat16), w.astype(jnp.bfloat16)
    palb = opdef.variants["pallas"](xb, wb, lab, epsilon=0.1)
    assert palb.dtype == jnp.float32
    np.testing.assert_allclose(np.asarray(palb), np.asarray(ref),
                               rtol=0.05, atol=0.05)


def test_attention_dropout_grouping_consistent():
    """The dropout mask is seeded per grid CELL, so forward and
    backward must group (batch, head) rows into cells identically
    whenever dropout is on (round-4 review: a fwd G=8 / bwd G=4 split
    at f32 regenerated different masks for heads the groupings
    assigned to different cells — silently wrong gradients)."""
    from paddle_tpu.ops.pallas.attention import _bwd_G, _pick_G

    for H in (1, 2, 4, 8, 16):
        for itemsize in (2, 4):
            for rate in (0.0, 0.1, 0.5):
                fwd_G = _pick_G(H, itemsize, rate)
                bwd_G = _bwd_G(H, itemsize)
                if rate > 0.0:
                    assert fwd_G == bwd_G, (H, itemsize, rate)
                # and the backward grouping always fits scoped VMEM
                assert bwd_G <= (8 if itemsize <= 2 else 4)


def test_sdpa_auto_flash_dispatch_envelope(monkeypatch):
    """FLAGS_sdpa_auto_flash routes the BASE lowering to the flash
    kernel exactly inside the chip-measured win envelope: TPU
    execution, <=2-byte dtype, dropout active, single-k-block shapes.
    Everything else (f32, no dropout, long sequences, interpret mode)
    keeps the XLA chain."""
    import jax
    import jax.numpy as jnp

    from paddle_tpu.core.flags import FLAGS
    from paddle_tpu.ops.pallas import attention as A

    calls = []
    monkeypatch.setattr(A, "interpret_mode", lambda: False)
    monkeypatch.setattr(
        A, "sdpa_pallas",
        lambda q, k, v, b, **kw: calls.append("flash") or q)
    rng = jax.random.key(0)

    def run(S=256, dtype=jnp.bfloat16, rate=0.1, auto=True):
        calls.clear()
        prev = FLAGS.sdpa_auto_flash
        FLAGS.sdpa_auto_flash = auto
        try:
            # non-degenerate inputs: BOTH paths must run clean — a
            # crash in either is a real failure (ADVICE r4: a blanket
            # except here swallowed the dispatched path's errors too)
            q = jnp.full((2, 4, S, 64), 0.1, dtype)
            A.scaled_dot_product_attention(
                q, q, q, None, scale=0.125, dropout_rate=rate,
                rng=rng)
        finally:
            FLAGS.sdpa_auto_flash = prev
        return calls == ["flash"]

    assert run()                              # envelope: dispatches
    assert not run(dtype=jnp.float32)         # f32: stays XLA
    assert not run(rate=0.0)                  # no dropout: stays XLA
    assert not run(S=1024)                    # blocked shapes: XLA
    assert not run(auto=False)                # flag off: stays XLA
