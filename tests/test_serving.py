"""Serving-engine tests: micro-batching correctness (bit-equal to the
unbatched predictor under concurrent ragged traffic), bounded compiles
via shape buckets, admission control, deadlines, graceful drain, chaos
(batcher death fails futures with a structured error instead of
hanging), clone first-compile race, and the signature sidecar."""

import os
import threading
import time

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import layers
from paddle_tpu.inference import AnalysisConfig, AnalysisPredictor
from paddle_tpu.serving import (BatcherDied, DeadlineExceeded,
                                EngineStopped, InvalidRequest,
                                ServerOverloaded, ServingConfig,
                                ServingEngine, bucket_for, bucket_sizes)

pytestmark = pytest.mark.serving


def _save_mlp_model(tmp_path, seed=7, in_dim=16, out_dim=4):
    """Tiny MLP inference model on disk; returns its dir."""
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = seed
    with fluid.program_guard(main, startup):
        x = layers.data(name="x", shape=[in_dim], dtype="float32")
        h = layers.fc(x, size=32, act="relu")
        pred = layers.fc(h, size=out_dim, act="softmax")
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe = fluid.Executor()
        exe.run(startup)
        d = str(tmp_path / "model")
        fluid.io.save_inference_model(d, ["x"], [pred], exe,
                                      main_program=main, scope=scope)
    return d


@pytest.fixture(scope="module")
def model_dir(tmp_path_factory):
    """ONE saved model shared by the whole module (read-only for every
    consumer) — rebuilding/saving it per test dominated the suite's
    runtime without adding coverage."""
    return _save_mlp_model(tmp_path_factory.mktemp("serving"))


def _engine(model_dir, **kw):
    kw.setdefault("max_batch_size", 16)
    kw.setdefault("max_queue_wait_us", 2000)
    return ServingEngine(model_dir, ServingConfig(**kw))


class TestBuckets:
    def test_bucket_math(self):
        assert bucket_sizes(64) == [1, 2, 4, 8, 16, 32, 64]
        assert bucket_sizes(1) == [1]
        assert bucket_sizes(48) == [1, 2, 4, 8, 16, 32, 48]
        sizes = bucket_sizes(64)
        assert bucket_for(1, sizes) == 1
        assert bucket_for(3, sizes) == 4
        assert bucket_for(64, sizes) == 64
        with pytest.raises(Exception):
            bucket_for(65, sizes)


class TestEngineCorrectness:
    def test_bit_equal_concurrent_ragged(self, model_dir):
        """The acceptance criterion: engine results bit-equal to the
        unbatched AnalysisPredictor.predict for EVERY request under 8
        concurrent client threads with ragged batch sizes.

        The bit-equal reference is a single-request predict of the
        SAME rows at the request's executed device shape (the bucket
        the Future reports) — proving coalescing, padding, offsets,
        and split/unpad are lossless with zero cross-request
        contamination. Against the NATIVE-shape predict the match is
        allclose-tight but not always bitwise: XLA CPU lowers an M=1
        matmul to a gemv whose accumulation order differs ~1 ulp from
        the same row inside a larger batch — executable-selection
        reassociation no serving layer controls (docs/serving.md)."""
        from paddle_tpu.serving import pad_batch

        d = model_dir
        reference = AnalysisPredictor(AnalysisConfig(d))
        engine = _engine(d)
        results = []
        lock = threading.Lock()

        def client(seed):
            r = np.random.RandomState(seed)
            for _ in range(5):
                n = int(r.randint(1, 10))
                feed = {"x": r.rand(n, 16).astype(np.float32)}
                fut = engine.infer(feed)
                out = fut.result(timeout=60)
                with lock:
                    results.append((feed, out, fut.bucket))

        threads = [threading.Thread(target=client, args=(i,))
                   for i in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert len(results) == 40
        buckets_seen = set()
        for feed, out, bucket in results:
            n = feed["x"].shape[0]
            assert out[0].shape[0] == n
            buckets_seen.add(bucket)
            # bit-equal vs the unbatched predict at the executed shape
            (expect,) = reference.predict(
                pad_batch(feed, n, bucket))
            np.testing.assert_array_equal(np.asarray(expect)[:n],
                                          out[0])
            # and numerically identical-to-tolerance vs native shape
            (native,) = reference.predict(feed)
            np.testing.assert_allclose(np.asarray(native), out[0],
                                       rtol=0, atol=1e-6)
        stats = engine.stats()
        engine.shutdown()
        assert stats["completed"] == 40
        # coalescing actually happened (fewer batches than requests,
        # requests executed at buckets above their own size)
        assert stats["batches"] < 40
        assert max(buckets_seen) > 1

    def test_bounded_compiles_100_ragged_requests(self, model_dir):
        """100 requests with random batch sizes in [1, 64] trigger at
        most 7 executable compiles (one per bucket), via the engine's
        compile counter."""
        d = model_dir
        engine = _engine(d, max_batch_size=64, max_queue_wait_us=500,
                         max_queue_size=512, warmup=False)
        r = np.random.RandomState(1)
        futs = [engine.infer(
            {"x": r.rand(int(r.randint(1, 65)), 16)
             .astype(np.float32)}) for _ in range(100)]
        for f in futs:
            f.result(timeout=120)
        stats = engine.stats()
        engine.shutdown()
        assert stats["completed"] == 100
        assert stats["compiles"] <= 7, stats

    def test_warmup_precompiles_all_buckets(self, model_dir):
        d = model_dir
        engine = _engine(d, max_batch_size=8)
        stats0 = engine.stats()
        assert stats0["warmed_buckets"] == [1, 2, 4, 8]
        assert stats0["compiles"] == 4
        r = np.random.RandomState(2)
        futs = [engine.infer(
            {"x": r.rand(int(r.randint(1, 9)), 16)
             .astype(np.float32)}) for _ in range(20)]
        for f in futs:
            f.result(timeout=60)
        # traffic added ZERO compiles: every bucket was pre-compiled
        stats = engine.stats()
        engine.shutdown()
        assert stats["compiles"] == 4

    def test_multi_model_routing(self, tmp_path):
        d_a = _save_mlp_model(tmp_path / "a", seed=5, out_dim=4)
        d_b = _save_mlp_model(tmp_path / "b", seed=6, out_dim=2)
        engine = ServingEngine()
        engine.add_model("a", d_a, ServingConfig(max_batch_size=4,
                                                 warmup=False))
        engine.add_model("b", d_b, ServingConfig(max_batch_size=4,
                                                 warmup=False))
        feed = {"x": np.ones((2, 16), np.float32)}
        out_a = engine.infer_sync(feed, model="a", timeout=60)
        out_b = engine.infer_sync(feed, model="b", timeout=60)
        assert out_a[0].shape == (2, 4) and out_b[0].shape == (2, 2)
        # default model is the first added
        assert engine.infer_sync(feed, timeout=60)[0].shape == (2, 4)
        with pytest.raises(InvalidRequest):
            engine.infer(feed, model="missing")
        s = engine.stats()
        assert set(s["models"]) == {"a", "b"}
        assert s["models"]["a"]["completed"] == 2
        assert engine.stats(model="b")["completed"] == 1
        engine.shutdown()

    def test_dispatch_spans_reach_chrome_trace(self, model_dir,
                                               tmp_path):
        """Serving shows up in the profiler: dispatch spans with
        bucket/rows args land in the exported chrome trace."""
        import json

        from paddle_tpu import profiler

        d = model_dir
        profiler.reset_profiler()
        path = str(tmp_path / "trace.json")
        with profiler.profiler("CPU", profile_path=path):
            engine = _engine(d, max_batch_size=4, warmup=False)
            engine.infer_sync({"x": np.ones((3, 16), np.float32)},
                              timeout=60)
            engine.shutdown()
        evs = json.load(open(path))["traceEvents"]
        spans = [e for e in evs
                 if e.get("name") == "serving_dispatch"]
        assert spans, [e.get("name") for e in evs][:20]
        assert spans[0]["args"]["bucket"] == 4
        assert spans[0]["args"]["rows"] == 3

    def test_stats_surface(self, model_dir):
        d = model_dir
        engine = _engine(d)
        r = np.random.RandomState(3)
        for _ in range(10):
            engine.infer_sync({"x": r.rand(3, 16).astype(np.float32)},
                              timeout=60)
        s = engine.stats()
        engine.shutdown()
        for key in ("p50_ms", "p95_ms", "p99_ms", "qps", "queue_depth",
                    "batch_occupancy", "compiles", "completed"):
            assert key in s, key
        assert s["p50_ms"] <= s["p99_ms"]
        assert 0 < s["batch_occupancy"]["mean"] <= 1.0
        assert s["queue_depth"] == 0


class TestAdmissionControl:
    def test_overload_rejection_is_structured(self, model_dir):
        d = model_dir
        engine = _engine(d, max_queue_size=3, max_batch_size=4)
        worker = engine._worker(None)
        release = threading.Event()
        entered = threading.Event()

        def hold(w, batch):
            entered.set()
            release.wait(30)

        worker._dispatch_hook = hold
        feed = {"x": np.zeros((1, 16), np.float32)}
        first = engine.infer(feed)      # picked up, held in dispatch
        assert entered.wait(10)
        queued = [engine.infer(feed) for _ in range(3)]  # fills queue
        with pytest.raises(ServerOverloaded) as ei:
            engine.infer(feed)
        assert ei.value.code == "SERVER_OVERLOADED"
        assert ei.value.details["queue_depth"] == 3
        assert ei.value.to_dict()["code"] == "SERVER_OVERLOADED"
        release.set()
        worker._dispatch_hook = None
        for f in [first] + queued:
            f.result(timeout=60)
        assert engine.stats()["rejected"] == 1
        engine.shutdown()

    def test_deadline_expires_queued_request(self, model_dir):
        d = model_dir
        engine = _engine(d, max_batch_size=4)
        worker = engine._worker(None)
        release = threading.Event()
        entered = threading.Event()

        def hold(w, batch):
            entered.set()
            release.wait(30)

        worker._dispatch_hook = hold
        feed = {"x": np.zeros((1, 16), np.float32)}
        first = engine.infer(feed)
        assert entered.wait(10)
        doomed = engine.infer(feed, deadline_ms=1.0)
        time.sleep(0.05)  # deadline passes while the batcher is held
        release.set()
        worker._dispatch_hook = None
        first.result(timeout=60)
        with pytest.raises(DeadlineExceeded) as ei:
            doomed.result(timeout=60)
        assert ei.value.code == "DEADLINE_EXCEEDED"
        assert engine.stats()["expired"] == 1
        engine.shutdown()

    def test_invalid_requests(self, model_dir):
        d = model_dir
        engine = _engine(d, max_batch_size=8)
        with pytest.raises(InvalidRequest):   # wrong input name
            engine.infer({"y": np.zeros((1, 16), np.float32)})
        with pytest.raises(InvalidRequest):   # oversize batch
            engine.infer({"x": np.zeros((9, 16), np.float32)})
        with pytest.raises(InvalidRequest):   # wrong trailing dim
            engine.infer({"x": np.zeros((2, 17), np.float32)})
        with pytest.raises(InvalidRequest):   # uncastable dtype
            engine.infer({"x": np.zeros((2, 16), np.complex64)})
        engine.shutdown()

    def test_dtype_normalized_not_batch_poisoning(self, model_dir):
        """A float64 client is normalized to the model's declared
        float32 at admission — co-batched float32 clients keep their
        bit-exact results, and no fresh compile signature is minted."""
        d = model_dir
        engine = _engine(d, max_batch_size=8, max_queue_wait_us=20000)
        worker = engine._worker(None)
        compiles0 = engine.stats()["compiles"]
        release = threading.Event()
        entered = threading.Event()

        def hold(w, batch):
            entered.set()
            release.wait(30)

        worker._dispatch_hook = hold
        r = np.random.RandomState(8)
        first = engine.infer({"x": r.rand(1, 16).astype(np.float32)})
        assert entered.wait(10)
        f32_feed = {"x": r.rand(2, 16).astype(np.float32)}
        fut32 = engine.infer(f32_feed)
        fut64 = engine.infer({"x": r.rand(2, 16)})  # float64 client
        release.set()
        worker._dispatch_hook = None
        first.result(timeout=60)
        out32, out64 = fut32.result(timeout=60), fut64.result(timeout=60)
        assert out32[0].dtype == np.float32
        assert out64[0].dtype == np.float32
        # the f32 batchmate is still bit-equal at its executed bucket
        from paddle_tpu.serving import pad_batch
        ref = AnalysisPredictor(AnalysisConfig(d))
        n = 2
        (expect,) = ref.predict(pad_batch(f32_feed, n, fut32.bucket))
        np.testing.assert_array_equal(np.asarray(expect)[:n], out32[0])
        assert engine.stats()["compiles"] - compiles0 <= \
            len(worker.buckets)
        engine.shutdown()

    def test_expired_head_does_not_drop_live_request(self, model_dir):
        """Regression: an expired request at the queue head while a
        batch is accumulating must expire ALONE — the live request
        behind it used to be popped and silently dropped (its future
        hung forever)."""
        d = model_dir
        engine = _engine(d, max_batch_size=4, max_queue_wait_us=100000)
        worker = engine._worker(None)
        release = threading.Event()
        entered = threading.Event()

        def hold(w, batch):
            entered.set()
            release.wait(30)

        worker._dispatch_hook = hold
        feed = {"x": np.zeros((2, 16), np.float32)}
        first = engine.infer(feed)           # held in dispatch
        assert entered.wait(10)
        doomed = engine.infer(feed, deadline_ms=1.0)  # head, expires
        live = engine.infer(feed)            # must NOT be dropped
        time.sleep(0.05)
        release.set()
        worker._dispatch_hook = None
        first.result(timeout=60)
        with pytest.raises(DeadlineExceeded):
            doomed.result(timeout=60)
        assert len(live.result(timeout=60)) == 1  # served, not hung
        engine.shutdown()

    def test_client_cancel_does_not_kill_batcher(self, model_dir):
        """Regression: a client cancelling its queued Future must not
        kill the batcher (set_result on a cancelled future raises
        InvalidStateError) — batchmates and later requests survive."""
        d = model_dir
        engine = _engine(d, max_batch_size=8)
        worker = engine._worker(None)
        release = threading.Event()
        entered = threading.Event()

        def hold(w, batch):
            entered.set()
            release.wait(30)

        worker._dispatch_hook = hold
        feed = {"x": np.zeros((1, 16), np.float32)}
        first = engine.infer(feed)
        assert entered.wait(10)
        cancelled = engine.infer(feed)
        survivor = engine.infer(feed)
        assert cancelled.cancel()
        release.set()
        worker._dispatch_hook = None
        first.result(timeout=60)
        assert len(survivor.result(timeout=60)) == 1
        # engine fully alive for new work
        assert len(engine.infer(feed).result(timeout=60)) == 1
        assert worker._dead_error is None
        engine.shutdown()

    def test_graceful_drain_on_shutdown(self, model_dir):
        d = model_dir
        engine = _engine(d, max_queue_wait_us=20000, max_batch_size=4)
        r = np.random.RandomState(4)
        futs = [engine.infer(
            {"x": r.rand(2, 16).astype(np.float32)})
            for _ in range(12)]
        engine.shutdown(drain=True, timeout=60)
        for f in futs:  # every queued request was served, none failed
            assert len(f.result(timeout=1)) == 1
        with pytest.raises(EngineStopped):
            engine.infer({"x": np.zeros((1, 16), np.float32)})

    def test_shutdown_without_drain_fails_queued(self, model_dir):
        d = model_dir
        engine = _engine(d, max_batch_size=4)
        worker = engine._worker(None)
        release = threading.Event()
        entered = threading.Event()

        def hold(w, batch):
            entered.set()
            release.wait(30)

        worker._dispatch_hook = hold
        feed = {"x": np.zeros((1, 16), np.float32)}
        first = engine.infer(feed)
        assert entered.wait(10)
        queued = engine.infer(feed)
        release.set()
        worker._dispatch_hook = None
        engine.shutdown(drain=False, timeout=60)
        first.result(timeout=60)
        with pytest.raises(EngineStopped):
            queued.result(timeout=60)


@pytest.mark.chaos
class TestChaos:
    def test_dead_batcher_fails_futures_structured(self, model_dir):
        """A batcher thread killed by an unexpected error must fail
        every queued future with a structured BatcherDied — clients
        never hang on a dead engine."""
        d = model_dir
        engine = _engine(d, max_batch_size=4)
        worker = engine._worker(None)
        armed = threading.Event()

        class _Kill(BaseException):  # escapes `except Exception`
            pass

        def bomb(w, batch):
            armed.set()
            raise _Kill("chaos: simulated batcher kill")

        worker._dispatch_hook = bomb
        feed = {"x": np.zeros((1, 16), np.float32)}
        futs = [engine.infer(feed) for _ in range(5)]
        assert armed.wait(10)
        for f in futs:
            with pytest.raises(BatcherDied) as ei:
                f.result(timeout=30)  # structured failure, no hang
            assert ei.value.code == "BATCHER_DIED"
            assert "chaos" in ei.value.details["cause"]
        # the engine is marked dead: new work is refused, not queued
        with pytest.raises((BatcherDied, EngineStopped)):
            engine.infer(feed)
        worker._thread.join(timeout=10)
        assert not worker._thread.is_alive()

    def test_per_batch_failure_does_not_kill_engine(self, model_dir):
        """An ordinary dispatch Exception fails only that batch; the
        batcher survives and keeps serving."""
        d = model_dir
        engine = _engine(d, max_batch_size=4)
        worker = engine._worker(None)
        fired = threading.Event()

        def bomb_once(w, batch):
            worker._dispatch_hook = None
            fired.set()
            raise RuntimeError("transient dispatch failure")

        worker._dispatch_hook = bomb_once
        feed = {"x": np.zeros((1, 16), np.float32)}
        doomed = engine.infer(feed)
        with pytest.raises(RuntimeError):
            doomed.result(timeout=30)
        assert fired.is_set()
        out = engine.infer(feed).result(timeout=60)  # engine lives
        assert out[0].shape == (1, 4)
        assert engine.stats()["failed"] == 1
        engine.shutdown()


class TestCloneThreadSafety:
    def test_clone_compile_race_compiles_once(self, model_dir):
        """Regression (satellite): two clones racing the same feed
        shape must share ONE compiled executable — the shared
        first-compile gate serializes only the first trace."""
        d = model_dir
        pred = AnalysisPredictor(AnalysisConfig(d))
        clones = [pred.clone() for _ in range(4)]
        assert all(c.exe is pred.exe for c in clones)
        base = pred.exe.compile_count
        feed = {"x": np.ones((5, 16), np.float32)}
        barrier = threading.Barrier(len(clones))
        outs, errs = [], []
        lock = threading.Lock()

        def race(c):
            try:
                barrier.wait(10)
                (o,) = c.predict(feed)
                with lock:
                    outs.append(o)
            except Exception as e:  # pragma: no cover
                with lock:
                    errs.append(e)

        threads = [threading.Thread(target=race, args=(c,))
                   for c in clones]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert not errs
        assert pred.exe.compile_count - base == 1
        for o in outs[1:]:
            np.testing.assert_array_equal(outs[0], o)


class TestSignatureSidecar:
    def test_sidecar_written_and_surfaced(self, model_dir):
        d = model_dir
        assert os.path.exists(os.path.join(d, "__signature__.json"))
        program, feed_names, fetch_vars = \
            fluid.io.load_inference_model(d, fluid.Executor(),
                                          scope=fluid.Scope())
        sig = program._inference_signature
        assert sig is not None and sig["version"] == 1
        (inp,) = sig["inputs"]
        assert inp["name"] == "x" and inp["dtype"] == "float32"
        assert inp["shape"] == [-1, 16] and inp["dynamic_dims"] == [0]
        assert len(sig["outputs"]) == 1

    def test_old_model_without_sidecar_still_loads(self, tmp_path):
        # own model (not the shared fixture): this test MUTATES the
        # dir by deleting the sidecar
        d = _save_mlp_model(tmp_path)
        os.remove(os.path.join(d, "__signature__.json"))
        pred = AnalysisPredictor(AnalysisConfig(d))
        # predictor derives the signature live from the program
        sig = pred.signature
        assert sig["inputs"][0]["dynamic_dims"] == [0]
        # and the serving engine still warms every bucket from it
        engine = ServingEngine(pred, ServingConfig(max_batch_size=4))
        assert engine.stats()["warmed_buckets"] == [1, 2, 4]
        out = engine.infer_sync(
            {"x": np.zeros((3, 16), np.float32)}, timeout=60)
        assert out[0].shape == (3, 4)
        engine.shutdown()


class TestInferencerFacade:
    def test_inferencer_routes_through_predictor(self, tmp_path):
        """Satellite: the deprecated contrib.Inferencer shares the
        AnalysisPredictor per-shape compile cache — repeated infers of
        one shape compile exactly once."""
        from paddle_tpu.contrib import Inferencer

        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            with fluid.unique_name.guard():
                x = layers.data("x", shape=[6])
                layers.fc(x, size=2,
                          param_attr=fluid.ParamAttr(name="w"))
        scope = fluid.Scope()
        with fluid.scope_guard(scope):
            fluid.Executor().run(startup)
            fluid.io.save_params(None, str(tmp_path / "params"),
                                 main_program=main, scope=scope)

        def infer_func():
            x = layers.data("x", shape=[6])
            return layers.fc(x, size=2,
                             param_attr=fluid.ParamAttr(name="w"))

        inf = Inferencer(infer_func=infer_func,
                         param_path=str(tmp_path / "params"))
        assert isinstance(inf._predictor, AnalysisPredictor)
        base = inf._predictor.exe.compile_count
        feed = {"x": np.ones((3, 6), np.float32)}
        (a,) = inf.infer(feed)
        (b,) = inf.infer(feed)
        np.testing.assert_array_equal(a, b)
        assert inf._predictor.exe.compile_count - base == 1
        with pytest.raises(ValueError):
            inf.infer([1, 2, 3])


class TestExecutorDonateCache:
    def test_donate_is_part_of_compile_cache_key(self):
        """Regression: donate is baked into the jitted fn
        (donate_argnums), so runs differing only in donate must not
        share a cached executable — a donate=False caller handed a
        donating one would have its param buffers invalidated."""
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            x = layers.data("x", shape=[4])
            layers.fc(x, size=2, name="dfc")
        scope = fluid.Scope()
        with fluid.scope_guard(scope):
            exe = fluid.Executor()
            exe.run(startup)
            feed = {"x": np.ones((2, 4), np.float32)}
            fetch = [main.global_block().var("dfc.tmp_1").name]
            base = exe.compile_count
            a = exe.run(main, feed=feed, fetch_list=fetch, scope=scope,
                        donate=False)
            n_cache = len(exe._cache)
            b = exe.run(main, feed=feed, fetch_list=fetch, scope=scope,
                        donate=True)
            assert len(exe._cache) == n_cache + 1  # distinct entries
            assert exe.compile_count - base == 2
            np.testing.assert_array_equal(a[0], b[0])
