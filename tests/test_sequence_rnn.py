"""Sequence + RNN op tests vs numpy references (reference pattern:
OpTest numeric checks, test_sequence_pool.py, test_lstm_op.py,
test_gru_op.py — padded+lengths redesign)."""

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import layers


def _run(build, feed, n_fetch=1):
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = 9
    with fluid.program_guard(main, startup):
        fetch = build()
        if not isinstance(fetch, (list, tuple)):
            fetch = [fetch]
    exe = fluid.Executor()
    exe.run(startup)
    return exe.run(main, feed=feed, fetch_list=list(fetch))


B, T, D = 3, 5, 4
LENS = np.array([5, 2, 3], np.int32)


def _x():
    return np.arange(B * T * D, dtype=np.float32).reshape(B, T, D) / 10.0


def test_sequence_pool_types():
    xv = _x()
    for pool_type, ref in [
        ("sum", lambda r, n: r[:n].sum(0)),
        ("average", lambda r, n: r[:n].mean(0)),
        ("sqrt", lambda r, n: r[:n].sum(0) / np.sqrt(n)),
        ("max", lambda r, n: r[:n].max(0)),
        ("first", lambda r, n: r[0]),
        ("last", lambda r, n: r[n - 1]),
    ]:
        def build():
            x = layers.data("x", shape=[B, T, D], append_batch_size=False)
            ln = layers.data("len", shape=[B], dtype="int32",
                             append_batch_size=False)
            return layers.sequence_pool(x, pool_type, seq_len=ln)

        (out,) = _run(build, {"x": xv, "len": LENS})
        want = np.stack([ref(xv[b], LENS[b]) for b in range(B)])
        np.testing.assert_allclose(out, want, rtol=1e-5,
                                   err_msg=pool_type)


def test_sequence_softmax_masked():
    xv = _x()

    def build():
        x = layers.data("x", shape=[B, T, D], append_batch_size=False)
        ln = layers.data("len", shape=[B], dtype="int32",
                         append_batch_size=False)
        return layers.sequence_softmax(x, seq_len=ln)

    (out,) = _run(build, {"x": xv, "len": LENS})
    for b in range(B):
        n = LENS[b]
        e = np.exp(xv[b, :n] - xv[b, :n].max(0))
        np.testing.assert_allclose(out[b, :n], e / e.sum(0), rtol=1e-5)
        assert np.all(out[b, n:] == 0)


def test_sequence_reverse():
    xv = _x()

    def build():
        x = layers.data("x", shape=[B, T, D], append_batch_size=False)
        ln = layers.data("len", shape=[B], dtype="int32",
                         append_batch_size=False)
        return layers.sequence_reverse(x, seq_len=ln)

    (out,) = _run(build, {"x": xv, "len": LENS})
    for b in range(B):
        n = LENS[b]
        np.testing.assert_allclose(out[b, :n], xv[b, :n][::-1])
        np.testing.assert_allclose(out[b, n:], xv[b, n:])


def test_sequence_expand_and_pad_unpad():
    xv = np.random.RandomState(0).randn(B, D).astype(np.float32)
    yv = np.zeros((B, T, D), np.float32)

    def build():
        x = layers.data("x", shape=[B, D], append_batch_size=False)
        y = layers.data("y", shape=[B, T, D], append_batch_size=False)
        ln = layers.data("len", shape=[B], dtype="int32",
                         append_batch_size=False)
        ex = layers.sequence_expand(x, y, y_seq_len=ln)
        padded, plen = layers.sequence_pad(ex, pad_value=-1.0,
                                           seq_len=ln)
        unp = layers.sequence_unpad(padded, plen)
        return ex, padded, plen, unp

    ex, padded, plen, unp = _run(
        build, {"x": xv, "y": yv, "len": LENS})
    for b in range(B):
        n = LENS[b]
        np.testing.assert_allclose(ex[b, :n], np.tile(xv[b], (n, 1)))
        assert np.all(ex[b, n:] == 0)
        assert np.all(padded[b, n:] == -1.0)
        assert np.all(unp[b, n:] == 0)
    np.testing.assert_array_equal(plen, LENS)


def test_sequence_concat():
    r = np.random.RandomState(1)
    x1 = r.randn(B, 3, D).astype(np.float32)
    x2 = r.randn(B, 4, D).astype(np.float32)
    l1 = np.array([3, 1, 2], np.int32)
    l2 = np.array([2, 4, 1], np.int32)

    def build():
        a = layers.data("a", shape=[B, 3, D], append_batch_size=False)
        b = layers.data("b", shape=[B, 4, D], append_batch_size=False)
        la = layers.data("la", shape=[B], dtype="int32",
                         append_batch_size=False)
        lb = layers.data("lb", shape=[B], dtype="int32",
                         append_batch_size=False)
        out, olen = layers.sequence_concat([a, b], seq_lens=[la, lb])
        return out, olen

    out, olen = _run(build, {"a": x1, "b": x2, "la": l1, "lb": l2})
    np.testing.assert_array_equal(olen, l1 + l2)
    for b in range(B):
        want = np.concatenate([x1[b, :l1[b]], x2[b, :l2[b]]])
        np.testing.assert_allclose(out[b, :l1[b] + l2[b]], want,
                                   rtol=1e-6)
        assert np.all(out[b, l1[b] + l2[b]:] == 0)


def test_sequence_slice_and_enumerate():
    xv = _x()
    off = np.array([1, 0, 2], np.int32)
    ln = np.array([2, 2, 1], np.int32)

    def build():
        x = layers.data("x", shape=[B, T, D], append_batch_size=False)
        o = layers.data("o", shape=[B], dtype="int32",
                        append_batch_size=False)
        l = layers.data("l", shape=[B], dtype="int32",
                        append_batch_size=False)
        return layers.sequence_slice(x, o, l)

    (out,) = _run(build, {"x": xv, "o": off, "l": ln})
    for b in range(B):
        np.testing.assert_allclose(out[b, :ln[b]],
                                   xv[b, off[b]:off[b] + ln[b]])
        assert np.all(out[b, ln[b]:] == 0)

    ids = np.array([[1, 2, 3, 4, 0], [7, 8, 0, 0, 0]], np.int64)
    lens = np.array([4, 2], np.int32)

    def build2():
        x = layers.data("ids", shape=[2, 5], dtype="int64",
                        append_batch_size=False)
        l = layers.data("l", shape=[2], dtype="int32",
                        append_batch_size=False)
        return layers.sequence_enumerate(x, win_size=2, pad_value=0,
                                         seq_len=l)

    (en,) = _run(build2, {"ids": ids, "l": lens})
    np.testing.assert_array_equal(en[0, 0], [1, 2])
    np.testing.assert_array_equal(en[0, 3], [4, 0])  # window past len
    np.testing.assert_array_equal(en[1, 1], [8, 0])


def _np_lstm(x, w, b, lens, hidden, peephole=False):
    B_, T_, _ = x.shape
    h = np.zeros((B_, hidden), np.float32)
    c = np.zeros((B_, hidden), np.float32)
    hs, cs = [], []
    sig = lambda v: 1.0 / (1.0 + np.exp(-v))
    bg = b[:, :4 * hidden].reshape(4 * hidden)
    for t in range(T_):
        gates = x[:, t] + h @ w + bg
        gi, gf, gc, go = np.split(gates, 4, axis=-1)
        i, f = sig(gi), sig(gf)
        c_new = f * c + i * np.tanh(gc)
        h_new = sig(go) * np.tanh(c_new)
        active = (t < lens)[:, None]
        h = np.where(active, h_new, h)
        c = np.where(active, c_new, c)
        hs.append(np.where(active, h_new, 0.0))
        cs.append(np.where(active, c_new, 0.0))
    return (np.stack(hs, 1), np.stack(cs, 1), h, c)


def test_dynamic_lstm_matches_numpy():
    hidden = 6
    r = np.random.RandomState(2)
    xv = r.randn(B, T, 4 * hidden).astype(np.float32)

    def build():
        x = layers.data("x", shape=[B, T, 4 * hidden],
                        append_batch_size=False)
        ln = layers.data("len", shape=[B], dtype="int32",
                         append_batch_size=False)
        h, c = layers.dynamic_lstm(x, size=4 * hidden,
                                   use_peepholes=False, seq_len=ln)
        return h, c

    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = 9
    with fluid.program_guard(main, startup):
        fetch = build()
    exe = fluid.Executor()
    exe.run(startup)
    hv, cv = exe.run(main, feed={"x": xv, "len": LENS},
                     fetch_list=list(fetch))
    w = np.asarray(fluid.global_scope().find_var("lstm_0.w_0"))
    b = np.asarray(fluid.global_scope().find_var("lstm_0.b_0"))
    want_h, want_c, _, _ = _np_lstm(xv, w, b, LENS, hidden)
    np.testing.assert_allclose(hv, want_h, rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(cv, want_c, rtol=1e-4, atol=1e-5)


def test_dynamic_gru_runs_and_masks():
    size = 5
    r = np.random.RandomState(3)
    xv = r.randn(B, T, 3 * size).astype(np.float32)

    def build():
        x = layers.data("x", shape=[B, T, 3 * size],
                        append_batch_size=False)
        ln = layers.data("len", shape=[B], dtype="int32",
                         append_batch_size=False)
        return layers.dynamic_gru(x, size=size, seq_len=ln)

    (out,) = _run(build, {"x": xv, "len": LENS})
    assert out.shape == (B, T, size)
    for b in range(B):
        assert np.all(out[b, LENS[b]:] == 0)
    assert np.isfinite(out).all()


def test_lstm_language_model_trains():
    """dynamic_lstm in a toy next-token model: loss decreases (the
    stacked_dynamic_lstm benchmark shape, miniature)."""
    V, E, H_ = 20, 8, 16
    Bs, Ts = 4, 6
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = 5
    with fluid.program_guard(main, startup):
        ids = layers.data("ids", shape=[Bs, Ts], dtype="int64",
                          append_batch_size=False)
        tgt = layers.data("tgt", shape=[Bs, Ts], dtype="int64",
                          append_batch_size=False)
        ln = layers.data("len", shape=[Bs], dtype="int32",
                         append_batch_size=False)
        emb = layers.embedding(ids, size=[V, E])
        proj = layers.fc(emb, size=4 * H_, num_flatten_dims=2,
                         bias_attr=False)
        h, _c = layers.dynamic_lstm(proj, size=4 * H_,
                                    use_peepholes=False, seq_len=ln)
        logits = layers.fc(h, size=V, num_flatten_dims=2)
        loss = layers.reduce_mean(
            layers.softmax_with_cross_entropy(
                logits, layers.unsqueeze(tgt, axes=[2])))
        fluid.optimizer.AdamOptimizer(5e-2).minimize(loss)
    exe = fluid.Executor()
    exe.run(startup)
    r = np.random.RandomState(0)
    ids_v = r.randint(0, V, (Bs, Ts)).astype(np.int64)
    tgt_v = np.roll(ids_v, -1, axis=1)
    lens = np.full((Bs,), Ts, np.int32)
    losses = []
    for _ in range(40):
        (lv,) = exe.run(main, feed={"ids": ids_v, "tgt": tgt_v,
                                    "len": lens}, fetch_list=[loss])
        losses.append(float(lv))
    assert losses[-1] < losses[0] * 0.5, losses[::8]


def test_gru_unit_and_lstm_unit_shapes():
    Bs, D_, H_ = 4, 3, 5
    r = np.random.RandomState(1)

    def build():
        x = layers.data("x", shape=[Bs, 3 * H_],
                        append_batch_size=False)
        h0 = layers.data("h0", shape=[Bs, H_], append_batch_size=False)
        nh = layers.gru_unit(x, h0, size=H_)
        x2 = layers.data("x2", shape=[Bs, D_], append_batch_size=False)
        c0 = layers.data("c0", shape=[Bs, H_], append_batch_size=False)
        h2, c2 = layers.lstm_unit(x2, h0, c0)
        return nh, h2, c2

    nh, h2, c2 = _run(build, {
        "x": r.randn(Bs, 3 * H_).astype(np.float32),
        "h0": r.randn(Bs, H_).astype(np.float32),
        "x2": r.randn(Bs, D_).astype(np.float32),
        "c0": r.randn(Bs, H_).astype(np.float32)})
    assert nh.shape == (Bs, H_)
    assert h2.shape == (Bs, H_) and c2.shape == (Bs, H_)
    assert np.isfinite(nh).all() and np.isfinite(h2).all()


class TestStackedLSTMModel:
    def test_trains(self):
        """The fifth fluid_benchmark model family (reference:
        benchmark/fluid/models/stacked_dynamic_lstm.py) learns the
        synthetic sentiment task."""
        import paddle_tpu as fluid
        from paddle_tpu.models import stacked_lstm as S

        cfg = S.StackedLSTMConfig(vocab_size=64, emb_dim=16,
                                  lstm_size=16, num_layers=2,
                                  num_classes=2, max_len=12)
        scope = fluid.Scope()
        with fluid.scope_guard(scope):
            main, startup = fluid.Program(), fluid.Program()
            main.random_seed = startup.random_seed = 13
            with fluid.program_guard(main, startup):
                loss, acc, _logit = S.stacked_lstm_net(cfg)
                fluid.optimizer.Adam(5e-3).minimize(loss)
            exe = fluid.Executor()
            exe.run(startup)
            losses = []
            for step in range(60):
                feed = S.make_fake_batch(cfg, 16, seed=step % 4)
                lv, av = exe.run(main, feed=feed,
                                 fetch_list=[loss, acc])
                losses.append(float(lv))
            assert losses[-1] < losses[0] * 0.5, losses[::10]
            assert float(av) >= 0.8
