"""Fleet distributed-UX tests.

The 2-process test follows the reference methodology exactly
(test_dist_base.py:316,:377,:465): spawn worker subprocesses on
localhost with PADDLE_* role env vars, collect each trainer's loss
trace, and assert it equals the local single-process trace.
"""

import json
import os
import socket
import subprocess
import sys

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import layers
from paddle_tpu.incubate.fleet.base import role_maker

HERE = os.path.dirname(os.path.abspath(__file__))
RUNNER = os.path.join(HERE, "dist_runner.py")
ROOT = os.path.dirname(HERE)


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _run(cmd, env, timeout=300):
    return subprocess.run(cmd, env=env, capture_output=True,
                          text=True, timeout=timeout)


def _parse_losses(proc):
    for line in proc.stdout.splitlines():
        if line.startswith("LOSSES:"):
            return json.loads(line[len("LOSSES:"):])
    raise AssertionError(
        "no LOSSES line; rc=%d\nstdout:\n%s\nstderr:\n%s"
        % (proc.returncode, proc.stdout[-2000:], proc.stderr[-2000:]))


class TestRoleMaker:
    def test_paddle_cloud_role_maker_env(self, monkeypatch):
        monkeypatch.setenv("TRAINING_ROLE", "TRAINER")
        monkeypatch.setenv("PADDLE_TRAINER_ID", "1")
        monkeypatch.setenv("PADDLE_TRAINER_ENDPOINTS",
                           "127.0.0.1:6170,127.0.0.1:6171")
        rm = role_maker.PaddleCloudRoleMaker()
        assert rm.is_worker() and not rm.is_server()
        assert rm.worker_index() == 1
        assert rm.worker_num() == 2
        assert not rm.is_first_worker()
        assert rm.get_trainer_endpoints() == ["127.0.0.1:6170",
                                              "127.0.0.1:6171"]

    def test_user_defined_role_maker(self):
        rm = role_maker.UserDefinedRoleMaker(
            current_id=0, role=role_maker.Role.WORKER, worker_num=4)
        assert rm.is_worker() and rm.worker_num() == 4
        assert rm.is_first_worker()

    def test_server_role(self, monkeypatch):
        monkeypatch.setenv("TRAINING_ROLE", "PSERVER")
        monkeypatch.setenv("PADDLE_PSERVERS_IP_PORT_LIST",
                           "127.0.0.1:7164")
        rm = role_maker.PaddleCloudRoleMaker()
        assert rm.is_server()
        assert rm.get_pserver_endpoints() == ["127.0.0.1:7164"]


class TestFleetSingleProcess:
    def test_collective_fleet_trains(self):
        """Single-worker fleet over the 8-device virtual mesh: the
        full init → distributed_optimizer → main_program flow."""
        from paddle_tpu import layers
        from paddle_tpu.incubate.fleet.collective import Collective

        fl = Collective()
        fl.init(role_maker.UserDefinedRoleMaker(0, worker_num=1))
        main, startup = fluid.Program(), fluid.Program()
        main.random_seed = startup.random_seed = 5
        scope = fluid.Scope()
        with fluid.scope_guard(scope):
            with fluid.program_guard(main, startup):
                x = layers.data("x", shape=[8, 4],
                                append_batch_size=False)
                y = layers.data("y", shape=[8, 1],
                                append_batch_size=False)
                pred = layers.fc(x, size=1)
                loss = layers.reduce_mean(
                    layers.square_error_cost(input=pred, label=y))
                opt = fl.distributed_optimizer(
                    fluid.optimizer.SGD(0.1))
                opt.minimize(loss)
            exe = fluid.Executor()
            exe.run(startup)
            rs = np.random.RandomState(0)
            losses = []
            for _ in range(12):
                xb = rs.rand(8, 4).astype(np.float32)
                yb = xb.sum(1, keepdims=True).astype(np.float32) * 0.3
                (lv,) = exe.run(fl.main_program,
                                feed={"x": xb, "y": yb},
                                fetch_list=[loss])
                losses.append(float(np.asarray(lv).reshape(-1)[0]))
            assert losses[-1] < losses[0] * 0.7, losses

    def test_server_entry_raises(self):
        from paddle_tpu.incubate.fleet.collective import Collective
        fl = Collective()
        fl.init(role_maker.UserDefinedRoleMaker(0, worker_num=1))
        with pytest.raises(NotImplementedError):
            fl.init_server()


class TestFleetTwoProcess:
    N_STEPS = 4

    def _env(self, rank, endpoints):
        env = dict(os.environ)
        env.update({
            "PYTHONPATH": ROOT,
            "JAX_PLATFORMS": "cpu",
            "XLA_FLAGS": "",
            "TRAINING_ROLE": "TRAINER",
            "PADDLE_TRAINER_ID": str(rank),
            "PADDLE_TRAINERS_NUM": "2",
            "PADDLE_TRAINER_ENDPOINTS": endpoints,
        })
        return env

    def test_two_process_loss_equals_local(self):
        """2 workers on localhost (jax.distributed over the fleet API)
        must reproduce the single-process loss trace — the reference's
        distributed pass criterion (test_dist_base.py:316)."""
        port = _free_port()
        endpoints = "127.0.0.1:%d,127.0.0.1:0" % port

        local = _run([sys.executable, RUNNER, "local",
                      str(self.N_STEPS)], self._env(0, endpoints))
        local_losses = _parse_losses(local)

        procs = [subprocess.Popen(
            [sys.executable, RUNNER, "fleet", str(self.N_STEPS)],
            env=self._env(r, endpoints), stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT, text=True) for r in range(2)]
        outs = []
        for p in procs:
            out, _ = p.communicate(timeout=300)
            outs.append(out)
        for r, (p, out) in enumerate(zip(procs, outs)):
            assert p.returncode == 0, \
                "worker %d failed:\n%s" % (r, out[-3000:])

        class _P:  # tiny adapter for _parse_losses
            def __init__(self, out):
                self.stdout, self.stderr, self.returncode = out, "", 0

        for r, out in enumerate(outs):
            dist_losses = _parse_losses(_P(out))
            np.testing.assert_allclose(
                dist_losses, local_losses, rtol=2e-4,
                err_msg="worker %d loss trace diverged" % r)


class TestFleetRealPS:
    def test_full_ps_ux(self, rng):
        """The reference fleet PS workflow end to end: server via
        init_server/run_server (thread), worker via init_worker +
        exe.run(fleet.main_program) + stop_worker — over the native
        RPC transport with a real port."""
        import socket
        import threading

        import numpy as np
        from paddle_tpu.incubate.fleet.base.role_maker import (
            Role, UserDefinedRoleMaker)
        from paddle_tpu.incubate.fleet.parameter_server import (
            ParameterServerFleet)

        # reserve a port for the pserver
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
        s.close()
        ep = "127.0.0.1:%d" % port

        def build():
            # separate processes each start a fresh name counter; the
            # in-process test must emulate that or the worker's param
            # names drift from the server's
            from paddle_tpu import unique_name
            with unique_name.guard():
                main, startup = fluid.Program(), fluid.Program()
                main.random_seed = startup.random_seed = 5
                with fluid.program_guard(main, startup):
                    x = layers.data(name="x", shape=[8],
                                    dtype="float32")
                    y = layers.data(name="y", shape=[1],
                                    dtype="int64")
                    pred = layers.fc(x, size=4, act="softmax")
                    loss = layers.mean(
                        layers.cross_entropy(pred, y))
            return main, startup, loss

        server_ready = threading.Event()
        server_err = []

        def run_server():
            try:
                f = ParameterServerFleet()
                f.init(UserDefinedRoleMaker(
                    current_id=0, role=Role.SERVER, worker_num=1,
                    server_endpoints=[ep]))
                main, startup, loss = build()
                with fluid.program_guard(main, startup):
                    opt = f.distributed_optimizer(
                        fluid.optimizer.SGDOptimizer(0.3))
                    opt.minimize(loss)
                f.init_server()
                server_ready.set()
                f.run_server()
            except Exception as e:  # surfaces in the main thread
                server_err.append(e)
                server_ready.set()

        th = threading.Thread(target=run_server, daemon=True)
        th.start()
        assert server_ready.wait(timeout=60)
        assert not server_err, server_err

        wf = ParameterServerFleet()
        wf.init(UserDefinedRoleMaker(
            current_id=0, role=Role.WORKER, worker_num=1,
            server_endpoints=[ep]))
        main, startup, loss = build()
        with fluid.program_guard(main, startup):
            opt = wf.distributed_optimizer(
                fluid.optimizer.SGDOptimizer(0.3))
            opt.minimize(loss)
        scope = fluid.Scope()
        with fluid.scope_guard(scope):
            exe = fluid.Executor()
            exe.run(startup)
            wf.init_worker()
            vals = []
            # ONE fixed batch: the labels are random (no learnable
            # x->y signal), so with a fresh batch per step the
            # trajectory is a noise walk around ln(4) and the
            # vals[-1] < vals[0] assertion was an RNG coin flip that
            # env drift finally lost (measured: 30 fresh-batch steps
            # hover 1.26..1.58). Memorizing one batch makes the
            # decrease deterministic while exercising the identical
            # PS send/optimize/recv path.
            feed = {"x": rng.rand(16, 8).astype(np.float32),
                    "y": rng.randint(0, 4, (16, 1)).astype(np.int64)}
            for _ in range(5):
                (lv,) = exe.run(wf.main_program, feed=feed,
                                fetch_list=[loss])
                vals.append(float(np.asarray(lv).reshape(-1)[0]))
            wf.stop_worker()
        th.join(timeout=60)
        assert not th.is_alive(), "server did not stop on COMPLETE"
        assert np.isfinite(vals).all()
        assert vals[-1] < vals[0]


class TestFleetPSTwoProcess:
    def test_ps_server_and_trainer_processes(self, tmp_path):
        """TRUE process isolation for PS mode (the reference's
        test_dist_base start_pserver:377 + _run_cluster:465
        methodology): a pserver subprocess serves over the native RPC
        transport, a trainer subprocess trains through
        fleet.main_program, and both exit cleanly."""
        import dist_runner as dr

        ep = "127.0.0.1:%d" % dr.free_port()

        def env(role):
            e = dict(os.environ)
            e.pop("JAX_PLATFORMS", None)
            e["PYTHONPATH"] = ROOT
            e["TRAINING_ROLE"] = role
            e["PADDLE_PSERVERS_IP_PORT_LIST"] = ep
            e["PADDLE_TRAINER_ID"] = "0"
            e["PADDLE_PSERVER_ID"] = "0"
            e["PADDLE_TRAINERS_NUM"] = "1"
            return e

        with open(str(tmp_path / "server.err"), "w+") as errfile:
            server = dr.spawn_pserver(env("PSERVER"), errfile,
                                      timeout=120)
            try:
                (out,) = dr.run_ps_trainers([env("TRAINER")], 5,
                                            timeout=240)
                losses = dr.parse_losses(out, "ps trainer")
                assert len(losses) == 5
                assert np.isfinite(losses).all()
                assert losses[-1] < losses[0]

                server.wait(timeout=60)
                sout = server.stdout.read()
                assert server.returncode == 0
                assert "SERVER_DONE" in sout
            finally:
                if server.poll() is None:
                    server.kill()


class TestLaunchModule:
    def test_cluster_env_contract(self):
        """python -m paddle_tpu.distributed.launch writes exactly the
        PADDLE_TRAINER_* env vars init_parallel_env consumes
        (reference launch.py's get_cluster env contract)."""
        from paddle_tpu.distributed import launch as L

        args = L._parse_args([
            "--cluster_node_ips=10.0.0.1,10.0.0.2",
            "--node_ip=10.0.0.2", "--started_port=7000",
            "--nproc_per_node=2", "train.py", "--foo"])
        envs = L.get_cluster_env(args)
        assert len(envs) == 2
        assert envs[0]["PADDLE_TRAINER_ID"] == "2"  # node 1, local 0
        assert envs[1]["PADDLE_TRAINER_ID"] == "3"
        assert envs[0]["PADDLE_TRAINERS_NUM"] == "4"
        eps = envs[0]["PADDLE_TRAINER_ENDPOINTS"].split(",")
        assert eps == ["10.0.0.1:7000", "10.0.0.1:7001",
                       "10.0.0.2:7000", "10.0.0.2:7001"]
        assert envs[1]["PADDLE_CURRENT_ENDPOINT"] == "10.0.0.2:7001"
        assert args.training_script == "train.py"
        assert args.training_script_args == ["--foo"]

    def test_bad_node_ip_rejected(self):
        from paddle_tpu.distributed import launch as L
        args = L._parse_args(["--node_ip=9.9.9.9", "t.py"])
        with pytest.raises(ValueError, match="not in"):
            L.get_cluster_env(args)

    def test_compile_cache_env_contract(self, monkeypatch):
        """Every role's env carries ONE shared
        PADDLE_TPU_COMPILE_CACHE_DIR (the ROADMAP compile-plane
        follow-up: real fleets share a persistent AOT cache by
        default), resolved journal-dir > user-cache, explicit flag
        wins, empty string opts out."""
        from paddle_tpu.distributed import launch as L
        monkeypatch.delenv("PADDLE_TPU_COMPILE_CACHE_DIR",
                           raising=False)

        args = L._parse_args(["--nproc_per_node=2",
                              "--server_num=1",
                              "--serving_replicas=1",
                              "--journal_dir=/tmp/jd", "t.py"])
        envs = (L.get_cluster_env(args) + L.get_server_env(args)
                + L.get_serving_env(args))
        assert len(envs) == 4
        dirs = {e["PADDLE_TPU_COMPILE_CACHE_DIR"] for e in envs}
        assert dirs == {os.path.join("/tmp/jd", "compile_cache")}

        # no journal/log dir: one stable per-user location
        args = L._parse_args(["t.py"])
        env = L.get_cluster_env(args)[0]
        assert env["PADDLE_TPU_COMPILE_CACHE_DIR"].endswith(
            os.path.join(".cache", "paddle_tpu", "compile_cache"))

        # explicit flag wins over journal dir; "" opts out by
        # stamping an EMPTY value (children inherit the launcher's
        # env, so the blank must override an inherited var —
        # compile_cache.active() reads "" as disabled)
        args = L._parse_args(["--journal_dir=/tmp/jd",
                              "--compile_cache_dir=/tmp/cc", "t.py"])
        assert L.get_cluster_env(args)[0][
            "PADDLE_TPU_COMPILE_CACHE_DIR"] == "/tmp/cc"
        args = L._parse_args(["--compile_cache_dir=", "t.py"])
        assert L.get_cluster_env(args)[0][
            "PADDLE_TPU_COMPILE_CACHE_DIR"] == ""

        # an INHERITED empty var is the documented disabled value:
        # the journal-dir fallback must NOT re-enable the cache
        # (children inherit the "" and stay disabled)
        monkeypatch.setenv("PADDLE_TPU_COMPILE_CACHE_DIR", "")
        args = L._parse_args(["--journal_dir=/tmp/jd", "t.py"])
        assert "PADDLE_TPU_COMPILE_CACHE_DIR" not in \
            L.get_cluster_env(args)[0]
        monkeypatch.delenv("PADDLE_TPU_COMPILE_CACHE_DIR")

        # the launcher's own env var is the fleet default and is
        # never overridden by the journal-dir fallback; an explicit
        # flag (or "") still beats it
        monkeypatch.setenv("PADDLE_TPU_COMPILE_CACHE_DIR",
                           "/tmp/inherited")
        args = L._parse_args(["--journal_dir=/tmp/jd", "t.py"])
        assert L.get_cluster_env(args)[0][
            "PADDLE_TPU_COMPILE_CACHE_DIR"] == "/tmp/inherited"
        args = L._parse_args(["--compile_cache_dir=/tmp/cc", "t.py"])
        assert L.get_cluster_env(args)[0][
            "PADDLE_TPU_COMPILE_CACHE_DIR"] == "/tmp/cc"
        args = L._parse_args(["--compile_cache_dir=", "t.py"])
        assert L.get_cluster_env(args)[0][
            "PADDLE_TPU_COMPILE_CACHE_DIR"] == ""

    def test_spawn_fleet_stamps_compile_cache(self, monkeypatch,
                                              tmp_path):
        """tools/load_gen.spawn_fleet stamps the shared cache dir
        into every replica's env (replica 0's warmup compiles become
        replicas 1..N's cache loads)."""
        import importlib
        sys.path.insert(0, os.path.join(os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))), "tools"))
        load_gen = importlib.import_module("load_gen")
        monkeypatch.delenv("PADDLE_TPU_COMPILE_CACHE_DIR",
                           raising=False)
        seen = {}

        class FakePopen:
            def __init__(self, cmd, env=None, **kw):
                seen["env"] = env
                raise RuntimeError("stop before spawning")

            def kill(self):
                pass

        monkeypatch.setattr("subprocess.Popen", FakePopen)
        with pytest.raises(RuntimeError, match="stop before"):
            load_gen.spawn_fleet(str(tmp_path), 1,
                                 compile_cache_dir=str(tmp_path /
                                                       "cc"))
        assert seen["env"]["PADDLE_TPU_COMPILE_CACHE_DIR"] == \
            str(tmp_path / "cc")
        # an explicit dir beats an INHERITED env var (the replica env
        # is seeded from os.environ), and "" blanks the inherited var
        # out — compile_cache.active() reads "" as disabled
        monkeypatch.setenv("PADDLE_TPU_COMPILE_CACHE_DIR",
                           "/tmp/inherited")
        with pytest.raises(RuntimeError, match="stop before"):
            load_gen.spawn_fleet(str(tmp_path), 1,
                                 compile_cache_dir=str(tmp_path /
                                                       "cc"))
        assert seen["env"]["PADDLE_TPU_COMPILE_CACHE_DIR"] == \
            str(tmp_path / "cc")
        with pytest.raises(RuntimeError, match="stop before"):
            load_gen.spawn_fleet(str(tmp_path), 1,
                                 compile_cache_dir="")
        assert seen["env"]["PADDLE_TPU_COMPILE_CACHE_DIR"] == ""

    def test_launch_runs_workers(self, tmp_path):
        """End to end: launch a 2-process script; each worker sees its
        rank env and exits 0; a failing worker propagates rc."""
        from paddle_tpu.distributed import launch as L

        script = tmp_path / "w.py"
        script.write_text(
            "import os, sys\n"
            "rid = os.environ['PADDLE_TRAINER_ID']\n"
            "print('rank', rid, 'of',\n"
            "      os.environ['PADDLE_TRAINERS_NUM'])\n"
            "sys.exit(0 if len(sys.argv) == 1 else int(sys.argv[1]))\n")
        args = L._parse_args(["--nproc_per_node=2",
                              "--log_dir", str(tmp_path / "logs"),
                              str(script)])
        assert L.launch(args) == 0
        logs = sorted((tmp_path / "logs").glob("worker.*.log"))
        assert [p.name for p in logs] == ["worker.0.log",
                                          "worker.1.log"]
        assert "rank 0 of 2" in logs[0].read_text()

        args2 = L._parse_args(["--nproc_per_node=2", str(script), "3"])
        assert L.launch(args2) == 3
