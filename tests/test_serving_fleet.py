"""Serving-fleet suite: ServingRouter over N RPC-fronted replicas.

What must hold (the fleet's acceptance bar):

  - routing is CORRECT: results through router -> RPC -> replica ->
    engine are the engine's own results (bit-exact for a lone
    request at its bucket);
  - queue-depth-aware dispatch actually uses the piggybacked load:
    a slow replica is routed AROUND, and least-loaded beats
    round-robin p99 under skewed per-request cost;
  - overload is a STRUCTURED, synchronous ``ServerOverloaded`` at
    the router — shedding, not queue-melt;
  - a replica killed mid-flight loses NOTHING: every future resolves
    (result / retried result / structured error), the lease evicts
    the corpse (journalled), and the fleet keeps serving at n-1;
  - versioned hot-swap flips v1 -> v2 under live load with zero
    failed requests, v2 warmed before admission, v1 drained away —
    and REFUSES a v2 whose signature would break v1 clients.
"""

import json
import os
import threading
import time

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import layers
from paddle_tpu import observability as obs
from paddle_tpu.serving import (InvalidRequest, ReplicaUnavailable,
                                RouterConfig, ServerOverloaded,
                                ServingConfig, ServingEngine,
                                ServingReplica, ServingRouter,
                                SignatureMismatch, pad_batch,
                                signature_compat)

pytestmark = pytest.mark.serving

IN_DIM = 16


def _save_mlp(dirname, seed=7, out_dim=4, in_dim=IN_DIM,
              extra_input=False, dtype="float32"):
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = seed
    with fluid.unique_name.guard():
        with fluid.program_guard(main, startup):
            x = layers.data(name="x", shape=[in_dim], dtype=dtype)
            feeds = ["x"]
            if extra_input:
                b = layers.data(name="bias_in", shape=[out_dim],
                                dtype=dtype)
                feeds.append("bias_in")
            h = layers.fc(x, size=8, act="relu")
            pred = layers.fc(h, size=out_dim, act="softmax")
            if extra_input:
                pred = layers.elementwise_add(pred, b)
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe = fluid.Executor()
        exe.run(startup)
        fluid.io.save_inference_model(str(dirname), feeds, [pred],
                                      exe, main_program=main,
                                      scope=scope)
    return str(dirname)


@pytest.fixture(scope="module")
def model_dir(tmp_path_factory):
    return _save_mlp(tmp_path_factory.mktemp("fleet_model"))


@pytest.fixture
def fleet(model_dir):
    """Factory for an in-process fleet (thread replicas over real
    TCP); everything built through it is torn down after the test."""
    created = []

    def make(n=2, model=model_dir, config=None, router_config=None):
        cfg = config or ServingConfig(max_batch_size=8,
                                      max_queue_wait_us=500)
        reps = [ServingReplica(model, cfg, replica_id=i).start()
                for i in range(n)]
        router = ServingRouter(
            [r.endpoint for r in reps],
            router_config or RouterConfig(
                lease_timeout_s=1.0, heartbeat_interval_s=0.1,
                rpc_deadline_s=10.0, connect_timeout_s=3.0))
        created.append((router, reps))
        return router, reps

    yield make
    for router, reps in created:
        try:
            router.shutdown()
        except Exception:
            pass
        for r in reps:
            try:
                r.shutdown()
            except Exception:
                pass


def _feed(rng, rows=2, in_dim=IN_DIM):
    return {"x": rng.rand(rows, in_dim).astype(np.float32)}


# ---------------------------------------------------------------------------
# signature compatibility (hot-swap gate)
# ---------------------------------------------------------------------------

class TestSignatureCompat:
    def _sig(self, d):
        with open(os.path.join(d, "__signature__.json")) as f:
            return json.load(f)

    def test_identical_is_compatible(self, model_dir, tmp_path):
        v2 = _save_mlp(tmp_path / "v2", seed=99)
        assert signature_compat(self._sig(model_dir),
                                self._sig(v2)) == []

    def test_static_to_dynamic_relax_is_compatible(self, model_dir):
        old = self._sig(model_dir)
        new = json.loads(json.dumps(old))
        new["inputs"][0]["shape"][1] = -1
        new["inputs"][0]["dynamic_dims"] = sorted(
            new["inputs"][0]["dynamic_dims"] + [1])
        assert signature_compat(old, new) == []

    def test_dynamic_to_static_tighten_refused(self, model_dir):
        old = self._sig(model_dir)
        new = json.loads(json.dumps(old))
        old2 = json.loads(json.dumps(old))
        old2["inputs"][0]["shape"][1] = -1
        problems = signature_compat(old2, new)
        assert any("dynamic (-1) -> static" in p for p in problems)

    def test_dtype_change_refused(self, model_dir, tmp_path):
        v2 = _save_mlp(tmp_path / "v2f64", dtype="float64")
        problems = signature_compat(self._sig(model_dir),
                                    self._sig(v2))
        assert any("dtype" in p and "float64" in p for p in problems)

    def test_added_and_removed_inputs_refused(self, model_dir,
                                              tmp_path):
        v2 = _save_mlp(tmp_path / "v2extra", extra_input=True)
        problems = signature_compat(self._sig(model_dir),
                                    self._sig(v2))
        assert any("added" in p for p in problems)
        # and the reverse direction reports the removal
        problems = signature_compat(self._sig(v2),
                                    self._sig(model_dir))
        assert any("removed" in p for p in problems)

    def test_static_dim_and_output_changes_refused(self, model_dir,
                                                   tmp_path):
        wide_in = _save_mlp(tmp_path / "v2wide", in_dim=32)
        problems = signature_compat(self._sig(model_dir),
                                    self._sig(wide_in))
        assert any("static 16 -> 32" in p for p in problems)
        wide_out = _save_mlp(tmp_path / "v2out", out_dim=6)
        problems = signature_compat(self._sig(model_dir),
                                    self._sig(wide_out))
        assert problems  # output dim 4 -> 6 must be flagged


# ---------------------------------------------------------------------------
# routing correctness + dispatch policy
# ---------------------------------------------------------------------------

class TestRouterDispatch:
    def test_lone_request_bit_exact(self, fleet, model_dir):
        router, reps = fleet(n=2)
        from paddle_tpu.inference import (AnalysisConfig,
                                          AnalysisPredictor)
        ref = AnalysisPredictor(AnalysisConfig(model_dir))
        rng = np.random.RandomState(0)
        for rows in (1, 2, 3, 5):
            feed = _feed(rng, rows)
            out = router.infer_sync(feed, timeout=30)
            # engine contract: equal to a single-request predict
            # padded to the request's own bucket
            from paddle_tpu.serving import bucket_for, bucket_sizes
            bucket = bucket_for(rows, bucket_sizes(8))
            want = ref.predict(pad_batch(dict(feed), rows, bucket))
            assert np.array_equal(out[0],
                                  np.asarray(want[0])[:rows])

    def test_concurrent_burst_correct_and_attributed(self, fleet):
        router, reps = fleet(n=2)
        rng = np.random.RandomState(1)
        feeds = [_feed(rng, int(rng.randint(1, 5)))
                 for _ in range(40)]
        futs = [router.infer(f) for f in feeds]
        outs = [f.result(30) for f in futs]
        assert all(o[0].shape[0] == f["x"].shape[0]
                   for o, f in zip(outs, feeds))
        st = router.stats()
        served = sum(s["requests"]
                     for s in st["replicas"].values())
        assert served == 40
        # both replicas participated (queue-depth dispatch spreads a
        # 40-request burst far wider than one worker)
        assert all(s["requests"] > 0
                   for s in st["replicas"].values())
        assert st["router"]["completed"] == 40

    def test_least_loaded_avoids_slow_replica_and_beats_rr(
            self, fleet, model_dir):
        def run(policy):
            router, reps = fleet(
                n=2, router_config=RouterConfig(
                    policy=policy, lease_timeout_s=5.0,
                    heartbeat_interval_s=0.2, rpc_deadline_s=30.0,
                    connect_timeout_s=3.0))
            # replica 0 pays a fixed 80 ms per dispatch (skewed
            # per-request cost: the piggybacked queue depth is the
            # only way the router can know)
            for w in reps[0].engine._workers.values():
                w._dispatch_hook = \
                    lambda worker, batch: time.sleep(0.08)
            rng = np.random.RandomState(2)
            lat = []
            lock = threading.Lock()

            def worker():
                for _ in range(6):
                    t0 = time.monotonic()
                    router.infer_sync(_feed(rng, 1), timeout=60)
                    with lock:
                        lat.append((time.monotonic() - t0) * 1e3)

            ths = [threading.Thread(target=worker)
                   for _ in range(4)]
            for t in ths:
                t.start()
            for t in ths:
                t.join()
            st = router.stats()
            return (np.asarray(lat),
                    {rid: s["requests"]
                     for rid, s in st["replicas"].items()})

        lat_ll, served_ll = run("least_loaded")
        lat_rr, served_rr = run("round_robin")
        # round-robin splits ~50/50 by construction; least-loaded
        # must route most traffic to the fast replica...
        assert served_ll["1"] > served_ll["0"]
        assert served_ll["1"] >= 0.6 * sum(served_ll.values())
        # ...and that shows up as better latency. MEAN, not p99: with
        # 24 samples p99 is effectively the max, and even least-loaded
        # tie-breaks its first request(s) onto the slow replica, so
        # BOTH policies' maxima sit near that replica's 80 ms floor —
        # the old p99 A/B decided on sub-1% scheduler noise and flaked
        # on loaded boxes (fails on the clean tree too). The mean
        # carries the routing signal the test is about; the bench's
        # p99-under-skew claim lives in serving_fleet_scaling with
        # real sample counts.
        assert float(lat_ll.mean()) < float(lat_rr.mean())

    def test_all_replicas_saturated_sheds_structured(self, fleet):
        router, reps = fleet(
            n=2, router_config=RouterConfig(
                shed_queue_depth=0,  # everything counts saturated
                lease_timeout_s=5.0, heartbeat_interval_s=0.2,
                connect_timeout_s=3.0))
        rng = np.random.RandomState(3)
        before = obs.registry().counter(
            "router_requests_total", outcome="shed").value
        with pytest.raises(ServerOverloaded) as ei:
            router.infer(_feed(rng))
        assert ei.value.code == "SERVER_OVERLOADED"
        assert "saturated" in str(ei.value)
        after = obs.registry().counter(
            "router_requests_total", outcome="shed").value
        assert after == before + 1
        assert any(e["kind"] == "router_shed"
                   for e in obs.journal_events(kind="router_shed"))

    def test_pending_cap_sheds_structured(self, fleet):
        router, _ = fleet(
            n=1, router_config=RouterConfig(
                max_pending=0, lease_timeout_s=5.0,
                heartbeat_interval_s=0.2, connect_timeout_s=3.0))
        with pytest.raises(ServerOverloaded) as ei:
            router.infer({"x": np.zeros((1, IN_DIM), np.float32)})
        assert "pending cap" in str(ei.value)

    def test_invalid_feed_is_structured_not_retried(self, fleet):
        router, _ = fleet(n=2)
        fut = router.infer({"nope": np.zeros((1, IN_DIM),
                                             np.float32)})
        with pytest.raises(InvalidRequest):
            fut.result(30)


# ---------------------------------------------------------------------------
# replica kill: zero lost futures, eviction, n-1 service
# ---------------------------------------------------------------------------

@pytest.mark.chaos
class TestReplicaKill:
    def test_kill_mid_flight_zero_lost_then_n_minus_1(self, fleet,
                                                      model_dir):
        router, reps = fleet(
            n=2, router_config=RouterConfig(
                lease_timeout_s=0.6, heartbeat_interval_s=0.1,
                rpc_deadline_s=5.0, connect_timeout_s=2.0,
                max_retries=4))
        rng = np.random.RandomState(4)
        feeds = [_feed(rng, int(rng.randint(1, 5)))
                 for _ in range(30)]
        futs = [router.infer(f) for f in feeds]
        reps[0].crash()  # SIGKILL stand-in: nothing in flight answers
        outs = [f.result(30) for f in futs]  # must ALL resolve
        assert all(o[0].shape[0] == f["x"].shape[0]
                   for o, f in zip(outs, feeds))
        # lease eviction journalled, fleet keeps serving at n-1
        deadline = time.monotonic() + 5.0
        while time.monotonic() < deadline:
            if obs.journal_events(kind="replica_evicted"):
                break
            time.sleep(0.05)
        evs = obs.journal_events(kind="replica_evicted")
        assert any(e.get("replica") == 0 for e in evs)
        out = router.infer_sync(_feed(rng), timeout=30)
        assert out[0].shape == (2, 4)
        st = router.stats()
        assert st["replicas"]["0"]["healthy"] is False
        assert st["replicas"]["1"]["healthy"] is True

    def test_all_replicas_dead_is_structured_error(self, fleet):
        router, reps = fleet(
            n=1, router_config=RouterConfig(
                lease_timeout_s=0.4, heartbeat_interval_s=0.1,
                rpc_deadline_s=2.0, connect_timeout_s=1.0,
                max_retries=1))
        reps[0].crash()
        deadline = time.monotonic() + 5.0
        while router._healthy() and time.monotonic() < deadline:
            time.sleep(0.05)
        fut = router.infer({"x": np.zeros((1, IN_DIM), np.float32)})
        with pytest.raises(ReplicaUnavailable):
            fut.result(30)


# ---------------------------------------------------------------------------
# versioned hot-swap
# ---------------------------------------------------------------------------

class TestHotSwap:
    def test_swap_under_live_load_zero_failures(self, fleet,
                                                model_dir, tmp_path):
        v2_dir = _save_mlp(tmp_path / "v2", seed=31)
        router, reps = fleet(n=2)
        stop = threading.Event()
        failures, completed = [], [0]

        def client(seed):
            rng = np.random.RandomState(seed)
            while not stop.is_set():
                try:
                    router.infer_sync(_feed(rng), timeout=30)
                    completed[0] += 1
                except Exception as e:  # ANY failure breaks the bar
                    failures.append(repr(e))

        ths = [threading.Thread(target=client, args=(s,))
               for s in (10, 11, 12)]
        for t in ths:
            t.start()
        time.sleep(0.3)
        report = router.swap_model(v2_dir)
        time.sleep(0.3)
        stop.set()
        for t in ths:
            t.join()
        assert not failures
        assert completed[0] > 0
        assert report["from"] == "v1" and report["to"] == "v2"
        # v2 warmed on every replica BEFORE admission
        assert sorted(report["warmed_buckets"]) == [0, 1]
        assert all(report["warmed_buckets"][r.replica_id]
                   for r in reps)
        # v1 drained + unloaded everywhere; v2 is the only version
        for rid in (0, 1):
            models = router.replica_stats(rid)["models"]
            assert models["default"]["active"] == "v2"
            assert models["default"]["versions"] == ["v2"]
        # and traffic now computes with the v2 weights, bit-exactly
        from paddle_tpu.inference import (AnalysisConfig,
                                          AnalysisPredictor)
        ref = AnalysisPredictor(AnalysisConfig(v2_dir))
        rng = np.random.RandomState(13)
        feed = _feed(rng, 2)
        out = router.infer_sync(feed, timeout=30)
        want = ref.predict(pad_batch(dict(feed), 2, 2))
        assert np.array_equal(out[0], np.asarray(want[0])[:2])

    def test_incompatible_swap_refused_with_reasons(self, fleet,
                                                    tmp_path):
        bad = _save_mlp(tmp_path / "bad", out_dim=6)
        router, reps = fleet(n=2)
        with pytest.raises(SignatureMismatch) as ei:
            router.swap_model(bad)
        assert "breaks live clients" in str(ei.value)
        assert ei.value.details["problems"]
        # nothing changed: v1 still the only version, still serving
        models = router.replica_stats(0)["models"]
        assert models["default"] == {"active": "v1",
                                     "versions": ["v1"]}
        out = router.infer_sync(
            {"x": np.zeros((1, IN_DIM), np.float32)}, timeout=30)
        assert out[0].shape == (1, 4)

    def test_missing_sidecar_refused_actionably(self, fleet,
                                                tmp_path):
        v2 = _save_mlp(tmp_path / "nosig", seed=55)
        os.remove(os.path.join(v2, "__signature__.json"))
        router, _ = fleet(n=1)
        with pytest.raises(SignatureMismatch) as ei:
            router.swap_model(v2)
        assert "__signature__.json" in str(ei.value)
        assert "save_inference_model" in str(ei.value)


# ---------------------------------------------------------------------------
# queue-depth surfacing (engine satellite)
# ---------------------------------------------------------------------------

class TestQueueDepth:
    def test_live_queue_depth_and_gauge(self, model_dir):
        eng = ServingEngine(model_dir, ServingConfig(
            max_batch_size=4, max_queue_wait_us=100))
        try:
            worker = eng._worker(None)
            release = threading.Event()
            worker._dispatch_hook = \
                lambda w, b: release.wait(10)
            rng = np.random.RandomState(5)
            futs = [eng.infer({"x": rng.rand(1, IN_DIM)
                               .astype(np.float32)})
                    for _ in range(6)]
            deadline = time.monotonic() + 5.0
            while eng.queue_depth() < 2 and \
                    time.monotonic() < deadline:
                time.sleep(0.01)
            depth = eng.queue_depth()
            assert depth >= 2
            gauge = obs.registry().gauge("serving_queue_depth",
                                         model="default")
            assert gauge.value >= 2
            # and the Prometheus text surface shows the series
            text = obs.registry().prometheus_text()
            assert 'serving_queue_depth{model="default"}' in text
            release.set()
            for f in futs:
                f.result(30)
            assert eng.queue_depth() == 0
        finally:
            eng.shutdown(drain=False)


# ---------------------------------------------------------------------------
# launcher fleet mode
# ---------------------------------------------------------------------------

class TestLaunchServingEnv:
    def test_get_serving_env_contract(self, tmp_path):
        from paddle_tpu.distributed import launch as L
        args = L._parse_args(
            ["--serving_replicas", "3",
             "--serving_started_port", "9300",
             "--journal_dir", str(tmp_path), "script.py"])
        envs = L.get_serving_env(args)
        assert len(envs) == 3
        eps = ["127.0.0.1:%d" % (9300 + k) for k in range(3)]
        for k, env in enumerate(envs):
            assert env["PADDLE_SERVING_REPLICA_ID"] == str(k)
            assert env["PADDLE_CURRENT_ENDPOINT"] == eps[k]
            assert env["PADDLE_SERVING_ENDPOINTS"] == ",".join(eps)
            assert env["PADDLE_TRAINING_ROLE"] == "SERVING"
            assert env["PADDLE_TPU_ROLE"] == "serving-%d" % k
            assert env["PADDLE_TPU_EVENT_JOURNAL"] == os.path.join(
                str(tmp_path), "events.serving-%d.jsonl" % k)

    def test_no_serving_replicas_means_no_envs(self):
        from paddle_tpu.distributed import launch as L
        args = L._parse_args(["script.py"])
        assert L.get_serving_env(args) == []


# ---------------------------------------------------------------------------
# load_gen: ramp mode + fleet smoke
# ---------------------------------------------------------------------------

class TestLoadGenRamp:
    def _load_gen(self):
        import importlib
        import sys
        sys.path.insert(0, os.path.join(os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))), "tools"))
        return importlib.import_module("load_gen")

    def test_ramp_mode_smoke(self, capsys):
        load_gen = self._load_gen()
        rc = load_gen.main(["--synthetic", "--mode", "ramp",
                            "--ramp", "1,2", "--step-duration",
                            "0.2", "--max-batch", "8"])
        assert rc == 0
        report = json.loads(
            capsys.readouterr().out.strip().splitlines()[-1])
        assert report["mode"] == "ramp"
        assert [s["concurrency"] for s in report["steps"]] == [1, 2]
        for s in report["steps"]:
            assert s["completed"] > 0
            assert s["p99_ms"] is not None
        assert report["client_failed"] == 0

    def test_fleet_subprocess_smoke_with_attribution(self, capsys):
        load_gen = self._load_gen()
        rc = load_gen.main(["--synthetic", "--replicas", "1",
                            "--mode", "closed", "--concurrency", "2",
                            "--duration", "0.3", "--max-batch", "8"])
        assert rc == 0
        report = json.loads(
            capsys.readouterr().out.strip().splitlines()[-1])
        assert report["replicas"] == 1
        assert report["completed"] > 0
        (attr,) = report["per_replica"].values()
        assert attr["requests"] == report["completed"]
        assert attr["sheds"] == 0
        assert attr["p99_ms"] is not None


# ---------------------------------------------------------------------------
# chaos: kill under 5% drop, merged trace, causal journal
# ---------------------------------------------------------------------------

@pytest.mark.chaos
class TestFleetChaos:
    # tier-1 headroom (PR 18): full fleet kill scenario (~11 s) -> slow;
    # kill semantics stay via
    # TestReplicaKill::test_kill_mid_flight_zero_lost_then_n_minus_1
    @pytest.mark.slow
    def test_serving_kill_scenario(self):
        """The full acceptance scenario (tools/chaos_run.py
        serving_kill): replica killed under NetFaultProxy 5% drop ->
        zero lost/hung futures, bounded p99, causal replica_evicted
        journal event, ONE merged trace with router->replica span
        links."""
        import importlib
        import sys
        tools = os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), "tools")
        sys.path.insert(0, tools)
        chaos_run = importlib.import_module("chaos_run")

        class A:
            seed = 0
            steps = 3
        verdict = chaos_run._scenario_serving_kill(A())
        assert verdict["ok"], verdict
        assert verdict["hung"] == []
        assert verdict["unstructured"] == []
        assert verdict["causal_order"]
        assert verdict["trace_links"] > 0
        assert verdict["replica_evicted_seq"] is not None
