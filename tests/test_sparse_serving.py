"""Sparse serving plane (docs/serving.md §"Sparse serving"): the
stamped authority (per-row push versions + shard watermark, surviving
snapshot round-trips and row migration), the stamped
LookupServiceClient (staleness bounds, watermark polls, authority
re-pulls), the device row tier (hit/miss accounting, CLOCK eviction,
pow-2 shape buckets), the SparseServingReplica's bounded-staleness
gate in its three modes (repull / shed / observe-only), group-sharded
lookup routing behind the PR 8 router, the ``stale_serving`` doctor
verdict, the lock_lint pin on serving/sparse.py, bench_diff direction
pins for the two bench rows, and — under ``-m chaos`` — the
train-AND-serve acceptance scenario (pserver kill mid-stream under
1->3->1 autoscaling; the multi-seed sweep rides ``-m slow``)."""

import argparse
import os
import sys

import numpy as np
import pytest

from paddle_tpu import observability as obs
from paddle_tpu.distributed import LargeScaleKV, LookupServiceClient
from paddle_tpu.distributed.ps import ListenAndServ
from paddle_tpu.distributed.rpc import RPCClient
from paddle_tpu.serving import (InvalidRequest, RouterConfig,
                                ServingError, ServingRouter,
                                SparseServingConfig,
                                SparseServingReplica, StaleRows)
from paddle_tpu.serving.sparse import _DeviceRowTier

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
TOOLS = os.path.join(ROOT, "tools")
sys.path.insert(0, TOOLS)

pytestmark = pytest.mark.sparse_serving

DIM = 16


def _shards(n=2, lr=0.5, seed=9):
    tables = [{"emb": LargeScaleKV(dim=DIM, lr=lr, seed=seed)}
              for _ in range(n)]
    servers = [ListenAndServ("127.0.0.1:0", {}, lambda nm, g: None,
                             lookup_tables=tb).start()
               for tb in tables]
    return servers, [s.endpoint for s in servers], tables


def _replica(eps, **cfg_kw):
    kw = dict(max_staleness_steps=2, watermark_poll_every=1,
              device_rows=64, cache_bytes=1 << 18)
    kw.update(cfg_kw)
    return SparseServingReplica(
        "emb", eps, DIM, config=SparseServingConfig(**kw)).start()


def _push_all(tables, ids, val=1.0, times=1):
    """Authority-side pushes: every shard applies ``times`` pushes on
    its subset of ``ids`` (ids route by id %% n_shards)."""
    n = len(tables)
    ids = np.asarray(ids, np.int64)
    for _ in range(times):
        for shard, tb in enumerate(tables):
            mine = ids[ids % n == shard]
            if mine.size:
                tb["emb"].push(mine,
                               np.full((mine.size, DIM), val,
                                       np.float32))


# ---------------------------------------------------------------------------
# stamped authority: versions + watermark on the table and the wire
# ---------------------------------------------------------------------------

class TestStampedAuthority:
    def test_watermark_counts_pushes_and_versions_stamp_rows(self):
        kv = LargeScaleKV(dim=DIM, lr=0.5, seed=1)
        assert kv.watermark() == 0
        ids = np.arange(4, dtype=np.int64)
        g = np.ones((4, DIM), np.float32)
        kv.push(ids, g)
        kv.push(ids[:2], g[:2])
        assert kv.watermark() == 2
        assert kv.versions(ids).tolist() == [2, 2, 1, 1]
        # 0 = never pushed: lazily-initialized rows are fresh by
        # construction (deterministic seed), not stale
        assert kv.versions([99]).tolist() == [0]

    def test_pull_stamped_is_one_consistent_read(self):
        kv = LargeScaleKV(dim=DIM, lr=0.5, seed=1)
        ids = np.arange(3, dtype=np.int64)
        kv.push(ids, np.ones((3, DIM), np.float32))
        rows, vers, wm = kv.pull_stamped(ids)
        assert rows.shape == (3, DIM)
        assert vers.tolist() == [1, 1, 1] and wm == 1
        rows0, vers0, wm0 = kv.pull_stamped(np.zeros(0, np.int64))
        assert rows0.shape == (0, DIM) and wm0 == 1

    def test_stamps_survive_snapshot_roundtrip(self):
        kv = LargeScaleKV(dim=DIM, lr=0.5, seed=1)
        ids = np.arange(4, dtype=np.int64)
        kv.push(ids, np.ones((4, DIM), np.float32))
        kv.push(ids[2:], np.ones((2, DIM), np.float32))
        state = kv.export_state()
        kv2 = LargeScaleKV(dim=DIM, lr=0.5, seed=1)
        kv2.import_state(state)
        # the stamp clock commits in the SAME durable boundary as the
        # rows: a restore rolls the watermark back exactly as far as
        # the rows it restores
        assert kv2.watermark() == kv.watermark() == 2
        assert kv2.versions(ids).tolist() == kv.versions(ids).tolist()

    def test_migrated_rows_stamp_at_dest_watermark(self):
        src = LargeScaleKV(dim=DIM, lr=0.5, seed=1)
        dst = LargeScaleKV(dim=DIM, lr=0.5, seed=2)
        ids = np.arange(3, dtype=np.int64)
        src.push(ids, np.ones((3, DIM), np.float32))
        dst.push(np.asarray([7], np.int64),
                 np.ones((1, DIM), np.float32))
        dst.import_rows(ids, src.pull(ids))
        # "fresh as of this shard's now" — the importing shard's clock
        # owns the rows from here on
        assert dst.versions(ids).tolist() == [1, 1, 1]
        dst.drop_rows(ids[:1])
        assert dst.versions(ids[:1]).tolist() == [0]

    def test_prefetch_stamped_verb_and_empty_poll(self):
        servers, eps, tables = _shards(1)
        try:
            tables[0]["emb"].push(np.arange(4, dtype=np.int64),
                                  np.ones((4, DIM), np.float32))
            c = RPCClient(eps[0])
            rows, vers, wm = c.prefetch_stamped(
                "emb", np.arange(4, dtype=np.int64))
            assert rows.shape == (4, DIM)
            assert vers.tolist() == [1, 1, 1, 1] and wm == 1
            # EMPTY ids = the cheap watermark poll
            rows0, vers0, wm0 = c.prefetch_stamped(
                "emb", np.zeros(0, np.int64))
            assert rows0.shape == (0, DIM) and wm0 == 1
            c.close()
        finally:
            for s in servers:
                s.shutdown()


# ---------------------------------------------------------------------------
# stamped client: staleness bounds + authority re-pull
# ---------------------------------------------------------------------------

class TestStampedClient:
    def test_staleness_minus_one_until_pulled_then_tracks_lag(self):
        servers, eps, tables = _shards(2)
        cl = LookupServiceClient("emb", eps, dim=DIM, stamped=True,
                                 write_policy="none")
        try:
            ids = np.arange(6, dtype=np.int64)
            assert (cl.staleness(ids) == -1).all()
            cl.pull(ids)
            assert (cl.staleness(ids) == 0).all()
            _push_all(tables, ids, times=3)
            cl.watermarks(refresh=True)
            assert (cl.staleness(ids) == 3).all()
            # authority re-read resets the stamps it refreshes
            cl.refresh_rows(ids[:3])
            lag = cl.staleness(ids)
            assert (lag[:3] == 0).all() and (lag[3:] == 3).all()
        finally:
            cl.close()
            for s in servers:
                s.shutdown()

    def test_watermark_regression_drops_stamps_and_caches(self):
        """An authority restored from an OLDER snapshot rolls its
        watermark clock backwards: the refresh poll must invalidate
        every stamp and the hot tier instead of clamping the negative
        lag to 0 — pre-restore cached rows are NOT lag-0 fresh."""
        servers, eps, tables = _shards(1)
        cl = LookupServiceClient("emb", eps, dim=DIM, stamped=True,
                                 write_policy="none",
                                 cache_bytes=1 << 16)
        try:
            kv = tables[0]["emb"]
            ids = np.arange(4, dtype=np.int64)
            g = np.ones((4, DIM), np.float32)
            kv.push(ids, g)                        # watermark 1
            old_state = kv.export_state()
            for _ in range(3):
                kv.push(ids, g)                    # watermark 4
            cl.pull(ids)                           # stamps @ wm 4
            assert (cl.staleness(ids) == 0).all()
            kv.import_state(old_state)             # wm back to 1
            cl.watermarks(refresh=True)
            # the poll saw the clock move backwards: stamps gone,
            # staleness unknown (fetch-before-serve), never lag 0
            assert cl.stats()["stamped_rows"] == 0
            assert (cl.staleness(ids) == -1).all()
            # the hot tier dropped with the stamps: pull re-reads the
            # restored authority, not the pre-restore cached image
            rows = cl.pull(ids)
            assert np.allclose(rows, kv.pull(ids))
            assert (cl.staleness(ids) == 0).all()
        finally:
            cl.close()
            for s in servers:
                s.shutdown()

    def test_stamp_map_bounded_by_lru_trim(self):
        """row_stamps must not outgrow the tiers it describes: the
        cap trims least-recently-pulled stamps WITH their host-cache
        rows ("host-cached => stamped"), and trimmed rows re-pull +
        re-stamp on next touch."""
        servers, eps, _tables = _shards(1)
        cl = LookupServiceClient("emb", eps, dim=DIM, stamped=True,
                                 write_policy="none",
                                 cache_bytes=1 << 16,
                                 max_stamp_rows=8)
        try:
            cl.pull(np.arange(20, dtype=np.int64))
            assert len(cl.row_stamps) == 8
            assert cl.stats()["stamps_trimmed"] == 12
            # survivors are the most recently pulled
            assert set(cl.row_stamps) == set(range(12, 20))
            # trimmed rows read as unknown, not fresh
            trimmed = np.arange(12, dtype=np.int64)
            assert (cl.staleness(trimmed) == -1).all()
            # ...and left the host cache with their stamps, so the
            # next touch is an authority pull that re-stamps them
            hits0 = cl.cache_hit_rows
            cl.pull(trimmed[:4])
            assert cl.cache_hit_rows == hits0
            assert (cl.staleness(trimmed[:4]) == 0).all()
            assert len(cl.row_stamps) == 8
        finally:
            cl.close()
            for s in servers:
                s.shutdown()

    def test_stats_carry_stamp_counters(self):
        servers, eps, _tables = _shards(1)
        cl = LookupServiceClient("emb", eps, dim=DIM, stamped=True,
                                 write_policy="none")
        try:
            cl.pull(np.arange(5, dtype=np.int64))
            st = cl.stats()
            assert st["stamped_rows"] == 5
            assert eps[0] in st["shard_watermarks"]
        finally:
            cl.close()
            for s in servers:
                s.shutdown()


# ---------------------------------------------------------------------------
# device row tier: accounting, CLOCK eviction, shape buckets
# ---------------------------------------------------------------------------

class TestDeviceTier:
    def test_hit_miss_accounting(self):
        t = _DeviceRowTier(DIM, 16)
        ids = np.arange(4, dtype=np.int64)
        slots = t.lookup(ids)
        assert (slots == -1).all() and t.misses == 4 and t.hits == 0
        t.fill(ids, np.ones((4, DIM), np.float32))
        slots = t.lookup(ids)
        assert (slots >= 0).all() and t.hits == 4
        got = t.gather(slots)
        assert got.shape == (4, DIM)
        assert np.allclose(got, 1.0)

    def test_clock_eviction_bounds_residency(self):
        t = _DeviceRowTier(DIM, 8)   # capacity floor is 8 slots
        for batch in range(4):
            ids = np.arange(batch * 8, batch * 8 + 8, dtype=np.int64)
            t.lookup(ids)
            t.fill(ids, np.full((8, DIM), float(batch), np.float32))
        st = t.stats()
        assert st["resident_rows"] == 8
        assert st["evictions"] == 24
        # the survivors serve the LAST batch's rows
        slots = t.lookup(np.arange(24, 32, dtype=np.int64))
        assert (slots >= 0).all()
        assert np.allclose(t.gather(slots), 3.0)

    def test_fill_pads_to_pow2_idempotently(self):
        t = _DeviceRowTier(DIM, 16)
        # 3 rows -> padded scatter of 4 (last pair repeated): the
        # duplicate write must not corrupt the slot
        ids = np.asarray([5, 6, 7], np.int64)
        rows = np.stack([np.full(DIM, float(i), np.float32)
                         for i in range(3)])
        slots = t.fill(ids, rows)
        assert len(slots) == 3
        assert np.allclose(t.gather(slots), rows)
        assert _DeviceRowTier._pow2(3) == 4
        assert _DeviceRowTier._pow2(8) == 8

    def test_fill_overflow_spills_instead_of_remapping(self):
        """A single fill larger than capacity must NOT wrap CLOCK
        back onto slots it just allocated (two ids -> one slot ->
        another id's row served): the unplaceable tail spills as -1
        and every placed id gathers ITS OWN row."""
        t = _DeviceRowTier(DIM, 8)
        ids = np.arange(10, dtype=np.int64)
        rows = np.stack([np.full(DIM, float(i), np.float32)
                         for i in range(10)])
        slots = t.fill(ids, rows)
        placed = slots >= 0
        assert int(placed.sum()) == 8 and t.overflow_rows == 2
        # no slot serves two ids
        assert len(set(slots[placed].tolist())) == 8
        assert np.allclose(t.gather(slots[placed]), rows[placed])

    def test_fill_never_evicts_pinned_hit_slots(self):
        """Slots the current request already depends on (its hits)
        survive any same-request fill — evicting one would corrupt
        the gather that is about to read it."""
        t = _DeviceRowTier(DIM, 8)
        hit_ids = np.arange(4, dtype=np.int64)
        hit_rows = np.stack([np.full(DIM, 100.0 + i, np.float32)
                             for i in range(4)])
        hit_slots = t.fill(hit_ids, hit_rows)
        new_ids = np.arange(50, 60, dtype=np.int64)   # 10 > 4 free
        new_rows = np.zeros((10, DIM), np.float32)
        t.fill(new_ids, new_rows, pinned=hit_slots)
        assert (t.lookup(hit_ids) == hit_slots).all()
        assert np.allclose(t.gather(hit_slots), hit_rows)

    def test_invalidation_frees_slots(self):
        t = _DeviceRowTier(DIM, 16)
        ids = np.arange(6, dtype=np.int64)
        t.fill(ids, np.ones((6, DIM), np.float32))
        assert t.invalidate_ids(ids[:2]) == 2
        assert (t.lookup(ids[:2]) == -1).all()
        assert t.invalidate_all() == 4
        assert t.stats()["resident_rows"] == 0


# ---------------------------------------------------------------------------
# the bounded-staleness gate: repull / shed / observe-only
# ---------------------------------------------------------------------------

class TestStalenessGate:
    def test_repull_serves_fresh_rows_within_bound(self):
        servers, eps, tables = _shards(2)
        rep = _replica(eps, max_staleness_steps=2)
        router = ServingRouter([rep.endpoint], RouterConfig())
        try:
            ids = np.arange(8, dtype=np.int64).reshape(4, 2)
            out1 = router.infer_sync({"ids": ids}, timeout=30)
            _push_all(tables, ids.reshape(-1), times=3)  # lag 3 > 2
            out2 = router.infer_sync({"ids": ids}, timeout=30)
            st = rep.stats()["staleness"]
            assert st["repulled_rows"] > 0
            assert st["stale_served_rows"] == 0
            assert st["max_lag_served"] <= 2
            # freshness is black-box observable: pooled rows moved
            assert not np.allclose(out1[1], out2[1])
        finally:
            router.shutdown()
            rep.shutdown()
            for s in servers:
                s.shutdown()

    def test_shed_raises_structured_stale_rows(self):
        servers, eps, tables = _shards(2)
        rep = _replica(eps, max_staleness_steps=0,
                       staleness_action="shed")
        router = ServingRouter([rep.endpoint], RouterConfig())
        try:
            ids = np.arange(6, dtype=np.int64).reshape(2, 3)
            router.infer_sync({"ids": ids}, timeout=30)  # fresh pull
            _push_all(tables, ids.reshape(-1), times=1)
            with pytest.raises(ServingError) as ei:
                router.infer_sync({"ids": ids}, timeout=30)
            # StaleRows crosses the wire structured: details intact
            # (the router maps unknown codes to the base class)
            assert ei.value.details["bound"] == 0
            assert ei.value.details["lag"] >= 1
            assert rep.stats()["staleness"]["shed_requests"] == 1
        finally:
            router.shutdown()
            rep.shutdown()
            for s in servers:
                s.shutdown()

    def test_shed_is_a_servingerror_subclass_locally(self):
        assert issubclass(StaleRows, ServingError)
        assert StaleRows.code == "STALE_ROWS"
        e = StaleRows("x", lag=3, bound=1)
        assert e.to_dict()["details"]["lag"] == 3

    def test_observe_only_serves_and_journals_breach(self):
        servers, eps, tables = _shards(2)
        rep = _replica(eps, max_staleness_steps=1, enforce=False)
        router = ServingRouter([rep.endpoint], RouterConfig())
        try:
            mark = obs.journal_events()[-1]["seq"] \
                if obs.journal_events() else 0
            ids = np.arange(4, dtype=np.int64).reshape(2, 2)
            router.infer_sync({"ids": ids}, timeout=30)
            _push_all(tables, ids.reshape(-1), times=4)
            out = router.infer_sync({"ids": ids}, timeout=30)
            assert out is not None          # served anyway
            st = rep.stats()["staleness"]
            assert st["stale_served_rows"] > 0
            assert st["max_lag_served"] >= 4
            evs = [e for e in obs.journal_events(since_seq=mark)
                   if e["kind"] == "stale_row_served"]
            assert evs, "breach must be journalled for doctor"
            e0 = evs[0]
            assert e0["bound"] == 1 and e0["lag"] >= 4
            assert "row_version" in e0 and "pull_watermark" in e0
        finally:
            router.shutdown()
            rep.shutdown()
            for s in servers:
                s.shutdown()

    def test_gate_disarmed_when_bound_none(self):
        servers, eps, tables = _shards(1)
        rep = _replica(eps, max_staleness_steps=None)
        router = ServingRouter([rep.endpoint], RouterConfig())
        try:
            ids = np.arange(4, dtype=np.int64).reshape(2, 2)
            router.infer_sync({"ids": ids}, timeout=30)
            _push_all(tables, ids.reshape(-1), times=5)
            router.infer_sync({"ids": ids}, timeout=30)
            st = rep.stats()["staleness"]
            assert st["repulled_rows"] == 0
            assert st["shed_requests"] == 0
        finally:
            router.shutdown()
            rep.shutdown()
            for s in servers:
                s.shutdown()

    def test_overflow_request_serves_authority_rows(self):
        """More unique ids in ONE request than the device tier holds:
        the overflow bypasses the tier and serves the authority rows
        already pulled — never another id's resident slot."""
        servers, eps, tables = _shards(2)
        rep = _replica(eps, device_rows=8, pull_q8=False)
        router = ServingRouter([rep.endpoint], RouterConfig())
        try:
            ids = np.arange(12, dtype=np.int64)
            out = router.infer_sync({"ids": ids.reshape(12, 1)},
                                    timeout=30)
            pooled = out[1]
            want = np.stack([
                tables[int(i) % 2]["emb"].pull(
                    np.asarray([i], np.int64))[0] for i in ids])
            assert np.allclose(pooled, want, atol=1e-5)
            tiers = rep.stats()["tiers"]
            assert tiers["device"]["overflow_rows"] == 4
            assert tiers["device_overflow_rows"] == 4
        finally:
            router.shutdown()
            rep.shutdown()
            for s in servers:
                s.shutdown()

    def test_tier_accounting_across_requests(self):
        servers, eps, _tables = _shards(2)
        rep = _replica(eps)
        router = ServingRouter([rep.endpoint], RouterConfig())
        try:
            ids = np.arange(10, dtype=np.int64).reshape(5, 2)
            router.infer_sync({"ids": ids}, timeout=30)
            tiers1 = rep.stats()["tiers"]
            assert tiers1["device"]["misses"] == 10
            assert tiers1["remote_rows"] == 10
            router.infer_sync({"ids": ids}, timeout=30)
            tiers2 = rep.stats()["tiers"]
            # second identical request is a pure device-tier hit: no
            # new host hits, no new authority rows
            assert tiers2["device"]["hits"] == 10
            assert tiers2["remote_rows"] == 10
            assert tiers2["host_hit_rows"] == tiers1["host_hit_rows"]
        finally:
            router.shutdown()
            rep.shutdown()
            for s in servers:
                s.shutdown()


# ---------------------------------------------------------------------------
# group-sharded lookup routing (PR 13 replica groups)
# ---------------------------------------------------------------------------

class TestGroupShardedRouting:
    def test_grouped_router_dispatches_to_rank0_executor(self):
        servers, eps, _tables = _shards(2)
        r0 = SparseServingReplica(
            "emb", eps, DIM, replica_id=0, group_rank=0, group_size=2,
            config=SparseServingConfig(max_staleness_steps=4)).start()
        r1 = SparseServingReplica(
            "emb", eps, DIM, replica_id=1, group_rank=1,
            group_size=2).start()
        router = ServingRouter([r0.endpoint, r1.endpoint],
                               RouterConfig(group_size=2))
        try:
            ids = np.arange(6, dtype=np.int64).reshape(2, 3)
            out = router.infer_sync({"ids": ids}, timeout=30)
            assert out[0].shape == (2,)
            # only the executor owns lookup state
            assert "tiers" in r0.stats()
            assert "tiers" not in r1.stats()
        finally:
            router.shutdown()
            r0.shutdown()
            r1.shutdown()
            for s in servers:
                s.shutdown()

    def test_member_rank_answers_structured_error(self):
        servers, eps, _tables = _shards(1)
        r1 = SparseServingReplica("emb", eps, DIM, replica_id=3,
                                  group_rank=1, group_size=2).start()
        router = ServingRouter([r1.endpoint], RouterConfig())
        try:
            ids = np.arange(2, dtype=np.int64).reshape(1, 2)
            with pytest.raises(InvalidRequest) as ei:
                router.infer_sync({"ids": ids}, timeout=30)
            assert ei.value.details["group_rank"] == 1
        finally:
            router.shutdown()
            r1.shutdown()
            for s in servers:
                s.shutdown()

    def test_missing_ids_input_is_invalid_request(self):
        servers, eps, _tables = _shards(1)
        rep = _replica(eps)
        router = ServingRouter([rep.endpoint], RouterConfig())
        try:
            with pytest.raises(InvalidRequest):
                router.infer_sync(
                    {"x": np.zeros((1, 2), np.float32)}, timeout=30)
        finally:
            router.shutdown()
            rep.shutdown()
            for s in servers:
                s.shutdown()


# ---------------------------------------------------------------------------
# doctor: the stale_serving verdict
# ---------------------------------------------------------------------------

def _stale_event(row=42, lag=9, bound=2, **kw):
    e = {"kind": "stale_row_served", "role": "serving", "seq": 10,
         "table": "emb", "replica": 0, "rows": 3, "row": row,
         "row_version": 17, "pull_watermark": 20,
         "shard_watermark": 29, "lag": lag, "bound": bound}
    e.update(kw)
    return e


class TestDoctorStaleServing:
    def test_breach_diagnosed_with_coherence_arithmetic(self):
        import doctor
        rep = doctor.diagnose([
            _stale_event(),
            {"kind": "stale_repull", "role": "serving", "seq": 11,
             "replica": 0, "rows": 5, "lag": 4},
        ])
        assert rep["top"] == "stale_serving"
        d = rep["diagnoses"][0]
        # the verdict cites the push seq and the pull watermark — the
        # exact numbers the coherence contract is stated in
        assert "version 17" in d["summary"]
        assert "watermark 20" in d["summary"]
        assert any(c.get("row_version") == 17
                   and c.get("pull_watermark") == 20
                   for c in d["evidence"])

    def test_repulls_alone_are_the_gate_working_not_a_breach(self):
        import doctor
        rep = doctor.diagnose([
            {"kind": "stale_repull", "role": "serving", "seq": 3,
             "replica": 0, "rows": 5, "lag": 4},
            {"kind": "stale_shed", "role": "serving", "seq": 4,
             "replica": 0, "rows": 2, "lag": 9},
        ])
        assert all(d["name"] != "stale_serving"
                   for d in rep["diagnoses"])

    def test_breach_outranks_pserver_restart(self):
        import doctor
        assert doctor._BASE_SCORE["stale_serving"] > \
            doctor._BASE_SCORE["pserver_restart"]


# ---------------------------------------------------------------------------
# bench_diff: both new rows' directions pinned
# ---------------------------------------------------------------------------

class TestBenchDiffDirections:
    def _diff(self, metric, unit, v1, v2):
        import bench_diff
        rounds = [
            {"round": 1, "path": "r1", "error": None,
             "rows": {metric: {"metric": metric, "value": v1,
                               "unit": unit}}},
            {"round": 2, "path": "r2", "error": None,
             "rows": {metric: {"metric": metric, "value": v2,
                               "unit": unit}}},
        ]
        return bench_diff.diff(rounds)

    def test_sparse_serving_qps_higher_is_better(self):
        unit = "qps closed-loop Zipf serving while training pushes"
        drop = self._diff("sparse_serving_qps", unit, 150.0, 60.0)
        assert [f["flag"] for f in drop["flags"]] == ["REGRESSION"]
        rise = self._diff("sparse_serving_qps", unit, 60.0, 150.0)
        assert rise["flags"] == []

    def test_fresh_weight_to_served_ms_lower_is_better(self):
        unit = "ms push-commit to first served read (bound 0)"
        rise = self._diff("fresh_weight_to_served_ms", unit, 5.0, 50.0)
        assert [f["flag"] for f in rise["flags"]] == ["REGRESSION"]
        drop = self._diff("fresh_weight_to_served_ms", unit, 50.0, 5.0)
        assert drop["flags"] == []


# ---------------------------------------------------------------------------
# lock_lint gate: serving/sparse.py pinned in the scan set
# ---------------------------------------------------------------------------

class TestLockLintSparseServingGate:
    def test_sparse_module_scanned_and_clean(self):
        import lock_lint
        assert "paddle_tpu/serving/sparse.py" in \
            lock_lint.DEFAULT_PATHS
        locks, funcs = lock_lint.scan(lock_lint.DEFAULT_PATHS)
        assert any(fk.startswith("paddle_tpu.serving.sparse.")
                   for fk in funcs), \
            "serving/sparse.py fell out of the lock_lint scan set"
        report = lock_lint.analyze(locks, funcs)
        assert report["violations"] == [], report["violations"]


# ---------------------------------------------------------------------------
# load_gen: the ONE shared Zipf traffic generator
# ---------------------------------------------------------------------------

class TestSharedTrafficGenerator:
    def test_bench_zipf_delegates_to_load_gen(self):
        import bench
        import load_gen
        a = bench.zipf_ids(np.random.RandomState(3), 100, 50)
        b = load_gen.zipf_ids(np.random.RandomState(3), 100, 50)
        assert np.array_equal(a, b)

    def test_zipf_skew_concentrates_head(self):
        import load_gen
        rng = np.random.RandomState(0)
        ids = load_gen.zipf_ids(rng, 1000, 5000, skew=0.9)
        assert ids.dtype == np.int64
        assert (ids < 1000).all() and (ids >= 0).all()
        # top-10% of ranks absorb well over a uniform share
        assert (ids < 100).mean() > 0.4

    def test_sparse_feed_maker_contract(self):
        import load_gen
        rng = np.random.RandomState(1)
        mk = load_gen.sparse_feed_maker(rng, 500, 3, 2, 6)
        feed, b = mk()
        assert set(feed) == {"ids"}
        assert feed["ids"].shape == (b, 3) and 2 <= b <= 6
        assert feed["ids"].dtype == np.int64


# ---------------------------------------------------------------------------
# the acceptance scenario (chaos: tier-1 seed; slow: the sweep)
# ---------------------------------------------------------------------------

@pytest.mark.chaos
class TestTrainAndServeScenario:
    def test_sparse_serving_green_and_diagnosed(self):
        """ISSUE 18 acceptance, seed 0: DeepFM-style trainer pushes a
        live stream while the SAME tables serve Zipf traffic, the
        ControlPlane scales serving 1->3->1, pserver shard 0 is
        killed mid-push and restarted from its snapshots — no served
        row beyond the bound, zero hung/unstructured futures, doctor
        names the restart and explains every autoscale action."""
        import chaos_run
        res = chaos_run._scenario_sparse_serving(
            argparse.Namespace(seed=0, steps=4))
        assert res["ok"], {k: v for k, v in res.items()
                           if k not in ("journal_kinds",)}
        assert res["kill_fired"] and res["peak_replicas"] == 3
        assert res["stale_served_rows"] == 0
        assert res["max_lag_served"] <= res["staleness_bound"]
        assert res["hung"] == [] and res["unstructured"] == []
        doc = res["doctor"]
        assert doc["match"] and doc["top"] == "pserver_restart"
        rem = doc["remediation"]
        assert rem["ok"] and rem["unexplained"] == []


@pytest.mark.slow
class TestTrainAndServeScenarioSweep:
    @pytest.mark.parametrize("seed", [1, 2])
    def test_seed_sweep(self, seed):
        import chaos_run
        res = chaos_run._scenario_sparse_serving(
            argparse.Namespace(seed=seed, steps=4))
        assert res["ok"], {k: v for k, v in res.items()
                           if k not in ("journal_kinds",)}
