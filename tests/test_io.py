"""Checkpoint + inference-model tests (reference analog:
unittests/test_io_save_load.py, book tests' save+reload round-trips)."""

import os

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import layers, optimizer
from paddle_tpu.core.scope import Scope
from paddle_tpu.io import (deserialize_tensor, load_inference_model,
                           load_persistables, save_inference_model,
                           save_persistables, serialize_tensor)


def _build_and_train(steps=5, seed=0):
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = seed
    startup.random_seed = seed
    with fluid.program_guard(main, startup):
        x = layers.data("x", shape=[4])
        y = layers.data("y", shape=[1])
        h = layers.fc(x, size=8, act="relu")
        pred = layers.fc(h, size=1)
        loss = layers.mean(layers.square_error_cost(pred, y))
        optimizer.Adam(learning_rate=1e-2).minimize(loss)
    exe = fluid.Executor()
    exe.run(startup)
    rng = np.random.RandomState(seed)
    for _ in range(steps):
        xv = rng.rand(8, 4).astype(np.float32)
        yv = xv.sum(1, keepdims=True).astype(np.float32)
        exe.run(main, feed={"x": xv, "y": yv}, fetch_list=[loss])
    return main, startup, exe, pred, loss


def test_tensor_roundtrip():
    for arr in (np.arange(12, dtype=np.float32).reshape(3, 4),
                np.array(3.5, dtype=np.float64),
                np.arange(5, dtype=np.int64),
                np.random.RandomState(0).rand(2, 3, 4).astype(
                    np.float32)):
        got, off = deserialize_tensor(serialize_tensor(arr))
        np.testing.assert_array_equal(got, arr)
        assert got.dtype == arr.dtype


def test_tensor_corrupt_rejected():
    buf = serialize_tensor(np.ones(3, np.float32))
    with pytest.raises(Exception, match="magic"):
        deserialize_tensor(b"XXXX" + buf[4:])


def test_save_load_persistables_roundtrip(tmp_path):
    main, startup, exe, pred, loss = _build_and_train()
    scope = fluid.global_scope()
    before = {v.name: np.asarray(scope.find_var(v.name))
              for v in main.list_vars()
              if v.persistable and not v.is_data}
    save_persistables(exe, str(tmp_path / "ckpt"), main)
    # load into a FRESH scope and compare every persistable (params,
    # Adam moments, beta pows, lr)
    fresh = Scope()
    load_persistables(exe, str(tmp_path / "ckpt"), main, scope=fresh)
    for name, want in before.items():
        got = np.asarray(fresh.find_var(name))
        np.testing.assert_array_equal(got, want, err_msg=name)


def test_save_load_combined_single_file(tmp_path):
    main, startup, exe, pred, loss = _build_and_train(seed=1)
    scope = fluid.global_scope()
    save_persistables(exe, str(tmp_path), main, filename="all.pdckpt")
    assert (tmp_path / "all.pdckpt").exists()
    fresh = Scope()
    load_persistables(exe, str(tmp_path), main, filename="all.pdckpt",
                      scope=fresh)
    for v in main.list_vars():
        if v.persistable and not v.is_data:
            np.testing.assert_array_equal(
                np.asarray(fresh.find_var(v.name)),
                np.asarray(scope.find_var(v.name)), err_msg=v.name)


def test_shape_mismatch_rejected(tmp_path):
    main, startup, exe, pred, loss = _build_and_train(seed=2)
    save_persistables(exe, str(tmp_path / "c"), main)
    # program with a different fc size must refuse the checkpoint
    main2, startup2 = fluid.Program(), fluid.Program()
    with fluid.program_guard(main2, startup2):
        x = layers.data("x", shape=[4])
        h = layers.fc(x, size=16, act="relu")  # 8 -> 16
    with pytest.raises(Exception, match="mismatch|missing"):
        load_persistables(exe, str(tmp_path / "c"), main2,
                          scope=Scope())


def test_inference_model_roundtrip(tmp_path):
    main, startup, exe, pred, loss = _build_and_train(steps=8, seed=3)
    xv = np.random.RandomState(9).rand(4, 4).astype(np.float32)
    want, = exe.run(main.clone(for_test=True), feed={
        "x": xv, "y": np.zeros((4, 1), np.float32)},
        fetch_list=[pred])

    save_inference_model(str(tmp_path / "m"), ["x"], [pred], exe, main)

    # reload into a fresh scope — as an inference process would
    fresh = Scope()
    prog, feed_names, fetch_vars = load_inference_model(
        str(tmp_path / "m"), exe, scope=fresh)
    assert feed_names == ["x"]
    with fluid.scope_guard(fresh):
        got, = exe.run(prog, feed={"x": xv}, fetch_list=fetch_vars)
    np.testing.assert_allclose(got, want, rtol=1e-5)
    # pruned program must not contain label/loss/optimizer machinery
    op_types = [op.type for op in prog.global_block().ops]
    assert "adam" not in op_types
    assert all("grad" not in t for t in op_types), op_types


def test_inference_model_strips_train_only_vars(tmp_path):
    main, startup, exe, pred, loss = _build_and_train(steps=2, seed=4)
    save_inference_model(str(tmp_path / "m2"), ["x"], [pred], exe, main)
    prog, _, _ = load_inference_model(str(tmp_path / "m2"), exe,
                                      scope=Scope())
    names = set()
    for b in prog.blocks:
        names.update(b.vars)
    assert not any("moment" in n or "@GRAD" in n for n in names), names


class TestCheckpointSaver:
    """Async + preemption-aware checkpointing (reference analog: the
    PS checkpoint_notify path, distribute_transpiler.py:1612; here
    atomic marker-gated dirs + background writes)."""

    def _model(self, seed=9):
        main, startup = fluid.Program(), fluid.Program()
        main.random_seed = startup.random_seed = seed
        # fresh name counters: a restarted process rebuilds the model
        # with identical var names (the restore contract)
        with fluid.unique_name.guard():
            with fluid.program_guard(main, startup):
                x = layers.data("x", shape=[4],
                                append_batch_size=False)
                w = layers.create_parameter(shape=(4,),
                                            dtype="float32", name="w")
                loss = layers.reduce_sum(layers.square(x - w))
                fluid.optimizer.SGD(0.1).minimize(loss)
        return main, startup, loss

    def test_async_save_restore_roundtrip(self, tmp_path):
        scope = fluid.Scope()
        with fluid.scope_guard(scope):
            main, startup, loss = self._model()
            exe = fluid.Executor()
            exe.run(startup)
            saver = fluid.io.CheckpointSaver(str(tmp_path), main,
                                             max_to_keep=2,
                                             scope=scope)
            x = np.ones(4, np.float32)
            snaps = {}
            for step in range(1, 5):
                exe.run(main, feed={"x": x}, fetch_list=[loss])
                h = saver.save(step)
                snaps[step] = np.asarray(
                    scope.find_var("w")).copy()
                if h:
                    h.wait()
            # pruned to the last 2 complete checkpoints
            assert saver.list_checkpoints() == [3, 4]
        # fresh scope restore
        scope2 = fluid.Scope()
        with fluid.scope_guard(scope2):
            main2, startup2, _ = self._model()
            exe2 = fluid.Executor()
            exe2.run(startup2)
            saver2 = fluid.io.CheckpointSaver(str(tmp_path), main2,
                                              scope=scope2)
            step = saver2.restore_latest(exe2)
            assert step == 4
            np.testing.assert_allclose(
                np.asarray(scope2.find_var("w")), snaps[4])

    def test_incomplete_checkpoint_skipped(self, tmp_path):
        scope = fluid.Scope()
        with fluid.scope_guard(scope):
            main, startup, loss = self._model()
            exe = fluid.Executor()
            exe.run(startup)
            saver = fluid.io.CheckpointSaver(str(tmp_path), main,
                                             scope=scope)
            exe.run(main, feed={"x": np.ones(4, np.float32)},
                    fetch_list=[loss])
            saver.save(1, sync=True)
            good = np.asarray(scope.find_var("w")).copy()
            exe.run(main, feed={"x": np.ones(4, np.float32)},
                    fetch_list=[loss])
            saver.save(2, sync=True)
            # simulate preemption mid-save: marker never written
            import os as _os
            _os.remove(str(tmp_path / "ckpt-2" /
                           fluid.io.CheckpointSaver.MARKER))
            assert saver.list_checkpoints() == [1]
        scope2 = fluid.Scope()
        with fluid.scope_guard(scope2):
            main2, startup2, _ = self._model()
            exe2 = fluid.Executor()
            exe2.run(startup2)
            saver2 = fluid.io.CheckpointSaver(str(tmp_path), main2,
                                              scope=scope2)
            assert saver2.restore_latest(exe2) == 1
            np.testing.assert_allclose(
                np.asarray(scope2.find_var("w")), good)

    def test_snapshot_isolated_from_later_updates(self, tmp_path):
        """The snapshot happens at save() call time — training steps
        racing the background write must not corrupt it."""
        scope = fluid.Scope()
        with fluid.scope_guard(scope):
            main, startup, loss = self._model()
            exe = fluid.Executor()
            exe.run(startup)
            exe.run(main, feed={"x": np.ones(4, np.float32)},
                    fetch_list=[loss])
            saver = fluid.io.CheckpointSaver(str(tmp_path), main,
                                             scope=scope)
            at_save = np.asarray(scope.find_var("w")).copy()
            h = saver.save(1)
            for _ in range(5):  # keep training while it writes
                exe.run(main, feed={"x": np.ones(4, np.float32)},
                        fetch_list=[loss])
            if h:
                h.wait()
        scope2 = fluid.Scope()
        with fluid.scope_guard(scope2):
            main2, startup2, _ = self._model()
            exe2 = fluid.Executor()
            exe2.run(startup2)
            fluid.io.CheckpointSaver(
                str(tmp_path), main2,
                scope=scope2).restore_latest(exe2)
            np.testing.assert_allclose(
                np.asarray(scope2.find_var("w")), at_save)
