"""Transformer-base NMT training (the flagship benchmark config) with
bf16 AMP and optional Megatron-style tensor parallelism.

Run small on CPU:
  JAX_PLATFORMS=cpu python examples/train_transformer.py --small
Multi-device data+tensor parallel (8 virtual CPU devices):
  XLA_FLAGS=--xla_force_host_platform_device_count=8 \
  JAX_PLATFORMS=cpu python examples/train_transformer.py --small --tp 2
"""
import argparse

import numpy as np

import paddle_tpu as fluid
from paddle_tpu import layers  # noqa: F401
from paddle_tpu.contrib import mixed_precision as amp
from paddle_tpu.models import transformer as T


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--small", action="store_true")
    ap.add_argument("--tp", type=int, default=1,
                    help="tensor-parallel ways (shards attention/ffn)")
    ap.add_argument("--steps", type=int, default=10)
    args = ap.parse_args()

    if args.small:
        cfg = T.TransformerConfig(src_vocab=1000, tgt_vocab=1000,
                                  max_len=32, d_model=64, d_ffn=128,
                                  n_head=4, n_layer=2)
        batch = 8
    else:
        cfg = T.TransformerConfig()  # transformer-base
        batch = 64

    main_prog, startup = fluid.Program(), fluid.Program()
    main_prog.random_seed = startup.random_seed = 1
    with fluid.program_guard(main_prog, startup):
        avg_cost, token_num, _ = T.transformer(cfg)
        opt = amp.decorate(fluid.optimizer.Adam(learning_rate=1e-3))
        opt.minimize(avg_cost)

    exe = fluid.Executor()
    exe.run(startup)

    prog = main_prog
    if args.tp > 1:
        T.shard_tp(main_prog)
        import jax
        dp = max(jax.device_count() // args.tp, 1)
        prog = fluid.CompiledProgram(main_prog).with_data_parallel(
            loss_name=avg_cost.name, axes={"dp": dp, "tp": args.tp})

    feed = T.make_fake_batch(cfg, batch)
    for step in range(args.steps):
        lv, = exe.run(prog, feed=feed, fetch_list=[avg_cost])
        print("step %d: loss=%.4f" % (step, float(np.ravel(lv)[0])))

    if args.tp == 1:
        # beam-search inference with the trained weights: the decode
        # program shares parameter names with the training graph
        from paddle_tpu import unique_name
        with unique_name.guard():
            dec = fluid.Program()
            with fluid.program_guard(dec, fluid.Program()):
                out_ids, out_scores = T.fast_decode(
                    cfg, beam_size=2,
                    max_out_len=min(8, cfg.max_len - 1))
        ids, scores = exe.run(
            dec, feed={"src_ids": feed["src_ids"],
                       "src_mask": feed["src_mask"]},
            fetch_list=[out_ids, out_scores])
        print("decoded[0], best beam:", ids[0, 0].tolist(),
              "score %.3f" % scores[0, 0])


if __name__ == "__main__":
    main()
