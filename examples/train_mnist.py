"""Minimal train/eval loop — the fluid book's recognize_digits flow.

Run: JAX_PLATFORMS=cpu python examples/train_mnist.py   (or on TPU,
leave the backend alone). Uses the real MNIST idx files when present
under DATA_HOME (paddle_tpu/dataset/mnist.py), synthetic otherwise.
"""
import sys

import numpy as np

import paddle_tpu as fluid
from paddle_tpu import layers
from paddle_tpu.dataset import mnist


def main():
    main_prog, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main_prog, startup):
        img = layers.data("img", shape=[784], dtype="float32")
        label = layers.data("label", shape=[1], dtype="int64")
        h = layers.fc(img, size=200, act="relu")
        h = layers.fc(h, size=200, act="relu")
        pred = layers.fc(h, size=10, act="softmax")
        loss = layers.mean(layers.cross_entropy(pred, label))
        acc = layers.accuracy(pred, label)
        test_prog = main_prog.clone(for_test=True)
        fluid.optimizer.Adam(learning_rate=1e-3).minimize(loss)

    exe = fluid.Executor()
    exe.run(startup)

    reader = fluid.reader.batch(mnist.train(), batch_size=128)
    feeder = fluid.DataFeeder(feed_list=[img, label],
                              place=fluid.CPUPlace(),
                              program=main_prog)
    for epoch in range(2):
        for step, batch in enumerate(reader()):
            lv, av = exe.run(main_prog, feed=feeder.feed(batch),
                             fetch_list=[loss, acc])
            if step % 100 == 0:
                print("epoch %d step %d: loss=%.4f acc=%.3f"
                      % (epoch, step, float(np.ravel(lv)[0]),
                         float(np.ravel(av)[0])))
            if step >= 300:
                break

    # eval with the test clone (deterministic, dropout off)
    test_batch = next(iter(fluid.reader.batch(mnist.test(), 256)()))
    lv, av = exe.run(test_prog, feed=feeder.feed(test_batch),
                     fetch_list=[loss, acc])
    print("eval: loss=%.4f acc=%.3f"
          % (float(np.ravel(lv)[0]), float(np.ravel(av)[0])))

    model_dir = sys.argv[1] if len(sys.argv) > 1 else "/tmp/mnist_model"
    fluid.io.save_inference_model(model_dir, ["img"], [pred],
                                  exe, main_program=main_prog)
    print("saved inference model to %s" % model_dir)


if __name__ == "__main__":
    main()
