"""Deployment flow: load a saved inference model through the
AnalysisPredictor (graph fusion passes at load, shared-program clone
for concurrent streams) — the reference's paddle_inference_api usage.

Run AFTER examples/train_mnist.py:
  JAX_PLATFORMS=cpu python examples/deploy_inference.py
"""
import sys

import numpy as np

from paddle_tpu.inference import (AnalysisConfig,
                                  create_paddle_predictor)


def main():
    model_dir = sys.argv[1] if len(sys.argv) > 1 else "/tmp/mnist_model"
    config = AnalysisConfig(model_dir)
    config.switch_ir_optim(True)
    predictor = create_paddle_predictor(config)

    img = np.random.rand(4, 784).astype(np.float32)
    out, = predictor.predict({"img": img})
    print("probabilities:", np.round(out[0], 3))
    print("argmax:", out.argmax(axis=1))

    # clone() shares the compiled program — per-thread streams
    worker = predictor.clone()
    out2, = worker.predict({"img": img})
    assert np.allclose(out, out2)
    print("clone agrees")


if __name__ == "__main__":
    main()
