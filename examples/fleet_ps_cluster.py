"""Parameter-server cluster on localhost: 1 pserver + 2 trainers over
the C++ framed-TCP transport (native/tensor_rpc.cpp) — the reference's
fleet workflow (init -> distributed_optimizer -> init_server/run_server
on the server; init_worker -> exe.run(fleet.main_program) ->
stop_worker on trainers).

Run (spawns its own role subprocesses):
  JAX_PLATFORMS=cpu python examples/fleet_ps_cluster.py
"""
import os
import socket
import subprocess
import sys

import numpy as np


def role_main():
    import paddle_tpu as fluid
    from paddle_tpu import layers
    from paddle_tpu.incubate.fleet.base.role_maker import (
        Role, UserDefinedRoleMaker)
    from paddle_tpu.incubate.fleet.parameter_server import (
        ParameterServerFleet)

    ep = os.environ["PS_ENDPOINT"]
    role_name = os.environ["PS_ROLE"]
    rid = int(os.environ.get("PS_ID", "0"))
    n_workers = 2

    fleet = ParameterServerFleet()
    fleet.init(UserDefinedRoleMaker(
        current_id=rid,
        role=Role.SERVER if role_name == "server" else Role.WORKER,
        worker_num=n_workers, server_endpoints=[ep]))

    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = 11
    with fluid.program_guard(main, startup):
        x = layers.data("x", shape=[13], dtype="float32")
        y = layers.data("y", shape=[1], dtype="float32")
        pred = layers.fc(x, size=1)
        loss = layers.reduce_mean(layers.square_error_cost(pred, y))
        opt = fleet.distributed_optimizer(
            fluid.optimizer.SGD(learning_rate=0.01))
        opt.minimize(loss)

    if role_name == "server":
        fleet.init_server()
        fleet.run_server()      # serves until the launcher kills us
        return

    exe = fluid.Executor()
    exe.run(startup)
    fleet.init_worker()   # adopt server-side init AFTER local startup
    rs = np.random.RandomState(rid)
    for step in range(5):
        xb = rs.rand(16, 13).astype(np.float32)
        yb = xb.sum(1, keepdims=True).astype(np.float32)
        lv, = exe.run(fleet.main_program, feed={"x": xb, "y": yb},
                      fetch_list=[loss])
        print("trainer %d step %d loss=%.5f"
              % (rid, step, float(np.ravel(lv)[0])), flush=True)
    fleet.stop_worker()


def launcher():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    ep = "127.0.0.1:%d" % s.getsockname()[1]
    s.close()

    def spawn(role, rid):
        env = dict(os.environ, PS_ENDPOINT=ep, PS_ROLE=role,
                   PS_ID=str(rid))
        return subprocess.Popen([sys.executable, __file__], env=env)

    server = spawn("server", 0)
    trainers = [spawn("worker", i) for i in range(2)]
    rc = 1
    try:
        rc = 0
        for p in trainers:
            rc |= p.wait(timeout=300)
    finally:
        # the pserver serves forever by design; never orphan it (it
        # would hold the inherited stdout pipe open past our exit)
        server.terminate()
    print("trainers done rc=%d" % rc)
    sys.exit(rc)


if __name__ == "__main__":
    if os.environ.get("PS_ROLE"):
        role_main()
    else:
        launcher()
