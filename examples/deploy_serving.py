"""Serving-engine deploy: load a saved inference model into the
micro-batching ServingEngine, fire concurrent ragged-batch clients at
it, verify bit-equality against the single-request predictor, and
print the latency/occupancy SLO stats.

Run AFTER examples/train_mnist.py:
  JAX_PLATFORMS=cpu python examples/deploy_serving.py /tmp/mnist_model
"""
import json
import sys
import threading

import numpy as np

from paddle_tpu.inference import AnalysisConfig, create_paddle_predictor
from paddle_tpu.serving import (ServingConfig, ServingEngine,
                                bucket_for, bucket_sizes, pad_batch)


def main():
    model_dir = sys.argv[1] if len(sys.argv) > 1 else "/tmp/mnist_model"
    engine = ServingEngine(model_dir, ServingConfig(
        max_batch_size=16, max_queue_wait_us=3000))
    reference = create_paddle_predictor(AnalysisConfig(model_dir))

    results = []
    lock = threading.Lock()

    def client(seed):
        r = np.random.RandomState(seed)
        for _ in range(4):
            n = int(r.randint(1, 9))  # ragged client batch sizes
            feed = {"img": r.rand(n, 784).astype(np.float32)}
            out = engine.infer_sync(feed, timeout=60)
            with lock:
                results.append((feed, out))

    threads = [threading.Thread(target=client, args=(i,))
               for i in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()

    # concurrent coalesced results agree with the single-request
    # predictor (different device batch sizes may differ by 1 ulp in
    # XLA's gemm summation order, hence allclose, not array_equal)
    for feed, out in results:
        (expect,) = reference.predict(feed)
        assert np.allclose(np.asarray(expect), out[0], atol=1e-6)
    print("serving engine agrees (%d concurrent requests)"
          % len(results))

    # bit-exactness proof: a lone request executes exactly the padded
    # bucket the reference would — split/unpad is lossless
    r = np.random.RandomState(99)
    feed = {"img": r.rand(3, 784).astype(np.float32)}
    out = engine.infer_sync(feed, timeout=60)
    bucket = bucket_for(3, bucket_sizes(16))
    (expect,) = reference.predict(pad_batch(feed, 3, bucket))
    assert np.array_equal(np.asarray(expect)[:3], out[0])
    print("split/unpad bit-exact vs padded reference")

    stats = engine.stats()
    engine.shutdown(drain=True)
    print("serving stats:", json.dumps(stats))
    assert stats["compiles"] <= len(stats["buckets"])
    print("bounded compiles: %d executables for %d requests"
          % (stats["compiles"], stats["completed"]))


if __name__ == "__main__":
    main()
