"""The parallelism matrix in one script: sequence parallelism (ring /
Ulysses attention), pipeline parallelism (GPipe), and expert
parallelism (Switch MoE) — each on its own mesh axis, each checked
against its single-device reference. The dp/tp axes are shown by
examples/train_transformer.py; together these cover dp x tp x sp x
pp x ep.

Run on 8 virtual CPU devices:
  XLA_FLAGS=--xla_force_host_platform_device_count=8 \
  JAX_PLATFORMS=cpu python examples/parallelism_matrix.py
"""
import numpy as np

import jax
import jax.numpy as jnp

from paddle_tpu.parallel import (gpipe_apply, make_mesh, moe_ffn,
                                 moe_ffn_reference, ring_attention_fn,
                                 stack_stage_params,
                                 ulysses_attention_fn)
from paddle_tpu.parallel.ulysses import _full_attention


def main():
    rs = np.random.RandomState(0)
    n = min(len(jax.devices()), 8)

    # --- sequence parallelism: ring + Ulysses over sp ------------------
    sp = 4 if n >= 4 else n
    mesh = make_mesh({"sp": sp}, jax.devices()[:sp])
    B, H, S, Dh = 2, 8, 256, 32
    q, k, v = (jnp.asarray(rs.randn(B, H, S, Dh).astype(np.float32))
               * 0.3 for _ in range(3))
    want = _full_attention(q, k, v, 0.5, True)
    for name, fn in (("ring", ring_attention_fn),
                     ("ulysses", ulysses_attention_fn)):
        got = fn(q, k, v, mesh=mesh, scale=0.5, causal=True)
        err = float(jnp.max(jnp.abs(got - want)))
        print("sp/%s attention (sp=%d, S=%d): max|err|=%.2e"
              % (name, sp, S, err))
        assert err < 1e-4

    # --- pipeline parallelism: GPipe over pp ---------------------------
    pp = 4 if n >= 4 else n
    mesh = make_mesh({"pp": pp}, jax.devices()[:pp])
    D = 32
    stages = stack_stage_params(
        [{"w": jnp.asarray(rs.randn(D, D).astype(np.float32) * 0.4),
          "b": jnp.zeros((D,), jnp.float32)} for _ in range(pp)])
    x = jnp.asarray(rs.randn(16, D).astype(np.float32))

    def stage(p, h):
        return jnp.tanh(h @ p["w"] + p["b"])

    got = gpipe_apply(stage, stages, x, mesh=mesh, n_micro=8)
    want = gpipe_apply(stage, stages, x, mesh=None, n_micro=8)
    err = float(jnp.max(jnp.abs(got - want)))
    print("pp/gpipe (pp=%d, micro=8): max|err|=%.2e" % (pp, err))
    assert err < 1e-5

    # --- expert parallelism: Switch MoE over ep ------------------------
    ep = 4 if n >= 4 else n
    mesh = make_mesh({"ep": ep}, jax.devices()[:ep])
    E, F, N = 8, 64, 64
    wt = dict(
        gate_w=jnp.asarray(rs.randn(D, E).astype(np.float32)),
        w1=jnp.asarray(rs.randn(E, D, F).astype(np.float32) * 0.2),
        b1=jnp.zeros((E, F), jnp.float32),
        w2=jnp.asarray(rs.randn(E, F, D).astype(np.float32) * 0.2),
        b2=jnp.zeros((E, D), jnp.float32))
    toks = jnp.asarray(rs.randn(N, D).astype(np.float32))
    got, aux = moe_ffn(toks, mesh=mesh, capacity_factor=float(E), **wt)
    want, aux_ref = moe_ffn_reference(toks, capacity_factor=float(E),
                                      **wt)
    err = float(jnp.max(jnp.abs(got - want)))
    print("ep/moe (ep=%d, E=%d): max|err|=%.2e aux=%.4f" %
          (ep, E, err, float(aux)))
    assert err < 1e-5 and abs(float(aux) - float(aux_ref)) < 1e-5
    print("parallelism matrix OK")


if __name__ == "__main__":
    main()
