"""Pass base + registry (reference: framework/ir/pass.h:34 `Pass`,
`PassRegistry`:145, REGISTER_PASS macro; build_strategy.cc drives pass
sequences)."""

from __future__ import annotations

from typing import Dict, List, Type

from ..core.enforce import AlreadyExistsError, NotFoundError, enforce
from .graph import Graph


class Pass:
    """A graph→graph transform. Subclasses set ``name`` and implement
    ``apply_impl``; attributes the pass needs (scope, place, …) are
    injected with ``set`` (the reference's Set/Get pass-attribute
    protocol, pass.h:51)."""

    name = None

    def __init__(self):
        self._attrs: Dict[str, object] = {}

    def set(self, key, value):
        self._attrs[key] = value
        return self

    def get(self, key, default=None):
        return self._attrs.get(key, default)

    def require(self, key):
        enforce(key in self._attrs,
                "pass %r requires attribute %r" % (self.name, key))
        return self._attrs[key]

    def apply(self, graph: Graph) -> Graph:
        enforce(isinstance(graph, Graph), "Pass.apply takes an ir.Graph")
        out = self.apply_impl(graph)
        return out if out is not None else graph

    def apply_impl(self, graph: Graph) -> Graph:
        raise NotImplementedError


_registry: Dict[str, Type[Pass]] = {}


def register_pass(cls: Type[Pass]) -> Type[Pass]:
    """Class decorator — the REGISTER_PASS macro analog."""
    enforce(cls.name, "pass class %s needs a `name`" % cls.__name__)
    if cls.name in _registry:
        raise AlreadyExistsError("pass %r already registered" % cls.name)
    _registry[cls.name] = cls
    return cls


def get_pass(name: str, **attrs) -> Pass:
    if name not in _registry:
        raise NotFoundError("no pass named %r (have: %s)" %
                            (name, ", ".join(sorted(_registry))))
    p = _registry[name]()
    for k, v in attrs.items():
        p.set(k, v)
    return p


def all_pass_names() -> List[str]:
    return sorted(_registry)


class PassManager:
    """Ordered pass sequence (reference: inference/analysis
    ir_pass_manager.cc / build_strategy.cc pass assembly)."""

    def __init__(self, passes=None):
        self.passes: List[Pass] = []
        for p in passes or []:
            self.add(p)

    def add(self, p):
        self.passes.append(get_pass(p) if isinstance(p, str) else p)
        return self

    def apply(self, graph: Graph) -> Graph:
        for p in self.passes:
            graph = p.apply(graph)
        return graph


def apply_passes(program, names, block_idx=0, **attrs):
    """Convenience: Program → Graph → passes → Program (in place)."""
    graph = Graph(program, block_idx)
    for name in names:
        graph = get_pass(name, **attrs).apply(graph)
    return graph.to_program()
