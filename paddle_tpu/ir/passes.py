"""Standard passes (reference: framework/ir/*_pass.cc).

- fuse_elewise_add_act_pass  <- ir/fuse_elewise_add_act_pass.cc
- fc_fuse_pass               <- ir/fc_fuse_pass.cc
- conv_bn_fuse_pass          <- ir/conv_bn_fuse_pass.cc (folds trained
                                BN statistics into conv weights; needs
                                the scope — a semantic rewrite XLA
                                cannot perform)
- graph_viz_pass             <- ir/graph_viz_pass.cc (graphviz dot)
"""

from __future__ import annotations

import numpy as np

from .graph import Graph, Node
from .pass_base import Pass, register_pass
from .pattern_detector import GraphPatternDetector, PDNode

_ACTS = ("relu", "sigmoid", "tanh", "gelu")
_HOUSEKEEPING_ATTRS = ("op_role", "op_namescope")


def _act_attrs(op):
    return {k: v for k, v in op.attrs.items()
            if k not in _HOUSEKEEPING_ATTRS}


def _slot_of(op, var_name, which="inputs"):
    for slot, names in getattr(op, which).items():
        if var_name in names:
            return slot
    return None


@register_pass
class FuseElewiseAddActPass(Pass):
    """elementwise_add → act  ⇒  fused_elemwise_activation."""

    name = "fuse_elewise_add_act_pass"

    def apply_impl(self, graph: Graph) -> Graph:
        det = GraphPatternDetector()
        det.node(PDNode.op("add", "elementwise_add"))
        det.node(PDNode.var("mid", intermediate=True))
        det.node(PDNode.op("act", _ACTS))
        det.node(PDNode.var("out"))
        det.link("add", "mid").link("mid", "act").link("act", "out")

        def rewrite(m, g):
            add_op, act_op = m["add"].op, m["act"].op
            x_name = add_op.input("X")[0]
            y_name = add_op.input("Y")[0]
            xs = [n for n in m["add"].inputs if n.name == x_name]
            ys = [n for n in m["add"].inputs if n.name == y_name]
            g.create_op_node(
                "fused_elemwise_activation",
                {"X": [xs[0]], "Y": [ys[0]]},
                {"Out": [m["out"]]},
                {"functor_list": ["elementwise_add", act_op.type],
                 "axis": add_op.attrs.get("axis", -1),
                 # the activation's own attrs ride along so fusion
                 # never changes numerics (gelu approximate=False)
                 "act_attrs": _act_attrs(act_op)})
            g.remove_nodes([m["add"], m["mid"], m["act"]])

        count = det.apply(graph, rewrite)
        self.set("fused_count", count)
        return graph


@register_pass
class FCFusePass(Pass):
    """mul → elementwise_add(bias) [→ act]  ⇒  fc op.

    The bias must be a persistable parameter (the fc layer's bias), the
    mul must be the standard x_num_col_dims projection."""

    name = "fc_fuse_pass"

    def apply_impl(self, graph: Graph) -> Graph:
        total = 0
        for with_act in (True, False):
            det = GraphPatternDetector()
            det.node(PDNode.op("mul", "mul"))
            det.node(PDNode.var("mul_out", intermediate=True))
            det.node(PDNode.op("add", "elementwise_add"))
            det.link("mul", "mul_out").link("mul_out", "add")
            if with_act:
                det.node(PDNode.var("add_out", intermediate=True))
                det.node(PDNode.op("act", _ACTS))
                det.node(PDNode.var("out"))
                det.link("add", "add_out").link("add_out", "act")
                det.link("act", "out")
            else:
                det.node(PDNode.var("out"))
                det.link("add", "out")

            def rewrite(m, g, with_act=with_act):
                mul_op, add_op = m["mul"].op, m["add"].op
                # the fc op flattens only its Input; a mul with
                # y_num_col_dims != 1 (W folded from >2-D) has no fc
                # equivalent — leave it unfused
                if mul_op.attrs.get("y_num_col_dims", 1) != 1:
                    return
                wv = m["mul"].op.input("Y")[0]
                wvar = g.program.block(g.block_idx) \
                    ._find_var_recursive(wv)
                if wvar is not None and wvar.shape and \
                        len(wvar.shape) != 2:
                    return
                # bias: the add input that ISN'T the mul result
                mul_out_name = mul_op.output("Out")[0]
                bias_name = next(n for n in add_op.input_arg_names
                                 if n != mul_out_name)
                bias_nodes = [n for n in m["add"].inputs
                              if n.name == bias_name]
                if not bias_nodes or not bias_nodes[0].persistable:
                    return
                if with_act and _act_attrs(m["act"].op):
                    # the fc op has no attr channel for the activation
                    # (activation_type is a bare name); refuse rather
                    # than silently change numerics
                    return
                x_name = mul_op.input("X")[0]
                w_name = mul_op.input("Y")[0]
                xn = next(n for n in m["mul"].inputs
                          if n.name == x_name)
                wn = next(n for n in m["mul"].inputs
                          if n.name == w_name)
                act = m["act"].op.type if with_act else ""
                g.create_op_node(
                    "fc",
                    {"Input": [xn], "W": [wn], "Bias": [bias_nodes[0]]},
                    {"Out": [m["out"]]},
                    {"in_num_col_dims":
                     mul_op.attrs.get("x_num_col_dims", 1),
                     "activation_type": act})
                dead = [m["mul"], m["mul_out"], m["add"]]
                if with_act:
                    dead += [m["add_out"], m["act"]]
                g.remove_nodes(dead)

            total += det.apply(graph, rewrite)
        self.set("fused_count", total)
        return graph


@register_pass
class ConvBNFusePass(Pass):
    """conv2d → batch_norm(is_test)  ⇒  conv2d(W′) → elementwise_add(b′)

    W′[o] = W[o] · γ[o]/√(σ²[o]+ε),  b′[o] = β[o] − μ[o]·γ[o]/√(σ²[o]+ε)

    Rewrites the *trained parameter values* in the scope (pass attr
    "scope") — the reference's conv_bn_fuse_pass.cc:169 recompute. Only
    valid for inference programs (running stats frozen)."""

    name = "conv_bn_fuse_pass"

    def apply_impl(self, graph: Graph) -> Graph:
        scope = self.require("scope")
        det = GraphPatternDetector()
        det.node(PDNode.op("conv", ("conv2d", "depthwise_conv2d")))
        det.node(PDNode.var("conv_out", intermediate=True))
        det.node(PDNode.op("bn", "batch_norm"))
        det.node(PDNode.var("y"))
        det.link("conv", "conv_out").link("conv_out", "bn")
        det.link("bn", "y")
        count = 0

        def rewrite(m, g):
            nonlocal count
            bn_op = m["bn"].op
            if not bn_op.attrs.get("is_test", False):
                return
            # bn's Y must be the matched output (not a stats output)
            if m["y"].name != bn_op.output("Y")[0]:
                return
            conv_op = m["conv"].op
            w_name = conv_op.input("Filter")[0]
            names = {s: bn_op.input(s)[0]
                     for s in ("Scale", "Bias", "Mean", "Variance")}
            vals = {k: np.asarray(scope.find_var(n))
                    for k, n in names.items()}
            w = np.asarray(scope.find_var(w_name))
            eps = bn_op.attrs.get("epsilon", 1e-5)
            istd = 1.0 / np.sqrt(vals["Variance"] + eps)
            gamma = vals["Scale"] * istd                 # [C_out]
            w_new = w * gamma.reshape(-1, 1, 1, 1)
            b_new = vals["Bias"] - vals["Mean"] * gamma
            scope.set_var(w_name, w_new.astype(w.dtype))

            # new bias param var reuses the BN beta var's storage slot
            bias_name = names["Bias"]
            scope.set_var(bias_name, b_new.astype(w.dtype))
            bias_node = next(n for n in m["bn"].inputs
                             if n.name == bias_name)
            g.create_op_node(
                "elementwise_add",
                {"X": [m["conv_out"]], "Y": [bias_node]},
                {"Out": [m["y"]]},
                {"axis": 1 if conv_op.attrs.get(
                    "data_format", "NCHW") == "NCHW" else -1})
            # keep conv + its output var; drop only the bn op (its
            # stats outputs become dead writes)
            dead_outs = [n for n in m["bn"].outputs if n is not m["y"]
                         and not n.outputs]
            g.remove_nodes([m["bn"]] + dead_outs)
            # conv_out is consumed by the new add now — it was matched
            # as intermediate but stays alive
            count += 1

        det.apply(graph, rewrite)
        self.set("fused_count", count)
        return graph


@register_pass
class GraphVizPass(Pass):
    """Dump the graph as graphviz dot (reference: ir/graph_viz_pass.cc;
    FLAGS_print_sub_graph_dir). Pass attr "path" = output file."""

    name = "graph_viz_pass"

    def apply_impl(self, graph: Graph) -> Graph:
        path = self.require("path")
        lines = ["digraph G {", "  rankdir=TB;"]
        ids = {}
        for i, n in enumerate(graph.nodes):
            ids[id(n)] = "n%d" % i
            if n.is_op():
                lines.append(
                    '  n%d [label="%s" shape=box style=filled '
                    'fillcolor="#90EE90"];' % (i, n.op.type))
            else:
                shape = "ellipse" if not n.persistable else "octagon"
                lines.append('  n%d [label="%s" shape=%s];'
                             % (i, n.name, shape))
        for n in graph.nodes:
            if n.is_op():
                for v in n.inputs:
                    lines.append("  %s -> %s;" % (ids[id(v)],
                                                  ids[id(n)]))
                for v in n.outputs:
                    lines.append("  %s -> %s;" % (ids[id(n)],
                                                  ids[id(v)]))
        lines.append("}")
        with open(path, "w") as f:
            f.write("\n".join(lines))
        return graph


@register_pass
class ConvElementwiseAddFusePass(Pass):
    """conv2d → elementwise_add(persistable bias)  ⇒  conv2d_fusion
    (reference: ir/conv_elementwise_add_fuse_pass.cc). Composes with
    conv_bn_fuse_pass, whose output is exactly this pattern."""

    name = "conv_elementwise_add_fuse_pass"

    def apply_impl(self, graph: Graph) -> Graph:
        det = GraphPatternDetector()
        det.node(PDNode.op("conv", ("conv2d", "depthwise_conv2d")))
        det.node(PDNode.var("conv_out", intermediate=True))
        det.node(PDNode.op("add", "elementwise_add"))
        det.node(PDNode.var("out"))
        det.link("conv", "conv_out").link("conv_out", "add")
        det.link("add", "out")
        count = 0

        def rewrite(m, g):
            nonlocal count
            conv_op, add_op = m["conv"].op, m["add"].op
            conv_out_name = conv_op.output("Output")[0]
            bias_name = next(n for n in add_op.input_arg_names
                             if n != conv_out_name)
            bias_nodes = [n for n in m["add"].inputs
                          if n.name == bias_name]
            if not bias_nodes or not bias_nodes[0].persistable:
                return
            # conv2d_fusion re-applies the bias PER CHANNEL, so the
            # add must be exactly a per-channel [C_out] broadcast:
            # axis 1 for NCHW (the conv builders emit this), and a
            # 1-D bias of length C_out. An axis=-1 trailing broadcast
            # over W would silently change numerics.
            data_format = conv_op.attrs.get("data_format", "NCHW")
            want_axis = 1 if data_format == "NCHW" else -1
            if add_op.attrs.get("axis", -1) != want_axis:
                return
            blk = g.program.block(g.block_idx)
            bvar = blk._find_var_recursive(bias_name)
            wvar = blk._find_var_recursive(conv_op.input("Filter")[0])
            if (bvar is None or wvar is None
                    or not bvar.shape or not wvar.shape):
                return
            c_out = wvar.shape[0]
            if tuple(bvar.shape) != (c_out,):
                return
            x_name = conv_op.input("Input")[0]
            w_name = conv_op.input("Filter")[0]
            xn = next(n for n in m["conv"].inputs if n.name == x_name)
            wn = next(n for n in m["conv"].inputs if n.name == w_name)
            attrs = {k: v for k, v in conv_op.attrs.items()
                     if k not in _HOUSEKEEPING_ATTRS}
            if (conv_op.type == "depthwise_conv2d"
                    and not attrs.get("groups")):
                # depthwise defaults groups to C_in at run time; the
                # fused op lowers through plain conv2d, so pin it
                xvar = blk._find_var_recursive(x_name)
                if xvar is None or not xvar.shape:
                    return
                attrs["groups"] = xvar.shape[
                    1 if data_format == "NCHW" else -1]
            attrs["activation"] = ""
            g.create_op_node(
                "conv2d_fusion",
                {"Input": [xn], "Filter": [wn],
                 "Bias": [bias_nodes[0]]},
                {"Output": [m["out"]]}, attrs)
            g.remove_nodes([m["conv"], m["conv_out"], m["add"]])
            count += 1

        det.apply(graph, rewrite)
        self.set("fused_count", count)
        return graph


def _producer(var_node, op_type):
    """The single producing op of ``var_node`` if it has the given
    type and this is its only consumer-visible use."""
    if len(var_node.inputs) != 1:
        return None
    op = var_node.inputs[0]
    if not op.is_op() or op.op.type != op_type:
        return None
    if len(var_node.outputs) != 1:
        return None
    return op


@register_pass
class TransposeFlattenConcatFusePass(Pass):
    """N x (transpose2 → flatten2) → concat  ⇒
    fusion_transpose_flatten_concat (reference:
    ir/transpose_flatten_concat_fuse_pass.cc — the SSD detection-head
    reshaping). All branches must share trans/flatten axes."""

    name = "transpose_flatten_concat_fuse_pass"

    def apply_impl(self, graph: Graph) -> Graph:
        count = 0
        for node in list(graph.nodes):
            if not node.is_op() or node.op.type != "concat":
                continue
            branches = []
            for cin in node.inputs:
                fl = _producer(cin, "flatten2")
                if fl is None:
                    branches = None
                    break
                fin = fl.inputs[0]
                tr = _producer(fin, "transpose2")
                if tr is None:
                    branches = None
                    break
                branches.append((tr, fin, fl, cin))
            if not branches:
                continue
            trans_axis = branches[0][0].op.attrs.get("axis")
            flatten_axis = branches[0][2].op.attrs.get("axis", 1)
            if any(b[0].op.attrs.get("axis") != trans_axis
                   or b[2].op.attrs.get("axis", 1) != flatten_axis
                   for b in branches):
                continue
            xs = [b[0].inputs[0] for b in branches]
            out = node.outputs[0]
            graph.create_op_node(
                "fusion_transpose_flatten_concat",
                {"X": xs}, {"Out": [out]},
                {"trans_axis": tuple(trans_axis),
                 "flatten_axis": flatten_axis,
                 "concat_axis": node.op.attrs.get("axis", 0)})
            dead = [node]
            for tr, fin, fl, cin in branches:
                dead += [tr, fin, fl, cin]
            graph.remove_nodes(dead)
            count += 1
        self.set("fused_count", count)
        return graph


@register_pass
class SeqPoolConcatFusePass(Pass):
    """N x sequence_pool → concat  ⇒  fusion_seqpool_concat
    (reference: ir/seqpool_concat_fuse_pass.cc — CTR slot pooling).
    All pools must share pool_type."""

    name = "seqpool_concat_fuse_pass"

    def apply_impl(self, graph: Graph) -> Graph:
        count = 0
        for node in list(graph.nodes):
            if not node.is_op() or node.op.type != "concat":
                continue
            if node.op.attrs.get("axis", 0) != 1:
                continue
            pools = []
            for cin in node.inputs:
                sp = _producer(cin, "sequence_pool")
                if sp is None:
                    pools = None
                    break
                pools.append((sp, cin))
            if not pools:
                continue
            ptype = pools[0][0].op.attrs.get("pool_type", "average")
            if any(p[0].op.attrs.get("pool_type", "average") != ptype
                   or p[0].op.attrs.get("pad_value", 0.0) != 0.0
                   for p in pools):
                continue
            xs, lens = [], []
            ok = True
            for sp, _cin in pools:
                x_name = sp.op.input("X")[0]
                xs.append(next(n for n in sp.inputs
                               if n.name == x_name))
                ln_names = sp.op.inputs.get("SeqLen", [])
                if ln_names:
                    lens.append(next(n for n in sp.inputs
                                     if n.name == ln_names[0]))
                elif lens:
                    ok = False  # mixed with/without lengths
                    break
            if not ok or (lens and len(lens) != len(xs)):
                continue
            out = node.outputs[0]
            inputs = {"X": xs}
            if lens:
                inputs["SeqLen"] = lens
            graph.create_op_node(
                "fusion_seqpool_concat", inputs, {"Out": [out]},
                {"pooltype": ptype.upper(), "axis": 1})
            dead = [node] + [p[0] for p in pools] + \
                [p[1] for p in pools]
            graph.remove_nodes(dead)
            count += 1
        self.set("fused_count", count)
        return graph


@register_pass
class FCLSTMFusePass(Pass):
    """mul(x, Wx) → lstm  ⇒  fusion_lstm (reference:
    ir/fc_lstm_fuse_pass.cc + operators/fused/fusion_lstm_op.cc: the
    input projection rides inside the scan op). The layers.lstm /
    dynamic_lstm builders emit exactly this mul+lstm shape."""

    name = "fc_lstm_fuse_pass"

    def apply_impl(self, graph: Graph) -> Graph:
        count = 0
        for node in list(graph.nodes):
            if not node.is_op() or node.op.type != "lstm":
                continue
            lstm_op = node.op
            in_name = lstm_op.input("Input")[0]
            proj = next((v for v in node.inputs
                         if v.name == in_name), None)
            if proj is None:
                continue
            mul = _producer(proj, "mul")
            if mul is None:
                continue
            if mul.op.attrs.get("y_num_col_dims", 1) != 1:
                continue
            x_name = mul.op.input("X")[0]
            wx_name = mul.op.input("Y")[0]
            xn = next(n for n in mul.inputs if n.name == x_name)
            wxn = next(n for n in mul.inputs if n.name == wx_name)

            def in_node(slot):
                names = lstm_op.inputs.get(slot, [])
                if not names:
                    return None
                return next(n for n in node.inputs
                            if n.name == names[0])

            wh = in_node("Weight")
            bias = in_node("Bias")
            outs = {s: [next(n for n in node.outputs
                             if n.name == lstm_op.output(s)[0])]
                    for s in ("Hidden", "Cell")}
            # LastH/LastC consumers block the fusion (fusion_lstm has
            # no last-state outputs, reference fusion_lstm_op.cc)
            last_used = False
            for s in ("LastH", "LastC"):
                names = lstm_op.outputs.get(s, [])
                for n in node.outputs:
                    if n.name in names and n.outputs:
                        last_used = True
            if last_used:
                continue
            inputs = {"X": [xn], "WeightX": [wxn], "WeightH": [wh]}
            if bias is not None:
                inputs["Bias"] = [bias]
            for s in ("H0", "C0", "SeqLen"):
                v = in_node(s)
                if v is not None:
                    inputs[s] = [v]
            attrs = {k: v for k, v in lstm_op.attrs.items()
                     if k not in _HOUSEKEEPING_ATTRS}
            graph.create_op_node("fusion_lstm", inputs, outs, attrs)
            dead = [mul, proj, node]
            dead += [n for n in node.outputs
                     if n.name in (lstm_op.outputs.get("LastH", [])
                                   + lstm_op.outputs.get("LastC", []))
                     and not n.outputs]
            graph.remove_nodes(dead)
            count += 1
        self.set("fused_count", count)
        return graph
