"""Standard passes (reference: framework/ir/*_pass.cc).

- fuse_elewise_add_act_pass  <- ir/fuse_elewise_add_act_pass.cc
- fc_fuse_pass               <- ir/fc_fuse_pass.cc
- conv_bn_fuse_pass          <- ir/conv_bn_fuse_pass.cc (folds trained
                                BN statistics into conv weights; needs
                                the scope — a semantic rewrite XLA
                                cannot perform)
- graph_viz_pass             <- ir/graph_viz_pass.cc (graphviz dot)
"""

from __future__ import annotations

import numpy as np

from .graph import Graph, Node
from .pass_base import Pass, register_pass
from .pattern_detector import GraphPatternDetector, PDNode

_ACTS = ("relu", "sigmoid", "tanh", "gelu")
_HOUSEKEEPING_ATTRS = ("op_role", "op_namescope")


def _act_attrs(op):
    return {k: v for k, v in op.attrs.items()
            if k not in _HOUSEKEEPING_ATTRS}


def _slot_of(op, var_name, which="inputs"):
    for slot, names in getattr(op, which).items():
        if var_name in names:
            return slot
    return None


@register_pass
class FuseElewiseAddActPass(Pass):
    """elementwise_add → act  ⇒  fused_elemwise_activation."""

    name = "fuse_elewise_add_act_pass"

    def apply_impl(self, graph: Graph) -> Graph:
        det = GraphPatternDetector()
        det.node(PDNode.op("add", "elementwise_add"))
        det.node(PDNode.var("mid", intermediate=True))
        det.node(PDNode.op("act", _ACTS))
        det.node(PDNode.var("out"))
        det.link("add", "mid").link("mid", "act").link("act", "out")

        def rewrite(m, g):
            add_op, act_op = m["add"].op, m["act"].op
            x_name = add_op.input("X")[0]
            y_name = add_op.input("Y")[0]
            xs = [n for n in m["add"].inputs if n.name == x_name]
            ys = [n for n in m["add"].inputs if n.name == y_name]
            g.create_op_node(
                "fused_elemwise_activation",
                {"X": [xs[0]], "Y": [ys[0]]},
                {"Out": [m["out"]]},
                {"functor_list": ["elementwise_add", act_op.type],
                 "axis": add_op.attrs.get("axis", -1),
                 # the activation's own attrs ride along so fusion
                 # never changes numerics (gelu approximate=False)
                 "act_attrs": _act_attrs(act_op)})
            g.remove_nodes([m["add"], m["mid"], m["act"]])

        count = det.apply(graph, rewrite)
        self.set("fused_count", count)
        return graph


@register_pass
class FCFusePass(Pass):
    """mul → elementwise_add(bias) [→ act]  ⇒  fc op.

    The bias must be a persistable parameter (the fc layer's bias), the
    mul must be the standard x_num_col_dims projection."""

    name = "fc_fuse_pass"

    def apply_impl(self, graph: Graph) -> Graph:
        total = 0
        for with_act in (True, False):
            det = GraphPatternDetector()
            det.node(PDNode.op("mul", "mul"))
            det.node(PDNode.var("mul_out", intermediate=True))
            det.node(PDNode.op("add", "elementwise_add"))
            det.link("mul", "mul_out").link("mul_out", "add")
            if with_act:
                det.node(PDNode.var("add_out", intermediate=True))
                det.node(PDNode.op("act", _ACTS))
                det.node(PDNode.var("out"))
                det.link("add", "add_out").link("add_out", "act")
                det.link("act", "out")
            else:
                det.node(PDNode.var("out"))
                det.link("add", "out")

            def rewrite(m, g, with_act=with_act):
                mul_op, add_op = m["mul"].op, m["add"].op
                # the fc op flattens only its Input; a mul with
                # y_num_col_dims != 1 (W folded from >2-D) has no fc
                # equivalent — leave it unfused
                if mul_op.attrs.get("y_num_col_dims", 1) != 1:
                    return
                wv = m["mul"].op.input("Y")[0]
                wvar = g.program.block(g.block_idx) \
                    ._find_var_recursive(wv)
                if wvar is not None and wvar.shape and \
                        len(wvar.shape) != 2:
                    return
                # bias: the add input that ISN'T the mul result
                mul_out_name = mul_op.output("Out")[0]
                bias_name = next(n for n in add_op.input_arg_names
                                 if n != mul_out_name)
                bias_nodes = [n for n in m["add"].inputs
                              if n.name == bias_name]
                if not bias_nodes or not bias_nodes[0].persistable:
                    return
                if with_act and _act_attrs(m["act"].op):
                    # the fc op has no attr channel for the activation
                    # (activation_type is a bare name); refuse rather
                    # than silently change numerics
                    return
                x_name = mul_op.input("X")[0]
                w_name = mul_op.input("Y")[0]
                xn = next(n for n in m["mul"].inputs
                          if n.name == x_name)
                wn = next(n for n in m["mul"].inputs
                          if n.name == w_name)
                act = m["act"].op.type if with_act else ""
                g.create_op_node(
                    "fc",
                    {"Input": [xn], "W": [wn], "Bias": [bias_nodes[0]]},
                    {"Out": [m["out"]]},
                    {"in_num_col_dims":
                     mul_op.attrs.get("x_num_col_dims", 1),
                     "activation_type": act})
                dead = [m["mul"], m["mul_out"], m["add"]]
                if with_act:
                    dead += [m["add_out"], m["act"]]
                g.remove_nodes(dead)

            total += det.apply(graph, rewrite)
        self.set("fused_count", total)
        return graph


@register_pass
class ConvBNFusePass(Pass):
    """conv2d → batch_norm(is_test)  ⇒  conv2d(W′) → elementwise_add(b′)

    W′[o] = W[o] · γ[o]/√(σ²[o]+ε),  b′[o] = β[o] − μ[o]·γ[o]/√(σ²[o]+ε)

    Rewrites the *trained parameter values* in the scope (pass attr
    "scope") — the reference's conv_bn_fuse_pass.cc:169 recompute. Only
    valid for inference programs (running stats frozen)."""

    name = "conv_bn_fuse_pass"

    def apply_impl(self, graph: Graph) -> Graph:
        scope = self.require("scope")
        det = GraphPatternDetector()
        det.node(PDNode.op("conv", ("conv2d", "depthwise_conv2d")))
        det.node(PDNode.var("conv_out", intermediate=True))
        det.node(PDNode.op("bn", "batch_norm"))
        det.node(PDNode.var("y"))
        det.link("conv", "conv_out").link("conv_out", "bn")
        det.link("bn", "y")
        count = 0

        def rewrite(m, g):
            nonlocal count
            bn_op = m["bn"].op
            if not bn_op.attrs.get("is_test", False):
                return
            # bn's Y must be the matched output (not a stats output)
            if m["y"].name != bn_op.output("Y")[0]:
                return
            conv_op = m["conv"].op
            w_name = conv_op.input("Filter")[0]
            names = {s: bn_op.input(s)[0]
                     for s in ("Scale", "Bias", "Mean", "Variance")}
            vals = {k: np.asarray(scope.find_var(n))
                    for k, n in names.items()}
            w = np.asarray(scope.find_var(w_name))
            eps = bn_op.attrs.get("epsilon", 1e-5)
            istd = 1.0 / np.sqrt(vals["Variance"] + eps)
            gamma = vals["Scale"] * istd                 # [C_out]
            w_new = w * gamma.reshape(-1, 1, 1, 1)
            b_new = vals["Bias"] - vals["Mean"] * gamma
            scope.set_var(w_name, w_new.astype(w.dtype))

            # new bias param var reuses the BN beta var's storage slot
            bias_name = names["Bias"]
            scope.set_var(bias_name, b_new.astype(w.dtype))
            bias_node = next(n for n in m["bn"].inputs
                             if n.name == bias_name)
            g.create_op_node(
                "elementwise_add",
                {"X": [m["conv_out"]], "Y": [bias_node]},
                {"Out": [m["y"]]},
                {"axis": 1 if conv_op.attrs.get(
                    "data_format", "NCHW") == "NCHW" else -1})
            # keep conv + its output var; drop only the bn op (its
            # stats outputs become dead writes)
            dead_outs = [n for n in m["bn"].outputs if n is not m["y"]
                         and not n.outputs]
            g.remove_nodes([m["bn"]] + dead_outs)
            # conv_out is consumed by the new add now — it was matched
            # as intermediate but stays alive
            count += 1

        det.apply(graph, rewrite)
        self.set("fused_count", count)
        return graph


@register_pass
class GraphVizPass(Pass):
    """Dump the graph as graphviz dot (reference: ir/graph_viz_pass.cc;
    FLAGS_print_sub_graph_dir). Pass attr "path" = output file."""

    name = "graph_viz_pass"

    def apply_impl(self, graph: Graph) -> Graph:
        path = self.require("path")
        lines = ["digraph G {", "  rankdir=TB;"]
        ids = {}
        for i, n in enumerate(graph.nodes):
            ids[id(n)] = "n%d" % i
            if n.is_op():
                lines.append(
                    '  n%d [label="%s" shape=box style=filled '
                    'fillcolor="#90EE90"];' % (i, n.op.type))
            else:
                shape = "ellipse" if not n.persistable else "octagon"
                lines.append('  n%d [label="%s" shape=%s];'
                             % (i, n.name, shape))
        for n in graph.nodes:
            if n.is_op():
                for v in n.inputs:
                    lines.append("  %s -> %s;" % (ids[id(v)],
                                                  ids[id(n)]))
                for v in n.outputs:
                    lines.append("  %s -> %s;" % (ids[id(n)],
                                                  ids[id(v)]))
        lines.append("}")
        with open(path, "w") as f:
            f.write("\n".join(lines))
        return graph
