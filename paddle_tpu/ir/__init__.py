"""Graph IR + pass framework.

Reference: paddle/fluid/framework/ir/ (~24.1k LoC) — ProgramDesc is
converted to an ``ir::Graph`` of op/var ``Node``s (ir/graph.h:72,
node.h), transformed by registered ``Pass``es (ir/pass.h:34,
PassRegistry :145) driven by pattern matching
(graph_pattern_detector.h), and converted back
(graph_to_program_pass.cc).

TPU-native scope: XLA already performs the reference's ~30 *kernel*
fusion passes (fc_fuse only saves a kernel launch there; here one jitted
program has no launches to save). What remains genuinely useful on this
substrate — and is built here — is *program-level* rewriting:

  - a stable Graph/Pass/PatternDetector toolkit that transpilers,
    inference optimization, and quantization rewrites share (the AMP
    decorator and QAT passes are ad-hoc program walkers today;
    new rewrites should use this),
  - semantic folds XLA cannot do because they change the *parameters*,
    not the computation graph of one step (conv+BN folding rewrites
    trained weights),
  - operator-count reduction for serialized inference programs
    (fc_fuse, fuse_elewise_add_act), which shrinks program artifacts
    and trace time,
  - debugging dumps (graph_viz_pass → graphviz dot, the analog of
    ir/graph_viz_pass.cc).
"""

from .graph import Graph, Node  # noqa: F401
from .pass_base import (Pass, PassManager, apply_passes,  # noqa: F401
                        get_pass, register_pass)
from .pattern_detector import GraphPatternDetector, PDNode  # noqa: F401
from . import passes  # noqa: F401  (registers the standard passes)
