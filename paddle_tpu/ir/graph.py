"""ir.Graph: SSA graph of op/var nodes over one Program block.

Reference: paddle/fluid/framework/ir/graph.h:72 (Graph),
ir/node.h (Node — an op node wraps an OpDesc, a var node wraps a
VarDesc; vars are versioned so each write creates a fresh node),
ir/graph_helper.h (TopologySortOperations),
ir/graph_to_program_pass.cc (rebuild the program from the graph).
"""

from __future__ import annotations

from typing import Dict, List, Optional

from ..core.enforce import InvalidArgumentError, enforce
from ..framework import Operator, Program, Variable


class Node:
    """Either an op node or a var node (reference: ir/node.h).

    Op nodes: ``node.op`` is a dict-like record {type, inputs, outputs,
    attrs} mirroring the Operator it came from; inputs/outputs are
    lists of var Nodes in slot order.

    Var nodes: ``node.name`` + ``node.var`` (the block's Variable desc,
    or None for a version created mid-graph); ``inputs`` holds the
    single writer op node (empty for graph inputs), ``outputs`` the
    reader op nodes.
    """

    OP = "op"
    VAR = "var"

    def __init__(self, kind, name=None, op=None, var=None, version=0):
        self.kind = kind
        self.name = name
        self.op = op            # framework.Operator for op nodes
        self.var = var          # framework.Variable desc for var nodes
        self.version = version  # SSA version for var nodes
        self.inputs: List[Node] = []
        self.outputs: List[Node] = []

    def is_op(self, type=None):
        return self.kind == Node.OP and (type is None or
                                         self.op.type == type)

    def is_var(self):
        return self.kind == Node.VAR

    @property
    def persistable(self):
        return self.var is not None and self.var.persistable

    def single_reader(self) -> Optional["Node"]:
        """The unique consumer op of a var node, or None."""
        if self.kind != Node.VAR or len(self.outputs) != 1:
            return None
        return self.outputs[0]

    def writer(self) -> Optional["Node"]:
        return self.inputs[0] if self.inputs else None

    def __repr__(self):
        if self.kind == Node.OP:
            return "OpNode(%s)" % self.op.type
        return "VarNode(%s@%d)" % (self.name, self.version)


class Graph:
    """Build the SSA node graph of ``program.block(idx)``.

    Each read links to the latest version of the name; each write
    creates a new version node — the reference's var-node versioning
    that makes write-after-read ordering explicit in graph edges.
    """

    def __init__(self, program: Program, block_idx: int = 0):
        enforce(isinstance(program, Program), "Graph wraps a Program")
        self.program = program
        self.block_idx = block_idx
        block = program.block(block_idx)
        self.nodes: List[Node] = []
        self._latest: Dict[str, Node] = {}
        self._versions: Dict[str, int] = {}
        # original positions: vjp ops reference their forward op BY
        # INDEX (attrs fwd_op_index keys the RNG fold and in-place
        # snapshots, executor.py _op_rng/run_op); to_program remaps
        # them after a rewrite shifts positions
        self._orig_index = {id(op): i for i, op in enumerate(block.ops)}

        for op in block.ops:
            self._add_op(op, block)

    # -- construction -------------------------------------------------------
    def _var_node(self, name, block, write=False) -> Node:
        if write or name not in self._latest:
            ver = self._versions.get(name, -1) + 1 \
                if (write and name in self._latest) else \
                self._versions.get(name, 0)
            self._versions[name] = ver
            node = Node(Node.VAR, name=name,
                        var=block._find_var_recursive(name), version=ver)
            self.nodes.append(node)
            self._latest[name] = node
            return node
        return self._latest[name]

    def _add_op(self, op: Operator, block) -> Node:
        op_node = Node(Node.OP, name=op.type, op=op)
        self.nodes.append(op_node)
        for names in op.inputs.values():
            for n in names:
                vn = self._var_node(n, block)
                op_node.inputs.append(vn)
                vn.outputs.append(op_node)
        for names in op.outputs.values():
            for n in names:
                vn = self._var_node(n, block, write=True)
                op_node.outputs.append(vn)
                vn.inputs.append(op_node)
        return op_node

    # -- queries ------------------------------------------------------------
    def op_nodes(self, type=None) -> List[Node]:
        return [n for n in self.nodes
                if n.kind == Node.OP and (type is None or
                                          n.op.type == type)]

    def var_nodes(self, name=None) -> List[Node]:
        return [n for n in self.nodes
                if n.kind == Node.VAR and (name is None or
                                           n.name == name)]

    # -- mutation (the pass API) -------------------------------------------
    def create_op_node(self, type, inputs, outputs, attrs=None) -> Node:
        """Insert a new op node wired to EXISTING var nodes.

        inputs/outputs: dict slot -> list of var Nodes (slot structure
        is recorded on the underlying Operator so graph_to_program
        round-trips)."""
        block = self.program.block(self.block_idx)
        op = Operator(block, type,
                      {s: [v.name for v in vs]
                       for s, vs in inputs.items()},
                      {s: [v.name for v in vs]
                       for s, vs in outputs.items()},
                      dict(attrs or {}))
        node = Node(Node.OP, name=type, op=op)
        self.nodes.append(node)
        for vs in inputs.values():
            for vn in vs:
                node.inputs.append(vn)
                vn.outputs.append(node)
        for vs in outputs.values():
            for vn in vs:
                node.outputs.append(vn)
                vn.inputs.insert(0, node)
        return node

    def remove_nodes(self, nodes) -> None:
        """Detach and drop a set of nodes (reference:
        GraphSafeRemoveNodes, graph_pattern_detector.cc)."""
        doomed = set(id(n) for n in nodes)
        for n in self.nodes:
            if id(n) in doomed:
                continue
            n.inputs = [m for m in n.inputs if id(m) not in doomed]
            n.outputs = [m for m in n.outputs if id(m) not in doomed]
        self.nodes = [n for n in self.nodes if id(n) not in doomed]

    # -- back to program ----------------------------------------------------
    def topological_order(self) -> List[Node]:
        """Kahn's algorithm over op nodes (reference:
        TopologySortOperations, ir/graph_helper.cc). Ties broken by
        original insertion order so unrelated ops keep program order
        (deterministic rebuilds)."""
        indeg: Dict[int, int] = {}
        pos = {id(n): i for i, n in enumerate(self.nodes)}
        ops = [n for n in self.nodes if n.kind == Node.OP]
        for n in ops:
            deps = set()
            for vn in n.inputs:
                for w in vn.inputs:  # writer ops of each input var
                    deps.add(id(w))
            indeg[id(n)] = len(deps)
        import heapq
        by_id = {id(n): n for n in ops}
        ready = [(pos[id(n)], id(n)) for n in ops
                 if indeg[id(n)] == 0]
        heapq.heapify(ready)
        order: List[Node] = []
        while ready:
            _, nid = heapq.heappop(ready)
            n = by_id[nid]
            order.append(n)
            seen = set()
            for vn in n.outputs:
                for r in vn.outputs:
                    rid = id(r)
                    if rid in seen or rid not in indeg:
                        continue
                    seen.add(rid)
                    indeg[rid] -= 1
                    if indeg[rid] == 0:
                        heapq.heappush(ready, (pos[rid], rid))
        if len(order) != len(ops):
            raise InvalidArgumentError(
                "graph has a cycle: %d of %d ops sorted"
                % (len(order), len(ops)))
        return order

    def to_program(self) -> Program:
        """Write the (possibly rewritten) op list back into the block
        in topological order (reference: graph_to_program_pass.cc).
        Mutates the wrapped Program in place and returns it.

        Gradient safety: generated ``vjp`` ops address their forward op
        by block index (``fwd_op_index`` — it keys the dropout-RNG fold
        and the in-place input snapshots in executor.run_block), so any
        rewrite that shifts positions would silently desynchronize
        forward and backward RNG streams. The indices are remapped
        here; a vjp whose forward op a pass deleted is an error."""
        block = self.program.block(self.block_idx)
        new_ops = [n.op for n in self.topological_order()]
        old_to_new = {}
        for new_i, op in enumerate(new_ops):
            old_i = self._orig_index.get(id(op))
            if old_i is not None:
                old_to_new[old_i] = new_i
        for op in new_ops:
            if op.type not in ("vjp", "vjp2"):
                continue
            old_fwd = op.attrs.get("fwd_op_index")
            if old_fwd is None:
                continue
            if old_fwd not in old_to_new:
                raise InvalidArgumentError(
                    "a pass removed forward op #%d (%s) that a vjp op "
                    "still differentiates — fusion across recorded "
                    "gradients is not legal" %
                    (old_fwd, op.attrs.get("fwd_type")))
            op.attrs["fwd_op_index"] = old_to_new[old_fwd]
        # remapped indices become the new baseline for a second pass
        self._orig_index = {id(op): i for i, op in enumerate(new_ops)}
        block.ops = new_ops
        self.program._bump()
        return self.program
