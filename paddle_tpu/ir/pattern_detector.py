"""Subgraph pattern matching over ir.Graph.

Reference: framework/ir/graph_pattern_detector.h — `PDNode` (a node
predicate + role flags), `PDPattern` (PDNodes + links), and
`GraphPatternDetector::operator()` which finds all subgraph matches and
invokes a handler per match. ~30 fusion passes are written against it.

The matcher here is a straightforward backtracking subgraph
isomorphism: pattern nodes are bound in declaration order, each
candidate must satisfy the PDNode predicate and every already-bound
link. Patterns are tiny (2–6 nodes), so this is never hot.
"""

from __future__ import annotations

from typing import Callable, Dict, List, Optional, Tuple

from ..core.enforce import enforce
from .graph import Graph, Node


class PDNode:
    """One slot in the pattern. ``predicate(node) -> bool``; role flags
    mirror the reference's AsInput/AsOutput/AsIntermediate — an
    intermediate var must have no consumers outside the match (safe to
    delete when the subgraph is replaced)."""

    def __init__(self, name, predicate, intermediate=False):
        self.name = name
        self.predicate = predicate
        self.intermediate = intermediate

    # -- common predicates --------------------------------------------------
    @staticmethod
    def op(name, type) -> "PDNode":
        if isinstance(type, (list, tuple, set, frozenset)):
            types = frozenset(type)
            return PDNode(name, lambda n: n.is_op() and
                          n.op.type in types)
        return PDNode(name, lambda n: n.is_op(type))

    @staticmethod
    def var(name, persistable=None, intermediate=False) -> "PDNode":
        def pred(n):
            if not n.is_var():
                return False
            if persistable is None:
                return True
            return n.persistable == persistable
        return PDNode(name, pred, intermediate=intermediate)


class GraphPatternDetector:
    """Build a pattern with ``node``/``link``, run with ``detect`` or
    ``apply`` (handler per match)."""

    def __init__(self):
        self.pattern: List[PDNode] = []
        self.links: List[Tuple[str, str]] = []
        self._by_name: Dict[str, PDNode] = {}

    def node(self, pdnode: PDNode) -> PDNode:
        enforce(pdnode.name not in self._by_name,
                "duplicate pattern node %r" % pdnode.name)
        self.pattern.append(pdnode)
        self._by_name[pdnode.name] = pdnode
        return pdnode

    def link(self, src: str, dst: str):
        """Declare that match[src] must appear in match[dst].inputs
        (i.e. an edge src → dst)."""
        enforce(src in self._by_name and dst in self._by_name,
                "link references unknown pattern node")
        self.links.append((src, dst))
        return self

    # -- matching -----------------------------------------------------------
    def detect(self, graph: Graph) -> List[Dict[str, Node]]:
        matches: List[Dict[str, Node]] = []
        nodes = list(graph.nodes)

        def consistent(binding, pd, cand):
            for src, dst in self.links:
                if src == pd.name and dst in binding:
                    if cand not in binding[dst].inputs:
                        return False
                if dst == pd.name and src in binding:
                    if binding[src] not in cand.inputs:
                        return False
            return True

        def backtrack(i, binding):
            if i == len(self.pattern):
                matches.append(dict(binding))
                return
            pd = self.pattern[i]
            for cand in nodes:
                if cand in binding.values():
                    continue
                if not pd.predicate(cand):
                    continue
                if not consistent(binding, pd, cand):
                    continue
                if pd.intermediate and cand.is_var():
                    # all consumers must be inside the pattern once the
                    # match completes; cheap precheck: writer exists
                    if not cand.inputs:
                        continue
                binding[pd.name] = cand
                backtrack(i + 1, binding)
                del binding[pd.name]

        backtrack(0, {})
        return self._filter_intermediates(matches)

    def _filter_intermediates(self, matches):
        """Drop matches whose intermediate vars leak outside the match
        (they can't be deleted) and overlapping matches (first wins,
        the reference's behavior when a node is consumed by an earlier
        rewrite)."""
        out, used = [], set()
        for m in matches:
            bound = set(id(n) for n in m.values())
            ok = True
            for pd in self.pattern:
                n = m[pd.name]
                if id(n) in used:
                    ok = False
                    break
                if pd.intermediate and n.is_var():
                    if any(id(r) not in bound for r in n.outputs):
                        ok = False
                        break
            if ok:
                out.append(m)
                used.update(id(n) for n in m.values()
                            if n.is_op() or
                            self._by_name_of(m, n).intermediate)
        return out

    def _by_name_of(self, match, node):
        for name, n in match.items():
            if n is node:
                return self._by_name[name]
        raise AssertionError

    def apply(self, graph: Graph,
              handler: Callable[[Dict[str, Node], Graph], None]) -> int:
        """Run handler per match; returns the match count (the
        reference detector's operator())."""
        matches = self.detect(graph)
        for m in matches:
            handler(m, graph)
        return len(matches)
