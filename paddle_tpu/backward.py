"""Static-graph autodiff: append_backward.

Reference: python/paddle/fluid/backward.py (append_backward:394,
_find_op_path_:579, _append_backward_ops_:252 querying C++ per-op
GradOpMakers via core.get_grad_op_desc, dedup of repeated grads via
inserted sum ops _addup_repetitive_outputs_:135, pruning :204).

TPU-native redesign: the walk over ops in reverse and the @GRAD naming
convention are kept — users see the same program structure — but there
are no hand-written per-op grad kernels. Each appended ``vjp`` op records
its forward op's signature; at trace time the executor calls jax.vjp on
the forward lowering (executor._run_vjp_op), so gradients are exact by
construction and XLA CSE merges the re-traced forward with the original.
Gradient accumulation for vars consumed by multiple ops happens by
add-accumulation into the @GRAD env entry (no explicit sum ops needed).
"""

from __future__ import annotations

from typing import Dict, List, Optional, Set

from . import framework, ops
from .core.enforce import InvalidArgumentError, enforce
from .framework import Variable, grad_var_name


def _op_path_to(block, target_op_index: int,
                stop_vars: Set[str]) -> List[int]:
    """Indices of ops (ascending) whose outputs can influence the target
    op, not crossing stop-gradient barriers (reference:
    backward.py:579 _find_op_path_)."""
    needed: Set[str] = set()
    target = block.ops[target_op_index]
    needed.update(target.input_arg_names)
    path = [target_op_index]
    for i in range(target_op_index - 1, -1, -1):
        op = block.ops[i]
        outs = set(op.output_arg_names)
        if outs & needed:
            path.append(i)
            for n in op.input_arg_names:
                if n not in stop_vars:
                    needed.add(n)
    path.reverse()
    return path


def _collect_stop_vars(block, no_grad_set) -> Set[str]:
    stop = set(no_grad_set or ())
    for name, var in block.vars.items():
        if var.stop_gradient:
            stop.add(name)
    return stop


def _append_sparse_lookup_grad(block, fwd, stop_vars) -> bool:
    """Append a lookup_table_grad op producing a SparseRows table
    gradient (the SelectedRows path of lookup_table_op.cc). Returns
    False when the table doesn't need a grad (caller falls through to
    the generic machinery, which will also produce nothing)."""
    w_name = fwd.inputs["W"][0]
    if w_name in stop_vars:
        return False
    w = block._find_var_recursive(w_name)
    out_name = fwd.outputs["Out"][0]
    og = grad_var_name(out_name)
    if not block.has_var(og):
        return False
    gn = grad_var_name(w_name)
    if not block.has_var(gn):
        block.create_var(name=gn, shape=w.shape, dtype=w.dtype,
                         stop_gradient=True)
    block.append_op(
        type="lookup_table_grad",
        inputs={"Ids": list(fwd.inputs["Ids"]), "OutGrad": [og]},
        outputs={"WGrad": [gn]},
        attrs={"height": int(w.shape[0]),
               "padding_idx": fwd.attrs.get("padding_idx", -1),
               "op_role": "backward"})
    return True


def append_backward(loss: Variable, parameter_list=None, no_grad_set=None,
                    callbacks=None):
    """Append gradient ops for ``loss`` to its program; returns
    [(param, grad_var)] like the reference (backward.py:394)."""
    enforce(isinstance(loss, Variable), "loss must be a Variable")
    program = loss.block.program
    block = program.global_block()

    # producer op of loss
    target_index = None
    for i in range(len(block.ops) - 1, -1, -1):
        if loss.name in block.ops[i].output_arg_names:
            target_index = i
            break
    enforce(target_index is not None,
            "loss %r has no producer op in the program" % loss.name)

    stop_vars = _collect_stop_vars(block, no_grad_set)
    path = _op_path_to(block, target_index, stop_vars)

    # d(loss)/d(loss) = 1
    loss_grad = block.create_var(
        name=grad_var_name(loss.name), shape=loss.shape, dtype=loss.dtype,
        persistable=False, stop_gradient=True)
    block.append_op(
        type="fill_constant",
        outputs={"Out": [loss_grad]},
        attrs={"shape": tuple(loss.shape), "dtype": loss.dtype,
               "value": 1.0, "op_role": "backward"})

    # reverse walk, one vjp op per differentiable forward op
    for i in reversed(path):
        fwd = block.ops[i]
        if not ops.has(fwd.type):
            continue
        opdef = ops.get(fwd.type)
        if not opdef.differentiable:
            continue

        if fwd.type == "lookup_table" and fwd.attrs.get("is_sparse"):
            # sparse embedding: emit the dedicated SparseRows grad op
            # (reference: lookup_table_op.cc is_sparse grad ->
            # SelectedRows) instead of the dense generic vjp
            if _append_sparse_lookup_grad(block, fwd, stop_vars):
                continue

        grad_outputs: Dict[str, List[str]] = {}
        any_grad = False
        for slot, _variadic in opdef.input_slots:
            if slot in opdef.nondiff_slots:
                continue
            names = fwd.inputs.get(slot, [])
            gnames = []
            for n in names:
                if n in stop_vars:
                    continue
                v = block._find_var_recursive(n)
                if v is not None and v.dtype in ("float32", "float64",
                                                 "float16", "bfloat16"):
                    gn = grad_var_name(n)
                    if not block.has_var(gn):
                        block.create_var(name=gn, shape=v.shape,
                                         dtype=v.dtype,
                                         stop_gradient=True)
                    gnames.append(gn)
                    any_grad = True
            if gnames:
                grad_outputs[slot + "@GRAD"] = gnames
        if not any_grad:
            continue

        out_grad_inputs = [grad_var_name(n) for n in fwd.output_arg_names]
        block.append_op(
            type="vjp",
            inputs={"FwdIn": fwd.input_arg_names,
                    "OutGrad": [g for g in out_grad_inputs
                                if block.has_var(g)]},
            outputs=grad_outputs,
            attrs={
                "fwd_type": fwd.type,
                "fwd_inputs": {k: list(v) for k, v in fwd.inputs.items()},
                "fwd_outputs": {k: list(v)
                                for k, v in fwd.outputs.items()},
                "fwd_attrs": dict(fwd.attrs),
                "fwd_op_index": i,
                "no_grad_vars": tuple(sorted(stop_vars)),
                "op_role": "backward",
            })

    # collect (param, grad) pairs
    params = block.all_parameters()
    if parameter_list is not None:
        wanted = {p if isinstance(p, str) else p.name
                  for p in parameter_list}
        params = [p for p in params if p.name in wanted]
    result = []
    for p in params:
        if not p.trainable:
            continue
        gn = grad_var_name(p.name)
        if block.has_var(gn):
            result.append((p, block.var(gn)))
    return result


def calc_gradient(targets, inputs, target_gradients=None,
                  no_grad_set=None):
    """Reference: backward.py:619. Gradients of targets w.r.t. inputs."""
    if isinstance(targets, Variable):
        targets = [targets]
    if isinstance(inputs, Variable):
        inputs = [inputs]
    enforce(len(targets) == 1,
            "calc_gradient currently supports a single target")
    target = targets[0]
    append_backward(target, no_grad_set=no_grad_set)
    block = target.block.program.global_block()
    outs = []
    for iv in inputs:
        gn = grad_var_name(iv.name)
        outs.append(block.var(gn) if block.has_var(gn) else None)
    return outs


def gradients(targets, inputs, target_gradients=None, no_grad_set=None):
    return calc_gradient(targets, inputs, target_gradients, no_grad_set)
