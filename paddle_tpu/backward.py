"""Static-graph autodiff: append_backward.

Reference: python/paddle/fluid/backward.py (append_backward:394,
_find_op_path_:579, _append_backward_ops_:252 querying C++ per-op
GradOpMakers via core.get_grad_op_desc, dedup of repeated grads via
inserted sum ops _addup_repetitive_outputs_:135, pruning :204).

TPU-native redesign: the walk over ops in reverse and the @GRAD naming
convention are kept — users see the same program structure — but there
are no hand-written per-op grad kernels. Each appended ``vjp`` op records
its forward op's signature; at trace time the executor calls jax.vjp on
the forward lowering (executor._run_vjp_op), so gradients are exact by
construction and XLA CSE merges the re-traced forward with the original.
Gradient accumulation for vars consumed by multiple ops happens by
add-accumulation into the @GRAD env entry (no explicit sum ops needed).
"""

from __future__ import annotations

from typing import Dict, List, Optional, Set

from . import framework, ops
from .core.enforce import InvalidArgumentError, enforce
from .framework import Variable, grad_var_name


def _op_path_to(block, target_op_index: int,
                stop_vars: Set[str]) -> List[int]:
    """Indices of ops (ascending) whose outputs can influence the target
    op, not crossing stop-gradient barriers (reference:
    backward.py:579 _find_op_path_)."""
    needed: Set[str] = set()
    target = block.ops[target_op_index]
    needed.update(target.input_arg_names)
    path = [target_op_index]
    for i in range(target_op_index - 1, -1, -1):
        op = block.ops[i]
        outs = set(op.output_arg_names)
        if outs & needed:
            path.append(i)
            for n in op.input_arg_names:
                if n not in stop_vars:
                    needed.add(n)
    path.reverse()
    return path


def _collect_stop_vars(block, no_grad_set) -> Set[str]:
    stop = set(no_grad_set or ())
    for name, var in block.vars.items():
        if var.stop_gradient:
            stop.add(name)
    return stop


def _append_sparse_lookup_grad(block, fwd, stop_vars) -> bool:
    """Append a lookup_table_grad op producing a SparseRows table
    gradient (the SelectedRows path of lookup_table_op.cc). Returns
    False when the table doesn't need a grad (caller falls through to
    the generic machinery, which will also produce nothing)."""
    w_name = fwd.inputs["W"][0]
    if w_name in stop_vars:
        return False
    w = block._find_var_recursive(w_name)
    out_name = fwd.outputs["Out"][0]
    og = grad_var_name(out_name)
    if not block.has_var(og):
        return False
    gn = grad_var_name(w_name)
    if not block.has_var(gn):
        block.create_var(name=gn, shape=w.shape, dtype=w.dtype,
                         stop_gradient=True)
    block.append_op(
        type="lookup_table_grad",
        inputs={"Ids": list(fwd.inputs["Ids"]), "OutGrad": [og]},
        outputs={"WGrad": [gn]},
        attrs={"height": int(w.shape[0]),
               "padding_idx": fwd.attrs.get("padding_idx", -1),
               "op_role": "backward"})
    return True


def append_backward(loss: Variable, parameter_list=None, no_grad_set=None,
                    callbacks=None, grad_suffix=""):
    """Append gradient ops for ``loss`` to its program; returns
    [(param, grad_var)] like the reference (backward.py:394).

    ``grad_suffix`` namespaces this pass's gradient vars
    (``x@GRAD<suffix>``) — the analog of the reference's @RENAME@
    dedup (backward.py:135): a second differentiation over the same
    program (calc_gradient for a gradient penalty, then minimize)
    must not accumulate into the first pass's ``@GRAD`` vars.
    """
    enforce(isinstance(loss, Variable), "loss must be a Variable")
    program = loss.block.program
    block = program.global_block()

    def gname(n):
        return grad_var_name(n) + grad_suffix

    # producer op of loss
    target_index = None
    for i in range(len(block.ops) - 1, -1, -1):
        if loss.name in block.ops[i].output_arg_names:
            target_index = i
            break
    enforce(target_index is not None,
            "loss %r has no producer op in the program" % loss.name)

    stop_vars = _collect_stop_vars(block, no_grad_set)
    path = _op_path_to(block, target_index, stop_vars)

    # d(loss)/d(loss) = 1
    loss_grad = block.create_var(
        name=gname(loss.name), shape=loss.shape, dtype=loss.dtype,
        persistable=False, stop_gradient=True)
    block.append_op(
        type="fill_constant",
        outputs={"Out": [loss_grad]},
        attrs={"shape": tuple(loss.shape), "dtype": loss.dtype,
               "value": 1.0, "op_role": "backward"})

    # reverse walk, one vjp op per differentiable forward op
    for i in reversed(path):
        fwd = block.ops[i]
        if fwd.type == "vjp":
            # differentiate THROUGH a previous pass's gradient op:
            # double backward (reference exercises this via
            # unittests/gradient_checker.py / gradient-penalty models)
            _append_vjp2(block, fwd, i, stop_vars, gname, grad_suffix)
            continue
        if fwd.type == "vjp2":
            enforce(False, "third-order differentiation through a "
                    "vjp2 op is not supported")
        if not ops.has(fwd.type):
            continue
        opdef = ops.get(fwd.type)
        if not opdef.differentiable:
            continue

        if fwd.type == "lookup_table" and fwd.attrs.get("is_sparse"):
            # sparse embedding: emit the dedicated SparseRows grad op
            # (reference: lookup_table_op.cc is_sparse grad ->
            # SelectedRows) instead of the dense generic vjp
            if _append_sparse_lookup_grad(block, fwd, stop_vars):
                continue

        grad_outputs: Dict[str, List[str]] = {}
        any_grad = False
        for slot, _variadic in opdef.input_slots:
            if slot in opdef.nondiff_slots:
                continue
            names = fwd.inputs.get(slot, [])
            gnames = []
            for n in names:
                if n in stop_vars:
                    continue
                v = block._find_var_recursive(n)
                if v is not None and v.dtype in ("float32", "float64",
                                                 "float16", "bfloat16"):
                    gn = gname(n)
                    if not block.has_var(gn):
                        # NOT stop_gradient: a later pass must be able
                        # to differentiate through this pass's grads
                        # (gradient-penalty double backward)
                        block.create_var(name=gn, shape=v.shape,
                                         dtype=v.dtype,
                                         stop_gradient=False)
                    gnames.append(gn)
                    any_grad = True
            if gnames:
                grad_outputs[slot + "@GRAD"] = gnames
        if not any_grad:
            continue

        if fwd.type == "while" and not fwd.attrs.get("max_iters"):
            # surface the XLA constraint at BUILD time (here) instead
            # of as a trace-time failure deep in the executor: an
            # unbounded lax.while_loop is forward-only
            enforce(False,
                    "gradients through a While loop need a trip "
                    "bound: build it as layers.While(cond, "
                    "max_iters=<bound>) so it lowers to a "
                    "differentiable lax.scan (op #%d)" % i)

        out_grad_inputs = [gname(n) for n in fwd.output_arg_names]
        block.append_op(
            type="vjp",
            inputs={"FwdIn": fwd.input_arg_names,
                    "OutGrad": [g for g in out_grad_inputs
                                if block.has_var(g)]},
            outputs=grad_outputs,
            attrs={
                "fwd_type": fwd.type,
                "fwd_inputs": {k: list(v) for k, v in fwd.inputs.items()},
                "fwd_outputs": {k: list(v)
                                for k, v in fwd.outputs.items()},
                "fwd_attrs": dict(fwd.attrs),
                "fwd_op_index": i,
                "no_grad_vars": tuple(sorted(stop_vars)),
                "grad_suffix": grad_suffix,
                "op_role": "backward",
            })

    # collect (param, grad) pairs
    params = block.all_parameters()
    if parameter_list is not None:
        wanted = {p if isinstance(p, str) else p.name
                  for p in parameter_list}
        params = [p for p in params if p.name in wanted]
    result = []
    for p in params:
        if not p.trainable:
            continue
        gn = gname(p.name)
        if block.has_var(gn):
            result.append((p, block.var(gn)))
    return result


def _append_vjp2(block, vop, i, stop_vars, gname, grad_suffix):
    """Append the second-order gradient op for a first-pass ``vjp`` op.

    A vjp op is a pure function (FwdIn, OutGrad) -> input-grads (the
    pullback of its forward op). Differentiating through it is
    jax.vjp of that pullback application (executor._run_vjp2_op);
    here we only declare which of its inputs receive this pass's
    gradients and which of its products carry upstream cotangents.
    """
    inner_suffix = vop.attrs.get("grad_suffix", "")

    grad_outputs = {"FwdIn@GRAD": [], "OutGrad@GRAD": []}
    fwd_in = list(vop.inputs.get("FwdIn", []))
    out_grad = list(vop.inputs.get("OutGrad", []))
    any_grad = False
    for key, names in (("FwdIn@GRAD", fwd_in),
                       ("OutGrad@GRAD", out_grad)):
        for n in names:
            if n in stop_vars:
                continue
            v = block._find_var_recursive(n)
            if v is None or v.dtype not in ("float32", "float64",
                                            "float16", "bfloat16"):
                continue
            gn = gname(n)
            if not block.has_var(gn):
                block.create_var(name=gn, shape=v.shape, dtype=v.dtype,
                                 stop_gradient=False)
            grad_outputs[key].append(gn)
            any_grad = True
    if not any_grad:
        return

    # upstream cotangents: this pass's grads of the vjp op's products
    up = [gname(g) for g in
          (n for outs in vop.outputs.values() for n in outs)]
    block.append_op(
        type="vjp2",
        inputs={"FwdIn": fwd_in, "OutGrad": out_grad,
                "UpGrad": [g for g in up if block.has_var(g)]},
        outputs=grad_outputs,
        attrs=dict(vop.attrs, grad_suffix_inner=inner_suffix,
                   grad_suffix=grad_suffix,
                   no_grad_vars_outer=tuple(sorted(stop_vars)),
                   op_role="backward"))


def calc_gradient(targets, inputs, target_gradients=None,
                  no_grad_set=None):
    """Reference: backward.py:619. Gradients of targets w.r.t. inputs.

    Multiple targets follow the reference semantics: the returned
    grads are ``d(sum_i <targets[i], target_gradients[i]>)/d(inputs)``
    (cotangents default to ones). Each call namespaces its gradient
    vars with a fresh suffix, so calc_gradient composes with a later
    ``minimize``/``append_backward`` over the same program — the
    double-backward (gradient-penalty) pattern.
    """
    if isinstance(targets, Variable):
        targets = [targets]
    if isinstance(inputs, Variable):
        inputs = [inputs]
    enforce(len(targets) >= 1, "calc_gradient needs at least 1 target")
    if target_gradients is None:
        target_gradients = [None] * len(targets)
    if isinstance(target_gradients, Variable):
        target_gradients = [target_gradients]
    enforce(len(target_gradients) == len(targets),
            "target_gradients must match targets (%d vs %d)"
            % (len(target_gradients), len(targets)))

    program = targets[0].block.program
    block = program.global_block()
    count = getattr(program, "_calc_grad_count", 0)
    program._calc_grad_count = count + 1
    suffix = "@CG%d" % count

    # combined scalar: sum_i <t_i, tg_i>; its backward yields exactly
    # the requested vector-Jacobian products
    from . import layers
    with framework.program_guard(program):
        terms = []
        for t, tg in zip(targets, target_gradients):
            if tg is None:
                terms.append(layers.reduce_sum(t))
            else:
                terms.append(layers.reduce_sum(
                    layers.elementwise_mul(t, tg)))
        combined = terms[0]
        for t in terms[1:]:
            combined = layers.elementwise_add(combined, t)

    stop = set(no_grad_set or ())
    for tg in target_gradients:
        if tg is not None:
            stop.add(tg.name)
    append_backward(combined, no_grad_set=stop, grad_suffix=suffix)
    outs = []
    for iv in inputs:
        gn = grad_var_name(iv.name) + suffix
        outs.append(block.var(gn) if block.has_var(gn) else None)
    return outs


def gradients(targets, inputs, target_gradients=None, no_grad_set=None):
    return calc_gradient(targets, inputs, target_gradients, no_grad_set)
