"""Typed global flag system.

TPU-native replacement for the reference's gflags-based configuration
(reference: 115 DEFINE_* sites across paddle/fluid; whitelist exported to
Python via core.init_gflags, python/paddle/fluid/__init__.py:136-196).

One typed registry, overridable from the environment as
``FLAGS_<name>=value`` (same spelling the reference uses), readable and
settable from Python at runtime. Flags that gate tracing-time behavior
take effect on the next program compilation.
"""

from __future__ import annotations

import os
from dataclasses import dataclass
from typing import Any, Callable, Dict

_BOOL_TRUE = {"1", "true", "yes", "on"}


def _parse_bool(s: str) -> bool:
    return s.strip().lower() in _BOOL_TRUE


@dataclass
class _FlagSpec:
    name: str
    default: Any
    parser: Callable[[str], Any]
    help: str


class _Flags:
    def __init__(self):
        self._specs: Dict[str, _FlagSpec] = {}
        self._values: Dict[str, Any] = {}

    def define(self, name, default, help=""):
        if isinstance(default, bool):
            parser = _parse_bool
        elif isinstance(default, int):
            parser = int
        elif isinstance(default, float):
            parser = float
        else:
            parser = str
        self._specs[name] = _FlagSpec(name, default, parser, help)
        env = os.environ.get("FLAGS_" + name)
        self._values[name] = parser(env) if env is not None else default

    def __getattr__(self, name):
        try:
            return self.__dict__["_values"][name]
        except KeyError:
            raise AttributeError("unknown flag %r" % name)

    def __setattr__(self, name, value):
        if name.startswith("_"):
            super().__setattr__(name, value)
            return
        if name not in self._specs:
            raise AttributeError("unknown flag %r" % name)
        self._values[name] = value

    def as_dict(self):
        return dict(self._values)


FLAGS = _Flags()

# Execution / debugging (reference: operator.cc FLAGS_check_nan_inf :950,
# FLAGS_benchmark :946; executor eager deletion FLAGS_eager_delete_tensor_gb).
FLAGS.define("check_nan_inf", False,
             "After each step, scan fetched outputs for NaN/Inf and raise.")
FLAGS.define("benchmark", False,
             "Block on device completion after every executor run.")
FLAGS.define("cpu_deterministic", True, "Deterministic reductions on host.")
FLAGS.define("infer_shape_debug", False,
             "Log shape-inference failures at op-append time instead of "
             "deferring errors to trace time.")
FLAGS.define("deterministic", True,
             "Ask XLA for deterministic reductions (analog of "
             "cudnn_deterministic / sync_nccl_allreduce).")

# Memory (analog of FLAGS_fraction_of_gpu_memory_to_use etc.; HBM is
# XLA-managed so these only gate host staging buffers).
FLAGS.define("host_pinned_pool_mb", 256,
             "Host staging pool for infeed, in MB.")
FLAGS.define("eager_delete_tensor_gb", 0.0,
             "Kept for API parity; XLA manages HBM lifetimes.")

# Tracing / profiling.
FLAGS.define("profile_dir", "", "If set, xprof traces are written here.")

# Random.
FLAGS.define("global_seed", 0, "Framework-wide RNG seed (0 = nondeterministic).")

# Distributed.
FLAGS.define("sync_collectives", True,
             "Deterministic collective order (analog of sync_nccl_allreduce).")
FLAGS.define("rpc_deadline", 180000, "DCN RPC deadline ms (parity).")

# Async communicator (reference: python/paddle/fluid/__init__.py:169-176
# communicator_* gflags tuning Communicator::SendThread batching).
FLAGS.define("communicator_max_merge_var_num", 20,
             "Max queued grads merged into one PS send.")
FLAGS.define("communicator_send_queue_size", 20,
             "Trainer-side send queue depth.")
FLAGS.define("communicator_independent_recv_thread", True,
             "Kept for API parity (recv is pull-on-demand here).")

FLAGS.define("sdpa_auto_flash", True,
             "scaled_dot_product_attention's base lowering routes to "
             "the flash pallas kernel inside its chip-measured win "
             "envelope (TPU backend, <=2-byte dtype, dropout active, "
             "single-k-block shapes) — the reference jit/ pool's "
             "best-impl-at-runtime dispatch. bench.py pins this off "
             "for its pure-XLA base row. Chip evidence 2026-07-31: "
             "+12% in-model on transformer-base b64.")

FLAGS.define("sp_attention", True,
             "scaled_dot_product_attention's base lowering routes "
             "through the sequence-parallel schedules when the ambient "
             "mesh carries an sp axis (parallel/ulysses.py "
             "sequence_parallel_attention): zigzag ring for causal "
             "no-bias shapes, Ulysses all-to-all head re-sharding "
             "otherwise. Off = keep the replicated full-attention "
             "lowering and let GSPMD place it (correct, but the "
             "S^2 score matrix is not sequence-sharded).")

FLAGS.define("ring_flash", True,
             "ring_attention computes each hop's block attention with "
             "the pallas partial-softmax kernels (ops/pallas/ring.py) "
             "so [Sq_loc, Sk_loc] scores stay in VMEM; falls back to "
             "the jnp body when no kernel geometry fits the scoped-"
             "VMEM model (ring.applicable).")

FLAGS.define("lean_xent_grad", True,
             "fused_linear_xent uses the hand-written one-fusion "
             "backward writing dlogits in the input dtype "
             "(ops/fused_ops.py _lean_xent). Off = autodiff of the "
             "composite lowering.")

FLAGS.define("mxu_bias_grad", True,
             "rank-1 bias adds compute their bf16 bias gradient as "
             "ones@dY on the MXU with f32 accumulation instead of "
             "the broadcast-transpose reduce (ops/math_ops.py "
             "_bias_add_vjp) — faster AND closer to the exact f32 "
             "sum.")

FLAGS.define("resnet_s2d_stem", False,
             "ResNet ImageNet stem runs as space_to_depth(2) + "
             "4x4/s1 conv (12 input channels) instead of 7x7/s2 on "
             "3 channels — the numerically-equivalent MLPerf stem "
             "(models/resnet.s2d_stem_weights). Default OFF until "
             "chip-measured in-model.")

FLAGS.define("mxu_ln_grad", False,
             "layer_norm's dScale/dBias column reductions run as "
             "ones@M MXU dots with f32 accumulation (the "
             "mxu_bias_grad treatment extended to the layer-norm "
             "affine tail — ops/nn_ops._ln_affine). Default OFF "
             "until chip-measured in-model (tools/lever_ab.py).")

FLAGS.define("multi_tensor_adam", False,
             "Trace consecutive dense adam/adamw ops over SMALL "
             "parameters as one concatenated multi-tensor update "
             "(the reference's fuse_adam_op_pass analog; "
             "framework/ir/fuse_optimizer_ops_pass). The update math "
             "is identical element-for-element; results match the "
             "per-op path to f32 ulp (XLA fusion grouping may "
             "contract FMAs differently). DEFAULT OFF: chip-measured "
             "2026-07-31 on transformer-base, the batch LOSES "
             "in-model at every tried threshold (11.42 vs 11.69 "
             "steps/s at 64k-numel; 1.8 at 1M) — XLA's per-param "
             "fusions already schedule well and the concat/slice "
             "copies only add traffic. Kept as the parity analog and "
             "for param-heavy models with many tiny tensors.")

FLAGS.define("verify_rewrites", False,
             "Run the static program verifier (paddle_tpu/analysis) "
             "automatically after each executor rewrite — guard "
             "install, sharded-state conversion, PS split, every "
             "trace entry — and raise on error-severity findings. "
             "The analysis plane's debug/verify mode; off (default) "
             "the hooks cost one flag read.")
