"""SparseRows — the SelectedRows analog (sparse gradients).

Reference: paddle/fluid/framework/selected_rows.h:32 (SelectedRows =
{rows, value tensor, height}), produced by the sparse path of
lookup_table's gradient (lookup_table_op.cc, attr ``is_sparse``) and
consumed natively by the sparse kernels of sgd/momentum/adam/adagrad
(e.g. adam_op.h SparseAdamFunctor) and by merge-add
(operators/math/selected_rows_functor.cc).

TPU-native redesign: a JAX pytree of {rows int32[n], values [n, ...]}
plus a static ``height``. All shapes are static (n = number of looked-up
ids per step), so the whole sparse-update path jits: gradient production
is a slice of the incoming cotangent (no scatter), duplicate-row merge
is sort + segment-sum at fixed width, and optimizer application is one
scatter over the touched rows — the full table is never densified,
which is what makes >HBM-grad-scale embedding tables trainable
(VERDICT round-1 gap #1: a 1e8-row table's dense grad would OOM; its
SparseRows grad is O(batch)).

Out-of-range sentinel: merged() marks unused segments with row index
``height``; scatters use mode="drop" so sentinel rows are no-ops, and
gathers clamp (the garbage value is dropped on write-back).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


@jax.tree_util.register_pytree_node_class
class SparseRows:
    """A sparse slab of a [height, ...] tensor: ``values[i]`` belongs at
    row ``rows[i]``; duplicate rows mean addition."""

    __slots__ = ("rows", "values", "height")

    def __init__(self, rows, values, height):
        self.rows = rows
        self.values = values
        self.height = int(height)

    def tree_flatten(self):
        return (self.rows, self.values), self.height

    @classmethod
    def tree_unflatten(cls, height, children):
        return cls(children[0], children[1], height)

    # -- tensor-ish surface -------------------------------------------------
    @property
    def dtype(self):
        return self.values.dtype

    @property
    def shape(self):
        return (self.height,) + tuple(self.values.shape[1:])

    def __repr__(self):
        return ("SparseRows(n=%s, height=%d, dim=%s, dtype=%s)"
                % (self.rows.shape[0], self.height,
                   tuple(self.values.shape[1:]), self.dtype))

    # -- algebra ------------------------------------------------------------
    def __add__(self, other):
        """Sparse+sparse concatenates (merge deferred to the consumer,
        reference merge_add); sparse+dense densifies — the grad var is
        also consumed by a dense op, so a dense result is semantically
        required."""
        if isinstance(other, SparseRows):
            if other.height != self.height:
                raise ValueError(
                    "SparseRows height mismatch: %d vs %d"
                    % (self.height, other.height))
            return SparseRows(
                jnp.concatenate([self.rows, other.rows]),
                jnp.concatenate([self.values, other.values]),
                self.height)
        return self.add_to(other)

    __radd__ = __add__

    def __mul__(self, scalar):
        """Scale values (loss-scaling unscale, 1/N DP averaging)."""
        return SparseRows(self.rows, self.values * scalar, self.height)

    __rmul__ = __mul__

    def add_to(self, dense):
        """dense + self via scatter-add (mode='drop' ignores sentinel
        rows)."""
        return dense.at[self.rows].add(
            self.values.astype(dense.dtype), mode="drop")

    def to_dense(self):
        base = jnp.zeros(self.shape, self.values.dtype)
        return self.add_to(base)

    def merged(self):
        """Sum duplicate rows (reference:
        math/selected_rows_functor.cc MergeAdd). Fixed-shape algorithm:
        sort by row id, segment-sum runs of equal ids; segments beyond
        the unique count keep the sentinel row ``height`` (dropped by
        scatters). Required before any non-linear per-row optimizer
        update (adam/adagrad: moments must see the SUMMED gradient of a
        row, not one update per duplicate)."""
        n = self.rows.shape[0]
        order = jnp.argsort(self.rows)
        r = jnp.take(self.rows, order)
        v = jnp.take(self.values, order, axis=0)
        first = jnp.concatenate(
            [jnp.ones((1,), bool), r[1:] != r[:-1]])
        seg = jnp.cumsum(first) - 1
        vals = jax.ops.segment_sum(v, seg, num_segments=n)
        # row id of each segment; empty segments get int32.min -> sentinel
        rows_u = jax.ops.segment_max(r, seg, num_segments=n)
        rows_u = jnp.where(rows_u < 0, self.height, rows_u)
        return SparseRows(rows_u, vals, self.height)


def is_sparse_rows(x) -> bool:
    return isinstance(x, SparseRows)
