"""Error-enforcement idiom.

TPU-native analog of the reference's ``PADDLE_ENFORCE`` family
(reference: paddle/fluid/platform/enforce.h). Errors carry the same
category taxonomy so user-facing messages are comparable, but raise
normal Python exceptions (there is no C++/Python boundary to marshal
across in the hot path — the whole step is one compiled XLA program).
"""

from __future__ import annotations


class EnforceNotMet(RuntimeError):
    """Base framework error (reference: enforce.h EnforceNotMet)."""


class InvalidArgumentError(EnforceNotMet, ValueError):
    pass


class NotFoundError(EnforceNotMet, KeyError):
    pass


class OutOfRangeError(EnforceNotMet, IndexError):
    pass


class AlreadyExistsError(EnforceNotMet):
    pass


class PreconditionNotMetError(EnforceNotMet):
    pass


class UnimplementedError(EnforceNotMet, NotImplementedError):
    pass


class UnavailableError(EnforceNotMet):
    """Resource/service exists but cannot be used right now (reference:
    platform/errors.h UNAVAILABLE)."""
    pass


def enforce(cond, msg="", *args, exc=InvalidArgumentError):
    """PADDLE_ENFORCE analog: raise ``exc`` with ``msg % args`` if not cond."""
    if not cond:
        raise exc(msg % args if args else msg)


def enforce_not_none(val, name=""):
    if val is None:
        raise NotFoundError("expected %r to be set, got None" % name)
    return val
