"""Shared shape-normalization helpers (the reference spreads private
_pair/_triple copies across layers; one canonical spot here)."""

from __future__ import annotations


def to_ntuple(v, n):
    """Normalize a scalar-or-sequence to an n-tuple."""
    if isinstance(v, (list, tuple)):
        return tuple(v)
    return (v,) * n


def pair(v):
    return to_ntuple(v, 2)


def triple(v):
    return to_ntuple(v, 3)
