"""Host + device introspection.

Reference: platform/cpu_info.cc (core counts, cache sizes,
FLAGS_fraction_of_cpu_memory_to_use), platform/gpu_info.cc (device
count, memory fractions). TPU-native: PJRT owns HBM, so this module
reports rather than budgets — memory_stats come from the runtime."""

from __future__ import annotations

import os
from typing import Dict, List, Optional


def cpu_core_count() -> int:
    return os.cpu_count() or 1


def cpu_memory_bytes() -> Optional[int]:
    try:
        pages = os.sysconf("SC_PHYS_PAGES")
        page_size = os.sysconf("SC_PAGE_SIZE")
        return pages * page_size
    except (ValueError, OSError):
        return None


def device_count() -> int:
    import jax
    return jax.device_count()


def device_properties(device_id: int = 0) -> Dict:
    """Kind + memory stats of one device (gpu_info.cc
    GpuMaxAllocSize analog; HBM numbers come straight from PJRT)."""
    import jax
    d = jax.devices()[device_id]
    props = {
        "device_kind": d.device_kind,
        "platform": d.platform,
        "id": d.id,
        "process_index": d.process_index,
    }
    try:
        stats = d.memory_stats() or {}
        props["bytes_limit"] = stats.get("bytes_limit")
        props["bytes_in_use"] = stats.get("bytes_in_use")
        props["peak_bytes_in_use"] = stats.get("peak_bytes_in_use")
    except Exception:
        pass  # CPU backend has no memory_stats
    return props


def all_device_properties() -> List[Dict]:
    import jax
    return [device_properties(i) for i in range(jax.device_count())]
