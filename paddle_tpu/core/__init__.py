"""Core substrate: scope, flags, errors, places.

Reference: the pybind ``core`` module (paddle/fluid/pybind/pybind.cc) +
platform/ (place.h, device_context.h). Device identity on TPU is a JAX
device or a mesh position; DeviceContext/stream management is owned by
PJRT/XLA, so Places here are lightweight tags for API parity.
"""

from __future__ import annotations

import jax

from .enforce import (AlreadyExistsError, EnforceNotMet,  # noqa: F401
                      InvalidArgumentError, NotFoundError,
                      OutOfRangeError, PreconditionNotMetError,
                      UnimplementedError, enforce, enforce_not_none)
from .flags import FLAGS  # noqa: F401
from .scope import Scope, global_scope  # noqa: F401


class CPUPlace:
    """Host place (reference: platform/place.h:26)."""

    def __repr__(self):
        return "CPUPlace"

    def __eq__(self, other):
        return isinstance(other, CPUPlace)


class TPUPlace:
    """Device place (TPU analog of CUDAPlace, place.h:37)."""

    def __init__(self, device_id=0):
        self.device_id = device_id

    def __repr__(self):
        return "TPUPlace(%d)" % self.device_id

    def __eq__(self, other):
        return (isinstance(other, TPUPlace)
                and other.device_id == self.device_id)


# CUDA-name alias for source compatibility with reference user scripts.
CUDAPlace = TPUPlace


class CUDAPinnedPlace:
    """Pinned host staging (place.h:52); host-side infeed buffers."""

    def __repr__(self):
        return "CUDAPinnedPlace"


def get_devices():
    return jax.devices()


def device_count():
    return jax.device_count()


def is_compiled_with_cuda():
    return False


def is_compiled_with_tpu():
    return any(d.platform != "cpu" for d in jax.devices())
