"""Scope: name -> value map with parent-chain lookup.

Reference: paddle/fluid/framework/scope.h:45 (``Scope::Var/FindVar/NewScope``).
Here a scope holds *device arrays* (jax.Array) for persistable variables —
parameters, optimizer accumulators, RNG state. Transient (per-step) values
never live in a scope: the whole step is one compiled XLA program and its
intermediates are XLA-managed, which is the TPU-native replacement for the
reference's per-op variable creation + garbage collection
(executor.cc:384, garbage_collector.cc).
"""

from __future__ import annotations

import itertools
from typing import Dict, Optional

from .enforce import NotFoundError

# Monotonic scope identity. ``id(scope)`` is reused by the allocator
# after a scope dies, so caches keyed on it (the collectives residual
# memo was) can silently treat a fresh scope as already-initialized;
# ``_uid`` never repeats within a process.
_scope_uid = itertools.count(1)


class Scope:
    def __init__(self, parent: Optional["Scope"] = None):
        self._vars: Dict[str, object] = {}
        self._parent = parent
        self._kids = []
        self._uid = next(_scope_uid)

    def new_scope(self) -> "Scope":
        kid = Scope(self)
        self._kids.append(kid)
        return kid

    def var(self, name: str):
        """Create-or-get in this scope (reference Scope::Var)."""
        if name not in self._vars:
            self._vars[name] = None
        return self._vars[name]

    def set_var(self, name: str, value):
        self._vars[name] = value

    def find_var(self, name: str):
        s = self
        while s is not None:
            if name in s._vars:
                return s._vars[name]
            s = s._parent
        return None

    def has_var(self, name: str) -> bool:
        s = self
        while s is not None:
            if name in s._vars:
                return True
            s = s._parent
        return False

    def get(self, name: str):
        v = self.find_var(name)
        if v is None and not self.has_var(name):
            raise NotFoundError("variable %r not found in scope" % name)
        return v

    def erase(self, name: str):
        self._vars.pop(name, None)

    def local_var_names(self):
        return list(self._vars.keys())

    def drop_kids(self):
        self._kids.clear()

    def drop_all(self):
        """Release every variable and child scope (frees the device
        buffers they pin — the reference's Scope::DeleteScope +
        variable erasure rolled into one; used between benchmark
        configs to return HBM)."""
        self._vars.clear()
        self._kids.clear()


_global_scope = Scope()


def global_scope() -> Scope:
    return _global_scope


def _reset_global_scope():
    """Test helper: fresh global scope."""
    global _global_scope
    _global_scope = Scope()
    return _global_scope
