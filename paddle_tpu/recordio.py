"""RecordIO — fault-tolerant chunked record files.

Reference: paddle/fluid/recordio/ (chunk.h, writer.h, scanner.h,
README.md). Records group into CRC-checksummed chunks; readers skip
corrupt/incomplete chunks (a crashed writer's tail) instead of
failing — the property industrial CTR pipelines rely on (SURVEY §2.2).

The hot path is C++ (native/recordio.cpp via ctypes — fread/CRC in
native code, GIL released during calls); a byte-compatible pure-Python
implementation serves as fallback and as the format's executable spec.
Both use the zlib CRC32 polynomial, so files interoperate.
"""

from __future__ import annotations

import ctypes
import os
import struct
import zlib
from typing import Iterator, Optional

from .core.enforce import InvalidArgumentError, enforce

MAGIC = 0x52494F31  # "RIO1"
_HEADER = struct.Struct("<IIII")  # magic, num_records, size, crc32
DEFAULT_CHUNK_BYTES = 1 << 20

_lib = None
_lib_tried = False


def _native():
    global _lib, _lib_tried
    if not _lib_tried:
        _lib_tried = True
        from . import native
        lib = native.load_library("recordio.cpp")
        if lib is not None:
            lib.rio_writer_open.restype = ctypes.c_void_p
            lib.rio_writer_open.argtypes = [ctypes.c_char_p,
                                            ctypes.c_uint64]
            lib.rio_writer_add.restype = ctypes.c_int
            lib.rio_writer_add.argtypes = [ctypes.c_void_p,
                                           ctypes.c_char_p,
                                           ctypes.c_uint64]
            lib.rio_writer_flush.argtypes = [ctypes.c_void_p]
            lib.rio_writer_close.argtypes = [ctypes.c_void_p]
            lib.rio_reader_open.restype = ctypes.c_void_p
            lib.rio_reader_open.argtypes = [ctypes.c_char_p]
            lib.rio_reader_next.restype = ctypes.c_int64
            lib.rio_reader_next.argtypes = [ctypes.c_void_p]
            lib.rio_reader_get.argtypes = [ctypes.c_void_p,
                                           ctypes.c_char_p]
            lib.rio_reader_skipped.restype = ctypes.c_uint64
            lib.rio_reader_skipped.argtypes = [ctypes.c_void_p]
            lib.rio_reader_close.argtypes = [ctypes.c_void_p]
        _lib = lib
    return _lib


class Writer:
    """Append records; chunks flush at ``max_chunk_bytes`` and on
    close (reference: recordio/writer.h)."""

    def __init__(self, path, max_chunk_bytes=DEFAULT_CHUNK_BYTES):
        self._path = path
        self._max = int(max_chunk_bytes)
        lib = _native()
        self._h = None
        self._f = None
        if lib is not None:
            self._h = lib.rio_writer_open(path.encode(), self._max)
        if self._h is None:
            # pure-python fallback
            self._f = open(path, "wb")
            self._payload = bytearray()
            self._num = 0

    def write(self, record: bytes):
        if isinstance(record, str):
            record = record.encode()
        if self._h is not None:
            rc = _native().rio_writer_add(self._h, record, len(record))
            enforce(rc == 0, "recordio write failed: %s", self._path,
                    exc=IOError)
            return
        self._payload += struct.pack("<I", len(record)) + record
        self._num += 1
        if len(self._payload) >= self._max:
            self._flush_py()

    def _flush_py(self):
        if not self._num:
            return
        payload = bytes(self._payload)
        self._f.write(_HEADER.pack(MAGIC, self._num, len(payload),
                                   zlib.crc32(payload)))
        self._f.write(payload)
        self._f.flush()
        self._payload = bytearray()
        self._num = 0

    def flush(self):
        if self._h is not None:
            _native().rio_writer_flush(self._h)
        else:
            self._flush_py()

    def close(self):
        if self._h is not None:
            _native().rio_writer_close(self._h)
            self._h = None
        elif self._f is not None:
            self._flush_py()
            self._f.close()
            self._f = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


class Scanner:
    """Iterate records; corrupt or truncated chunks are skipped and
    counted in ``skipped_chunks`` (reference: recordio/scanner.h +
    README fault-tolerant reading)."""

    def __init__(self, path):
        enforce(os.path.exists(path), "no such recordio file: %s",
                path, exc=InvalidArgumentError)
        self._path = path
        self._py_skipped = 0
        self._native_skipped = 0

    @property
    def skipped_chunks(self) -> int:
        """Corrupt chunks skipped by the most recent iteration."""
        return self._native_skipped or self._py_skipped

    def __iter__(self) -> Iterator[bytes]:
        """Each iteration scans the file from the start; the native
        reader handle lives only for the duration of one pass (no
        leaked FILE* when a Scanner is constructed but abandoned)."""
        lib = _native()
        if lib is not None:
            h = lib.rio_reader_open(self._path.encode())
            enforce(h is not None, "cannot open %s", self._path,
                    exc=IOError)
            try:
                while True:
                    n = lib.rio_reader_next(h)
                    if n < 0:
                        break
                    buf = ctypes.create_string_buffer(n)
                    lib.rio_reader_get(h, buf)
                    yield buf.raw
            finally:
                self._native_skipped = int(lib.rio_reader_skipped(h))
                lib.rio_reader_close(h)
            return
        self._py_skipped = 0
        yield from self._iter_py()

    def _iter_py(self):
        with open(self._path, "rb") as f:
            data = f.read()
        off = 0
        while off + _HEADER.size <= len(data):
            magic, num, size, crc = _HEADER.unpack_from(data, off)
            if magic != MAGIC:
                nxt = data.find(struct.pack("<I", MAGIC), off + 1)
                self._py_skipped += 1
                if nxt < 0:
                    return
                off = nxt
                continue
            payload = data[off + _HEADER.size:
                           off + _HEADER.size + size]
            if len(payload) < size:
                # truncated tail OR corrupted size field — resync on
                # the next magic (none left at a genuine tail)
                self._py_skipped += 1
                nxt = data.find(struct.pack("<I", MAGIC), off + 1)
                if nxt < 0:
                    return
                off = nxt
                continue
            if zlib.crc32(payload) != crc:
                self._py_skipped += 1
                nxt = data.find(struct.pack("<I", MAGIC),
                                off + 1)
                if nxt < 0:
                    return
                off = nxt
                continue
            pos, ok, recs = 0, True, []
            for _ in range(num):
                if pos + 4 > len(payload):
                    ok = False
                    break
                (ln,) = struct.unpack_from("<I", payload, pos)
                pos += 4
                if pos + ln > len(payload):
                    ok = False
                    break
                recs.append(payload[pos:pos + ln])
                pos += ln
            off += _HEADER.size + size
            if not ok:
                self._py_skipped += 1
                continue
            yield from recs


def write_records(path, records, max_chunk_bytes=DEFAULT_CHUNK_BYTES):
    """Convenience: dump an iterable of byte strings."""
    with Writer(path, max_chunk_bytes) as w:
        for r in records:
            w.write(r)


def read_records(path):
    return list(Scanner(path))
