"""Reader composition toolkit (reference: python/paddle/reader/ —
decorator.py combinators over "reader creators": zero-arg callables
returning sample iterators)."""

from .decorator import (buffered, cache, chain, compose,  # noqa: F401
                        firstn, map_readers, shuffle, xmap_readers)
from .decorator import (ComposeNotAligned, Fake,  # noqa: F401
                        PipeReader, batch, multiprocess_reader)
