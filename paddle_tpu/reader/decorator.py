"""Reader-creator combinators (reference:
python/paddle/reader/decorator.py — map_readers:35, shuffle:62,
chain:92, compose:130, buffered:180, firstn:252, xmap_readers:279;
batch lives in python/paddle/batch.py).

A *reader creator* is a zero-arg callable returning an iterator of
samples. Combinators wrap creators and return new creators — pure-host
python; the device never sees any of this (feeding happens via
DataFeeder/PyReader)."""

from __future__ import annotations

import queue
import random
import threading
from itertools import chain as it_chain

__all__ = ["map_readers", "shuffle", "chain", "compose", "buffered",
           "firstn", "xmap_readers", "cache", "batch"]


def map_readers(func, *readers):
    """Element-wise zip+map over several readers (reference :35)."""

    def reader():
        for vals in zip(*(r() for r in readers)):
            yield func(*vals)

    return reader


def shuffle(reader, buf_size):
    """Pool-based shuffle with a buf_size reservoir (reference :62)."""

    def shuffled():
        buf = []
        for e in reader():
            buf.append(e)
            if len(buf) >= buf_size:
                random.shuffle(buf)
                yield from buf
                buf = []
        if buf:
            random.shuffle(buf)
            yield from buf

    return shuffled


def chain(*readers):
    """Concatenate readers back to back (reference :92)."""

    def reader():
        return it_chain(*(r() for r in readers))

    return reader


def compose(*readers, check_alignment=True):
    """Zip readers into tuple samples (reference :130)."""

    def _flatten(x):
        return x if isinstance(x, tuple) else (x,)

    def reader():
        its = [r() for r in readers]
        while True:
            rows = []
            done = 0
            for it in its:
                try:
                    rows.append(_flatten(next(it)))
                except StopIteration:
                    done += 1
                    rows.append(None)
            if done == len(its):
                return
            if done > 0:
                if check_alignment:
                    raise RuntimeError(
                        "compose: readers of different lengths")
                return
            yield sum(rows, ())

    return reader


def buffered(reader, size):
    """Background-thread prefetch into a bounded queue (reference
    :180) — keeps the host pipeline ahead of the device step."""

    class _End:
        pass

    def data_reader():
        r = reader()
        q = queue.Queue(maxsize=size)
        err = []

        def _fill():
            try:
                for d in r:
                    q.put(d)
            except BaseException as e:  # re-raised on the consumer side
                err.append(e)
            finally:
                q.put(_End)

        t = threading.Thread(target=_fill, daemon=True)
        t.start()
        while True:
            e = q.get()
            if e is _End:
                if err:
                    raise err[0]
                return
            yield e

    return data_reader


def firstn(reader, n):
    """First n samples (reference :252)."""

    def firstn_reader():
        for i, item in enumerate(reader()):
            if i >= n:
                return
            yield item

    return firstn_reader


def xmap_readers(mapper, reader, process_num, buffer_size,
                 order=False):
    """Parallel map with worker threads (reference :279). order=True
    preserves input order."""

    def ordered():
        # single pipeline thread keeps ordering trivially correct
        for s in buffered(map_readers(mapper, reader), buffer_size)():
            yield s

    if order:
        return ordered

    end = object()

    def data_reader():
        in_q = queue.Queue(buffer_size)
        out_q = queue.Queue(buffer_size)
        err = []

        def _feed():
            try:
                for s in reader():
                    in_q.put(s)
            except BaseException as e:
                err.append(e)
            finally:
                for _ in range(process_num):
                    in_q.put(end)

        def _work():
            try:
                while True:
                    s = in_q.get()
                    if s is end:
                        return
                    out_q.put(mapper(s))
            except BaseException as e:
                err.append(e)
            finally:
                out_q.put(end)

        threading.Thread(target=_feed, daemon=True).start()
        workers = [threading.Thread(target=_work, daemon=True)
                   for _ in range(process_num)]
        for w in workers:
            w.start()
        finished = 0
        while finished < process_num:
            s = out_q.get()
            if s is end:
                finished += 1
            else:
                yield s
        if err:
            raise err[0]

    return data_reader


def cache(reader):
    """Materialize once, replay from memory (reference: cache)."""
    all_data = []
    filled = [False]

    def cache_reader():
        if not filled[0]:
            data = list(reader())  # atomic: partial fills don't stick
            all_data.extend(data)
            filled[0] = True
        yield from all_data

    return cache_reader


def batch(reader, batch_size, drop_last=False):
    """Group samples into lists of batch_size (reference:
    python/paddle/batch.py)."""

    def batch_reader():
        b = []
        for inst in reader():
            b.append(inst)
            if len(b) == batch_size:
                yield b
                b = []
        if b and not drop_last:
            yield b

    return batch_reader
