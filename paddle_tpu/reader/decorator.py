"""Reader-creator combinators (reference:
python/paddle/reader/decorator.py — map_readers:35, shuffle:62,
chain:92, compose:130, buffered:180, firstn:252, xmap_readers:279;
batch lives in python/paddle/batch.py).

A *reader creator* is a zero-arg callable returning an iterator of
samples. Combinators wrap creators and return new creators — pure-host
python; the device never sees any of this (feeding happens via
DataFeeder/PyReader)."""

from __future__ import annotations

import queue
import random
import threading
from itertools import chain as it_chain

__all__ = ["ComposeNotAligned", "Fake", "PipeReader",
           "multiprocess_reader",
           "map_readers", "shuffle", "chain", "compose", "buffered",
           "firstn", "xmap_readers", "cache", "batch"]


class ComposeNotAligned(ValueError):
    """Raised by compose() when readers end at different lengths
    (reference reader/decorator.py:44)."""


def map_readers(func, *readers):
    """Element-wise zip+map over several readers (reference :35)."""

    def reader():
        for vals in zip(*(r() for r in readers)):
            yield func(*vals)

    return reader


def shuffle(reader, buf_size):
    """Pool-based shuffle with a buf_size reservoir (reference :62)."""

    def shuffled():
        buf = []
        for e in reader():
            buf.append(e)
            if len(buf) >= buf_size:
                random.shuffle(buf)
                yield from buf
                buf = []
        if buf:
            random.shuffle(buf)
            yield from buf

    return shuffled


def chain(*readers):
    """Concatenate readers back to back (reference :92)."""

    def reader():
        return it_chain(*(r() for r in readers))

    return reader


def compose(*readers, check_alignment=True):
    """Zip readers into tuple samples (reference :130)."""

    def _flatten(x):
        return x if isinstance(x, tuple) else (x,)

    def reader():
        its = [r() for r in readers]
        while True:
            rows = []
            done = 0
            for it in its:
                try:
                    rows.append(_flatten(next(it)))
                except StopIteration:
                    done += 1
                    rows.append(None)
            if done == len(its):
                return
            if done > 0:
                if check_alignment:
                    raise ComposeNotAligned(
                        "compose: readers of different lengths")
                return
            yield sum(rows, ())

    return reader


def buffered(reader, size):
    """Background-thread prefetch into a bounded queue (reference
    :180) — keeps the host pipeline ahead of the device step. The
    filler ALWAYS terminates with an end sentinel (after an upstream
    exception too, which is re-raised consumer-side), and a consumer
    that abandons the iterator early releases the filler instead of
    leaving it blocked on the full queue pinning ``size`` samples."""

    class _End:
        pass

    def data_reader():
        # the one shared put/stop contract (pyreader._bounded_put);
        # imported lazily so this pure-host combinator module doesn't
        # pull the framework in at import time
        from ..pyreader import _bounded_put
        r = reader()
        q = queue.Queue(maxsize=size)
        err = []
        stop = threading.Event()

        def _fill():
            try:
                for d in r:
                    if not _bounded_put(q, stop, d):
                        return  # consumer abandoned iteration
            except BaseException as e:  # re-raised on the consumer side
                err.append(e)
            finally:
                _bounded_put(q, stop, _End)

        t = threading.Thread(target=_fill, daemon=True)
        t.start()
        try:
            while True:
                e = q.get()
                if e is _End:
                    if err:
                        raise err[0]
                    return
                yield e
        finally:
            stop.set()

    return data_reader


def firstn(reader, n):
    """First n samples (reference :252)."""

    def firstn_reader():
        for i, item in enumerate(reader()):
            if i >= n:
                return
            yield item

    return firstn_reader


def xmap_readers(mapper, reader, process_num, buffer_size,
                 order=False):
    """Parallel map with worker threads (reference :279). order=True
    preserves input order."""

    def ordered():
        # single pipeline thread keeps ordering trivially correct
        for s in buffered(map_readers(mapper, reader), buffer_size)():
            yield s

    if order:
        return ordered

    end = object()

    def data_reader():
        in_q = queue.Queue(buffer_size)
        out_q = queue.Queue(buffer_size)
        err = []

        def _feed():
            try:
                for s in reader():
                    in_q.put(s)
            except BaseException as e:
                err.append(e)
            finally:
                for _ in range(process_num):
                    in_q.put(end)

        def _work():
            try:
                while True:
                    s = in_q.get()
                    if s is end:
                        return
                    out_q.put(mapper(s))
            except BaseException as e:
                err.append(e)
            finally:
                out_q.put(end)

        threading.Thread(target=_feed, daemon=True).start()
        workers = [threading.Thread(target=_work, daemon=True)
                   for _ in range(process_num)]
        for w in workers:
            w.start()
        finished = 0
        while finished < process_num:
            s = out_q.get()
            if s is end:
                finished += 1
            else:
                yield s
        if err:
            raise err[0]

    return data_reader


def cache(reader):
    """Materialize once, replay from memory (reference: cache)."""
    all_data = []
    filled = [False]

    def cache_reader():
        if not filled[0]:
            data = list(reader())  # atomic: partial fills don't stick
            all_data.extend(data)
            filled[0] = True
        yield from all_data

    return cache_reader


def batch(reader, batch_size, drop_last=False):
    """Group samples into lists of batch_size (reference:
    python/paddle/batch.py)."""

    def batch_reader():
        b = []
        for inst in reader():
            b.append(inst)
            if len(b) == batch_size:
                yield b
                b = []
        if b and not drop_last:
            yield b

    return batch_reader


class Fake:
    """Caches the FIRST sample and replays it forever-ish (reference
    reader/decorator.py:437 Fake): pipeline benchmarking without real
    data cost. Call the instance with (reader, length)."""

    def __init__(self):
        self.data = None
        self.yield_num = 0

    def __call__(self, reader, length):
        def fake_reader():
            if self.data is None:
                self.data = next(reader())
            while self.yield_num < length:
                self.yield_num += 1
                yield self.data
            self.yield_num = 0

        return fake_reader


class _WorkerError:
    """Crosses the process boundary in place of the sentinel when a
    worker raises, carrying the original error text."""

    def __init__(self, msg):
        self.msg = msg


def _mp_work(r, put):
    """Worker body shared by the queue and pipe paths: samples, then
    ALWAYS a terminator — None on success, _WorkerError on failure.
    A reader yielding None is an error (the reference's
    'sample has None' ValueError): None is the exhaustion sentinel."""
    try:
        for sample in r():
            if sample is None:
                raise ValueError(
                    "multiprocess_reader: sample has None")
            put(sample)
        put(None)
    except Exception as e:  # noqa: BLE001 — crosses process boundary
        put(_WorkerError("%s: %s" % (type(e).__name__, e)))


def multiprocess_reader(readers, use_pipe=True, queue_size=1000):
    """Fan readers out over worker PROCESSES (reference
    reader/decorator.py:480): ``use_pipe`` streams each worker over
    its own os pipe (no /dev/shm requirement), else one shared
    multiprocessing.Queue. Sample order interleaves arbitrarily."""
    import multiprocessing
    import queue as queue_mod

    def _finish(item, live):
        if isinstance(item, _WorkerError):
            raise RuntimeError(
                "multiprocess_reader worker failed: %s" % item.msg)
        assert item is None
        return live - 1

    def queue_reader():
        q = multiprocessing.Queue(queue_size)
        procs = [multiprocessing.Process(
            target=_mp_work, args=(r, q.put), daemon=True)
            for r in readers]
        for pr in procs:
            pr.start()
        live = len(readers)
        try:
            while live > 0:
                try:
                    sample = q.get(timeout=5)
                except queue_mod.Empty:
                    # a crashed worker can die between samples without
                    # its terminator (e.g. SIGKILL); poll liveness
                    # instead of hanging forever
                    dead = [pr for pr in procs
                            if not pr.is_alive()
                            and pr.exitcode not in (0, None)]
                    if dead:
                        raise RuntimeError(
                            "multiprocess_reader: worker exited "
                            "rc=%s without finishing"
                            % dead[0].exitcode)
                    continue
                if sample is None or isinstance(sample, _WorkerError):
                    live = _finish(sample, live)
                else:
                    yield sample
        finally:
            for pr in procs:
                if pr.is_alive():
                    pr.terminate()

    def pipe_reader():
        conns, procs = [], []
        for r in readers:
            rx, tx = multiprocessing.Pipe(duplex=False)
            pr = multiprocessing.Process(
                target=_mp_work, args=(r, tx.send), daemon=True)
            procs.append(pr)
            conns.append(rx)
            pr.start()
            tx.close()
        try:
            while conns:
                ready = multiprocessing.connection.wait(conns,
                                                        timeout=5)
                if not ready:
                    dead = [pr for pr in procs
                            if not pr.is_alive()
                            and pr.exitcode not in (0, None)]
                    if dead:
                        raise RuntimeError(
                            "multiprocess_reader: worker exited "
                            "rc=%s without finishing"
                            % dead[0].exitcode)
                    continue
                for rx in ready:
                    try:
                        sample = rx.recv()
                    except EOFError:
                        conns.remove(rx)
                        continue
                    if sample is None or isinstance(sample,
                                                    _WorkerError):
                        _finish(sample, 0)
                        conns.remove(rx)
                    else:
                        yield sample
        finally:
            for pr in procs:
                if pr.is_alive():
                    pr.terminate()

    return pipe_reader if use_pipe else queue_reader


class PipeReader:
    """Stream samples from a shell command's stdout (reference
    reader/decorator.py:550 — the HDFS-cat ingestion path).
    get_line() yields decoded lines split on ``cut_lines``."""

    def __init__(self, command, bufsize=8192, file_type="plain"):
        import subprocess
        if not isinstance(command, str):
            raise TypeError("PipeReader command must be a string")
        if file_type not in ("plain", "gzip"):
            raise TypeError("PipeReader file_type %r is not allowed "
                            "(plain, gzip)" % (file_type,))
        self.command = command
        self.bufsize = bufsize
        self.file_type = file_type
        self.process = subprocess.Popen(
            command.split(" "), bufsize=bufsize,
            stdout=subprocess.PIPE)

    def get_line(self, cut_lines=True, line_break="\n"):
        remained = ""
        while True:
            buff = self.process.stdout.read(self.bufsize)
            if not buff:
                break
            if self.file_type == "gzip":
                import zlib
                decomp = getattr(self, "_decomp", None)
                if decomp is None:
                    decomp = self._decomp = zlib.decompressobj(
                        32 + zlib.MAX_WBITS)
                buff = decomp.decompress(buff)
            buff = buff.decode("utf-8", "replace")
            if cut_lines:
                lines = (remained + buff).split(line_break)
                remained = lines.pop(-1)
                for line in lines:
                    yield line
            else:
                yield buff
        if remained:
            yield remained
