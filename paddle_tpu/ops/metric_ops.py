"""Metric ops — metrics run on-device, in-graph, like the reference
(paddle/fluid/operators/metrics/: accuracy_op.cc, auc_op.cc,
precision_recall_op.cc)."""

from __future__ import annotations

import jax.numpy as jnp

from .registry import register


@register("accuracy", ["Out", "Indices", "Label"],
          ["Accuracy", "Correct", "Total"], differentiable=False)
def accuracy(out, indices, label):
    """top-k accuracy given top_k's (values, indices) and int labels
    (reference: accuracy_op.cc)."""
    lab = label.squeeze(-1) if label.ndim == 2 else label
    correct = jnp.any(indices == lab[:, None], axis=1)
    num_correct = jnp.sum(correct.astype(jnp.float32))
    total = jnp.asarray(lab.shape[0], dtype=jnp.float32)
    return num_correct / total, num_correct, total


@register("auc", ["Predict", "Label", "StatPos", "StatNeg"],
          ["AUC", "StatPosOut", "StatNegOut"], differentiable=False)
def auc(predict, label, stat_pos, stat_neg, *, num_thresholds=4095):
    """Streaming AUC via threshold buckets (reference: auc_op.cc).
    stat_pos/stat_neg are persistable bucket counters the program wires
    back in place."""
    lab = label.squeeze(-1) if label.ndim == 2 else label
    pos_prob = predict[:, 1] if predict.ndim == 2 and predict.shape[1] == 2 \
        else predict.reshape(-1)
    bucket = jnp.clip((pos_prob * num_thresholds).astype(jnp.int32), 0,
                      num_thresholds)
    is_pos = (lab > 0).astype(stat_pos.dtype)
    pos_new = stat_pos.at[bucket].add(is_pos)
    neg_new = stat_neg.at[bucket].add(1.0 - is_pos)
    # trapezoid integration over buckets, descending threshold
    pos_rev = jnp.flip(pos_new)
    neg_rev = jnp.flip(neg_new)
    tp = jnp.cumsum(pos_rev)
    fp = jnp.cumsum(neg_rev)
    tot_pos = tp[-1]
    tot_neg = fp[-1]
    tp_prev = jnp.concatenate([jnp.zeros(1, tp.dtype), tp[:-1]])
    fp_prev = jnp.concatenate([jnp.zeros(1, fp.dtype), fp[:-1]])
    area = jnp.sum((fp - fp_prev) * (tp + tp_prev) / 2.0)
    auc_val = jnp.where(tot_pos * tot_neg > 0,
                        area / jnp.maximum(tot_pos * tot_neg, 1.0), 0.0)
    return auc_val, pos_new, neg_new


@register("precision_recall",
          ["MaxProbs", "Indices", "Labels", "StatesInfo"],
          ["BatchMetrics", "AccumMetrics", "AccumStatesInfo"],
          differentiable=False)
def precision_recall(max_probs, indices, labels, states, *, class_number):
    lab = labels.squeeze(-1) if labels.ndim == 2 else labels
    pred = indices.reshape(-1)
    ids = jnp.arange(class_number)
    tp = jnp.sum((pred[:, None] == ids) & (lab[:, None] == ids), axis=0)
    fp = jnp.sum((pred[:, None] == ids) & (lab[:, None] != ids), axis=0)
    fn = jnp.sum((pred[:, None] != ids) & (lab[:, None] == ids), axis=0)
    batch = jnp.stack([tp, fp, fn], axis=1).astype(jnp.float32)
    accum = states + batch

    def _metrics(s):
        tp_, fp_, fn_ = s[:, 0], s[:, 1], s[:, 2]
        prec = tp_ / jnp.maximum(tp_ + fp_, 1.0)
        rec = tp_ / jnp.maximum(tp_ + fn_, 1.0)
        f1 = 2 * prec * rec / jnp.maximum(prec + rec, 1e-6)
        return jnp.stack([jnp.mean(prec), jnp.mean(rec), jnp.mean(f1),
                          jnp.mean(prec), jnp.mean(rec), jnp.mean(f1)])

    return _metrics(batch), _metrics(accum), accum
