"""Optimizer update ops.

Reference: paddle/fluid/operators/optimizers/ (~4.4k LoC: sgd_op.cc,
momentum_op.cc w/ LARS variant, adam_op.cc, adamax_op, adagrad_op,
adadelta_op, rmsprop_op, decayed_adagrad_op, proximal_*, ftrl_op,
lamb_op). Optimizer state lives in persistable vars, updates are ops in
the graph — exactly the reference's design, which is ALSO the idiomatic
TPU design: the whole (fwd + bwd + update) step is one XLA program, so
parameter updates fuse and stay in HBM.

Each op returns the updated param + state; the program wires the outputs
back to the same var names (in-place, as the reference's ParamOut ==
Param). The executor donates the old buffers to XLA.
"""

from __future__ import annotations

import jax.numpy as jnp
from jax import lax

from ..core.selected_rows import SparseRows
from .registry import register

# Sparse (SelectedRows) update kernels: the reference implements sparse
# variants for sgd/momentum/adam/adagrad (sgd_op.h SparseSGDFunctor,
# adam_op.h SparseAdamFunctor with lazy_mode, adagrad_op.h, momentum's
# SelectedRows path). Here each dense update fn branches on a SparseRows
# grad: merge duplicate rows, gather the touched optimizer-state rows,
# update, scatter back (mode="drop" ignores merge-sentinel rows). The
# table is never densified, so update cost is O(touched rows).


@register("sgd", ["Param", "Grad", "LearningRate"], ["ParamOut"],
          differentiable=False)
def sgd(param, grad, lr):
    if isinstance(grad, SparseRows):
        # linear update: duplicates sum correctly without a merge
        upd = (lr * grad.values).astype(param.dtype)
        return param.at[grad.rows].add(-upd, mode="drop")
    return param - lr * grad


@register("momentum", ["Param", "Grad", "Velocity", "LearningRate"],
          ["ParamOut", "VelocityOut"], differentiable=False)
def momentum(param, grad, velocity, lr, *, mu, use_nesterov=False):
    if isinstance(grad, SparseRows):
        g = grad.merged()
        rows, vals = g.rows, g.values
        vr = mu * velocity[rows] + vals
        if use_nesterov:
            upd = (vals + mu * vr) * lr
        else:
            upd = lr * vr
        return (param.at[rows].add(-upd.astype(param.dtype),
                                   mode="drop"),
                velocity.at[rows].set(vr.astype(velocity.dtype),
                                      mode="drop"))
    v = mu * velocity + grad
    if use_nesterov:
        p = param - (grad + mu * v) * lr
    else:
        p = param - lr * v
    return p, v


@register("lars_momentum", ["Param", "Grad", "Velocity", "LearningRate"],
          ["ParamOut", "VelocityOut"], differentiable=False)
def lars_momentum(param, grad, velocity, lr, *, mu, lars_coeff=0.001,
                  lars_weight_decay=0.0005, epsilon=1e-9):
    pn = jnp.sqrt(jnp.sum(jnp.square(param)))
    gn = jnp.sqrt(jnp.sum(jnp.square(grad)))
    local_lr = lr * lars_coeff * pn / (gn + lars_weight_decay * pn
                                       + epsilon)
    v = mu * velocity + local_lr * (grad + lars_weight_decay * param)
    return param - v, v


@register("adam",
          ["Param", "Grad", "Moment1", "Moment2", "Beta1Pow", "Beta2Pow",
           "LearningRate"],
          ["ParamOut", "Moment1Out", "Moment2Out", "Beta1PowOut",
           "Beta2PowOut"],
          differentiable=False)
def adam(param, grad, m1, m2, b1p, b2p, lr, *, beta1=0.9, beta2=0.999,
         epsilon=1e-8, lazy_mode=False):
    """Reference: adam_op.cc (+ fuse_adam_op_pass — here fusion across
    params happens automatically because all updates sit in one XLA
    program). Pallas fused variant in ops/pallas/fused_adam.py. A
    SparseRows grad takes the reference's lazy sparse path
    (adam_op.h SparseAdamFunctor): only touched rows' moments and
    params update; beta-power state still advances globally."""
    if isinstance(grad, SparseRows):
        g = grad.merged()
        rows, vals = g.rows, g.values
        lr_t = lr * jnp.sqrt(1.0 - b2p) / (1.0 - b1p)
        if lazy_mode:
            # rows-only: moments and params of untouched rows frozen
            # (adam_op.h lazy_mode=true) — the industrial-scale path
            m1r = beta1 * m1[rows] + (1.0 - beta1) * vals
            m2r = beta2 * m2[rows] + (1.0 - beta2) * jnp.square(vals)
            upd = lr_t * m1r / (jnp.sqrt(m2r) + epsilon)
            return (param.at[rows].add(-upd.astype(param.dtype),
                                       mode="drop"),
                    m1.at[rows].set(m1r.astype(m1.dtype), mode="drop"),
                    m2.at[rows].set(m2r.astype(m2.dtype), mode="drop"),
                    b1p * beta1, b2p * beta2)
        # non-lazy (reference default): identical trajectory to the
        # dense update with a zero-filled grad — moments decay on every
        # row; only the grad itself stays sparse (no densify)
        m1n = (beta1 * m1).at[rows].add(
            ((1.0 - beta1) * vals).astype(m1.dtype), mode="drop")
        m2n = (beta2 * m2).at[rows].add(
            ((1.0 - beta2) * jnp.square(vals)).astype(m2.dtype),
            mode="drop")
        pn = param - lr_t * m1n / (jnp.sqrt(m2n) + epsilon)
        return pn, m1n, m2n, b1p * beta1, b2p * beta2
    m1n = beta1 * m1 + (1.0 - beta1) * grad
    m2n = beta2 * m2 + (1.0 - beta2) * jnp.square(grad)
    lr_t = lr * jnp.sqrt(1.0 - b2p) / (1.0 - b1p)
    pn = param - lr_t * m1n / (jnp.sqrt(m2n) + epsilon)
    return pn, m1n, m2n, b1p * beta1, b2p * beta2


@register("adamw",
          ["Param", "Grad", "Moment1", "Moment2", "Beta1Pow", "Beta2Pow",
           "LearningRate"],
          ["ParamOut", "Moment1Out", "Moment2Out", "Beta1PowOut",
           "Beta2PowOut"],
          differentiable=False)
def adamw(param, grad, m1, m2, b1p, b2p, lr, *, beta1=0.9, beta2=0.999,
          epsilon=1e-8, weight_decay=0.01):
    m1n = beta1 * m1 + (1.0 - beta1) * grad
    m2n = beta2 * m2 + (1.0 - beta2) * jnp.square(grad)
    lr_t = lr * jnp.sqrt(1.0 - b2p) / (1.0 - b1p)
    pn = param - lr_t * (m1n / (jnp.sqrt(m2n) + epsilon)) \
        - lr * weight_decay * param
    return pn, m1n, m2n, b1p * beta1, b2p * beta2


@register("adamax",
          ["Param", "Grad", "Moment", "InfNorm", "Beta1Pow",
           "LearningRate"],
          ["ParamOut", "MomentOut", "InfNormOut", "Beta1PowOut"],
          differentiable=False)
def adamax(param, grad, moment, inf_norm, b1p, lr, *, beta1=0.9,
           beta2=0.999, epsilon=1e-8):
    mn = beta1 * moment + (1.0 - beta1) * grad
    inf_n = jnp.maximum(beta2 * inf_norm, jnp.abs(grad))
    lr_t = lr / (1.0 - b1p)
    pn = param - lr_t * mn / (inf_n + epsilon)
    return pn, mn, inf_n, b1p * beta1


@register("adagrad", ["Param", "Grad", "Moment", "LearningRate"],
          ["ParamOut", "MomentOut"], differentiable=False)
def adagrad(param, grad, moment, lr, *, epsilon=1e-6):
    if isinstance(grad, SparseRows):
        g = grad.merged()
        rows, vals = g.rows, g.values
        mr = moment[rows] + jnp.square(vals)
        upd = lr * vals / (jnp.sqrt(mr) + epsilon)
        return (param.at[rows].add(-upd.astype(param.dtype),
                                   mode="drop"),
                moment.at[rows].set(mr.astype(moment.dtype),
                                    mode="drop"))
    mn = moment + jnp.square(grad)
    return param - lr * grad / (jnp.sqrt(mn) + epsilon), mn


@register("decayed_adagrad", ["Param", "Grad", "Moment", "LearningRate"],
          ["ParamOut", "MomentOut"], differentiable=False)
def decayed_adagrad(param, grad, moment, lr, *, decay=0.95, epsilon=1e-6):
    mn = decay * moment + (1.0 - decay) * jnp.square(grad)
    return param - lr * grad / (jnp.sqrt(mn) + epsilon), mn


@register("adadelta", ["Param", "Grad", "AvgSquaredGrad",
                       "AvgSquaredUpdate"],
          ["ParamOut", "AvgSquaredGradOut", "AvgSquaredUpdateOut"],
          differentiable=False)
def adadelta(param, grad, avg_sq_grad, avg_sq_upd, *, rho=0.95,
             epsilon=1e-6):
    asg = rho * avg_sq_grad + (1.0 - rho) * jnp.square(grad)
    update = -jnp.sqrt((avg_sq_upd + epsilon) / (asg + epsilon)) * grad
    asu = rho * avg_sq_upd + (1.0 - rho) * jnp.square(update)
    return param + update, asg, asu


@register("rmsprop", ["Param", "Grad", "Moment", "MeanSquare", "MeanGrad",
                      "LearningRate"],
          ["ParamOut", "MomentOut", "MeanSquareOut", "MeanGradOut"],
          differentiable=False)
def rmsprop(param, grad, moment, mean_square, mean_grad, lr, *, rho=0.95,
            epsilon=1e-6, momentum=0.0, centered=False):
    ms = rho * mean_square + (1.0 - rho) * jnp.square(grad)
    if centered:
        mg = rho * mean_grad + (1.0 - rho) * grad
        denom = ms - jnp.square(mg) + epsilon
    else:
        mg = mean_grad
        denom = ms + epsilon
    mom = momentum * moment + lr * grad * lax.rsqrt(denom)
    return param - mom, mom, ms, mg


@register("ftrl", ["Param", "Grad", "SquaredAccumulator",
                   "LinearAccumulator", "LearningRate"],
          ["ParamOut", "SquaredAccumOut", "LinearAccumOut"],
          differentiable=False)
def ftrl(param, grad, sq_accum, lin_accum, lr, *, l1=0.0, l2=0.0,
         lr_power=-0.5):
    new_sq = sq_accum + jnp.square(grad)
    sigma = (jnp.power(new_sq, -lr_power)
             - jnp.power(sq_accum, -lr_power)) / lr
    new_lin = lin_accum + grad - sigma * param
    x = l1 * jnp.sign(new_lin) - new_lin
    y = jnp.power(new_sq, -lr_power) / lr + 2.0 * l2
    pre = x / y
    pn = jnp.where(jnp.abs(new_lin) > l1, pre, jnp.zeros_like(param))
    return pn, new_sq, new_lin


@register("lamb",
          ["Param", "Grad", "Moment1", "Moment2", "Beta1Pow", "Beta2Pow",
           "LearningRate"],
          ["ParamOut", "Moment1Out", "Moment2Out", "Beta1PowOut",
           "Beta2PowOut"],
          differentiable=False)
def lamb(param, grad, m1, m2, b1p, b2p, lr, *, beta1=0.9, beta2=0.999,
         epsilon=1e-6, weight_decay=0.01):
    """Reference: lamb_op.cc — layer-adaptive large-batch optimizer."""
    m1n = beta1 * m1 + (1.0 - beta1) * grad
    m2n = beta2 * m2 + (1.0 - beta2) * jnp.square(grad)
    m1h = m1n / (1.0 - b1p)
    m2h = m2n / (1.0 - b2p)
    r = m1h / (jnp.sqrt(m2h) + epsilon) + weight_decay * param
    w_norm = jnp.sqrt(jnp.sum(jnp.square(param)))
    r_norm = jnp.sqrt(jnp.sum(jnp.square(r)))
    ratio = jnp.where((w_norm > 0) & (r_norm > 0), w_norm / r_norm, 1.0)
    return param - lr * ratio * r, m1n, m2n, b1p * beta1, b2p * beta2


@register("proximal_gd", ["Param", "Grad", "LearningRate"], ["ParamOut"],
          differentiable=False)
def proximal_gd(param, grad, lr, *, l1=0.0, l2=0.0):
    prox = param - lr * grad
    if l1 > 0:
        prox = jnp.sign(prox) * jnp.maximum(jnp.abs(prox) - lr * l1, 0.0)
    return prox / (1.0 + lr * l2)


# -- gradient accumulation (batch merge) -----------------------------------
# Reference: framework/ir/multi_batch_merge_pass.cc replicates the
# fwd/bwd subgraph N times and runs the optimizer section once per N
# micro-batches. The TPU-native formulation keeps ONE program: a
# persistable accumulator per parameter plus a step counter, with the
# update ops gated (executor._gate_result selects old vs new state) —
# no graph replication, no dynamic control flow, everything jits.


@register("accum_steps_counter", ["Counter"], ["CounterOut", "ShouldApply"],
          differentiable=False)
def accum_steps_counter(counter, *, k):
    """Micro-step counter: rolls over every ``k`` steps; ShouldApply is
    true on the k-th micro-step."""
    c = (counter + 1) % k
    return c, c == 0


@register("grad_accumulate", ["Acc", "Grad", "ShouldApply"],
          ["AccOut", "GradOut"], differentiable=False)
def grad_accumulate(acc, grad, should_apply, *, k):
    """AccOut = running sum (reset to zero on the apply step);
    GradOut = mean gradient over the window, consumed by the gated
    update op that runs right after."""
    s = acc + grad
    return jnp.where(should_apply, jnp.zeros_like(s), s), \
        s / jnp.asarray(k, s.dtype)


# -- parameter averaging ---------------------------------------------------

_K_MAX_NUM_ACCUMULATES = 16384  # average_accumulates_op.h kMaxNumAccumulates


@register("average_accumulates",
          ["Param", "Sum1", "Sum2", "Sum3", "NumAccumulates",
           "OldNumAccumulates", "NumUpdates"],
          ["Sum1Out", "Sum2Out", "Sum3Out", "NumAccumulatesOut",
           "OldNumAccumulatesOut", "NumUpdatesOut"],
          differentiable=False)
def average_accumulates(param, s1, s2, s3, num_acc, old_num_acc,
                        num_updates, *, average_window=0.0,
                        min_average_window=10000,
                        max_average_window=10000):
    """Sliding-window parameter sum for ModelAverage (reference:
    operators/average_accumulates_op.h). sum_1 accumulates every step;
    it periodically spills into sum_2 (bounding float error); when the
    window is full the total snapshots into sum_3 and restarts."""
    num_updates = num_updates + 1
    num_acc = num_acc + 1
    s1 = s1 + param
    spill = num_updates % _K_MAX_NUM_ACCUMULATES == 0
    s2 = jnp.where(spill, s2 + s1, s2)
    s1 = jnp.where(spill, jnp.zeros_like(s1), s1)
    window = jnp.minimum(
        jnp.asarray(max_average_window, num_updates.dtype),
        (num_updates.astype(jnp.float32)
         * average_window).astype(num_updates.dtype))
    full = (num_acc >= min_average_window) & (num_acc >= window)
    s3 = jnp.where(full, s1 + s2, s3)
    s1 = jnp.where(full, jnp.zeros_like(s1), s1)
    s2 = jnp.where(full, jnp.zeros_like(s2), s2)
    old_num_acc = jnp.where(full, num_acc, old_num_acc)
    num_acc = jnp.where(full, jnp.zeros_like(num_acc), num_acc)
    return s1, s2, s3, num_acc, old_num_acc, num_updates


@register("model_average_apply",
          ["Sum1", "Sum2", "Sum3", "NumAccumulates", "OldNumAccumulates"],
          ["Out"], differentiable=False)
def model_average_apply(s1, s2, s3, num_acc, old_num_acc):
    n = jnp.maximum(num_acc + old_num_acc, 1).astype(s1.dtype)
    return (s1 + s2 + s3) / n


# -- exponential moving average --------------------------------------------


@register("ema_update", ["Param", "Ema", "DecayPow", "Step"],
          ["EmaOut", "DecayPowOut"], differentiable=False)
def ema_update(param, ema, decay_pow, step=None, *, decay=0.999,
               use_thres=False):
    """Shadow-variable update (reference: optimizer.py:2412
    ExponentialMovingAverage). ``use_thres`` ramps the decay like the
    reference's thres_steps mode: decay_t = min(decay, (1+t)/(10+t));
    Step is only wired in that mode. DecayPow tracks the product of
    applied decays for bias correction."""
    d = jnp.asarray(decay, param.dtype)
    if use_thres:
        t = step.astype(param.dtype)
        d = jnp.minimum(d, (1.0 + t) / (10.0 + t))
    return d * ema + (1.0 - d) * param, \
        decay_pow * d.astype(decay_pow.dtype)


@register("ema_apply", ["Ema", "DecayPow"], ["Out"], differentiable=False)
def ema_apply(ema, decay_pow):
    """Bias-corrected shadow value: ema / (1 - prod(decay)); before any
    update (decay_pow == 1) the raw ema (zeros) is returned as-is."""
    denom = 1.0 - decay_pow
    out = jnp.where(denom > 0,
                    ema / jnp.where(denom > 0, denom, 1.0).astype(
                        ema.dtype), ema)
    return out.astype(ema.dtype)


# -- deep gradient compression ---------------------------------------------


@register("dgc", ["U", "V", "Grad", "CurrentStep"],
          ["UOut", "VOut", "EncodedGrad"], differentiable=False)
def dgc(u, v, grad, step, *, m=0.9, sparsity=(0.999,),
        rampup_begin_step=0, rampup_step=1, use_nesterov=False):
    """Deep Gradient Compression (reference: optimizer.py:786
    DGCMomentumOptimizer + operators/dgc_op; paper arXiv:1712.01887).

    Algorithm (post-rampup): momentum-correct locally (u = m*u + g;
    v = v + u), emit only the top-(1-s) fraction of |v| as the update,
    keep the residual accumulated, and apply momentum factor masking
    (u, v zeroed where communicated). Pre-rampup it behaves as plain
    momentum.

    TPU-native formulation: the reference sparsifies BEFORE its NCCL
    allreduce to save network bandwidth (sparse_all_reduce_op_handle);
    under GSPMD the gradient averaging is a compiler-inserted psum
    inside the same XLA program, so the *semantics* (sparse updates +
    residual accumulation — what determines convergence) live here as
    one fused op, while transport stays a dense ICI collective — on
    ICI the bandwidth DGC buys back on commodity networks is not the
    bottleneck. The per-step sparsity follows the reference's rampup
    schedule; the top-k threshold is a sorted-|v| dynamic index (no
    data-dependent shapes)."""
    if isinstance(grad, SparseRows):
        from ..core.enforce import UnimplementedError
        raise UnimplementedError(
            "dgc does not support SparseRows gradients — compression "
            "of an already-sparse embedding grad is redundant; use "
            "MomentumOptimizer (its sparse path) for lookup tables")
    # CurrentStep is read AFTER its in-graph increment, so subtract 1
    # for the 0-based step index (run 0 must see schedule entry 0 and
    # honor rampup_begin_step exactly)
    sched = jnp.asarray(sparsity, jnp.float32)
    nsched = sched.shape[0]
    stepf = step.astype(jnp.float32) - 1.0
    pos = (stepf - float(rampup_begin_step)) / \
        max(float(rampup_step), 1.0) * nsched
    s = sched[jnp.clip(pos.astype(jnp.int32), 0, nsched - 1)]

    # pre-rampup: vanilla momentum (the reference switches op paths;
    # here a select on the same state keeps one compiled program)
    u_pre = m * u + grad
    pre_encoded = grad + m * u_pre if use_nesterov else u_pre

    # post-rampup momentum correction (paper §3.1; nesterov variant
    # u = m(u+g), accumulate u+g)
    if use_nesterov:
        u1 = m * (u + grad)
        v1 = v + u1 + grad
    else:
        u1 = m * u + grad
        v1 = v + u1
    flat = jnp.abs(v1).reshape(-1)
    nelem = flat.shape[0]
    kth = jnp.clip((s * nelem).astype(jnp.int32), 0, nelem - 1)
    thresh = jnp.sort(flat)[kth]
    mask = jnp.abs(v1) >= thresh
    encoded = jnp.where(mask, v1, 0.0)
    u_post = jnp.where(mask, 0.0, u1)
    v_post = jnp.where(mask, 0.0, v1)

    is_pre = stepf < float(rampup_begin_step)
    u_out = jnp.where(is_pre, u_pre, u_post)
    v_out = jnp.where(is_pre, v, v_post)
    enc = jnp.where(is_pre, pre_encoded, encoded)
    return u_out, v_out, enc


# -- SelectedRows utility ops (reference: merge_selected_rows_op.cc,
# get_tensor_from_selected_rows_op.cc — the conversion ops programs use
# around sparse grads) --------------------------------------------------

@register("merge_selected_rows", ["X"], ["Out"])
def merge_selected_rows(x):
    """Merge duplicate rows by addition (reference:
    operators/merge_selected_rows_op.cc over
    math/selected_rows_functor.cc MergeAdd)."""
    from ..core.selected_rows import SparseRows
    if isinstance(x, SparseRows):
        return x.merged()
    return x


@register("get_tensor_from_selected_rows", ["X"], ["Out"])
def get_tensor_from_selected_rows(x):
    """Densify a SparseRows into its full [height, ...] tensor
    (reference: get_tensor_from_selected_rows_op.cc)."""
    from ..core.selected_rows import SparseRows
    if not isinstance(x, SparseRows):
        return x
    dense = jnp.zeros((x.height,) + tuple(x.values.shape[1:]),
                      x.values.dtype)
    return dense.at[x.rows].add(x.values, mode="drop")
