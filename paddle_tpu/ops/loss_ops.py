"""Sequence-labeling, ranking, and sampled-softmax loss ops.

Reference coverage (paddle/fluid/operators/):
  warpctc_op.cc (CTC loss, via the external warp-ctc lib),
  ctc_align_op.cc, edit_distance_op.cc, linear_chain_crf_op.cc,
  crf_decoding_op.cc, nce_op.cc, sampling_id_op.cc, sample_logits_op.cc,
  hierarchical_sigmoid_op.cc, rank_loss_op.cc, bpr_loss_op.cc,
  modified_huber_loss_op.cc, teacher_student_sigmoid_loss_op.cc,
  cos_sim_op.cc, squared_l2_distance_op.cc, squared_l2_norm_op.cc,
  l1_norm_op.cc, bilinear_tensor_product_op.cc.

TPU-native redesign notes:
- The reference's LoD-batched sequence inputs become padded
  [B, T, ...] + explicit length vectors (SURVEY hard part 1).
- CTC/CRF run their per-timestep recurrences as lax.scan in log space;
  gradients come from JAX autodiff through the scan instead of the
  reference's hand-written backward kernels (warp-ctc,
  linear_chain_crf_grad).
- Sampling ops draw on the counter-based step RNG (needs_rng) instead
  of curand/std::mt19937.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from .registry import register

_NEG = -1e30


def _len_mask(lengths, maxlen):
    return lax.broadcasted_iota(jnp.int32, (lengths.shape[0], maxlen),
                                1) < lengths.reshape(-1, 1).astype(
                                    jnp.int32)


# ---------------------------------------------------------------------------
# CTC
# ---------------------------------------------------------------------------

@register("warpctc", ["Logits", "Label", "LogitsLength", "LabelLength"],
          ["Loss"], nondiff=("Label", "LogitsLength", "LabelLength"))
def warpctc(logits, label, logit_len, label_len, *, blank=0,
            norm_by_times=False):
    """CTC negative log-likelihood (reference: warpctc_op.cc wrapping
    the warp-ctc CUDA lib). Log-space alpha recursion over the
    extended label sequence [blank, l1, blank, ..., lL, blank] as one
    lax.scan over time; everything batch-vectorized so the MXU/VPU see
    [B, 2L+1] panels, not per-sequence loops."""
    logits = logits.astype(jnp.float32)
    B, T, C = logits.shape
    L = label.shape[1]
    S = 2 * L + 1
    logp = jax.nn.log_softmax(logits, axis=-1)
    label = label.astype(jnp.int32)
    logit_len = logit_len.reshape(-1).astype(jnp.int32)
    label_len = label_len.reshape(-1).astype(jnp.int32)

    ext = jnp.full((B, S), blank, jnp.int32)
    ext = ext.at[:, 1::2].set(label)
    pos = jnp.arange(S)
    valid_s = pos[None, :] < (2 * label_len[:, None] + 1)

    # skip transition s-2 -> s allowed when ext[s] is a label distinct
    # from ext[s-2]
    ext_m2 = jnp.pad(ext, ((0, 0), (2, 0)), constant_values=-1)[:, :S]
    can_skip = (ext != blank) & (ext != ext_m2)

    def emit(t_logp):
        # t_logp [B, C] -> [B, S] gathered at ext
        return jnp.take_along_axis(t_logp, ext, axis=1)

    alpha0 = jnp.full((B, S), _NEG)
    alpha0 = alpha0.at[:, 0].set(emit(logp[:, 0])[:, 0])
    has_lab = label_len > 0
    alpha0 = alpha0.at[:, 1].set(
        jnp.where(has_lab, emit(logp[:, 0])[:, 1], _NEG))
    alpha0 = jnp.where(valid_s, alpha0, _NEG)

    def shift(a, k):
        return jnp.pad(a, ((0, 0), (k, 0)),
                       constant_values=_NEG)[:, :S]

    def step(alpha, t):
        stay = alpha
        one = shift(alpha, 1)
        two = jnp.where(can_skip, shift(alpha, 2), _NEG)
        merged = jnp.logaddexp(jnp.logaddexp(stay, one), two)
        new = merged + emit(logp[:, t])
        new = jnp.where(valid_s, new, _NEG)
        # freeze finished sequences (t >= logit_len)
        live = (t < logit_len).reshape(-1, 1)
        new = jnp.where(live, new, alpha)
        return new, None

    alphaT, _ = lax.scan(step, alpha0, jnp.arange(1, T))
    # final states: S_b-1 (last blank) and S_b-2 (last label)
    send = 2 * label_len  # index of final blank
    a_end = jnp.take_along_axis(alphaT, send[:, None], axis=1)[:, 0]
    a_pre = jnp.take_along_axis(
        alphaT, jnp.maximum(send - 1, 0)[:, None], axis=1)[:, 0]
    a_pre = jnp.where(label_len > 0, a_pre, _NEG)
    ll = jnp.logaddexp(a_end, a_pre)
    loss = -ll
    if norm_by_times:
        loss = loss / jnp.maximum(logit_len.astype(jnp.float32), 1.0)
    return loss.reshape(-1, 1)


@register("ctc_align", ["Input", "InputLength"],
          ["Output", "OutputLength"], differentiable=False)
def ctc_align(ids, input_len, *, blank=0, merge_repeated=True):
    """CTC greedy-decode postprocess: drop repeats then blanks
    (reference: ctc_align_op.cc). Static shapes: output stays [B, T]
    padded with ``blank``; OutputLength carries the compacted
    lengths."""
    ids = ids.astype(jnp.int32)
    B, T = ids.shape
    input_len = input_len.reshape(-1).astype(jnp.int32)
    inside = _len_mask(input_len, T)
    prev = jnp.pad(ids, ((0, 0), (1, 0)), constant_values=-1)[:, :T]
    keep = inside & (ids != blank)
    if merge_repeated:
        keep &= ids != prev
    # stable compaction: target position = cumsum(keep) - 1
    pos = jnp.cumsum(keep.astype(jnp.int32), axis=1) - 1
    out_len = jnp.max(jnp.where(keep, pos + 1, 0), axis=1)
    out = jnp.full((B, T), blank, jnp.int32)
    bidx = lax.broadcasted_iota(jnp.int32, (B, T), 0)
    safe_pos = jnp.where(keep, pos, T)  # dropped -> scatter off-end
    out = out.at[bidx, safe_pos].set(ids, mode="drop")
    return out, out_len.reshape(-1, 1)


@register("edit_distance", ["Hyps", "Refs", "HypsLength", "RefsLength"],
          ["Out", "SequenceNum"], differentiable=False)
def edit_distance(hyps, refs, hyp_len, ref_len, *, normalized=False):
    """Levenshtein distance per pair (reference: edit_distance_op.cc).
    DP over hypothesis positions with the row vector as scan carry —
    [B, Lr+1] panels per step, batch-vectorized."""
    hyps = hyps.astype(jnp.int32)
    refs = refs.astype(jnp.int32)
    B, Lh = hyps.shape
    Lr = refs.shape[1]
    hyp_len = hyp_len.reshape(-1).astype(jnp.int32)
    ref_len = ref_len.reshape(-1).astype(jnp.int32)
    cols = jnp.arange(Lr + 1)
    # row 0: distance from empty hyp = j (capped at ref_len)
    row0 = jnp.minimum(jnp.broadcast_to(cols, (B, Lr + 1)),
                       ref_len[:, None]).astype(jnp.float32)
    big = 1e9

    def step(row, i):
        h = hyps[:, i]  # [B]
        sub = (refs != h[:, None]).astype(jnp.float32)  # [B, Lr]
        live = (i < hyp_len).astype(jnp.float32)[:, None]

        # new[0] = i+1; new[j] = min(row[j]+1, new[j-1]+1, row[j-1]+sub)
        # the new[j-1] dependence is a running min -> associative scan
        del_cost = row + 1.0                      # deletion of h[i]
        sub_cost = row[:, :-1] + sub              # [B, Lr]
        base = jnp.concatenate(
            [jnp.full((B, 1), i + 1.0), jnp.minimum(del_cost[:, 1:],
                                                    sub_cost)], axis=1)
        # insertion chain: new[j] = min over k<=j of base[k] + (j-k)
        chain = lax.associative_scan(jnp.minimum,
                                     base - cols[None, :], axis=1)
        new = chain + cols[None, :]
        # beyond ref_len the row is frozen (only [0..ref_len] matters);
        # freeze finished hyps
        new = jnp.where(live > 0, new, row)
        return new, None

    row, _ = lax.scan(step, row0, jnp.arange(Lh))
    dist = jnp.take_along_axis(row, ref_len[:, None], axis=1)[:, 0]
    if normalized:
        dist = dist / jnp.maximum(ref_len.astype(jnp.float32), 1.0)
    return dist.reshape(-1, 1), jnp.asarray(B, jnp.int64)


# ---------------------------------------------------------------------------
# linear-chain CRF
# ---------------------------------------------------------------------------

def _crf_unpack(transition):
    # reference layout (linear_chain_crf_op.h): row 0 = start weights,
    # row 1 = stop weights, rows 2.. = [D, D] transition matrix
    return transition[0], transition[1], transition[2:]


@register("linear_chain_crf",
          ["Emission", "Transition", "Label", "Length"],
          ["LogLikelihood"], nondiff=("Label", "Length"))
def linear_chain_crf(emission, transition, label, length):
    """Sequence log-likelihood under a linear-chain CRF (reference:
    linear_chain_crf_op.cc). Forward (partition) recursion is a
    logsumexp lax.scan; grads for Emission/Transition via autodiff (the
    reference writes the backward by hand from saved alpha/exps)."""
    emission = emission.astype(jnp.float32)
    B, T, D = emission.shape
    start, stop, trans = _crf_unpack(transition.astype(jnp.float32))
    label = label.astype(jnp.int32)
    length = length.reshape(-1).astype(jnp.int32)

    # ---- partition function ----
    alpha0 = start[None, :] + emission[:, 0]      # [B, D]

    def fstep(alpha, t):
        # [B, D, D]: alpha[b, i] + trans[i, j]
        scores = alpha[:, :, None] + trans[None, :, :]
        new = jax.nn.logsumexp(scores, axis=1) + emission[:, t]
        live = (t < length)[:, None]
        return jnp.where(live, new, alpha), None

    alphaT, _ = lax.scan(fstep, alpha0, jnp.arange(1, T))
    logZ = jax.nn.logsumexp(
        alphaT + stop[None, :], axis=1)            # [B]

    # ---- gold path score ----
    bidx = jnp.arange(B)
    emit_g = jnp.take_along_axis(emission, label[:, :, None],
                                 axis=2)[:, :, 0]  # [B, T]
    tmask = _len_mask(length, T)
    emit_score = jnp.sum(jnp.where(tmask, emit_g, 0.0), axis=1)
    prev_l = label[:, :-1]
    next_l = label[:, 1:]
    pair = trans[prev_l, next_l]                   # [B, T-1]
    pair_mask = tmask[:, 1:]
    trans_score = jnp.sum(jnp.where(pair_mask, pair, 0.0), axis=1)
    last = jnp.maximum(length - 1, 0)
    start_score = start[label[:, 0]]
    stop_score = stop[label[bidx, last]]
    gold = emit_score + trans_score + start_score + stop_score
    return (gold - logZ).reshape(-1, 1)


@register("crf_decoding", ["Emission", "Transition", "Length"],
          ["ViterbiPath"], differentiable=False)
def crf_decoding(emission, transition, length):
    """Viterbi decode (reference: crf_decoding_op.cc): forward max
    scan records argmax backpointers; a reverse scan walks them back.
    Positions past each row's length emit label 0."""
    emission = emission.astype(jnp.float32)
    B, T, D = emission.shape
    start, stop, trans = _crf_unpack(transition.astype(jnp.float32))
    length = length.reshape(-1).astype(jnp.int32)

    v0 = start[None, :] + emission[:, 0]

    def fstep(v, t):
        scores = v[:, :, None] + trans[None, :, :]     # [B, i, j]
        best_prev = jnp.argmax(scores, axis=1)         # [B, D]
        new = jnp.max(scores, axis=1) + emission[:, t]
        live = (t < length)[:, None]
        new = jnp.where(live, new, v)
        return new, jnp.where(live, best_prev, -1)

    vT, back = lax.scan(fstep, v0, jnp.arange(1, T))   # back [T-1,B,D]
    # stop weights apply at each sequence's OWN last step; since vT
    # froze at t = length-1, add stop now
    last_state = jnp.argmax(vT + stop[None, :], axis=1)  # [B]

    def bstep(state, t):
        bp = back[t]                                    # [B, D]
        prev = jnp.take_along_axis(bp, state[:, None],
                                   axis=1)[:, 0]
        # frozen steps recorded -1 backpointers: stay in place there
        live = prev >= 0
        new = jnp.where(live, prev, state)
        return new, new

    # walk back[T-2] .. back[0]; emitted states are the labels at
    # times T-2 .. 0, i.e. the path reversed (without the last step)
    _, states_rev = lax.scan(bstep, last_state,
                             jnp.arange(T - 2, -1, -1))
    path = jnp.concatenate([jnp.flip(states_rev, axis=0),
                            last_state[None]], axis=0).T  # [B, T]
    return jnp.where(_len_mask(length, T), path, 0)


# ---------------------------------------------------------------------------
# sampled / hierarchical softmax family
# ---------------------------------------------------------------------------

@register("nce", ["Input", "Weight", "Bias", "Label"], ["Cost"],
          nondiff=("Label",), needs_rng=True)
def nce(x, weight, bias, label, *, num_total_classes,
        num_neg_samples=10, seed=0, rng=None):
    """Noise-contrastive estimation (reference: nce_op.cc, uniform
    sampler). Loss per example: -log sigma(s_true - log(kq)) -
    sum_neg log sigma(-(s_neg - log(kq))) with q = 1/num_classes."""
    x = x.astype(jnp.float32)
    weight = weight.astype(jnp.float32)
    B = x.shape[0]
    label = label.reshape(B, -1).astype(jnp.int32)
    k = int(num_neg_samples)
    key = jax.random.key(seed) if seed else rng
    neg = jax.random.randint(key, (B, k), 0, num_total_classes)

    def score(ids):
        w = weight[ids]                      # [B, n, D]
        b = bias[ids] if bias is not None else 0.0
        return jnp.einsum("bd,bnd->bn", x, w) + b

    logq = jnp.log(jnp.asarray(k / float(num_total_classes)))
    s_true = score(label) - logq
    s_neg = score(neg) - logq
    cost = -jnp.sum(jax.nn.log_sigmoid(s_true), axis=1) \
        - jnp.sum(jax.nn.log_sigmoid(-s_neg), axis=1)
    return cost.reshape(-1, 1)


@register("sampling_id", ["X"], ["Out"], differentiable=False,
          needs_rng=True)
def sampling_id(x, *, min=0.0, max=1.0, seed=0, rng=None):
    """Sample a category id per row of a probability matrix
    (reference: sampling_id_op.cc)."""
    key = jax.random.key(seed) if seed else rng
    return jax.random.categorical(
        key, jnp.log(jnp.maximum(x.astype(jnp.float32), 1e-20)),
        axis=-1)


@register("sample_logits",
          ["Logits", "Labels"],
          ["SampledLogits", "SampledLabels", "Samples"],
          nondiff=("Labels",), needs_rng=True)
def sample_logits(logits, labels, *, num_samples, seed=0,
                  use_customized_samples=False, remove_accidental_hits=True,
                  uniq=True, rng=None):
    """Sampled-softmax helper (reference: sample_logits_op.cc): gather
    the true-label logits plus ``num_samples`` uniformly sampled class
    logits, adjusted by -log(expected count); feed the result to
    softmax_with_cross_entropy with the remapped labels."""
    logits = logits.astype(jnp.float32)
    B, C = logits.shape
    nt = labels.shape[1]
    key = jax.random.key(seed) if seed else rng
    samples = jax.random.randint(key, (B, num_samples), 0, C)
    all_ids = jnp.concatenate([labels.astype(jnp.int32), samples],
                              axis=1)               # [B, nt+S]
    picked = jnp.take_along_axis(logits, all_ids, axis=1)
    logq = -jnp.log(jnp.asarray(float(C)))
    picked = picked - logq
    if remove_accidental_hits:
        hit = samples == labels[:, :1]
        picked = picked.at[:, nt:].add(jnp.where(hit, -1e20, 0.0))
    new_labels = jnp.broadcast_to(jnp.arange(nt), (B, nt))
    return picked, new_labels, all_ids


@register("hierarchical_sigmoid",
          ["X", "W", "Bias", "Label"], ["Out", "PreOut"],
          nondiff=("Label",))
def hierarchical_sigmoid(x, w, bias, label, *, num_classes):
    """Hierarchical sigmoid over the default complete binary tree
    (reference: hierarchical_sigmoid_op.cc / math/matrix_bit_code.h:
    leaf code for class c is c + num_classes, path bits walk to the
    root). Cost = sum over path of sigmoid CE against the branch
    bit."""
    x = x.astype(jnp.float32)
    B, D = x.shape
    C = int(num_classes)
    depth = max(int(C - 1).bit_length(), 1)
    code = label.reshape(-1).astype(jnp.int32) + C  # [B]
    pre_list, loss = [], 0.0
    node = code
    for _ in range(depth):
        parent = node // 2
        bit = (node & 1).astype(jnp.float32)        # right child = 1
        idx = parent - 1                            # node 1.. -> row 0..
        valid = (parent >= 1) & (idx < C - 1)
        safe = jnp.clip(idx, 0, C - 2)
        wrow = w[safe]                              # [B, D]
        pre = jnp.einsum("bd,bd->b", x, wrow)
        if bias is not None:
            pre = pre + bias.reshape(-1)[safe]
        # sigmoid CE toward the bit, masked off-path
        ce = jnp.maximum(pre, 0) - pre * bit + \
            jnp.log1p(jnp.exp(-jnp.abs(pre)))
        loss = loss + jnp.where(valid, ce, 0.0)
        pre_list.append(jnp.where(valid, pre, 0.0))
        node = parent
    preout = jnp.stack(pre_list, axis=1)            # [B, depth]
    return loss.reshape(-1, 1), preout


# ---------------------------------------------------------------------------
# pairwise / pointwise losses
# ---------------------------------------------------------------------------

@register("rank_loss", ["Label", "Left", "Right"], ["Out"],
          nondiff=("Label",))
def rank_loss(label, left, right):
    """Pairwise RankNet loss (reference: rank_loss_op.cc):
    out = log(1 + exp(l - r)) - label * (l - r), stabilized."""
    o = left - right
    return jnp.maximum(o, 0) - label * o + jnp.log1p(jnp.exp(-jnp.abs(o)))


@register("bpr_loss", ["X", "Label"], ["Out"], nondiff=("Label",))
def bpr_loss(x, label):
    """Bayesian personalized ranking (reference: bpr_loss_op.cc):
    -mean_j log sigmoid(x[label] - x[j]) over the negative classes."""
    x = x.astype(jnp.float32)
    B, C = x.shape
    pos = jnp.take_along_axis(x, label.reshape(-1, 1).astype(jnp.int32),
                              axis=1)               # [B, 1]
    diff = pos - x                                  # [B, C]
    neg_mask = jnp.ones((B, C), bool).at[
        jnp.arange(B), label.reshape(-1).astype(jnp.int32)].set(False)
    lose = -jax.nn.log_sigmoid(diff)
    return (jnp.sum(jnp.where(neg_mask, lose, 0.0), axis=1) /
            jnp.maximum(C - 1, 1)).reshape(-1, 1)


@register("modified_huber_loss", ["X", "Y"], ["Out"], nondiff=("Y",))
def modified_huber_loss(x, y):
    """Reference: modified_huber_loss_op.cc. y in {0,1} -> {-1,+1};
    z = x*y': z >= -1 -> max(0, 1-z)^2, else -4z."""
    z = x * (2.0 * y - 1.0)
    return jnp.where(z >= -1.0, jnp.square(jnp.maximum(1.0 - z, 0.0)),
                     -4.0 * z)


@register("teacher_student_sigmoid_loss", ["X", "Label"], ["Y"],
          nondiff=("Label",))
def teacher_student_sigmoid_loss(x, label, *, soft_max_up_bound=15.0,
                                 soft_max_lower_bound=-15.0):
    """Reference: teacher_student_sigmoid_loss_op.cc — sigmoid CE where
    the label carries a teacher score: hard part uses sign(label),
    soft part (|label| in (0,1)) adds a distillation CE on the clipped
    logit."""
    x = x.astype(jnp.float32)
    label = label.astype(jnp.float32)
    hard = jnp.where(label > 0, 1.0, 0.0)
    ce = jnp.maximum(x, 0) - x * hard + jnp.log1p(jnp.exp(-jnp.abs(x)))
    xs = jnp.clip(x, soft_max_lower_bound, soft_max_up_bound)
    soft_lab = jnp.abs(label) - jnp.floor(jnp.abs(label))
    soft = jnp.maximum(xs, 0) - xs * soft_lab + \
        jnp.log1p(jnp.exp(-jnp.abs(xs)))
    use_soft = (soft_lab > 0) & (soft_lab < 1)
    return jnp.where(use_soft, ce + soft, ce)


@register("cos_sim", ["X", "Y"], ["Out", "XNorm", "YNorm"])
def cos_sim(x, y):
    """Row cosine similarity; Y broadcasts over rows when [1, D]
    (reference: cos_sim_op.cc)."""
    xn = jnp.sqrt(jnp.sum(jnp.square(x), axis=-1, keepdims=True))
    yn = jnp.sqrt(jnp.sum(jnp.square(y), axis=-1, keepdims=True))
    dot = jnp.sum(x * y, axis=-1, keepdims=True)
    return dot / jnp.maximum(xn * yn, 1e-12), xn, yn


@register("squared_l2_distance", ["X", "Y"], ["Out", "sub_result"])
def squared_l2_distance(x, y):
    sub = x - y
    return jnp.sum(jnp.square(sub), axis=-1, keepdims=True), sub


@register("squared_l2_norm", ["X"], ["Out"])
def squared_l2_norm(x):
    return jnp.sum(jnp.square(x)).reshape(1)


@register("l1_norm", ["X"], ["Out"])
def l1_norm(x):
    return jnp.sum(jnp.abs(x)).reshape(1)


@register("bilinear_tensor_product", ["X", "Y", "Weight", "Bias"],
          ["Out"])
def bilinear_tensor_product(x, y, weight, bias):
    """out[b, s] = x[b] @ W[s] @ y[b]^T (+bias) (reference:
    bilinear_tensor_product_op.cc)."""
    out = jnp.einsum("bm,smn,bn->bs", x, weight, y)
    if bias is not None:
        out = out + bias.reshape(1, -1)
    return out


def _tree_eta_np(edges, n_nodes, max_depth):
    """Host-side tree2col coefficients (reference: math/tree2col.cc
    construct_tree/construct_patch + the eta formulas of tree2col.h).
    edges [E, 2] int, 1-based, (0,0)-terminated; returns
    eta [n_nodes, n_nodes, 3] with coefficient order (l, r, t)."""
    import numpy as _np
    adj = [[] for _ in range(n_nodes + 2)]
    # node_count derives from the edge list (reference construct_tree:
    # #real edges + 1); PADDING rows beyond it must stay zero — they
    # are not tree nodes, and giving them self-patches would leak
    # activations/gradients into padding embeddings
    node_count = 1
    for u, v in edges:
        u, v = int(u), int(v)
        if u == 0 or v == 0:
            break
        node_count += 1
        if u <= n_nodes and v <= n_nodes:
            adj[u].append(v)
    node_count = min(node_count, n_nodes)
    eta = _np.zeros((n_nodes, n_nodes, 3), _np.float32)
    md = float(max_depth)
    for root in range(1, node_count + 1):
        # iterative DFS matching the reference's stack discipline
        patch = [(root, 1, 1, 0)]
        visited = {root}
        stack = [(root, 0)]
        while stack:
            node, depth = stack[-1]
            sz = len(adj[node])
            advanced = False
            for i, v in enumerate(adj[node]):
                if v not in visited and depth + 1 < max_depth:
                    visited.add(v)
                    stack.append((v, depth + 1))
                    patch.append((v, i + 1, sz, depth + 1))
                    advanced = True
            if not advanced:
                stack.pop()
        for (v, index, pclen, depth) in patch:
            eta_t = (md - depth) / md
            temp = 0.5 if pclen == 1 else (index - 1.0) / (pclen - 1.0)
            eta_l = (1.0 - eta_t) * temp
            eta_r = (1.0 - eta_t) * (1.0 - eta_l)
            eta[root - 1, v - 1, 0] += eta_l
            eta[root - 1, v - 1, 1] += eta_r
            eta[root - 1, v - 1, 2] += eta_t
    return eta


@register("tree_conv", ["NodesVector", "EdgeSet", "Filter"], ["Out"],
          nondiff=("EdgeSet",))
def tree_conv(nodes, edges, filt, *, max_depth):
    """Tree-based convolution (TBCNN — reference: tree_conv_op.cc over
    math/tree2col): nodes [B, N, F], edges [B, E, 2] (1-based,
    0-terminated), filter [F, 3, O, K] -> out [B, N, O, K].

    TPU split: the data-dependent tree patches become a host-computed
    coefficient tensor eta[B, N, N, 3] (a pure function of the INT
    edge data — jax.pure_callback, no gradients needed), and ALL the
    FLOPs run as two einsums on the MXU; autodiff through the einsums
    replaces the hand-written col2tree backward."""
    B, N, F = nodes.shape

    def host(e):
        import numpy as _np
        return _np.stack([
            _tree_eta_np(_np.asarray(e[b]).reshape(-1, 2), N,
                         max_depth)
            for b in range(e.shape[0])])

    eta = jax.pure_callback(
        host, jax.ShapeDtypeStruct((B, N, N, 3), jnp.float32),
        lax.stop_gradient(edges))
    patch = jnp.einsum("buvc,bvf->bufc", eta,
                       nodes.astype(jnp.float32))
    return jnp.einsum("bufc,fcok->buok", patch,
                      filt.astype(jnp.float32)).astype(nodes.dtype)
