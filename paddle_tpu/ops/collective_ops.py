"""Collective communication ops.

Reference: paddle/fluid/operators/distributed_ops/ (allreduce_op,
sparse_all_reduce_op_handle) — collectives as graph ops. Here GSPMD
inserts most collectives from sharding annotations; this module
registers the QUANTIZED gradient all-reduce as an explicit op so the
per-op library-mix machinery (registry.pick best-impl-wins) and the
test_op_sweep harness cover it like any other kernel. The heavy
lifting lives in parallel/collectives.py; the executor's
BuildStrategy.gradient_sync rewrite uses the same functions directly.
"""

from __future__ import annotations

import jax.numpy as jnp

from .registry import register, register_variant


@register("quant_allreduce", ["X", "Residual"], ["Out", "ResidualOut"],
          differentiable=False)
def quant_allreduce(x, residual, *, block_size=256, axis="dp"):
    """Block-scaled int8 all-reduce with error feedback over the
    ambient mesh's ``axis`` (EQuARX, arXiv:2506.17615 analog; see
    parallel/collectives.all_reduce_q8). Without a mesh (or a 1-device
    axis) the transport disappears but the quantize/dequant round-trip
    and residual carry remain, so the op's numerics are scale-
    invariant and testable on a single device."""
    from ..parallel import collectives
    from ..parallel import mesh as mesh_lib
    if residual is None:
        residual = jnp.zeros(jnp.shape(x), jnp.float32)
    return collectives.all_reduce_q8(x, residual,
                                     mesh_lib.current_mesh(),
                                     axis=axis, block_size=block_size)


@register_variant("quant_allreduce", "exact")
def quant_allreduce_exact(x, residual, *, block_size=256, axis="dp"):
    """Lossless twin for the best-impl-wins mix: full-precision
    all-reduce, any pending residual transmitted in full and zeroed."""
    from ..parallel import collectives
    from ..parallel import mesh as mesh_lib
    mesh = mesh_lib.current_mesh()
    if residual is None:
        residual = jnp.zeros(jnp.shape(x), jnp.float32)
    n = collectives.axis_size(mesh, axis)
    y = collectives.all_reduce_exact(x, mesh, axis)
    y = y.astype(jnp.float32) + n * residual
    return y.astype(jnp.asarray(x).dtype), jnp.zeros_like(residual)
