"""Fused composite ops produced by ir fusion passes.

Reference: paddle/fluid/operators/fused/fused_elemwise_activation_op.cc
and operators/fc_op (the fc op the fc_fuse_pass emits,
framework/ir/fc_fuse_pass.cc). On TPU these exist for *program-level*
compactness — fewer ops in serialized inference programs and shorter
traces — not for kernel-launch savings (XLA fuses either way).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax

from .math_ops import _bcast_y
from .registry import register

_UNARY = {
    "relu": lambda x, **kw: jax.nn.relu(x),
    "sigmoid": lambda x, **kw: jax.nn.sigmoid(x),
    "tanh": lambda x, **kw: jnp.tanh(x),
    "gelu": lambda x, **kw: jax.nn.gelu(x, **kw),
    "identity": lambda x, **kw: x,
    "scale": lambda x, scale=1.0, **kw: x * scale,
    "": lambda x, **kw: x,
}

_BINARY = {
    "elementwise_add": jnp.add,
    "elementwise_sub": jnp.subtract,
    "elementwise_mul": jnp.multiply,
}


@register("fused_elemwise_activation", ["X", "Y"], ["Out"])
def fused_elemwise_activation(x, y, *, functor_list, axis=-1,
                              act_attrs=None):
    """functor_list = [binary, unary] (binary first, e.g.
    ["elementwise_add", "relu"]) or [unary, binary] for
    act-then-add; ``act_attrs`` carries the original activation op's
    attrs (gelu approximate=...) so fusion preserves numerics.
    Reference: fused_elemwise_activation_op.h functor composition.
    Broadcast follows the fluid elementwise convention
    (math_ops._bcast_y — the same helper the unfused ops use)."""
    f0, f1 = functor_list
    kw = dict(act_attrs or {})
    if f0 in _BINARY:
        out = _BINARY[f0](x, _bcast_y(x, y, axis))
        return _UNARY[f1](out, **kw)
    return _BINARY[f1](_UNARY[f0](x, **kw), _bcast_y(x, y, axis))


@register("fc", ["Input", "W", "Bias"], ["Out"])
def fc(x, w, bias, *, in_num_col_dims=1, activation_type=""):
    """The fc_fuse_pass target op (reference: operators/fc_op.cc;
    ir/fc_fuse_pass.cc rewrites mul+elementwise_add(+act) into it)."""
    lead = x.shape[:in_num_col_dims]
    k = 1
    for d in x.shape[in_num_col_dims:]:
        k *= d
    x2 = x.reshape(lead + (k,))
    out = jnp.matmul(x2, w)
    if bias is not None:
        out = out + bias
    return _UNARY[activation_type](out)


@functools.lru_cache(maxsize=None)
def _lean_xent(epsilon, V):
    """custom_vjp core of fused_linear_xent, cached per (epsilon, V)
    so the primitive identity is stable across traces.

    The hand-written backward exists for bandwidth, not math: the
    autodiff backward of the composite materialized a float32
    ``dlogits`` [N, V] (2 GB at the flagship 16k x 30k head) built
    from a scatter (take_along_axis transpose) plus three broadcast
    fusions. Here the whole thing is ONE fusion — softmax recomputed
    from the saved (logits, lse) residuals, one-hot as an iota
    compare (no scatter) — and the result is written in the INPUT
    dtype, so under AMP the tensor the two head matmuls re-read is
    half the bytes. dlogits rounds to bf16 exactly once, the same
    contract as the attention probs residual (ops/pallas/attention.py
    _softmax_save_lowp); the f32 path is bit-identical to the
    composite's gradients.

    The label rides as float32 through the custom_vjp boundary to
    avoid the int-cotangent float0 dance (the attention kernel's seed
    uses the same trick)."""

    @jax.custom_vjp
    def f(x, w, lab_f):
        return _fwd(x, w, lab_f)[0]

    def _fwd(x, w, lab_f):
        logits = jnp.dot(x, w,
                         preferred_element_type=jnp.float32)  # [..., V]
        lse = jax.scipy.special.logsumexp(logits, axis=-1,
                                          keepdims=True)
        lab = lab_f.astype(jnp.int32)
        picked = jnp.take_along_axis(logits, lab, axis=-1)
        loss = lse - (1.0 - epsilon) * picked
        if epsilon:
            loss = loss - (epsilon / V) * jnp.sum(logits, axis=-1,
                                                  keepdims=True)
        return loss, (x, w, lab_f, logits, lse)

    def _bwd(res, g):
        x, w, lab_f, logits, lse = res
        lab = lab_f.astype(jnp.int32)
        p = jnp.exp(logits - lse)                       # softmax, f32
        # one-hot via iota compare, fused into the single dlogits
        # fusion. A 16k-row scatter-add variant (.at[rows, lab].add)
        # chip-measured CATASTROPHIC: 10.27 vs 13.08 steps/s in-model
        # (+21 ms/step) — TPU lowers variable-index scatters to a
        # serialized loop. The iota compare costs one extra [N, V]
        # compare+select inside a fusion that is reading 2 GB anyway.
        hot = (lax.broadcasted_iota(jnp.int32, logits.shape,
                                    logits.ndim - 1) == lab)
        if epsilon:
            soft = jnp.where(hot, 1.0 - epsilon + epsilon / V,
                             epsilon / V)
        else:
            soft = hot.astype(jnp.float32)
        dlogits = (g * (p - soft)).astype(x.dtype)
        dx = jnp.dot(dlogits, w.T,
                     preferred_element_type=jnp.float32).astype(x.dtype)
        bdims = tuple(range(x.ndim - 1))
        dw = lax.dot_general(
            x, dlogits, ((bdims, bdims), ((), ())),
            preferred_element_type=jnp.float32).astype(w.dtype)
        return dx, dw, jnp.zeros_like(lab_f)

    f.defvjp(_fwd, _bwd)
    return f


@register("fused_linear_xent", ["X", "W", "Label"], ["Loss"],
          nondiff=("Label",))
def fused_linear_xent(x, w, label, *, epsilon=0.0):
    """Fused vocabulary projection + label-smoothed softmax
    cross-entropy: ``loss = xent(x @ w, smooth(onehot(label), eps))``.

    Reference: the proj fc + label_smooth_op.cc + softmax_with_cross_
    entropy_op.cu chain every NMT/LM model ends with (e.g.
    benchmark/fluid/models/machine_translation.py) — fused here because
    the [N, V] logits of a 30k vocab dwarf every other activation in
    the model. Uniform smoothing has the closed form
    ``loss = lse - (1-eps)*logit[y] - eps/V * sum(logits)`` so neither
    the smoothed targets nor log-probabilities need materializing.
    The pallas variant (ops/pallas/fused_xent.py) streams vocabulary
    blocks through VMEM so the logits never reach HBM at all.

    Label: int [..., 1] (hard indices only; arbitrary soft targets stay
    on the unfused path). Loss: float32 [..., 1].

    Forward logits stay f32, deliberately: a bf16-logits variant
    (halving the [N, V] traffic, f32 in-register reductions) was
    chip-measured in round 4 at 0.287 MFU vs 0.372 — the (2,1)-packed
    bf16 layout breaks XLA's convert_reduce fusions around the head
    and costs far more than the bandwidth saves. Measured beats
    theorized. The BACKWARD is hand-written (see _lean_xent): bf16
    dlogits only feed matmuls, which is the case packed bf16 is good
    at.
    """
    from ..core.flags import FLAGS
    V = w.shape[-1]
    lab = label.astype(jnp.int32)
    if lab.ndim == x.ndim - 1:
        lab = lab[..., None]
    if FLAGS.lean_xent_grad:
        return _lean_xent(float(epsilon), int(V))(
            x, w, lab.astype(jnp.float32))
    logits = jnp.dot(x, w,
                     preferred_element_type=jnp.float32)  # [..., V]
    lse = jax.scipy.special.logsumexp(logits, axis=-1, keepdims=True)
    picked = jnp.take_along_axis(logits, lab, axis=-1)
    loss = lse - (1.0 - epsilon) * picked
    if epsilon:
        loss = loss - (epsilon / V) * jnp.sum(logits, axis=-1,
                                              keepdims=True)
    return loss


@register("conv2d_fusion", ["Input", "Filter", "Bias", "ResidualData"],
          ["Output"])
def conv2d_fusion(x, w, bias, residual, *, strides=(1, 1),
                  paddings=(0, 0), dilations=(1, 1), groups=1,
                  data_format="NCHW", activation=""):
    """conv + bias (+ residual) (+ activation) in one op — what
    conv_elementwise_add_fuse_pass emits (reference:
    operators/fused/conv_fusion_op.cc; ir/
    conv_elementwise_add_fuse_pass.cc). XLA fuses the epilogue into
    the convolution either way; the op exists for program
    compactness."""
    from .nn_ops import conv2d as _conv2d

    out = _conv2d(x, w, strides=strides, paddings=paddings,
                  dilations=dilations, groups=groups,
                  data_format=data_format)
    if bias is not None:
        shape = [1, -1, 1, 1] if data_format == "NCHW" else \
            [1, 1, 1, -1]
        out = out + bias.reshape(shape)
    if residual is not None:
        out = out + residual
    return _UNARY[activation](out)


@register("fusion_transpose_flatten_concat", ["X*"], ["Out"])
def fusion_transpose_flatten_concat(xs, *, trans_axis, flatten_axis,
                                    concat_axis):
    """transpose each input by ``trans_axis``, flatten from
    ``flatten_axis``, concat (reference: operators/fused/
    fusion_transpose_flatten_concat_op.cc — the SSD-head pattern
    ir/transpose_flatten_concat_fuse_pass.cc targets)."""
    from .tensor_ops import flatten as _flatten

    outs = [_flatten(jnp.transpose(x, trans_axis), axis=flatten_axis)
            for x in xs]
    return jnp.concatenate(outs, axis=concat_axis)


@register("fusion_seqpool_concat", ["X*", "SeqLen*"], ["Out"],
          nondiff=("SeqLen",))
def fusion_seqpool_concat(xs, seq_lens, *, pooltype="SUM", axis=1):
    """sequence_pool each input then concat (reference:
    operators/fused/fusion_seqpool_concat_op.cc, emitted by
    ir/seqpool_concat_fuse_pass.cc — the CTR-model slot-pool
    pattern)."""
    from .sequence_ops import sequence_pool as _sp

    pool = {"SUM": "sum", "AVERAGE": "average", "SQRT": "sqrt",
            "MAX": "max", "LAST": "last", "FIRST": "first"}[
        pooltype.upper()]
    if not seq_lens:
        seq_lens = [None] * len(xs)
    outs = [_sp(x, ln, pool_type=pool)
            for x, ln in zip(xs, seq_lens)]
    return jnp.concatenate(outs, axis=axis)


@register("fusion_lstm",
          ["X", "WeightX", "WeightH", "Bias", "H0", "C0", "SeqLen"],
          ["Hidden", "Cell"], nondiff=("SeqLen",))
def fusion_lstm(x, wx, wh, bias, h0, c0, seq_len, *,
                use_peepholes=False, is_reverse=False,
                gate_activation="sigmoid", cell_activation="tanh",
                candidate_activation="tanh"):
    """Input projection + LSTM scan in ONE op (reference:
    operators/fused/fusion_lstm_op.cc, emitted by
    ir/fc_lstm_fuse_pass.cc). x [B, T, D], wx [D, 4H], wh [H, 4H];
    bias carries the gate bias [1, 4H(+3H peepholes)]."""
    from .rnn_ops import lstm as _lstm

    proj = jnp.einsum("btd,dh->bth", x, wx)
    return _lstm(proj, h0, c0, wh, bias, seq_len,
                 use_peepholes=use_peepholes,
                 is_reverse=is_reverse,
                 gate_activation=gate_activation,
                 cell_activation=cell_activation,
                 candidate_activation=candidate_activation)[:2]
