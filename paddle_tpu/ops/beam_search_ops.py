"""Beam search ops — fixed-width dense redesign.

Reference: paddle/fluid/operators/beam_search_op.{cc,h} +
math/beam_search.{cc,cu} (one selection step over LoD candidate lists)
and beam_search_decode_op.cc (backtracks the id/parent arrays into
final sequences).

TPU-native redesign: the reference prunes beams dynamically through
LoD offsets — dynamic shapes XLA can't compile. Here the beam is a
dense, fixed ``[batch, beam_size]`` frontier:
  - finished beams (last id == end_id) survive as "continue with
    end_id" candidates carrying their score unchanged;
  - each step flattens [batch, beam, vocab] -> top-k over beam*vocab
    (ONE xla top-k, MXU-adjacent, no host sync);
  - ``beam_search_decode`` backtracks parent pointers. It accepts the
    eager-mode tensor arrays written inside a While loop (fluid
    parity) — and the same functions compose under lax.scan for the
    fully-jitted fast path (models/transformer fast decode).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..core.enforce import enforce
from .registry import register


def beam_search_step(pre_ids, pre_scores, scores, *, beam_size, end_id,
                     is_accumulated=False):
    """One dense beam-search step. pre_ids/pre_scores: [B, K];
    scores: [B, K, V] log-probs — per-step (the op adds pre_scores)
    unless ``is_accumulated``, in which case they are already full-path
    totals and are used directly (reference: beam_search_op.cc attr of
    the same name; the default differs because reference users
    pre-accumulate with elementwise ops while models here pass raw
    log-softmax output). Returns (ids [B,K], total_scores [B,K],
    parent_idx [B,K])."""
    B, K, V = scores.shape
    neg_inf = jnp.asarray(jnp.finfo(scores.dtype).min, scores.dtype)
    finished = pre_ids == end_id  # [B, K]
    if is_accumulated:
        total = scores
    else:
        total = pre_scores[..., None] + scores
    # finished beams: only the end_id continuation is allowed, and it
    # keeps the already-accumulated score
    keep = jnp.full((V,), False).at[end_id].set(True)
    total = jnp.where(
        finished[..., None],
        jnp.where(keep, pre_scores[..., None],
                  jnp.full_like(total, neg_inf)),
        total)
    flat = total.reshape(B, K * V)
    sel_scores, flat_idx = jax.lax.top_k(flat, beam_size)
    parent = (flat_idx // V).astype(jnp.int32)
    ids = (flat_idx % V).astype(pre_ids.dtype)
    return ids, sel_scores, parent


@register("beam_search", ["PreIds", "PreScores", "Scores"],
          ["SelectedIds", "SelectedScores", "ParentIdx"],
          differentiable=False)
def beam_search(pre_ids, pre_scores, scores, *, beam_size, end_id,
                level=0, is_accumulated=False):
    return beam_search_step(pre_ids, pre_scores, scores,
                            beam_size=beam_size, end_id=end_id,
                            is_accumulated=is_accumulated)


def beam_search_backtrack(ids_steps, parent_steps, scores, *, end_id):
    """Backtrack T steps of [B, K] ids + parent pointers into full
    sequences [B, K, T] ordered best-first by final score."""
    T = len(ids_steps)
    ids_steps = [jnp.asarray(s) for s in ids_steps]
    parent_steps = [jnp.asarray(s) for s in parent_steps]
    B, K = ids_steps[0].shape
    bidx = jnp.arange(B)[:, None]
    seqs = []
    beam = jnp.broadcast_to(jnp.arange(K)[None, :], (B, K))
    for t in range(T - 1, -1, -1):
        seqs.append(ids_steps[t][bidx, beam])
        beam = parent_steps[t][bidx, beam]
    seqs.reverse()
    out = jnp.stack(seqs, axis=-1)  # [B, K, T]
    order = jnp.argsort(-scores, axis=1)
    out = jnp.take_along_axis(out, order[..., None], axis=1)
    sorted_scores = jnp.take_along_axis(scores, order, axis=1)
    return out, sorted_scores


@register("beam_search_decode", ["Ids", "Parents", "Scores"],
          ["SentenceIds", "SentenceScores"], differentiable=False)
def beam_search_decode(ids_array, parents_array, scores, *, beam_size=0,
                       end_id=0):
    """Ids/Parents are tensor arrays (lists of [B, K] steps) written by
    a While decode loop; Scores is the final [B, K] accumulated score
    (reference: beam_search_decode_op.cc)."""
    enforce(isinstance(ids_array, (list, tuple)) and
            isinstance(parents_array, (list, tuple)),
            "beam_search_decode expects tensor arrays (use array_write "
            "inside the decode While loop)")
    enforce(len(ids_array) == len(parents_array),
            "Ids and Parents arrays must have equal length")
    return beam_search_backtrack(list(ids_array), list(parents_array),
                                 jnp.asarray(scores), end_id=end_id)
