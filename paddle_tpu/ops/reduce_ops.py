"""Reduction ops (reference: paddle/fluid/operators/reduce_ops/, ~3k LoC,
templated on functors; here each is a one-line jnp lowering)."""

from __future__ import annotations

import jax.numpy as jnp

from .registry import register


def _reduce(name, fn, differentiable=True):
    @register(name, ["X"], ["Out"], differentiable=differentiable)
    def impl(x, *, dim=None, keep_dim=False, reduce_all=False):
        axis = None if (reduce_all or dim is None) else tuple(
            d % x.ndim for d in (dim if isinstance(dim, (list, tuple))
                                 else [dim]))
        return fn(x, axis=axis, keepdims=keep_dim)
    return impl


_reduce("reduce_sum", jnp.sum)
_reduce("reduce_mean", jnp.mean)
_reduce("reduce_max", jnp.max)
_reduce("reduce_min", jnp.min)
_reduce("reduce_prod", jnp.prod)
_reduce("reduce_all", jnp.all, differentiable=False)
_reduce("reduce_any", jnp.any, differentiable=False)


@register("mean", ["X"], ["Out"])
def mean(x):
    return jnp.mean(x)


@register("logsumexp", ["X"], ["Out"])
def logsumexp(x, *, dim=None, keep_dim=False):
    from jax.scipy.special import logsumexp as lse
    axis = None if dim is None else tuple(
        d % x.ndim for d in (dim if isinstance(dim, (list, tuple))
                             else [dim]))
    return lse(x, axis=axis, keepdims=keep_dim)


@register("frobenius_norm", ["X"], ["Out"])
def frobenius_norm(x, *, dim=None, keep_dim=False):
    axis = None if dim is None else tuple(dim)
    return jnp.sqrt(jnp.sum(jnp.square(x), axis=axis, keepdims=keep_dim))
