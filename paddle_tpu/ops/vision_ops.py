"""Vision / spatial rearrangement ops.

Reference coverage (paddle/fluid/operators/): lrn_op.cc,
affine_channel_op.cc, affine_grid_op.cc, pool_op.cc (pool3d),
max_pool_with_index_op (max_pool2d/3d_with_index), unpool_op.cc,
spp_op.cc, temporal_shift_op.cc, shuffle_channel_op.cc,
space_to_depth_op.cc, crop_op.cc, pad_constant_like_op.cc,
random_crop_op.cc, multiplex_op.cc, reverse_op.cc, interpolate_op.cc
(nearest_interp / bilinear_interp), conv_transpose_op.cc
(conv3d_transpose), sync_batch_norm_op.cu, mean_iou_op.cc,
spectral_norm_op.cc, fsp_op.cc, conv_shift_op.cc, row_conv_op.cc,
im2sequence_op.cc, add_position_encoding_op.cc, data_norm_op.cc,
cvm_op.cc, lstmp_op.cc is in rnn territory (kept there).

All lower to jnp/lax; XLA owns layout + fusion on TPU.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from .registry import register


# ---------------------------------------------------------------------------
# normalization-ish
# ---------------------------------------------------------------------------

@register("lrn", ["X"], ["Out", "MidOut"])
def lrn(x, *, n=5, k=1.0, alpha=1e-4, beta=0.75):
    """Local response normalization across channels (reference:
    lrn_op.cc, NCHW). mid = k + alpha * local_sum(x^2);
    out = x * mid^-beta."""
    sq = jnp.square(x)
    half = n // 2
    # sum over a channel window via reduce_window on axis 1
    local = lax.reduce_window(
        sq, 0.0, lax.add, (1, n, 1, 1), (1, 1, 1, 1),
        [(0, 0), (half, n - 1 - half), (0, 0), (0, 0)])
    mid = k + alpha * local
    return x * jnp.power(mid, -beta), mid


@register("affine_channel", ["X", "Scale", "Bias"], ["Out"])
def affine_channel(x, scale, bias, *, data_layout="NCHW"):
    """Per-channel x*scale+bias (reference: affine_channel_op.cc —
    the BN-fold target op)."""
    shape = [1] * x.ndim
    c = 1 if data_layout == "NCHW" else x.ndim - 1
    shape[c] = x.shape[c]
    return x * scale.reshape(shape) + bias.reshape(shape)


@register("data_norm", ["X", "BatchSize", "BatchSum", "BatchSquareSum"],
          ["Y", "Means", "Scales"],
          nondiff=("BatchSize", "BatchSum", "BatchSquareSum"))
def data_norm(x, batch_size, batch_sum, batch_sq, *, epsilon=1e-4):
    """Stats-carried normalization for CTR features (reference:
    data_norm_op.cc): mean = sum/size, scale = rsqrt(var);
    accumulators update outside the op (summary_decay path)."""
    mean = batch_sum / batch_size
    var = batch_sq / batch_size - jnp.square(mean)
    scale = lax.rsqrt(var + epsilon)
    return (x - mean) * scale, mean, scale


@register("spectral_norm", ["Weight", "U", "V"], ["Out"],
          nondiff=("U", "V"))
def spectral_norm(w, u, v, *, dim=0, power_iters=1, eps=1e-12):
    """Spectral weight normalization (reference: spectral_norm_op.cc):
    power-iterate u,v; out = W / sigma. The iteration count is a
    static attr so the loop unrolls under jit."""
    shape = w.shape
    if dim != 0:
        perm = (dim,) + tuple(i for i in range(w.ndim) if i != dim)
        w_mat = jnp.transpose(w, perm)
    else:
        w_mat = w
    h = w_mat.shape[0]
    mat = w_mat.reshape(h, -1)

    def l2n(a):
        return a / jnp.maximum(jnp.linalg.norm(a), eps)

    u = u.reshape(-1)
    v = v.reshape(-1)
    for _ in range(max(power_iters, 0)):
        v = l2n(mat.T @ u)
        u = l2n(mat @ v)
    sigma = u @ mat @ v
    out = w_mat.reshape(w_mat.shape) / sigma
    if dim != 0:
        inv = [0] * w.ndim
        for i, p in enumerate(perm):
            inv[p] = i
        out = jnp.transpose(out.reshape(w_mat.shape), inv)
    else:
        out = out.reshape(shape)
    return out


@register("sync_batch_norm",
          ["X", "Scale", "Bias", "Mean", "Variance"],
          ["Y", "MeanOut", "VarianceOut", "SavedMean", "SavedVariance"],
          nondiff=("Mean", "Variance"))
def sync_batch_norm(x, scale, bias, mean, var, *, epsilon=1e-5,
                    momentum=0.9, is_test=False, data_layout="NCHW",
                    use_global_stats=False):
    """Cross-replica batch norm (reference: sync_batch_norm_op.cu —
    ncclAllReduce of the per-device moments). TPU-native: the batch
    axis of a global array already spans the dp mesh, so plain
    batch_norm's moments ARE the global-batch moments; GSPMD inserts
    the cross-chip reduction where the batch is sharded. Registered
    separately so programs using the reference op name run unchanged."""
    from .nn_ops import batch_norm
    return batch_norm(x, scale, bias, mean, var, epsilon=epsilon,
                      momentum=momentum, is_test=is_test,
                      data_layout=data_layout,
                      use_global_stats=use_global_stats)


# ---------------------------------------------------------------------------
# pooling family
# ---------------------------------------------------------------------------

def _triple(v):
    return tuple(v) if isinstance(v, (list, tuple)) else (v,) * 3


@register("pool3d", ["X"], ["Out"])
def pool3d(x, *, ksize, pooling_type="max", strides=(1, 1, 1),
           paddings=(0, 0, 0), global_pooling=False, ceil_mode=False,
           exclusive=True, adaptive=False):
    """NCDHW 3-D pooling (reference: pool_op.cc pool3d)."""
    ks, st, pd = _triple(ksize), _triple(strides), _triple(paddings)
    if global_pooling:
        ks = x.shape[2:]
        pd = (0, 0, 0)
    window = (1, 1) + tuple(ks)
    stride = (1, 1) + tuple(st)
    pads = [(0, 0), (0, 0)] + [(p, p) for p in pd]
    if pooling_type == "max":
        return lax.reduce_window(x, -jnp.inf, lax.max, window, stride,
                                 pads)
    s = lax.reduce_window(x, 0.0, lax.add, window, stride, pads)
    if exclusive and any(pd):
        ones = jnp.ones_like(x)
        cnt = lax.reduce_window(ones, 0.0, lax.add, window, stride,
                                pads)
        return s / cnt
    return s / float(ks[0] * ks[1] * ks[2])


def _pool_with_index(x, ksize, strides, paddings):
    """Shared max-pool-with-argmax: value path is a plain (autodiff-
    friendly) max reduce_window; the winner's FLAT spatial index comes
    from a variadic reduce_window on stop_gradient values (no JVP rule
    exists for general variadic reducers, and indices carry no
    tangents anyway). Reference: max_pool_with_index_op."""
    ks, st, pd = tuple(ksize), tuple(strides), tuple(paddings)
    window = (1, 1) + ks
    stride = (1, 1) + st
    pads = [(0, 0), (0, 0)] + [(p, p) for p in pd]
    out = lax.reduce_window(x, -jnp.inf, lax.max, window, stride, pads)

    sizes = x.shape[2:]
    total = 1
    for s in sizes:
        total *= s
    flat = jnp.arange(total, dtype=jnp.float32).reshape(sizes)
    flat = jnp.broadcast_to(flat, x.shape)

    def sel(a, b):
        av, ai = a
        bv, bi = b
        take_b = bv > av
        return jnp.where(take_b, bv, av), jnp.where(take_b, bi, ai)

    _, idx = lax.reduce_window(
        (lax.stop_gradient(x), flat), (-jnp.inf, jnp.float32(-1)),
        sel, window, stride, pads)
    return out, idx.astype(jnp.int32)


@register("max_pool2d_with_index", ["X"], ["Out", "Mask"],
          nondiff=())
def max_pool2d_with_index(x, *, ksize, strides=(1, 1),
                          paddings=(0, 0), global_pooling=False,
                          adaptive=False):
    ks = tuple(ksize) if isinstance(ksize, (list, tuple)) \
        else (ksize,) * 2
    if global_pooling:
        ks = x.shape[2:]
    st = tuple(strides) if isinstance(strides, (list, tuple)) \
        else (strides,) * 2
    pd = tuple(paddings) if isinstance(paddings, (list, tuple)) \
        else (paddings,) * 2
    return _pool_with_index(x, ks, st, pd)


@register("max_pool3d_with_index", ["X"], ["Out", "Mask"],
          nondiff=())
def max_pool3d_with_index(x, *, ksize, strides=(1, 1, 1),
                          paddings=(0, 0, 0), global_pooling=False,
                          adaptive=False):
    ks = _triple(ksize)
    if global_pooling:
        ks = x.shape[2:]
    return _pool_with_index(x, ks, _triple(strides),
                            _triple(paddings))


@register("unpool", ["X", "Indices"], ["Out"], nondiff=("Indices",))
def unpool(x, indices, *, unpooling_type="max", ksize=(2, 2),
           strides=(2, 2), paddings=(0, 0), output_size=None):
    """Max-unpool: scatter pooled values back to their argmax positions
    (reference: unpool_op.cc). Indices are flat H*W positions from
    max_pool2d_with_index."""
    B, C, Hp, Wp = x.shape
    if output_size is not None:
        H, W = output_size[-2:]
    else:
        H = (Hp - 1) * strides[0] - 2 * paddings[0] + ksize[0]
        W = (Wp - 1) * strides[1] - 2 * paddings[1] + ksize[1]
    flat = jnp.zeros((B, C, H * W), x.dtype)
    idx = indices.reshape(B, C, -1).astype(jnp.int32)
    vals = x.reshape(B, C, -1)
    bidx = lax.broadcasted_iota(jnp.int32, idx.shape, 0)
    cidx = lax.broadcasted_iota(jnp.int32, idx.shape, 1)
    flat = flat.at[bidx, cidx, idx].add(vals, mode="drop")
    return flat.reshape(B, C, H, W)


@register("spp", ["X"], ["Out"])
def spp(x, *, pyramid_height=3, pooling_type="max"):
    """Spatial pyramid pooling (reference: spp_op.cc): concat the
    flattened adaptive pools at 1x1, 2x2, ... 2^(h-1) bins."""
    from .nn_ops import adaptive_pool2d
    outs = []
    for level in range(pyramid_height):
        bins = 2 ** level
        p = adaptive_pool2d(x, pool_size=(bins, bins),
                            pooling_type=pooling_type)
        outs.append(p.reshape(x.shape[0], -1))
    return jnp.concatenate(outs, axis=1)


# ---------------------------------------------------------------------------
# rearrangement
# ---------------------------------------------------------------------------

@register("temporal_shift", ["X"], ["Out"])
def temporal_shift(x, *, seg_num, shift_ratio=0.25):
    """TSM channel shift across the time dimension (reference:
    temporal_shift_op.cc): x [N*T, C, H, W]; first ratio*C channels
    shift t-1, next ratio*C shift t+1, rest stay."""
    NT, C, H, W = x.shape
    T = seg_num
    N = NT // T
    x5 = x.reshape(N, T, C, H, W)
    c1 = int(C * shift_ratio)
    c2 = int(C * 2 * shift_ratio)
    back = jnp.pad(x5[:, 1:, :c1], ((0, 0), (0, 1), (0, 0), (0, 0),
                                    (0, 0)))
    fwd = jnp.pad(x5[:, :-1, c1:c2], ((0, 0), (1, 0), (0, 0), (0, 0),
                                      (0, 0)))
    out = jnp.concatenate([back, fwd, x5[:, :, c2:]], axis=2)
    return out.reshape(NT, C, H, W)


@register("shuffle_channel", ["X"], ["Out"])
def shuffle_channel(x, *, group):
    """ShuffleNet channel shuffle (reference: shuffle_channel_op.cc)."""
    B, C, H, W = x.shape
    return x.reshape(B, group, C // group, H, W) \
        .transpose(0, 2, 1, 3, 4).reshape(B, C, H, W)


@register("space_to_depth", ["X"], ["Out"])
def space_to_depth(x, *, blocksize):
    """Rearrange spatial blocks into channels (reference:
    space_to_depth_op.cc, NCHW)."""
    B, C, H, W = x.shape
    bs = blocksize
    x = x.reshape(B, C, H // bs, bs, W // bs, bs)
    return x.transpose(0, 3, 5, 1, 2, 4).reshape(
        B, C * bs * bs, H // bs, W // bs)


@register("crop", ["X", "Offsets"], ["Out"], nondiff=("Offsets",))
def crop(x, offsets=None, *, shape, offsets_attr=None):
    """Crop to ``shape`` at static or tensor offsets (reference:
    crop_op.cc)."""
    if offsets is None:
        offsets = jnp.asarray(offsets_attr or [0] * x.ndim)
    offsets = offsets.reshape(-1).astype(jnp.int32)
    starts = [offsets[i] for i in range(x.ndim)]
    return lax.dynamic_slice(x, starts, shape)


@register("pad_constant_like", ["X", "Y"], ["Out"], nondiff=("X",))
def pad_constant_like(x, y, *, pad_value=0.0):
    """Pad Y at the tail of every dim up to X's shape (reference:
    pad_constant_like_op.cc)."""
    pads = [(0, x.shape[i] - y.shape[i]) for i in range(y.ndim)]
    return jnp.pad(y, pads, constant_values=pad_value)


@register("random_crop", ["X", "Seed"], ["Out", "SeedOut"],
          nondiff=("Seed",), needs_rng=True)
def random_crop(x, seed, *, shape, startup_seed=0, rng=None):
    """Random spatial crop of the trailing dims (reference:
    random_crop_op.cc; it threads an integer seed var — kept as a
    pass-through output, the actual bits come from the step RNG)."""
    ndim_crop = len(shape)
    lead = x.ndim - ndim_crop
    keys = jax.random.split(rng, ndim_crop)
    starts = [jnp.int32(0)] * lead
    for i in range(ndim_crop):
        limit = x.shape[lead + i] - shape[i]
        starts.append(jax.random.randint(keys[i], (), 0, limit + 1))
    out = lax.dynamic_slice(x, starts,
                            x.shape[:lead] + tuple(shape))
    return out, seed


@register("multiplex", ["Ids", "X*"], ["Out"], nondiff=("Ids",))
def multiplex(ids, xs):
    """Row-wise select among candidate tensors (reference:
    multiplex_op.cc): out[r] = X[ids[r]][r]."""
    stack = jnp.stack(xs, axis=0)                   # [n, B, ...]
    idx = ids.reshape(-1).astype(jnp.int32)
    return stack[idx, jnp.arange(stack.shape[1])]


@register("reverse", ["X"], ["Out"])
def reverse(x, *, axis):
    axes = axis if isinstance(axis, (list, tuple)) else [axis]
    return jnp.flip(x, axis=tuple(a % x.ndim for a in axes))


# interp aliases over the shared lowering (reference registers
# nearest_interp / bilinear_interp as separate op types)
@register("nearest_interp", ["X", "OutSize"], ["Out"],
          nondiff=("OutSize",))
def nearest_interp(x, out_size=None, *, out_h=-1, out_w=-1,
                   align_corners=True, align_mode=1,
                   data_layout="NCHW"):
    from .nn_ops import interpolate
    shape = (int(out_size[0]), int(out_size[1])) \
        if out_size is not None else (out_h, out_w)
    return interpolate(x, out_shape=shape, method="nearest",
                       align_corners=align_corners)


@register("bilinear_interp", ["X", "OutSize"], ["Out"],
          nondiff=("OutSize",))
def bilinear_interp(x, out_size=None, *, out_h=-1, out_w=-1,
                    align_corners=True, align_mode=1,
                    data_layout="NCHW"):
    from .nn_ops import interpolate
    shape = (int(out_size[0]), int(out_size[1])) \
        if out_size is not None else (out_h, out_w)
    return interpolate(x, out_shape=shape, method="bilinear",
                       align_corners=align_corners)


@register("conv3d_transpose", ["Input", "Filter"], ["Output"])
def conv3d_transpose(x, w, *, strides=(1, 1, 1), paddings=(0, 0, 0),
                     dilations=(1, 1, 1), groups=1):
    """NCDHW deconvolution (reference: conv_transpose_op.cc). Same
    input-dilated formulation as conv2d_transpose."""
    st, dl = _triple(strides), _triple(dilations)
    pd = _triple(paddings)
    ks = w.shape[2:]
    pad = [(dl[i] * (ks[i] - 1) - pd[i],) * 2 for i in range(3)]
    w_flip = jnp.flip(w, axis=(2, 3, 4))
    dn = lax.conv_dimension_numbers(x.shape, w.shape,
                                    ("NCDHW", "IODHW", "NCDHW"))
    return lax.conv_general_dilated(
        x, w_flip, window_strides=(1, 1, 1), padding=pad,
        lhs_dilation=st, rhs_dilation=dl, dimension_numbers=dn,
        feature_group_count=groups)


# ---------------------------------------------------------------------------
# grids / misc
# ---------------------------------------------------------------------------

@register("affine_grid", ["Theta", "OutputShape"], ["Output"],
          nondiff=("OutputShape",))
def affine_grid(theta, output_shape=None, *, output_shape_attr=None,
                align_corners=True):
    """Affine sampling-grid generation (reference: affine_grid_op.cc):
    theta [B,2,3] -> grid [B,H,W,2] of (x,y) source coords in
    [-1,1]."""
    shape = [int(v) for v in (
        output_shape if output_shape is not None
        else output_shape_attr)]
    H, W = int(shape[-2]), int(shape[-1])
    B = theta.shape[0]

    def axis_coords(n):
        if align_corners:
            return jnp.linspace(-1.0, 1.0, n)
        step = 2.0 / n
        return jnp.linspace(-1.0 + step / 2, 1.0 - step / 2, n)

    ys = axis_coords(H)
    xs = axis_coords(W)
    gx, gy = jnp.meshgrid(xs, ys)                  # [H, W]
    ones = jnp.ones_like(gx)
    base = jnp.stack([gx, gy, ones], axis=-1)      # [H, W, 3]
    grid = jnp.einsum("hwk,bck->bhwc", base,
                      theta.astype(jnp.float32))   # [B, H, W, 2]
    return grid


@register("mean_iou", ["Predictions", "Labels"],
          ["OutMeanIou", "OutWrong", "OutCorrect"],
          differentiable=False)
def mean_iou(pred, label, *, num_classes):
    """Mean intersection-over-union (reference: mean_iou_op.cc)."""
    pred = pred.reshape(-1).astype(jnp.int32)
    label = label.reshape(-1).astype(jnp.int32)
    correct_mask = pred == label
    out_correct = jnp.zeros((num_classes,), jnp.int32).at[
        jnp.where(correct_mask, label, num_classes)].add(
        1, mode="drop")
    pred_cnt = jnp.zeros((num_classes,), jnp.int32).at[pred].add(
        1, mode="drop")
    lab_cnt = jnp.zeros((num_classes,), jnp.int32).at[label].add(
        1, mode="drop")
    union = pred_cnt + lab_cnt - out_correct
    valid = union > 0
    iou = jnp.where(valid, out_correct / jnp.maximum(union, 1), 0.0)
    miou = jnp.sum(iou) / jnp.maximum(jnp.sum(valid), 1)
    out_wrong = (lab_cnt - out_correct).astype(jnp.int32)
    return miou.astype(jnp.float32), out_wrong, out_correct


@register("fsp", ["X", "Y"], ["Out"])
def fsp(x, y):
    """Flow-of-solution-procedure matrix for distillation (reference:
    fsp_op.cc): out[b,i,j] = mean_hw x[b,i,h,w] * y[b,j,h,w]."""
    B, C1, H, W = x.shape
    return jnp.einsum("bihw,bjhw->bij", x, y) / float(H * W)


@register("conv_shift", ["X", "Y"], ["Out"])
def conv_shift(x, y):
    """Circular correlation (reference: conv_shift_op.cc): out[b,i] =
    sum_j x[b, (i+j-M/2) mod N] * y[b,j]. M is small; the loop
    unrolls statically."""
    B, N = x.shape
    M = y.shape[1]
    half = M // 2
    out = jnp.zeros_like(x)
    for j in range(M):
        out = out + jnp.roll(x, half - j, axis=1) * y[:, j:j + 1]
    return out


@register("row_conv", ["X", "Filter"], ["Out"])
def row_conv(x, filt):
    """Lookahead row convolution (reference: row_conv_op.cc):
    out[b,t] = sum_{j<ctx} x[b,t+j] * filt[j] (zero past the end).
    x [B, T, D], filt [ctx, D]."""
    ctx = filt.shape[0]
    out = jnp.zeros_like(x)
    for j in range(ctx):
        shifted = jnp.pad(x[:, j:], ((0, 0), (0, j), (0, 0)))
        out = out + shifted * filt[j]
    return out


@register("im2sequence", ["X"], ["Out"])
def im2sequence(x, *, kernels, strides=(1, 1), paddings=(0, 0, 0, 0)):
    """Image -> patch sequence (reference: im2sequence_op.cc):
    [B,C,H,W] -> [B, oh*ow, C*kh*kw]."""
    kh, kw = kernels
    patches = lax.conv_general_dilated_patches(
        x, (kh, kw), tuple(strides),
        [(paddings[0], paddings[2]), (paddings[1], paddings[3])])
    B, CKK, OH, OW = patches.shape
    return patches.reshape(B, CKK, OH * OW).transpose(0, 2, 1)


@register("add_position_encoding", ["X"], ["Out"])
def add_position_encoding(x, *, alpha=1.0, beta=1.0):
    """Sinusoidal position encoding add (reference:
    add_position_encoding_op.cc): out = alpha*x + beta*PE."""
    B, T, D = x.shape
    pos = jnp.arange(T, dtype=jnp.float32)[:, None]
    half = D // 2
    div = jnp.power(10000.0, jnp.arange(half, dtype=jnp.float32) /
                    max(half, 1))
    ang = pos / div[None, :]
    pe = jnp.concatenate([jnp.sin(ang), jnp.cos(ang)], axis=1)
    if pe.shape[1] < D:
        pe = jnp.pad(pe, ((0, 0), (0, D - pe.shape[1])))
    return alpha * x + beta * pe[None, :, :].astype(x.dtype)


@register("cvm", ["X", "CVM"], ["Y"], nondiff=("CVM",))
def cvm(x, cvm_feats, *, use_cvm=True):
    """Continuous-value-model feature handling (reference: cvm_op.cc):
    the first two columns are show/click counters; use_cvm keeps them
    (log-transformed by the feed pipeline), otherwise they are cut."""
    if use_cvm:
        return x
    return x[:, 2:]


def _bilinear_gather(img, ys, xs):
    """img [C, H, W]; ys/xs float [...]: bilinear sample with zero
    padding outside. Returns [C, ...]."""
    C, H, W = img.shape
    y0 = jnp.floor(ys)
    x0 = jnp.floor(xs)
    dy = ys - y0
    dx = xs - x0

    def tap(yi, xi):
        inside = (yi >= 0) & (yi <= H - 1) & (xi >= 0) & (xi <= W - 1)
        yc = jnp.clip(yi, 0, H - 1).astype(jnp.int32)
        xc = jnp.clip(xi, 0, W - 1).astype(jnp.int32)
        v = img[:, yc, xc]                    # [C, ...]
        return jnp.where(inside, v, 0.0)

    return (tap(y0, x0) * (1 - dy) * (1 - dx) +
            tap(y0, x0 + 1) * (1 - dy) * dx +
            tap(y0 + 1, x0) * dy * (1 - dx) +
            tap(y0 + 1, x0 + 1) * dy * dx)


@register("deformable_conv", ["Input", "Offset", "Mask", "Filter"],
          ["Output"])
def deformable_conv(x, offset, mask, w, *, strides=(1, 1),
                    paddings=(0, 0), dilations=(1, 1),
                    deformable_groups=1, groups=1, im2col_step=64):
    """Deformable convolution v1/v2 (reference:
    deformable_conv_op.cc): each kernel tap samples the input at its
    regular position PLUS a learned offset (bilinear), optionally
    modulated by a mask (v2). Offset [N, 2*dg*kh*kw, Ho, Wo] ordered
    (y, x) per tap; Mask [N, dg*kh*kw, Ho, Wo] or None.

    TPU formulation: build the sampled patch tensor
    [N, C, kh*kw, Ho, Wo] with vectorized bilinear gathers (XLA lowers
    them to dynamic-gathers; the backward scatter-adds are derived by
    autodiff, replacing the hand-written deformable_col2im kernels),
    then contract with the filter in ONE einsum on the MXU."""
    N, C, H, W = x.shape
    Co, Cg, kh, kw = w.shape
    sh, sw = strides
    ph, pw = paddings
    dh, dw = dilations
    K = kh * kw
    Ho = (H + 2 * ph - (dh * (kh - 1) + 1)) // sh + 1
    Wo = (W + 2 * pw - (dw * (kw - 1) + 1)) // sw + 1
    dg = deformable_groups

    # base sampling positions per tap: [K, Ho, Wo]
    oy = jnp.arange(Ho) * sh - ph
    ox = jnp.arange(Wo) * sw - pw
    ky, kx = jnp.meshgrid(jnp.arange(kh) * dh, jnp.arange(kw) * dw,
                          indexing="ij")
    base_y = ky.reshape(K, 1, 1) + oy.reshape(1, Ho, 1)
    base_x = kx.reshape(K, 1, 1) + ox.reshape(1, 1, Wo)
    base_y = jnp.broadcast_to(base_y, (K, Ho, Wo)).astype(jnp.float32)
    base_x = jnp.broadcast_to(base_x, (K, Ho, Wo)).astype(jnp.float32)

    off = offset.reshape(N, dg, K, 2, Ho, Wo).astype(jnp.float32)
    ys = base_y[None, None] + off[:, :, :, 0]       # [N, dg, K, Ho, Wo]
    xs = base_x[None, None] + off[:, :, :, 1]
    if mask is not None:
        m = mask.reshape(N, dg, K, Ho, Wo).astype(jnp.float32)
    else:
        m = None

    cpg = C // dg  # channels per deformable group

    def one_image(args):
        img, ysn, xsn, mn = args

        def one_dg(d):
            sub = lax.dynamic_slice_in_dim(img, d * cpg, cpg, axis=0)
            s = _bilinear_gather(sub, ysn[d], xsn[d])  # [cpg, K, Ho, Wo]
            if mn is not None:
                s = s * mn[d][None]
            return s

        # dg is small and static: unrolled loop keeps indexing static
        parts = [one_dg(d) for d in range(dg)]
        return jnp.concatenate(parts, axis=0)       # [C, K, Ho, Wo]

    if m is None:
        cols = lax.map(lambda a: one_image((a[0], a[1], a[2], None)),
                       (x, ys, xs))
    else:
        cols = lax.map(one_image, (x, ys, xs, m))
    # cols [N, C, K, Ho, Wo] x w [Co, Cg, kh, kw] -> [N, Co, Ho, Wo]
    wk = w.reshape(Co, Cg, K)
    if groups == 1:
        out = jnp.einsum("nckhw,ock->nohw", cols, wk)
    else:
        cpg2 = C // groups
        opg = Co // groups
        cols_g = cols.reshape(N, groups, cpg2, K, Ho, Wo)
        wk_g = wk.reshape(groups, opg, Cg, K)
        out = jnp.einsum("ngckhw,gock->ngohw", cols_g, wk_g) \
            .reshape(N, Co, Ho, Wo)
    return out.astype(x.dtype)
