"""Shared helpers for the pallas TPU kernel library.

This package is the analog of the reference's hand-tuned kernel layers
— operators/jit/ (runtime x86 codegen, jit/README.en.md), operators/
fused/ and operators/math/ — re-targeted at the TPU: each kernel is a
pallas Mosaic program registered as a ``library="pallas"`` variant of a
regular op (ops/registry.py register_variant), mirroring the
reference's kernel-type dispatch on library=CUDNN/MKLDNN
(op_kernel_type.h). Every variant keeps the pure-jnp lowering as its
reference implementation (the jit/refer/ pattern) — used for the
backward pass (recompute-style custom_vjp) and as the fallback when
pallas is disabled.

Enable with ``FLAGS_op_library=pallas`` (core/flags.py) or per-run via
Executor internals; tests exercise both paths and compare (the
operators/jit/test.cc pattern).
"""

from __future__ import annotations

import jax
from jax.experimental.pallas import tpu as _pltpu

from ...core.flags import FLAGS

# jax renamed the Mosaic compiler-params dataclass across releases
# (<=0.4.3x: ``TPUCompilerParams``; newer: ``CompilerParams``). The
# kernels import this alias so they collect and run on either API.
CompilerParams = getattr(_pltpu, "CompilerParams", None) or \
    getattr(_pltpu, "TPUCompilerParams")


def interpret_mode() -> bool:
    """Pallas kernels compile for TPU; everywhere else (CPU unit tests,
    the 8-device virtual mesh) they run in interpreter mode."""
    return jax.default_backend() != "tpu"


def blk(n: int, target: int = 128) -> int:
    """Largest divisor of n that is <= target (block size picker —
    shapes in the models are powers of two, so this lands on 128/64/...;
    degenerate shapes fall back to the full dimension)."""
    if n <= target:
        return n
    for b in range(target, 0, -1):
        if n % b == 0:
            return b
    return n


FLAGS.define("op_library", "",
             "kernel library variant for op lowerings ('' = pure jnp "
             "XLA path, 'pallas' = hand-written TPU kernels)")
