"""Per-hop flash kernels for ring attention (sequence parallelism).

The jnp ring body (parallel/ring_attention.py) materializes a
[B, H, Sq_loc, Sk_loc] score tensor in HBM on EVERY ring hop — at pod
scale (S=32k, sp=32 -> 1k x 1k blocks x n hops) that is the whole HBM
bandwidth budget. These kernels compute one hop's block-attention
partials with the scores living only in VMEM:

  forward:  (pv, m, l) = softmax-partials(q, k_blk, v_blk)
            — unnormalized p@v plus the row max/sum, combined across
            hops by the caller's online-softmax rescale (the O(Sq*Dh)
            rescale stays in jnp: it is tiny next to the O(Sq*Sk)
            scores the kernel keeps on-chip);
  backward: (dq_blk, dk_blk, dv_blk) from a single in-kernel exp
            recompute against the saved global lse and delta =
            rowsum(do * out) — the flash backward identity, per hop.

Absolute q/k sequence offsets ride in SMEM so the causal mask works on
the global positions of the local shards (they are traced values —
lax.axis_index under shard_map).

No reference analog (SURVEY §5 long-context exceeds the 2019
reference); kernel discipline follows ops/pallas/attention.py: VMEM
budget model chooses the row group G and q block, with a plain-jnp
fallback when no geometry fits (caller checks ``applicable``).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .common import CompilerParams, blk, interpret_mode

_NEG = -1.0e30

_QK = (((2,), (2,)), ((0,), (0,)))   # [G,q,d]x[G,k,d] -> [G,q,k]
_PV = (((2,), (1,)), ((0,), (0,)))   # [G,q,k]x[G,k,d] -> [G,q,d]
_TT = (((1,), (1,)), ((0,), (0,)))   # [G,q,k]^T contractions

# Same modeling constants as the 1k kernels (attention.py): ~2 f32
# score temporaries live after Mosaic reuse, 15 MB of the 16 MB v5e
# scoped limit.
_TEMP_BYTES = 8
_VMEM_BUDGET = 15 << 20


def _row_bytes(itemsize, blk_q, Sk, Dh, bwd):
    lanes = max(Dh, 128)
    # fwd streams: q,pv rows of blk_q; k,v rows of Sk
    # bwd streams: q,do,dq rows of blk_q; k,v rows of Sk; PLUS the
    # RESIDENT dk/dv f32 accumulator blocks (revisited across q-steps)
    if bwd:
        stream = (3 * blk_q + 2 * Sk) * lanes * itemsize * 2
        stream += 2 * Sk * lanes * 4 * 2
    else:
        stream = (2 * blk_q + 2 * Sk) * lanes * itemsize * 2
    return stream + blk_q * Sk * _TEMP_BYTES


def _pick_geometry(BH, Sq, Sk, Dh, itemsize, bwd):
    """(G, blk_q) fitting the VMEM budget, or None."""
    blk_q = blk(Sq, 256)
    G = blk(BH, 8)
    while True:
        if G * _row_bytes(itemsize, blk_q, Sk, Dh, bwd) \
                <= _VMEM_BUDGET:
            return G, blk_q
        if G > 1:
            G = blk(BH, G // 2)
            continue
        if blk_q > 8 and blk(Sq, blk_q // 2) < blk_q:
            blk_q = blk(Sq, blk_q // 2)
            continue
        return None


def applicable(B, H, Sq, Sk, Dh, itemsize):
    """True when both hop kernels have a fitting geometry AND the
    shapes land on natural TPU tiles (no padding logic in the
    kernels)."""
    if Sq % 8 != 0 or Sk % 128 != 0 or Dh % 8 != 0:
        return False
    bh = B * H
    return (_pick_geometry(bh, Sq, Sk, Dh, itemsize, False) is not None
            and _pick_geometry(bh, Sq, Sk, Dh, itemsize, True)
            is not None)


def _causal_mask_s(s, offs_ref, j, blk_q, Sk):
    q_pos = offs_ref[0] + j * blk_q + lax.broadcasted_iota(
        jnp.int32, s.shape, 1)
    k_pos = offs_ref[1] + lax.broadcasted_iota(jnp.int32, s.shape, 2)
    return jnp.where(k_pos <= q_pos, s, _NEG)


def _fwd_kernel(offs_ref, q_ref, k_ref, v_ref, pv_ref, m_ref, l_ref,
                *, scale, causal, blk_q, Sk):
    j = pl.program_id(1)
    s = lax.dot_general(q_ref[...].astype(jnp.float32) * scale,
                        k_ref[...].astype(jnp.float32), _QK,
                        preferred_element_type=jnp.float32)
    if causal:
        s = _causal_mask_s(s, offs_ref, j, blk_q, Sk)
    m = jnp.max(s, -1)                                # [G, blk_q]
    p = jnp.exp(s - m[:, :, None])
    p = jnp.where(s <= _NEG / 2, 0.0, p)              # fully-masked rows
    l = jnp.sum(p, -1)
    pv_ref[...] = lax.dot_general(
        p.astype(v_ref.dtype), v_ref[...], _PV,
        preferred_element_type=jnp.float32)
    m_ref[...] = m
    l_ref[...] = l


def _bwd_kernel(offs_ref, q_ref, k_ref, v_ref, do_ref, lse_ref,
                delta_ref, dq_ref, dk_ref, dv_ref, *, scale, causal,
                blk_q, Sk):
    j = pl.program_id(1)
    s = lax.dot_general(q_ref[...].astype(jnp.float32) * scale,
                        k_ref[...].astype(jnp.float32), _QK,
                        preferred_element_type=jnp.float32)
    if causal:
        s = _causal_mask_s(s, offs_ref, j, blk_q, Sk)
    p = jnp.exp(s - lse_ref[...][:, :, None])
    p = jnp.where(s <= _NEG / 2, 0.0, p)
    do = do_ref[...]
    dp = lax.dot_general(do, v_ref[...], _QK,
                         preferred_element_type=jnp.float32)
    ds = p * (dp - delta_ref[...][:, :, None]) * scale
    dq_ref[...] = lax.dot_general(
        ds.astype(q_ref.dtype), k_ref[...], _PV,
        preferred_element_type=jnp.float32)
    dk = lax.dot_general(ds.astype(q_ref.dtype), q_ref[...], _TT,
                         preferred_element_type=jnp.float32)
    dv = lax.dot_general(p.astype(do.dtype), do, _TT,
                         preferred_element_type=jnp.float32)

    @pl.when(j == 0)
    def _init():
        dk_ref[...] = dk
        dv_ref[...] = dv

    @pl.when(j > 0)
    def _acc():
        dk_ref[...] += dk
        dv_ref[...] += dv


def fwd_block(q, k, v, q_off, k_off, scale, causal):
    """One ring hop's attention partials. q [B,H,Sq,Dh]; k,v
    [B,H,Sk,Dh]; q_off/k_off traced int32 global offsets. Returns
    (pv [B,H,Sq,Dh] f32 unnormalized, m [B,H,Sq] f32, l [B,H,Sq]
    f32)."""
    B, H, Sq, Dh = q.shape
    Sk = k.shape[2]
    BH = B * H
    geo = _pick_geometry(BH, Sq, Sk, Dh, q.dtype.itemsize, False)
    if geo is None or not applicable(B, H, Sq, Sk, Dh,
                                     q.dtype.itemsize):
        raise ValueError(
            "ring flash kernel has no fitting geometry for "
            "B=%d H=%d Sq=%d Sk=%d Dh=%d itemsize=%d — check "
            "ring.applicable() before forcing use_flash=True"
            % (B, H, Sq, Sk, Dh, q.dtype.itemsize))
    G, blk_q = geo
    n_q = Sq // blk_q
    offs = jnp.stack([jnp.asarray(q_off, jnp.int32),
                      jnp.asarray(k_off, jnp.int32)])
    pv, m, l = pl.pallas_call(
        functools.partial(_fwd_kernel, scale=scale, causal=causal,
                          blk_q=blk_q, Sk=Sk),
        out_shape=(jax.ShapeDtypeStruct((BH, Sq, Dh), jnp.float32),
                   jax.ShapeDtypeStruct((BH, Sq), jnp.float32),
                   jax.ShapeDtypeStruct((BH, Sq), jnp.float32)),
        grid=(BH // G, n_q),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec((G, blk_q, Dh), lambda i, j: (i, j, 0)),
            pl.BlockSpec((G, Sk, Dh), lambda i, j: (i, 0, 0)),
            pl.BlockSpec((G, Sk, Dh), lambda i, j: (i, 0, 0)),
        ],
        out_specs=(pl.BlockSpec((G, blk_q, Dh), lambda i, j: (i, j, 0)),
                   pl.BlockSpec((G, blk_q), lambda i, j: (i, j)),
                   pl.BlockSpec((G, blk_q), lambda i, j: (i, j))),
        compiler_params=CompilerParams(
            dimension_semantics=("parallel", "parallel")),
        interpret=interpret_mode(),
    )(offs, q.reshape(BH, Sq, Dh), k.reshape(BH, Sk, Dh),
      v.reshape(BH, Sk, Dh))
    return (pv.reshape(B, H, Sq, Dh), m.reshape(B, H, Sq),
            l.reshape(B, H, Sq))


def bwd_block(q, k, v, do, lse, delta, q_off, k_off, scale, causal):
    """One ring hop's backward: (dq_blk, dk_blk, dv_blk) f32 from the
    saved lse/delta — the flash backward identity, scores recomputed
    in VMEM."""
    B, H, Sq, Dh = q.shape
    Sk = k.shape[2]
    BH = B * H
    geo = _pick_geometry(BH, Sq, Sk, Dh, q.dtype.itemsize, True)
    if geo is None:
        raise ValueError(
            "ring flash backward has no fitting geometry for "
            "B=%d H=%d Sq=%d Sk=%d Dh=%d itemsize=%d"
            % (B, H, Sq, Sk, Dh, q.dtype.itemsize))
    G, blk_q = geo
    n_q = Sq // blk_q
    offs = jnp.stack([jnp.asarray(q_off, jnp.int32),
                      jnp.asarray(k_off, jnp.int32)])
    dq, dk, dv = pl.pallas_call(
        functools.partial(_bwd_kernel, scale=scale, causal=causal,
                          blk_q=blk_q, Sk=Sk),
        out_shape=(jax.ShapeDtypeStruct((BH, Sq, Dh), jnp.float32),
                   jax.ShapeDtypeStruct((BH, Sk, Dh), jnp.float32),
                   jax.ShapeDtypeStruct((BH, Sk, Dh), jnp.float32)),
        grid=(BH // G, n_q),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec((G, blk_q, Dh), lambda i, j: (i, j, 0)),
            pl.BlockSpec((G, Sk, Dh), lambda i, j: (i, 0, 0)),
            pl.BlockSpec((G, Sk, Dh), lambda i, j: (i, 0, 0)),
            pl.BlockSpec((G, blk_q, Dh), lambda i, j: (i, j, 0)),
            pl.BlockSpec((G, blk_q), lambda i, j: (i, j)),
            pl.BlockSpec((G, blk_q), lambda i, j: (i, j)),
        ],
        out_specs=(
            pl.BlockSpec((G, blk_q, Dh), lambda i, j: (i, j, 0)),
            pl.BlockSpec((G, Sk, Dh), lambda i, j: (i, 0, 0)),
            pl.BlockSpec((G, Sk, Dh), lambda i, j: (i, 0, 0))),
        compiler_params=CompilerParams(
            dimension_semantics=("parallel", "arbitrary")),
        interpret=interpret_mode(),
    )(offs, q.reshape(BH, Sq, Dh), k.reshape(BH, Sk, Dh),
      v.reshape(BH, Sk, Dh), do.reshape(BH, Sq, Dh),
      lse.reshape(BH, Sq), delta.reshape(BH, Sq))
    return (dq.reshape(B, H, Sq, Dh), dk.reshape(B, H, Sk, Dh),
            dv.reshape(B, H, Sk, Dh))
