"""Flash attention: fused scaled-dot-product attention pallas kernels.

The analog of the reference's fused attention path — the 2019 reference
composes attention op-by-op (matmul/softmax/matmul through separate
kernels, e.g. the benchmark transformer), which round-trips the
[B,H,Sq,Sk] score matrix through HBM twice in the forward and again in
the backward. On TPU this kernel family never materializes the score
matrix in HBM in either direction:

- **Forward**: k-blocked online softmax. Running max ``m``, normalizer
  ``l`` and the output accumulator live in VMEM scratch; the softmax
  statistics ``lse = m + log(l)`` are saved for the backward.
- **Backward**: two pallas kernels with per-block recompute —
  ``dq`` (scanning k-blocks) and ``dk/dv`` (scanning q-blocks). Each
  block recomputes ``p = exp(s - lse)`` from q/k and the saved
  statistics; only O(seq * head_dim) residuals (out, lse) ever hit HBM.
- **Dropout** runs in-kernel with the TPU PRNG
  (``pltpu.prng_seed``/``prng_random_bits``), seeded per
  (grid cell, q-block, k-block) so the backward regenerates the exact
  forward mask without storing it.
- **Causal** masking skips fully-masked k-blocks (roughly halves the
  decoder self-attention work).
- **Short-sequence batching**: each grid cell processes ``G``
  (batch, head) rows at once (batched dot_generals over the leading
  dim). At flagship shape (B=64 H=8 S=256) the naive per-row grid is
  512 cells of ~0.3us of MXU work each — pure per-cell overhead; G=8
  cuts the grid to 64 cells with 8x the work and 8x larger DMA
  transfers. G divides H, so a cell never straddles a batch row and
  per-BATCH bias blocks stay well-defined.
- **Single-k-block specialization** (``_1k_applicable``: Sq<=256,
  Sk<=512, natural tiling): when the whole key range fits one block,
  the online-softmax machinery is dropped (plain softmax in
  registers, no m/l scratch, no lane-replicated statistics), and the
  backward is ONE kernel producing dq/dk/dv from a single exp
  recompute with lse and delta derived in-kernel — the only HBM
  residual is the forward output. Chip-measured 2026-07-31: IN-MODEL
  this mix wins +12% on transformer-base b64 (13.08 vs 11.69
  steps/s, MFU 0.374 -> 0.419) — XLA's fused chain pays RNG mask
  materialization + probs HBM round-trips at all 18 attention sites.
  The f32 no-dropout micro-benchmark has the kernel 0.94x of XLA:
  micro-benchmarks do not transfer, in either direction; only
  in-model numbers decide (BASELINE.md round-4).

``Bias`` is an additive attention mask (0 / -1e9, built from data by the
models) and is registered non-differentiable: the base lowering and the
pallas kernel therefore agree that no dbias flows. A *trainable*
attention bias should be added with a separate elementwise_add before a
bias-free sdpa call.

Reference precedent for the fused-kernel + refer-impl pairing:
/root/reference/paddle/fluid/operators/jit/README.en.md (best-impl-wins
kernel dispatch), operators/fused/.
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ..registry import register, register_variant
from .common import CompilerParams, blk, interpret_mode

_NEG_INF = -1e30

# batched dot_general dimension numbers over leading G dim
_QK = (((2,), (2,)), ((0,), (0,)))     # [G,q,d] x [G,k,d] -> [G,q,k]
_PV = (((2,), (1,)), ((0,), (0,)))     # [G,q,k] x [G,k,d] -> [G,q,d]
_TT = (((1,), (1,)), ((0,), (0,)))     # [G,q,k] x [G,q,d] -> [G,k,d]


def _causal_mask(s, j, kk, blk_q, blk_k):
    rows = j * blk_q + lax.broadcasted_iota(jnp.int32, s.shape, 1)
    cols = kk * blk_k + lax.broadcasted_iota(jnp.int32, s.shape, 2)
    return jnp.where(rows >= cols, s, _NEG_INF)


def _dropout_keep(seed_ref, i, j, kk, n_q, n_k, shape, rate):
    """Deterministic per-block dropout mask; identical bits are
    regenerated in the backward kernels. The (cell, q-block, k-block)
    coordinates are folded into one scalar seed (single-arg prng_seed —
    the multi-arg form doesn't lower on this Mosaic version) with a
    Knuth-style odd multiplier so nearby blocks decorrelate."""
    flat = (i * n_q + j) * n_k + kk
    pltpu.prng_seed(seed_ref[0] + flat * jnp.int32(-1640531527))
    bits = pltpu.prng_random_bits(shape)
    u = lax.bitcast_convert_type(bits, jnp.uint32)
    thresh = jnp.uint32(min(int(rate * (1 << 32)), (1 << 32) - 1))
    return u >= thresh


@functools.lru_cache(maxsize=None)
def _softmax_save_lowp(dtype_name):
    """Softmax computed in f32 that SAVES ONLY the low-precision
    probabilities for its backward (flash-attention discipline).
    jax.nn.softmax's own vjp residual is the f32 output — at
    [B,H,S,S] x 18 attention sites that one choice added ~4 GB of
    HLO temps at batch 128 (observed in the round-4 OOM dump) and
    doubled the probs read/write traffic; the bf16-rounded residual
    changes the gradient by <=1 ulp of bf16, the same rounding every
    flash kernel accepts."""
    out_dtype = jnp.dtype(dtype_name)

    @jax.custom_vjp
    def f(s):
        return jax.nn.softmax(s, axis=-1).astype(out_dtype)

    def fwd(s):
        w = jax.nn.softmax(s, axis=-1).astype(out_dtype)
        return w, w

    def bwd(w, g):
        w32 = w.astype(jnp.float32)
        g32 = g.astype(jnp.float32)
        inner = jnp.sum(g32 * w32, axis=-1, keepdims=True)
        return ((g32 - inner) * w32,)

    f.defvjp(fwd, bwd)
    return f


def _sdpa_reference(q, k, v, bias, *, scale, dropout_rate=0.0,
                    causal=False, rng=None):
    """Pure-jnp composite (the jit/refer/ analog): q,k,v [B,H,S,Dh],
    bias additive, broadcastable to [B,1_or_H,Sq,Sk].

    Precision follows standard TPU practice (and the reference's f32
    softmax accumulate): scores and softmax in float32 — the MXU
    accumulates f32 for free and bf16 exp/sums over the key axis lose
    real mantissa — then the probabilities drop back to the input
    dtype (saving only the low-precision copy for the backward) for
    the dropout mask and the PV matmul, so the [B,H,S,S] traffic
    rides at half width under AMP."""
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k,
                   preferred_element_type=jnp.float32) * scale
    if bias is not None:
        s = s + lax.stop_gradient(bias).astype(jnp.float32)
    if causal:
        sq, sk = s.shape[-2], s.shape[-1]
        rows = lax.broadcasted_iota(jnp.int32, (sq, sk), 0)
        cols = lax.broadcasted_iota(jnp.int32, (sq, sk), 1)
        s = jnp.where(rows >= cols, s, _NEG_INF)
    w = _softmax_save_lowp(jnp.dtype(q.dtype).name)(s)
    if dropout_rate > 0.0:
        from ..nn_ops import _keep_mask
        keep = _keep_mask(rng, dropout_rate, w.shape)
        w = jnp.where(keep, w / (1.0 - dropout_rate),
                      jnp.zeros((), q.dtype))
    return jnp.einsum("bhqk,bhkd->bhqd", w, v,
                      preferred_element_type=jnp.float32).astype(
        q.dtype)


@register("scaled_dot_product_attention", ["Q", "K", "V", "Bias"],
          ["Out"], nondiff=("Bias",), needs_rng=True)
def scaled_dot_product_attention(q, k, v, bias, *, scale=1.0,
                                 dropout_rate=0.0, causal=False,
                                 is_test=False, rng=None):
    """Base lowering: XLA fuses the chain — except inside the flash
    kernel's chip-measured win envelope, where the base dispatches to
    it (FLAGS_sdpa_auto_flash, the jit/README.en.md best-impl-wins
    pool applied at run time). The envelope is exactly what the
    2026-07-31 in-model A/B measured winning (+12%): TPU execution,
    low-precision operands, dropout active, single-k-block shapes;
    everything else keeps the XLA chain, which measured faster there."""
    rate = 0.0 if is_test else float(dropout_rate)
    from ...core.flags import FLAGS
    if FLAGS.sp_attention and rate == 0.0:
        # model-parallel production path: under a mesh with an sp axis
        # (CompiledProgram.with_data_parallel(axes={"dp":d,"sp":s})
        # installs it as the ambient mesh for the whole trace) the one
        # attention op the models build lowers to the zigzag ring /
        # Ulysses schedule — activations stay sequence-sharded through
        # the S^2 core instead of replicating. Returns None when no sp
        # axis is in scope or the geometry doesn't admit a schedule,
        # in which case the replicated lowerings below stay in charge.
        from ...parallel.ulysses import sequence_parallel_attention
        routed = sequence_parallel_attention(q, k, v, bias=bias,
                                             scale=scale,
                                             causal=causal)
        if routed is not None:
            return routed
    if (FLAGS.sdpa_auto_flash and rate > 0.0 and rng is not None
            and not interpret_mode()
            and jnp.dtype(q.dtype).itemsize <= 2
            and _1k_applicable(q.shape[2], k.shape[2])):
        return sdpa_pallas(q, k, v, bias, scale=scale,
                           dropout_rate=dropout_rate, causal=causal,
                           is_test=is_test, rng=rng)
    return _sdpa_reference(q, k, v, bias, scale=scale,
                           dropout_rate=rate, causal=causal, rng=rng)


# ---------------------------------------------------------------------------
# single-k-block specialization (short sequences — the flagship S=256
# and BERT S=128 shapes). When the whole key range fits one block the
# online-softmax machinery is pure overhead: no m/l scratch, no alpha
# rescales, no lane-replicated statistics round-tripping through HBM.
# The backward is ONE kernel computing dq/dk/dv together from a single
# exp recompute (the blocked path needs two kernels = two recomputes),
# with lse and delta = rowsum(dO*O) derived in-kernel so the only HBM
# residual is the forward output itself.
# ---------------------------------------------------------------------------


def _attn_scores(q_ref, k_ref, b_ref, *, scale, causal):
    s = lax.dot_general(q_ref[...], k_ref[...], _QK,
                        preferred_element_type=jnp.float32) * scale
    if b_ref is not None:
        s = s + b_ref[:, 0].astype(jnp.float32)
    if causal:
        s = _causal_mask(s, 0, 0, s.shape[1], s.shape[2])
    return s                                        # [G, Sq, Sk] f32


def _fwd_kernel_1k(seed_ref, q_ref, k_ref, v_ref, b_ref, o_ref, *,
                   scale, rate, causal):
    i = pl.program_id(0)
    s = _attn_scores(q_ref, k_ref, b_ref, scale=scale, causal=causal)
    m = jnp.max(s, -1, keepdims=True)
    p = jnp.exp(s - m)
    l = jnp.sum(p, -1, keepdims=True)
    if rate > 0.0:
        keep = _dropout_keep(seed_ref, i, 0, 0, 1, 1, p.shape, rate)
        p = jnp.where(keep, p * (1.0 / (1.0 - rate)), 0.0)
    pv = lax.dot_general(p.astype(v_ref.dtype), v_ref[...], _PV,
                         preferred_element_type=jnp.float32)
    # reciprocal-multiply: a [G,Sq,1]-broadcast divide on the [G,Sq,Dh]
    # tile costs ~4x a multiply on the VPU
    rl = 1.0 / jnp.where(l == 0.0, 1.0, l)
    o_ref[...] = (pv * rl).astype(o_ref.dtype)


def _bwd_kernel_1k(seed_ref, q_ref, k_ref, v_ref, b_ref, do_ref, o_ref,
                   dq_ref, dk_ref, dv_ref, *, scale, rate, causal):
    i = pl.program_id(0)
    s = _attn_scores(q_ref, k_ref, b_ref, scale=scale, causal=causal)
    m = jnp.max(s, -1, keepdims=True)
    e = jnp.exp(s - m)
    l = jnp.sum(e, -1, keepdims=True)
    rl = 1.0 / jnp.where(l == 0.0, 1.0, l)          # [G, Sq, 1]
    p = e * rl                                      # [G, Sq, Sk] f32
    do = do_ref[...]                                # [G, Sq, Dh]
    delta = jnp.sum(do.astype(jnp.float32)
                    * o_ref[...].astype(jnp.float32), -1,
                    keepdims=True)                  # [G, Sq, 1]
    dp = lax.dot_general(do, v_ref[...], _QK,
                         preferred_element_type=jnp.float32)
    if rate > 0.0:
        keep = _dropout_keep(seed_ref, i, 0, 0, 1, 1, p.shape, rate)
        inv = 1.0 / (1.0 - rate)
        pd = jnp.where(keep, p * inv, 0.0)
        dp = jnp.where(keep, dp * inv, 0.0)
    else:
        pd = p
    dv_ref[...] = lax.dot_general(
        pd.astype(do.dtype), do, _TT,
        preferred_element_type=jnp.float32).astype(dv_ref.dtype)
    ds = (p * (dp - delta) * scale).astype(q_ref.dtype)
    dq_ref[...] = lax.dot_general(
        ds, k_ref[...], _PV,
        preferred_element_type=jnp.float32).astype(dq_ref.dtype)
    dk_ref[...] = lax.dot_general(
        ds, q_ref[...], _TT,
        preferred_element_type=jnp.float32).astype(dk_ref.dtype)


def _1k_applicable(Sq, Sk):
    # whole key range in one block, natural TPU tiling (no padding)
    return (Sq <= 256 and Sk <= 512
            and Sq % 8 == 0 and Sk % 128 == 0)


# VMEM model for the single-k-block kernels (ADVICE r4: the corner
# Sq=256/Sk=512 exceeded scoped VMEM at the uncapped G=8). Per grid
# row the kernels hold:
#   - streamed blocks, double-buffered: q/do/o/dq rows of Sq, and
#     k/v/dk/dv rows of Sk, each lane-padded to 128 in the minor dim;
#   - [G,Sq,Sk] f32 score temporaries. 8 bytes/element — ~2 f32
#     arrays live after Mosaic's buffer reuse. This constant is
#     ANCHORED on chip evidence, not source-level counting: the
#     bf16 [8,256,256] backward (5 source-level f32 temps = 20 B/elem
#     would predict 22 MB) compiled and ran at G=8 in the round-4
#     headline capture, so Mosaic demonstrably reuses all but ~2.
# Budget 15 MB of the 16 MB v5e scoped limit; G halves until the
# modeled row total fits. tests/test_pallas_vmem.py replays this
# model at every _1k_applicable corner AND pins the chip-measured
# headline geometry (bf16 256x256 dropout) to G=8.
_1K_TEMP_BYTES = 8
_1K_VMEM_BUDGET = 15 << 20

# Blocked-path tile targets, env-tunable for on-chip sweeps
# (tools/blocked_sweep.py): PALLAS_BLK_Q / PALLAS_BLK_K. The committed
# defaults are the round-4 choices; any change must be chip-measured
# in-model at S>=1024 first (the blocked path never dispatches at the
# S=256 flagship — _1k_applicable owns that envelope).
_BLK_Q_TARGET = int(os.environ.get("PALLAS_BLK_Q", "256"))
_BLK_K_TARGET = int(os.environ.get("PALLAS_BLK_K", "512"))


def _1k_row_bytes(itemsize, Sq, Sk, Dh, n_sq_ops, n_sk_ops, has_bias):
    lanes = max(Dh, 128)
    stream = (n_sq_ops * Sq + n_sk_ops * Sk) * lanes * itemsize * 2
    temps = Sq * Sk * _1K_TEMP_BYTES
    if has_bias:
        # bias block (streamed, double-buffered; charged per-row even
        # for the shared non-per-head slab — conservative) plus the
        # s + b f32 addend the biased kernel keeps live
        temps += Sq * Sk * (itemsize * 2 + 4)
    return stream + temps


def _1k_bwd_G(H, itemsize, Sq, Sk, Dh, has_bias=False):
    """Backward rows per grid cell, capped by the VMEM model
    (streams: q,do,o,dq + k,v,dk,dv)."""
    base = 8 if itemsize <= 2 else 4
    row = _1k_row_bytes(itemsize, Sq, Sk, Dh, 4, 4, has_bias)
    while base > 1 and base * row > _1K_VMEM_BUDGET:
        base //= 2
    return blk(H, base)


def _1k_fwd_G(H, itemsize, rate, Sq, Sk, Dh, has_bias=False):
    """Forward rows per grid cell. With dropout it MUST equal the
    backward's G (the per-cell PRNG seed mapping — see _pick_G's
    invariant note); without dropout the forward only needs its own
    streams (q,o + k,v) to fit."""
    if rate > 0.0:
        return _1k_bwd_G(H, itemsize, Sq, Sk, Dh, has_bias)
    base = 8
    row = _1k_row_bytes(itemsize, Sq, Sk, Dh, 2, 2, has_bias)
    while base > 1 and base * row > _1K_VMEM_BUDGET:
        base //= 2
    return blk(H, base)


def _bwd_G(H, itemsize):
    """Backward rows per grid cell: the backward streams six operands
    + three outputs + the f32 score/prob temporaries, so f32 needs
    G=4 to fit the 16 MB scoped VMEM (tests/test_pallas_vmem.py).
    The ONE definition both backward wrappers and _pick_G use — the
    fwd/bwd dropout-seed consistency invariant hangs off it."""
    return blk(H, 8 if itemsize <= 2 else 4)


def _pick_G(H, itemsize, rate):
    """Rows per grid cell — ONE choice shared by forward and backward.

    The in-kernel dropout mask is seeded per grid CELL
    (_dropout_keep), so the (batch, head) -> cell mapping MUST be
    identical in the kernels that generate and regenerate it: a
    fwd G=8 / bwd G=4 split at f32 silently regenerates different
    masks for every head the two groupings assign to different
    cells (caught by round-4 review: f32 H=8 dropout grads diverged
    from finite differences on heads >= 4). Without dropout the
    forward may keep G=8 at f32 (it streams fewer operands than the
    backward, which needs G=4 to fit the 16 MB scoped VMEM —
    tests/test_pallas_vmem.py), because no PRNG state crosses the
    kernels."""
    if rate == 0.0:
        return blk(H, 8)
    return _bwd_G(H, itemsize)


def _1k_specs_args(q, k, v, bias, per_head, seed, G, hb):
    """Shared in_specs/args plumbing for the single-k-block kernels."""
    B, H, Sq, Dh = q.shape
    Sk = k.shape[2]
    BH = B * H
    in_specs = [
        pl.BlockSpec(memory_space=pltpu.SMEM),
        pl.BlockSpec((G, Sq, Dh), lambda i: (i, 0, 0)),
        pl.BlockSpec((G, Sk, Dh), lambda i: (i, 0, 0)),
        pl.BlockSpec((G, Sk, Dh), lambda i: (i, 0, 0)),
    ]
    args = [seed, q.reshape(BH, Sq, Dh), k.reshape(BH, Sk, Dh),
            v.reshape(BH, Sk, Dh)]
    if bias is not None:
        if per_head:
            in_specs.append(pl.BlockSpec((G, 1, Sq, Sk),
                                         lambda i: (i, 0, 0, 0)))
        else:
            in_specs.append(pl.BlockSpec((1, 1, Sq, Sk),
                                         lambda i: (i // hb, 0, 0, 0)))
        args.append(bias)
    return in_specs, args


def _flash_fwd_1k(q, k, v, bias, seed_f, scale, rate, causal):
    B, H, Sq, Dh = q.shape
    Sk = k.shape[2]
    BH = B * H
    bias, per_head = _prep_bias(bias, B, H, Sq, Sk)
    G = _1k_fwd_G(H, q.dtype.itemsize, rate, Sq, Sk, Dh,
                  bias is not None)
    hb = H // G
    seed = jnp.asarray([seed_f.astype(jnp.int32)], jnp.int32)

    in_specs, args = _1k_specs_args(q, k, v, bias, per_head, seed, G,
                                    hb)
    if bias is not None:
        kernel = _fwd_kernel_1k
    else:
        kernel = (lambda sr, qr, kr, vr, orf, **kw:
                  _fwd_kernel_1k(sr, qr, kr, vr, None, orf, **kw))

    out = pl.pallas_call(
        functools.partial(kernel, scale=scale, rate=rate,
                          causal=causal),
        out_shape=jax.ShapeDtypeStruct((BH, Sq, Dh), q.dtype),
        grid=(BH // G,),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((G, Sq, Dh), lambda i: (i, 0, 0)),
        compiler_params=CompilerParams(
            dimension_semantics=("parallel",)),
        interpret=interpret_mode(),
    )(*args)
    return out.reshape(B, H, Sq, Dh)


def _flash_bwd_1k(q, k, v, bias, seed_f, o, g, scale, rate, causal):
    B, H, Sq, Dh = q.shape
    Sk = k.shape[2]
    BH = B * H
    bias, per_head = _prep_bias(bias, B, H, Sq, Sk)
    G = _1k_bwd_G(H, q.dtype.itemsize, Sq, Sk, Dh, bias is not None)
    hb = H // G
    seed = jnp.asarray([seed_f.astype(jnp.int32)], jnp.int32)

    in_specs, args = _1k_specs_args(q, k, v, bias, per_head, seed, G,
                                    hb)
    if bias is not None:
        kernel = _bwd_kernel_1k
    else:
        kernel = (lambda sr, qr, kr, vr, dor, orf, *outs, **kw:
                  _bwd_kernel_1k(sr, qr, kr, vr, None, dor, orf,
                                 *outs, **kw))
    in_specs += [pl.BlockSpec((G, Sq, Dh), lambda i: (i, 0, 0)),
                 pl.BlockSpec((G, Sq, Dh), lambda i: (i, 0, 0))]
    args += [g.reshape(BH, Sq, Dh), o.reshape(BH, Sq, Dh)]

    dq, dk, dv = pl.pallas_call(
        functools.partial(kernel, scale=scale, rate=rate,
                          causal=causal),
        out_shape=[jax.ShapeDtypeStruct((BH, Sq, Dh), q.dtype),
                   jax.ShapeDtypeStruct((BH, Sk, Dh), k.dtype),
                   jax.ShapeDtypeStruct((BH, Sk, Dh), v.dtype)],
        grid=(BH // G,),
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec((G, Sq, Dh), lambda i: (i, 0, 0)),
            pl.BlockSpec((G, Sk, Dh), lambda i: (i, 0, 0)),
            pl.BlockSpec((G, Sk, Dh), lambda i: (i, 0, 0)),
        ],
        compiler_params=CompilerParams(
            dimension_semantics=("parallel",)),
        interpret=interpret_mode(),
    )(*args)
    return (dq.reshape(B, H, Sq, Dh), dk.reshape(B, H, Sk, Dh),
            dv.reshape(B, H, Sk, Dh))


# ---------------------------------------------------------------------------
# forward kernel
# ---------------------------------------------------------------------------

def _fwd_kernel(seed_ref, q_ref, k_ref, v_ref, b_ref, o_ref, lse_ref,
                acc_ref, m_ref, l_ref, *, scale, blk_q, blk_k, n_q,
                n_k, rate, causal):
    i = pl.program_id(0)
    j = pl.program_id(1)
    kk = pl.program_id(2)

    @pl.when(kk == 0)
    def _init():
        m_ref[...] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)
        acc_ref[...] = jnp.zeros_like(acc_ref)

    live = (kk * blk_k <= j * blk_q + blk_q - 1) if causal else True

    @pl.when(live)
    def _step():
        q = q_ref[...]                                  # [G, bq, Dh]
        s = lax.dot_general(q, k_ref[...], _QK,
                            preferred_element_type=jnp.float32) * scale
        if b_ref is not None:
            # per-head: [G,1,bq,bk] -> [G,bq,bk]; per-batch:
            # [1,1,bq,bk] broadcasts over G
            s = s + b_ref[:, 0].astype(jnp.float32)
        if causal:
            s = _causal_mask(s, j, kk, blk_q, blk_k)
        m_prev = m_ref[..., :1]                         # [G, bq, 1]
        l_prev = l_ref[..., :1]
        m_new = jnp.maximum(m_prev, jnp.max(s, -1, keepdims=True))
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new)
        l_new = alpha * l_prev + jnp.sum(p, -1, keepdims=True)
        m_ref[...] = jnp.broadcast_to(m_new, m_ref.shape)
        l_ref[...] = jnp.broadcast_to(l_new, l_ref.shape)
        if rate > 0.0:
            keep = _dropout_keep(seed_ref, i, j, kk, n_q, n_k,
                                 p.shape, rate)
            p = jnp.where(keep, p / (1.0 - rate), 0.0)
        pv = lax.dot_general(p.astype(v_ref.dtype), v_ref[...], _PV,
                             preferred_element_type=jnp.float32)
        acc_ref[...] = acc_ref[...] * alpha + pv

    @pl.when(kk == n_k - 1)
    def _finish():
        l_safe = jnp.where(l_ref[...] == 0.0, 1.0, l_ref[...])
        o_ref[...] = (acc_ref[...] / l_safe[..., :1]).astype(
            o_ref.dtype)
        # lane-replicated [G, blk_q, 128] (the TPU min-tile layout);
        # the wrapper slices lane 0 out for the residual
        lse_ref[...] = m_ref[...] + jnp.log(l_safe)


def _prep_bias(bias, B, H, Sq, Sk):
    """Normalize an additive mask for the kernels. Returns
    (bias array, per_head): per-BATCH biases stay [B, 1, Sq, Sk] and a
    grid cell of G rows indexes batch (i*G)//H; a per-HEAD bias
    [B, H, Sq, Sk] reshapes to [B*H, 1, Sq, Sk] and blocks G rows
    directly — both paths are G-consistent because G divides H."""
    if bias is None:
        return None, False
    if bias.ndim == 4 and bias.shape[1] == H and H > 1:
        return (jnp.broadcast_to(bias, (B, H, Sq, Sk))
                .reshape(B * H, 1, Sq, Sk)), True
    return jnp.broadcast_to(bias, (B, 1, Sq, Sk)), False


def _flash_fwd(q, k, v, bias, seed_f, scale, rate, causal):
    B, H, Sq, Dh = q.shape
    Sk = k.shape[2]
    BH = B * H
    bias, per_head = _prep_bias(bias, B, H, Sq, Sk)
    # must match _flash_bwd's grouping when dropout is on (same
    # per-cell PRNG seeding — see _pick_G)
    G = _pick_G(H, q.dtype.itemsize, rate)
    hb = H // G                    # cells per batch row
    q3 = q.reshape(BH, Sq, Dh)
    k3 = k.reshape(BH, Sk, Dh)
    v3 = v.reshape(BH, Sk, Dh)
    blk_q = blk(Sq, _BLK_Q_TARGET)
    blk_k = blk(Sk, _BLK_K_TARGET)
    n_k = Sk // blk_k
    grid = (BH // G, Sq // blk_q, n_k)
    seed = jnp.asarray([seed_f.astype(jnp.int32)], jnp.int32)

    in_specs = [
        pl.BlockSpec(memory_space=pltpu.SMEM),
        pl.BlockSpec((G, blk_q, Dh), lambda i, j, kk: (i, j, 0)),
        pl.BlockSpec((G, blk_k, Dh), lambda i, j, kk: (i, kk, 0)),
        pl.BlockSpec((G, blk_k, Dh), lambda i, j, kk: (i, kk, 0)),
    ]
    args = [seed, q3, k3, v3]
    if bias is not None:
        if per_head:
            bspec = pl.BlockSpec((G, 1, blk_q, blk_k),
                                 lambda i, j, kk: (i, 0, j, kk))
        else:
            bspec = pl.BlockSpec((1, 1, blk_q, blk_k),
                                 lambda i, j, kk: (i // hb, 0, j, kk))
        in_specs.append(bspec)
        args.append(bias)
        kernel = _fwd_kernel
    else:
        kernel = (lambda sr, qr, kr, vr, orf, lr, ar, mr, llr, **kw:
                  _fwd_kernel(sr, qr, kr, vr, None, orf, lr, ar, mr,
                              llr, **kw))

    out, lse = pl.pallas_call(
        functools.partial(kernel, scale=scale, blk_q=blk_q,
                          blk_k=blk_k, n_q=Sq // blk_q, n_k=n_k,
                          rate=rate, causal=causal),
        out_shape=[jax.ShapeDtypeStruct((BH, Sq, Dh), q.dtype),
                   jax.ShapeDtypeStruct((BH, Sq, 128), jnp.float32)],
        grid=grid,
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec((G, blk_q, Dh), lambda i, j, kk: (i, j, 0)),
            pl.BlockSpec((G, blk_q, 128), lambda i, j, kk: (i, j, 0)),
        ],
        scratch_shapes=[
            pltpu.VMEM((G, blk_q, Dh), jnp.float32),
            pltpu.VMEM((G, blk_q, 128), jnp.float32),
            pltpu.VMEM((G, blk_q, 128), jnp.float32),
        ],
        compiler_params=CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret_mode(),
    )(*args)
    return out.reshape(B, H, Sq, Dh), lse[:, :, 0]


# ---------------------------------------------------------------------------
# backward kernels
# ---------------------------------------------------------------------------

def _recompute_p(q_ref, k_ref, b_ref, lse_ref, *, scale, j, kk, blk_q,
                 blk_k, causal):
    s = lax.dot_general(q_ref[...], k_ref[...], _QK,
                        preferred_element_type=jnp.float32) * scale
    if b_ref is not None:
        s = s + b_ref[:, 0].astype(jnp.float32)
    if causal:
        s = _causal_mask(s, j, kk, blk_q, blk_k)
    return jnp.exp(s - lse_ref[..., :1])          # [G, blk_q, blk_k]


def _dq_kernel(seed_ref, q_ref, k_ref, v_ref, b_ref, do_ref, lse_ref,
               dl_ref, dq_ref, dq_acc, *, scale, blk_q, blk_k, n_q,
               n_k, rate, causal):
    i = pl.program_id(0)
    j = pl.program_id(1)
    kk = pl.program_id(2)

    @pl.when(kk == 0)
    def _init():
        dq_acc[...] = jnp.zeros_like(dq_acc)

    live = (kk * blk_k <= j * blk_q + blk_q - 1) if causal else True

    @pl.when(live)
    def _step():
        p = _recompute_p(q_ref, k_ref, b_ref, lse_ref, scale=scale,
                         j=j, kk=kk, blk_q=blk_q, blk_k=blk_k,
                         causal=causal)
        do = do_ref[...]                              # [G, bq, Dh]
        dp = lax.dot_general(do, v_ref[...], _QK,
                             preferred_element_type=jnp.float32)
        if rate > 0.0:
            keep = _dropout_keep(seed_ref, i, j, kk, n_q, n_k,
                                 dp.shape, rate)
            dp = jnp.where(keep, dp / (1.0 - rate), 0.0)
        delta = dl_ref[..., :1]                       # [G, bq, 1]
        ds = (p * (dp - delta) * scale).astype(k_ref.dtype)
        dq_acc[...] += lax.dot_general(
            ds, k_ref[...], _PV,
            preferred_element_type=jnp.float32)

    @pl.when(kk == n_k - 1)
    def _finish():
        dq_ref[...] = dq_acc[...].astype(dq_ref.dtype)


def _dkv_kernel(seed_ref, q_ref, k_ref, v_ref, b_ref, do_ref, lse_ref,
                dl_ref, dk_ref, dv_ref, dk_acc, dv_acc, *, scale,
                blk_q, blk_k, n_q, n_k, rate, causal):
    i = pl.program_id(0)
    kk = pl.program_id(1)
    j = pl.program_id(2)

    @pl.when(j == 0)
    def _init():
        dk_acc[...] = jnp.zeros_like(dk_acc)
        dv_acc[...] = jnp.zeros_like(dv_acc)

    live = (kk * blk_k <= j * blk_q + blk_q - 1) if causal else True

    @pl.when(live)
    def _step():
        p = _recompute_p(q_ref, k_ref, b_ref, lse_ref, scale=scale,
                         j=j, kk=kk, blk_q=blk_q, blk_k=blk_k,
                         causal=causal)
        do = do_ref[...]
        if rate > 0.0:
            keep = _dropout_keep(seed_ref, i, j, kk, n_q, n_k,
                                 p.shape, rate)
            pd = jnp.where(keep, p / (1.0 - rate), 0.0)
        else:
            pd = p
        # dv += Pd^T @ dO (per row)
        dv_acc[...] += lax.dot_general(
            pd.astype(do.dtype), do, _TT,
            preferred_element_type=jnp.float32)
        dp = lax.dot_general(do, v_ref[...], _QK,
                             preferred_element_type=jnp.float32)
        if rate > 0.0:
            dp = jnp.where(keep, dp / (1.0 - rate), 0.0)
        delta = dl_ref[..., :1]
        ds = (p * (dp - delta) * scale).astype(q_ref.dtype)
        # dk += dS^T @ Q (per row)
        dk_acc[...] += lax.dot_general(
            ds, q_ref[...], _TT,
            preferred_element_type=jnp.float32)

    @pl.when(j == n_q - 1)
    def _finish():
        dk_ref[...] = dk_acc[...].astype(dk_ref.dtype)
        dv_ref[...] = dv_acc[...].astype(dv_ref.dtype)


def _flash_bwd(q, k, v, bias, seed_f, o, lse, g, scale, rate, causal):
    B, H, Sq, Dh = q.shape
    Sk = k.shape[2]
    BH = B * H
    bias, per_head = _prep_bias(bias, B, H, Sq, Sk)
    # the bwd streams 6 (G, blk, Dh) operands + 2 outputs + 2 scratch;
    # with Dh<=64 lane-padded to 128, G=8 at f32 models ~18 MB and
    # trips the v5e 16 MB scoped-VMEM limit — halve the (batch,head)
    # rows per grid cell for 4-byte dtypes (shared _bwd_G definition)
    G = _bwd_G(H, q.dtype.itemsize)
    hb = H // G
    q3 = q.reshape(BH, Sq, Dh)
    k3 = k.reshape(BH, Sk, Dh)
    v3 = v.reshape(BH, Sk, Dh)
    do3 = g.reshape(BH, Sq, Dh)
    blk_q = blk(Sq, _BLK_Q_TARGET)
    blk_k = blk(Sk, _BLK_K_TARGET)
    n_q, n_k = Sq // blk_q, Sk // blk_k
    seed = jnp.asarray([seed_f.astype(jnp.int32)], jnp.int32)
    # delta_i = rowsum(dO * O): O(S*Dh) elementwise work, XLA fuses it.
    # lse/delta enter the kernels lane-replicated to the 128-lane
    # min-tile (the layout the fwd kernel produced them in).
    delta = jnp.sum(do3.astype(jnp.float32) * o.reshape(BH, Sq, Dh)
                    .astype(jnp.float32), axis=-1)
    lse128 = jnp.broadcast_to(lse[:, :, None], (BH, Sq, 128))
    delta128 = jnp.broadcast_to(delta[:, :, None], (BH, Sq, 128))

    def specs(order):
        """order: 'dq' grid (BH/G, n_q, n_k) or 'dkv' (BH/G, n_k, n_q)."""
        if order == "dq":
            qi = lambda i, j, kk: (i, j, 0)
            ki = lambda i, j, kk: (i, kk, 0)
            if per_head:
                bi = lambda i, j, kk: (i, 0, j, kk)
            else:
                bi = lambda i, j, kk: (i // hb, 0, j, kk)
        else:
            qi = lambda i, kk, j: (i, j, 0)
            ki = lambda i, kk, j: (i, kk, 0)
            if per_head:
                bi = lambda i, kk, j: (i, 0, j, kk)
            else:
                bi = lambda i, kk, j: (i // hb, 0, j, kk)
        sp = [pl.BlockSpec(memory_space=pltpu.SMEM),
              pl.BlockSpec((G, blk_q, Dh), qi),
              pl.BlockSpec((G, blk_k, Dh), ki),
              pl.BlockSpec((G, blk_k, Dh), ki)]
        ar = [seed, q3, k3, v3]
        if bias is not None:
            gb = G if per_head else 1
            sp.append(pl.BlockSpec((gb, 1, blk_q, blk_k), bi))
            ar.append(bias)
        sp += [pl.BlockSpec((G, blk_q, Dh), qi),
               pl.BlockSpec((G, blk_q, 128), qi),
               pl.BlockSpec((G, blk_q, 128), qi)]
        ar += [do3, lse128, delta128]
        return sp, ar

    def with_bias(kern):
        if bias is not None:
            return kern
        return functools.partial(
            lambda f, sr, qr, kr, vr, *rest, **kw:
            f(sr, qr, kr, vr, None, *rest, **kw), kern)

    sp, ar = specs("dq")
    dq = pl.pallas_call(
        functools.partial(with_bias(_dq_kernel), scale=scale,
                          blk_q=blk_q, blk_k=blk_k, n_q=n_q, n_k=n_k,
                          rate=rate, causal=causal),
        out_shape=jax.ShapeDtypeStruct((BH, Sq, Dh), q.dtype),
        grid=(BH // G, n_q, n_k),
        in_specs=sp,
        out_specs=pl.BlockSpec((G, blk_q, Dh),
                               lambda i, j, kk: (i, j, 0)),
        scratch_shapes=[pltpu.VMEM((G, blk_q, Dh), jnp.float32)],
        compiler_params=CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret_mode(),
    )(*ar)

    sp, ar = specs("dkv")
    dk, dv = pl.pallas_call(
        functools.partial(with_bias(_dkv_kernel), scale=scale,
                          blk_q=blk_q, blk_k=blk_k, n_q=n_q, n_k=n_k,
                          rate=rate, causal=causal),
        out_shape=[jax.ShapeDtypeStruct((BH, Sk, Dh), k.dtype),
                   jax.ShapeDtypeStruct((BH, Sk, Dh), v.dtype)],
        grid=(BH // G, n_k, n_q),
        in_specs=sp,
        out_specs=[
            pl.BlockSpec((G, blk_k, Dh), lambda i, kk, j: (i, kk, 0)),
            pl.BlockSpec((G, blk_k, Dh), lambda i, kk, j: (i, kk, 0)),
        ],
        scratch_shapes=[pltpu.VMEM((G, blk_k, Dh), jnp.float32),
                        pltpu.VMEM((G, blk_k, Dh), jnp.float32)],
        compiler_params=CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret_mode(),
    )(*ar)

    dq = dq.reshape(B, H, Sq, Dh)
    dk = dk.reshape(B, H, Sk, Dh)
    dv = dv.reshape(B, H, Sk, Dh)
    return dq, dk, dv


@functools.partial(jax.custom_vjp, nondiff_argnums=(5, 6, 7))
def _sdpa_flash(q, k, v, bias, seed_f, scale, rate, causal):
    if _1k_applicable(q.shape[2], k.shape[2]):
        return _flash_fwd_1k(q, k, v, bias, seed_f, scale, rate,
                             causal)
    out, _lse = _flash_fwd(q, k, v, bias, seed_f, scale, rate, causal)
    return out


def _sdpa_flash_fwd(q, k, v, bias, seed_f, scale, rate, causal):
    if _1k_applicable(q.shape[2], k.shape[2]):
        out = _flash_fwd_1k(q, k, v, bias, seed_f, scale, rate,
                            causal)
        # the single-block backward re-derives lse in-kernel: the
        # forward output is the only tensor residual
        return out, (q, k, v, bias, seed_f, out, None)
    out, lse = _flash_fwd(q, k, v, bias, seed_f, scale, rate, causal)
    return out, (q, k, v, bias, seed_f, out, lse)


def _sdpa_flash_bwd(scale, rate, causal, res, g):
    q, k, v, bias, seed_f, out, lse = res
    if lse is None:
        dq, dk, dv = _flash_bwd_1k(q, k, v, bias, seed_f, out, g,
                                   scale, rate, causal)
    else:
        dq, dk, dv = _flash_bwd(q, k, v, bias, seed_f, out, lse, g,
                                scale, rate, causal)
    dbias = None if bias is None else jnp.zeros_like(bias)
    return dq, dk, dv, dbias, jnp.zeros_like(seed_f)


_sdpa_flash.defvjp(_sdpa_flash_fwd, _sdpa_flash_bwd)


@register_variant("scaled_dot_product_attention", "pallas")
def sdpa_pallas(q, k, v, bias, *, scale=1.0, dropout_rate=0.0,
                causal=False, is_test=False, rng=None):
    rate = 0.0 if is_test else float(dropout_rate)
    # per-head bias [B, H, Sq, Sk] is handled natively: _prep_bias
    # flattens it to one slab per (batch, head) grid row
    if rate > 0.0 and (rng is None or interpret_mode()):
        # the TPU PRNG has no interpreter emulation; CPU tests take the
        # reference path (dropout masks differ across libraries anyway)
        return _sdpa_reference(q, k, v, bias, scale=scale,
                               dropout_rate=rate, causal=causal, rng=rng)
    if rate > 0.0:
        # fold the step key into a scalar TPU PRNG seed; float32 carries
        # it through custom_vjp without an int-cotangent (float0) dance
        seed_f = jax.random.randint(rng, (), 0, 1 << 23).astype(
            jnp.float32)
    else:
        seed_f = jnp.float32(0)
    return _sdpa_flash(q, k, v, bias, seed_f, float(scale), rate,
                       bool(causal))
