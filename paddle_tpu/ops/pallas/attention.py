"""Fused scaled-dot-product attention (flash-style) pallas kernel.

The analog of the reference's fused attention ops (operators/fused/
fused_embedding_fc_lstm_op.cc era had no flash attention — attention in
the 2019 reference is composed op-by-op, e.g. benchmark transformer
models multiply/softmax/multiply through separate kernels). On TPU the
composed form round-trips the [B,H,Sq,Sk] score matrix through HBM
twice; this kernel keeps each q-block's scores in VMEM, fusing
QK^T -> +bias -> softmax -> @V into one MXU-resident pass.

Forward: pallas kernel (one grid cell per (batch*head, q-block)).
Backward: custom_vjp that recomputes through the pure-jnp composite —
the flash-attention recompute strategy: no score matrix is ever stored
for backward, trading FLOPs for HBM (SURVEY §7 "HBM bandwidth").
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ..registry import register, register_variant
from .common import blk, interpret_mode


def _sdpa_reference(q, k, v, bias, *, scale):
    """Pure-jnp composite (the jit/refer/ analog): q,k,v [B,H,S,Dh],
    bias [B,1,Sq,Sk] additive (or None)."""
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k) * scale
    if bias is not None:
        s = s + bias
    w = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", w, v)


@register("scaled_dot_product_attention", ["Q", "K", "V", "Bias"],
          ["Out"])
def scaled_dot_product_attention(q, k, v, bias, *, scale=1.0):
    """Base lowering: XLA fuses the chain; the pallas variant below is
    substituted when FLAGS_op_library=pallas."""
    return _sdpa_reference(q, k, v, bias, scale=scale)


def _mha_fwd_kernel(q_ref, k_ref, v_ref, b_ref, o_ref, *, scale):
    q = q_ref[0]                       # [blk_q, dh]
    kk = k_ref[0]                      # [sk, dh]
    s = jax.lax.dot_general(
        q, kk, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32) * scale  # [blk_q, sk]
    if b_ref is not None:
        s = s + b_ref[0, 0].astype(jnp.float32)
    m = jnp.max(s, axis=-1, keepdims=True)
    e = jnp.exp(s - m)
    w = e / jnp.sum(e, axis=-1, keepdims=True)
    o = jnp.dot(w.astype(v_ref.dtype), v_ref[0],
                preferred_element_type=jnp.float32)
    o_ref[0] = o.astype(o_ref.dtype)


def _sdpa_pallas_fwd(q, k, v, bias, scale):
    B, H, Sq, Dh = q.shape
    Sk = k.shape[2]
    BH = B * H
    if bias is not None and bias.shape != (B, 1, Sq, Sk):
        # encoder-style [B,1,1,Sk] (or other broadcastable) biases:
        # materialize the per-batch [Sq,Sk] block the BlockSpec expects
        bias = jnp.broadcast_to(bias, (B, 1, Sq, Sk))
    q3 = q.reshape(BH, Sq, Dh)
    k3 = k.reshape(BH, Sk, Dh)
    v3 = v.reshape(BH, Sk, Dh)
    blk_q = blk(Sq)
    grid = (BH, Sq // blk_q)

    in_specs = [
        pl.BlockSpec((1, blk_q, Dh), lambda i, j: (i, j, 0),
                     memory_space=pltpu.VMEM),
        pl.BlockSpec((1, Sk, Dh), lambda i, j: (i, 0, 0),
                     memory_space=pltpu.VMEM),
        pl.BlockSpec((1, Sk, Dh), lambda i, j: (i, 0, 0),
                     memory_space=pltpu.VMEM),
    ]
    args = [q3, k3, v3]
    if bias is not None:
        # bias [B, 1, Sq, Sk] shared across the H heads of a batch row
        in_specs.append(pl.BlockSpec(
            (1, 1, blk_q, Sk), lambda i, j: (i // H, 0, j, 0),
            memory_space=pltpu.VMEM))
        args.append(bias)
        kernel = functools.partial(_mha_fwd_kernel, scale=scale)
    else:
        kernel = functools.partial(
            lambda qr, kr, vr, orf, **kw: _mha_fwd_kernel(
                qr, kr, vr, None, orf, **kw), scale=scale)

    out = pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((BH, Sq, Dh), q.dtype),
        grid=grid,
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, blk_q, Dh), lambda i, j: (i, j, 0),
                               memory_space=pltpu.VMEM),
        interpret=interpret_mode(),
    )(*args)
    return out.reshape(B, H, Sq, Dh)


@functools.partial(jax.custom_vjp, nondiff_argnums=(4,))
def _sdpa_pallas(q, k, v, bias, scale):
    return _sdpa_pallas_fwd(q, k, v, bias, scale)


def _sdpa_vjp_fwd(q, k, v, bias, scale):
    return _sdpa_pallas_fwd(q, k, v, bias, scale), (q, k, v, bias)


def _sdpa_vjp_bwd(scale, res, g):
    q, k, v, bias = res
    if bias is None:
        _out, pull = jax.vjp(
            lambda q_, k_, v_: _sdpa_reference(q_, k_, v_, None,
                                               scale=scale), q, k, v)
        dq, dk, dv = pull(g)
        return dq, dk, dv, None
    _out, pull = jax.vjp(
        lambda q_, k_, v_, b_: _sdpa_reference(q_, k_, v_, b_,
                                               scale=scale),
        q, k, v, bias)
    return pull(g)


_sdpa_pallas.defvjp(_sdpa_vjp_fwd, _sdpa_vjp_bwd)


@register_variant("scaled_dot_product_attention", "pallas")
def sdpa_pallas(q, k, v, bias, *, scale=1.0):
    return _sdpa_pallas(q, k, v, bias, scale)
