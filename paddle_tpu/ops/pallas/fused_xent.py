"""Fused vocabulary-projection + softmax cross-entropy pallas kernel.

The analog of the reference's fused logits-loss chain
(operators/math/cross_entropy.cu + the operators/fused/ pattern): every
NMT/LM model ends in ``fc(d_model -> V) + label_smooth + softmax_xent``
whose [N, V] logits (N = batch*seq, V ~ 30k) are by far the largest
activation in the model — at transformer-base flagship shape the bf16
logits alone are ~1 GB/step of HBM writes that XLA then re-reads for
the log-softmax. This kernel streams vocabulary blocks through VMEM and
reduces them online (flash-attention-style running logsumexp), so the
logits never reach HBM at all. Only the per-row logsumexp ([N, 1]) is
saved for the backward, which recomputes the logits blockwise — XLA
fuses the softmax-minus-target epilogue into the recompute matmul, so
the backward materializes exactly one [N, V] bf16 array (the scaled
gradient) instead of logits + softmax + dlogits.

Grid layout: vocab-major ``(nvj, ni)`` so each W block ([D, bv]) loads
once total while X row blocks re-stream per vocab block — W is the
big operand (D*V), X the small one (N*D), so this order minimizes HBM
traffic.

Rows ride the LANE axis everywhere outside the matmul: TPU VMEM tiles
are (8, 128), so a ``[N, 1]`` f32 buffer is lane-padded 128x (8 MB at
N=16k — the scoped-VMEM OOM observed on chip in round 4). Running
statistics therefore live in ``(ni, bn)`` scratch indexed ``(1, bn)``
per row block, the logits block is computed TRANSPOSED ``[bv, bn]``
(``dot_general`` contracting D on both operands), and all row
reductions are axis-0 — lane-major stats with no relayouts.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ..registry import get, register_variant
from .common import CompilerParams, blk, interpret_mode


def _fwd_kernel(x_ref, w_ref, lab_ref, loss_ref, lse_ref,
                m_sc, z_sc, s_sc, p_sc, *, V, eps, nvj):
    j = pl.program_id(0)
    i = pl.program_id(1)
    row = (pl.ds(i, 1), slice(None))     # (1, bn) stats slice

    # transposed block [bv, bn]: contract D of w [D, bv] with D of
    # x [bn, D] so rows land on lanes and every reduction is axis-0
    logits = jax.lax.dot_general(
        w_ref[:], x_ref[:], (((0,), (1,)), ((), ())),
        preferred_element_type=jnp.float32)                # [bv, bn]
    bv = logits.shape[0]
    col = jax.lax.broadcasted_iota(jnp.int32, logits.shape, 0) + j * bv
    valid = col < V                      # mask the padded vocab tail

    @pl.when(j == 0)
    def _init():
        bn = logits.shape[1]
        m_sc[row] = jnp.full((1, bn), -jnp.inf, jnp.float32)
        z_sc[row] = jnp.zeros((1, bn), jnp.float32)
        s_sc[row] = jnp.zeros((1, bn), jnp.float32)
        p_sc[row] = jnp.zeros((1, bn), jnp.float32)

    m_old = m_sc[row]
    blk_max = jnp.max(jnp.where(valid, logits, -jnp.inf), axis=0,
                      keepdims=True)
    m_new = jnp.maximum(m_old, blk_max)
    e = jnp.where(valid, jnp.exp(logits - m_new), 0.0)
    z_sc[row] = z_sc[row] * jnp.exp(m_old - m_new) \
        + jnp.sum(e, axis=0, keepdims=True)
    m_sc[row] = m_new
    s_sc[row] = s_sc[row] + jnp.sum(jnp.where(valid, logits, 0.0),
                                    axis=0, keepdims=True)
    lab = lab_ref[:]                                       # [1, bn]
    p_sc[row] = p_sc[row] + jnp.sum(
        jnp.where(col == lab, logits, 0.0), axis=0, keepdims=True)

    @pl.when(j == nvj - 1)
    def _finish():
        lse = m_sc[row] + jnp.log(z_sc[row])
        lse_ref[:] = lse
        # loss = lse - (1-eps)*logit[y] - eps/V * sum(logits)
        loss_ref[:] = (lse - (1.0 - eps) * p_sc[row]
                       - (eps / V) * s_sc[row])


def _fwd_call(x2, w, lab2, eps):
    N, D = x2.shape
    V = w.shape[-1]
    bn = blk(N, 512)
    ni = N // bn
    # bv=1024: the 2048 block's f32 working set (double-buffered W
    # block + transposed logits + exp) hit 16.11M scoped VMEM on chip,
    # 112K over the 16M stack limit
    bv = min(1024, -(-V // 128) * 128)
    nvj = -(-V // bv)
    Vp = nvj * bv
    if Vp > V:
        w = jnp.pad(w, ((0, 0), (0, Vp - V)))
    lab_row = lab2.reshape(1, N)
    kernel = functools.partial(_fwd_kernel, V=V, eps=eps, nvj=nvj)
    # outputs are lane-major [1, N]: a (1, bn) block over an (ni, bn)
    # array is ILLEGAL on the TPU lowering (sublane block dim 1 is
    # neither 8-divisible nor the full dim); over (1, N) it is exact in
    # the sublane and 128-divisible in the lane
    loss, lse = pl.pallas_call(
        kernel,
        out_shape=(jax.ShapeDtypeStruct((1, N), jnp.float32),
                   jax.ShapeDtypeStruct((1, N), jnp.float32)),
        grid=(nvj, ni),
        in_specs=[pl.BlockSpec((bn, D), lambda j, i: (i, 0),
                               memory_space=pltpu.VMEM),
                  pl.BlockSpec((D, bv), lambda j, i: (0, j),
                               memory_space=pltpu.VMEM),
                  pl.BlockSpec((1, bn), lambda j, i: (0, i),
                               memory_space=pltpu.VMEM)],
        out_specs=(pl.BlockSpec((1, bn), lambda j, i: (0, i),
                                memory_space=pltpu.VMEM),
                   pl.BlockSpec((1, bn), lambda j, i: (0, i),
                                memory_space=pltpu.VMEM)),
        scratch_shapes=[pltpu.VMEM((ni, bn), jnp.float32)] * 4,
        compiler_params=CompilerParams(
            dimension_semantics=("arbitrary", "arbitrary")),
        cost_estimate=pl.CostEstimate(
            flops=2 * N * D * Vp, transcendentals=N * Vp,
            bytes_accessed=(N * D * nvj + D * Vp) * x2.dtype.itemsize),
        interpret=interpret_mode(),
    )(x2, w, lab_row)
    return loss.reshape(N, 1), lse.reshape(N, 1)  # [1, N] -> [N, 1]


@functools.lru_cache(maxsize=None)
def _fused(eps):
    @jax.custom_vjp
    def f(x2, w, lab2):
        return _fwd_call(x2, w, lab2, eps)[0]

    def fwd(x2, w, lab2):
        loss, lse = _fwd_call(x2, w, lab2, eps)
        return loss, (x2, w, lab2, lse)

    def bwd(res, g):
        # Recompute the logits blockwise-in-XLA: the exp/subtract
        # epilogue fuses into the matmul, so only the scaled gradient
        # G ([N, V], input dtype) is ever materialized.
        x2, w, lab2, lse = res
        V = w.shape[-1]
        logits = jnp.dot(x2, w, preferred_element_type=jnp.float32)
        y = jax.nn.one_hot(lab2[:, 0], V, dtype=jnp.float32)
        p = jnp.exp(logits - lse)
        G = ((p - eps / V - (1.0 - eps) * y)
             * g.astype(jnp.float32)).astype(x2.dtype)
        dx = jnp.dot(G, w.T)
        dw = jnp.dot(x2.T, G)
        return dx, dw, None

    f.defvjp(fwd, bwd)
    return f


@register_variant("fused_linear_xent", "pallas")
def fused_linear_xent_pallas(x, w, label, *, epsilon=0.0):
    N = 1
    for d in x.shape[:-1]:
        N *= d
    # four (ni, bn) f32 running-stat buffers (N packed along lanes,
    # 4 bytes/row each) must fit VMEM scratch
    if N * 16 > (2 << 20):
        return get("fused_linear_xent").fn(x, w, label,
                                           epsilon=epsilon)
    x2 = x.reshape(N, x.shape[-1])
    lab2 = label.reshape(N, 1).astype(jnp.int32)
    loss = _fused(float(epsilon))(x2, w, lab2)
    return loss.reshape(x.shape[:-1] + (1,))
