"""Pallas TPU kernel library (see common.py for the design contract —
the operators/jit + operators/fused analog)."""

from . import common  # noqa: F401  (defines FLAGS_op_library)
from . import attention  # noqa: F401
from . import layer_norm  # noqa: F401
from . import softmax_xent  # noqa: F401
from . import fused_xent  # noqa: F401
from . import fused_adam  # noqa: F401
