"""Fused softmax + cross-entropy pallas kernel.

Reference: operators/softmax_with_cross_entropy_op.cu
(SoftmaxWithCrossEntropyFusedKernel) — the same fusion argument holds
on TPU: one VMEM pass produces both the softmax and the picked
log-likelihood, instead of XLA materializing softmax AND log_softmax
([N, C] each) in HBM between the decomposed stages."""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ..registry import get, register_variant
from .common import blk, interpret_mode


def _xent_kernel(lg_ref, lb_ref, sm_ref, loss_ref):
    lg = lg_ref[:].astype(jnp.float32)          # [blk_n, C]
    lab = lb_ref[:]                             # [blk_n, 1] int32
    m = jnp.max(lg, axis=1, keepdims=True)
    sh = lg - m
    e = jnp.exp(sh)
    z = jnp.sum(e, axis=1, keepdims=True)
    sm_ref[:] = (e / z).astype(sm_ref.dtype)
    logp = sh - jnp.log(z)                      # [blk_n, C]
    C = lg.shape[1]
    cols = jax.lax.broadcasted_iota(jnp.int32, logp.shape, 1)
    picked = jnp.sum(jnp.where(cols == lab, logp, 0.0), axis=1,
                     keepdims=True)
    loss_ref[:] = (-picked).astype(loss_ref.dtype)


def _xent_pallas_fwd(logits, label):
    orig_shape = logits.shape
    C = orig_shape[-1]
    N = 1
    for d in orig_shape[:-1]:
        N *= d
    lg2 = logits.reshape(N, C)
    lb2 = label.reshape(N, 1).astype(jnp.int32)
    # VMEM-aware row block: ~7 [blk_n, C] f32 buffers live at once
    # (double-buffered in/out blocks + exp/logp intermediates) under
    # the 16M scoped-VMEM stack limit
    target = max(1, min(256, (6 << 20) // (12 * C)))
    blk_n = blk(N, target)
    sm, loss = pl.pallas_call(
        functools.partial(_xent_kernel),
        out_shape=(jax.ShapeDtypeStruct((N, C), logits.dtype),
                   jax.ShapeDtypeStruct((N, 1), logits.dtype)),
        grid=(N // blk_n,),
        in_specs=[pl.BlockSpec((blk_n, C), lambda i: (i, 0),
                               memory_space=pltpu.VMEM),
                  pl.BlockSpec((blk_n, 1), lambda i: (i, 0),
                               memory_space=pltpu.VMEM)],
        out_specs=(pl.BlockSpec((blk_n, C), lambda i: (i, 0),
                                memory_space=pltpu.VMEM),
                   pl.BlockSpec((blk_n, 1), lambda i: (i, 0),
                                memory_space=pltpu.VMEM)),
        interpret=interpret_mode(),
    )(lg2, lb2)
    return (sm.reshape(orig_shape),
            loss.reshape(orig_shape[:-1] + (1,)))


@jax.custom_vjp
def _xent_pallas(logits, label):
    return _xent_pallas_fwd(logits, label)


def _xent_vjp_fwd(logits, label):
    sm, loss = _xent_pallas_fwd(logits, label)
    return (sm, loss), (sm, label)


def _xent_vjp_bwd(res, g):
    # d(loss)/d(logits) = softmax - onehot(label); the softmax output
    # cotangent is folded in exactly as the composite's vjp would
    sm, label = res
    g_sm, g_loss = g
    C = sm.shape[-1]
    lab = label.astype(jnp.int32)
    if lab.ndim == sm.ndim:
        lab = lab.squeeze(-1)
    onehot = jax.nn.one_hot(lab, C, dtype=sm.dtype)
    dlogits = (sm - onehot) * g_loss
    if g_sm is not None:
        # vjp of softmax at `sm`: sm * (g - sum(g*sm))
        inner = jnp.sum(g_sm * sm, axis=-1, keepdims=True)
        dlogits = dlogits + sm * (g_sm - inner)
    return dlogits, None


_xent_pallas.defvjp(_xent_vjp_fwd, _xent_vjp_bwd)


@register_variant("softmax_with_cross_entropy", "pallas")
def softmax_with_cross_entropy_pallas(logits, label, *, soft_label=False,
                                      ignore_index=-100, axis=-1,
                                      return_softmax=True,
                                      numeric_stable_mode=True):
    if soft_label or axis not in (-1, logits.ndim - 1) \
            or ignore_index >= 0:
        # uncommon modes (soft labels, inner axis, active
        # ignore_index) fall back to the reference lowering
        return get("softmax_with_cross_entropy").fn(
            logits, label, soft_label=soft_label,
            ignore_index=ignore_index, axis=axis,
            return_softmax=return_softmax,
            numeric_stable_mode=numeric_stable_mode)
    return _xent_pallas(logits, label)
