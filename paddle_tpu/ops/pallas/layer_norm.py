"""Fused layer_norm pallas kernel (reference: layer_norm_op.cu's fused
CUDA kernel; jit/gen had the x86 analog). One VMEM pass computes
mean/var/normalize/affine per row block — XLA's decomposed form emits
several HBM-bound elementwise stages on big rows."""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ..registry import get, register_variant
from .common import blk, interpret_mode


def _ln_kernel(x_ref, s_ref, b_ref, y_ref, m_ref, v_ref, *, eps):
    x = x_ref[:].astype(jnp.float32)           # [blk_r, D]
    mean = jnp.mean(x, axis=1, keepdims=True)
    xc = x - mean
    var = jnp.mean(xc * xc, axis=1, keepdims=True)
    inv = jax.lax.rsqrt(var + eps)
    y = xc * inv
    if s_ref is not None:
        y = y * s_ref[:].astype(jnp.float32)
    if b_ref is not None:
        y = y + b_ref[:].astype(jnp.float32)
    y_ref[:] = y.astype(y_ref.dtype)
    m_ref[:] = mean.astype(m_ref.dtype)
    v_ref[:] = var.astype(v_ref.dtype)


def _ln_pallas_fwd(x, scale, bias, eps, begin_norm_axis):
    rows = 1
    for d in x.shape[:begin_norm_axis]:
        rows *= d
    D = 1
    for d in x.shape[begin_norm_axis:]:
        D *= d
    x2 = x.reshape(rows, D)
    blk_r = blk(rows, 256)
    grid = (rows // blk_r,)

    specs = [pl.BlockSpec((blk_r, D), lambda i: (i, 0),
                          memory_space=pltpu.VMEM)]
    args = [x2]
    affine_spec = pl.BlockSpec((1, D), lambda i: (0, 0),
                               memory_space=pltpu.VMEM)
    if scale is not None:
        specs.append(affine_spec)
        args.append(scale.reshape(1, D))
    if bias is not None:
        specs.append(affine_spec)
        args.append(bias.reshape(1, D))

    def kernel(*refs):
        x_ref = refs[0]
        idx = 1
        s_ref = b_ref = None
        if scale is not None:
            s_ref = refs[idx]
            idx += 1
        if bias is not None:
            b_ref = refs[idx]
            idx += 1
        y_ref, m_ref, v_ref = refs[idx:idx + 3]
        _ln_kernel(x_ref, s_ref, b_ref, y_ref, m_ref, v_ref, eps=eps)

    y, mean, var = pl.pallas_call(
        kernel,
        out_shape=(jax.ShapeDtypeStruct((rows, D), x.dtype),
                   jax.ShapeDtypeStruct((rows, 1), jnp.float32),
                   jax.ShapeDtypeStruct((rows, 1), jnp.float32)),
        grid=grid,
        in_specs=specs,
        out_specs=(pl.BlockSpec((blk_r, D), lambda i: (i, 0),
                                memory_space=pltpu.VMEM),
                   pl.BlockSpec((blk_r, 1), lambda i: (i, 0),
                                memory_space=pltpu.VMEM),
                   pl.BlockSpec((blk_r, 1), lambda i: (i, 0),
                                memory_space=pltpu.VMEM)),
        interpret=interpret_mode(),
    )(*args)
    mshape = x.shape[:begin_norm_axis]
    return (y.reshape(x.shape), mean.reshape(mshape),
            var.reshape(mshape))


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def _ln_pallas(x, scale, bias, eps, begin_norm_axis):
    return _ln_pallas_fwd(x, scale, bias, eps, begin_norm_axis)


def _ln_vjp_fwd(x, scale, bias, eps, begin_norm_axis):
    out = _ln_pallas_fwd(x, scale, bias, eps, begin_norm_axis)
    return out, (x, scale, bias)


def _ln_vjp_bwd(eps, begin_norm_axis, res, g):
    x, scale, bias = res
    ref_fn = get("layer_norm").fn

    def composite(x_, s_, b_):
        return ref_fn(x_, s_, b_, epsilon=eps,
                      begin_norm_axis=begin_norm_axis)

    if scale is None and bias is None:
        _o, pull = jax.vjp(lambda x_: composite(x_, None, None), x)
        (dx,) = pull(g)
        return dx, None, None
    if scale is None:
        _o, pull = jax.vjp(lambda x_, b_: composite(x_, None, b_),
                           x, bias)
        dx, db = pull(g)
        return dx, None, db
    if bias is None:
        _o, pull = jax.vjp(lambda x_, s_: composite(x_, s_, None),
                           x, scale)
        dx, ds = pull(g)
        return dx, ds, None
    _o, pull = jax.vjp(composite, x, scale, bias)
    return pull(g)


_ln_pallas.defvjp(_ln_vjp_fwd, _ln_vjp_bwd)


@register_variant("layer_norm", "pallas")
def layer_norm_pallas(x, scale, bias, *, epsilon=1e-5,
                      begin_norm_axis=1):
    return _ln_pallas(x, scale, bias, epsilon, begin_norm_axis)
