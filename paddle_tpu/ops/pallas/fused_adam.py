"""Fused Adam update pallas kernel.

Reference: operators/optimizers/adam_op.h AdamFunctor (one fused
elementwise pass) + framework/ir/fuse_optimizer_ops_pass/
fuse_adam_op_pass.cc (fusing N per-param updates). Here each param's
update is one pallas kernel touching param/m1/m2/grad exactly once in
VMEM; cross-param fusion still comes for free from all updates living
in the single XLA step program."""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ..registry import register_variant
from .common import interpret_mode

_LANES = 128


def _adam_kernel(scal_ref, p_ref, g_ref, m1_ref, m2_ref,
                 po_ref, m1o_ref, m2o_ref, *, beta1, beta2, epsilon):
    lr_t = scal_ref[0, 0]
    g = g_ref[:].astype(jnp.float32)
    m1n = beta1 * m1_ref[:] + (1.0 - beta1) * g
    m2n = beta2 * m2_ref[:] + (1.0 - beta2) * g * g
    po_ref[:] = (p_ref[:] - lr_t * m1n /
                 (jnp.sqrt(m2n) + epsilon)).astype(po_ref.dtype)
    m1o_ref[:] = m1n
    m2o_ref[:] = m2n


@register_variant("adam", "pallas")
def adam_pallas(param, grad, m1, m2, b1p, b2p, lr, *, beta1=0.9,
                beta2=0.999, epsilon=1e-8, lazy_mode=False):
    from ...core.selected_rows import SparseRows
    if isinstance(grad, SparseRows):
        # sparse grads take the scatter-apply reference path (the
        # pallas kernel is a dense-elementwise fusion)
        from ..registry import get
        return get("adam").fn(param, grad, m1, m2, b1p, b2p, lr,
                              beta1=beta1, beta2=beta2,
                              epsilon=epsilon, lazy_mode=lazy_mode)
    shape, dtype = param.shape, param.dtype
    n = param.size
    # flatten + pad to [rows, 128] lanes, rows a multiple of the row
    # block so the grid divides exactly; big params stream block by
    # block through VMEM instead of loading whole (embedding tables
    # exceed the ~16MB VMEM)
    blk_r = 256
    rows = -(-n // _LANES)
    rows = -(-rows // blk_r) * blk_r
    pad = rows * _LANES - n
    grid = (rows // blk_r,)

    def flat(x, d):
        x = x.reshape(-1).astype(d)
        if pad:
            x = jnp.pad(x, (0, pad))
        return x.reshape(rows, _LANES)

    lr_t = (lr * jnp.sqrt(1.0 - b2p) / (1.0 - b1p)) \
        .astype(jnp.float32).reshape(1, 1)
    import functools
    row_spec = lambda: pl.BlockSpec((blk_r, _LANES), lambda i: (i, 0),
                                    memory_space=pltpu.VMEM)
    pn, m1n, m2n = pl.pallas_call(
        functools.partial(_adam_kernel, beta1=float(beta1),
                          beta2=float(beta2), epsilon=float(epsilon)),
        out_shape=(jax.ShapeDtypeStruct((rows, _LANES), dtype),
                   jax.ShapeDtypeStruct((rows, _LANES), jnp.float32),
                   jax.ShapeDtypeStruct((rows, _LANES), jnp.float32)),
        grid=grid,
        in_specs=[pl.BlockSpec((1, 1), lambda i: (0, 0),
                               memory_space=pltpu.SMEM),
                  row_spec(), row_spec(), row_spec(), row_spec()],
        out_specs=(row_spec(), row_spec(), row_spec()),
        interpret=interpret_mode(),
    )(lr_t, flat(param, dtype), flat(grad, jnp.float32),
      flat(m1, jnp.float32), flat(m2, jnp.float32))

    def unflat(x, d):
        return x.reshape(-1)[:n].reshape(shape).astype(d)

    return (unflat(pn, dtype), unflat(m1n, jnp.float32),
            unflat(m2n, jnp.float32), b1p * beta1, b2p * beta2)
