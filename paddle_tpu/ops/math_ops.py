"""Elementwise + linear-algebra ops.

Reference: paddle/fluid/operators/elementwise/ (~4.4k LoC, broadcasting
machinery in elementwise_op_function.h), activation_op.cc, matmul_op.cc,
mul_op.cc, operators/math/blas.h (cuBLAS/MKL dispatch).

TPU-native: every op is a jnp/lax lowering; XLA handles broadcasting,
fusion into MXU matmuls, and dtype promotion. The reference's ``axis``
broadcasting convention (align Y's dims starting at ``axis`` of X) is kept
for API parity but lowered to ordinary reshape+broadcast.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from .registry import register


def _bcast_y(x, y, axis):
    """Fluid elementwise broadcast: Y aligns to X's dims starting at axis."""
    if axis == -1 or y.ndim == x.ndim or y.ndim == 0:
        return y
    # insert trailing singleton dims so that y spans x.dims[axis:axis+y.ndim]
    shape = [1] * x.ndim
    for i in range(y.ndim):
        shape[axis + i] = y.shape[i]
    return y.reshape(shape)


def _elementwise(fn):
    def impl(x, y, *, axis=-1):
        return fn(x, _bcast_y(x, y, axis))
    return impl


@functools.lru_cache(maxsize=None)
def _bias_add_vjp(dt_name):
    """x + bias (y rank-1 over x's last dim) with the bias gradient
    computed as ``ones @ dY`` on the MXU instead of autodiff's
    broadcast-transpose reduce_sum.

    Why: on transformer-base the step profile shows ~30
    convert+reduce fusions/step re-reading the [16k, d] bf16
    upstream gradients at well below HBM bandwidth (~0.26 ms each,
    ~13x the traffic floor). A [1, N] x [N, d] dot streams dY once at
    matmul speed with f32 accumulation — better-or-equal precision
    than the f32 convert_reduce. Only the bf16 cotangent case routes
    to the MXU (an f32 dot could be demoted to bf16 under
    --xla_allow_excess_precision, which would LOSE precision vs the
    exact f32 reduce)."""

    dt = np.dtype(dt_name)

    @jax.custom_vjp
    def f(x, y):
        return x + y

    def fwd(x, y):
        return x + y, None

    def bwd(_, g):
        g2 = g.reshape(-1, g.shape[-1])
        if g2.dtype == jnp.bfloat16:
            ones = jnp.ones((g2.shape[0],), g2.dtype)
            db = lax.dot_general(ones, g2, (((0,), (0,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        elif g2.dtype.itemsize <= 4:
            db = jnp.sum(g2.astype(jnp.float32), axis=0)
        else:
            # f64: a native-dtype reduce — an f32 accumulator would
            # DOWNGRADE precision vs autodiff's own sum
            db = jnp.sum(g2, axis=0)
        return g.astype(dt), db.astype(dt)

    f.defvjp(fwd, bwd)
    return f


def _elementwise_add(x, y, *, axis=-1):
    from ..core.flags import FLAGS
    if (FLAGS.mxu_bias_grad
            and getattr(y, "ndim", None) == 1
            and getattr(x, "ndim", 0) >= 2
            and (axis in (-1, x.ndim - 1))
            and x.shape[-1] == y.shape[0]
            and jnp.issubdtype(jnp.asarray(x).dtype, jnp.floating)
            and jnp.issubdtype(jnp.asarray(y).dtype, jnp.floating)
            and jnp.asarray(x).dtype == jnp.asarray(y).dtype):
        return _bias_add_vjp(jnp.asarray(x).dtype.name)(x, y)
    return jnp.add(x, _bcast_y(x, y, axis))


register("elementwise_add", ["X", "Y"], ["Out"])(_elementwise_add)
register("elementwise_sub", ["X", "Y"], ["Out"])(_elementwise(jnp.subtract))
register("elementwise_mul", ["X", "Y"], ["Out"])(_elementwise(jnp.multiply))
register("elementwise_div", ["X", "Y"], ["Out"])(_elementwise(jnp.divide))
register("elementwise_min", ["X", "Y"], ["Out"])(_elementwise(jnp.minimum))
register("elementwise_max", ["X", "Y"], ["Out"])(_elementwise(jnp.maximum))
register("elementwise_pow", ["X", "Y"], ["Out"])(_elementwise(jnp.power))
register("elementwise_mod", ["X", "Y"], ["Out"], differentiable=False)(
    _elementwise(jnp.mod))
register("elementwise_floordiv", ["X", "Y"], ["Out"], differentiable=False)(
    _elementwise(jnp.floor_divide))


@register("scale", ["X"], ["Out"])
def scale(x, *, scale=1.0, bias=0.0, bias_after_scale=True):
    if bias_after_scale:
        return x * scale + bias
    return (x + bias) * scale


@register("mul", ["X", "Y"], ["Out"])
def mul(x, y, *, x_num_col_dims=1, y_num_col_dims=1):
    """Fluid 'mul': flatten x to 2-D at x_num_col_dims, then matmul
    (reference: mul_op.cc)."""
    xm = x
    if x.ndim != 2:
        lead = 1
        for d in x.shape[:x_num_col_dims]:
            lead *= d
        xm = x.reshape((lead, -1))
    ym = y
    if y.ndim != 2:
        lead = 1
        for d in y.shape[:y_num_col_dims]:
            lead *= d
        ym = y.reshape((lead, -1))
    out = jnp.matmul(xm, ym)
    if x.ndim != 2:
        out = out.reshape(x.shape[:x_num_col_dims] + (ym.shape[1],))
    return out


@register("matmul", ["X", "Y"], ["Out"])
def matmul(x, y, *, transpose_x=False, transpose_y=False, alpha=1.0):
    if transpose_x:
        x = jnp.swapaxes(x, -1, -2) if x.ndim > 1 else x
    if transpose_y:
        y = jnp.swapaxes(y, -1, -2) if y.ndim > 1 else y
    out = jnp.matmul(x, y)
    if alpha != 1.0:
        out = out * alpha
    return out


# -- unary activations / math (reference: activation_op.cc) -----------------

def _unary(name, fn, differentiable=True):
    register(name, ["X"], ["Out"], differentiable=differentiable)(
        lambda x: fn(x))


_unary("sqrt", jnp.sqrt)
_unary("rsqrt", lax.rsqrt)
_unary("abs", jnp.abs)
_unary("ceil", jnp.ceil)
_unary("floor", jnp.floor)
_unary("round", jnp.round)
_unary("exp", jnp.exp)
_unary("log", jnp.log)
_unary("log1p", jnp.log1p)
_unary("square", jnp.square)
_unary("reciprocal", jnp.reciprocal)
_unary("sign", jnp.sign)
_unary("sin", jnp.sin)
_unary("cos", jnp.cos)
_unary("tan", jnp.tan)
_unary("asin", jnp.arcsin)
_unary("acos", jnp.arccos)
_unary("atan", jnp.arctan)
_unary("sinh", jnp.sinh)
_unary("cosh", jnp.cosh)
_unary("erf", jax.scipy.special.erf)
_unary("logical_not", jnp.logical_not, differentiable=False)
_unary("isnan", jnp.isnan, differentiable=False)
_unary("isinf", jnp.isinf, differentiable=False)
_unary("isfinite", jnp.isfinite, differentiable=False)


@register("clip", ["X"], ["Out"])
def clip(x, *, min, max):
    return jnp.clip(x, min, max)


@register("clip_by_norm", ["X"], ["Out"])
def clip_by_norm(x, *, max_norm):
    norm = jnp.sqrt(jnp.sum(jnp.square(x)))
    return jnp.where(norm > max_norm, x * (max_norm / norm), x)


@register("pow", ["X"], ["Out"])
def pow_(x, *, factor=1.0):
    return jnp.power(x, factor)


# -- comparison / logical (reference: controlflow/compare_op.cc) ------------

def _cmp(name, fn):
    register(name, ["X", "Y"], ["Out"], differentiable=False)(
        _elementwise(fn))


_cmp("less_than", jnp.less)
_cmp("less_equal", jnp.less_equal)
_cmp("greater_than", jnp.greater)
_cmp("greater_equal", jnp.greater_equal)
_cmp("equal", jnp.equal)
_cmp("not_equal", jnp.not_equal)
_cmp("logical_and", jnp.logical_and)
_cmp("logical_or", jnp.logical_or)
_cmp("logical_xor", jnp.logical_xor)


@register("cast", ["X"], ["Out"])
def cast(x, *, dtype):
    return x.astype(dtype)


@register("sum", ["X*"], ["Out"])
def sum_(xs):
    """add_n over a variadic slot (reference: sum_op.cc — the op
    backward.py inserts to add up repeated gradients)."""
    out = xs[0]
    for x in xs[1:]:
        out = out + x
    return out


@register("dot", ["X", "Y"], ["Out"])
def dot(x, y):
    return jnp.sum(x * y, axis=-1, keepdims=True)


@register("norm", ["X"], ["Out"])
def norm(x, *, axis=-1, epsilon=1e-10):
    return x / jnp.sqrt(jnp.sum(jnp.square(x), axis=axis, keepdims=True)
                        + epsilon)


@register("p_norm", ["X"], ["Out"])
def p_norm(x, *, porder=2.0, axis=-1, keepdim=False, epsilon=1e-12):
    return jnp.power(jnp.sum(jnp.power(jnp.abs(x), porder), axis=axis,
                             keepdims=keepdim) + epsilon, 1.0 / porder)
