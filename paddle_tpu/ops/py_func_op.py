"""py_func: user-defined Python callables as first-class ops.

Reference: operators/py_func_op.cc + layers/nn.py py_func — arbitrary
Python runs inside the graph, with an optional Python backward.

TPU-native: the op lowers to ``jax.pure_callback`` — the compiled XLA
program ships the operands to the host, runs the callable, and
continues on device (the callback is the TPU analog of the reference's
"call back into the interpreter from the executor loop"). When a
``backward_func`` is registered the op wraps in ``jax.custom_vjp``
whose backward is a second callback:

    backward_func(*inputs, *outputs, *output_grads) -> input grads
    (positional; return one array per DIFFERENTIABLE input, or None
    for no gradient)

Callables are process-local (kept in a registry keyed by the op's
``func_id`` attr), so a serialized program carries the id but needs
re-registration on load — same restriction as the reference, whose
PyFuncRegistry also lives in the process.

Cost note: in a TRAINING program the forward callable runs twice per
step — the executor's generic vjp machinery re-enters every forward
lowering under jax.vjp and XLA cannot CSE host callbacks the way it
CSEs device ops (the design trade documented in executor.py; the
reference instead saves outputs op-side). Keep py_func forwards cheap
in training graphs, or wrap only the inference-side computation.
"""

from __future__ import annotations

from typing import Callable, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from .registry import register

_PY_FUNCS: List[dict] = []


def register_py_func(func: Callable,
                     backward_func: Optional[Callable] = None) -> int:
    """Park the callables; returns the func_id the op attr carries
    (reference: PyFuncRegistry::Register). Entries live as long as the
    process (exactly the reference's PyFuncRegistry) — rebuilding
    programs in a loop accretes entries, so long-lived drivers should
    build once or call clear_py_funcs() between generations."""
    _PY_FUNCS.append({"fwd": func, "bwd": backward_func})
    return len(_PY_FUNCS) - 1


def clear_py_funcs():
    """Drop every registered callable (test isolation; invalidates
    func_ids of existing programs)."""
    _PY_FUNCS.clear()


def _specs(shapes, dtypes):
    return [jax.ShapeDtypeStruct(tuple(s), np.dtype(d))
            for s, d in zip(shapes, dtypes)]


@register("py_func", ["X*"], ["Out*"])
def py_func(xs, *, func_id, out_shapes, out_dtypes):
    entry = _PY_FUNCS[func_id]
    fwd = entry["fwd"]
    bwd = entry["bwd"]
    # A LEADING -1 (batch) dim in a declared out shape binds to the
    # first input's leading dim at trace time (callbacks need static
    # shapes); -1 anywhere else has no trace-time value to bind
    lead = xs[0].shape[0] if xs else 1
    resolved = []
    for shape in out_shapes:
        if any(d == -1 for d in shape[1:]):
            raise ValueError(
                "py_func out var declares -1 in a non-leading dim %s "
                "— callbacks need static shapes; declare the real "
                "size" % (tuple(shape),))
        resolved.append(tuple(lead if d == -1 else d for d in shape))
    out_shapes = resolved
    out_specs = _specs(out_shapes, out_dtypes)

    def host_fwd(*vals):
        outs = fwd(*vals)
        if not isinstance(outs, (list, tuple)):
            outs = (outs,)
        if len(outs) != len(out_dtypes):
            raise ValueError(
                "py_func callable returned %d outputs but %d out "
                "vars were declared" % (len(outs), len(out_dtypes)))
        return tuple(np.asarray(o, np.dtype(d))
                     for o, d in zip(outs, out_dtypes))

    def call_fwd(*args):
        res = jax.pure_callback(host_fwd, tuple(out_specs), *args)
        return tuple(res)

    if bwd is None:
        # no backward registered: gradients do not flow (the reference
        # marks such py_funcs non-differentiable too)
        def call_nograd(*args):
            return call_fwd(*jax.tree_util.tree_map(
                jax.lax.stop_gradient, args))
        return list(call_nograd(*xs))

    @jax.custom_vjp
    def f(*args):
        return call_fwd(*args)

    def f_fwd(*args):
        outs = call_fwd(*args)
        return outs, (args, outs)

    def f_bwd(res, gouts):
        args, outs = res
        in_specs = [jax.ShapeDtypeStruct(a.shape, a.dtype)
                    for a in args]

        def host_bwd(*vals):
            n = len(args)
            m = len(outs)
            grads = bwd(*vals[:n], *vals[n:n + m], *vals[n + m:])
            if not isinstance(grads, (list, tuple)):
                grads = (grads,)
            return tuple(
                np.zeros(s.shape, s.dtype) if g is None
                else np.asarray(g, s.dtype)
                for g, s in zip(grads, in_specs))

        gin = jax.pure_callback(host_bwd, tuple(in_specs),
                                *args, *outs, *gouts)
        return tuple(gin)

    f.defvjp(f_fwd, f_bwd)
    return list(f(*xs))
