"""Neural-net ops: activations, losses, conv/pool, normalization, embedding.

Reference: paddle/fluid/operators/{activation_op.cc, softmax_op.cc,
cross_entropy_op.cc, softmax_with_cross_entropy_op.cc, conv_op.cc
(+ conv_cudnn_op.cu.cc), pool_op.cc, batch_norm_op.cc, layer_norm_op.cc,
group_norm_op.cc, dropout_op.cc, lookup_table_op.cc, ...}.

TPU-native: convs lower to lax.conv_general_dilated (XLA tiles them onto
the MXU); normalizations are expressed in plain jnp so XLA fuses the
elementwise chains into surrounding matmuls; dropout uses counter-based
RNG threaded by the executor. Data layout follows the reference's NCHW
for API parity — XLA relayouts internally for the TPU.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax

from ..core.enforce import InvalidArgumentError
from .registry import register


# -- activations ------------------------------------------------------------

def _unary(name, fn):
    register(name, ["X"], ["Out"])(lambda x: fn(x))


_unary("relu", jax.nn.relu)
_unary("sigmoid", jax.nn.sigmoid)
_unary("tanh", jnp.tanh)
_unary("softplus", jax.nn.softplus)
_unary("softsign", jax.nn.soft_sign)
_unary("relu6", lambda x: jnp.clip(x, 0.0, 6.0))
_unary("logsigmoid", jax.nn.log_sigmoid)


@register("gelu", ["X"], ["Out"])
def gelu(x, *, approximate=True):
    return jax.nn.gelu(x, approximate=approximate)


@register("leaky_relu", ["X"], ["Out"])
def leaky_relu(x, *, alpha=0.02):
    return jnp.where(x >= 0, x, alpha * x)


@register("elu", ["X"], ["Out"])
def elu(x, *, alpha=1.0):
    return jax.nn.elu(x, alpha)


@register("selu", ["X"], ["Out"])
def selu(x, *, scale=1.0507009873554805, alpha=1.6732632423543772):
    return scale * jnp.where(x > 0, x, alpha * (jnp.exp(x) - 1.0))


@register("swish", ["X"], ["Out"])
def swish(x, *, beta=1.0):
    return x * jax.nn.sigmoid(beta * x)


@register("hard_sigmoid", ["X"], ["Out"])
def hard_sigmoid(x, *, slope=0.2, offset=0.5):
    return jnp.clip(slope * x + offset, 0.0, 1.0)


@register("hard_swish", ["X"], ["Out"])
def hard_swish(x, *, threshold=6.0, scale=6.0, offset=3.0):
    return x * jnp.clip(x + offset, 0.0, threshold) / scale


@register("prelu", ["X", "Alpha"], ["Out"])
def prelu(x, alpha, *, mode="all"):
    if mode == "channel" and alpha.ndim == 1:
        alpha = alpha.reshape((1, -1) + (1,) * (x.ndim - 2))
    return jnp.where(x >= 0, x, alpha * x)


@register("softmax", ["X"], ["Out"])
def softmax(x, *, axis=-1):
    return jax.nn.softmax(x, axis=axis)


@register("log_softmax", ["X"], ["Out"])
def log_softmax(x, *, axis=-1):
    return jax.nn.log_softmax(x, axis=axis)


@register("maxout", ["X"], ["Out"])
def maxout(x, *, groups, axis=1):
    c = x.shape[axis]
    new_shape = (x.shape[:axis] + (c // groups, groups)
                 + x.shape[axis + 1:])
    return jnp.max(x.reshape(new_shape), axis=axis + 1)


# -- losses -----------------------------------------------------------------

@register("cross_entropy", ["X", "Label"], ["Y"], nondiff=("Label",))
def cross_entropy(x, label, *, soft_label=False, ignore_index=-100):
    """x is a probability distribution (post-softmax), fluid semantics
    (reference: cross_entropy_op.cc)."""
    eps = 1e-8
    if soft_label:
        return -jnp.sum(label * jnp.log(x + eps), axis=-1, keepdims=True)
    lab = label.squeeze(-1) if label.ndim == x.ndim else label
    picked = jnp.take_along_axis(x, lab[..., None].astype(jnp.int32),
                                 axis=-1)
    loss = -jnp.log(picked + eps)
    if ignore_index >= 0:
        loss = jnp.where((lab == ignore_index)[..., None], 0.0, loss)
    return loss


@functools.lru_cache(maxsize=None)
def _lean_softmax_xent(ignore_index):
    """Hand-written backward for the hard-label softmax+xent chain
    (the same bandwidth discipline as fused_ops._lean_xent): autodiff
    of the softmax+log_softmax composite saves BOTH [N, V] float32
    outputs as residuals and rebuilds dlogits from a scatter; here the
    residuals are (logits, lse) — logits is usually live anyway — and
    the backward is ONE fusion: ``dlogits = sm*(g_sm - <g_sm, sm>) +
    (sm - onehot)*g_loss`` with the one-hot as an iota compare. The
    label rides as float32 through the custom_vjp boundary (the float0
    dance — see ops/pallas/attention.py seed_f)."""

    from jax.custom_derivatives import SymbolicZero

    def _core(logits, lab_f):
        x = logits.astype(jnp.float32)
        m = jnp.max(x, axis=-1, keepdims=True)
        e = jnp.exp(x - m)
        s = jnp.sum(e, axis=-1, keepdims=True)
        lse = m + jnp.log(s)
        sm = e / s
        lab = lab_f.astype(jnp.int32)
        picked = jnp.take_along_axis(x, lab, axis=-1)
        loss = lse - picked
        if ignore_index >= 0:
            loss = jnp.where(lab == ignore_index, 0.0, loss)
        return (sm.astype(logits.dtype), loss), (logits, lse, lab_f)

    @jax.custom_vjp
    def f(logits, lab_f):
        return _core(logits, lab_f)[0]

    def fwd(logits_p, lab_p):
        # symbolic_zeros=True wraps primals in CustomVJPPrimal
        return _core(logits_p.value, lab_p.value)

    def _bwd(res, gs):
        logits, lse, lab_f = res
        g_sm, g_loss = gs
        lab = lab_f.astype(jnp.int32)
        sm = jnp.exp(logits.astype(jnp.float32) - lse)
        d = None
        # symbolic-zero cotangents (the common loss-only training
        # case leaves g_sm a SymbolicZero) skip their whole [N, V]
        # term — XLA does not fold float multiplies by zero
        if not isinstance(g_loss, SymbolicZero):
            gl = g_loss.astype(jnp.float32)
            if ignore_index >= 0:
                gl = jnp.where(lab == ignore_index, 0.0, gl)
            # one-hot via iota compare — variable-index scatters
            # serialize on TPU (see fused_ops._lean_xent)
            hot = (lax.broadcasted_iota(jnp.int32, logits.shape,
                                        logits.ndim - 1) == lab)
            d = (sm - hot.astype(jnp.float32)) * gl
        if not isinstance(g_sm, SymbolicZero):
            gsm = g_sm.astype(jnp.float32)
            t = sm * (gsm - jnp.sum(gsm * sm, axis=-1,
                                    keepdims=True))
            d = t if d is None else d + t
        if d is None:
            return (jnp.zeros_like(logits),
                    jnp.zeros_like(lab_f))
        return d.astype(logits.dtype), jnp.zeros_like(lab_f)

    f.defvjp(fwd, _bwd, symbolic_zeros=True)
    return f


@register("softmax_with_cross_entropy", ["Logits", "Label"],
          ["Softmax", "Loss"], nondiff=("Label",))
def softmax_with_cross_entropy(logits, label, *, soft_label=False,
                               ignore_index=-100, axis=-1,
                               return_softmax=True,
                               numeric_stable_mode=True):
    from ..core.flags import FLAGS
    # Internals run in float32 regardless of input dtype (loss stays
    # f32; the softmax output follows the input dtype) — that is what
    # makes the op AMP-gray-safe: bf16 activations enter directly,
    # like layer_norm (fp16_lists.py).
    if soft_label:
        x32 = logits.astype(jnp.float32)
        sm = jax.nn.softmax(x32, axis=axis)
        logp = jax.nn.log_softmax(x32, axis=axis)
        loss = -jnp.sum(label.astype(jnp.float32) * logp, axis=axis,
                        keepdims=True)
        return sm.astype(logits.dtype), loss
    if FLAGS.lean_xent_grad and axis in (-1, logits.ndim - 1):
        lab = label.squeeze(axis) if label.ndim == logits.ndim \
            else label
        return _lean_softmax_xent(int(ignore_index))(
            logits, lab[..., None].astype(jnp.float32))
    x32 = logits.astype(jnp.float32)
    sm = jax.nn.softmax(x32, axis=axis)
    logp = jax.nn.log_softmax(x32, axis=axis)
    lab = label.squeeze(axis) if label.ndim == logits.ndim else label
    picked = jnp.take_along_axis(logp, lab[..., None].astype(jnp.int32),
                                 axis=axis)
    loss = -picked
    if ignore_index >= 0:
        loss = jnp.where((lab == ignore_index)[..., None], 0.0, loss)
    return sm.astype(logits.dtype), loss


@register("sigmoid_cross_entropy_with_logits", ["X", "Label"], ["Out"],
          nondiff=("Label",))
def sigmoid_cross_entropy_with_logits(x, label, *, ignore_index=-100,
                                      normalize=False):
    loss = jnp.maximum(x, 0) - x * label + jnp.log1p(jnp.exp(-jnp.abs(x)))
    if ignore_index >= 0:
        mask = (label != ignore_index).astype(x.dtype)
        loss = loss * mask
        if normalize:
            loss = loss / jnp.maximum(jnp.sum(mask), 1.0)
    return loss


@register("square_error_cost", ["X", "Y"], ["Out"])
def square_error_cost(x, y):
    return jnp.square(x - y)


@register("smooth_l1_loss", ["X", "Y"], ["Out"])
def smooth_l1(x, y, *, sigma=1.0):
    s2 = sigma * sigma
    d = x - y
    ad = jnp.abs(d)
    loss = jnp.where(ad < 1.0 / s2, 0.5 * s2 * d * d, ad - 0.5 / s2)
    return jnp.sum(loss, axis=-1, keepdims=True)


@register("huber_loss", ["X", "Y"], ["Out"])
def huber_loss(x, y, *, delta=1.0):
    d = y - x
    ad = jnp.abs(d)
    return jnp.where(ad <= delta, 0.5 * d * d,
                     delta * (ad - 0.5 * delta))


@register("kldiv_loss", ["X", "Target"], ["Loss"], nondiff=("Target",))
def kldiv_loss(x, target, *, reduction="mean"):
    loss = target * (jnp.log(jnp.maximum(target, 1e-10)) - x)
    if reduction == "mean":
        return jnp.mean(loss)
    if reduction == "sum":
        return jnp.sum(loss)
    if reduction == "batchmean":
        return jnp.sum(loss) / x.shape[0]
    return loss


@register("log_loss", ["Predicted", "Labels"], ["Loss"],
          nondiff=("Labels",))
def log_loss(pred, label, *, epsilon=1e-4):
    return (-label * jnp.log(pred + epsilon)
            - (1.0 - label) * jnp.log(1.0 - pred + epsilon))


@register("margin_rank_loss", ["X1", "X2", "Label"], ["Out"],
          nondiff=("Label",))
def margin_rank_loss(x1, x2, label, *, margin=0.0):
    return jnp.maximum(0.0, -label * (x1 - x2) + margin)


@register("hinge_loss", ["Logits", "Labels"], ["Loss"], nondiff=("Labels",))
def hinge_loss(logits, labels):
    return jnp.maximum(0.0, 1.0 - (2.0 * labels - 1.0) * logits)


@register("mse_loss", ["X", "Y"], ["Out"])
def mse_loss(x, y):
    return jnp.mean(jnp.square(x - y))


# -- conv / pool ------------------------------------------------------------

def _pair(v, n=2):
    if isinstance(v, (list, tuple)):
        return tuple(v)
    return (v,) * n


@register("conv2d", ["Input", "Filter"], ["Output"])
def conv2d(x, w, *, strides=(1, 1), paddings=(0, 0), dilations=(1, 1),
           groups=1, data_format="NCHW"):
    """Reference: conv_op.cc / conv_cudnn_op.cu.cc:68. Lowered to one
    lax.conv_general_dilated — XLA picks the MXU tiling (the analog of
    cuDNN algo search at :139-151 is done by the compiler)."""
    strides, dilations = _pair(strides), _pair(dilations)
    p = _pair(paddings)
    if len(p) == 2:
        pad = [(p[0], p[0]), (p[1], p[1])]
    else:
        pad = [(p[0], p[1]), (p[2], p[3])]
    dn = lax.conv_dimension_numbers(
        x.shape, w.shape,
        ("NCHW", "OIHW", "NCHW") if data_format == "NCHW"
        else ("NHWC", "HWIO", "NHWC"))
    # NOTE: no preferred_element_type here — requesting an f32 output
    # from a bf16 conv breaks JAX's transpose rule under AMP (the
    # backward conv then mixes bf16/f32 operands). The MXU accumulates
    # in f32 internally either way; the output rounds to the input
    # dtype like every other white-list matmul op.
    return lax.conv_general_dilated(
        x, w, window_strides=strides, padding=pad,
        rhs_dilation=dilations, dimension_numbers=dn,
        feature_group_count=groups)


@register("depthwise_conv2d", ["Input", "Filter"], ["Output"])
def depthwise_conv2d(x, w, *, strides=(1, 1), paddings=(0, 0),
                     dilations=(1, 1), groups=None, data_format="NCHW"):
    g = groups or x.shape[1]
    return conv2d(x, w, strides=strides, paddings=paddings,
                  dilations=dilations, groups=g, data_format=data_format)


@register("conv3d", ["Input", "Filter"], ["Output"])
def conv3d(x, w, *, strides=(1, 1, 1), paddings=(0, 0, 0),
           dilations=(1, 1, 1), groups=1):
    strides = _pair(strides, 3)
    dilations = _pair(dilations, 3)
    p = _pair(paddings, 3)
    pad = [(pi, pi) for pi in p]
    dn = lax.conv_dimension_numbers(x.shape, w.shape,
                                    ("NCDHW", "OIDHW", "NCDHW"))
    return lax.conv_general_dilated(x, w, window_strides=strides,
                                    padding=pad, rhs_dilation=dilations,
                                    dimension_numbers=dn,
                                    feature_group_count=groups)


@register("conv2d_transpose", ["Input", "Filter"], ["Output"])
def conv2d_transpose(x, w, *, strides=(1, 1), paddings=(0, 0),
                     dilations=(1, 1), groups=1, output_size=None):
    """Gradient-of-conv semantics: out = (H-1)*stride - 2*pad +
    dilation*(k-1) + 1 (reference: conv_transpose_op.cc). Lowered to an
    input-dilated conv with per-side pads of dilation*(k-1) - pad."""
    strides, dilations = _pair(strides), _pair(dilations)
    p = _pair(paddings)
    ks = w.shape[2:]
    pad = [(dilations[i] * (ks[i] - 1) - p[i],) * 2 for i in range(2)]
    # fluid filter layout for transpose: (in, out//groups, kh, kw).
    # Deconv = conv of the input dilated by `strides` with the spatially
    # flipped kernel; the IOHW dimension spec swaps in/out channels.
    w_flip = jnp.flip(w, axis=(2, 3))
    if groups == 1:
        dn = lax.conv_dimension_numbers(x.shape, w.shape,
                                        ("NCHW", "IOHW", "NCHW"))
        return lax.conv_general_dilated(
            x, w_flip, window_strides=(1, 1), padding=pad,
            lhs_dilation=strides, rhs_dilation=dilations,
            dimension_numbers=dn)
    # Grouped deconv: (g*in_g, out_g, kh, kw) -> (g*out_g, in_g, kh,
    # kw) OIHW so lax's consecutive-block group semantics line up with
    # fluid's consecutively-grouped output channels.
    cin, out_g, kh, kw = w_flip.shape
    in_g = cin // groups
    w_oihw = (w_flip.reshape(groups, in_g, out_g, kh, kw)
              .transpose(0, 2, 1, 3, 4)
              .reshape(groups * out_g, in_g, kh, kw))
    dn = lax.conv_dimension_numbers(x.shape, w_oihw.shape,
                                    ("NCHW", "OIHW", "NCHW"))
    return lax.conv_general_dilated(
        x, w_oihw, window_strides=(1, 1), padding=pad,
        lhs_dilation=strides, rhs_dilation=dilations,
        dimension_numbers=dn, feature_group_count=groups)


@register("depthwise_conv2d_transpose", ["Input", "Filter"], ["Output"])
def depthwise_conv2d_transpose(x, w, *, strides=(1, 1), paddings=(0, 0),
                               dilations=(1, 1), groups=None,
                               output_size=None):
    """Reference: conv_transpose_op.cc (depthwise variant). Per-channel
    transposed conv: groups defaults to the input channel count."""
    g = groups or x.shape[1]
    return conv2d_transpose(x, w, strides=strides, paddings=paddings,
                            dilations=dilations, groups=g,
                            output_size=output_size)


@register("pool2d", ["X"], ["Out"])
def pool2d(x, *, ksize, pooling_type="max", strides=(1, 1),
           paddings=(0, 0), global_pooling=False, ceil_mode=False,
           exclusive=True, adaptive=False, data_format="NCHW"):
    """Reference: pool_op.cc. Lowered to lax.reduce_window; NHWC runs
    through a transpose pair XLA folds into the window layout."""
    if data_format == "NHWC":
        out = pool2d(x.transpose(0, 3, 1, 2), ksize=ksize,
                     pooling_type=pooling_type, strides=strides,
                     paddings=paddings, global_pooling=global_pooling,
                     ceil_mode=ceil_mode, exclusive=exclusive,
                     adaptive=adaptive, data_format="NCHW")
        return out.transpose(0, 2, 3, 1)
    if data_format != "NCHW":
        raise InvalidArgumentError(
            "pool2d data_format must be NCHW or NHWC, got %r"
            % (data_format,))
    if global_pooling or adaptive and tuple(_pair(ksize)) == (1, 1):
        axis = (2, 3)
        if pooling_type == "max":
            return jnp.max(x, axis=axis, keepdims=True)
        return jnp.mean(x, axis=axis, keepdims=True)
    k = _pair(ksize)
    s = _pair(strides)
    p = _pair(paddings)
    window = (1, 1) + k
    stride = (1, 1) + s
    hi = [p[0], p[1]]
    if ceil_mode:
        # reference pool_op.cc ceil formula: output covers the input
        # tail by padding the high side up to a full extra stride
        for i, (L, kk, ss, pp) in enumerate(
                zip(x.shape[2:], k, s, p)):
            out_ceil = -(-(L + 2 * pp - kk) // ss) + 1
            hi[i] = (out_ceil - 1) * ss + kk - (L + pp)
    pads = [(0, 0), (0, 0), (p[0], hi[0]), (p[1], hi[1])]
    if pooling_type == "max":
        init = -jnp.inf if jnp.issubdtype(x.dtype, jnp.floating) else \
            jnp.iinfo(x.dtype).min
        return lax.reduce_window(x, init, lax.max, window, stride, pads)
    # avg pool
    ones = jnp.ones_like(x)
    summed = lax.reduce_window(x, 0.0, lax.add, window, stride, pads)
    if exclusive:
        # padding contributes 0 to counts, so ceil-mode tail windows
        # divide by their real element count
        counts = lax.reduce_window(ones, 0.0, lax.add, window, stride,
                                   pads)
    else:
        counts = float(k[0] * k[1])
    return summed / counts


def _adaptive_pool(x, out_sizes, axes, pooling_type):
    """General adaptive pooling: output cell i over axis of length L
    covers [floor(i*L/O), ceil((i+1)*L/O)) — the reference's
    AdaptiveStartIndex/AdaptiveEndIndex (pool_op.h:42-52). Bin
    boundaries are static, so uneven sizes lower to a static slice
    per cell (cheap: O cells is small); the even case keeps the fused
    one-reshape reduction."""
    if all(x.shape[ax] % o == 0 for ax, o in zip(axes, out_sizes)):
        shape, red_axes = [], []
        for d in range(x.ndim):
            if d in axes:
                o = out_sizes[axes.index(d)]
                shape += [o, x.shape[d] // o]
                red_axes.append(len(shape) - 1)
            else:
                shape.append(x.shape[d])
        xr = x.reshape(shape)
        reduce = jnp.max if pooling_type == "max" else jnp.mean
        return reduce(xr, axis=tuple(red_axes))

    def pool_axis(arr, ax, o):
        L = arr.shape[ax]
        cells = []
        for i in range(o):
            lo, hi = (i * L) // o, -((-(i + 1) * L) // o)  # ceil
            sl = [slice(None)] * arr.ndim
            sl[ax] = slice(lo, hi)
            reduce = jnp.max if pooling_type == "max" else jnp.mean
            cells.append(reduce(arr[tuple(sl)], axis=ax,
                                keepdims=True))
        return jnp.concatenate(cells, axis=ax)

    for ax, o in zip(axes, out_sizes):
        x = pool_axis(x, ax, o)
    return x


@register("adaptive_pool2d", ["X"], ["Out"])
def adaptive_pool2d(x, *, pool_size, pooling_type="avg"):
    oh, ow = _pair(pool_size)
    return _adaptive_pool(x, (oh, ow), (2, 3), pooling_type)


# -- normalization ----------------------------------------------------------

@register("batch_norm",
          ["X", "Scale", "Bias", "Mean", "Variance"],
          ["Y", "MeanOut", "VarianceOut", "SavedMean", "SavedVariance"],
          nondiff=("Mean", "Variance"))
def batch_norm(x, scale, bias, mean, var, *, epsilon=1e-5, momentum=0.9,
               is_test=False, data_layout="NCHW", use_global_stats=False):
    """Reference: batch_norm_op.cc/.cu. Running stats are persistable vars
    updated functionally (MeanOut/VarianceOut alias Mean/Variance in the
    program, as the reference does)."""
    axes = (0, 2, 3) if (x.ndim == 4 and data_layout == "NCHW") else \
        tuple(i for i in range(x.ndim) if i != x.ndim - 1) \
        if data_layout == "NHWC" else (0,)
    if x.ndim == 2:
        axes = (0,)
    bshape = [1] * x.ndim
    caxis = 1 if (data_layout == "NCHW" and x.ndim == 4) else x.ndim - 1
    if x.ndim == 2:
        caxis = 1
    bshape[caxis] = x.shape[caxis]

    def _r(v):
        return v.reshape(bshape)

    if is_test or use_global_stats:
        y = ((x.astype(jnp.float32) - _r(mean)) * _r(scale) *
             lax.rsqrt(_r(var) + epsilon) +
             _r(bias)).astype(x.dtype)
        return y, mean, var, mean, var
    # Statistics ALWAYS in f32 (the reference's fp16 BN keeps float
    # accumulators, batch_norm_op.cu): the one-pass E[x^2]-E[x]^2 form
    # in bf16 cancels catastrophically (negative variance -> rsqrt
    # NaN under AMP). Two-pass + f32 is cheap and stable.
    xf = x.astype(jnp.float32)
    bmean = jnp.mean(xf, axis=axes)
    bvar = jnp.mean(jnp.square(xf - _r(bmean)), axis=axes)
    y = ((xf - _r(bmean)) * _r(scale) *
         lax.rsqrt(_r(bvar) + epsilon) + _r(bias)).astype(x.dtype)
    mean_out = momentum * mean + (1.0 - momentum) * bmean
    var_out = momentum * var + (1.0 - momentum) * bvar
    return y, mean_out, var_out, bmean, bvar


@jax.custom_vjp
def _ln_affine(norm, scale, bias):
    """custom-vjp affine tail of layer_norm (FLAGS.mxu_ln_grad): the
    dScale/dBias column reductions over N rows run as ones@M MXU dots
    with f32 accumulation instead of the convert_reduce fusions the
    round-4 step anatomy charged ~7.8 ms/step to (BASELINE.md). Same
    treatment as ops/math_ops._bias_add_vjp, extended to the scale
    product. dX path (through mean/var) stays autodiff. scale/bias
    arrive already broadcast-shaped ([1, ..., D])."""
    return norm * scale + bias


def _ln_affine_fwd(norm, scale, bias):
    return norm * scale + bias, (norm, scale)


def _ln_affine_bwd(res, g):
    norm, scale = res
    dnorm = g * scale
    d = g.shape[-1]
    g2 = g.reshape(-1, d)
    n2 = norm.reshape(-1, d)
    ones = jnp.ones((g2.shape[0],), g2.dtype)
    dims = (((0,), (0,)), ((), ()))
    dbias = lax.dot_general(ones, g2, dims,
                            preferred_element_type=jnp.float32)
    dscale = lax.dot_general(ones, g2 * n2, dims,
                             preferred_element_type=jnp.float32)
    return (dnorm, dscale.reshape(scale.shape).astype(scale.dtype),
            dbias.reshape(scale.shape).astype(g.dtype))


_ln_affine.defvjp(_ln_affine_fwd, _ln_affine_bwd)


@register("layer_norm", ["X", "Scale", "Bias"], ["Y", "Mean", "Variance"])
def layer_norm(x, scale, bias, *, epsilon=1e-5, begin_norm_axis=1):
    """Reference: layer_norm_op.cc. Normalizes over dims
    [begin_norm_axis:]; pallas variant registered in ops/pallas.

    Statistics in f32 regardless of input dtype (bf16 moment sums lose
    precision), output back in the INPUT dtype — under AMP this keeps
    the bf16 stream flowing instead of shipping f32 activations to the
    next matmul's cast (the same policy as batch_norm)."""
    from ..core.flags import FLAGS
    axes = tuple(range(begin_norm_axis, x.ndim))
    xf = x.astype(jnp.float32)
    mean = jnp.mean(xf, axis=axes, keepdims=True)
    var = jnp.mean(jnp.square(xf - mean), axis=axes, keepdims=True)
    inv = lax.rsqrt(var + epsilon)
    norm = (xf - mean) * inv
    bshape = [1] * begin_norm_axis + list(x.shape[begin_norm_axis:])
    if (FLAGS.mxu_ln_grad and scale is not None and bias is not None
            and len(axes) == 1 and x.shape[-1] == scale.shape[-1]):
        norm = _ln_affine(norm,
                          scale.reshape(bshape).astype(norm.dtype),
                          bias.reshape(bshape).astype(norm.dtype))
        return norm.astype(x.dtype), jnp.squeeze(mean), jnp.squeeze(var)
    if scale is not None:
        norm = norm * scale.reshape(bshape)
    if bias is not None:
        norm = norm + bias.reshape(bshape)
    return norm.astype(x.dtype), jnp.squeeze(mean), jnp.squeeze(var)


@register("group_norm", ["X", "Scale", "Bias"], ["Y", "Mean", "Variance"])
def group_norm(x, scale, bias, *, groups, epsilon=1e-5):
    n, c, h, w = x.shape
    g = groups
    xg = x.reshape(n, g, c // g, h, w)
    mean = jnp.mean(xg, axis=(2, 3, 4), keepdims=True)
    var = jnp.var(xg, axis=(2, 3, 4), keepdims=True)
    xn = ((xg - mean) * lax.rsqrt(var + epsilon)).reshape(n, c, h, w)
    if scale is not None:
        xn = xn * scale.reshape(1, c, 1, 1)
    if bias is not None:
        xn = xn + bias.reshape(1, c, 1, 1)
    return xn, jnp.squeeze(mean), jnp.squeeze(var)


@register("instance_norm", ["X", "Scale", "Bias"], ["Y"])
def instance_norm(x, scale, bias, *, epsilon=1e-5):
    axes = tuple(range(2, x.ndim))
    mean = jnp.mean(x, axis=axes, keepdims=True)
    var = jnp.var(x, axis=axes, keepdims=True)
    y = (x - mean) * lax.rsqrt(var + epsilon)
    c = x.shape[1]
    bshape = (1, c) + (1,) * (x.ndim - 2)
    if scale is not None:
        y = y * scale.reshape(bshape)
    if bias is not None:
        y = y + bias.reshape(bshape)
    return y


@register("l2_normalize", ["X"], ["Out"])
def l2_normalize(x, *, axis=-1, epsilon=1e-12):
    return x * lax.rsqrt(jnp.sum(jnp.square(x), axis=axis, keepdims=True)
                         + epsilon)


# -- dropout / embedding ----------------------------------------------------

@register("dropout", ["X"], ["Out", "Mask"], needs_rng=True)
def dropout(x, *, dropout_prob=0.5, is_test=False,
            dropout_implementation="downgrade_in_infer", seed=0, rng=None):
    """Reference: dropout_op.cc. Counter-based RNG replaces curand.

    The backward RECOMPUTES the keep mask from the saved key instead of
    keeping the full-tensor mask live from forward to backward — with
    the counter-based generator the bits cost a few fused vector ops,
    while a saved mask costs a full HBM round-trip per dropout site
    (~30 sites x [16k, 512]+ on transformer-base). The Mask output is
    still emitted for API parity; XLA CSEs it against the forward's
    in-register mask and dead-codes it when nothing consumes it."""
    if is_test:
        if dropout_implementation == "upscale_in_train":
            return x, jnp.ones_like(x)
        return x * (1.0 - dropout_prob), jnp.ones_like(x)
    key = jax.random.key(seed) if seed else rng
    upscale = dropout_implementation == "upscale_in_train"
    out = _dropout_train(float(dropout_prob), upscale)(x, key)
    mask = _keep_mask(key, dropout_prob, x.shape).astype(x.dtype)
    return out, mask


@functools.lru_cache(maxsize=None)
def _dropout_train(rate, upscale):
    @jax.custom_vjp
    def f(x, key):
        mask = _keep_mask(key, rate, x.shape).astype(x.dtype)
        return x * mask / (1.0 - rate) if upscale else x * mask

    def fwd(x, key):
        return f(x, key), (key,)

    def bwd(res, g):
        (key,) = res
        mask = _keep_mask(key, rate, g.shape).astype(g.dtype)
        dx = g * mask / (1.0 - rate) if upscale else g * mask
        return dx, None

    f.defvjp(fwd, bwd)
    return f


def _keep_mask(key, rate, shape):
    """Bernoulli(1-rate) keep mask by raw-bit threshold compare.

    Equivalent to jax.random.bernoulli (bits uniform, so
    P[bits >= rate*2^B] = 1-rate to within 2^-B) but skips the
    bits->float-uniform conversion — on the bench transformer the mask
    generation over the [B,H,S,S] attention weights and FFN
    activations is ~1/5 of step time, so the elementwise work here is
    a measured win. (A u16-halves variant — one generated u32 serving
    two elements — was chip-measured in round 4 and did NOT win: the
    bitcast+reshape breaks the generator's fusion with the consumer,
    and the rbg generator is not bit-count-bound.) RNG impl is
    whatever jax.random.bits uses (rbg on TPU via bench.py)."""
    bits = jax.random.bits(key, shape, jnp.uint32)
    thresh = min(int(rate * (1 << 32)), (1 << 32) - 1)
    return bits >= jnp.uint32(thresh)


@register("lookup_table", ["W", "Ids"], ["Out"], nondiff=("Ids",))
def lookup_table(w, ids, *, padding_idx=-1, is_sparse=False,
                 is_distributed=False):
    """Embedding lookup (reference: lookup_table_op.cc). On TPU this is a
    dense HBM gather; XLA emits an efficient dynamic-gather. Sparse-grad
    handling (SelectedRows) is subsumed by XLA scatter-add in the VJP."""
    ids2 = ids.squeeze(-1) if ids.ndim > 1 and ids.shape[-1] == 1 else ids
    out = jnp.take(w, ids2, axis=0)
    if padding_idx is not None and padding_idx >= 0:
        out = jnp.where((ids2 == padding_idx)[..., None], 0.0, out)
    return out


@register("lookup_table_grad", ["Ids", "OutGrad"], ["WGrad"],
          differentiable=False, accumulate_outputs=True)
def lookup_table_grad(ids, out_grad, *, height, padding_idx=-1):
    """Sparse gradient of lookup_table (reference: lookup_table_op.cc
    ``is_sparse`` grad path emitting SelectedRows). Appended by
    backward.append_backward instead of a generic vjp op when the
    forward lookup has is_sparse=True: the table gradient is the
    incoming cotangent re-labelled with its row ids — O(batch), no
    scatter, and the [height, dim] table is never densified."""
    from ..core.selected_rows import SparseRows

    ids2 = ids.squeeze(-1) if ids.ndim > 1 and ids.shape[-1] == 1 \
        else ids
    rows = ids2.reshape(-1).astype(jnp.int32)
    dim = out_grad.shape[-1]
    values = out_grad.reshape(-1, dim)
    if padding_idx is not None and padding_idx >= 0:
        # forward zeroed padding rows; their cotangent must not flow
        values = jnp.where((rows == padding_idx)[:, None], 0.0, values)
    return SparseRows(rows, values, height)


@register("embedding_bag", ["W", "Ids"], ["Out"], nondiff=("Ids",))
def embedding_bag(w, ids, *, mode="sum", padding_idx=-1):
    """Fused embedding + sequence-pool (reference:
    fused_embedding_seq_pool_op.cc). ids: [batch, bag]; padding_idx rows
    contribute zero."""
    emb = jnp.take(w, ids, axis=0)
    if padding_idx is not None and padding_idx >= 0:
        mask = (ids != padding_idx).astype(w.dtype)[..., None]
        emb = emb * mask
        denom = jnp.maximum(jnp.sum(mask, axis=1), 1.0)
    else:
        denom = float(ids.shape[1])
    if mode == "sum":
        return jnp.sum(emb, axis=1)
    if mode == "mean":
        return jnp.sum(emb, axis=1) / denom
    return jnp.max(emb, axis=1)


# -- misc -------------------------------------------------------------------

@register("interpolate", ["X"], ["Out"])
def interpolate(x, *, out_shape, method="nearest", align_corners=False,
                data_format="NCHW"):
    n, c, h, w = x.shape
    oh, ow = out_shape
    return jax.image.resize(x, (n, c, oh, ow),
                            method="nearest" if method == "nearest"
                            else "bilinear")


@register("pixel_shuffle", ["X"], ["Out"])
def pixel_shuffle(x, *, upscale_factor):
    n, c, h, w = x.shape
    r = upscale_factor
    x = x.reshape(n, c // (r * r), r, r, h, w)
    x = jnp.transpose(x, (0, 1, 4, 2, 5, 3))
    return x.reshape(n, c // (r * r), h * r, w * r)


@register("grid_sampler", ["X", "Grid"], ["Output"])
def grid_sampler(x, grid):
    n, c, h, w = x.shape
    gx = (grid[..., 0] + 1.0) * (w - 1) / 2.0
    gy = (grid[..., 1] + 1.0) * (h - 1) / 2.0
    x0 = jnp.floor(gx).astype(jnp.int32)
    y0 = jnp.floor(gy).astype(jnp.int32)
    x1, y1 = x0 + 1, y0 + 1
    wx, wy = gx - x0, gy - y0

    def _sample(xi, yi):
        xi = jnp.clip(xi, 0, w - 1)
        yi = jnp.clip(yi, 0, h - 1)
        batch_idx = jnp.arange(n)[:, None, None]
        return x[batch_idx, :, yi, xi]  # [n, oh, ow, c]

    v00 = _sample(x0, y0)
    v01 = _sample(x1, y0)
    v10 = _sample(x0, y1)
    v11 = _sample(x1, y1)
    wx_, wy_ = wx[..., None], wy[..., None]
    out = (v00 * (1 - wx_) * (1 - wy_) + v01 * wx_ * (1 - wy_)
           + v10 * (1 - wx_) * wy_ + v11 * wx_ * wy_)
    return jnp.transpose(out, (0, 3, 1, 2))


@register("fsp_matrix", ["X", "Y"], ["Out"])
def fsp_matrix(x, y):
    """Reference: operators/fsp_op.cc — flow-of-solution-procedure
    matrix between two [b, c1, h, w] / [b, c2, h, w] feature maps:
    Out[b, i, j] = sum_hw X[b,i,h,w] * Y[b,j,h,w] / (h*w). One MXU
    einsum on TPU."""
    h, w = x.shape[2], x.shape[3]
    return jnp.einsum("bihw,bjhw->bij", x, y) / float(h * w)


@register("label_smooth", ["X", "PriorDist"], ["Out"])
def label_smooth(x, prior_dist=None, *, epsilon=0.1):
    """Reference: operators/label_smooth_op.cc — uniform (or prior)
    smoothing of one-hot targets."""
    k = x.shape[-1]
    if prior_dist is not None:
        return (1.0 - epsilon) * x + epsilon * prior_dist
    return (1.0 - epsilon) * x + epsilon / k


@register("brelu", ["X"], ["Out"])
def brelu(x, *, t_min=0.0, t_max=24.0):
    """Reference: operators/activation_op.cc BRelu."""
    return jnp.clip(x, t_min, t_max)


@register("soft_relu", ["X"], ["Out"])
def soft_relu(x, *, threshold=40.0):
    """Reference: activation_op.cc SoftRelu: log(1 + exp(clip(x)))."""
    return jnp.log1p(jnp.exp(jnp.clip(x, -threshold, threshold)))


@register("stanh", ["X"], ["Out"])
def stanh(x, *, scale_a=0.67, scale_b=1.7159):
    """Reference: activation_op.cc STanh."""
    return scale_b * jnp.tanh(scale_a * x)


@register("adaptive_pool3d", ["X"], ["Out"])
def adaptive_pool3d(x, *, pool_size, pooling_type="avg"):
    """Reference: pool_op.cc adaptive 3-D (NCDHW); uneven splits use
    the reference's floor/ceil bin boundaries (pool_op.h:42-52)."""
    od, oh, ow = (pool_size if isinstance(pool_size, (list, tuple))
                  else (pool_size,) * 3)
    return _adaptive_pool(x, (od, oh, ow), (2, 3, 4), pooling_type)


@register("dice_loss", ["X", "Label"], ["Out"], nondiff=("Label",))
def dice_loss(x, label, *, epsilon=1e-5):
    """Reference: layers/nn.py dice_loss (composite in the reference
    python layer): 1 - 2*|X∩L| / (|X|+|L|), reduced over all but the
    batch dim."""
    label = label.astype(x.dtype)
    reduce_dims = tuple(range(1, x.ndim))
    inter = jnp.sum(x * label, axis=reduce_dims)
    union = jnp.sum(x, axis=reduce_dims) + jnp.sum(label,
                                                   axis=reduce_dims)
    return jnp.mean(1.0 - (2.0 * inter + epsilon) / (union + epsilon))


@register("npair_loss", ["Anchor", "Positive", "Labels"], ["Out"],
          nondiff=("Labels",))
def npair_loss(anchor, positive, labels, *, l2_reg=0.002):
    """Reference: layers/loss.py npair_loss composite — softmax
    cross-entropy over anchor·positiveᵀ similarities with same-label
    targets, plus l2 regularization on the embeddings."""
    sim = jnp.dot(anchor, positive.T)                   # [B, B]
    lab = labels.reshape(-1)
    same = (lab[:, None] == lab[None, :]).astype(sim.dtype)
    tgt = same / jnp.maximum(jnp.sum(same, axis=1, keepdims=True),
                             1.0)
    logp = jax.nn.log_softmax(sim, axis=1)
    ce = -jnp.mean(jnp.sum(tgt * logp, axis=1))
    reg = l2_reg * (jnp.mean(jnp.sum(jnp.square(anchor), axis=1))
                    + jnp.mean(jnp.sum(jnp.square(positive),
                                       axis=1))) / 2.0
    return ce + reg


@register("similarity_focus", ["X"], ["Out"], differentiable=False)
def similarity_focus(x, *, axis, indexes):
    """Reference: operators/similarity_focus_op.cc — build a 0/1
    focus mask: for each selected channel index along ``axis``, mark
    the argmax positions per remaining row/col (NCHW only, axis=1 as
    the reference supports)."""
    n, c, h, w = x.shape
    out = jnp.zeros_like(x)
    for idx in indexes:
        sl = x[:, idx]                                  # [N, H, W]
        row_best = jnp.argmax(sl, axis=2)               # [N, H]
        col_best = jnp.argmax(sl, axis=1)               # [N, W]
        mask = jnp.zeros((n, h, w), x.dtype)
        mask = mask.at[jnp.arange(n)[:, None],
                       jnp.arange(h)[None, :], row_best].set(1.0)
        mask = mask.at[jnp.arange(n)[:, None], col_best,
                       jnp.arange(w)[None, :]].set(1.0)
        out = out + mask[:, None, :, :] * jnp.ones((1, c, 1, 1),
                                                   x.dtype)
    return jnp.minimum(out, 1.0)
