"""Structured control-flow ops: while, scan-based RNNs, tensor arrays.

Reference: paddle/fluid/operators/controlflow/while_op.cc (runs a
sub-block via a nested Executor), lod_tensor_array ops
(controlflow/tensor_array_read_write_op.cc), and the recurrent op
machinery the reference drives through while+arrays.

TPU-native redesign:
  - ``static_rnn`` / ``dynamic_rnn`` lower the recorded sub-block through
    ``lax.scan`` — ONE fused XLA loop, reverse-mode differentiable, with
    masking replacing the reference's LoD sequence reordering
    (math/sequence2batch.h). This is the training-path recurrence.
  - ``while`` interprets its sub-block eagerly (a Python loop over the
    ops' JAX lowerings) with full dynamism — the analog of the
    reference's op-by-op interpreter; the Executor automatically drops
    to eager mode for programs containing it. Inference decode loops
    that need to be compiled use the dedicated beam-search ops instead.
  - tensor arrays are Python lists of device arrays (eager mode only);
    ``lax.scan``'s stacked outputs replace them on the compiled path.

The sub-block is looked up through the tracing-program context
(framework._trace_program_guard) because op attrs hold only the block
index — attrs must stay deep-copyable metadata.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..core.enforce import InvalidArgumentError, enforce
from .registry import register


def _tracing_block(sub_block):
    from .. import framework
    program = framework._current_tracing_program()
    enforce(program is not None,
            "control-flow op traced outside an executor/infer-shape "
            "context (no tracing program set)")
    return program.block(sub_block)


def _run_sub_block(block, env, rng):
    from .. import executor as _ex
    _ex.run_block(block, env, rng)
    return env


def _concrete_index(i, what):
    try:
        return int(np.asarray(i).reshape(-1)[0])
    except jax.errors.TracerArrayConversionError:
        raise InvalidArgumentError(
            "%s requires a concrete index — tensor-array ops only run in "
            "eager (interpreted) mode; use static_rnn/dynamic_rnn or "
            "beam search for compiled loops" % what)


# ---------------------------------------------------------------------------
# while — compiled loop (reference: while_op.cc:59 WhileOp::Run runs the
# sub-block via a nested Executor; while_grad_op re-runs it backward)
#
# TPU-native lowering ladder:
#   1. body uses tensor arrays            -> eager interpreted loop
#      (full dynamism; the Executor drops the program to eager mode)
#   2. ``max_iters`` attr set             -> lax.scan over max_iters with
#      a done-mask: ONE fused XLA loop, reverse-mode DIFFERENTIABLE —
#      the analog of while_grad_op (bounded-unroll checkpointing is
#      jax.checkpoint on the body if memory demands it)
#   3. otherwise                          -> lax.while_loop: compiled,
#      data-dependent trip count, forward-only (XLA While HLO)
# ---------------------------------------------------------------------------

# Tensor-array op types: list-valued, need concrete indices. Single
# source of truth — the Executor's whole-program eager decision imports
# this same set (executor._EAGER_OP_TYPES).
ARRAY_OP_TYPES = frozenset({"create_array", "array_write", "array_read",
                            "array_length"})


def _block_uses_arrays(blk) -> bool:
    for op in blk.ops:
        if op.type in ARRAY_OP_TYPES:
            return True
    return False


@register("while", ["Condition", "X*"], ["Out*"], differentiable=True,
          needs_rng=True)
def while_op(cond, xs, *, sub_block, in_names, out_names, cond_name,
             rng, is_test=False, max_iters=0):
    blk = _tracing_block(sub_block)

    if _block_uses_arrays(blk):
        return _while_eager(blk, cond, xs, in_names, out_names,
                            cond_name, rng)

    # vars written by the body are loop-carried; read-only vars are
    # loop invariants and close over (XLA keeps them resident)
    carried = [n for n in out_names if n != cond_name]
    invariant_env = {n: x for n, x in zip(in_names, xs)
                     if n not in carried}
    init_vals = []
    by_name = dict(zip(in_names, xs))
    for n in carried:
        enforce(n in by_name,
                "While-carried var %r has no initial value" % n)
        init_vals.append(by_name[n])

    def run_body(cond_val, vals, it):
        env = dict(invariant_env)
        env.update(zip(carried, vals))
        env[cond_name] = cond_val
        _run_sub_block(blk, env, jax.random.fold_in(rng, it))
        return env[cond_name], [env[n] for n in carried]

    def collect(cond_val, vals):
        env = dict(zip(carried, vals))
        env[cond_name] = cond_val
        return [env[n] for n in out_names]

    if max_iters and max_iters > 0:
        # differentiable bounded loop: scan max_iters steps, freeze the
        # carry once the condition drops (reference while_grad
        # correctness; grads flow through the active prefix only)
        def body(carry, it):
            cond_val, vals = carry
            active = jnp.asarray(cond_val).reshape(()).astype(bool)
            new_cond, new_vals = run_body(cond_val, vals, it)
            keep_cond = jnp.where(active, new_cond, cond_val)
            keep_vals = [jnp.where(active, nv, v)
                         for nv, v in zip(new_vals, vals)]
            return (keep_cond, keep_vals), None

        (final_cond, final_vals), _ = jax.lax.scan(
            body, (cond, init_vals), jnp.arange(int(max_iters)))
        return collect(final_cond, final_vals)

    def cond_fn(carry):
        cond_val, _vals, _it = carry
        return jnp.asarray(cond_val).reshape(()).astype(bool)

    def body_fn(carry):
        cond_val, vals, it = carry
        new_cond, new_vals = run_body(cond_val, vals, it)
        return (new_cond, new_vals, it + 1)

    final_cond, final_vals, _ = jax.lax.while_loop(
        cond_fn, body_fn, (cond, init_vals, jnp.int32(0)))
    return collect(final_cond, final_vals)


def _while_eager(blk, cond, xs, in_names, out_names, cond_name, rng):
    """Op-by-op interpreted loop — the analog of the reference's nested
    Executor (while_op.cc). Required for tensor-array bodies (growing
    Python lists); the Executor runs the whole program eagerly."""
    env = dict(zip(in_names, xs))
    env[cond_name] = cond

    def _alive(c):
        try:
            return bool(np.asarray(c).reshape(-1)[0])
        except jax.errors.TracerBoolConversionError:
            raise InvalidArgumentError(
                "While bodies with tensor arrays interpret eagerly and "
                "cannot run under jit/scan; use static_rnn/dynamic_rnn "
                "or beam search for compiled recurrence")

    it = 0
    while _alive(env[cond_name]):
        _run_sub_block(blk, env, jax.random.fold_in(rng, it))
        it += 1
    return [env[n] for n in out_names]


# ---------------------------------------------------------------------------
# static_rnn — lax.scan over a fixed-length time-major sequence
# (reference: the recurrent op built by layers.StaticRNN,
#  python/paddle/fluid/layers/control_flow.py:406)
# ---------------------------------------------------------------------------

@register("static_rnn", ["StepIn*", "Init*", "X*"], ["Out*", "LastMem*"],
          needs_rng=True)
def static_rnn(step_ins, inits, outers, *, sub_block, step_in_names,
               mem_pre_names, mem_new_names, out_names, outer_names, rng):
    blk = _tracing_block(sub_block)
    enforce(len(step_ins) > 0, "StaticRNN needs at least one step_input")
    seq_len = step_ins[0].shape[0]
    outer_env = dict(zip(outer_names, outers))

    def body(carry, scanned):
        t, xs = scanned
        env = dict(outer_env)
        env.update(zip(mem_pre_names, carry))
        env.update(zip(step_in_names, xs))
        _run_sub_block(blk, env, jax.random.fold_in(rng, t))
        new_carry = [env[n] for n in mem_new_names]
        outs = [env[n] for n in out_names]
        return new_carry, outs

    xs = (jnp.arange(seq_len), list(step_ins))
    last_mems, ys = jax.lax.scan(body, list(inits), xs)
    return list(ys), list(last_mems)


# ---------------------------------------------------------------------------
# dynamic_rnn — lax.scan over batch-major padded sequences + length mask
# (replaces the reference's LoD-driven DynamicRNN; variable length is
#  carried as an explicit lengths vector, the padded+mask redesign of
#  lod_tensor.h:110)
# ---------------------------------------------------------------------------

def _mask_like(active, val):
    # active: [batch] bool -> broadcastable to val [batch, ...]
    return active.reshape(active.shape + (1,) * (val.ndim - 1))


@register("dynamic_rnn", ["StepIn*", "Init*", "SeqLen", "X*"],
          ["Out*", "LastMem*"], nondiff=("SeqLen",), needs_rng=True)
def dynamic_rnn(step_ins, inits, seq_len, outers, *, sub_block,
                step_in_names, mem_pre_names, mem_new_names, out_names,
                outer_names, rng):
    blk = _tracing_block(sub_block)
    enforce(len(step_ins) > 0, "DynamicRNN needs at least one step_input")
    max_len = step_ins[0].shape[1]
    outer_env = dict(zip(outer_names, outers))
    # scan wants time-major
    xs_tm = [jnp.moveaxis(x, 1, 0) for x in step_ins]

    def body(carry, scanned):
        t, xs = scanned
        env = dict(outer_env)
        env.update(zip(mem_pre_names, carry))
        env.update(zip(step_in_names, xs))
        _run_sub_block(blk, env, jax.random.fold_in(rng, t))
        if seq_len is not None:
            active = t < seq_len  # [batch] bool
            new_carry = [jnp.where(_mask_like(active, n), n, p)
                         for p, n in zip(carry,
                                         (env[m] for m in mem_new_names))]
            outs = [jnp.where(_mask_like(active, env[n]), env[n],
                              jnp.zeros_like(env[n]))
                    for n in out_names]
        else:
            new_carry = [env[n] for n in mem_new_names]
            outs = [env[n] for n in out_names]
        return new_carry, outs

    xs = (jnp.arange(max_len), xs_tm)
    last_mems, ys = jax.lax.scan(body, list(inits), xs)
    # back to batch-major
    return ([jnp.moveaxis(y, 0, 1) for y in ys], list(last_mems))


# ---------------------------------------------------------------------------
# tensor arrays (reference: controlflow/tensor_array_read_write_op.cc,
# LoDTensorArray framework/lod_tensor_array.h) — eager mode only
# ---------------------------------------------------------------------------

@register("create_array", [], ["Out"], differentiable=False)
def create_array(*, dtype="float32"):
    return []


@register("array_write", ["X", "I", "Array"], ["Out"],
          differentiable=False, nondiff=("I", "Array"))
def array_write(x, i, array):
    arr = list(array) if array is not None else []
    idx = _concrete_index(i, "array_write")
    enforce(0 <= idx <= len(arr),
            "array_write index %d out of range [0, %d]" % (idx, len(arr)))
    if idx == len(arr):
        arr.append(x)
    else:
        arr[idx] = x
    return arr


@register("array_read", ["Array", "I"], ["Out"], differentiable=False,
          nondiff=("I",))
def array_read(array, i):
    idx = _concrete_index(i, "array_read")
    enforce(0 <= idx < len(array),
            "array_read index %d out of range [0, %d)" % (idx, len(array)))
    return array[idx]


@register("array_length", ["Array"], ["Out"], differentiable=False)
def array_length(array):
    return jnp.asarray([len(array)], dtype=jnp.int64)


@register("tensor_array_to_tensor", ["Array"], ["Out", "OutIndex"],
          differentiable=False)
def tensor_array_to_tensor(array, *, axis=0, use_stack=False):
    """Reference: operators/tensor_array_to_tensor_op.cc — stack or
    concat a LoDTensorArray; OutIndex records per-entry extents."""
    enforce(array, "tensor_array_to_tensor on an empty array")
    if use_stack:
        out = jnp.stack(array, axis=axis)
        index = jnp.full((len(array),), 1, jnp.int32)
    else:
        out = jnp.concatenate(array, axis=axis)
        index = jnp.asarray([t.shape[axis] for t in array], jnp.int32)
    return out, index
