"""Sequence ops over padded batch-major tensors + explicit lengths.

Reference: paddle/fluid/operators/sequence_ops/ (5.3k LoC: sequence_pool
_op, sequence_softmax_op, sequence_expand_op, sequence_pad_op,
sequence_unpad_op, sequence_reverse_op, sequence_concat_op,
sequence_slice_op, sequence_enumerate_op, sequence_expand_as_op) and
the LoD machinery they consume (framework/lod_tensor.h:110).

TPU-native redesign: the reference's LoD tensors carry ragged offsets
and every sequence op re-walks them on CPU/GPU. XLA wants static shapes,
so sequences are ``[batch, max_len, ...]`` padded tensors with an
explicit ``lengths`` int vector ([batch]); every op here is a masked
dense computation (MXU/VPU friendly, fusable). ``lengths=None`` means
"all rows full length". Bucketing in the data pipeline (reader.py)
keeps padding waste bounded — together these replace LoD end to end.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax
import numpy as np

from ..core.enforce import enforce
from .registry import register


def _time_mask(x, lengths, fill=0.0):
    """Mask [B, T, ...] x past per-row length with ``fill``."""
    if lengths is None:
        return x
    T = x.shape[1]
    m = jnp.arange(T)[None, :] < lengths[:, None]  # [B, T]
    m = m.reshape(m.shape + (1,) * (x.ndim - 2))
    return jnp.where(m, x, jnp.full_like(x, fill))


@register("sequence_pool", ["X", "SeqLen"], ["Out"], nondiff=("SeqLen",))
def sequence_pool(x, lengths, *, pool_type="average", pad_value=0.0):
    """[B, T, ...] -> [B, ...] pooled over the valid prefix (reference:
    sequence_ops/sequence_pool_op.cc; math/sequence_pooling.cc).
    Rows with length 0 produce ``pad_value``, as in the reference."""
    T = x.shape[1]
    pool_type = pool_type.lower()
    if lengths is None:
        n = jnp.full((x.shape[0],), T, x.dtype)
    else:
        n = jnp.maximum(lengths, 1).astype(x.dtype)
    n = n.reshape(n.shape + (1,) * (x.ndim - 2))
    if pool_type == "sum":
        out = _time_mask(x, lengths).sum(axis=1)
    elif pool_type == "average":
        out = _time_mask(x, lengths).sum(axis=1) / n
    elif pool_type == "sqrt":
        out = _time_mask(x, lengths).sum(axis=1) / jnp.sqrt(n)
    elif pool_type == "max":
        neg = jnp.finfo(x.dtype).min
        out = _time_mask(x, lengths, fill=neg).max(axis=1)
    elif pool_type == "first":
        out = x[:, 0]
    elif pool_type == "last":
        if lengths is None:
            out = x[:, -1]
        else:
            idx = jnp.maximum(lengths - 1, 0)
            idx = idx.reshape(idx.shape + (1,) * (x.ndim - 1))
            out = jnp.take_along_axis(x, idx, axis=1)[:, 0]
    else:
        raise ValueError("unknown pool_type %r" % pool_type)
    if lengths is not None:
        empty = (lengths == 0).reshape(
            lengths.shape + (1,) * (out.ndim - 1))
        out = jnp.where(empty, jnp.full_like(out, pad_value), out)
    return out


@register("sequence_softmax", ["X", "SeqLen"], ["Out"],
          nondiff=("SeqLen",))
def sequence_softmax(x, lengths):
    """Softmax over the time axis restricted to the valid prefix
    (reference: sequence_softmax_op.cc)."""
    if lengths is not None:
        T = x.shape[1]
        m = jnp.arange(T)[None, :] < lengths[:, None]
        m = m.reshape(m.shape + (1,) * (x.ndim - 2))
        x = jnp.where(m, x, jnp.full_like(x, jnp.finfo(x.dtype).min))
    out = jax.nn.softmax(x, axis=1)
    if lengths is not None:
        out = _time_mask(out, lengths)
    return out


def reverse_valid_prefix(x, lengths):
    """Reverse each row's valid prefix along the time axis (axis 1);
    padding positions stay in place. Shared by sequence_reverse and the
    is_reverse RNN paths (rnn_ops._scan_rnn)."""
    if lengths is None:
        return x[:, ::-1]
    T = x.shape[1]
    idx = jnp.arange(T)[None, :]
    rev = jnp.where(idx < lengths[:, None], lengths[:, None] - 1 - idx,
                    idx)
    rev = rev.reshape(rev.shape + (1,) * (x.ndim - 2))
    return jnp.take_along_axis(x, rev, axis=1)


@register("sequence_reverse", ["X", "SeqLen"], ["Out"],
          nondiff=("SeqLen",))
def sequence_reverse(x, lengths):
    """Reverse each row's valid prefix; padding stays in place
    (reference: sequence_reverse_op.h)."""
    return reverse_valid_prefix(x, lengths)


def _seq_expand_impl(x, y, y_lengths):
    """Repeat each row x[b] across y's time axis: x [B, ...] or
    [B, 1, ...] is broadcast to y's [B, T, ...], masked by y's
    lengths."""
    T = y.shape[1]
    if x.ndim == y.ndim:  # [B, 1, ...] -> squeeze the time axis
        x = x[:, 0]
    out = jnp.broadcast_to(x[:, None], (x.shape[0], T) + x.shape[1:])
    return _time_mask(out, y_lengths)


@register("sequence_expand", ["X", "Y", "SeqLenY"], ["Out"],
          nondiff=("Y", "SeqLenY"))
def sequence_expand(x, y, y_lengths, *, ref_level=0):
    """Reference: sequence_expand_op.cc, padded-layout specialization."""
    return _seq_expand_impl(x, y, y_lengths)


@register("sequence_expand_as", ["X", "Y", "SeqLenY"], ["Out"],
          nondiff=("Y", "SeqLenY"))
def sequence_expand_as(x, y, y_lengths):
    """Reference: sequence_expand_as_op.cc."""
    return _seq_expand_impl(x, y, y_lengths)


@register("sequence_pad", ["X", "SeqLen"], ["Out", "Length"],
          nondiff=("SeqLen",))
def sequence_pad(x, lengths, *, pad_value=0.0, padded_length=-1):
    """Normalize padding: positions past each row's length are set to
    ``pad_value``; optionally re-pad the time axis to ``padded_length``
    (reference: sequence_pad_op.cc — the ragged->padded boundary op; in
    the padded-native design it canonicalizes the pad region)."""
    if padded_length not in (-1, None) and padded_length != x.shape[1]:
        T = x.shape[1]
        enforce(padded_length >= T,
                "padded_length %d < current max_len %d"
                % (padded_length, T))
        pad_width = [(0, 0), (0, padded_length - T)] + \
            [(0, 0)] * (x.ndim - 2)
        x = jnp.pad(x, pad_width, constant_values=pad_value)
    out = _time_mask(x, lengths, fill=pad_value)
    if lengths is None:
        lengths = jnp.full((x.shape[0],), x.shape[1], jnp.int32)
    return out, lengths


@register("sequence_unpad", ["X", "Length"], ["Out"],
          nondiff=("Length",))
def sequence_unpad(x, lengths):
    """Zero out the pad region (reference: sequence_unpad_op.cc returns
    ragged data; the static-shape analog keeps [B, T, ...] and
    guarantees pad positions are exactly zero)."""
    return _time_mask(x, lengths)


@register("sequence_concat", ["X*", "SeqLen*"], ["Out", "OutLen"],
          nondiff=("SeqLen",))
def sequence_concat(xs, lengths):
    """Concatenate sequences along time per row (reference:
    sequence_concat_op.cc): row b of the output is
    x0[b,:l0] ++ x1[b,:l1] ++ ... followed by padding. An empty
    ``lengths`` list means every input row is full length."""
    enforce(len(xs) >= 1, "sequence_concat needs inputs")
    if not lengths:
        lengths = [None] * len(xs)
    enforce(len(lengths) == len(xs),
            "sequence_concat needs one lengths vector per input")
    B = xs[0].shape[0]
    T_out = sum(x.shape[1] for x in xs)
    dense = jnp.concatenate(
        [_time_mask(x, l) for x, l in zip(xs, lengths)], axis=1)
    # target position of each (input i, time t) element within the row
    offs = []
    total = jnp.zeros((B,), jnp.int32)
    for x, l in zip(xs, lengths):
        T = x.shape[1]
        li = (jnp.full((B,), T, jnp.int32) if l is None
              else l.astype(jnp.int32))
        offs.append(total[:, None] + jnp.arange(T)[None, :])
        total = total + li
    pos = jnp.concatenate(offs, axis=1)  # [B, T_out]
    valid = jnp.concatenate(
        [(jnp.arange(x.shape[1])[None, :] <
          (jnp.full((B, 1), x.shape[1], jnp.int32) if l is None
           else l[:, None])) for x, l in zip(xs, lengths)], axis=1)
    pos = jnp.where(valid, pos, T_out)  # dump invalid into scratch slot
    out = jnp.zeros((B, T_out + 1) + dense.shape[2:], dense.dtype)
    bidx = jnp.arange(B)[:, None]
    out = out.at[bidx, pos].set(dense)
    return out[:, :T_out], total


@register("sequence_slice", ["X", "Offset", "Length"], ["Out"],
          nondiff=("Offset", "Length"))
def sequence_slice(x, offset, length):
    """Per-row slice of the time axis (reference: sequence_slice_op.h):
    out[b] = x[b, offset[b]:offset[b]+length[b]] left-aligned, zero
    padded to max(length). Positions whose source index falls past the
    time axis yield 0 (the reference enforces offset+length in range;
    an in-graph check can't raise, so out-of-range reads are zeroed
    rather than silently duplicating the last step)."""
    offset = offset.reshape(-1).astype(jnp.int32)
    length = length.reshape(-1).astype(jnp.int32)
    T = x.shape[1]
    idx = offset[:, None] + jnp.arange(T)[None, :]  # [B, T]
    in_range = (idx >= 0) & (idx < T)
    idx_c = jnp.clip(idx, 0, T - 1)
    gathered = jnp.take_along_axis(
        x, idx_c.reshape(idx_c.shape + (1,) * (x.ndim - 2)), axis=1)
    m = in_range.reshape(in_range.shape + (1,) * (x.ndim - 2))
    gathered = jnp.where(m, gathered, jnp.zeros_like(gathered))
    return _time_mask(gathered, length)


@register("sequence_enumerate", ["X", "SeqLen"], ["Out"],
          differentiable=False, nondiff=("SeqLen",))
def sequence_enumerate(x, lengths, *, win_size, pad_value=0):
    """Sliding windows over the time axis (reference:
    sequence_enumerate_op.cc): out[b, t] = x[b, t:t+win_size], positions
    past the row length filled with pad_value. x: [B, T] int ids ->
    out: [B, T, win_size]."""
    B, T = x.shape[0], x.shape[1]
    starts = jnp.arange(T)[:, None] + jnp.arange(win_size)[None, :]
    win_idx = jnp.clip(starts, 0, T - 1)  # [T, W]
    out = x[:, win_idx]  # [B, T, W]
    if lengths is None:
        valid = (starts < T)[None]
    else:
        valid = starts[None, :, :] < lengths[:, None, None]
    return jnp.where(valid, out, jnp.full_like(out, pad_value))


@register("sequence_first_step", ["X", "SeqLen"], ["Out"],
          nondiff=("SeqLen",))
def sequence_first_step(x, lengths):
    return x[:, 0]


@register("sequence_last_step", ["X", "SeqLen"], ["Out"],
          nondiff=("SeqLen",))
def sequence_last_step(x, lengths):
    if lengths is None:
        return x[:, -1]
    idx = jnp.maximum(lengths - 1, 0)
    idx = idx.reshape(idx.shape + (1,) * (x.ndim - 1))
    return jnp.take_along_axis(x, idx, axis=1)[:, 0]


@register("sequence_conv", ["X", "Filter", "Lengths"], ["Out"],
          nondiff=("Lengths",))
def sequence_conv(x, filt, lengths, *, context_length,
                  context_start=None, context_stride=1):
    """Context-window convolution over padded sequences (reference:
    sequence_ops/sequence_conv_op.cc; math/context_project.h builds
    the im2col-style context matrix). x [B, T, D], filter
    [context_length*D, M]; frames outside the row's length (or the
    sequence bounds) contribute zeros."""
    B, T, D = x.shape
    start = -((context_length - 1) // 2) if context_start is None \
        else context_start
    if lengths is not None:
        x = _time_mask(x, lengths)
    frames = []
    for j in range(context_length):
        off = start + j
        if off < 0:
            shifted = jnp.pad(x[:, :T + off], ((0, 0), (-off, 0),
                                               (0, 0)))
        elif off > 0:
            shifted = jnp.pad(x[:, off:], ((0, 0), (0, off), (0, 0)))
        else:
            shifted = x
        frames.append(shifted)
    ctx = jnp.concatenate(frames, axis=2)        # [B, T, ctx*D]
    out = jnp.einsum("btc,cm->btm", ctx, filt)
    if lengths is not None:
        out = _time_mask(out, lengths)
    return out


@register("sequence_reshape", ["X", "Lengths"], ["Out", "OutLengths"],
          nondiff=("Lengths",))
def sequence_reshape(x, lengths, *, new_dim):
    """Trade time steps for feature width (reference:
    sequence_ops/sequence_reshape_op.cc): each row's l*D values regroup
    into (l*D/new_dim) steps of new_dim. Padded form: the dense
    [B, T*D] buffer reshapes to [B, T*D/new_dim, new_dim] and lengths
    scale by D/new_dim (every row's l*D must divide new_dim, as the
    reference enforces per sequence)."""
    B, T, D = x.shape
    total = T * D
    out = x.reshape(B, total // new_dim, new_dim)
    if lengths is None:
        new_len = None
    else:
        new_len = (lengths.astype(jnp.int32) * D) // new_dim
    return out, new_len


@register("sequence_scatter", ["X", "Ids", "Updates", "Lengths"],
          ["Out"], nondiff=("Ids", "Lengths"))
def sequence_scatter(x, ids, updates, lengths):
    """Per-row scatter-add of sequence updates (reference:
    sequence_ops/sequence_scatter_op.cc): out[b, ids[b,i]] +=
    updates[b,i] for i < lengths[b]. x [B, N]; ids/updates [B, L]."""
    B, L = ids.shape
    ids = ids.astype(jnp.int32)
    if lengths is not None:
        live = lax.broadcasted_iota(jnp.int32, (B, L), 1) < \
            lengths.reshape(-1, 1).astype(jnp.int32)
        safe = jnp.where(live, ids, x.shape[1])  # drop masked writes
    else:
        safe = ids
    bidx = lax.broadcasted_iota(jnp.int32, (B, L), 0)
    return x.at[bidx, safe].add(updates, mode="drop")
