"""Op library: every op is a pure JAX lowering registered in `registry`.

This package replaces the reference's paddle/fluid/operators/ (~164k LoC
of C++/CUDA, 404 registered ops). Capability classes map as:
  math_ops      <- elementwise/, activation_op, matmul/mul, blas
  tensor_ops    <- reshape/transpose/concat/... manipulation ops
  reduce_ops    <- reduce_ops/
  nn_ops        <- conv, pool, norm, dropout, lookup_table, losses
  sequence_ops  <- sequence_ops/ (LoD -> mask-based, static shapes)
  rnn_ops       <- lstm/gru ops (lax.scan replaces sequence2batch)
  optimizer_ops <- optimizers/
  metric_ops    <- metrics/
  init_ops      <- fill_constant/gaussian_random/... startup ops
  pallas/       <- fused/ + jit/ analog: hand-written TPU kernels
"""

from . import registry  # noqa: F401
from .registry import (all_op_types, get, has, register,  # noqa: F401
                       register_variant)

# Importing the modules registers the ops.
from . import math_ops  # noqa: F401,E402
from . import tensor_ops  # noqa: F401,E402
from . import reduce_ops  # noqa: F401,E402
from . import init_ops  # noqa: F401,E402
from . import nn_ops  # noqa: F401,E402
from . import optimizer_ops  # noqa: F401,E402
from . import metric_ops  # noqa: F401,E402
from . import control_flow_ops  # noqa: F401,E402
from . import sequence_ops  # noqa: F401,E402
from . import rnn_ops  # noqa: F401,E402
from . import beam_search_ops  # noqa: F401,E402
from . import detection_ops  # noqa: F401,E402
from . import quant_ops  # noqa: F401,E402
from . import loss_ops  # noqa: F401,E402
from . import vision_ops  # noqa: F401,E402
from . import fused_ops  # noqa: F401,E402
from . import collective_ops  # noqa: F401,E402
from . import py_func_op  # noqa: F401,E402
from . import pallas  # noqa: F401,E402
