"""Fake-quantization ops for quantization-aware training.

Reference: paddle/fluid/operators/fake_quantize_op.cc
(FakeQuantizeAbsMax, FakeQuantizeRangeAbsMax,
FakeQuantizeMovingAverageAbsMax, FakeChannelWiseQuantizeAbsMax) used by
contrib/slim/quantization/quantization_pass.py.

TPU-native notes: quantize-dequantize stays in float (int8 storage
happens only at freeze/export time — ConvertToInt8Pass), the
straight-through estimator is expressed as
``x + stop_gradient(qdq(x) - x)`` so the generic vjp machinery yields
the STE backward with no hand-written grad, and the moving-average
scale is a persistable var updated in-graph (the reference's
accumulator pattern).
"""

from __future__ import annotations

import jax.numpy as jnp
from jax import lax

from .registry import register


def _qdq(x, scale, bit_length):
    """Quantize-dequantize to ``bit_length`` signed levels at
    ``scale`` (maps [-scale, scale] onto the int grid)."""
    qmax = float(2 ** (bit_length - 1) - 1)
    s = jnp.maximum(scale, 1e-8)
    q = jnp.clip(jnp.round(x / s * qmax), -qmax, qmax)
    return q * s / qmax


def _ste(x, dequant):
    # straight-through estimator: identity gradient through the
    # round/clip (reference: fake_quantize_op grad passes through)
    return x + lax.stop_gradient(dequant - x)


@register("fake_quantize_dequantize_abs_max", ["X"],
          ["Out", "OutScale"])
def fake_quantize_dequantize_abs_max(x, *, bit_length=8):
    """Dynamic per-tensor scale = max|x| each step (the 'abs_max'
    activation/weight mode)."""
    scale = jnp.max(jnp.abs(x))
    return _ste(x, _qdq(x, scale, bit_length)), scale


@register("fake_channel_wise_quantize_dequantize_abs_max", ["X"],
          ["Out", "OutScale"])
def fake_channel_wise_quantize_dequantize_abs_max(x, *, bit_length=8,
                                                 quant_axis=0):
    """Per-output-channel scales for weights (the
    'channel_wise_abs_max' weight mode)."""
    axes = tuple(i for i in range(x.ndim) if i != quant_axis)
    scale = jnp.max(jnp.abs(x), axis=axes, keepdims=True)
    out = _ste(x, _qdq(x, scale, bit_length))
    return out, scale.reshape(-1)


@register("fake_quantize_dequantize_moving_average_abs_max",
          ["X", "InScale"], ["Out", "OutScale"],
          nondiff=("InScale",))
def fake_quantize_dequantize_moving_average_abs_max(
        x, in_scale, *, bit_length=8, moving_rate=0.9, is_test=False):
    """Activation quantization with a running abs-max scale
    (reference: FakeQuantizeMovingAverageAbsMax): scale_t =
    rate * scale_{t-1} + (1-rate) * max|x|; at test time the frozen
    scale is used as-is."""
    if is_test:
        scale = in_scale
    else:
        cur = jnp.max(jnp.abs(x))
        scale = jnp.where(in_scale > 0,
                          moving_rate * in_scale +
                          (1.0 - moving_rate) * cur, cur)
    out = _ste(x, _qdq(x, lax.stop_gradient(scale), bit_length))
    return out, scale


@register("dequantize_weight", ["X", "Scale"], ["Out"],
          nondiff=("Scale",))
def dequantize_weight(x, scale, *, bit_length=8, quant_axis=0):
    """int8 weight -> float (inference path after the freeze pass).
    Per-channel when Scale has >1 element, broadcasting along
    ``quant_axis``."""
    qmax = float(2 ** (bit_length - 1) - 1)
    xf = x.astype(jnp.float32)
    if scale.ndim and scale.shape[0] > 1:
        shape = [1] * xf.ndim
        shape[quant_axis] = scale.shape[0]
        return xf * scale.reshape(shape) / qmax
    return xf * scale / qmax
