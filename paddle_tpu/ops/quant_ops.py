"""Fake-quantization ops for quantization-aware training.

Reference: paddle/fluid/operators/fake_quantize_op.cc
(FakeQuantizeAbsMax, FakeQuantizeRangeAbsMax,
FakeQuantizeMovingAverageAbsMax, FakeChannelWiseQuantizeAbsMax) used by
contrib/slim/quantization/quantization_pass.py.

TPU-native notes: quantize-dequantize stays in float (int8 storage
happens only at freeze/export time — ConvertToInt8Pass), the
straight-through estimator is expressed as
``x + stop_gradient(qdq(x) - x)`` so the generic vjp machinery yields
the STE backward with no hand-written grad, and the moving-average
scale is a persistable var updated in-graph (the reference's
accumulator pattern).
"""

from __future__ import annotations

import jax.numpy as jnp
from jax import lax

from .registry import register


def _quant(x, scale, bit_length):
    """Map onto the signed int grid (kept in a float container — int8
    storage happens at export; XLA computes in f32/bf16 either way)."""
    qmax = float(2 ** (bit_length - 1) - 1)
    s = jnp.maximum(scale, 1e-8)
    return jnp.clip(jnp.round(x / s * qmax), -qmax, qmax)


def _qdq(x, scale, bit_length):
    """Quantize-dequantize to ``bit_length`` signed levels at
    ``scale`` (maps [-scale, scale] onto the int grid)."""
    qmax = float(2 ** (bit_length - 1) - 1)
    s = jnp.maximum(scale, 1e-8)
    return _quant(x, scale, bit_length) * s / qmax


def _ste(x, dequant):
    # straight-through estimator: identity gradient through the
    # round/clip (reference: fake_quantize_op grad passes through)
    return x + lax.stop_gradient(dequant - x)


@register("fake_quantize_dequantize_abs_max", ["X"],
          ["Out", "OutScale"])
def fake_quantize_dequantize_abs_max(x, *, bit_length=8):
    """Dynamic per-tensor scale = max|x| each step (the 'abs_max'
    activation/weight mode)."""
    scale = jnp.max(jnp.abs(x))
    return _ste(x, _qdq(x, scale, bit_length)), scale


@register("fake_channel_wise_quantize_dequantize_abs_max", ["X"],
          ["Out", "OutScale"])
def fake_channel_wise_quantize_dequantize_abs_max(x, *, bit_length=8,
                                                 quant_axis=0):
    """Per-output-channel scales for weights (the
    'channel_wise_abs_max' weight mode)."""
    axes = tuple(i for i in range(x.ndim) if i != quant_axis)
    scale = jnp.max(jnp.abs(x), axis=axes, keepdims=True)
    out = _ste(x, _qdq(x, scale, bit_length))
    return out, scale.reshape(-1)


@register("fake_quantize_dequantize_moving_average_abs_max",
          ["X", "InScale"], ["Out", "OutScale"],
          nondiff=("InScale",))
def fake_quantize_dequantize_moving_average_abs_max(
        x, in_scale, *, bit_length=8, moving_rate=0.9, is_test=False):
    """Activation quantization with a running abs-max scale
    (reference: FakeQuantizeMovingAverageAbsMax): scale_t =
    rate * scale_{t-1} + (1-rate) * max|x|; at test time the frozen
    scale is used as-is."""
    if is_test:
        scale = in_scale
    else:
        cur = jnp.max(jnp.abs(x))
        scale = jnp.where(in_scale > 0,
                          moving_rate * in_scale +
                          (1.0 - moving_rate) * cur, cur)
    out = _ste(x, _qdq(x, lax.stop_gradient(scale), bit_length))
    return out, scale


@register("dequantize_weight", ["X", "Scale"], ["Out"],
          nondiff=("Scale",))
def dequantize_weight(x, scale, *, bit_length=8, quant_axis=0):
    """int8 weight -> float (inference path after the freeze pass).
    Per-channel when Scale has >1 element, broadcasting along
    ``quant_axis``."""
    qmax = float(2 ** (bit_length - 1) - 1)
    xf = x.astype(jnp.float32)
    if scale.ndim and scale.shape[0] > 1:
        shape = [1] * xf.ndim
        shape[quant_axis] = scale.shape[0]
        return xf * scale.reshape(shape) / qmax
    return xf * scale / qmax


# ---------------------------------------------------------------------------
# Separate quantize / dequantize family (reference registers these 8
# alongside the combined QDQ ops; needed to load reference-style
# quantized programs): fake_quantize_op.cc:493-528,
# fake_dequantize_op.cc:186-193.
# ---------------------------------------------------------------------------

@register("fake_quantize_abs_max", ["X"], ["Out", "OutScale"])
def fake_quantize_abs_max(x, *, bit_length=8):
    """Reference: FakeQuantizeAbsMaxOp (fake_quantize_op.cc:493)."""
    scale = jnp.max(jnp.abs(x))
    return _ste(x, _quant(x, scale, bit_length)), scale


@register("fake_quantize_range_abs_max",
          ["X", "InScale", "Iter", "ScalesBuffer"],
          ["Out", "OutScale", "OutScalesBuffer", "IterOut"],
          nondiff=("InScale", "Iter", "ScalesBuffer"))
def fake_quantize_range_abs_max(x, in_scale, it, scales_buffer, *,
                                bit_length=8, window_size=10000,
                                is_test=False):
    """Reference: FakeQuantizeRangeAbsMaxOp (fake_quantize_op.cc:499):
    training scale = max of the last ``window_size`` batch abs-maxes
    (a rolling scales buffer); test time uses the frozen InScale."""
    if is_test:
        scale = in_scale
        out = _ste(x, _quant(x, scale, bit_length))
        return out, scale, scales_buffer, it
    cur = jnp.max(jnp.abs(x))
    pos = (it % scales_buffer.shape[0]).astype(jnp.int32)
    buf = scales_buffer.at[pos].set(cur)
    scale = jnp.max(buf)
    out = _ste(x, _quant(x, lax.stop_gradient(scale), bit_length))
    return out, scale, buf, it + 1


@register("fake_quantize_moving_average_abs_max",
          ["X", "InScale", "InAccum", "InState"],
          ["Out", "OutScale", "OutAccum", "OutState"],
          nondiff=("InScale", "InAccum", "InState"))
def fake_quantize_moving_average_abs_max(
        x, in_scale, in_accum, in_state, *, bit_length=8,
        moving_rate=0.9, is_test=False):
    """Reference: FakeQuantizeMovingAverageAbsMaxOp
    (fake_quantize_op.cc:505): accum/state running sums give the
    debiased moving-average scale."""
    if is_test:
        out = _ste(x, _quant(x, in_scale, bit_length))
        return out, in_scale, in_accum, in_state
    cur = jnp.max(jnp.abs(x))
    accum = moving_rate * in_accum + cur
    state = moving_rate * in_state + 1.0
    scale = accum / state
    out = _ste(x, _quant(x, lax.stop_gradient(scale), bit_length))
    return out, scale, accum, state


@register("fake_channel_wise_quantize_abs_max", ["X"],
          ["Out", "OutScale"])
def fake_channel_wise_quantize_abs_max(x, *, bit_length=8,
                                       quant_axis=0):
    """Reference: FakeChannelWiseQuantizeAbsMaxOp
    (fake_quantize_op.cc:521)."""
    axes = tuple(i for i in range(x.ndim) if i != quant_axis)
    scale = jnp.max(jnp.abs(x), axis=axes, keepdims=True)
    out = _ste(x, _quant(x, scale, bit_length))
    return out, scale.reshape(-1)


@register("moving_average_abs_max_scale",
          ["X", "InAccum", "InState"],
          ["Out", "OutScale", "OutAccum", "OutState"],
          nondiff=("InAccum", "InState"))
def moving_average_abs_max_scale(x, in_accum, in_state, *,
                                 moving_rate=0.9, is_test=False):
    """Observer only (reference: MovingAverageAbsMaxScaleOp,
    fake_quantize_op.cc:528): passes X through, tracks the scale."""
    if is_test:
        return x, in_accum / jnp.maximum(in_state, 1e-6), in_accum, \
            in_state
    cur = jnp.max(jnp.abs(x))
    accum = moving_rate * in_accum + cur
    state = moving_rate * in_state + 1.0
    return x, accum / state, accum, state


@register("fake_dequantize_max_abs", ["X", "Scale"], ["Out"],
          nondiff=("Scale",))
def fake_dequantize_max_abs(x, scale, *, max_range=127.0):
    """Reference: FakeDequantizeMaxAbsOp (fake_dequantize_op.cc:186):
    Out = X * Scale / max_range."""
    return x.astype(jnp.float32) * scale / max_range


@register("fake_channel_wise_dequantize_max_abs", ["X", "Scales*"],
          ["Out"], nondiff=("Scales",))
def fake_channel_wise_dequantize_max_abs(x, scales, *,
                                         quant_bits=(8,),
                                         quant_axis=0):
    """Reference: FakeChannelWiseDequantizeMaxAbsOp
    (fake_dequantize_op.cc:193): per-channel weight scales, plus an
    optional second per-tensor activation scale."""
    out = x.astype(jnp.float32)
    wscale = scales[0]
    qmax0 = float(2 ** (int(quant_bits[0]) - 1) - 1)
    shape = [1] * out.ndim
    shape[quant_axis] = -1
    out = out * wscale.reshape(shape) / qmax0
    if len(scales) > 1 and scales[1] is not None:
        qmax1 = float(2 ** (int(quant_bits[min(1, len(quant_bits) - 1)])
                            - 1) - 1)
        out = out * scales[1] / qmax1
    return out
