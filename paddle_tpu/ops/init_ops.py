"""Initializer ops (run inside the startup program).

Reference: paddle/fluid/operators/{fill_constant_op.cc,
gaussian_random_op.cc, uniform_random_op.cc,
truncated_gaussian_random_op.cc} and python/paddle/fluid/initializer.py
which appends these ops to the startup program.

RNG: deterministic counter-based keys (jax.random) derived from the
per-op ``seed`` attr; seed==0 draws from the executor-provided stream —
same contract as the reference (seed 0 = fresh randomness each startup
run).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from .registry import register


def _key(seed, rng):
    if seed:
        return jax.random.key(seed)
    return rng


@register("gaussian_random", [], ["Out"], differentiable=False,
          needs_rng=True)
def gaussian_random(*, shape, mean=0.0, std=1.0, seed=0, dtype="float32",
                    rng=None):
    return mean + std * jax.random.normal(_key(seed, rng), shape,
                                          dtype=dtype)


@register("uniform_random", [], ["Out"], differentiable=False,
          needs_rng=True)
def uniform_random(*, shape, min=-1.0, max=1.0, seed=0, dtype="float32",
                   rng=None):
    return jax.random.uniform(_key(seed, rng), shape, dtype=dtype,
                              minval=min, maxval=max)


@register("truncated_gaussian_random", [], ["Out"], differentiable=False,
          needs_rng=True)
def truncated_gaussian_random(*, shape, mean=0.0, std=1.0, seed=0,
                              dtype="float32", rng=None):
    # truncated to [-2 std, 2 std] around mean, as the reference kernel does
    return mean + std * jax.random.truncated_normal(
        _key(seed, rng), -2.0, 2.0, shape, dtype=dtype)


@register("randint", [], ["Out"], differentiable=False, needs_rng=True)
def randint(*, shape, low, high, seed=0, dtype="int64", rng=None):
    return jax.random.randint(_key(seed, rng), shape, low, high,
                              dtype=dtype)


@register("randperm", [], ["Out"], differentiable=False, needs_rng=True)
def randperm(*, n, seed=0, dtype="int64", rng=None):
    return jax.random.permutation(_key(seed, rng), n).astype(dtype)
