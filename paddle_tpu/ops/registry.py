"""Op registry.

Reference: paddle/fluid/framework/op_registry.h:66 (OpRegistry, the
REGISTER_OPERATOR / REGISTER_OP_*_KERNEL macros) and op_info.h:80
(OpInfoMap). The reference registers, per op, a C++ creator + CPU/CUDA
kernels + a grad-op maker + shape inference.

TPU-native redesign: one registration per op — a *pure JAX function* that
lowers the op to jnp/lax (and hence XLA HLO). This single function is
simultaneously:
  - the "kernel" for every backend (XLA compiles it for TPU/CPU),
  - the shape/dtype inference (tracing infers shapes),
  - the gradient definition (jax.vjp of the function replaces the
    reference's per-op GradOpMaker, grad_op_desc_maker.h).
Ops that want a hand-written TPU kernel register a pallas variant which
the executor substitutes when enabled (the analog of the reference's
kernel-type dispatch on library=CUDNN/MKLDNN, op_kernel_type.h).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Tuple

from ..core.enforce import AlreadyExistsError, NotFoundError, enforce


@dataclass
class OpDef:
    type: str
    fn: Callable
    # slot name, variadic flag. A variadic slot (declared "X*") receives a
    # list of values — reference OpDesc's name->var-list maps.
    input_slots: List[Tuple[str, bool]]
    output_slots: List[str]
    differentiable: bool = True
    # input slots excluded from differentiation (e.g. integer indices)
    nondiff_slots: frozenset = frozenset()
    needs_rng: bool = False
    # outputs ADD into existing env entries instead of overwriting —
    # for grad-producing ops (the reference's grad-accumulation sum)
    accumulate_outputs: bool = False
    # alternate lowerings, e.g. {"pallas": fn} — kernel-type dispatch analog
    variants: Dict[str, Callable] = field(default_factory=dict)

    def pick(self, library: Optional[str] = None) -> Callable:
        """Choose the lowering. ``library`` may be a plain library name
        ("pallas": every op that has that variant uses it) or a
        per-op mix "op_a:lib,op_b:lib" — the best-impl-WINS dispatch
        of the reference's jit kernel pool (operators/jit/README.en.md:
        per-kernel, not per-build, selection)."""
        if library and ":" in library:
            for item in library.split(","):
                op, _, lib = item.partition(":")
                if op == self.type and lib in self.variants:
                    return self.variants[lib]
            return self.fn
        if library and library in self.variants:
            return self.variants[library]
        return self.fn


_registry: Dict[str, OpDef] = {}


def register(type, inputs, outputs, differentiable=True, nondiff=(),
             needs_rng=False, accumulate_outputs=False):
    """Decorator registering an op implementation.

    ``inputs``: list of slot names; suffix ``*`` marks a variadic slot.
    The wrapped fn takes one positional arg per input slot (a list for
    variadic slots), attrs as keyword args, and returns one value per
    output slot (a single value if there is exactly one output).
    """
    input_slots = []
    for s in inputs:
        if s.endswith("*"):
            input_slots.append((s[:-1], True))
        else:
            input_slots.append((s, False))

    def deco(fn):
        if type in _registry:
            raise AlreadyExistsError("op %r already registered" % type)
        _registry[type] = OpDef(
            type=type, fn=fn, input_slots=input_slots,
            output_slots=list(outputs), differentiable=differentiable,
            nondiff_slots=frozenset(nondiff), needs_rng=needs_rng,
            accumulate_outputs=accumulate_outputs)
        return fn

    return deco


def register_variant(type, library):
    """Attach an alternate lowering (e.g. a pallas kernel) to an op."""

    def deco(fn):
        get(type).variants[library] = fn
        return fn

    return deco


def get(type) -> OpDef:
    try:
        return _registry[type]
    except KeyError:
        raise NotFoundError("op %r is not registered" % type)


def has(type) -> bool:
    return type in _registry


def all_op_types() -> List[str]:
    return sorted(_registry)
