"""Recurrent ops: LSTM / GRU over padded batch-major sequences.

Reference: paddle/fluid/operators/lstm_op.{cc,h} (dynamic_lstm),
gru_op.{cc,h} (dynamic_gru), lstm_unit_op.cc, gru_unit_op.cc and the
kernel library paddle/fluid/operators/math/{lstm_compute,gru_compute,
sequence2batch}.h.

TPU-native redesign: the reference reorders variable-length LoD
sequences into time-batched dense chunks (sequence2batch) and runs a
hand-written fused cell kernel per time step. Here sequences are padded
``[batch, max_len, ...]`` with an explicit per-example ``lengths``
vector; the whole recurrence is ONE ``lax.scan`` whose body is the cell
math — XLA fuses the gate arithmetic into the matmul, and steps past an
example's length neither update state nor emit output (masked), which
reproduces the LoD semantics with static shapes.

Gate layout convention (documented, differs from the reference's
internal [c,i,f,o] buffer layout): the projected input and the
hidden-hidden weight produce gates ordered ``[i, f, c, o]`` for LSTM and
``[u, r, c]`` for GRU. Equations follow the reference docs:

  LSTM (peepholes optional, lstm_op.cc doc block):
    i_t = sig(x_i + h_{t-1} W_i + w_ic * c_{t-1} + b_i)
    f_t = sig(x_f + h_{t-1} W_f + w_fc * c_{t-1} + b_f)
    c~  = tanh(x_c + h_{t-1} W_c + b_c)
    c_t = f_t * c_{t-1} + i_t * c~
    o_t = sig(x_o + h_{t-1} W_o + w_oc * c_t + b_o)
    h_t = o_t * tanh(c_t)

  GRU (gru_op.cc doc block):
    u_t = sig(x_u + h_{t-1} W_u + b_u)
    r_t = sig(x_r + h_{t-1} W_r + b_r)
    c~  = tanh(x_c + (r_t * h_{t-1}) W_c + b_c)
    h_t = (1 - u_t) * h_{t-1} + u_t * c~
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.enforce import enforce
from .registry import register

_ACT = {
    "sigmoid": jax.nn.sigmoid,
    "tanh": jnp.tanh,
    "relu": jax.nn.relu,
    "identity": lambda x: x,
}


def _mask(active, val):
    return active.reshape(active.shape + (1,) * (val.ndim - 1))


def _scan_rnn(cell, x, states, seq_len, is_reverse):
    """Run ``cell(x_t, states) -> (new_states, out)`` over time with
    length masking. x: [B, T, D] batch-major. Returns (outs [B,T,H],
    last_states)."""
    from .sequence_ops import reverse_valid_prefix
    B, T = x.shape[0], x.shape[1]
    if is_reverse:
        x = reverse_valid_prefix(x, seq_len)
    xs = jnp.moveaxis(x, 1, 0)  # [T, B, D]

    def body(carry, scanned):
        t, x_t = scanned
        new_states, out = cell(x_t, carry)
        if seq_len is not None:
            active = t < seq_len
            new_states = tuple(
                jnp.where(_mask(active, n), n, p)
                for p, n in zip(carry, new_states))
            out = jnp.where(_mask(active, out), out,
                            jnp.zeros_like(out))
        return new_states, out

    last, ys = jax.lax.scan(body, states, (jnp.arange(T), xs))
    ys = jnp.moveaxis(ys, 0, 1)  # [B, T, H]
    if is_reverse:
        ys = reverse_valid_prefix(ys, seq_len)
    return ys, last


@register("lstm", ["Input", "H0", "C0", "Weight", "Bias", "SeqLen"],
          ["Hidden", "Cell", "LastH", "LastC"], nondiff=("SeqLen",))
def lstm(x, h0, c0, weight, bias, seq_len, *, use_peepholes=False,
         is_reverse=False, gate_activation="sigmoid",
         cell_activation="tanh", candidate_activation="tanh"):
    """x: [B, T, 4H] (pre-projected input), weight: [H, 4H] hidden-hidden,
    bias: [4H] (+[3H] peepholes w_ic,w_fc,w_oc when use_peepholes)."""
    B, T, H4 = x.shape
    H = H4 // 4
    enforce(weight.shape == (H, 4 * H),
            "lstm weight must be [H, 4H], got %s" % (weight.shape,))
    gact = _ACT[gate_activation]
    cact = _ACT[cell_activation]
    candact = _ACT[candidate_activation]
    if h0 is None:
        h0 = jnp.zeros((B, H), x.dtype)
    if c0 is None:
        c0 = jnp.zeros((B, H), x.dtype)
    b_gates = bias[..., :4 * H].reshape(4 * H) if bias is not None else 0.0
    if use_peepholes and bias is not None:
        peep = bias.reshape(-1)[4 * H:]
        w_ic, w_fc, w_oc = peep[:H], peep[H:2 * H], peep[2 * H:3 * H]
    else:
        w_ic = w_fc = w_oc = None

    def cell(x_t, states):
        h_prev, c_prev = states
        gates = x_t + h_prev @ weight + b_gates
        gi, gf, gc, go = jnp.split(gates, 4, axis=-1)
        if w_ic is not None:
            gi = gi + w_ic * c_prev
            gf = gf + w_fc * c_prev
        i = gact(gi)
        f = gact(gf)
        c = f * c_prev + i * candact(gc)
        if w_oc is not None:
            go = go + w_oc * c
        o = gact(go)
        h = o * cact(c)
        return (h, c), jnp.concatenate([h, c], axis=-1)

    hc, (last_h, last_c) = _scan_rnn(cell, x, (h0, c0), seq_len,
                                     is_reverse)
    hidden, cellv = hc[..., :H], hc[..., H:]
    return hidden, cellv, last_h, last_c


@register("gru", ["Input", "H0", "Weight", "Bias", "SeqLen"],
          ["Hidden", "LastH"], nondiff=("SeqLen",))
def gru(x, h0, weight, bias, seq_len, *, is_reverse=False,
        gate_activation="sigmoid", candidate_activation="tanh"):
    """x: [B, T, 3H] (pre-projected), weight: [H, 3H] hidden-hidden laid
    out as [W_u | W_r | W_c], bias: [3H]."""
    B, T, H3 = x.shape
    H = H3 // 3
    enforce(weight.shape == (H, 3 * H),
            "gru weight must be [H, 3H], got %s" % (weight.shape,))
    gact = _ACT[gate_activation]
    candact = _ACT[candidate_activation]
    if h0 is None:
        h0 = jnp.zeros((B, H), x.dtype)
    b = bias.reshape(3 * H) if bias is not None else jnp.zeros(3 * H,
                                                               x.dtype)
    w_ur, w_c = weight[:, :2 * H], weight[:, 2 * H:]

    def cell(x_t, states):
        (h_prev,) = states
        x_ur, x_c = x_t[..., :2 * H], x_t[..., 2 * H:]
        ur = gact(x_ur + h_prev @ w_ur + b[:2 * H])
        u, r = ur[..., :H], ur[..., H:]
        c = candact(x_c + (r * h_prev) @ w_c + b[2 * H:])
        h = (1.0 - u) * h_prev + u * c
        return (h,), h

    hidden, (last_h,) = _scan_rnn(cell, x, (h0,), seq_len, is_reverse)
    return hidden, last_h


@register("lstm_unit", ["X", "HPrev", "CPrev", "Weight", "Bias"],
          ["H", "C"])
def lstm_unit(x, h_prev, c_prev, weight, bias, *, forget_bias=0.0):
    """Single LSTM step (reference: lstm_unit_op.cc). x: [B, 4H] gate
    pre-activations (the layer projects concat([x, h]) with one fc, as
    the reference does); Weight, if given, adds a separate hidden-hidden
    contribution [H, 4H]. Gates ordered [i, f, c, o]."""
    H = h_prev.shape[-1]
    gates = x
    if weight is not None:
        gates = gates + h_prev @ weight
    if bias is not None:
        gates = gates + bias.reshape(4 * H)
    gi, gf, gc, go = jnp.split(gates, 4, axis=-1)
    i = jax.nn.sigmoid(gi)
    f = jax.nn.sigmoid(gf + forget_bias)
    c = f * c_prev + i * jnp.tanh(gc)
    h = jax.nn.sigmoid(go) * jnp.tanh(c)
    return h, c


@register("gru_unit", ["X", "HPrev", "Weight", "Bias"], ["H"])
def gru_unit(x, h_prev, weight, bias, *, gate_activation="sigmoid",
             activation="tanh"):
    """Single GRU step (reference: gru_unit_op.cc). x: [B, 3H]."""
    H = h_prev.shape[-1]
    gact = _ACT[gate_activation]
    candact = _ACT[activation]
    b = bias.reshape(3 * H) if bias is not None else 0.0
    x = x + b
    w_ur, w_c = weight[:, :2 * H], weight[:, 2 * H:]
    ur = gact(x[..., :2 * H] + h_prev @ w_ur)
    u, r = ur[..., :H], ur[..., H:]
    c = candact(x[..., 2 * H:] + (r * h_prev) @ w_c)
    return (1.0 - u) * h_prev + u * c


@register("lstmp",
          ["Input", "H0", "C0", "Weight", "ProjWeight", "Bias",
           "SeqLen"],
          ["Projection", "Cell", "LastH", "LastC"],
          nondiff=("SeqLen",))
def lstmp(x, h0, c0, weight, proj_weight, bias, seq_len, *,
          use_peepholes=False, is_reverse=False,
          gate_activation="sigmoid", cell_activation="tanh",
          candidate_activation="tanh", proj_activation="tanh",
          proj_clip=0.0, cell_clip=0.0):
    """LSTM with a recurrent projection layer (reference:
    lstmp_op.cc — LSTMP, Sak et al.): the recurrent state is the
    PROJECTED hidden r = act_p(h @ P) with P [H, R]; weight is
    [R, 4H] (recurrence runs on the projection). x: [B, T, 4H]."""
    B, T, H4 = x.shape
    H = H4 // 4
    R = proj_weight.shape[1]
    enforce(weight.shape == (R, 4 * H),
            "lstmp weight must be [R, 4H], got %s" % (weight.shape,))
    gact = _ACT[gate_activation]
    cact = _ACT[cell_activation]
    candact = _ACT[candidate_activation]
    pact = _ACT[proj_activation]
    if h0 is None:
        r0 = jnp.zeros((B, R), x.dtype)
    else:
        r0 = h0 if h0.shape[-1] == R else pact(h0 @ proj_weight)
    if c0 is None:
        c0 = jnp.zeros((B, H), x.dtype)
    b_gates = bias[..., :4 * H].reshape(4 * H) if bias is not None \
        else 0.0
    if use_peepholes and bias is not None:
        peep = bias.reshape(-1)[4 * H:]
        w_ic, w_fc, w_oc = peep[:H], peep[H:2 * H], peep[2 * H:3 * H]
    else:
        w_ic = w_fc = w_oc = None

    def cell(x_t, states):
        r_prev, c_prev = states
        gates = x_t + r_prev @ weight + b_gates
        gi, gf, gc, go = jnp.split(gates, 4, axis=-1)
        if w_ic is not None:
            gi = gi + w_ic * c_prev
            gf = gf + w_fc * c_prev
        i = gact(gi)
        f = gact(gf)
        c = f * c_prev + i * candact(gc)
        if cell_clip > 0.0:
            c = jnp.clip(c, -cell_clip, cell_clip)
        if w_oc is not None:
            go = go + w_oc * c
        o = gact(go)
        h = o * cact(c)
        r = pact(h @ proj_weight)
        if proj_clip > 0.0:
            r = jnp.clip(r, -proj_clip, proj_clip)
        return (r, c), jnp.concatenate([r, c], axis=-1)

    rc, (last_r, last_c) = _scan_rnn(cell, x, (r0, c0), seq_len,
                                     is_reverse)
    proj, cellv = rc[..., :R], rc[..., R:]
    return proj, cellv, last_r, last_c
