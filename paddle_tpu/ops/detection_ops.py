"""Detection ops — SSD / Faster-RCNN / YOLOv3 op suite.

Reference: paddle/fluid/operators/detection/ (~13.6k LoC: prior_box_op,
density_prior_box_op, anchor_generator_op, box_coder_op, box_clip_op,
iou_similarity_op, bipartite_match_op, target_assign_op,
mine_hard_examples_op, multiclass_nms_op, yolo_box_op, yolov3_loss_op,
generate_proposals_op, rpn_target_assign_op, box_decoder_and_assign_op,
polygon_box_transform_op, collect/distribute_fpn_proposals_op) plus
operators/roi_align_op.cc, roi_pool_op.cc.

TPU-native redesign (NOT a port of the CPU kernels):

- **Padded batches replace LoD.** The reference threads variable-length
  ground-truth/ROI sets through LoD offsets; XLA wants static shapes, so
  every op here takes dense ``[N, M, ...]`` tensors where invalid slots
  are marked (gt boxes of all zeros, match index -1, score -1) and
  returns padded outputs plus a valid-count vector — the same
  ragged→padded+mask boundary the rest of the framework uses for
  sequences.
- **Fixed-trip-count selection replaces dynamic loops.** Greedy
  bipartite matching and NMS are data-dependent sequential algorithms;
  they become `lax.fori_loop`s with a static trip count (min(rows,cols)
  / nms_top_k) over masked argmax — compilable, differentiable-free
  selection with O(k) steps of vectorized work.
- **Everything jits and vmaps.** Per-image kernels are written for one
  image and lifted with jax.vmap — the analog of the reference's
  per-LoD-segment CPU loops, but batched on device.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax

from .registry import register

_EPS = 1e-10


# ---------------------------------------------------------------------------
# anchors / priors


@register("prior_box", ["Input", "Image"], ["Boxes", "Variances"],
          differentiable=False)
def prior_box(input, image, *, min_sizes, max_sizes=(), aspect_ratios=(1.0,),
              variances=(0.1, 0.1, 0.2, 0.2), flip=False, clip=False,
              step_w=0.0, step_h=0.0, offset=0.5,
              min_max_aspect_ratios_order=False):
    """SSD prior boxes (reference: detection/prior_box_op.cc, .h
    ExpandAspectRatios). Output [H, W, num_priors, 4] (normalized
    xmin,ymin,xmax,ymax) + same-shape variances."""
    feat_h, feat_w = input.shape[2], input.shape[3]
    img_h, img_w = image.shape[2], image.shape[3]

    ars = [1.0]
    for ar in aspect_ratios:
        if not any(abs(ar - a) < 1e-6 for a in ars):
            ars.append(float(ar))
            if flip:
                ars.append(1.0 / float(ar))

    sw = float(step_w) if step_w > 0 else img_w / feat_w
    sh = float(step_h) if step_h > 0 else img_h / feat_h

    # per-cell prior (w, h) list — static python loop, mirrors
    # prior_box_op.h but emitted once at trace time
    whs = []
    for s, ms in enumerate(min_sizes):
        ms = float(ms)
        if min_max_aspect_ratios_order:
            whs.append((ms, ms))
            if max_sizes and s < len(max_sizes):
                big = (ms * float(max_sizes[s])) ** 0.5
                whs.append((big, big))
            for ar in ars:
                if abs(ar - 1.0) < 1e-6:
                    continue
                whs.append((ms * ar ** 0.5, ms / ar ** 0.5))
        else:
            for ar in ars:
                whs.append((ms * ar ** 0.5, ms / ar ** 0.5))
            if max_sizes and s < len(max_sizes):
                big = (ms * float(max_sizes[s])) ** 0.5
                whs.append((big, big))
    wh = jnp.asarray(whs, jnp.float32)  # [P, 2]

    cx = (jnp.arange(feat_w, dtype=jnp.float32) + offset) * sw
    cy = (jnp.arange(feat_h, dtype=jnp.float32) + offset) * sh
    cxg, cyg = jnp.meshgrid(cx, cy)  # [H, W]
    cxg = cxg[:, :, None]
    cyg = cyg[:, :, None]
    half_w = wh[None, None, :, 0] / 2.0
    half_h = wh[None, None, :, 1] / 2.0
    boxes = jnp.stack([(cxg - half_w) / img_w, (cyg - half_h) / img_h,
                       (cxg + half_w) / img_w, (cyg + half_h) / img_h],
                      axis=-1)  # [H, W, P, 4]
    if clip:
        boxes = jnp.clip(boxes, 0.0, 1.0)
    var = jnp.broadcast_to(jnp.asarray(variances, jnp.float32),
                           boxes.shape)
    return boxes, var


@register("density_prior_box", ["Input", "Image"], ["Boxes", "Variances"],
          differentiable=False)
def density_prior_box(input, image, *, densities, fixed_sizes,
                      fixed_ratios,
                      variances=(0.1, 0.1, 0.2, 0.2), clip=False,
                      step_w=0.0, step_h=0.0, offset=0.5,
                      flatten_to_2d=False):
    """Densified priors (reference: density_prior_box_op.cc): each
    fixed_size spawns a density x density grid of shifted centers."""
    feat_h, feat_w = input.shape[2], input.shape[3]
    img_h, img_w = image.shape[2], image.shape[3]
    sw = float(step_w) if step_w > 0 else img_w / feat_w
    sh = float(step_h) if step_h > 0 else img_h / feat_h

    entries = []  # (shift_x, shift_y, w, h) per prior, static
    for size, dens in zip(fixed_sizes, densities):
        size, dens = float(size), int(dens)
        for ar in fixed_ratios:
            bw = size * float(ar) ** 0.5
            bh = size / float(ar) ** 0.5
            shift = size / dens
            for di in range(dens):
                for dj in range(dens):
                    ox = -size / 2.0 + shift / 2.0 + dj * shift
                    oy = -size / 2.0 + shift / 2.0 + di * shift
                    entries.append((ox, oy, bw, bh))
    ent = jnp.asarray(entries, jnp.float32)  # [P, 4]

    cx = (jnp.arange(feat_w, dtype=jnp.float32) + offset) * sw
    cy = (jnp.arange(feat_h, dtype=jnp.float32) + offset) * sh
    cxg, cyg = jnp.meshgrid(cx, cy)
    ccx = cxg[:, :, None] + ent[None, None, :, 0]
    ccy = cyg[:, :, None] + ent[None, None, :, 1]
    hw = ent[None, None, :, 2] / 2.0
    hh = ent[None, None, :, 3] / 2.0
    boxes = jnp.stack([(ccx - hw) / img_w, (ccy - hh) / img_h,
                       (ccx + hw) / img_w, (ccy + hh) / img_h], axis=-1)
    if clip:
        boxes = jnp.clip(boxes, 0.0, 1.0)
    var = jnp.broadcast_to(jnp.asarray(variances, jnp.float32),
                           boxes.shape)
    if flatten_to_2d:
        boxes = boxes.reshape(-1, 4)
        var = var.reshape(-1, 4)
    return boxes, var


@register("anchor_generator", ["Input"], ["Anchors", "Variances"],
          differentiable=False)
def anchor_generator(input, *, anchor_sizes=(64.0, 128.0, 256.0, 512.0),
                     aspect_ratios=(0.5, 1.0, 2.0),
                     variances=(0.1, 0.1, 0.2, 0.2),
                     stride=(16.0, 16.0), offset=0.5):
    """RPN anchors (reference: detection/anchor_generator_op.cc/.h) —
    output [H, W, A, 4] in image coordinates (unnormalized)."""
    feat_h, feat_w = input.shape[2], input.shape[3]
    sw, sh = float(stride[0]), float(stride[1])

    whs = []
    for ar in aspect_ratios:
        for size in anchor_sizes:
            area = sw * sh
            area_ratios = area / float(ar)
            base_w = round(area_ratios ** 0.5)
            base_h = round(base_w * float(ar))
            scale_w = float(size) / sw
            scale_h = float(size) / sh
            whs.append((scale_w * base_w, scale_h * base_h))
    wh = jnp.asarray(whs, jnp.float32)  # [A, 2]

    cx = (jnp.arange(feat_w, dtype=jnp.float32) + offset) * sw
    cy = (jnp.arange(feat_h, dtype=jnp.float32) + offset) * sh
    cxg, cyg = jnp.meshgrid(cx, cy)
    cxg, cyg = cxg[:, :, None], cyg[:, :, None]
    hw = wh[None, None, :, 0] / 2.0
    hh = wh[None, None, :, 1] / 2.0
    anchors = jnp.stack([cxg - hw, cyg - hh, cxg + hw, cyg + hh],
                        axis=-1)
    var = jnp.broadcast_to(jnp.asarray(variances, jnp.float32),
                           anchors.shape)
    return anchors, var


# ---------------------------------------------------------------------------
# box geometry


def _box_wh(box):
    # +1 conventions differ per op; detection box_coder/iou use the
    # normalized no-offset convention by default
    return box[..., 2] - box[..., 0], box[..., 3] - box[..., 1]


def _iou_matrix(x, y, box_normalized=True):
    """Pairwise IoU of x [N,4] vs y [M,4] → [N,M] (reference:
    iou_similarity_op.h IOUSimilarityFunctor)."""
    off = 0.0 if box_normalized else 1.0
    area_x = (x[:, 2] - x[:, 0] + off) * (x[:, 3] - x[:, 1] + off)
    area_y = (y[:, 2] - y[:, 0] + off) * (y[:, 3] - y[:, 1] + off)
    lt = jnp.maximum(x[:, None, :2], y[None, :, :2])
    rb = jnp.minimum(x[:, None, 2:], y[None, :, 2:])
    wh = jnp.maximum(rb - lt + off, 0.0)
    inter = wh[..., 0] * wh[..., 1]
    union = area_x[:, None] + area_y[None, :] - inter
    return jnp.where(union > 0, inter / jnp.maximum(union, _EPS), 0.0)


@register("iou_similarity", ["X", "Y"], ["Out"], differentiable=False)
def iou_similarity(x, y, *, box_normalized=True):
    """[N,4] x [M,4] -> [N,M], or batched [B,N,4] x [B,M,4] -> [B,N,M]."""
    if x.ndim == 3:
        return jax.vmap(
            lambda a, b: _iou_matrix(a, b, box_normalized))(x, y)
    return _iou_matrix(x, y, box_normalized)


@register("box_coder", ["PriorBox", "PriorBoxVar", "TargetBox"],
          ["OutputBox"], nondiff=("PriorBox", "PriorBoxVar"))
def box_coder(prior_box, prior_box_var, target_box, *,
              code_type="encode_center_size", box_normalized=True,
              axis=0, variance=()):
    """Encode/decode box deltas (reference: box_coder_op.cc/.h).

    encode: target [N,4] against priors [M,4] → [N,M,4]
    decode: deltas [N,M,4] (or [N,4] broadcast) + priors → boxes.
    Differentiable through TargetBox (deltas) so RPN/RCNN heads train
    through the decode."""
    off = 0.0 if box_normalized else 1.0
    pw = prior_box[:, 2] - prior_box[:, 0] + off
    ph = prior_box[:, 3] - prior_box[:, 1] + off
    pcx = prior_box[:, 0] + pw / 2.0
    pcy = prior_box[:, 1] + ph / 2.0

    if prior_box_var is not None:
        pvar = prior_box_var
    elif len(variance):
        pvar = jnp.broadcast_to(jnp.asarray(variance, jnp.float32),
                                prior_box.shape)
    else:
        pvar = jnp.ones_like(prior_box)

    if code_type == "encode_center_size":
        tw = target_box[:, 2] - target_box[:, 0] + off
        th = target_box[:, 3] - target_box[:, 1] + off
        tcx = target_box[:, 0] + tw / 2.0
        tcy = target_box[:, 1] + th / 2.0
        # [N, M]
        dx = (tcx[:, None] - pcx[None, :]) / pw[None, :]
        dy = (tcy[:, None] - pcy[None, :]) / ph[None, :]
        dw = jnp.log(jnp.maximum(tw[:, None] / pw[None, :], _EPS))
        dh = jnp.log(jnp.maximum(th[:, None] / ph[None, :], _EPS))
        out = jnp.stack([dx, dy, dw, dh], axis=-1) / pvar[None, :, :]
        return out
    elif code_type == "decode_center_size":
        t = target_box
        if t.ndim == 2:
            t = t[:, None, :]
        # axis=0: priors broadcast over rows; axis=1: over columns
        if axis == 0:
            pw_, ph_, pcx_, pcy_ = (pw[None, :], ph[None, :],
                                    pcx[None, :], pcy[None, :])
            pv = pvar[None, :, :]
        else:
            pw_, ph_, pcx_, pcy_ = (pw[:, None], ph[:, None],
                                    pcx[:, None], pcy[:, None])
            pv = pvar[:, None, :]
        d = t * pv
        cx = d[..., 0] * pw_ + pcx_
        cy = d[..., 1] * ph_ + pcy_
        w = jnp.exp(d[..., 2]) * pw_
        h = jnp.exp(d[..., 3]) * ph_
        return jnp.stack([cx - w / 2.0, cy - h / 2.0,
                          cx + w / 2.0 - off, cy + h / 2.0 - off],
                         axis=-1)
    raise ValueError("unknown code_type %r" % code_type)


@register("box_clip", ["Input", "ImInfo"], ["Output"],
          nondiff=("ImInfo",))
def box_clip(input, im_info, *_, **__):
    """Clip boxes to image bounds (reference: box_clip_op.h). Boxes
    [N, M, 4] with im_info [N, 3] (h, w, scale)."""
    h = im_info[:, 0] / im_info[:, 2]
    w = im_info[:, 1] / im_info[:, 2]
    zero = jnp.zeros_like(h)
    maxes = jnp.stack([w - 1, h - 1, w - 1, h - 1], -1)[:, None, :]
    mins = jnp.stack([zero, zero, zero, zero], -1)[:, None, :]
    return jnp.clip(input, mins, maxes)


@register("polygon_box_transform", ["Input"], ["Output"],
          differentiable=False)
def polygon_box_transform(input):
    """Quad offsets → absolute corner coordinates (reference:
    polygon_box_transform_op.cc — EAST-style geometry maps). Input
    [N, 8k, H, W]: channel 2i is an x-offset, 2i+1 a y-offset, each
    relative to the pixel's (4*col, 4*row) position."""
    n, c, h, w = input.shape
    col = jnp.arange(w, dtype=input.dtype)[None, None, None, :] * 4.0
    row = jnp.arange(h, dtype=input.dtype)[None, None, :, None] * 4.0
    is_x = (jnp.arange(c) % 2 == 0)[None, :, None, None]
    return jnp.where(is_x, col - input, row - input)


# ---------------------------------------------------------------------------
# matching / target assignment


def _bipartite_match_one(dist, match_type, overlap_threshold):
    """Greedy bipartite match for one image: dist [N, M] (rows =
    ground-truth, cols = priors). Returns (match_idx [M] int32 row or
    -1, match_dist [M]). Reference: bipartite_match_op.cc
    BipartiteMatchFunctor — iteratively takes the global max of the
    remaining matrix; fixed trip count min(N, M)."""
    n, m = dist.shape
    neg = jnp.asarray(-1.0, dist.dtype)

    def body(_, state):
        d, midx, mdist = state
        flat = jnp.argmax(d)
        i, j = flat // m, flat % m
        best = d[i, j]
        take = best > 0
        midx = jnp.where(take, midx.at[j].set(i.astype(jnp.int32)), midx)
        mdist = jnp.where(take, mdist.at[j].set(best), mdist)
        # knock out the matched row and column
        d = jnp.where(take, d.at[i, :].set(neg).at[:, j].set(neg), d)
        return d, midx, mdist

    init = (dist, jnp.full((m,), -1, jnp.int32),
            jnp.zeros((m,), dist.dtype))
    _, midx, mdist = lax.fori_loop(0, min(n, m), body, init)

    if match_type == "per_prediction":
        # unmatched columns take their argmax row if above threshold
        best_row = jnp.argmax(dist, axis=0).astype(jnp.int32)
        best_val = jnp.max(dist, axis=0)
        extra = (midx < 0) & (best_val >= overlap_threshold)
        midx = jnp.where(extra, best_row, midx)
        mdist = jnp.where(extra, best_val, mdist)
    return midx, mdist


@register("bipartite_match", ["DistMat"],
          ["ColToRowMatchIndices", "ColToRowMatchDist"],
          differentiable=False)
def bipartite_match(dist_mat, *, match_type="bipartite",
                    dist_threshold=0.5):
    """Batched greedy bipartite matching. DistMat [B, N, M] (padded
    ground-truth rows must be all-zero so they never win a match);
    outputs [B, M]."""
    if dist_mat.ndim == 2:
        dist_mat = dist_mat[None]
    fn = functools.partial(_bipartite_match_one,
                           match_type=match_type,
                           overlap_threshold=dist_threshold)
    return jax.vmap(fn)(dist_mat)


@register("target_assign", ["X", "MatchIndices", "NegIndices"],
          ["Out", "OutWeight"],
          nondiff=("MatchIndices", "NegIndices"))
def target_assign(x, match_indices, neg_indices, *, mismatch_value=0.0):
    """Gather per-prior targets by match index (reference:
    target_assign_op.h). x [B, N, K] (entity targets), match_indices
    [B, M] → out [B, M, K]; weight 1 where matched (or listed in
    neg_indices mask [B, M]), else mismatch_value/0.

    LoD redesign: the reference's NegIndices is a ragged index list;
    here it is an optional [B, M] 0/1 mask."""
    b, m = match_indices.shape
    k = x.shape[2]
    idx = jnp.maximum(match_indices, 0)
    out = jnp.take_along_axis(x, idx[:, :, None].repeat(k, axis=2),
                              axis=1)
    matched = (match_indices >= 0)[:, :, None]
    out = jnp.where(matched, out,
                    jnp.asarray(mismatch_value, x.dtype))
    weight = matched.astype(jnp.float32)
    if neg_indices is not None:
        weight = jnp.maximum(weight,
                             neg_indices[:, :, None].astype(jnp.float32))
    return out, weight


@register("mine_hard_examples",
          ["ClsLoss", "LocLoss", "MatchIndices", "MatchDist"],
          ["NegIndices", "UpdatedMatchIndices"], differentiable=False)
def mine_hard_examples(cls_loss, loc_loss, match_indices, match_dist, *,
                       neg_pos_ratio=3.0, neg_dist_threshold=0.5,
                       mining_type="max_negative", sample_size=0):
    """Hard-negative mining (reference: mine_hard_examples_op.cc).
    Selects the highest-loss negatives per image, at most
    neg_pos_ratio * num_pos (or sample_size). Returns a [B, M] 0/1
    negative mask (the LoD NegIndices redesign) and match indices with
    unselected negatives left at -1 (selected stay -1 too — they are
    negatives; the op only *selects*, mirroring UpdatedMatchIndices)."""
    loss = cls_loss + (loc_loss if loc_loss is not None else 0.0)
    is_neg = (match_indices < 0) & (match_dist < neg_dist_threshold)
    num_pos = jnp.sum((match_indices >= 0).astype(jnp.int32), axis=1)
    if mining_type == "max_negative":
        limit = (num_pos.astype(jnp.float32) * neg_pos_ratio)
    else:  # hard_example
        limit = jnp.full_like(num_pos, float(sample_size or 0),
                              jnp.float32)
    neg_loss = jnp.where(is_neg, loss, -jnp.inf)
    order = jnp.argsort(-neg_loss, axis=1)
    ranks = jnp.argsort(order, axis=1).astype(jnp.float32)
    selected = is_neg & (ranks < limit[:, None])
    upd = jnp.where(selected, -1, match_indices)
    return selected.astype(jnp.int32), upd


# ---------------------------------------------------------------------------
# NMS family


def _nms_mask(boxes, scores, valid, iou_threshold, top_k,
              normalized=True, eta=1.0):
    """Fixed-size NMS for one class: boxes [M,4], scores [M]. Sorts by
    score, keeps at most top_k, suppresses IoU > threshold against any
    earlier kept box. Returns keep mask aligned with the SORTED order
    plus the sort indices. O(top_k) sequential steps over vectorized
    suppression rows — the TPU formulation of the reference's
    NMSFast (multiclass_nms_op.cc), including the adaptive-threshold
    ``eta`` shrink (threshold *= eta after each kept box while > 0.5)."""
    m = boxes.shape[0]
    k = min(top_k, m) if top_k > 0 else m
    order = jnp.argsort(-jnp.where(valid, scores, -jnp.inf))
    sb = boxes[order][:k]
    sv = valid[order][:k] & (scores[order][:k] > -jnp.inf)
    iou = _iou_matrix(sb, sb, box_normalized=normalized)

    def body(i, state):
        keep, thresh = state
        sup = jnp.any(keep & (jnp.arange(k) < i) & (iou[i] > thresh))
        kept = sv[i] & ~sup
        if eta < 1.0:
            thresh = jnp.where(kept & (thresh > 0.5), thresh * eta,
                               thresh)
        return keep.at[i].set(kept), thresh

    keep, _ = lax.fori_loop(
        0, k, body,
        (jnp.zeros((k,), bool), jnp.asarray(iou_threshold, jnp.float32)))
    return keep, order[:k]


def _multiclass_nms_one(bboxes, scores, *, background_label, score_threshold,
                        nms_top_k, nms_threshold, nms_eta, keep_top_k,
                        normalized):
    """One image: bboxes [M, 4] (shared across classes) or [C, M, 4],
    scores [C, M]. Returns (out [keep_top_k, 6], count)."""
    c, m = scores.shape
    shared = bboxes.ndim == 2
    if c == 1 and background_label == 0:
        raise ValueError("multiclass_nms: all classes are background")
    results = []  # per class: (label, score, box, keep)
    for cls in range(c):
        if cls == background_label:
            continue
        cls_scores = scores[cls]
        cls_boxes = bboxes if shared else bboxes[cls]
        valid = cls_scores > score_threshold
        keep, order = _nms_mask(cls_boxes, cls_scores, valid,
                                nms_threshold, nms_top_k,
                                normalized=normalized, eta=nms_eta)
        results.append((cls, cls_scores[order], cls_boxes[order], keep))

    labels = jnp.concatenate([
        jnp.full(r[3].shape, r[0], jnp.float32) for r in results])
    scs = jnp.concatenate([r[1] for r in results])
    bxs = jnp.concatenate([r[2] for r in results], axis=0)
    keeps = jnp.concatenate([r[3] for r in results])

    scs = jnp.where(keeps, scs, -jnp.inf)
    k = min(keep_top_k if keep_top_k > 0 else scs.shape[0],
            scs.shape[0])
    top = jnp.argsort(-scs)[:k]
    sel_valid = scs[top] > -jnp.inf
    out = jnp.concatenate([
        labels[top][:, None], jnp.where(sel_valid, scs[top], 0.0)[:, None],
        bxs[top]], axis=1)
    out = jnp.where(sel_valid[:, None], out, -1.0)
    return out, jnp.sum(sel_valid.astype(jnp.int32))


@register("multiclass_nms", ["BBoxes", "Scores"], ["Out", "NmsRoisNum"],
          differentiable=False)
def multiclass_nms(bboxes, scores, *, background_label=0,
                   score_threshold=0.0, nms_top_k=-1, nms_threshold=0.3,
                   nms_eta=1.0, keep_top_k=-1, normalized=True):
    """Batched multi-class NMS (reference: multiclass_nms_op.cc).
    bboxes [N, M, 4], scores [N, C, M] → padded Out [N, K, 6]
    (label, score, x1, y1, x2, y2; -1 rows are padding) + per-image
    valid counts [N] (the LoD → padded+count redesign)."""
    fn = functools.partial(
        _multiclass_nms_one, background_label=background_label,
        score_threshold=score_threshold, nms_top_k=nms_top_k,
        nms_threshold=nms_threshold, nms_eta=nms_eta,
        keep_top_k=keep_top_k, normalized=normalized)
    return jax.vmap(fn)(bboxes, scores)


@register("generate_proposals",
          ["Scores", "BboxDeltas", "ImInfo", "Anchors", "Variances"],
          ["RpnRois", "RpnRoiProbs", "RpnRoisNum"], differentiable=False)
def generate_proposals(scores, bbox_deltas, im_info, anchors, variances,
                       *, pre_nms_top_n=6000, post_nms_top_n=1000,
                       nms_thresh=0.5, min_size=0.1, eta=1.0):
    """RPN proposal generation (reference: generate_proposals_op.cc).
    scores [N, A, H, W], bbox_deltas [N, 4A, H, W], anchors
    [H, W, A, 4] → padded RpnRois [N, post_nms_top_n, 4] + counts.

    Static-shape pipeline: top-pre_nms scores → decode → clip →
    min-size filter (mask) → fixed-size NMS → top-post_nms."""
    n, a, h, w = scores.shape
    anc = anchors.reshape(-1, 4)
    var = variances.reshape(-1, 4)
    total = a * h * w

    def one(sc, bd, info):
        # [A,H,W] → [H,W,A] flattened to match anchors layout
        sc = sc.transpose(1, 2, 0).reshape(-1)
        bd = bd.reshape(a, 4, h, w).transpose(2, 3, 0, 1).reshape(-1, 4)
        k = min(pre_nms_top_n, total) if pre_nms_top_n > 0 else total
        top = jnp.argsort(-sc)[:k]
        sc_k, bd_k, anc_k, var_k = sc[top], bd[top], anc[top], var[top]
        # decode (same math as box_coder decode with per-anchor var)
        aw = anc_k[:, 2] - anc_k[:, 0] + 1.0
        ah = anc_k[:, 3] - anc_k[:, 1] + 1.0
        acx = anc_k[:, 0] + aw / 2.0
        acy = anc_k[:, 1] + ah / 2.0
        d = bd_k * var_k
        cx = d[:, 0] * aw + acx
        cy = d[:, 1] * ah + acy
        bw = jnp.exp(jnp.minimum(d[:, 2], 10.0)) * aw
        bh = jnp.exp(jnp.minimum(d[:, 3], 10.0)) * ah
        props = jnp.stack([cx - bw / 2.0, cy - bh / 2.0,
                           cx + bw / 2.0 - 1.0, cy + bh / 2.0 - 1.0], -1)
        # clip to image
        ih, iw = info[0], info[1]
        props = jnp.clip(props,
                         jnp.zeros(4, props.dtype),
                         jnp.asarray([iw - 1, ih - 1, iw - 1, ih - 1],
                                     props.dtype))
        # filter boxes smaller than min_size * scale
        ms = jnp.maximum(min_size * info[2], 1.0)
        pw = props[:, 2] - props[:, 0] + 1.0
        ph = props[:, 3] - props[:, 1] + 1.0
        keep_sz = (pw >= ms) & (ph >= ms)
        # proposals use pixel coordinates (+1 width convention); ALL
        # pre-NMS candidates stay eligible (top_k = k), so boxes below
        # rank post_nms_top_n can replace suppressed ones — matching
        # the reference's full NMS scan over pre_nms_top_n boxes
        keep, order = _nms_mask(props, sc_k, keep_sz, nms_thresh,
                                k, normalized=False, eta=eta)
        final_sc = jnp.where(keep, sc_k[order], -jnp.inf)
        take = jnp.argsort(-final_sc)[:post_nms_top_n]
        ok = final_sc[take] > -jnp.inf
        rois = jnp.where(ok[:, None], props[order][take], 0.0)
        probs = jnp.where(ok, sc_k[order][take], 0.0)
        return rois, probs, jnp.sum(ok.astype(jnp.int32))

    return jax.vmap(one)(scores, bbox_deltas, im_info)


# ---------------------------------------------------------------------------
# YOLO


@register("yolo_box", ["X", "ImgSize"], ["Boxes", "Scores"],
          differentiable=False)
def yolo_box(x, img_size, *, anchors, class_num, conf_thresh=0.01,
             downsample_ratio=32, clip_bbox=True):
    """Decode YOLOv3 head output (reference: yolo_box_op.h). x
    [N, A*(5+C), H, W], img_size [N, 2] (h, w) → boxes
    [N, A*H*W, 4], scores [N, A*H*W, C]. Low-confidence boxes are
    zeroed (the reference sets them to zero rather than pruning —
    already static-shape-friendly)."""
    n, _, h, w = x.shape
    na = len(anchors) // 2
    anc = jnp.asarray(anchors, jnp.float32).reshape(na, 2)
    x = x.reshape(n, na, 5 + class_num, h, w)

    grid_x = jnp.arange(w, dtype=jnp.float32)[None, None, None, :]
    grid_y = jnp.arange(h, dtype=jnp.float32)[None, None, :, None]
    pred_xy_x = (jax.nn.sigmoid(x[:, :, 0]) + grid_x) / w
    pred_xy_y = (jax.nn.sigmoid(x[:, :, 1]) + grid_y) / h
    input_h = downsample_ratio * h
    input_w = downsample_ratio * w
    pred_w = jnp.exp(x[:, :, 2]) * anc[None, :, 0, None, None] / input_w
    pred_h = jnp.exp(x[:, :, 3]) * anc[None, :, 1, None, None] / input_h
    conf = jax.nn.sigmoid(x[:, :, 4])
    probs = jax.nn.sigmoid(x[:, :, 5:]) * conf[:, :, None]

    img_h = img_size[:, 0].astype(jnp.float32)[:, None, None, None]
    img_w = img_size[:, 1].astype(jnp.float32)[:, None, None, None]
    x1 = (pred_xy_x - pred_w / 2.0) * img_w
    y1 = (pred_xy_y - pred_h / 2.0) * img_h
    x2 = (pred_xy_x + pred_w / 2.0) * img_w
    y2 = (pred_xy_y + pred_h / 2.0) * img_h
    if clip_bbox:
        x1 = jnp.clip(x1, 0.0, img_w - 1)
        y1 = jnp.clip(y1, 0.0, img_h - 1)
        x2 = jnp.clip(x2, 0.0, img_w - 1)
        y2 = jnp.clip(y2, 0.0, img_h - 1)
    keep = conf >= conf_thresh
    boxes = jnp.stack([x1, y1, x2, y2], axis=-1) * keep[..., None]
    boxes = boxes.reshape(n, -1, 4)
    scores = (probs * keep[:, :, None]).transpose(
        0, 1, 3, 4, 2).reshape(n, -1, class_num)
    return boxes, scores


def _sigmoid_bce(logit, label):
    return jnp.maximum(logit, 0) - logit * label + \
        jnp.log1p(jnp.exp(-jnp.abs(logit)))


@register("yolov3_loss", ["X", "GTBox", "GTLabel", "GTScore"],
          ["Loss"], nondiff=("GTBox", "GTLabel", "GTScore"))
def yolov3_loss(x, gt_box, gt_label, gt_score, *, anchors, anchor_mask,
                class_num, ignore_thresh=0.7, downsample_ratio=32,
                use_label_smooth=True):
    """YOLOv3 training loss (reference: yolov3_loss_op.h). x
    [N, A*(5+C), H, W]; gt_box [N, B, 4] (cx, cy, w, h normalized,
    all-zero rows are padding), gt_label [N, B] int; gt_score [N, B]
    (mixup weight, None → 1). Returns per-image loss [N].

    Differentiable through X: the whole target construction is
    select/scatter on static shapes, so the generic vjp covers the
    backward (the reference hand-writes the CPU gradient).
    """
    n, _, h, w = x.shape
    mask = list(anchor_mask)
    na = len(mask)
    anc = jnp.asarray(anchors, jnp.float32).reshape(-1, 2)
    anc_m = anc[jnp.asarray(mask)]
    input_size = downsample_ratio * h
    nb = gt_box.shape[1]

    x = x.reshape(n, na, 5 + class_num, h, w)
    px, py = x[:, :, 0], x[:, :, 1]
    pw, ph = x[:, :, 2], x[:, :, 3]
    pobj = x[:, :, 4]
    pcls = x[:, :, 5:]

    gt_valid = (gt_box[..., 2] > 0) & (gt_box[..., 3] > 0)  # [N, B]
    if gt_score is None:
        gt_score = jnp.ones(gt_label.shape, jnp.float32)

    # --- objectness ignore mask: pred boxes with IoU > thresh vs any gt
    grid_x = jnp.arange(w, dtype=jnp.float32)[None, None, None, :]
    grid_y = jnp.arange(h, dtype=jnp.float32)[None, None, :, None]
    bx = (jax.nn.sigmoid(px) + grid_x) / w
    by = (jax.nn.sigmoid(py) + grid_y) / h
    bw = jnp.exp(pw) * anc_m[None, :, 0, None, None] / input_size
    bh = jnp.exp(ph) * anc_m[None, :, 1, None, None] / input_size

    pred = jnp.stack([bx - bw / 2, by - bh / 2, bx + bw / 2,
                      by + bh / 2], axis=-1)  # [N,A,H,W,4]
    gx1 = gt_box[..., 0] - gt_box[..., 2] / 2
    gy1 = gt_box[..., 1] - gt_box[..., 3] / 2
    gx2 = gt_box[..., 0] + gt_box[..., 2] / 2
    gy2 = gt_box[..., 1] + gt_box[..., 3] / 2
    gt_c = jnp.stack([gx1, gy1, gx2, gy2], axis=-1)  # [N,B,4]

    def img_iou(p, g, gv):
        pm = p.reshape(-1, 4)
        m = _iou_matrix(pm, g)
        m = jnp.where(gv[None, :], m, 0.0)
        return jnp.max(m, axis=1).reshape(p.shape[:-1])

    best_iou = jax.vmap(img_iou)(pred, gt_c, gt_valid)  # [N,A,H,W]
    ignore = best_iou > ignore_thresh

    # --- per-gt responsible cell + best anchor (shape IoU vs ALL
    # anchors; only anchors in this head's mask contribute targets)
    gw = gt_box[..., 2] * input_size
    gh = gt_box[..., 3] * input_size
    inter = jnp.minimum(gw[..., None], anc[None, None, :, 0]) * \
        jnp.minimum(gh[..., None], anc[None, None, :, 1])
    union = gw[..., None] * gh[..., None] + \
        anc[None, None, :, 0] * anc[None, None, :, 1] - inter
    shape_iou = inter / jnp.maximum(union, _EPS)  # [N,B,num_anchors]
    best_anchor = jnp.argmax(shape_iou, axis=-1)  # [N,B]

    gi = jnp.clip((gt_box[..., 0] * w).astype(jnp.int32), 0, w - 1)
    gj = jnp.clip((gt_box[..., 1] * h).astype(jnp.int32), 0, h - 1)

    # map best_anchor to index within this head's mask (-1 if absent)
    mask_arr = jnp.asarray(mask)
    in_mask = best_anchor[..., None] == mask_arr[None, None, :]
    an_idx = jnp.where(jnp.any(in_mask, -1),
                       jnp.argmax(in_mask, -1), -1)  # [N,B]
    resp = gt_valid & (an_idx >= 0)

    # scatter gt targets onto the [N,A,H,W] lattice; non-responsible
    # rows (padding, or best anchor outside this head's mask) are
    # routed to an out-of-bounds row index so mode="drop" discards them
    # — they must NOT land on (0, 0, 0) and clobber a real target there
    bidx = jnp.broadcast_to(jnp.arange(n)[:, None], (n, nb))
    flat = lambda t: t.reshape(-1)
    gj_s = jnp.where(resp, gj, h)  # h = out of bounds → dropped
    scat_idx = (flat(bidx), flat(jnp.maximum(an_idx, 0)), flat(gj_s),
                flat(gi))

    def scatter(vals, init):
        t = jnp.full((n, na, h, w), init, jnp.float32)
        return t.at[scat_idx].set(flat(vals), mode="drop")

    tx = scatter(gt_box[..., 0] * w - gi.astype(jnp.float32), 0.0)
    ty = scatter(gt_box[..., 1] * h - gj.astype(jnp.float32), 0.0)
    anc_w = anc[jnp.maximum(best_anchor, 0), 0]
    anc_h = anc[jnp.maximum(best_anchor, 0), 1]
    tw = scatter(jnp.log(jnp.maximum(gw / anc_w, _EPS)), 0.0)
    th = scatter(jnp.log(jnp.maximum(gh / anc_h, _EPS)), 0.0)
    tscore = scatter(gt_score, 0.0)
    obj_mask = scatter(jnp.ones_like(gt_score), 0.0) > 0
    tcls_idx = scatter(gt_label.astype(jnp.float32), -1.0)

    # box scale weight: 2 - w*h (bigger gt → smaller weight)
    bscale = scatter(2.0 - gt_box[..., 2] * gt_box[..., 3], 0.0)

    wgt = bscale * tscore
    loss_xy = _sigmoid_bce(px, tx) * wgt + _sigmoid_bce(py, ty) * wgt
    loss_wh = (jnp.abs(pw - tw) + jnp.abs(ph - th)) * wgt
    loss_box = jnp.where(obj_mask, loss_xy + loss_wh, 0.0)

    loss_obj_pos = _sigmoid_bce(pobj, jnp.ones_like(pobj)) * tscore
    loss_obj_neg = _sigmoid_bce(pobj, jnp.zeros_like(pobj))
    loss_obj = jnp.where(obj_mask, loss_obj_pos,
                         jnp.where(ignore, 0.0, loss_obj_neg))

    if use_label_smooth:
        delta = 1.0 / class_num
        on, off = 1.0 - delta, delta
    else:
        on, off = 1.0, 0.0
    onehot = (jnp.arange(class_num)[None, None, None, None, :]
              == tcls_idx[..., None]) * (on - off) + off
    loss_cls = jnp.sum(
        _sigmoid_bce(pcls.transpose(0, 1, 3, 4, 2), onehot), -1)
    loss_cls = jnp.where(obj_mask, loss_cls * tscore, 0.0)

    per_img = (loss_box + loss_obj + loss_cls).reshape(n, -1).sum(1)
    return per_img


# ---------------------------------------------------------------------------
# ROI feature extraction (reference: operators/roi_align_op.cc,
# roi_pool_op.cc — LoD rois; here rois carry an explicit batch index)


@register("roi_align", ["X", "ROIs", "RoisBatchIdx"], ["Out"],
          nondiff=("ROIs", "RoisBatchIdx"))
def roi_align(x, rois, rois_batch_idx, *, pooled_height=1,
              pooled_width=1, spatial_scale=1.0, sampling_ratio=-1):
    """ROI Align with bilinear sampling. x [N, C, H, W], rois [R, 4]
    (x1, y1, x2, y2 in image coords), rois_batch_idx [R] int32.
    Differentiable through X (gather → XLA derives the scatter-add
    backward the reference hand-writes in roi_align_op.cu).

    Static-shape deviation: the reference's sampling_ratio=-1 means
    *adaptive* (ceil(roi_size / pooled_size) samples per bin, a
    data-dependent count); XLA needs a fixed grid, so -1 selects a
    fixed 4x4 sampling pattern per bin. Pass an explicit
    sampling_ratio to control accuracy/cost."""
    n, c, hh, ww = x.shape
    sr = sampling_ratio if sampling_ratio > 0 else 4
    ph, pw = pooled_height, pooled_width

    def one_roi(roi, bidx):
        img = x[jnp.clip(bidx, 0, n - 1)]  # [C, H, W]
        x1, y1, x2, y2 = roi * spatial_scale
        rw = jnp.maximum(x2 - x1, 1.0)
        rh = jnp.maximum(y2 - y1, 1.0)
        bin_w = rw / pw
        bin_h = rh / ph
        # sample grid: [ph, pw, sr, sr]
        iy = jnp.arange(ph, dtype=jnp.float32)[:, None, None, None]
        ix = jnp.arange(pw, dtype=jnp.float32)[None, :, None, None]
        sy = jnp.arange(sr, dtype=jnp.float32)[None, None, :, None]
        sx = jnp.arange(sr, dtype=jnp.float32)[None, None, None, :]
        yy = y1 + iy * bin_h + (sy + 0.5) * bin_h / sr
        xx = x1 + ix * bin_w + (sx + 0.5) * bin_w / sr
        yy = jnp.clip(yy, 0.0, hh - 1.0)
        xx = jnp.clip(xx, 0.0, ww - 1.0)
        y0 = jnp.floor(yy).astype(jnp.int32)
        x0 = jnp.floor(xx).astype(jnp.int32)
        y1i = jnp.minimum(y0 + 1, hh - 1)
        x1i = jnp.minimum(x0 + 1, ww - 1)
        ly = yy - y0.astype(jnp.float32)
        lx = xx - x0.astype(jnp.float32)

        def gat(yi, xi):
            return img[:, yi, xi]  # [C, ph, pw, sr, sr]

        val = (gat(y0, x0) * ((1 - ly) * (1 - lx))[None] +
               gat(y0, x1i) * ((1 - ly) * lx)[None] +
               gat(y1i, x0) * (ly * (1 - lx))[None] +
               gat(y1i, x1i) * (ly * lx)[None])
        return val.mean(axis=(-1, -2))  # [C, ph, pw]

    return jax.vmap(one_roi)(rois, rois_batch_idx)


@register("roi_pool", ["X", "ROIs", "RoisBatchIdx"], ["Out", "Argmax"],
          nondiff=("ROIs", "RoisBatchIdx"))
def roi_pool(x, rois, rois_batch_idx, *, pooled_height=1,
             pooled_width=1, spatial_scale=1.0):
    """ROI max pooling (reference: roi_pool_op.h). Exact semantics via
    bin-index scatter-max: each (h, w) cell computes its bin and
    contributes by segment-max — no data-dependent slice sizes, so the
    whole op jits. Sequential lax.map over ROIs bounds memory."""
    n, c, hh, ww = x.shape
    ph, pw = pooled_height, pooled_width

    hs = jnp.arange(hh, dtype=jnp.float32)
    ws = jnp.arange(ww, dtype=jnp.float32)

    def one_roi(args):
        roi, bidx = args
        img = x[jnp.clip(bidx, 0, n - 1)]  # [C, H, W]
        rx1 = jnp.round(roi[0] * spatial_scale)
        ry1 = jnp.round(roi[1] * spatial_scale)
        rx2 = jnp.round(roi[2] * spatial_scale)
        ry2 = jnp.round(roi[3] * spatial_scale)
        rh = jnp.maximum(ry2 - ry1 + 1.0, 1.0)
        rw = jnp.maximum(rx2 - rx1 + 1.0, 1.0)
        # bin index per pixel (floor div by bin size), valid-range mask
        bin_h = rh / ph
        bin_w = rw / pw
        bi = jnp.floor((hs - ry1) / bin_h).astype(jnp.int32)
        bj = jnp.floor((ws - rx1) / bin_w).astype(jnp.int32)
        okh = (hs >= ry1) & (hs <= ry2) & (bi >= 0) & (bi < ph)
        okw = (ws >= rx1) & (ws <= rx2) & (bj >= 0) & (bj < pw)
        ok = okh[:, None] & okw[None, :]
        bin_idx = jnp.where(ok, bi[:, None] * pw + bj[None, :],
                            ph * pw)  # dump bin
        flatv = img.reshape(c, -1)
        flati = bin_idx.reshape(-1)
        out = jnp.full((c, ph * pw + 1), -jnp.inf)
        out = out.at[:, flati].max(flatv)
        out = out[:, :ph * pw].reshape(c, ph, pw)
        return jnp.where(jnp.isfinite(out), out, 0.0)

    out = lax.map(one_roi, (rois, rois_batch_idx))
    return out, jnp.zeros(out.shape, jnp.int32)


# ---------------------------------------------------------------------------
# misc assignment / FPN


@register("box_decoder_and_assign",
          ["PriorBox", "PriorBoxVar", "TargetBox", "BoxScore"],
          ["DecodeBox", "OutputAssignBox"], differentiable=False)
def box_decoder_and_assign(prior_box, prior_box_var, target_box,
                           box_score, *, box_clip=4.135166556742356):
    """Decode per-class deltas and pick each ROI's best-class box
    (reference: box_decoder_and_assign_op.cc). target_box [R, 4*C],
    box_score [R, C]."""
    r = prior_box.shape[0]
    cnum = box_score.shape[1]
    pw = prior_box[:, 2] - prior_box[:, 0] + 1.0
    ph = prior_box[:, 3] - prior_box[:, 1] + 1.0
    pcx = prior_box[:, 0] + pw / 2.0
    pcy = prior_box[:, 1] + ph / 2.0
    t = target_box.reshape(r, cnum, 4)
    var = prior_box_var if prior_box_var is not None else \
        jnp.ones((4,), jnp.float32)
    if var.ndim == 2:
        var = var[0]
    dx = t[..., 0] * var[0]
    dy = t[..., 1] * var[1]
    dw = jnp.clip(t[..., 2] * var[2], -box_clip, box_clip)
    dh = jnp.clip(t[..., 3] * var[3], -box_clip, box_clip)
    cx = dx * pw[:, None] + pcx[:, None]
    cy = dy * ph[:, None] + pcy[:, None]
    w = jnp.exp(dw) * pw[:, None]
    h = jnp.exp(dh) * ph[:, None]
    dec = jnp.stack([cx - w / 2.0, cy - h / 2.0,
                     cx + w / 2.0 - 1.0, cy + h / 2.0 - 1.0], axis=-1)
    best = jnp.argmax(box_score, axis=1)
    assign = jnp.take_along_axis(
        dec, best[:, None, None].repeat(4, 2), axis=1)[:, 0]
    return dec.reshape(r, cnum * 4), assign


@register("distribute_fpn_proposals", ["FpnRois"],
          ["MultiFpnRois*", "RestoreIndex"], differentiable=False)
def distribute_fpn_proposals(fpn_rois, *, min_level=2, max_level=5,
                             refer_level=4, refer_scale=224):
    """Route each ROI to its FPN level (reference:
    distribute_fpn_proposals_op.h).

    Static redesign: the reference compacts ROIs into ragged per-level
    lists and returns a RestoreIndex mapping concat positions back to
    the original order. Here every per-level output keeps the FULL
    [R, 4] shape *in the original ROI order* with non-member rows
    zeroed — so per-level roi_align results recombine by masked sum
    (zero boxes pool zeros) and NO reordering ever happens.
    RestoreIndex is therefore the identity [R, 1] (kept for API
    parity); each ROI's level is recoverable as the level whose output
    row is nonzero."""
    r = fpn_rois.shape[0]
    w = fpn_rois[:, 2] - fpn_rois[:, 0]
    h = fpn_rois[:, 3] - fpn_rois[:, 1]
    scale = jnp.sqrt(jnp.maximum(w * h, _EPS))
    lvl = jnp.floor(jnp.log2(scale / refer_scale + _EPS)) + refer_level
    lvl = jnp.clip(lvl, min_level, max_level).astype(jnp.int32)
    outs = []
    for L in range(min_level, max_level + 1):
        m = (lvl == L)[:, None]
        outs.append(jnp.where(m, fpn_rois, 0.0))
    restore = jnp.arange(r, dtype=jnp.int32)[:, None]
    return outs, restore


@register("collect_fpn_proposals", ["MultiLevelRois*", "MultiLevelScores*"],
          ["FpnRois"], differentiable=False)
def collect_fpn_proposals(multi_rois, multi_scores, *, post_nms_topN):
    """Merge per-level proposals by score (reference:
    collect_fpn_proposals_op.h). Inputs are padded per-level [R_l, 4] /
    [R_l]; zero-score rows are padding."""
    rois = jnp.concatenate(multi_rois, axis=0)
    scores = jnp.concatenate(multi_scores, axis=0)
    k = min(post_nms_topN, scores.shape[0])
    top = jnp.argsort(-scores)[:k]
    return rois[top]


@register("rpn_target_assign",
          ["Anchor", "GtBoxes", "IsCrowd", "ImInfo"],
          ["LocationIndex", "ScoreIndex", "TargetLabel", "TargetBBox",
           "BBoxInsideWeight"], differentiable=False, needs_rng=True)
def rpn_target_assign(anchor, gt_boxes, is_crowd, im_info, *, rng,
                      rpn_batch_size_per_im=256,
                      rpn_straddle_thresh=0.0, rpn_fg_fraction=0.5,
                      rpn_positive_overlap=0.7,
                      rpn_negative_overlap=0.3, use_random=True):
    """RPN anchor sampling (reference: rpn_target_assign_op.cc).

    Static redesign: instead of ragged index lists, returns fixed-size
    [N, S] (S = rpn_batch_size_per_im) index tensors padded with -1,
    labels (1 fg / 0 bg / -1 pad), encoded target boxes for the fg
    slots, and inside weights. gt_boxes is padded [N, B, 4] (all-zero
    rows invalid); is_crowd [N, B] marks crowd gt to skip."""
    a4 = anchor.reshape(-1, 4)
    na = a4.shape[0]
    n = gt_boxes.shape[0]
    s = rpn_batch_size_per_im
    n_fg_max = int(rpn_fg_fraction * s)

    def one(gts, crowd, info, key):
        valid_gt = (gts[:, 2] > gts[:, 0]) & (gts[:, 3] > gts[:, 1]) & \
            (crowd == 0)
        iou = _iou_matrix(a4, gts)  # [A, B]
        iou = jnp.where(valid_gt[None, :], iou, 0.0)
        max_iou = jnp.max(iou, axis=1)
        argmax_gt = jnp.argmax(iou, axis=1)
        # anchors straddling the image boundary are excluded
        if rpn_straddle_thresh >= 0:
            ih, iw = info[0], info[1]
            inside = (a4[:, 0] >= -rpn_straddle_thresh) & \
                (a4[:, 1] >= -rpn_straddle_thresh) & \
                (a4[:, 2] < iw + rpn_straddle_thresh) & \
                (a4[:, 3] < ih + rpn_straddle_thresh)
        else:
            inside = jnp.ones((na,), bool)
        # fg: best anchor per gt, or IoU above positive threshold
        best_per_gt = jnp.max(jnp.where(inside[:, None], iou, -1.0),
                              axis=0)
        is_best = jnp.any(
            (iou >= jnp.maximum(best_per_gt[None, :], _EPS))
            & valid_gt[None, :], axis=1)
        fg = inside & ((max_iou >= rpn_positive_overlap) | is_best)
        bg = inside & ~fg & (max_iou < rpn_negative_overlap)

        noise = jax.random.uniform(key, (na,)) if use_random else \
            jnp.zeros((na,))
        # rank fg and bg separately, take quotas
        fg_rank = jnp.argsort(
            jnp.argsort(-(fg.astype(jnp.float32) + noise * 1e-3)))
        n_fg = jnp.minimum(jnp.sum(fg.astype(jnp.int32)), n_fg_max)
        fg_sel = fg & (fg_rank < n_fg)
        n_bg = s - n_fg
        bg_rank = jnp.argsort(
            jnp.argsort(-(bg.astype(jnp.float32) + noise * 1e-3)))
        bg_sel = bg & (bg_rank < n_bg)

        sel = fg_sel | bg_sel
        sel_rank = jnp.argsort(jnp.argsort(
            -(sel.astype(jnp.float32) * 2 + fg_sel.astype(jnp.float32))))
        # positions [S]: anchor index or -1
        slot_ok = jnp.arange(s) < jnp.sum(sel.astype(jnp.int32))
        order = jnp.argsort(sel_rank)[:s]
        loc_idx = jnp.where(slot_ok, order.astype(jnp.int32), -1)
        lbl = jnp.where(slot_ok,
                        fg_sel[order].astype(jnp.int32), -1)
        # encode fg targets against their matched gt
        mg = gts[argmax_gt[order]]
        aw = a4[order, 2] - a4[order, 0] + 1.0
        ah = a4[order, 3] - a4[order, 1] + 1.0
        acx = a4[order, 0] + aw / 2.0
        acy = a4[order, 1] + ah / 2.0
        gw = mg[:, 2] - mg[:, 0] + 1.0
        gh = mg[:, 3] - mg[:, 1] + 1.0
        gcx = mg[:, 0] + gw / 2.0
        gcy = mg[:, 1] + gh / 2.0
        tgt = jnp.stack([(gcx - acx) / aw, (gcy - acy) / ah,
                         jnp.log(jnp.maximum(gw / aw, _EPS)),
                         jnp.log(jnp.maximum(gh / ah, _EPS))], axis=-1)
        fg_slot = (lbl == 1)[:, None]
        tgt = jnp.where(fg_slot, tgt, 0.0)
        w = fg_slot.astype(jnp.float32) * jnp.ones((1, 4), jnp.float32)
        return loc_idx, loc_idx, lbl, tgt, w

    keys = jax.random.split(rng, n)
    return jax.vmap(one)(gt_boxes, is_crowd, im_info, keys)


# ---------------------------------------------------------------------------
# SSD loss (fused)


@register("ssd_loss", ["Location", "Confidence", "GtBox", "GtLabel",
                       "PriorBox", "PriorBoxVar"],
          ["Loss"], nondiff=("GtBox", "GtLabel", "PriorBox",
                             "PriorBoxVar"))
def ssd_loss(location, confidence, gt_box, gt_label, prior_box,
             prior_box_var, *, background_label=0,
             overlap_threshold=0.5, neg_pos_ratio=3.0, neg_overlap=0.5,
             loc_loss_weight=1.0, conf_loss_weight=1.0,
             match_type="per_prediction", mining_type="max_negative",
             normalize=True, sample_size=0):
    """Fused SSD multibox loss (reference: layers/detection.py ssd_loss,
    which composes iou_similarity → bipartite_match → target_assign →
    mine_hard_examples → smooth_l1 + softmax CE as ~10 graph ops).

    TPU-native: ONE op — XLA fuses the whole pipeline, and the padded
    redesign (gt_box [N, B, 4] with all-zero padding rows, gt_label
    [N, B]) replaces the reference's LoD segments. location [N, P, 4],
    confidence [N, P, C], prior_box [P, 4]. Returns [N, P] weighted
    loss, normalized by the number of matched priors."""
    n, p, cnum = confidence.shape

    if prior_box_var is None:
        prior_box_var = jnp.full((p, 4), 1.0, jnp.float32)

    pw = prior_box[:, 2] - prior_box[:, 0]
    ph = prior_box[:, 3] - prior_box[:, 1]
    pcx = prior_box[:, 0] + pw / 2.0
    pcy = prior_box[:, 1] + ph / 2.0

    def one(loc, conf, gts, gtl):
        valid_gt = (gts[:, 2] > gts[:, 0]) & (gts[:, 3] > gts[:, 1])
        iou = _iou_matrix(gts, prior_box)
        iou = jnp.where(valid_gt[:, None], iou, 0.0)
        midx, mdist = _bipartite_match_one(iou, match_type,
                                           overlap_threshold)
        matched = midx >= 0

        # conf target + loss
        tlabel = jnp.where(matched, gtl[jnp.maximum(midx, 0)],
                           background_label)
        logp = jax.nn.log_softmax(conf, axis=-1)
        conf_loss = -jnp.take_along_axis(logp, tlabel[:, None],
                                         axis=1)[:, 0]

        # hard negative mining on conf loss
        is_neg = ~matched & (mdist < neg_overlap)
        num_pos = jnp.sum(matched.astype(jnp.int32))
        if mining_type == "max_negative":
            limit = num_pos.astype(jnp.float32) * neg_pos_ratio
        else:
            limit = jnp.asarray(float(sample_size or 0))
        neg_loss = jnp.where(is_neg, conf_loss, -jnp.inf)
        ranks = jnp.argsort(jnp.argsort(-neg_loss)).astype(jnp.float32)
        selected_neg = is_neg & (ranks < limit)

        conf_w = matched.astype(jnp.float32) + \
            selected_neg.astype(jnp.float32)

        # loc target (encode matched gt against priors) + smooth l1
        mg = gts[jnp.maximum(midx, 0)]
        gw = mg[:, 2] - mg[:, 0]
        gh = mg[:, 3] - mg[:, 1]
        gcx = mg[:, 0] + gw / 2.0
        gcy = mg[:, 1] + gh / 2.0
        tloc = jnp.stack([
            (gcx - pcx) / jnp.maximum(pw, _EPS),
            (gcy - pcy) / jnp.maximum(ph, _EPS),
            jnp.log(jnp.maximum(gw / jnp.maximum(pw, _EPS), _EPS)),
            jnp.log(jnp.maximum(gh / jnp.maximum(ph, _EPS), _EPS))],
            axis=-1) / prior_box_var
        d = loc - tloc
        ad = jnp.abs(d)
        sl1 = jnp.where(ad < 1.0, 0.5 * d * d, ad - 0.5).sum(-1)
        loc_loss = sl1 * matched.astype(jnp.float32)

        total = conf_loss_weight * conf_loss * conf_w + \
            loc_loss_weight * loc_loss
        if normalize:
            total = total / jnp.maximum(num_pos.astype(jnp.float32),
                                        1.0)
        return total

    return jax.vmap(one)(location, confidence, gt_box, gt_label)


@register("psroi_pool", ["X", "ROIs", "RoisBatchIdx"], ["Out"],
          nondiff=("ROIs", "RoisBatchIdx"))
def psroi_pool(x, rois, rois_batch_idx, *, output_channels,
               pooled_height=1, pooled_width=1, spatial_scale=1.0):
    """Position-sensitive ROI pooling (reference: psroi_pool_op.cc,
    R-FCN): x [N, output_channels*ph*pw, H, W]; bin (i, j) of output
    channel c AVERAGE-pools the input channel c*ph*pw + i*pw + j over
    that bin's region. Same static-shape strategy as roi_pool: bin
    membership masks + segment reduction, lax.map over ROIs."""
    n, cin, hh, ww = x.shape
    ph, pw = pooled_height, pooled_width
    co = output_channels
    hs = jnp.arange(hh, dtype=jnp.float32)
    ws = jnp.arange(ww, dtype=jnp.float32)

    def one_roi(args):
        roi, bidx = args
        img = x[jnp.clip(bidx, 0, n - 1)]          # [Cin, H, W]
        # reference rounds the roi to the feature grid
        rx1 = jnp.round(roi[0] * spatial_scale)
        ry1 = jnp.round(roi[1] * spatial_scale)
        rx2 = jnp.round(roi[2] * spatial_scale)
        ry2 = jnp.round(roi[3] * spatial_scale)
        rw = jnp.maximum(rx2 - rx1, 0.1)
        rh = jnp.maximum(ry2 - ry1, 0.1)
        bin_h = rh / ph
        bin_w = rw / pw
        # bin index of every cell (or -1 outside the roi)
        bh = jnp.floor((hs - ry1) / bin_h)
        bw = jnp.floor((ws - rx1) / bin_w)
        in_h = (hs >= ry1) & (hs < ry2)
        in_w = (ws >= rx1) & (ws < rx2)
        bh = jnp.clip(bh, 0, ph - 1).astype(jnp.int32)
        bw = jnp.clip(bw, 0, pw - 1).astype(jnp.int32)
        # one-hot bin masks: [ph, H] and [pw, W]
        mh = (jnp.arange(ph)[:, None] == bh[None, :]) & in_h[None, :]
        mw = (jnp.arange(pw)[:, None] == bw[None, :]) & in_w[None, :]
        mh = mh.astype(x.dtype)
        mw = mw.astype(x.dtype)
        # sums per (channel, bin): [Cin, ph, pw]
        sums = jnp.einsum("chw,ih,jw->cij", img, mh, mw)
        cnts = jnp.maximum(jnp.einsum("ih,jw->ij", mh, mw), 1.0)
        avg = sums / cnts[None]
        # position-sensitive channel selection:
        # out[c, i, j] = avg[c*ph*pw + i*pw + j, i, j]
        avg = avg.reshape(co, ph, pw, ph, pw)
        ii = jnp.arange(ph)
        jj = jnp.arange(pw)
        return avg[:, ii[:, None], jj[None, :],
                   ii[:, None], jj[None, :]]

    return lax.map(one_roi, (rois.astype(jnp.float32),
                             rois_batch_idx.astype(jnp.int32)))


@register("roi_perspective_transform", ["X", "ROIs", "RoisBatchIdx"],
          ["Out"], nondiff=("ROIs", "RoisBatchIdx"))
def roi_perspective_transform(x, rois, rois_batch_idx, *,
                              transformed_height, transformed_width,
                              spatial_scale=1.0):
    """Perspective-warp quadrilateral ROIs to a fixed rectangle
    (reference: detection/roi_perspective_transform_op.cc, used by
    OCR-style detectors): rois [R, 8] are quad corners
    (x1,y1,...,x4,y4) in tl/tr/br/bl order; each quad maps onto a
    [th, tw] grid through the closed-form unit-square->quad homography
    (the reference's get_transform_matrix) and the input samples
    bilinearly. Differentiable through X via the gather autodiff."""
    from .vision_ops import _bilinear_gather
    N, C, H, W = x.shape
    th, tw = transformed_height, transformed_width
    q = rois.astype(jnp.float32).reshape(-1, 4, 2) * spatial_scale
    x0, y0 = q[:, 0, 0], q[:, 0, 1]
    x1, y1 = q[:, 1, 0], q[:, 1, 1]
    x2, y2 = q[:, 2, 0], q[:, 2, 1]
    x3, y3 = q[:, 3, 0], q[:, 3, 1]
    # square->quad projective coefficients (Heckbert's formulation)
    dx1 = x1 - x2
    dx2 = x3 - x2
    dx3 = x0 - x1 + x2 - x3
    dy1 = y1 - y2
    dy2 = y3 - y2
    dy3 = y0 - y1 + y2 - y3
    den = dx1 * dy2 - dx2 * dy1
    den = jnp.where(jnp.abs(den) < 1e-9, 1e-9, den)
    g = (dx3 * dy2 - dx2 * dy3) / den
    h2 = (dx1 * dy3 - dx3 * dy1) / den
    a = x1 - x0 + g * x1
    b = x3 - x0 + h2 * x3
    c = x0
    d = y1 - y0 + g * y1
    e = y3 - y0 + h2 * y3
    f = y0

    # unit-square grid over the output rectangle
    u = (jnp.arange(tw, dtype=jnp.float32) + 0.5) / tw
    v = (jnp.arange(th, dtype=jnp.float32) + 0.5) / th
    uu, vv = jnp.meshgrid(u, v)                      # [th, tw]

    def one_roi(args):
        (ai, bi, ci, di, ei, fi, gi, hi, bidx) = args
        den2 = gi * uu + hi * vv + 1.0
        # degenerate/misordered quads can cross zero inside the square;
        # clamp so the sample coords stay finite (they land outside the
        # image and zero-pad, instead of NaN-poisoning the tile)
        den2 = jnp.where(jnp.abs(den2) < 1e-6,
                         jnp.where(den2 < 0, -1e-6, 1e-6), den2)
        xs = (ai * uu + bi * vv + ci) / den2
        ys = (di * uu + ei * vv + fi) / den2
        img = x[jnp.clip(bidx, 0, N - 1)]
        return _bilinear_gather(img, ys, xs)         # [C, th, tw]

    return lax.map(one_roi,
                   (a, b, c, d, e, f, g, h2,
                    rois_batch_idx.astype(jnp.int32)))


# ---------------------------------------------------------------------------
# Mask-RCNN training targets
# ---------------------------------------------------------------------------

@register("generate_proposal_labels",
          ["RpnRois", "GtClasses", "IsCrowd", "GtBoxes", "ImInfo"],
          ["Rois", "LabelsInt32", "BboxTargets", "BboxInsideWeights",
           "BboxOutsideWeights"], differentiable=False,
          needs_rng=True)
def generate_proposal_labels(rpn_rois, gt_classes, is_crowd, gt_boxes,
                             im_info, *, rng, batch_size_per_im=256,
                             fg_fraction=0.25, fg_thresh=0.5,
                             bg_thresh_hi=0.5, bg_thresh_lo=0.0,
                             bbox_reg_weights=(0.1, 0.1, 0.2, 0.2),
                             class_nums=81, use_random=True):
    """Fast/Mask-RCNN second-stage RoI sampling (reference:
    generate_proposal_labels_op.cc SampleRoisForOneImage:228 —
    append gt boxes to proposals, match by IoU, sample a
    fg_fraction-balanced quota, emit per-class bbox regression
    targets).

    Static TPU redesign: ragged per-image LoD outputs become padded
    [N, S] tensors (S = batch_size_per_im); pad slots carry label -1
    and zero weights, so downstream losses mask on label >= 0. Crowd
    and all-zero (pad) gt rows are excluded from matching. The
    reservoir sampling of the reference becomes noise-ranked quota
    selection (same marginal distribution under a uniform key).

    Shapes: RpnRois [N, R, 4]; GtClasses/IsCrowd [N, B];
    GtBoxes [N, B, 4]; ImInfo [N, 3]. Rois [N, S, 4];
    LabelsInt32 [N, S]; targets/weights [N, S, 4*class_nums].
    """
    n, r = rpn_rois.shape[0], rpn_rois.shape[1]
    b = gt_boxes.shape[1]
    s = int(batch_size_per_im)
    n_fg_max = int(s * fg_fraction)
    wx, wy, ww, wh = [float(w) for w in bbox_reg_weights]

    def one(rois, gts, classes, crowd, key):
        valid_gt = (gts[:, 2] > gts[:, 0]) & (gts[:, 3] > gts[:, 1]) \
            & (crowd == 0)
        # candidate boxes: valid gts first (the reference concats
        # gt_boxes before rpn_rois), then proposals
        boxes = jnp.concatenate([gts, rois], axis=0)     # [B+R, 4]
        valid_box = jnp.concatenate(
            [valid_gt, (rois[:, 2] > rois[:, 0])
             & (rois[:, 3] > rois[:, 1])])
        iou = _iou_matrix(boxes, gts)                    # [B+R, B]
        iou = jnp.where(valid_gt[None, :] & valid_box[:, None],
                        iou, 0.0)
        max_iou = jnp.max(iou, axis=1)
        gt_ind = jnp.argmax(iou, axis=1)
        fg = valid_box & (max_iou > fg_thresh)
        bg = valid_box & ~fg & (max_iou >= bg_thresh_lo) \
            & (max_iou < bg_thresh_hi)

        noise = jax.random.uniform(key, max_iou.shape) if use_random \
            else jnp.zeros_like(max_iou)
        fg_rank = jnp.argsort(jnp.argsort(
            -(fg.astype(jnp.float32) + noise * 1e-3)))
        n_fg = jnp.minimum(jnp.sum(fg.astype(jnp.int32)), n_fg_max)
        fg_sel = fg & (fg_rank < n_fg)
        bg_rank = jnp.argsort(jnp.argsort(
            -(bg.astype(jnp.float32) + noise * 1e-3)))
        n_bg = jnp.minimum(jnp.sum(bg.astype(jnp.int32)), s - n_fg)
        bg_sel = bg & (bg_rank < n_bg)

        sel = fg_sel | bg_sel
        # fg slots first, then bg, then padding (stable by rank noise)
        order_key = -(fg_sel.astype(jnp.float32) * 2.0
                      + bg_sel.astype(jnp.float32)) + noise * 1e-6
        order = jnp.argsort(order_key)[:s]
        slot_ok = jnp.arange(s) < jnp.sum(sel.astype(jnp.int32))
        out_rois = jnp.where(slot_ok[:, None], boxes[order], 0.0)
        is_fg_slot = slot_ok & fg_sel[order]
        labels = jnp.where(
            is_fg_slot, classes[gt_ind[order]].astype(jnp.int32),
            jnp.where(slot_ok, 0, -1))

        # encode fg targets vs matched gt (BoxToDelta with weights)
        mg = gts[gt_ind[order]]
        bw = out_rois[:, 2] - out_rois[:, 0] + 1.0
        bh = out_rois[:, 3] - out_rois[:, 1] + 1.0
        bcx = out_rois[:, 0] + bw / 2.0
        bcy = out_rois[:, 1] + bh / 2.0
        gw = mg[:, 2] - mg[:, 0] + 1.0
        gh = mg[:, 3] - mg[:, 1] + 1.0
        gcx = mg[:, 0] + gw / 2.0
        gcy = mg[:, 1] + gh / 2.0
        delta = jnp.stack(
            [(gcx - bcx) / bw / wx, (gcy - bcy) / bh / wy,
             jnp.log(jnp.maximum(gw / jnp.maximum(bw, _EPS), _EPS))
             / ww,
             jnp.log(jnp.maximum(gh / jnp.maximum(bh, _EPS), _EPS))
             / wh], axis=-1)                              # [S, 4]
        # scatter into the per-class layout [S, 4*class_nums]
        cls = jnp.where(is_fg_slot, labels, 0)
        col = jax.lax.broadcasted_iota(jnp.int32,
                                       (s, 4 * class_nums), 1)
        in_class = (col >= cls[:, None] * 4) \
            & (col < (cls[:, None] + 1) * 4)
        hit = in_class & is_fg_slot[:, None]
        tiled = jnp.tile(delta, (1, class_nums))
        targets = jnp.where(hit, tiled, 0.0)
        weights = hit.astype(jnp.float32)
        return out_rois, labels, targets, weights, weights

    keys = jax.random.split(rng, n)
    return jax.vmap(one)(rpn_rois.astype(jnp.float32),
                         gt_boxes.astype(jnp.float32),
                         gt_classes.astype(jnp.int32),
                         is_crowd.astype(jnp.int32), keys)


@register("generate_mask_labels",
          ["ImInfo", "GtClasses", "IsCrowd", "GtMasks", "Rois",
           "LabelsInt32"],
          ["MaskRois", "RoiHasMaskInt32", "MaskInt32"],
          differentiable=False)
def generate_mask_labels(im_info, gt_classes, is_crowd, gt_masks,
                         rois, labels_int32, *, num_classes=81,
                         resolution=14):
    """Mask-head training targets (reference:
    generate_mask_labels_op.cc — match fg RoIs to gt masks and
    rasterize the cropped segmentation at ``resolution^2`` per class;
    non-target class slots are -1 = don't-count, matching the
    reference's ExpandMaskTarget).

    TPU redesign: the reference consumes ragged COCO polygon lists
    (LoD level 3) and rasterizes host-side via poly2mask; here gt
    segmentations arrive already rasterized as GtMasks [N, B, H, W]
    binary maps (the dataset pipeline's poly2mask analog), and the
    crop+resize to [resolution, resolution] is a nearest-neighbor
    gather the compiler vectorizes. Rois/labels are the padded [N, S]
    outputs of generate_proposal_labels; mask targets are emitted for
    every fg slot (label > 0), RoiHasMaskInt32 marking them.
    """
    m = int(resolution)
    h, w = gt_masks.shape[2], gt_masks.shape[3]

    def one(gts_mask, classes, crowd, img_rois, labels):
        valid_gt = (classes > 0) & (crowd == 0) \
            & (jnp.sum(gts_mask, axis=(1, 2)) > 0)
        is_fg = labels > 0

        # match each fg roi to the gt whose class equals its label and
        # whose mask overlaps the roi most (reference matches through
        # the sampled gt index; recover it by overlap)
        x0, y0 = img_rois[:, 0], img_rois[:, 1]
        x1, y1 = img_rois[:, 2], img_rois[:, 3]

        ys = jnp.clip(
            (y0[:, None] + (jnp.arange(m)[None, :] + 0.5)
             * (y1 - y0)[:, None] / m).astype(jnp.int32), 0, h - 1)
        xs = jnp.clip(
            (x0[:, None] + (jnp.arange(m)[None, :] + 0.5)
             * (x1 - x0)[:, None] / m).astype(jnp.int32), 0, w - 1)

        def crop(mask):
            # [S, m, m] nearest-neighbor crop of ONE gt mask
            return mask[ys[:, :, None], xs[:, None, :]]

        crops = jax.vmap(crop)(gts_mask)            # [B, S, m, m]
        # overlap score of each gt's mask inside each roi
        score = jnp.sum(crops, axis=(2, 3)).astype(jnp.float32)
        class_ok = (classes[:, None] == labels[None, :]) \
            & valid_gt[:, None]
        score = jnp.where(class_ok, score, -1.0)
        best_gt = jnp.argmax(score, axis=0)         # [S]
        matched = jnp.max(score, axis=0) >= 0.0

        has_mask = is_fg & matched
        sel = jnp.take_along_axis(
            crops, best_gt[None, :, None, None], axis=0)[0]  # [S,m,m]
        flat = sel.reshape(-1, m * m).astype(jnp.int32)

        cls = jnp.where(has_mask, labels, 0)
        col = jax.lax.broadcasted_iota(
            jnp.int32, (labels.shape[0], num_classes * m * m), 1)
        in_class = (col >= cls[:, None] * m * m) \
            & (col < (cls[:, None] + 1) * m * m)
        tiled = jnp.tile(flat, (1, num_classes))
        mask_t = jnp.where(in_class & has_mask[:, None], tiled, -1)
        mask_rois = jnp.where(has_mask[:, None], img_rois, 0.0)
        return mask_rois, has_mask.astype(jnp.int32), mask_t

    return jax.vmap(one)(gt_masks.astype(jnp.float32),
                         gt_classes.astype(jnp.int32),
                         is_crowd.astype(jnp.int32),
                         rois.astype(jnp.float32),
                         labels_int32.astype(jnp.int32))
