"""Tensor manipulation ops.

Reference: paddle/fluid/operators/{reshape_op.cc, transpose_op.cc,
concat_op.cc, split_op.cc, stack_op.cc, squeeze_op.cc, unsqueeze_op.cc,
expand_op.cc, slice_op.cc, gather_op.cc, scatter_op.cc, assign_op.cc,
shape_op.cc, fill_constant_op.cc, range_op.cc, one_hot_op.cc ...}.

All lower directly to jnp/lax; shapes are static (XLA requirement), so
shape-producing ops return trace-time constants where possible.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from .registry import register


@register("reshape2", ["X"], ["Out"])
def reshape(x, *, shape):
    # fluid semantics: 0 means copy dim from input, -1 infers.
    out_shape = []
    for i, d in enumerate(shape):
        if d == 0:
            out_shape.append(x.shape[i])
        else:
            out_shape.append(d)
    return x.reshape(out_shape)


@register("transpose2", ["X"], ["Out"])
def transpose(x, *, axis):
    return jnp.transpose(x, axis)


@register("concat", ["X*"], ["Out"])
def concat(xs, *, axis=0):
    return jnp.concatenate(xs, axis=axis)


@register("split", ["X"], ["Out*"])
def split(x, *, num_or_sections, axis=0):
    if isinstance(num_or_sections, int):
        return tuple(jnp.split(x, num_or_sections, axis=axis))
    # sections list -> cumulative indices
    idx, cum = [], 0
    for s in num_or_sections[:-1]:
        cum += s
        idx.append(cum)
    return tuple(jnp.split(x, idx, axis=axis))


@register("stack", ["X*"], ["Y"])
def stack(xs, *, axis=0):
    return jnp.stack(xs, axis=axis)


@register("unstack", ["X"], ["Y*"])
def unstack(x, *, axis=0, num=None):
    n = num or x.shape[axis]
    return tuple(jnp.squeeze(p, axis=axis)
                 for p in jnp.split(x, n, axis=axis))


@register("squeeze2", ["X"], ["Out"])
def squeeze(x, *, axes=()):
    if not axes:
        return jnp.squeeze(x)
    return jnp.squeeze(x, axis=tuple(axes))


@register("unsqueeze2", ["X"], ["Out"])
def unsqueeze(x, *, axes):
    for a in sorted(axes):
        x = jnp.expand_dims(x, a)
    return x


@register("expand", ["X"], ["Out"])
def expand(x, *, expand_times):
    return jnp.tile(x, expand_times)


@register("expand_as", ["X", "Y"], ["Out"], nondiff=("Y",))
def expand_as(x, y):
    return jnp.broadcast_to(x, y.shape)


@register("tile", ["X"], ["Out"])
def tile(x, *, repeat_times):
    return jnp.tile(x, repeat_times)


@register("slice", ["X"], ["Out"])
def slice_(x, *, axes, starts, ends):
    idx = [slice(None)] * x.ndim
    for a, s, e in zip(axes, starts, ends):
        dim = x.shape[a]
        s2 = s + dim if s < 0 else min(s, dim)
        e2 = e + dim if e < 0 else min(e, dim)
        idx[a] = slice(s2, e2)
    return x[tuple(idx)]


@register("strided_slice", ["X"], ["Out"])
def strided_slice(x, *, axes, starts, ends, strides):
    idx = [slice(None)] * x.ndim
    for a, s, e, st in zip(axes, starts, ends, strides):
        idx[a] = slice(s, e, st)
    return x[tuple(idx)]


@register("gather", ["X", "Index"], ["Out"], nondiff=("Index",))
def gather(x, index, *, axis=0):
    return jnp.take(x, index, axis=axis)


@register("gather_nd", ["X", "Index"], ["Out"], nondiff=("Index",))
def gather_nd(x, index):
    return x[tuple(jnp.moveaxis(index, -1, 0))]


@register("scatter", ["X", "Ids", "Updates"], ["Out"], nondiff=("Ids",))
def scatter(x, ids, updates, *, overwrite=True):
    if overwrite:
        return x.at[ids].set(updates)
    return x.at[ids].add(updates)


@register("scatter_nd_add", ["X", "Index", "Updates"], ["Out"],
          nondiff=("Index",))
def scatter_nd_add(x, index, updates):
    return x.at[tuple(jnp.moveaxis(index, -1, 0))].add(updates)


@register("assign", ["X"], ["Out"])
def assign(x):
    return x


@register("shape", ["X"], ["Out"], differentiable=False)
def shape_(x):
    return jnp.array(x.shape, dtype=jnp.int32)


@register("fill_constant", [], ["Out"], differentiable=False)
def fill_constant(*, shape, dtype, value):
    return jnp.full(shape, value, dtype=dtype)


@register("fill_constant_batch_size_like", ["Input"], ["Out"],
          differentiable=False)
def fill_constant_batch_size_like(ref, *, shape, dtype, value,
                                  input_dim_idx=0, output_dim_idx=0):
    out_shape = list(shape)
    out_shape[output_dim_idx] = ref.shape[input_dim_idx]
    return jnp.full(out_shape, value, dtype=dtype)


@register("fill_zeros_like", ["X"], ["Out"], differentiable=False)
def fill_zeros_like(x):
    return jnp.zeros_like(x)


@register("fill_any_like", ["X"], ["Out"], differentiable=False)
def fill_any_like(x, *, value):
    return jnp.full_like(x, value)


@register("range", [], ["Out"], differentiable=False)
def range_(*, start, end, step, dtype):
    return jnp.arange(start, end, step, dtype=dtype)


@register("linspace", [], ["Out"], differentiable=False)
def linspace(*, start, stop, num, dtype):
    return jnp.linspace(start, stop, num, dtype=dtype)


@register("one_hot", ["X"], ["Out"], differentiable=False)
def one_hot(x, *, depth, dtype="float32"):
    x = jnp.squeeze(x, -1) if x.ndim > 1 and x.shape[-1] == 1 else x
    return (x[..., None] == jnp.arange(depth, dtype=x.dtype)).astype(dtype)


@register("flatten2", ["X"], ["Out"])
def flatten(x, *, axis=1):
    lead = 1
    for d in x.shape[:axis]:
        lead *= d
    return x.reshape((lead, -1))


@register("flip", ["X"], ["Out"])
def flip(x, *, axis):
    return jnp.flip(x, axis=tuple(axis))


@register("roll", ["X"], ["Out"])
def roll(x, *, shifts, axis):
    return jnp.roll(x, shifts, axis=axis)


@register("tril_triu", ["X"], ["Out"])
def tril_triu(x, *, diagonal=0, lower=True):
    return jnp.tril(x, diagonal) if lower else jnp.triu(x, diagonal)


@register("eye", [], ["Out"], differentiable=False)
def eye(*, num_rows, num_columns=None, dtype="float32"):
    return jnp.eye(num_rows, num_columns, dtype=dtype)


@register("diag", ["Diagonal"], ["Out"])
def diag(d):
    return jnp.diag(d)


@register("where", ["Condition", "X", "Y"], ["Out"], nondiff=("Condition",))
def where(cond, x, y):
    return jnp.where(cond, x, y)


@register("cumsum", ["X"], ["Out"])
def cumsum(x, *, axis=-1, exclusive=False, reverse=False):
    if reverse:
        x = jnp.flip(x, axis)
    out = jnp.cumsum(x, axis=axis)
    if exclusive:
        out = out - x
    if reverse:
        out = jnp.flip(out, axis)
    return out


@register("pad", ["X"], ["Out"])
def pad(x, *, paddings, pad_value=0.0):
    cfg = [(paddings[2 * i], paddings[2 * i + 1]) for i in range(x.ndim)]
    return jnp.pad(x, cfg, constant_values=pad_value)


@register("pad2d", ["X"], ["Out"])
def pad2d(x, *, paddings, mode="constant", pad_value=0.0,
          data_format="NCHW"):
    if data_format == "NCHW":
        cfg = [(0, 0), (0, 0), (paddings[0], paddings[1]),
               (paddings[2], paddings[3])]
    else:
        cfg = [(0, 0), (paddings[0], paddings[1]),
               (paddings[2], paddings[3]), (0, 0)]
    mode_map = {"constant": "constant", "reflect": "reflect",
                "edge": "edge"}
    if mode == "constant":
        return jnp.pad(x, cfg, constant_values=pad_value)
    return jnp.pad(x, cfg, mode=mode_map[mode])


@register("sequence_mask", ["X"], ["Y"], differentiable=False)
def sequence_mask(lengths, *, maxlen, dtype="float32"):
    return (jnp.arange(maxlen)[None, :] < lengths[:, None]).astype(dtype)


@register("increment", ["X"], ["Out"])
def increment(x, *, step=1.0):
    return x + jnp.asarray(step, dtype=x.dtype)


@register("cum_step_counter", ["X"], ["Out"], differentiable=False)
def cum_step_counter(x):
    """Global-step counter increment (int64-safe)."""
    return x + 1


@register("argsort", ["X"], ["Out", "Indices"], differentiable=False)
def argsort(x, *, axis=-1, descending=False):
    xs = -x if descending else x
    idx = jnp.argsort(xs, axis=axis)
    out = jnp.take_along_axis(x, idx, axis=axis)
    return out, idx.astype(jnp.int32)


@register("arg_max", ["X"], ["Out"], differentiable=False)
def arg_max(x, *, axis=-1, keepdims=False):
    return jnp.argmax(x, axis=axis, keepdims=keepdims).astype(jnp.int32)


@register("arg_min", ["X"], ["Out"], differentiable=False)
def arg_min(x, *, axis=-1, keepdims=False):
    return jnp.argmin(x, axis=axis, keepdims=keepdims).astype(jnp.int32)


@register("top_k", ["X"], ["Out", "Indices"], differentiable=False)
def top_k(x, *, k):
    vals, idx = lax.top_k(x, k)
    return vals, idx.astype(jnp.int32)


@register("assign_numpy_value", [], ["Out"], differentiable=False)
def assign_numpy_value(*, _value, dtype):
    """Materialize a host constant (NumpyArrayInitializer's op;
    reference: assign_value_op.cc)."""
    return jnp.asarray(_value, dtype=dtype)


@register("is_empty", ["X"], ["Out"], differentiable=False)
def is_empty(x):
    """Static-shape emptiness test (reference:
    controlflow/is_empty_op.cc) — a compile-time constant under XLA."""
    return jnp.asarray(x.size == 0)


@register("print", ["X"], ["Out"])
def print_op(x, *, message="", first_n=-1, summarize=20,
             print_phase="both"):
    """Host-side value printing from inside the compiled step
    (reference: operators/print_op.cc + the fetch-var printing of
    platform/lodtensor_printer.cc). Lowered to a debug callback: the
    device ships the value to the host printer without breaking the
    XLA program. ``first_n`` limits prints with a host-side counter
    (callback runs once per executed step, so the counter sees real
    executions, not traces)."""
    state = {"n": 0}

    def _emit(val):
        if first_n >= 0 and state["n"] >= first_n:
            return
        state["n"] += 1
        import numpy as _np
        flat = _np.asarray(val).reshape(-1)
        shown = flat[:summarize] if summarize >= 0 else flat
        print("%s shape=%s %s%s" % (
            message or "print_op", _np.asarray(val).shape,
            shown, "..." if shown.size < flat.size else ""))

    jax.debug.callback(_emit, x)
    return x


# ---------------------------------------------------------------------------
# v1 op-name aliases: the reference registers both the original ops and
# their "2" successors (reshape/reshape2 etc. — reshape_op.cc registers
# BOTH). Same lowerings, second name, so serialized v1 programs run.
# ---------------------------------------------------------------------------

register("reshape", ["X"], ["Out"])(reshape)
register("transpose", ["X"], ["Out"])(transpose)
register("squeeze", ["X"], ["Out"])(squeeze)
register("unsqueeze", ["X"], ["Out"])(unsqueeze)
register("flatten", ["X"], ["Out"])(flatten)
register("fill_zeros_like2", ["X"], ["Out"],
         differentiable=False)(fill_zeros_like)


@register("fill", [], ["Out"], differentiable=False)
def fill(*, shape, dtype="float32", value=0.0):
    """Reference: fill_op.cc (value as attr list or scalar)."""
    arr = jnp.asarray(value, dtype=dtype)
    if arr.ndim == 0:
        return jnp.full(shape, arr, dtype=dtype)
    return arr.reshape(shape)


@register("minus", ["X", "Y"], ["Out"])
def minus(x, y):
    """Reference: minus_op.cc — plain x - y (no axis broadcast)."""
    return x - y


@register("gaussian_random_batch_size_like", ["Input"], ["Out"],
          differentiable=False, needs_rng=True)
def gaussian_random_batch_size_like(ref, *, shape, mean=0.0, std=1.0,
                                    seed=0, dtype="float32",
                                    input_dim_idx=0, output_dim_idx=0,
                                    rng=None):
    """Reference: gaussian_random_batch_size_like_op.cc."""
    out_shape = list(shape)
    out_shape[output_dim_idx] = ref.shape[input_dim_idx]
    key = jax.random.key(seed) if seed else rng
    return mean + std * jax.random.normal(key, tuple(out_shape),
                                          dtype=dtype)


@register("uniform_random_batch_size_like", ["Input"], ["Out"],
          differentiable=False, needs_rng=True)
def uniform_random_batch_size_like(ref, *, shape, min=-1.0, max=1.0,
                                   seed=0, dtype="float32",
                                   input_dim_idx=0, output_dim_idx=0,
                                   rng=None):
    out_shape = list(shape)
    out_shape[output_dim_idx] = ref.shape[input_dim_idx]
    key = jax.random.key(seed) if seed else rng
    return jax.random.uniform(key, tuple(out_shape), dtype=dtype,
                              minval=min, maxval=max)


@register("cross_entropy2", ["X", "Label"], ["Y", "MatchX"],
          nondiff=("Label",))
def cross_entropy2(x, label):
    """Hard-label-only cross entropy (reference: cross_entropy2_op.cc
    — the soft_label-free fast path; also outputs the matched
    probability)."""
    lab = label.reshape(label.shape[0], -1).astype(jnp.int32)
    match = jnp.take_along_axis(x, lab, axis=-1)
    return -jnp.log(jnp.maximum(match, 1e-20)), match


@register("has_inf", ["X"], ["Out"], differentiable=False)
def has_inf(x):
    """Reference: operators/isfinite_op.cc (overflow check family)."""
    return jnp.any(jnp.isinf(x))


@register("has_nan", ["X"], ["Out"], differentiable=False)
def has_nan(x):
    return jnp.any(jnp.isnan(x))


@register("hash", ["X"], ["Out"], differentiable=False)
def hash_op(x, *, num_hash=1, mod_by=100000000):
    """Reference: operators/hash_op.cc (xxhash of int-id rows). TPU
    redesign: a splitmix-style integer mix per hash seed — same
    contract (deterministic bucketed ids in [0, mod_by)), vectorizes
    on the VPU instead of calling a byte-stream hasher."""
    ids = x.astype(jnp.uint32)
    outs = []
    for seed in range(num_hash):
        h = ids * jnp.uint32(0x9E3779B9) + jnp.uint32(seed * 0x85EBCA6B)
        h = h ^ (h >> 16)
        h = h * jnp.uint32(0x45D9F3B)
        h = h ^ (h >> 16)
        # fold the row's element hashes into one bucket per row
        outs.append(jnp.sum(h, axis=-1, dtype=jnp.uint32))
    out = jnp.stack(outs, axis=-1).astype(jnp.int64)
    return jnp.abs(out) % mod_by
