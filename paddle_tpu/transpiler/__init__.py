"""Distribute/memory transpilers.

Reference: python/paddle/fluid/transpiler/ (distribute_transpiler.py:178
DistributeTranspiler — slices params into blocks :69,:1286, rewrites
trainer programs with send/recv :646, generates pserver programs with
server-side optimize blocks :780; ps_dispatcher.py round-robin/hash
placement; memory_optimization_transpiler.py).

TPU-native split by mode:
  - mode="nccl2" (collective DP): the program is returned untouched and
    topology recorded; the pod mesh + GSPMD collectives replace
    inserted allreduce ops (multihost.init_parallel_env is the
    gen_nccl_id analog). **This is the mode for TPU pods.**
  - PS mode is REAL: get_trainer_program() strips the optimize ops
    (they move server-side — run it through
    distributed.ParameterServerRuntime, which sends grads / recvs
    params around each step), get_pserver_program(endpoint) builds a
    program holding that server's params + their update ops, and the
    distributed package (native tensor_rpc transport + ListenAndServ
    loop) moves grads/params over DCN — the reference's
    send/recv/listen_and_serv path (listen_and_serv_op.cc:109) for CPU
    PS clusters and asynchronous SGD. The ORIGINAL program (optimize
    ops intact) additionally gets the ZeRO-style sharded-state
    BuildStrategy, so pod launches without pservers keep running it
    directly. The optimize-op split is validated lazily, on the first
    call that needs it — transpile() itself accepts any program.
"""

from __future__ import annotations

import warnings
from typing import Dict, List

from ..core.enforce import UnavailableError, enforce
from ..framework import (Parameter, Program, default_main_program,
                         default_startup_program, grad_var_name)
# string constant (resilience.guard.FLAG_KEY) imported lazily-safe:
# guard.py has no transpiler dependency, so the direct import is fine
from ..resilience.guard import FLAG_KEY as _GUARD_FLAG_KEY

__all__ = ["DistributeTranspiler", "DistributeTranspilerConfig",
           "memory_optimize", "release_memory", "HashName",
           "RoundRobin"]


class DistributeTranspilerConfig:
    """Reference: distribute_transpiler.py:130."""

    def __init__(self):
        self.slice_var_up = True
        self.split_method = RoundRobin
        self.min_block_size = 8192
        self.enable_dc_asgd = False
        # DC-ASGD compensation strength (the lambda of g+l*g*g*(w-bak);
        # the reference hardcodes it inside _append_dc_asgd_ops)
        self.dc_asgd_lambda = 0.05
        self.mode = "pserver"
        self.print_log = False
        self.wait_port = True
        self.use_hierarchical_allreduce = False
        self.hierarchical_allreduce_inter_nranks = 0


class _PSDispatcher:
    def __init__(self, pserver_endpoints):
        self._eps = list(pserver_endpoints)
        self._step = 0

    def dispatch(self, varlist):
        raise NotImplementedError

    def reset(self):
        self._step = 0


class RoundRobin(_PSDispatcher):
    """Reference: ps_dispatcher.py RoundRobin."""

    def dispatch(self, varlist):
        out = []
        for _v in varlist:
            out.append(self._eps[self._step % len(self._eps)])
            self._step += 1
        return out


class HashName(_PSDispatcher):
    """Reference: ps_dispatcher.py HashName."""

    def dispatch(self, varlist):
        import zlib
        return [self._eps[zlib.crc32(v.name.encode()) % len(self._eps)]
                for v in varlist]


def _copy_op(dst_block, op):
    return dst_block.append_op(type=op.type, inputs=dict(op.inputs),
                               outputs=dict(op.outputs),
                               attrs=dict(op.attrs))


def _copy_var(dst_block, var, **over):
    if var.name in dst_block.vars:
        return dst_block.vars[var.name]
    kw = dict(name=var.name, shape=var.shape, dtype=var.dtype,
              persistable=var.persistable)
    kw.update(over)
    if isinstance(var, Parameter) and not over:
        return dst_block.create_parameter(**kw)
    return dst_block.create_var(**kw)


class DistributeTranspiler:
    """Reference: distribute_transpiler.py:178 (see module docstring
    for the TPU mapping)."""

    def __init__(self, config=None):
        self.config = config or DistributeTranspilerConfig()
        self._transpiled = False

    def transpile(self, trainer_id, program=None,
                  pservers="127.0.0.1:6170", trainers=1, sync_mode=True,
                  startup_program=None,
                  current_endpoint="127.0.0.1:6170"):
        self.trainer_id = trainer_id
        self.trainer_num = trainers if isinstance(trainers, int) \
            else len(trainers.split(","))
        self.sync_mode = sync_mode
        self.origin_program = program or default_main_program()
        self.startup_program = startup_program or \
            default_startup_program()
        self.pserver_endpoints = pservers.split(",")
        self.current_endpoint = current_endpoint
        self._split_done = False
        self._transpiled = True
        if self.config.mode == "nccl2":
            # collective mode: topology only; the pod mesh + GSPMD
            # collectives replace inserted allreduce ops
            return
        # Annotate for pod execution: dense parameter serving maps to
        # ZeRO-sharded state when the ORIGINAL program runs WITHOUT
        # pservers. The PS split itself is computed lazily so models
        # this PS split can't express (LR schedules, global clip)
        # still transpile for pod use.
        from ..compiler import BuildStrategy
        bs = BuildStrategy()
        bs.reduce_strategy = BuildStrategy.ReduceStrategy.Reduce
        bs.num_trainers = self.trainer_num
        bs.trainer_id = trainer_id
        self.origin_program._build_strategy = bs

    # -- analysis -----------------------------------------------------------
    def _ensure_split(self):
        enforce(self._transpiled, "call transpile() first")
        enforce(self.config.mode != "nccl2",
                "PS products are undefined in nccl2 (collective) mode")
        if not self._split_done:
            self._split_optimize_ops()
            self._split_done = True

    def _split_optimize_ops(self):
        """Group op_role=optimize ops by the parameter they update (the
        analog of the reference's per-param pserver optimize blocks,
        get_pserver_program:780). Ops reachable into more than one
        param's update (shared counters, global-norm clip, lr
        schedules) have no per-param home — the reference runs them in
        a dedicated server block; unsupported here."""
        block = self.origin_program.global_block()
        opt_ops = [op for op in block.ops
                   if op.attrs.get("op_role") == "optimize"]
        self._opt_ops = opt_ops
        pos = {id(op): i for i, op in enumerate(block.ops)}
        produced: Dict[str, List] = {}
        for op in opt_ops:
            for n in op.output_arg_names:
                produced.setdefault(n, []).append(op)

        def closure(op, acc):
            if id(op) in acc:
                return
            acc[id(op)] = op
            for n in op.input_arg_names:
                for prod in produced.get(n, []):
                    closure(prod, acc)

        self._param_ops: Dict[str, List] = {}
        owner: Dict[int, str] = {}
        shared = set()
        for op in opt_ops:
            pnames = op.input("Param")
            if not pnames:
                continue
            pname = pnames[0]
            acc: Dict[int, object] = {}
            closure(op, acc)
            self._param_ops[pname] = sorted(
                acc.values(), key=lambda o: pos[id(o)])
            for oid in acc:
                if oid in owner and owner[oid] != pname:
                    shared.add(oid)
                owner[oid] = pname
        if shared:
            types = sorted({o.type for ops in self._param_ops.values()
                            for o in ops if id(o) in shared})
            raise UnavailableError(
                "PS mode cannot split optimize ops shared across "
                "parameters (%s) — global-norm clip / LR schedules / "
                "shared counters run per server block in the "
                "reference; use a constant learning rate and per-param "
                "clip, or collective (nccl2) mode" % ", ".join(types))
        covered = {id(o) for ops in self._param_ops.values()
                   for o in ops}
        # Server-side ops may only consume: the param's grad, persistable
        # state, or values produced inside their own group. A value
        # computed by regular trainer ops each step (decayed LR, global
        # grad norm) has no transport here — the reference gives those a
        # dedicated server block (:1527); unsupported.
        opt_ids = {id(o) for o in opt_ops}
        produced_by_trainer = set()
        for op in block.ops:
            if id(op) not in opt_ids:
                produced_by_trainer.update(op.output_arg_names)
        for pname, ops in self._param_ops.items():
            internal = {n for o in ops for n in o.output_arg_names}
            for o in ops:
                for n in o.input_arg_names:
                    if n in internal or n == grad_var_name(pname):
                        continue
                    v = block._find_var_recursive(n)
                    if v is not None and v.persistable:
                        continue
                    if n in produced_by_trainer:
                        raise UnavailableError(
                            "PS mode: update of %r consumes %r which "
                            "is recomputed by trainer ops every step "
                            "(LR schedule / global clip?). Use a "
                            "constant learning rate and per-param "
                            "clip, or collective (nccl2) mode"
                            % (pname, n))
        dangling = [op.type for op in opt_ops
                    if id(op) not in covered]
        if dangling:
            warnings.warn("optimize ops with no Param slot stay on the "
                          "trainer: %s" % sorted(set(dangling)))
        self._make_blocks()
        # placement over BLOCKS (the reference places VarBlocks
        # round-robin/hash, :1286 _init_splited_vars)
        dispatcher = self.config.split_method(self.pserver_endpoints)
        blocks = [b for p in sorted(self._blocks)
                  for b in self._blocks[p]]
        eps = dispatcher.dispatch(
            [type("V", (), {"name": b["name"]}) for b in blocks])
        for b, ep in zip(blocks, eps):
            b["endpoint"] = ep
        # param-level placement view (unsliced params: their single
        # block's endpoint; sliced: endpoint of block 0 for display)
        self._placement = {p: self._blocks[p][0]["endpoint"]
                           for p in self._blocks}

    def _sliceable(self, pname):
        """A param can block-slice when its single update op's
        tensor-state inputs/outputs are all param-shaped (row slicing
        stays consistent) or scalars (replicated per block)."""
        if not self.config.slice_var_up:
            return False
        if len(self.pserver_endpoints) < 2:
            return False
        src = self.origin_program.global_block()
        p = src.vars[pname]
        numel = 1
        for d in p.shape:
            numel *= d
        if not p.shape or p.shape[0] < len(self.pserver_endpoints) \
                or numel < self.config.min_block_size:
            return False
        for op in self._param_ops[pname]:
            for n in set(op.input_arg_names) | \
                    set(op.output_arg_names):
                v = src._find_var_recursive(n)
                if v is None:
                    continue
                if v.shape not in ((), p.shape) and \
                        n != grad_var_name(pname):
                    return False
        return True

    def _make_blocks(self):
        """Slice large params into row blocks, one per pserver
        (reference: VarBlock :69 + slice_var_up; blocks here are
        per-endpoint contiguous row ranges rather than fixed-size
        chunks — same balancing effect, simpler reassembly)."""
        src = self.origin_program.global_block()
        n_eps = len(self.pserver_endpoints)
        self._blocks: Dict[str, List[dict]] = {}
        for pname in sorted(self._param_ops):
            p = src.vars[pname]
            if self._sliceable(pname):
                rows = p.shape[0]
                base, extra = divmod(rows, n_eps)
                blocks, start = [], 0
                for k in range(n_eps):
                    size = base + (1 if k < extra else 0)
                    blocks.append({
                        "param": pname,
                        "name": "%s.block%d" % (pname, k),
                        "start": start, "end": start + size,
                        "shape": (size,) + tuple(p.shape[1:])})
                    start += size
                self._blocks[pname] = blocks
            else:
                self._blocks[pname] = [{
                    "param": pname, "name": pname, "start": 0,
                    "end": p.shape[0] if p.shape else 1,
                    "shape": tuple(p.shape)}]

    def block_table(self) -> Dict[str, List[dict]]:
        """param -> [{name, endpoint, start, end, shape}] — the
        trainer runtime's send/recv plan."""
        self._ensure_split()
        return {p: [dict(b) for b in bs]
                for p, bs in self._blocks.items()}

    def set_block_endpoints(self, block_names, endpoint):
        """Re-point blocks at a live endpoint (launchers bind
        ephemeral ports after transpile; the reference's wait_port
        dance). The endpoint universe follows the remap, so pserver
        products (params_on / get_pserver_program) stay reachable
        under the LIVE endpoint — a restarted PServerRuntime builds
        against the port it actually serves."""
        self._ensure_split()
        names = set(block_names)
        olds = set()
        for pname, bs in self._blocks.items():
            for b in bs:
                if b["name"] in names:
                    olds.add(b.get("endpoint"))
                    b["endpoint"] = endpoint
            self._placement[pname] = bs[0]["endpoint"]
        self.pserver_endpoints = [endpoint if ep in olds else ep
                                  for ep in self.pserver_endpoints]

    # -- products -----------------------------------------------------------
    def get_trainer_program(self, wait_port=True) -> Program:
        enforce(self._transpiled, "call transpile() first")
        if self.config.mode == "nccl2":
            return self.origin_program
        self._ensure_split()
        split = {id(o) for ops in self._param_ops.values() for o in ops}
        trainer = self.origin_program.clone()
        blk = trainer.global_block()
        orig_ops = self.origin_program.global_block().ops
        keep = [i for i, op in enumerate(orig_ops)
                if id(op) not in split]
        blk.ops = [blk.ops[i] for i in keep]
        trainer._bump()
        from ..analysis import maybe_verify_rewrite
        maybe_verify_rewrite(trainer, "ps_trainer_split")
        return trainer

    def _block_rename(self, pname, binfo):
        """Name map for one block of a sliced param: param-shaped vars
        (param, grad, same-shape accumulators) and written scalars get
        a .block{k} suffix; input-only scalars (the LR) stay shared."""
        if binfo["name"] == pname:
            return {grad_var_name(pname): grad_var_name(pname)}
        suffix = binfo["name"][len(pname):]        # ".block{k}"
        src = self.origin_program.global_block()
        p_shape = tuple(src.vars[pname].shape)
        written = {n for op in self._param_ops[pname]
                   for n in op.output_arg_names}
        rename = {}
        for op in self._param_ops[pname]:
            for n in set(op.input_arg_names) | \
                    set(op.output_arg_names):
                v = src._find_var_recursive(n)
                if v is None:
                    continue
                if tuple(v.shape) == p_shape or \
                        (v.shape == () and n in written):
                    rename[n] = n + suffix
        rename[grad_var_name(pname)] = grad_var_name(binfo["name"])
        return rename

    def _append_param_ops(self, prog, pname, binfo=None):
        src = self.origin_program.global_block()
        blk = prog.global_block()
        binfo = binfo or self._blocks[pname][0]
        rename = self._block_rename(pname, binfo)
        bshape = tuple(binfo["shape"])
        p_shape = tuple(src.vars[pname].shape)

        def new_shape(v):
            return bshape if tuple(v.shape) == p_shape else v.shape

        for op in self._param_ops[pname]:
            for n in op.input_arg_names:
                v = src._find_var_recursive(n)
                if v is None:
                    continue
                if n == grad_var_name(pname):
                    _copy_var(blk, v, persistable=False, is_data=True,
                              name=rename.get(n, n), shape=bshape)
                else:
                    _copy_var(blk, v, name=rename.get(n, n),
                              shape=new_shape(v),
                              persistable=v.persistable)
            for n in op.output_arg_names:
                v = src._find_var_recursive(n)
                if v is not None:
                    _copy_var(blk, v, name=rename.get(n, n),
                              shape=new_shape(v),
                              persistable=v.persistable)
            attrs = dict(op.attrs)
            # anomaly-guard gates are trainer-side in-graph state: the
            # all-finite flag is derived from the traced step's raw
            # gradients by the guard plan, which cannot exist in a
            # standalone server-side update program — a copied gate
            # would read an undefined key and kill the pserver trace
            # (found by analysis.composition_matrix, guard x PS).
            if attrs.get("gate") == _GUARD_FLAG_KEY:
                attrs.pop("gate")
            blk.append_op(
                type=op.type,
                inputs={sl: [rename.get(n, n) for n in ns]
                        for sl, ns in op.inputs.items()},
                outputs={sl: [rename.get(n, n) for n in ns]
                         for sl, ns in op.outputs.items()},
                attrs=attrs)
        return prog

    def get_param_program(self, pname) -> Program:
        """One param's server-side update as a standalone program (the
        per-param optimize block, reference :780); its Grad var is the
        feed. Sliced params: use get_block_program per block."""
        self._ensure_split()
        return self._append_param_ops(Program(), pname)

    def get_block_program(self, block_name) -> Program:
        """Standalone update program for one VarBlock (reference:
        VarBlock :69 + per-block optimize blocks)."""
        self._ensure_split()
        for pname, bs in self._blocks.items():
            for b in bs:
                if b["name"] == block_name:
                    return self._append_param_ops(Program(), pname, b)
        raise UnavailableError("unknown block %r" % block_name)

    def get_pserver_program(self, endpoint) -> Program:
        """Program holding this endpoint's param BLOCKS, their
        optimizer state, and update ops; each Grad input becomes a
        feed var. (Reference: get_pserver_program:780.)"""
        self._ensure_split()
        enforce(endpoint in self.pserver_endpoints,
                "endpoint %r not in %s" % (endpoint,
                                           self.pserver_endpoints))
        prog = Program()
        for pname in sorted(self._blocks):
            for b in self._blocks[pname]:
                if b["endpoint"] == endpoint:
                    self._append_param_ops(prog, pname, b)
        from ..analysis import maybe_verify_rewrite
        maybe_verify_rewrite(prog, "ps_pserver_split")
        return prog

    def params_on(self, endpoint) -> List[str]:
        """Block names served by this endpoint."""
        self._ensure_split()
        return sorted(b["name"] for bs in self._blocks.values()
                      for b in bs if b["endpoint"] == endpoint)

    def get_pserver_programs(self, endpoint):
        return (self.get_pserver_program(endpoint),
                self.get_startup_program(endpoint))

    def get_startup_program(self, endpoint=None, pserver_program=None,
                            startup_program=None) -> Program:
        """Init ops (from the trainer startup program) for the vars the
        pserver program owns. ``endpoint`` defaults to the
        current_endpoint recorded by transpile()."""
        enforce(self._transpiled, "call transpile() first")
        self._ensure_split()
        if endpoint is None:
            endpoint = self.current_endpoint
        pserver_program = pserver_program or \
            self.get_pserver_program(endpoint)
        pvars = pserver_program.global_block().vars
        want = {n for n, v in pvars.items() if v.persistable}
        src = self.startup_program.global_block()
        prog = Program()
        prog.random_seed = self.startup_program.random_seed
        blk = prog.global_block()

        import re
        block_re = re.compile(r"^(.*)\.block(\d+)$")
        init_of = {}
        for op in src.ops:
            for n in op.output_arg_names:
                init_of[n] = op

        copied = set()
        for name in sorted(want):
            v = pvars[name]
            m = block_re.match(name)
            base = m.group(1) if m else name
            op = init_of.get(base)
            _copy_var(blk, v, name=name, shape=v.shape,
                      persistable=True)
            if op is None:
                continue
            if not m:
                if id(op) not in copied:
                    copied.add(id(op))
                    _copy_op(blk, op)
                continue
            # sliced var: re-emit the init with the block's shape
            # (random inits redraw per block — trainers adopt server
            # values via init_params, so only the distribution must
            # match; deterministic inits slice exactly)
            attrs = dict(op.attrs)
            if "shape" in attrs:
                attrs["shape"] = tuple(v.shape)
            if op.type == "assign_numpy_value":
                import numpy as _np
                start = next(b["start"]
                             for b in self._blocks[base]
                             if b["name"] == name)
                end = next(b["end"] for b in self._blocks[base]
                           if b["name"] == name)
                attrs["_value"] = _np.asarray(
                    attrs["_value"])[start:end]
            blk.append_op(type=op.type, inputs=dict(op.inputs),
                          outputs={next(iter(op.outputs)): [name]},
                          attrs=attrs)
        return prog

    # -- runtime hooks (consumed by distributed.ps) -------------------------
    def param_placement(self) -> Dict[str, str]:
        self._ensure_split()
        return dict(self._placement)

    def grad_to_param(self) -> Dict[str, str]:
        """grad var name -> param name, for the trainer's send loop."""
        self._ensure_split()
        return {grad_var_name(p): p for p in self._param_ops}

    def param_grad_table(self) -> Dict[str, str]:
        """param -> the Grad var its update op consumes (feed name on
        the pserver)."""
        self._ensure_split()
        return {p: grad_var_name(p) for p in self._param_ops}


def memory_optimize(input_program, skip_opt_set=None, print_log=False,
                    level=0, skip_grads=True):
    """Reference: memory_optimization_transpiler.py — var-reuse
    rewriting. XLA's buffer assignment performs this; parity no-op."""
    return input_program


def release_memory(input_program, skip_opt_set=None):
    return input_program
