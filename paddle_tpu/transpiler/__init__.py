"""Distribute/memory transpilers — API-compatible front ends.

Reference: python/paddle/fluid/transpiler/ (distribute_transpiler.py:178
DistributeTranspiler — slices params into blocks :69,:1286, rewrites
trainer programs with send/recv :646, generates pserver programs with
server-side optimize blocks :780; ps_dispatcher.py round-robin/hash
placement; memory_optimization_transpiler.py).

TPU-native redesign: the parameter-server topology dissolves. Dense
params + optimizer state shard over the mesh (ZeRO-style
ReduceStrategy.Reduce — the kReduce strategy was exactly the PS
update-sharding idea in-graph), and collectives replace send/recv.
``DistributeTranspiler`` keeps the reference's API so launch scripts
run unchanged:
  - mode="nccl2" (collective DP): returns the program untouched and
    records trainer topology; run it under CompiledProgram/fleet with
    a pod mesh (multihost.init_parallel_env is the gen_nccl_id
    analog).
  - PS mode: get_trainer_program() returns the original program
    configured for sharded-state execution; get_pserver_program()
    raises with guidance — there is no separate server process to run
    on a TPU pod.
"""

from __future__ import annotations

from ..core.enforce import UnavailableError, enforce
from ..framework import Program, default_main_program

__all__ = ["DistributeTranspiler", "DistributeTranspilerConfig",
           "memory_optimize", "release_memory", "HashName",
           "RoundRobin"]


class DistributeTranspilerConfig:
    """Reference: distribute_transpiler.py:130."""

    def __init__(self):
        self.slice_var_up = True
        self.split_method = RoundRobin
        self.min_block_size = 8192
        self.enable_dc_asgd = False
        self.mode = "pserver"
        self.print_log = False
        self.wait_port = True
        self.use_hierarchical_allreduce = False
        self.hierarchical_allreduce_inter_nranks = 0


class _PSDispatcher:
    def __init__(self, pserver_endpoints):
        self._eps = list(pserver_endpoints)
        self._step = 0

    def dispatch(self, varlist):
        raise NotImplementedError


class RoundRobin(_PSDispatcher):
    """Reference: ps_dispatcher.py RoundRobin."""

    def dispatch(self, varlist):
        out = []
        for _v in varlist:
            out.append(self._eps[self._step % len(self._eps)])
            self._step += 1
        return out


class HashName(_PSDispatcher):
    """Reference: ps_dispatcher.py HashName."""

    def dispatch(self, varlist):
        import zlib
        return [self._eps[zlib.crc32(v.name.encode()) % len(self._eps)]
                for v in varlist]


class DistributeTranspiler:
    """Reference: distribute_transpiler.py:178 (see module docstring
    for the TPU mapping)."""

    def __init__(self, config=None):
        self.config = config or DistributeTranspilerConfig()
        self._transpiled = False

    def transpile(self, trainer_id, program=None, pservers="127.0.0.1:6170",
                  trainers=1, sync_mode=True, startup_program=None,
                  current_endpoint="127.0.0.1:6170"):
        self.trainer_id = trainer_id
        self.trainer_num = trainers if isinstance(trainers, int) \
            else len(trainers.split(","))
        self.sync_mode = sync_mode
        self.origin_program = program or default_main_program()
        self.pserver_endpoints = pservers.split(",")
        self._transpiled = True
        if self.config.mode == "nccl2":
            # collective mode: topology only; the pod mesh + GSPMD
            # collectives replace inserted allreduce ops
            return
        # PS mode: dense parameter serving maps to ZeRO-sharded state;
        # annotate the program so CompiledProgram defaults to Reduce
        from ..compiler import BuildStrategy
        bs = BuildStrategy()
        bs.reduce_strategy = BuildStrategy.ReduceStrategy.Reduce
        bs.num_trainers = self.trainer_num
        bs.trainer_id = trainer_id
        self.origin_program._build_strategy = bs

    def get_trainer_program(self, wait_port=True) -> Program:
        enforce(self._transpiled, "call transpile() first")
        return self.origin_program

    def get_pserver_program(self, endpoint):
        raise UnavailableError(
            "there are no parameter-server processes on a TPU pod: "
            "dense parameters shard over the device mesh "
            "(BuildStrategy.ReduceStrategy.Reduce — already set on the "
            "trainer program by transpile()); launch every process as "
            "a trainer with parallel.multihost.init_parallel_env()")

    def get_pserver_programs(self, endpoint):
        return self.get_pserver_program(endpoint)

    def get_startup_program(self, endpoint=None, pserver_program=None,
                            startup_program=None):
        return self.get_pserver_program(endpoint)


def memory_optimize(input_program, skip_opt_set=None, print_log=False,
                    level=0, skip_grads=True):
    """Reference: memory_optimization_transpiler.py — var-reuse
    rewriting. XLA's buffer assignment performs this; parity no-op."""
    return input_program


def release_memory(input_program, skip_opt_set=None):
    return input_program
