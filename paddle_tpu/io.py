"""Checkpoint save/load + inference-model export (reference:
python/paddle/fluid/io.py — save_vars:98, save_params, save_persistables
:462, load_vars, load_persistables:698, save_inference_model:903,
load_inference_model:1083; tensor wire format mirrors
framework/lod_tensor.h:214 SerializeToStream's versioned header).

TPU-native difference: the reference appends `save`/`save_combine` ops
and executes them inside the graph; here params are fetched from the
scope (device→host once per checkpoint) and written host-side — there is
no op-level graph to splice into, and checkpointing shouldn't invalidate
the compiled step program.
"""

from __future__ import annotations

import json
import os
import pickle
import shutil
import struct
import threading
from typing import List, Optional

import numpy as np

from . import framework
from . import observability as _obs
from .core.enforce import InvalidArgumentError, enforce
from .core.scope import global_scope
from .framework import Parameter, Program, Variable, default_main_program

__all__ = ["save_vars", "save_params", "save_persistables", "load_vars",
           "load_params", "load_persistables", "save_inference_model",
           "load_inference_model", "get_program_persistable_vars",
           "infer_signature"]

_TENSOR_MAGIC = b"PTPU"
_TENSOR_VERSION = 1


def _fsync_dir(path):
    """fsync a DIRECTORY so its entries (new files, renames) are
    durable, not merely in the page cache. No-op on platforms whose
    directory handles refuse fsync (Windows)."""
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


# ---------------------------------------------------------------------------
# tensor wire format
# ---------------------------------------------------------------------------

def serialize_tensor(arr: np.ndarray) -> bytes:
    """magic | u32 version | u16 len(dtype) | dtype utf8 | u32 ndim |
    i64 dims... | payload (C-order)."""
    arr = np.ascontiguousarray(arr)
    dt = arr.dtype.name.encode()
    head = _TENSOR_MAGIC + struct.pack("<IH", _TENSOR_VERSION, len(dt))
    head += dt + struct.pack("<I", arr.ndim)
    head += struct.pack("<%dq" % arr.ndim, *arr.shape)
    return head + arr.tobytes()


def deserialize_tensor(buf: bytes, offset: int = 0):
    """Returns (ndarray, next_offset)."""
    enforce(buf[offset:offset + 4] == _TENSOR_MAGIC,
            "bad tensor magic — corrupt or foreign checkpoint")
    offset += 4
    version, dlen = struct.unpack_from("<IH", buf, offset)
    enforce(version == _TENSOR_VERSION,
            "unsupported tensor version %d" % version)
    offset += 6
    dtype = np.dtype(buf[offset:offset + dlen].decode())
    offset += dlen
    (ndim,) = struct.unpack_from("<I", buf, offset)
    offset += 4
    dims = struct.unpack_from("<%dq" % ndim, buf, offset)
    offset += 8 * ndim
    count = int(np.prod(dims)) if ndim else 1
    nbytes = count * dtype.itemsize
    arr = np.frombuffer(buf, dtype=dtype, count=count,
                        offset=offset).reshape(dims)
    return arr.copy(), offset + nbytes


# ---------------------------------------------------------------------------
# var save/load
# ---------------------------------------------------------------------------

def _is_persistable(var) -> bool:
    return bool(var.persistable) and not var.is_data


def _is_parameter(var) -> bool:
    return isinstance(var, Parameter)


def get_program_persistable_vars(program) -> List[Variable]:
    return [v for v in program.list_vars() if _is_persistable(v)]


def _collect(program, vars, predicate):
    program = program or default_main_program()
    if vars is None:
        vars = [v for v in program.list_vars() if predicate(v)]
    return program, vars


def _fetch_numpy(value):
    enforce(value is not None, "variable has no value in scope — "
            "did you run the startup program?")
    return np.asarray(value)


def save_vars(executor=None, dirname=None, main_program=None, vars=None,
              predicate=None, filename=None, scope=None):
    """Save selected vars under ``dirname`` — one file per var, or a
    single combined ``filename`` (the save_combine path, reference
    io.py:98/save_combine_op.cc)."""
    scope = scope or global_scope()
    program, vars = _collect(main_program, vars,
                             predicate or _is_persistable)
    os.makedirs(dirname, exist_ok=True)
    if filename is None:
        for v in vars:
            arr = _fetch_numpy(scope.find_var(v.name))
            with open(os.path.join(dirname, v.name), "wb") as f:
                f.write(serialize_tensor(arr))
    else:
        with open(os.path.join(dirname, filename), "wb") as f:
            names = [v.name for v in vars]
            f.write(struct.pack("<I", len(names)))
            for n in names:
                nb = n.encode()
                f.write(struct.pack("<H", len(nb)) + nb)
            for v in vars:
                arr = _fetch_numpy(scope.find_var(v.name))
                f.write(serialize_tensor(arr))


def save_params(executor=None, dirname=None, main_program=None,
                filename=None, scope=None):
    return save_vars(executor, dirname, main_program, None,
                     _is_parameter, filename, scope)


def save_persistables(executor=None, dirname=None, main_program=None,
                      filename=None, scope=None):
    return save_vars(executor, dirname, main_program, None,
                     _is_persistable, filename, scope)


def load_vars(executor=None, dirname=None, main_program=None, vars=None,
              predicate=None, filename=None, scope=None):
    """Mirror of save_vars (reference io.py:498). Shape/dtype are
    validated against the program's declaration."""
    scope = scope or global_scope()
    program, vars = _collect(main_program, vars,
                             predicate or _is_persistable)
    if filename is None:
        for v in vars:
            path = os.path.join(dirname, v.name)
            if not os.path.exists(path) and _ckpt_optional(v):
                _default_fill(scope, v)
                continue
            enforce(os.path.exists(path),
                    "checkpoint file missing for var %r: %s"
                    % (v.name, path))
            with open(path, "rb") as f:
                arr, _ = deserialize_tensor(f.read())
            _check_and_set(scope, v, arr)
    else:
        with open(os.path.join(dirname, filename), "rb") as f:
            buf = f.read()
        (n,) = struct.unpack_from("<I", buf, 0)
        off = 4
        names = []
        for _ in range(n):
            (ln,) = struct.unpack_from("<H", buf, off)
            off += 2
            names.append(buf[off:off + ln].decode())
            off += ln
        tensors = {}
        for name in names:
            arr, off = deserialize_tensor(buf, off)
            tensors[name] = arr
        for v in vars:
            if v.name not in tensors and _ckpt_optional(v):
                _default_fill(scope, v)
                continue
            enforce(v.name in tensors,
                    "var %r not in combined checkpoint" % v.name)
            _check_and_set(scope, v, tensors[v.name])


def _ckpt_optional(v) -> bool:
    """Vars a checkpoint may legitimately lack: subsystems added AFTER
    a checkpoint was written (the anomaly guard's counters) mark their
    vars ``_ckpt_optional`` so old checkpoints stay loadable — the var
    default-fills instead of failing the whole restore. The name-prefix
    fallback keeps the property through to_dict/from_dict round-trips,
    which do not carry ad-hoc attributes."""
    return bool(getattr(v, "_ckpt_optional", False)) \
        or v.name.startswith("__guard_")


def _default_fill(scope, v):
    shape = tuple(int(d) for d in v.shape if d != -1)
    scope.set_var(v.name, np.zeros(shape, np.dtype(v.dtype)))


def _check_and_set(scope, v, arr):
    geom = getattr(v, "_shard_geometry", None)
    if geom is not None:
        # sharded optimizer slot (collectives.ensure_sharded_state):
        # declared (padded,). A checkpoint written under the same world
        # size holds exactly that; a replicated-era checkpoint holds
        # the full param shape — pad-flatten it into the shard layout
        # (value-preserving, the same conversion ensure applies to
        # scope values).
        numel, padded = geom
        if tuple(arr.shape) != (padded,) and arr.size == numel:
            flat = np.zeros((padded,), arr.dtype)
            flat[:numel] = arr.reshape(-1)
            arr = flat
    want = tuple(int(d) for d in v.shape if d != -1)
    got = tuple(arr.shape)
    if want and got != want:
        hint = ""
        if geom is not None:
            hint = (" — sharded slot: the padded shard length depends "
                    "on world size; restore under the same device "
                    "count the checkpoint was saved with")
        raise InvalidArgumentError(
            "shape mismatch loading %r: checkpoint %s vs program %s%s"
            % (v.name, got, want, hint))
    scope.set_var(v.name, arr)


def load_params(executor=None, dirname=None, main_program=None,
                filename=None, scope=None):
    return load_vars(executor, dirname, main_program, None,
                     _is_parameter, filename, scope)


def load_persistables(executor=None, dirname=None, main_program=None,
                      filename=None, scope=None):
    return load_vars(executor, dirname, main_program, None,
                     _is_persistable, filename, scope)


# ---------------------------------------------------------------------------
# inference model
# ---------------------------------------------------------------------------

SIGNATURE_FILENAME = "__signature__.json"


def infer_signature(program, feed_names, fetch_vars):
    """Model I/O signature: per-tensor name, dtype, and per-dim
    static/dynamic sizes (-1 = bound at trace time, by convention the
    batch dim). Saved as a human-readable sidecar next to ``__model__``
    so a serving layer can derive warmup shape buckets without user
    hints; also derivable live from any loaded program (old models
    without the sidecar lose nothing)."""
    blk = program.global_block()

    def entry(v):
        dims = [int(d) for d in v.shape]
        return {"name": v.name, "dtype": str(v.dtype), "shape": dims,
                "dynamic_dims": [i for i, d in enumerate(dims)
                                 if d == -1]}

    inputs = []
    for n in feed_names:
        v = blk.vars.get(n)
        if v is None:
            # a feed name the inference prune dropped (declared for
            # training, unused by the served targets) — legal in the
            # reference's save path, so the signature records it
            # shape-less instead of failing the save
            inputs.append({"name": n, "dtype": None, "shape": None,
                           "dynamic_dims": []})
        else:
            inputs.append(entry(v))
    outputs = [entry(blk.vars[t.name if isinstance(t, Variable) else t])
               for t in fetch_vars]
    return {"version": 1, "inputs": inputs, "outputs": outputs}


def save_inference_model(dirname, feeded_var_names, target_vars,
                         executor=None, main_program=None,
                         model_filename=None, params_filename=None,
                         scope=None):
    """Prune to the inference slice and persist program + params
    (reference io.py:903). Returns the target var names."""
    main_program = main_program or default_main_program()
    enforce(isinstance(feeded_var_names, (list, tuple)),
            "feeded_var_names must be a list of names")
    targets = list(target_vars)
    inf_prog = main_program.clone(for_test=True)._prune(targets)
    target_names = [t.name if isinstance(t, Variable) else t
                    for t in targets]
    desc = {"program": inf_prog.to_dict(),
            "feed_names": list(feeded_var_names),
            "fetch_names": target_names}
    os.makedirs(dirname, exist_ok=True)
    model_path = os.path.join(dirname, model_filename or "__model__")
    with open(model_path, "wb") as f:
        pickle.dump(desc, f, protocol=4)
    # signature sidecar (input names/dtypes/static-vs-dynamic dims):
    # lets a serving engine pre-compile its shape buckets at load
    # without user hints; readers tolerate its absence (old models)
    sig = infer_signature(inf_prog, list(feeded_var_names), target_names)
    with open(os.path.join(dirname, SIGNATURE_FILENAME), "w") as f:
        json.dump(sig, f, indent=1, sort_keys=True)
    save_persistables(executor, dirname, inf_prog,
                      filename=params_filename, scope=scope)
    return target_names


def load_inference_model(dirname, executor=None, model_filename=None,
                         params_filename=None, scope=None):
    """Returns (program, feed_names, fetch_vars) (reference
    io.py:1083)."""
    model_path = os.path.join(dirname, model_filename or "__model__")
    enforce(os.path.exists(model_path),
            "no inference model at %s" % model_path)
    with open(model_path, "rb") as f:
        desc = pickle.load(f)
    program = Program.from_dict(desc["program"])
    load_persistables(executor, dirname, program,
                      filename=params_filename, scope=scope)
    blk = program.global_block()
    fetch_vars = [blk.var(n) for n in desc["fetch_names"]]
    # surface the signature sidecar when present; a missing or corrupt
    # sidecar must never fail an otherwise-loadable model (pre-sidecar
    # models), so consumers re-derive from the program declaration
    program._inference_signature = None
    sig_path = os.path.join(dirname, SIGNATURE_FILENAME)
    if os.path.exists(sig_path):
        try:
            with open(sig_path) as f:
                program._inference_signature = json.load(f)
        except (OSError, ValueError):
            import warnings
            warnings.warn("ignoring unreadable signature sidecar %s"
                          % sig_path)
    return program, desc["feed_names"], fetch_vars


# ---------------------------------------------------------------------------
# Asynchronous / preemption-aware checkpointing


class _AsyncSave:
    """Handle for an in-flight background save."""

    def __init__(self, thread, error):
        self._thread = thread
        self._error = error

    def wait(self, timeout=None):
        self._thread.join(timeout)
        if self._error:
            raise self._error[0]

    def done(self):
        return not self._thread.is_alive()


def durable_publish_dir(dirname, final_name, files, marker="_COMPLETE",
                        marker_text="", file_hook=None):
    """Publish ``files`` (an iterable of ``(name, bytes)``) as
    ``dirname/final_name`` with the crash/power-loss-safe ordering the
    CheckpointSaver pioneered (PR 2):

    1. every file is written AND fsynced into a ``.tmp-<final_name>-*``
       dir;
    2. the ``marker`` file is written + fsynced INSIDE the tmp dir,
       last — a marker can never exist next to unsynced data;
    3. the tmp dir itself is fsynced (directory entries durable);
    4. ONE ``os.rename`` publishes the dir atomically, then the parent
       dir is fsynced so the rename itself is durable.

    A crash anywhere before (4) strands only an invisible tmp dir
    (callers sweep those at init); after (4) the dir is complete by
    construction. An existing ``final_name`` is removed unmark-first
    (``remove_marked_dir``) so a kill mid-replace can never leave a
    marked-but-partial dir. ``file_hook(name, index)`` is the chaos
    seam, called after each data file lands."""
    tmp = os.path.join(dirname, ".tmp-%s-%d" % (final_name,
                                                os.getpid()))
    os.makedirs(tmp, exist_ok=True)
    for i, (name, blob) in enumerate(files):
        with open(os.path.join(tmp, name), "wb") as f:
            f.write(blob)
            f.flush()
            os.fsync(f.fileno())
        if file_hook is not None:
            file_hook(name, i)
    with open(os.path.join(tmp, marker), "w") as f:
        f.write(marker_text)
        f.flush()
        os.fsync(f.fileno())
    _fsync_dir(tmp)
    final = os.path.join(dirname, final_name)
    if os.path.exists(final):
        remove_marked_dir(final, marker)
    os.rename(tmp, final)
    _fsync_dir(dirname)
    return final


def remove_marked_dir(d, marker="_COMPLETE"):
    """Delete a published dir with the marker removed FIRST (the commit
    point): unmarking makes the dir invisible to readers, so a kill
    mid-rmtree can never leave a marked-but-partial dir (rmtree's
    deletion order is arbitrary — the marker could otherwise outlive
    the files it vouches for). Callers sweep unmarked dirs at init."""
    try:
        os.remove(os.path.join(d, marker))
        _fsync_dir(d)
    except OSError:
        pass
    shutil.rmtree(d, ignore_errors=True)


class CheckpointSaver:
    """Preemption-aware, asynchronous checkpointing.

    Reference: the PS checkpoint machinery — checkpoint_notify op +
    server-side save blocks (distribute_transpiler.py:1612,
    checkpoint_notify_op.cc:87) and fleet save_persistables
    (pslib/__init__.py:188). The reference's story is "each component
    saves its shard on notify"; the TPU-native redesign:

      - ``save(step)`` SNAPSHOTS the persistables on the calling thread
        (device→host copies — fast) and writes files on a background
        thread, so training never blocks on the filesystem;
      - each checkpoint is a ``ckpt-<step>/`` directory made visible
        ATOMICALLY by writing a ``_COMPLETE`` marker last — a writer
        killed mid-save (preemption) can never be mistaken for a valid
        checkpoint, and ``restore_latest`` skips incomplete dirs
        (the recordio corrupt-tail philosophy applied to checkpoints);
      - ``install_signal_handler()`` hooks SIGTERM (the preemption
        notice) to flush a final synchronous save before exit;
      - ``max_to_keep`` prunes old complete checkpoints.

    Only worker 0 should save in multi-process runs (fleet handles
    this in its save_persistables; here pass ``only_rank0=True``).
    """

    MARKER = "_COMPLETE"

    def __init__(self, dirname, main_program=None, max_to_keep=3,
                 scope=None, only_rank0=True):
        enforce(int(max_to_keep) >= 1, "max_to_keep must be >= 1")
        self._dir = dirname
        self._program = main_program
        self._max_to_keep = int(max_to_keep)
        self._scope = scope
        self._only_rank0 = only_rank0
        self._inflight = None
        self._last_step = None
        self._last_snapshot = None
        self._last_write_error = None
        # test seam: called as (step, name, index) after each data file
        # lands in the tmp dir (resilience.faults crashes the writer
        # here to prove torn writes stay invisible)
        self._write_file_hook = None
        os.makedirs(dirname, exist_ok=True)
        for name in os.listdir(dirname):
            path = os.path.join(dirname, name)
            if name.startswith(".tmp-ckpt-"):
                # tmp dirs stranded by a writer killed mid-save
                shutil.rmtree(path, ignore_errors=True)
            elif name.startswith("ckpt-") and not os.path.exists(
                    os.path.join(path, self.MARKER)):
                # an unmarked final dir is wreckage from a killed
                # _prune (the marker is removed FIRST as the prune
                # commit point) — finish the job
                shutil.rmtree(path, ignore_errors=True)

    # -- writing -------------------------------------------------------
    def _should_save(self):
        if not self._only_rank0:
            return True
        try:
            import jax
            return jax.process_index() == 0
        except Exception:
            return True

    def _snapshot(self):
        import jax
        scope = self._scope or global_scope()
        program = self._program or framework.default_main_program()
        vars_ = get_program_persistable_vars(program)
        snap = {}
        for v in vars_:
            val = scope.find_var(v.name) if scope.has_var(v.name) \
                else None
            # fail LOUDLY at save time: restore enforces one file per
            # persistable var, so a silently partial snapshot would
            # produce a COMPLETE checkpoint that can never be loaded
            enforce(val is not None,
                    "persistable var %r has no value in the scope — "
                    "run the startup program before saving", v.name)
            # device→host copy now; the training loop may donate
            # and overwrite the device buffer right after
            snap[v.name] = np.asarray(jax.device_get(val))
        return snap

    def _write(self, snap, step, error_box):
        """Durability ordering: see ``durable_publish_dir`` (extracted
        so the distributed PS shard snapshots share the exact same
        crash/power-loss-safe sequence)."""
        try:
            hook = None
            if self._write_file_hook is not None:
                hook = lambda name, i: self._write_file_hook(  # noqa: E731
                    step, name, i)
            durable_publish_dir(
                self._dir, "ckpt-%d" % step,
                [(name, serialize_tensor(arr))
                 for name, arr in snap.items()],
                marker=self.MARKER, marker_text=str(step),
                file_hook=hook)
            _obs.emit("checkpoint_published", step=int(step),
                      vars=len(snap), dir=self._dir)
            self._prune()
        except Exception as e:  # surfaced via wait()
            _obs.emit("checkpoint_failed", step=int(step),
                      error=repr(e))
            error_box.append(e)

    def _ckpt_dir(self, step):
        return os.path.join(self._dir, "ckpt-%d" % step)

    def save(self, step, sync=False):
        """Snapshot now, write in the background (or synchronously
        with ``sync=True``). Returns an _AsyncSave handle or None when
        this rank doesn't save. A PREVIOUS background write's failure
        never aborts this save — it is parked for
        ``take_write_error()`` (the failure belongs to the old step,
        and a training loop must survive a failed checkpoint)."""
        if not self._should_save():
            return None
        # one writer at a time: drain the previous save first
        self.wait_quietly()
        snap = self._snapshot()
        self._last_step = step
        # retained so the preemption handler can re-write THIS step's
        # weights if its background write gets killed (one host copy)
        self._last_snapshot = snap
        error_box = []
        if sync:
            self._write(snap, step, error_box)
            if error_box:
                raise error_box[0]
            return None
        t = threading.Thread(target=self._write,
                             args=(snap, step, error_box), daemon=True)
        t.start()
        self._inflight = _AsyncSave(t, error_box)
        return self._inflight

    def wait(self):
        if self._inflight is not None:
            self._inflight.wait()

    def wait_quietly(self):
        """Drain any in-flight write WITHOUT raising; its error (if
        any) is parked for ``take_write_error()``."""
        if self._inflight is None:
            return
        self._inflight._thread.join()
        if self._inflight._error:
            self._last_write_error = self._inflight._error[0]
            self._inflight = None

    def take_write_error(self):
        """Return-and-clear the most recent FINISHED background
        write's error (None when the last write succeeded or is still
        running). Lets a caller that never blocks on wait() still
        account for failed checkpoints."""
        if self._inflight is not None and self._inflight.done():
            if self._inflight._error:
                self._last_write_error = self._inflight._error[0]
            self._inflight = None
        err = getattr(self, "_last_write_error", None)
        self._last_write_error = None
        return err

    def _remove_ckpt_dir(self, d):
        """Delete a checkpoint dir with the marker removed FIRST (the
        commit point): unmarking makes the dir invisible to
        restore_latest, so a kill mid-rmtree can never leave a
        marked-but-partial checkpoint (rmtree's deletion order is
        arbitrary — the marker could otherwise outlive the tensors it
        vouches for). init sweeps unmarked ckpt-* dirs left by exactly
        this kill."""
        remove_marked_dir(d, self.MARKER)

    def _prune(self):
        steps = sorted(self.list_checkpoints())
        for s in steps[:-self._max_to_keep]:
            self._remove_ckpt_dir(self._ckpt_dir(s))
            _obs.emit("checkpoint_pruned", step=int(s), dir=self._dir)

    # -- reading -------------------------------------------------------
    def list_checkpoints(self):
        """Steps of COMPLETE checkpoints (marker present)."""
        out = []
        for name in os.listdir(self._dir):
            if not name.startswith("ckpt-"):
                continue
            if os.path.exists(os.path.join(self._dir, name,
                                           self.MARKER)):
                try:
                    out.append(int(name[len("ckpt-"):]))
                except ValueError:
                    continue
        return sorted(out)

    def restore_latest(self, executor=None, max_step=None):
        """Load the newest complete checkpoint into the scope; returns
        its step, or None if there is none. A marked checkpoint that
        fails to LOAD (torn by a pre-durability-fix power loss, or
        hand-damaged) is skipped with a warning and the next older one
        is tried — a rollback must never be stopped by the very
        corruption it exists to escape. ``max_step`` bounds the search
        (the GuardedTrainer restores the newest checkpoint from BEFORE
        a poisoned window, not one saved inside it)."""
        import warnings
        last_err = None
        steps = self.list_checkpoints()
        if max_step is not None:
            # STRICT: restoring something newer than the bound would
            # hand the caller state from outside the window it asked
            # for (None is an answer the caller can reason about; a
            # too-new checkpoint is not)
            steps = [s for s in steps if s <= max_step]
        for step in reversed(steps):
            try:
                load_persistables(executor, self._ckpt_dir(step),
                                  self._program, scope=self._scope)
                return step
            except Exception as e:
                last_err = e
                warnings.warn(
                    "checkpoint ckpt-%d is marked complete but failed "
                    "to load (%r); falling back to the previous one"
                    % (step, e))
        if last_err is not None:
            raise last_err
        return None

    # -- preemption ----------------------------------------------------
    def install_signal_handler(self, signals=None, get_step=None):
        """Flush checkpoints when the preemption notice (SIGTERM)
        arrives, then re-raise the default action. Semantics:

        - any in-flight background write is drained;
        - if the most recent save()'s checkpoint is INCOMPLETE on disk
          (its write was the casualty), its retained snapshot — the
          weights as of that step, not the current ones — is rewritten
          synchronously; a checkpoint that already completed is left
          alone (rewriting it with newer weights would mislabel them);
        - with ``get_step`` (a callable returning the current step), a
          fresh synchronous save of the live scope is taken under that
          step number.
        Errors never swallow the signal: the default action re-raises
        regardless."""
        import signal as signal_mod
        signals = signals or (signal_mod.SIGTERM,)

        def handler(signum, frame):
            try:
                try:
                    self.wait()
                except Exception:
                    pass  # a failed async save must not block exit
                if self._last_step is not None and \
                        self._last_step not in self.list_checkpoints() \
                        and self._last_snapshot is not None:
                    box = []
                    self._write(self._last_snapshot, self._last_step,
                                box)
                if get_step is not None:
                    self.save(int(get_step()), sync=True)
            finally:
                signal_mod.signal(signum, signal_mod.SIG_DFL)
                os.kill(os.getpid(), signum)

        for s in signals:
            signal_mod.signal(s, handler)
