"""Checkpoint save/load + inference-model export (reference:
python/paddle/fluid/io.py — save_vars:98, save_params, save_persistables
:462, load_vars, load_persistables:698, save_inference_model:903,
load_inference_model:1083; tensor wire format mirrors
framework/lod_tensor.h:214 SerializeToStream's versioned header).

TPU-native difference: the reference appends `save`/`save_combine` ops
and executes them inside the graph; here params are fetched from the
scope (device→host once per checkpoint) and written host-side — there is
no op-level graph to splice into, and checkpointing shouldn't invalidate
the compiled step program.
"""

from __future__ import annotations

import os
import pickle
import struct
from typing import List, Optional

import numpy as np

from .core.enforce import InvalidArgumentError, enforce
from .core.scope import global_scope
from .framework import Parameter, Program, Variable, default_main_program

__all__ = ["save_vars", "save_params", "save_persistables", "load_vars",
           "load_params", "load_persistables", "save_inference_model",
           "load_inference_model", "get_program_persistable_vars"]

_TENSOR_MAGIC = b"PTPU"
_TENSOR_VERSION = 1


# ---------------------------------------------------------------------------
# tensor wire format
# ---------------------------------------------------------------------------

def serialize_tensor(arr: np.ndarray) -> bytes:
    """magic | u32 version | u16 len(dtype) | dtype utf8 | u32 ndim |
    i64 dims... | payload (C-order)."""
    arr = np.ascontiguousarray(arr)
    dt = arr.dtype.name.encode()
    head = _TENSOR_MAGIC + struct.pack("<IH", _TENSOR_VERSION, len(dt))
    head += dt + struct.pack("<I", arr.ndim)
    head += struct.pack("<%dq" % arr.ndim, *arr.shape)
    return head + arr.tobytes()


def deserialize_tensor(buf: bytes, offset: int = 0):
    """Returns (ndarray, next_offset)."""
    enforce(buf[offset:offset + 4] == _TENSOR_MAGIC,
            "bad tensor magic — corrupt or foreign checkpoint")
    offset += 4
    version, dlen = struct.unpack_from("<IH", buf, offset)
    enforce(version == _TENSOR_VERSION,
            "unsupported tensor version %d" % version)
    offset += 6
    dtype = np.dtype(buf[offset:offset + dlen].decode())
    offset += dlen
    (ndim,) = struct.unpack_from("<I", buf, offset)
    offset += 4
    dims = struct.unpack_from("<%dq" % ndim, buf, offset)
    offset += 8 * ndim
    count = int(np.prod(dims)) if ndim else 1
    nbytes = count * dtype.itemsize
    arr = np.frombuffer(buf, dtype=dtype, count=count,
                        offset=offset).reshape(dims)
    return arr.copy(), offset + nbytes


# ---------------------------------------------------------------------------
# var save/load
# ---------------------------------------------------------------------------

def _is_persistable(var) -> bool:
    return bool(var.persistable) and not var.is_data


def _is_parameter(var) -> bool:
    return isinstance(var, Parameter)


def get_program_persistable_vars(program) -> List[Variable]:
    return [v for v in program.list_vars() if _is_persistable(v)]


def _collect(program, vars, predicate):
    program = program or default_main_program()
    if vars is None:
        vars = [v for v in program.list_vars() if predicate(v)]
    return program, vars


def _fetch_numpy(value):
    enforce(value is not None, "variable has no value in scope — "
            "did you run the startup program?")
    return np.asarray(value)


def save_vars(executor=None, dirname=None, main_program=None, vars=None,
              predicate=None, filename=None, scope=None):
    """Save selected vars under ``dirname`` — one file per var, or a
    single combined ``filename`` (the save_combine path, reference
    io.py:98/save_combine_op.cc)."""
    scope = scope or global_scope()
    program, vars = _collect(main_program, vars,
                             predicate or _is_persistable)
    os.makedirs(dirname, exist_ok=True)
    if filename is None:
        for v in vars:
            arr = _fetch_numpy(scope.find_var(v.name))
            with open(os.path.join(dirname, v.name), "wb") as f:
                f.write(serialize_tensor(arr))
    else:
        with open(os.path.join(dirname, filename), "wb") as f:
            names = [v.name for v in vars]
            f.write(struct.pack("<I", len(names)))
            for n in names:
                nb = n.encode()
                f.write(struct.pack("<H", len(nb)) + nb)
            for v in vars:
                arr = _fetch_numpy(scope.find_var(v.name))
                f.write(serialize_tensor(arr))


def save_params(executor=None, dirname=None, main_program=None,
                filename=None, scope=None):
    return save_vars(executor, dirname, main_program, None,
                     _is_parameter, filename, scope)


def save_persistables(executor=None, dirname=None, main_program=None,
                      filename=None, scope=None):
    return save_vars(executor, dirname, main_program, None,
                     _is_persistable, filename, scope)


def load_vars(executor=None, dirname=None, main_program=None, vars=None,
              predicate=None, filename=None, scope=None):
    """Mirror of save_vars (reference io.py:498). Shape/dtype are
    validated against the program's declaration."""
    scope = scope or global_scope()
    program, vars = _collect(main_program, vars,
                             predicate or _is_persistable)
    if filename is None:
        for v in vars:
            path = os.path.join(dirname, v.name)
            enforce(os.path.exists(path),
                    "checkpoint file missing for var %r: %s"
                    % (v.name, path))
            with open(path, "rb") as f:
                arr, _ = deserialize_tensor(f.read())
            _check_and_set(scope, v, arr)
    else:
        with open(os.path.join(dirname, filename), "rb") as f:
            buf = f.read()
        (n,) = struct.unpack_from("<I", buf, 0)
        off = 4
        names = []
        for _ in range(n):
            (ln,) = struct.unpack_from("<H", buf, off)
            off += 2
            names.append(buf[off:off + ln].decode())
            off += ln
        tensors = {}
        for name in names:
            arr, off = deserialize_tensor(buf, off)
            tensors[name] = arr
        for v in vars:
            enforce(v.name in tensors,
                    "var %r not in combined checkpoint" % v.name)
            _check_and_set(scope, v, tensors[v.name])


def _check_and_set(scope, v, arr):
    want = tuple(int(d) for d in v.shape if d != -1)
    got = tuple(arr.shape)
    if want and got != want:
        raise InvalidArgumentError(
            "shape mismatch loading %r: checkpoint %s vs program %s"
            % (v.name, got, want))
    scope.set_var(v.name, arr)


def load_params(executor=None, dirname=None, main_program=None,
                filename=None, scope=None):
    return load_vars(executor, dirname, main_program, None,
                     _is_parameter, filename, scope)


def load_persistables(executor=None, dirname=None, main_program=None,
                      filename=None, scope=None):
    return load_vars(executor, dirname, main_program, None,
                     _is_persistable, filename, scope)


# ---------------------------------------------------------------------------
# inference model
# ---------------------------------------------------------------------------

def save_inference_model(dirname, feeded_var_names, target_vars,
                         executor=None, main_program=None,
                         model_filename=None, params_filename=None,
                         scope=None):
    """Prune to the inference slice and persist program + params
    (reference io.py:903). Returns the target var names."""
    main_program = main_program or default_main_program()
    enforce(isinstance(feeded_var_names, (list, tuple)),
            "feeded_var_names must be a list of names")
    targets = list(target_vars)
    inf_prog = main_program.clone(for_test=True)._prune(targets)
    target_names = [t.name if isinstance(t, Variable) else t
                    for t in targets]
    desc = {"program": inf_prog.to_dict(),
            "feed_names": list(feeded_var_names),
            "fetch_names": target_names}
    os.makedirs(dirname, exist_ok=True)
    model_path = os.path.join(dirname, model_filename or "__model__")
    with open(model_path, "wb") as f:
        pickle.dump(desc, f, protocol=4)
    save_persistables(executor, dirname, inf_prog,
                      filename=params_filename, scope=scope)
    return target_names


def load_inference_model(dirname, executor=None, model_filename=None,
                         params_filename=None, scope=None):
    """Returns (program, feed_names, fetch_vars) (reference
    io.py:1083)."""
    model_path = os.path.join(dirname, model_filename or "__model__")
    enforce(os.path.exists(model_path),
            "no inference model at %s" % model_path)
    with open(model_path, "rb") as f:
        desc = pickle.load(f)
    program = Program.from_dict(desc["program"])
    load_persistables(executor, dirname, program,
                      filename=params_filename, scope=scope)
    blk = program.global_block()
    fetch_vars = [blk.var(n) for n in desc["fetch_names"]]
    return program, desc["feed_names"], fetch_vars
