"""Weight-decay regularizers (reference:
python/paddle/fluid/regularizer.py — append_regularization_ops,
L1DecayRegularizer, L2DecayRegularizer)."""

from __future__ import annotations

from .framework import default_main_program
from .layer_helper import LayerHelper


class WeightDecayRegularizer:
    def append_regularization_op(self, param, grad, block):
        raise NotImplementedError


class L2DecayRegularizer(WeightDecayRegularizer):
    def __init__(self, regularization_coeff=0.0):
        self._coeff = regularization_coeff

    def append_regularization_op(self, param, grad, block):
        helper = LayerHelper("l2_decay")
        decay = helper.create_variable_for_type_inference(param.dtype)
        block.append_op(type="scale", inputs={"X": [param]},
                        outputs={"Out": [decay]},
                        attrs={"scale": self._coeff, "bias": 0.0,
                               "bias_after_scale": True,
                               "op_role": "backward"})
        out = helper.create_variable_for_type_inference(param.dtype)
        block.append_op(type="sum", inputs={"X": [grad, decay]},
                        outputs={"Out": [out]},
                        attrs={"op_role": "backward"})
        return out


class L1DecayRegularizer(WeightDecayRegularizer):
    def __init__(self, regularization_coeff=0.0):
        self._coeff = regularization_coeff

    def append_regularization_op(self, param, grad, block):
        helper = LayerHelper("l1_decay")
        sign = helper.create_variable_for_type_inference(param.dtype)
        block.append_op(type="sign", inputs={"X": [param]},
                        outputs={"Out": [sign]},
                        attrs={"op_role": "backward"})
        decay = helper.create_variable_for_type_inference(param.dtype)
        block.append_op(type="scale", inputs={"X": [sign]},
                        outputs={"Out": [decay]},
                        attrs={"scale": self._coeff, "bias": 0.0,
                               "bias_after_scale": True,
                               "op_role": "backward"})
        out = helper.create_variable_for_type_inference(param.dtype)
        block.append_op(type="sum", inputs={"X": [grad, decay]},
                        outputs={"Out": [out]},
                        attrs={"op_role": "backward"})
        return out


def append_regularization_ops(params_grads, regularization=None):
    """Reference: regularizer.py append_regularization_ops — per-param
    regularizer wins over the optimizer-level one."""
    block = default_main_program().global_block()
    out = []
    for param, grad in params_grads:
        if grad is None:
            out.append((param, grad))
            continue
        reg = param.regularizer or regularization
        if reg is None:
            out.append((param, grad))
            continue
        new_grad = reg.append_regularization_op(param, grad, block)
        out.append((param, new_grad))
    return out


# fluid-style aliases
L1Decay = L1DecayRegularizer
L2Decay = L2DecayRegularizer
