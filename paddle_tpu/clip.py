"""Gradient clipping (reference: python/paddle/fluid/clip.py —
GradientClipByValue, GradientClipByNorm, GradientClipByGlobalNorm,
set_gradient_clip, append_gradient_clip_ops)."""

from __future__ import annotations

from .framework import default_main_program
from .layer_helper import LayerHelper
from .layers import nn


class BaseGradientClipAttr:
    def _append_clip_op(self, block, grad):
        raise NotImplementedError


class GradientClipByValue(BaseGradientClipAttr):
    def __init__(self, max, min=None):
        self.max = max
        self.min = -max if min is None else min

    def _append_clip_op(self, block, grad):
        return nn.clip(grad, self.min, self.max)


class GradientClipByNorm(BaseGradientClipAttr):
    def __init__(self, clip_norm):
        self.clip_norm = clip_norm

    def _append_clip_op(self, block, grad):
        return nn.clip_by_norm(grad, self.clip_norm)


class GradientClipByGlobalNorm(BaseGradientClipAttr):
    """Rescale all grads so their joint L2 norm <= clip_norm (reference:
    clip.py GradientClipByGlobalNorm)."""

    def __init__(self, clip_norm):
        self.clip_norm = clip_norm

    def _clip_all(self, params_grads):
        helper = LayerHelper("global_norm_clip")
        sq_sums = []
        for _p, g in params_grads:
            if g is None:
                continue
            sq = nn.reduce_sum(g * g)
            sq_sums.append(sq)
        from .layers import tensor as t
        total = t.sums(sq_sums) if len(sq_sums) > 1 else sq_sums[0]
        from .layers.ops import sqrt as _sqrt
        global_norm = _sqrt(total)
        clip_var = t.fill_constant((), "float32", self.clip_norm)
        scale = clip_var / nn.elementwise_max(global_norm, clip_var)
        out = []
        for p, g in params_grads:
            if g is None:
                out.append((p, g))
            else:
                out.append((p, g * scale))
        return out


_gradient_clip_attr = None


def set_gradient_clip(clip, param_list=None, program=None):
    global _gradient_clip_attr
    _gradient_clip_attr = clip
    if param_list:
        for p in param_list:
            p.gradient_clip_attr = clip


def append_gradient_clip_ops(params_grads, clip=None):
    clip = clip or _gradient_clip_attr
    if clip is None:
        return params_grads
    if isinstance(clip, GradientClipByGlobalNorm):
        return clip._clip_all(params_grads)
    block = default_main_program().global_block()
    out = []
    for p, g in params_grads:
        if g is None:
            out.append((p, g))
            continue
        per_param = p.gradient_clip_attr or clip
        out.append((p, per_param._append_clip_op(block, g)))
    return out


ErrorClipByValue = GradientClipByValue  # parity alias
