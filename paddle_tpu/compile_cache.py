"""Persistent AOT compile cache: serialized XLA executables shared
across fleet processes, keyed on a canonical program fingerprint.

Every replica cold-start, autoscale spin-up, hot-swap warmup, and
restart used to re-pay XLA compilation invisibly (ROADMAP "Compile
plane"). This module makes the executor's compiles *portable*: the
first process to compile a (program, shape, mesh) serializes the
executable here (``jax.experimental.serialize_executable``), and every
later process — a fresh replica, a restarted trainer, a warmup pass —
loads it in O(read) instead of O(compile).

Key design points:

  - **Canonical key.** The fingerprint is a SHA-256 over the program's
    lowered StableHLO text — which is independent of process-local
    identities (``Program._uid``, object ids, scope addresses): two
    processes that build the same program the same way produce the
    same text, so they share cache entries. The full disk key adds
    everything else that changes the produced executable: backend
    platform, device count, jax/jaxlib versions, and the mesh
    fingerprint (shapes/dtypes are already inside the HLO).
  - **Observable.** Every hit/miss/store/evict bumps labeled registry
    counters and emits a journal event; a hit's journal record carries
    the ORIGIN of the entry (pid/role/wall-time of the process that
    paid the compile, and what it paid), so a fleet journal shows who
    compiled what and who rode for free.
  - **Crash-safe.** Entries are written tmp-file + ``os.replace``
    (atomic on POSIX); readers of a torn/garbage entry treat it as a
    miss and overwrite. Concurrent writers of the same key converge on
    identical bytes.
  - **Bounded.** ``max_bytes`` arms LRU eviction (by last-use mtime,
    ``get`` touches entries); evicted keys are remembered in
    ``evicted.jsonl`` so the executor can attribute a later recompile
    to ``evicted`` rather than a cold cache.

Enable per process with ``configure(dir)`` or the
``PADDLE_TPU_COMPILE_CACHE_DIR`` env var (the launcher / bench can
stamp one shared directory per fleet); ``PADDLE_TPU_COMPILE_CACHE_MAX_BYTES``
bounds it. Disabled (the default) the executor compiles exactly as
before — the cache is strictly additive.

See docs/compile.md for the on-disk layout and the provenance record
schema this feeds.
"""

from __future__ import annotations

import hashlib
import json
import os
import pickle
import threading
import time
from typing import Optional

from . import observability as _obs

__all__ = ["CompileCache", "CacheHit", "configure", "active",
           "canonical_fingerprint", "cache_key", "stats",
           "reset_stats"]

ENV_DIR = "PADDLE_TPU_COMPILE_CACHE_DIR"
ENV_MAX_BYTES = "PADDLE_TPU_COMPILE_CACHE_MAX_BYTES"
EVICTED_INDEX = "evicted.jsonl"

_MU = threading.Lock()
_ACTIVE: Optional["CompileCache"] = None
_ENV_CHECKED = False


def canonical_fingerprint(hlo_text: str) -> str:
    """SHA-256 hex of a program's lowered (StableHLO) text — the
    ``_uid``-independent identity the provenance ledger and the disk
    cache share. The text is deterministic for a program built the
    same way in any process (verified cross-process by tests)."""
    return hashlib.sha256(hlo_text.encode()).hexdigest()


def cache_key(fingerprint: str, mesh_fp=None) -> str:
    """Full disk key: the canonical fingerprint plus everything else
    that changes the produced executable — backend platform + device
    count (an executable deserializes only onto the topology it was
    compiled for) and jax/jaxlib versions (serialization format and
    codegen both move between releases). Shapes, dtypes, and sharding
    annotations are already inside the fingerprinted HLO; the mesh
    fingerprint is included for explicitness (axis names/sizes)."""
    import jax
    import jaxlib
    backend = jax.default_backend()
    material = "|".join([
        fingerprint, backend, str(jax.device_count()),
        jax.__version__, jaxlib.__version__, repr(mesh_fp)])
    return hashlib.sha256(material.encode()).hexdigest()


class CacheHit:
    """One successful load: the callable ``loaded`` executable plus
    the stored origin metadata and what the load itself cost."""

    def __init__(self, loaded, meta, load_seconds, nbytes):
        self.loaded = loaded
        self.meta = meta
        self.load_seconds = load_seconds
        self.nbytes = nbytes


class CompileCache:
    """On-disk store of serialized XLA executables (see module doc).

    Layout under ``dir``: ``<key>.bin`` (pickle of the
    ``serialize_executable`` triple), ``<key>.json`` (origin + cost
    metadata, human-readable), ``evicted.jsonl`` (one key per line,
    append-only memory of LRU evictions)."""

    def __init__(self, dir: str, max_bytes: Optional[int] = None):
        self.dir = os.path.abspath(dir)
        self.max_bytes = int(max_bytes) if max_bytes else None
        os.makedirs(self.dir, exist_ok=True)
        self._mu = threading.Lock()
        reg = _obs.registry()
        self._m_hit = reg.counter("compile_cache_hits_total")
        self._m_miss = reg.counter("compile_cache_misses_total")
        self._m_store = reg.counter("compile_cache_stores_total")
        self._m_evict = reg.counter("compile_cache_evictions_total")
        self._m_bytes_in = reg.counter("compile_cache_bytes_loaded_total")
        self._m_bytes_out = reg.counter("compile_cache_bytes_stored_total")
        self._h_load = reg.histogram("compile_cache_load_seconds")

    # -- paths ---------------------------------------------------------
    def _bin(self, key: str) -> str:
        return os.path.join(self.dir, key + ".bin")

    def _meta(self, key: str) -> str:
        return os.path.join(self.dir, key + ".json")

    # -- read ----------------------------------------------------------
    def get(self, key: str, entry: str = "?") -> Optional[CacheHit]:
        """Load + deserialize one executable; None on miss (including
        torn/undeserializable entries, which are misses by contract —
        the caller recompiles and overwrites)."""
        path = self._bin(key)
        t0 = time.perf_counter()
        try:
            try:
                st = os.stat(path)
            except OSError:
                st = None
            with open(path, "rb") as f:
                blob = f.read()
            payload, in_tree, out_tree = pickle.loads(blob)
            from jax.experimental import serialize_executable as _se
            loaded = _se.deserialize_and_load(payload, in_tree,
                                              out_tree)
        except FileNotFoundError:
            self._m_miss.inc()
            return None
        except Exception as e:
            # torn write / version skew / foreign topology: a miss,
            # and the entry is dead weight — drop it so the recompile
            # can overwrite cleanly. Only if UNCHANGED since our read:
            # a sibling process may have re-stored a good entry in the
            # window, and deleting that would cost the fleet a compile.
            self._m_miss.inc()
            _obs.emit("compile_cache_corrupt", key=key, entry=entry,
                      error=repr(e))
            try:
                st2 = os.stat(path)
                if st is not None and (st2.st_mtime == st.st_mtime
                                       and st2.st_size == st.st_size):
                    self._remove(key)
            except OSError:
                pass
            return None
        dt = time.perf_counter() - t0
        meta = self._read_meta(key)
        # touch for LRU recency (best effort)
        try:
            os.utime(path, None)
        except OSError:
            pass
        self._m_hit.inc()
        self._m_bytes_in.inc(len(blob))
        self._h_load.observe(dt)
        return CacheHit(loaded, meta, dt, len(blob))

    def _read_meta(self, key: str) -> dict:
        try:
            with open(self._meta(key)) as f:
                return json.load(f)
        except Exception:
            return {}

    def contains(self, key: str) -> bool:
        return os.path.exists(self._bin(key))

    # -- write ---------------------------------------------------------
    def put(self, key: str, compiled, meta: dict) -> Optional[int]:
        """Serialize ``compiled`` (a jax.stages.Compiled/Loaded) under
        ``key`` with ``meta`` stamped with this process's identity.
        Returns the stored byte count, or None when the executable
        does not support serialization on this backend (the cache
        degrades to ledger-only, never raises into the compile
        path)."""
        try:
            from jax.experimental import serialize_executable as _se
            payload, in_tree, out_tree = _se.serialize(compiled)
            blob = pickle.dumps((payload, in_tree, out_tree))
        except Exception as e:
            _obs.emit("compile_cache_unserializable", key=key,
                      error=repr(e), entry=meta.get("entry"))
            return None
        m = dict(meta)
        m.update(key=key, origin_pid=os.getpid(),
                 origin_role=_obs.get_role(), origin_t_wall=time.time(),
                 bytes=len(blob))
        tmp = self._bin(key) + ".tmp.%d" % os.getpid()
        mtmp = self._meta(key) + ".tmp.%d" % os.getpid()
        try:
            with open(tmp, "wb") as f:
                f.write(blob)
            os.replace(tmp, self._bin(key))
            with open(mtmp, "w") as f:
                json.dump(m, f, indent=1, default=repr)
            os.replace(mtmp, self._meta(key))
        except OSError as e:
            _obs.emit("compile_cache_write_failed", key=key,
                      error=repr(e))
            for p in (tmp, mtmp):
                try:
                    os.remove(p)
                except OSError:
                    pass
            return None
        # a re-stored key is no longer "evicted": prune it from the
        # index or a later unrelated miss (corrupt entry, wiped dir)
        # would misclassify as evicted forever
        self._unmark_evicted(key)
        self._m_store.inc()
        self._m_bytes_out.inc(len(blob))
        _obs.emit("compile_cache_store", key=key,
                  entry=meta.get("entry"),
                  fingerprint=meta.get("fingerprint"),
                  bytes=len(blob),
                  compile_seconds=meta.get("compile_seconds"))
        if self.max_bytes is not None:
            self._evict_lru()
        return len(blob)

    def _remove(self, key: str):
        for p in (self._bin(key), self._meta(key)):
            try:
                os.remove(p)
            except OSError:
                pass

    # -- eviction ------------------------------------------------------
    # a tmp file this old was orphaned by a killed writer (a live
    # put() holds one for milliseconds) — reaped during eviction scans
    TMP_ORPHAN_AGE_S = 3600.0

    def _evict_lru(self):
        """Drop least-recently-used entries until under ``max_bytes``;
        remember each evicted key so a later recompile of it can be
        attributed (miss reason ``evicted``, not ``cache_cold``). The
        budget counts each entry's .bin AND .json sidecar, and the
        scan reaps tmp files orphaned by killed writers — a shared
        fleet dir must not outgrow max_bytes through invisible
        bookkeeping bytes."""
        now = time.time()
        with self._mu:
            sizes = {}
            try:
                for n in os.listdir(self.dir):
                    p = os.path.join(self.dir, n)
                    if ".tmp." in n:
                        try:
                            if now - os.path.getmtime(p) \
                                    > self.TMP_ORPHAN_AGE_S:
                                os.remove(p)
                        except OSError:
                            pass
                        continue
                    try:
                        sizes[n] = (os.path.getmtime(p),
                                    os.path.getsize(p))
                    except OSError:
                        pass
            except OSError:
                return
            entries = []  # (mtime, bin+json bytes, key)
            for n, (mt, sz) in sizes.items():
                if not n.endswith(".bin"):
                    continue
                key = n[:-4]
                sz += sizes.get(key + ".json", (0, 0))[1]
                entries.append((mt, sz, key))
            total = sum(sz for _, sz, _ in entries)
            if total <= self.max_bytes:
                return
            entries.sort()  # oldest mtime first
            idx = os.path.join(self.dir, EVICTED_INDEX)
            for _, sz, key in entries:
                if total <= self.max_bytes:
                    break
                self._remove(key)
                total -= sz
                try:
                    with open(idx, "a") as f:
                        f.write(json.dumps(
                            {"key": key, "t_wall": time.time()}) + "\n")
                except OSError:
                    pass
                self._m_evict.inc()
                _obs.emit("compile_cache_evict", key=key, bytes=sz)
            self._compact_index_locked()

    # keep the append-only index bounded: compact to
    # last-record-per-key once it exceeds this many lines (evictions
    # are rare relative to compiles, so the O(N) rewrite is rarer
    # still). The rewrite can in principle drop a line a concurrent
    # process appends during it — worst case one later miss reads
    # cache_cold instead of evicted, a benign telemetry skew.
    INDEX_COMPACT_LINES = 4096

    def _compact_index_locked(self):
        idx = os.path.join(self.dir, EVICTED_INDEX)
        try:
            with open(idx) as f:
                lines = f.readlines()
            if len(lines) <= self.INDEX_COMPACT_LINES:
                return
            last = {}
            for line in lines:
                try:
                    rec = json.loads(line)
                except ValueError:
                    continue
                if "key" in rec:
                    last[rec["key"]] = rec
            tmp = idx + ".tmp.%d" % os.getpid()
            with open(tmp, "w") as f:
                for rec in last.values():
                    if not rec.get("restored"):
                        f.write(json.dumps(rec) + "\n")
            os.replace(tmp, idx)
        except OSError:
            pass

    def was_evicted(self, key: str) -> bool:
        """True when ``key`` is absent AND the eviction index's LAST
        record for it is an eviction (``put`` appends a ``restored``
        tombstone when a key is re-stored, so eviction status does not
        outlive the eviction). The index is append-only — concurrent
        evictors/restorers across processes each append one small
        O_APPEND line and never rewrite each other's records."""
        if self.contains(key):
            return False
        idx = os.path.join(self.dir, EVICTED_INDEX)
        evicted = False
        try:
            with open(idx) as f:
                for line in f:
                    try:
                        rec = json.loads(line)
                    except ValueError:
                        continue
                    if rec.get("key") == key:
                        evicted = not rec.get("restored", False)
        except OSError:
            return False
        return evicted

    def _unmark_evicted(self, key: str):
        """Append a ``restored`` tombstone for a re-stored key (only
        when the index currently ends on an eviction for it) — see
        was_evicted for the last-record-wins contract."""
        if not self.contains(key):
            return
        idx = os.path.join(self.dir, EVICTED_INDEX)
        if not os.path.exists(idx):
            return
        # cheap pre-check: no record, nothing to tombstone
        try:
            with open(idx) as f:
                pending = False
                for line in f:
                    try:
                        rec = json.loads(line)
                    except ValueError:
                        continue
                    if rec.get("key") == key:
                        pending = not rec.get("restored", False)
            if not pending:
                return
            with open(idx, "a") as f:
                f.write(json.dumps({"key": key, "restored": True,
                                    "t_wall": time.time()}) + "\n")
        except OSError:
            pass

    # -- accounting ----------------------------------------------------
    def stats(self) -> dict:
        """Registry-backed snapshot of this process's cache activity
        (the counters are process-wide: one active cache per
        process)."""
        return {
            "dir": self.dir,
            "hits": self._m_hit.value,
            "misses": self._m_miss.value,
            "stores": self._m_store.value,
            "evictions": self._m_evict.value,
            "bytes_loaded": self._m_bytes_in.value,
            "bytes_stored": self._m_bytes_out.value,
            "load_seconds_total": self._h_load.sum,
        }

    def disk_entries(self) -> int:
        try:
            return sum(1 for n in os.listdir(self.dir)
                       if n.endswith(".bin"))
        except OSError:
            return 0


# ---------------------------------------------------------------------------
# process-wide active cache
# ---------------------------------------------------------------------------

def configure(dir: Optional[str] = None,
              max_bytes: Optional[int] = None) -> Optional[CompileCache]:
    """Set (or with ``dir=None`` disable) this process's persistent
    compile cache; overrides the env var. Returns the active cache."""
    global _ACTIVE, _ENV_CHECKED
    with _MU:
        _ENV_CHECKED = True
        _ACTIVE = CompileCache(dir, max_bytes=max_bytes) if dir \
            else None
        return _ACTIVE


def active() -> Optional[CompileCache]:
    """The process's active cache, lazily picked up from
    ``PADDLE_TPU_COMPILE_CACHE_DIR`` on first use (the launcher stamps
    one shared dir per fleet); None when disabled."""
    global _ACTIVE, _ENV_CHECKED
    if _ENV_CHECKED:
        return _ACTIVE
    with _MU:
        if not _ENV_CHECKED:
            _ENV_CHECKED = True
            path = os.environ.get(ENV_DIR)
            if path:
                try:
                    mb = int(os.environ.get(ENV_MAX_BYTES, "0")) or None
                except ValueError:
                    mb = None
                try:
                    _ACTIVE = CompileCache(path, max_bytes=mb)
                except OSError as e:
                    # a bad/read-only fleet-stamped dir must degrade
                    # to cache-disabled, not crash the first compile —
                    # the cache is strictly additive (explicit
                    # configure() still raises: the caller asked)
                    _obs.emit("compile_cache_unavailable", dir=path,
                              error=repr(e))
                    _ACTIVE = None
        return _ACTIVE


def stats() -> Optional[dict]:
    """Stats of the active cache (None when disabled) — what
    ``Executor.telemetry()`` surfaces under ``compile_cache``."""
    c = active()
    return c.stats() if c is not None else None


def reset_stats():
    """Zero the cache counters (tests/bench probes)."""
    c = active()
    if c is None:
        return
    for m in (c._m_hit, c._m_miss, c._m_store, c._m_evict,
              c._m_bytes_in, c._m_bytes_out, c._h_load):
        m.reset()
