"""Serving SLO metrics: latency percentiles, queue depth, batch
occupancy, QPS, compile count.

The host-side accumulator twin of ``profiler.py``'s event spans: the
engine records one latency sample per completed request and one
occupancy sample per dispatched device batch; ``snapshot()`` reduces
them into the SLO dict ``engine.stats()`` returns. Bounded memory: the
latency/qps window is a ring buffer, occupancy aggregates into a
per-bucket histogram.
"""

from __future__ import annotations

import collections
import threading
import time
from typing import Optional

import numpy as np

from ..observability import registry as _registry

__all__ = ["EngineStats"]


class EngineStats:
    """Thread-safe metric accumulator for one served model.

    Registry-backed: every count/latency/batch sample is mirrored into
    the process-wide ``observability.MetricsRegistry`` under
    ``serving_*`` metrics labeled by model, so the serving SLO numbers
    show up in ``/metrics`` and ``tools/obs_dump.py`` next to the rest
    of the runtime. The snapshot()/stats() surface is unchanged."""

    # EWMA smoothing for the per-model latency signal replicas
    # piggyback to the router (a full percentile window is too heavy
    # to ship per response; one smoothed scalar is enough to rank
    # replicas)
    EWMA_ALPHA = 0.2

    def __init__(self, window: int = 4096, model: str = "default"):
        self._lock = threading.Lock()
        # (t_done, latency_seconds) ring; t_done drives windowed QPS
        self._lat = collections.deque(maxlen=int(window))
        self._ewma_s = None
        self._bucket_hist = collections.Counter()
        self._occ_rows = 0        # live rows dispatched
        self._occ_capacity = 0    # sum of bucket sizes dispatched
        self.completed = 0
        self.rejected = 0         # ServerOverloaded admissions
        self.expired = 0          # deadline passed before dispatch
        self.failed = 0           # dispatch raised / batcher died
        self.batches = 0
        self.started_at = time.monotonic()
        reg = _registry()
        self._m = {f: reg.counter("serving_requests_total",
                                  model=model, outcome=f)
                   for f in ("completed", "rejected", "expired",
                             "failed")}
        self._m["batches"] = reg.counter("serving_batches_total",
                                         model=model)
        self._m_rows = reg.counter("serving_rows_total", model=model)
        self._h_latency = reg.histogram("serving_latency_seconds",
                                        model=model)

    # -- recording -----------------------------------------------------
    def record_request(self, latency_s: float,
                       t_done: Optional[float] = None):
        with self._lock:
            self.completed += 1
            self._lat.append((t_done if t_done is not None
                              else time.monotonic(), latency_s))
            a = self.EWMA_ALPHA
            self._ewma_s = latency_s if self._ewma_s is None \
                else a * latency_s + (1.0 - a) * self._ewma_s
        self._m["completed"].inc()
        self._h_latency.observe(latency_s)

    @property
    def ewma_ms(self):
        """Smoothed request latency in ms (None before any request) —
        the scalar replicas piggyback on INFER responses/heartbeats."""
        with self._lock:
            return None if self._ewma_s is None \
                else round(self._ewma_s * 1e3, 3)

    def record_batch(self, rows: int, bucket: int):
        with self._lock:
            self.batches += 1
            self._bucket_hist[int(bucket)] += 1
            self._occ_rows += int(rows)
            self._occ_capacity += int(bucket)
        self._m["batches"].inc()
        self._m_rows.inc(rows)

    def count(self, field: str, n: int = 1):
        with self._lock:
            setattr(self, field, getattr(self, field) + n)
        m = self._m.get(field)
        if m is not None:
            m.inc(n)

    # -- reducing ------------------------------------------------------
    def snapshot(self) -> dict:
        with self._lock:
            lat = list(self._lat)
            hist = dict(self._bucket_hist)
            occ_rows, occ_cap = self._occ_rows, self._occ_capacity
            completed, rejected = self.completed, self.rejected
            expired, failed = self.expired, self.failed
            batches = self.batches
        ms = np.asarray([l * 1e3 for _, l in lat])
        if ms.size:
            p50, p95, p99 = (float(np.percentile(ms, q))
                             for q in (50, 95, 99))
        else:
            p50 = p95 = p99 = None
        # windowed QPS over the ring's completion timestamps; a single
        # sample (or none) has no window to rate over
        if len(lat) >= 2:
            span = lat[-1][0] - lat[0][0]
            qps = round((len(lat) - 1) / span, 2) if span > 0 else None
        else:
            qps = None
        return {
            "completed": completed, "rejected": rejected,
            "expired": expired, "failed": failed, "batches": batches,
            "ewma_ms": self.ewma_ms,
            "p50_ms": round(p50, 3) if p50 is not None else None,
            "p95_ms": round(p95, 3) if p95 is not None else None,
            "p99_ms": round(p99, 3) if p99 is not None else None,
            "qps": qps,
            "batch_occupancy": {
                "mean": round(occ_rows / occ_cap, 4) if occ_cap else None,
                "hist": hist,
            },
        }
