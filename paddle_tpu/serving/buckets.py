"""Shape-bucket math for the serving engine.

The scarce resource on a compiled-executable backend is COMPILED-SHAPE
CARDINALITY, not bytes (EQuARX-style transport thinking applied to
serving, PAPERS.md arXiv:2506.17615): every distinct device batch size
is one more XLA executable, one more cold-compile stall, and one more
resident program in HBM. Padding every device batch up to a power of
two caps the executable count at ``ceil(log2(max_batch)) + 1`` no
matter how ragged client batch sizes are — 100 distinct client sizes
in [1, 64] hit at most the 7 buckets [1, 2, 4, 8, 16, 32, 64], all
pre-compilable by a warmup pass at model load.
"""

from __future__ import annotations

from typing import Dict, List, Sequence

import numpy as np

from ..core.enforce import InvalidArgumentError, enforce

__all__ = ["bucket_sizes", "bucket_for", "pad_batch"]


def bucket_sizes(max_batch_size: int) -> List[int]:
    """Powers of two up to (and always including) ``max_batch_size``:
    64 -> [1, 2, 4, 8, 16, 32, 64]; a non-power-of-two cap becomes the
    last bucket (48 -> [1, 2, 4, 8, 16, 32, 48])."""
    enforce(int(max_batch_size) >= 1,
            "max_batch_size must be >= 1, got %s" % max_batch_size)
    max_batch_size = int(max_batch_size)
    sizes = []
    b = 1
    while b < max_batch_size:
        sizes.append(b)
        b *= 2
    sizes.append(max_batch_size)
    return sizes


def bucket_for(rows: int, sizes: Sequence[int]) -> int:
    """Smallest bucket that holds ``rows``."""
    for s in sizes:
        if rows <= s:
            return s
    raise InvalidArgumentError(
        "batch of %d rows exceeds the largest bucket (%d)"
        % (rows, sizes[-1]))


def pad_batch(feed: Dict[str, np.ndarray], rows: int,
              bucket: int) -> Dict[str, np.ndarray]:
    """Pad every input's leading (batch) axis from ``rows`` up to
    ``bucket`` with zeros. Zero is always shape/dtype-valid (and a
    legal id-0 row for integer lookup inputs); the padded rows' outputs
    are sliced away before results reach any caller, so their values
    never escape. Per-row-independent inference graphs (everything a
    ``clone(for_test=True)`` program contains — batch_norm uses saved
    stats at inference) make the live rows bit-identical to an unpadded
    run."""
    if rows == bucket:
        return feed
    out = {}
    for name, arr in feed.items():
        arr = np.asarray(arr)
        pad = np.zeros((bucket - rows,) + arr.shape[1:], arr.dtype)
        out[name] = np.concatenate([arr, pad], axis=0)
    return out
