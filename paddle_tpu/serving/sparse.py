"""Sparse serving plane: train-AND-serve the >HBM recommender through
one embedding authority (docs/serving.md §Sparse serving).

PR 14 built the storage tiers (hot row cache, q8 wire, durable spill,
bit-exact table snapshots) and PR 17 made the pserver plane elastically
reshardable — this module SERVES through all of it. A
``SparseServingReplica`` answers the PR 8 router's INFER protocol
(``pack_blob`` meta + tensors, piggybacked load, HEARTBEAT lease,
structured errors, chaos ``crash()`` seam — wire-compatible with
``ServingRouter`` unchanged), but its per-request forward consults a
``LookupServiceClient`` instead of a compiled model: the request's id
set keys a batch prefetch against the live ``LargeScaleKV`` shards the
TRAINERS are pushing into, so freshly trained rows reach serving with
no export/reload step in between.

Cache tiers, top down:

  - **device tier** (``_DeviceRowTier``): the hottest rows resident as
    ONE pinned device array (slots gathered on device per request,
    CLOCK eviction, per-tier hit/miss counters);
  - **host Tier 0**: the client's ``EmbeddingRowCache`` (PR 14 —
    touch-frequency admission under a byte budget);
  - **authority**: the pserver shards (``PREFETCH_STAMPED`` — the
    PREFETCH_Q8 codec plus per-row versions + the shard's push
    watermark), with Tier 2 spill + snapshots below, so the served
    table can be bigger than any host.

Bounded-staleness coherence contract (async multi-trainer fleets —
beyond ``mirror_sgd``'s bit-equal sync-only contract): every shard
counts applied pushes (its WATERMARK) and stamps each row with the
watermark of its last update; every stamped pull records (row version,
watermark seen). A cached row's staleness bound is the shard's current
watermark minus the watermark it was pulled at — the number of pushes
the copy can possibly have missed. Before serving, the gate bounds that
lag by ``max_staleness_steps``: rows over the bound are RE-PULLED from
authority (``staleness_action="repull"``, the default) or the request
is SHED with a structured ``StaleRows`` error ("shed"). Watermark
knowledge stays fresh for free on every authority read and is refreshed
by an amortized empty-prefetch poll every ``watermark_poll_every``
requests. With ``enforce=False`` the gate only OBSERVES: over-bound
rows are served and journalled as ``stale_row_served`` (row id, its
last-push version, the replica's pull watermark, the shard's current
watermark) — the event ``tools/doctor.py`` turns into a
``stale_serving`` verdict.

Lock discipline (tools/lock_lint.py pins this file): journal emits
NEVER happen under the cache mutex — handlers collect events while
holding ``_mu`` and flush them after release.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional

import numpy as np

from .. import observability as _obs
from ..distributed.lookup_service import LookupServiceClient
from .engine import InvalidRequest, ServingError
from .replica import pack_blob, unpack_blob

__all__ = ["SparseServingReplica", "StaleRows", "SparseServingConfig"]


class StaleRows(ServingError):
    """The staleness gate shed this request: rows in its id set exceed
    ``max_staleness_steps`` and the replica is configured to refuse
    rather than re-pull (e.g. while its authority shard is
    restarting). Structured — the router/client can switch on the
    code and retry elsewhere or later."""
    code = "STALE_ROWS"


class SparseServingConfig:
    """Knobs of one sparse serving replica (constructor kwargs live
    here so the chaos scenario, bench, and load_gen share defaults).

    - ``max_staleness_steps``: serve no row whose copy may have missed
      more than this many authority pushes (None disarms the gate).
    - ``staleness_action``: ``"repull"`` re-reads over-bound rows from
      authority; ``"shed"`` refuses the request with ``StaleRows``.
    - ``enforce``: False = observe-only (serve + journal
      ``stale_row_served`` — the doctor-visible breach).
    - ``watermark_poll_every``: refresh every shard's watermark by an
      empty stamped prefetch every N requests (authority reads keep it
      fresh in between, for free).
    - ``device_rows``: capacity of the pinned device array tier.
    - ``cache_bytes``: host Tier 0 budget (EmbeddingRowCache).
    """

    def __init__(self, max_staleness_steps: Optional[int] = 8,
                 staleness_action: str = "repull",
                 enforce: bool = True,
                 watermark_poll_every: int = 16,
                 device_rows: int = 1024,
                 cache_bytes: int = 1 << 20,
                 pull_q8: bool = True,
                 admit_after: int = 1,
                 deadline_s: float = 10.0,
                 retry=None,
                 workers: int = 4):
        if staleness_action not in ("repull", "shed"):
            raise ValueError("staleness_action must be 'repull' or "
                             "'shed', got %r" % (staleness_action,))
        self.max_staleness_steps = max_staleness_steps
        self.staleness_action = staleness_action
        self.enforce = bool(enforce)
        self.watermark_poll_every = max(1, int(watermark_poll_every))
        self.device_rows = int(device_rows)
        self.cache_bytes = int(cache_bytes)
        self.pull_q8 = bool(pull_q8)
        self.admit_after = int(admit_after)
        self.deadline_s = float(deadline_s)
        self.retry = retry
        self.workers = max(1, int(workers))


class _DeviceRowTier:
    """The hottest rows as one resident device array: ``capacity``
    slots of ``dim`` f32, id->slot map with CLOCK eviction, per-tier
    hit/miss accounting. Slot bookkeeping is mutex-guarded; the device
    array update itself runs outside the mutex (the replica serializes
    fills through its lookup lock, and a device write is exactly the
    slow path the bookkeeping lock must not cover)."""

    def __init__(self, dim: int, capacity_rows: int):
        import jax
        import jax.numpy as jnp
        self._jnp = jnp
        self.dim = int(dim)
        self.capacity = max(8, int(capacity_rows))
        self._slots = jax.device_put(
            jnp.zeros((self.capacity, self.dim), jnp.float32))
        self._mu = threading.Lock()
        self._slot_of: Dict[int, int] = {}
        self._rid_of: List[Optional[int]] = [None] * self.capacity
        self._ref = bytearray(self.capacity)
        self._hand = 0
        self._free = list(range(self.capacity - 1, -1, -1))
        self.hits = 0
        self.misses = 0
        self.fills = 0
        self.evictions = 0
        self.invalidated_rows = 0
        self.overflow_rows = 0

    def lookup(self, uniq: np.ndarray) -> np.ndarray:
        """-> per-id slot (int32), -1 = miss. Touches CLOCK bits."""
        with self._mu:
            out = np.full(len(uniq), -1, np.int32)
            for j, rid in enumerate(uniq):
                s = self._slot_of.get(int(rid))
                if s is not None:
                    out[j] = s
                    self._ref[s] = 1
            n_hit = int((out >= 0).sum())
            self.hits += n_hit
            self.misses += len(uniq) - n_hit
            return out

    def _alloc_locked(self, pinned) -> int:
        """One free or evictable slot, or -1 when every candidate is
        in ``pinned`` — slots the CURRENT request's gather depends on
        (its hits plus ids placed earlier in the same fill). Without
        the pin set, a fill larger than free capacity would CLOCK its
        way back onto its own slots and map two ids to one row."""
        if self._free:
            return self._free.pop()
        if len(pinned) >= self.capacity:
            return -1
        for _ in range(2 * self.capacity):
            s = self._hand
            self._hand = (self._hand + 1) % self.capacity
            if s in pinned:
                continue
            if self._ref[s]:
                self._ref[s] = 0
            else:
                return s
        return -1

    @staticmethod
    def _pow2(n: int) -> int:
        """Shape bucket (serving/buckets.py posture): device scatter/
        gather index counts round up to the next power of two so the
        jit cache holds O(log capacity) programs, not one per distinct
        id-set size."""
        return 1 << max(0, int(n) - 1).bit_length()

    def fill(self, ids, rows, pinned=()) -> np.ndarray:
        """Install host ``rows`` for ``ids``; returns their slots,
        ``-1`` for ids the tier could NOT place (one request's unique
        ids exceed capacity) — the caller serves those from the host
        rows it already holds. ``pinned``: slots the current request's
        gather already depends on (its hit slots); they are never
        evicted, so a full tier can't remap an id out from under the
        request that is about to read it."""
        ids = [int(i) for i in np.asarray(ids, np.int64)]
        pin = {int(s) for s in np.asarray(pinned, np.int64).reshape(-1)
               if s >= 0}
        with self._mu:
            slots = []
            for rid in ids:
                s = self._slot_of.get(rid)
                if s is None:
                    s = self._alloc_locked(pin)
                    if s < 0:
                        self.overflow_rows += 1
                        slots.append(-1)
                        continue
                    old = self._rid_of[s]
                    if old is not None:
                        del self._slot_of[old]
                        self.evictions += 1
                    self._slot_of[rid] = s
                    self._rid_of[s] = rid
                self._ref[s] = 1
                pin.add(s)
                slots.append(s)
                self.fills += 1
        slots = np.asarray(slots, np.int32)
        placed = slots >= 0
        if not placed.any():
            return slots
        slots_p = slots[placed]
        rows_p = np.asarray(rows, np.float32)[placed]
        # bucket-pad by REPEATING the last (slot, row) pair: writing
        # one slot twice with the same row is idempotent, and the
        # padded scatter shape comes from a pow-2 menu
        pad = self._pow2(len(slots_p)) - len(slots_p)
        if pad:
            slots_w = np.concatenate([slots_p,
                                      np.repeat(slots_p[-1:], pad)])
            rows_w = np.concatenate([rows_p,
                                     np.repeat(rows_p[-1:], pad, 0)])
        else:
            slots_w, rows_w = slots_p, rows_p
        self._slots = self._slots.at[slots_w].set(
            self._jnp.asarray(rows_w))
        return slots

    def gather(self, slots: np.ndarray) -> np.ndarray:
        """Device-side gather of resident rows -> host [n, dim]
        (bucket-padded with slot 0, sliced back after)."""
        n = len(slots)
        pad = self._pow2(n) - n
        slots_w = np.concatenate([np.asarray(slots, np.int32),
                                  np.zeros(pad, np.int32)]) \
            if pad else np.asarray(slots, np.int32)
        out = self._jnp.take(self._slots,
                             self._jnp.asarray(slots_w), axis=0)
        return np.asarray(out, np.float32)[:n]

    def invalidate_ids(self, ids) -> int:
        with self._mu:
            n = 0
            for rid in np.asarray(ids, np.int64).reshape(-1):
                s = self._slot_of.pop(int(rid), None)
                if s is not None:
                    self._rid_of[s] = None
                    self._ref[s] = 0
                    self._free.append(s)
                    n += 1
            self.invalidated_rows += n
            return n

    def invalidate_all(self) -> int:
        with self._mu:
            n = len(self._slot_of)
            self._slot_of.clear()
            self._rid_of = [None] * self.capacity
            self._ref = bytearray(self.capacity)
            self._free = list(range(self.capacity - 1, -1, -1))
            self._hand = 0
            self.invalidated_rows += n
            return n

    def stats(self) -> dict:
        with self._mu:
            return {"capacity_rows": self.capacity,
                    "resident_rows": len(self._slot_of),
                    "hits": self.hits, "misses": self.misses,
                    "hit_rate": self.hits / (self.hits + self.misses)
                    if (self.hits + self.misses) else 0.0,
                    "fills": self.fills, "evictions": self.evictions,
                    "invalidated_rows": self.invalidated_rows,
                    "overflow_rows": self.overflow_rows}


class SparseServingReplica:
    """One sparse serving replica: the ``ServingReplica`` wire surface
    (INFER/HEARTBEAT/CTRL verbs, piggybacked load, structured errors,
    ``crash()``) with a LookupServiceClient + device tier forward
    instead of a compiled model, and the bounded-staleness gate in
    front of every served row.

    The forward is a DeepFM-style scoring head over the live table:
    request arrays carry an int64 id matrix ``[batch, slots]``; the
    reply is ``[scores [batch], pooled [batch, dim]]`` where pooled is
    the device-side sum of the slots' embedding rows and the score a
    seeded fixed linear head over it — a deterministic function of the
    authority rows, so freshness is black-box observable (the bench's
    ``fresh_weight_to_served_ms`` row and the chaos scenario's
    staleness assertions both key on it).

    ``group_rank``/``group_size`` mirror the PR 13 sharded replica
    groups: rank 0 is the group's executor (owns the lookup client +
    device tier), ranks > 0 are the group's lease surface — an INFER
    landing there answers a structured error, never silence. Behind a
    ``RouterConfig(group_size=N)`` router the whole group admits and
    evicts atomically, so a table larger than one host serves from as
    many hosts as its shards need."""

    def __init__(self, table: str, endpoints: List[str], dim: int,
                 config: Optional[SparseServingConfig] = None,
                 endpoint: str = "127.0.0.1:0", replica_id: int = 0,
                 group_rank: int = 0, group_size: int = 1,
                 topology=None, head_seed: int = 7,
                 version: str = "v1"):
        self.table = table
        self.dim = int(dim)
        self.config = config or SparseServingConfig()
        self.replica_id = int(replica_id)
        self.group_rank = int(group_rank)
        self.group_size = int(group_size)
        self.version = version
        cfg = self.config
        self._crashed = False
        self._mu = threading.Lock()        # counters + ewma only
        self._lookup_mu = threading.Lock()  # serializes tier pipeline
        self._inflight = 0
        self._ewma_ms: Optional[float] = None
        self._req_count = 0
        self._seen_invalidations = 0
        # per-tier accounting (requested-row basis, like the client's)
        self.host_hit_rows = 0
        self.remote_rows = 0
        self.device_overflow_rows = 0
        self.repulled_rows = 0
        self.shed_requests = 0
        self.stale_served_rows = 0
        self.max_lag_served = 0
        self.client: Optional[LookupServiceClient] = None
        self.device_tier: Optional[_DeviceRowTier] = None
        if self.group_rank == 0:
            self.client = LookupServiceClient(
                table, list(endpoints), dim=dim,
                deadline_s=cfg.deadline_s, retry=cfg.retry,
                cache_bytes=cfg.cache_bytes,
                admit_after=cfg.admit_after,
                pull_q8=cfg.pull_q8, write_policy="none",
                topology=topology, stamped=True)
            self.device_tier = _DeviceRowTier(dim, cfg.device_rows)
            rs = np.random.RandomState(head_seed)
            self._head = (rs.randn(dim) / np.sqrt(dim)).astype(
                np.float32)
        import concurrent.futures
        self._pool = concurrent.futures.ThreadPoolExecutor(
            max_workers=cfg.workers,
            thread_name_prefix="sparse-serve-%d" % self.replica_id)
        from ..distributed.rpc import RPCServer
        self.server = RPCServer(endpoint)
        self.endpoint = self.server.endpoint
        self.server.register_deferred("INFER", self._on_infer)
        self.server.register_deferred("CTRL", self._on_ctrl)
        self.server.register("HEARTBEAT", self._on_heartbeat)

    # -- load piggyback / wire plumbing (ServingReplica contract) ------
    def load_snapshot(self) -> dict:
        with self._mu:
            return {"replica_id": self.replica_id,
                    "queue_depth": self._inflight,
                    "ewma_ms": self._ewma_ms}

    def _err_meta(self, exc) -> dict:
        err = exc.to_dict() if isinstance(exc, ServingError) else {
            "code": "SERVING_ERROR", "message": repr(exc),
            "details": {}}
        return {"ok": False, "error": err,
                "load": self.load_snapshot()}

    def _respond(self, responder, status, payload):
        if self._crashed:
            return
        try:
            responder(status, payload)
        except Exception:
            pass

    # -- the staleness gate + tier pipeline ----------------------------
    def _gate_locked(self, uniq: np.ndarray, events: list):
        """Bound every row's possible missed-push count BEFORE it is
        served. Called under ``_lookup_mu``; journal emits are
        deferred into ``events``. Raises ``StaleRows`` on shed (the
        caller flushes events first)."""
        cfg = self.config
        cl = self.client
        if cfg.max_staleness_steps is None:
            return None
        self._req_count += 1
        if (self._req_count % cfg.watermark_poll_every == 0
                or not cl.shard_watermarks):
            cl.watermarks(refresh=True)
        lag = cl.staleness(uniq)
        unknown = lag < 0
        if unknown.any():
            # no stamp = "fetch before serving" (never pulled, stamp
            # trimmed under the cap, or dropped by a fence): any
            # device-resident copy predates stamp knowledge — drop it
            # so the miss path below re-pulls from authority
            self.device_tier.invalidate_ids(uniq[unknown])
        over = lag > cfg.max_staleness_steps
        # the served-lag audit is measured against THIS gate's
        # watermark snapshot — the bound is relative to the coherence
        # check, not to pushes that land while the reply is in flight
        # (those are the NEXT request's gate's problem). Rows the gate
        # passes bound it; rows it repulls serve at lag 0 on this
        # clock; -1 (never stamped) rows are pulled fresh below.
        if over.any() or lag.size:
            under = lag[~over]
            if under.size and under.max() > 0:
                self.max_lag_served = max(self.max_lag_served,
                                          int(under.max()))
        if not over.any():
            return None
        stale = uniq[over]
        worst = int(lag[over].max())
        if not cfg.enforce:
            # observe-only: the breach doctor must be able to explain
            self.stale_served_rows += int(stale.size)
            self.max_lag_served = max(self.max_lag_served, worst)
            rid = int(stale[0])
            ver, seen_w = cl.row_stamps.get(rid, (0, 0))
            shard = int(rid % len(cl.clients))
            events.append(("stale_row_served", dict(
                table=self.table, replica=self.replica_id,
                rows=int(stale.size), row=rid, row_version=ver,
                pull_watermark=seen_w,
                shard_watermark=cl.shard_watermarks.get(
                    cl.clients[shard].endpoint),
                lag=worst, bound=cfg.max_staleness_steps)))
            return None
        if cfg.staleness_action == "shed":
            self.shed_requests += 1
            events.append(("stale_shed", dict(
                table=self.table, replica=self.replica_id,
                rows=int(stale.size), lag=worst,
                bound=cfg.max_staleness_steps)))
            return StaleRows(
                "replica %d refuses %d row(s) up to %d push(es) "
                "stale (bound %d)" % (self.replica_id, stale.size,
                                      worst, cfg.max_staleness_steps),
                replica=self.replica_id, rows=int(stale.size),
                lag=worst, bound=cfg.max_staleness_steps)
        # repull: authority re-read; device-tier copies of the stale
        # rows drop so the fill below re-installs the fresh image
        cl.refresh_rows(stale)
        if self.device_tier is not None:
            self.device_tier.invalidate_ids(stale)
        self.repulled_rows += int(stale.size)
        events.append(("stale_repull", dict(
            table=self.table, replica=self.replica_id,
            rows=int(stale.size), lag=worst,
            bound=cfg.max_staleness_steps)))
        return None

    def _forward(self, id_batch: np.ndarray):
        """ids [batch, slots] -> (scores [batch], pooled [batch, dim]).
        Returns (outputs, events, exc): emits NEVER fire under
        ``_lookup_mu`` — the caller flushes ``events`` after release
        (lock_lint gate)."""
        events: list = []
        cl = self.client
        tier = self.device_tier
        b, s = id_batch.shape
        flat = id_batch.reshape(-1)
        uniq, inv = np.unique(flat, return_inverse=True)
        with self._lookup_mu:
            # a restarted/resharded authority dropped the client's hot
            # tier: the device tier mirrors those rows and must drop
            # with it, exactly once per observed invalidation
            if cl.invalidation_count != self._seen_invalidations:
                self._seen_invalidations = cl.invalidation_count
                dropped = tier.invalidate_all()
                events.append(("sparse_device_tier_invalidated", dict(
                    table=self.table, replica=self.replica_id,
                    rows_dropped=dropped)))
            exc = self._gate_locked(uniq, events)
            if exc is not None:
                return None, events, exc
            slots = tier.lookup(uniq)
            miss = slots < 0
            rows_miss = None
            if miss.any():
                hits0 = cl.cache_hit_rows
                rows_miss = cl.pull(uniq[miss])
                host_hits = cl.cache_hit_rows - hits0
                self.host_hit_rows += host_hits
                self.remote_rows += int(miss.sum()) - host_hits
                # the request's hit slots are PINNED: a fill bigger
                # than free capacity must spill, never remap a slot
                # this gather is about to read
                slots[miss] = tier.fill(uniq[miss], rows_miss,
                                        pinned=slots[~miss])
            ovf = slots < 0
            emb_uniq = tier.gather(np.where(ovf, 0, slots))
            if ovf.any():
                # overflow: more unique ids than the tier could place
                # for ONE request — those ids bypass the device tier
                # and serve the authority rows already pulled above
                # (-1 slots only ever come from this fill's misses);
                # gather hands back a read-only device view, so copy
                emb_uniq = np.array(emb_uniq)
                emb_uniq[ovf] = rows_miss[ovf[miss]]
                self.device_overflow_rows += int(ovf.sum())
                events.append(("sparse_device_tier_overflow", dict(
                    table=self.table, replica=self.replica_id,
                    rows=int(ovf.sum()),
                    capacity_rows=tier.capacity)))
        pooled = emb_uniq[inv].reshape(b, s, self.dim).sum(axis=1)
        scores = pooled @ self._head
        return ([np.asarray(scores, np.float32),
                 np.asarray(pooled, np.float32)], events, None)

    # -- handlers ------------------------------------------------------
    def _serve(self, payload, responder):
        t0 = time.monotonic()
        events = ()
        try:
            meta, arrays = unpack_blob(payload)
            if self.group_rank != 0:
                raise InvalidRequest(
                    "replica %d is shard member rank %d of a "
                    "group-of-%d — INFER dispatches to the group's "
                    "rank-0 executor" % (self.replica_id,
                                         self.group_rank,
                                         self.group_size),
                    replica=self.replica_id,
                    group_rank=self.group_rank)
            names = list(meta.get("inputs") or ())
            if "ids" not in names or not arrays:
                raise InvalidRequest(
                    "sparse INFER needs an int64 'ids' array, got "
                    "inputs=%r" % (names,), replica=self.replica_id)
            ids = np.asarray(arrays[names.index("ids")], np.int64)
            if ids.ndim == 1:
                ids = ids[:, None]
            outs, events, exc = self._forward(ids)
            for kind, fields in events:
                _obs.emit(kind, **fields)
            events = ()
            if exc is not None:
                raise exc
            meta_out = {"ok": True, "version": self.version,
                        "load": self.load_snapshot()}
            self._respond(responder, 0, pack_blob(meta_out, outs))
        except Exception as e:
            for kind, fields in events:
                _obs.emit(kind, **fields)
            self._respond(responder, 0, pack_blob(self._err_meta(e)))
        finally:
            dt_ms = (time.monotonic() - t0) * 1e3
            with self._mu:
                self._inflight -= 1
                self._ewma_ms = dt_ms if self._ewma_ms is None \
                    else 0.2 * dt_ms + 0.8 * self._ewma_ms

    def _on_infer(self, wire, payload, responder):
        with self._mu:
            self._inflight += 1
        self._pool.submit(self._serve, payload, responder)

    def _on_heartbeat(self, wire, payload):
        from ..distributed.rpc import unpack_wire_meta
        _base, tid, seq, _tok = unpack_wire_meta(wire)
        if seq is not None:
            _obs.emit("heartbeat_recv", tid=tid, beat=seq,
                      endpoint=self.endpoint)
        return pack_blob({"ok": True, "load": self.load_snapshot()})

    def _on_ctrl(self, wire, payload, responder):
        try:
            meta, _ = unpack_blob(payload)
            op = meta.get("op")
            if op == "stats":
                out = {"ok": True, "stats": self.stats()}
            else:
                raise InvalidRequest("unknown CTRL op %r" % op, op=op)
        except Exception as e:
            out = self._err_meta(e)
        self._respond(responder, 0, pack_blob(out))

    # -- introspection / lifecycle ------------------------------------
    def stats(self) -> dict:
        out = {"replica_id": self.replica_id,
               "endpoint": self.endpoint,
               "table": self.table,
               "group_rank": self.group_rank,
               "group_size": self.group_size,
               "load": self.load_snapshot(),
               "staleness": {
                   "bound": self.config.max_staleness_steps,
                   "action": self.config.staleness_action,
                   "enforce": self.config.enforce,
                   "repulled_rows": self.repulled_rows,
                   "shed_requests": self.shed_requests,
                   "stale_served_rows": self.stale_served_rows,
                   "max_lag_served": self.max_lag_served}}
        if self.client is not None:
            out["tiers"] = {
                "device": self.device_tier.stats(),
                "host_hit_rows": self.host_hit_rows,
                "remote_rows": self.remote_rows,
                "device_overflow_rows": self.device_overflow_rows,
                "client": self.client.stats()}
        return out

    def start(self):
        self.server.start()
        return self

    def crash(self):
        """Chaos seam: die like a SIGKILLed process — sockets closed
        NOW, in-flight INFERs never answered."""
        self._crashed = True
        self.server._crash()

    def shutdown(self):
        self.server.shutdown()
        self._pool.shutdown(wait=False)
        if self.client is not None:
            self.client.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.shutdown()
        return False
